"""Zero-stall async checkpoint engine — snapshot to host, write behind.

The synchronous path (:class:`apex_tpu.checkpoint.CheckpointManager`)
hands the live device state to orbax on the step path: the save call
pays the device→host copy (and, depending on the backend, part of the
serialization) before control returns to the training loop, so
checkpoint cadence trades directly against step time.  On a
preemptible fleet that tradeoff is fatal — you either checkpoint
rarely (and lose work on every eviction) or often (and burn the step
budget on I/O stalls).

:class:`AsyncCheckpointEngine` splits the save the TorchTitan way
(async distributed checkpointing, PAPERS.md):

1. **snapshot** — :func:`host_snapshot` copies the state pytree to
   host buffers using the same async device→host machinery the
   :class:`~apex_tpu.observability.MetricRegistry` fetch cadence uses
   (``copy_to_host_async`` issued for every leaf first, then
   materialized — transfers overlap each other, and the step program
   already running on device overlaps all of them).  The snapshot is
   **copy-on-snapshot**: the caller may mutate, donate, or delete the
   state the moment ``save`` returns.
2. **background write** — one writer thread drains a bounded queue,
   driving the sharded orbax save into ``<dir>/<step>``.  Orbax stages
   into ``<step>.orbax-checkpoint-tmp-*`` and commits by atomic
   rename, so a crash/SIGTERM mid-write leaves only debris that
   :func:`apex_tpu.checkpoint.all_steps` ignores — the previous
   checkpoint stays intact and restorable.
3. **barrier only at finalize** — :meth:`wait_until_finished` joins
   the queue (``run_resilient`` calls it at rollback anchoring, before
   the forced preemption checkpoint, and at shutdown, so in-flight
   writes always drain).  Nothing else on the step path blocks on the
   write.

The step path's ONLY checkpoint cost is the snapshot + enqueue, and
the engine accounts for it: every completed phase lands as an event
(:meth:`drain_events` — ``run_resilient`` forwards them to the
observer protocol's ``on_checkpoint``, where
:class:`~apex_tpu.observability.spans.SpanRecorder` turns them into
``ckpt/snapshot`` / ``ckpt/write`` / ``ckpt/finalize`` spans on the
Perfetto timeline) and as board gauges
(``goodput/ckpt/stall_frac`` is what
:class:`~apex_tpu.observability.health.CheckpointStallRule` pages on).

Failure contract (mirrors the sync manager's scope note): a
background write that fails permanently loses that one step's
checkpoint, never crash consistency — the error is deferred and
raised at the next synchronization point, whichever comes first: the
NEXT ``save`` call (so the
:class:`~apex_tpu.resilience.runner.ResilientCheckpointManager` retry
wrapper clears it and re-enqueues the current step) or
:meth:`wait_until_finished` (so a shutdown/preemption drain can never
report success for a final checkpoint that never reached disk).  The
incomplete step stays invisible to ``latest_step``; resume falls back
one interval.

See ``docs/goodput.md``.
"""

from __future__ import annotations

import collections
import os
import queue
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from apex_tpu import checkpoint as _ckpt
from apex_tpu.observability.locks import TrackedLock

__all__ = [
    "host_snapshot",
    "resolve_queue_depth",
    "AsyncCheckpointEngine",
]


def host_snapshot(state):
    """Copy a state pytree to host buffers, snapshot-isolated.

    - ``jax.Array`` leaves: ``copy_to_host_async`` is issued for EVERY
      leaf before any is materialized, so the device→host transfers
      overlap each other (and whatever is running on device).  Fully
      addressable arrays come back as numpy; a non-addressable
      (multi-host sharded) leaf passes through untouched — orbax owns
      its distributed write, and jax arrays are immutable so the
      snapshot hazard does not apply to them.
    - numpy leaves are **copied** — the caller mutating them in place
      after ``save`` returns must not corrupt the written checkpoint
      (the documented hazard of handing live buffers to an async
      writer).
    - python scalars pass through (immutable).

    Costs and caveats the caller owns:

    - The snapshot holds ONE full host copy of the state — a leaf
      sharded across local devices is gathered into a single
      contiguous buffer (orbax's inline path streamed per shard), so
      budget host RAM for the whole logical state per in-flight save.
    - A **non-addressable** (multi-host sharded) leaf is NOT snapshot
      isolated: immutability protects it from mutation, but a step
      that **donates** such a leaf while the background write is still
      serializing it invalidates the buffer mid-write.  On multi-host
      meshes, either keep checkpointed leaves out of ``donate_argnums``
      or barrier on ``wait_until_finished`` before the next donated
      step.
    """
    leaves, treedef = jax.tree_util.tree_flatten(state)
    for x in leaves:
        copy = getattr(x, "copy_to_host_async", None)
        if copy is not None and getattr(x, "is_fully_addressable", True):
            copy()
    out = []
    for x in leaves:
        if isinstance(x, jax.Array):
            if getattr(x, "is_fully_addressable", True):
                out.append(np.asarray(x))
            else:
                out.append(x)
        elif isinstance(x, np.ndarray):
            out.append(np.array(x, copy=True))
        elif isinstance(x, np.generic):
            # numpy SCALAR: immutable, but orbax's standard handler
            # refuses the type — normalize to a 0-d array (same value)
            out.append(np.asarray(x))
        else:
            out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


_SENTINEL = object()

#: env override for the write-queue depth (same idiom as
#: ``APEX_TPU_COMM_CHUNKS``): env > explicit ``queue_depth`` arg >
#: default 4.  The default absorbs a few intervals of write jitter at
#: production cadences (saves minutes apart, writes seconds long); a
#: compressed-timescale harness (the CI storm drill saves every few
#: hundred ms onto whatever disk the runner has) raises it so the
#: measured stall fraction keeps meaning "the step path pays only the
#: snapshot" instead of "this machine's disk was slow today".
ENV_QUEUE_DEPTH = "APEX_TPU_CKPT_QUEUE"


def resolve_queue_depth(queue_depth: Optional[int] = None) -> int:
    """Write-queue depth: env :data:`ENV_QUEUE_DEPTH` > explicit arg >
    default 4.  Always >= 1 — depth 0 would turn every save into a
    synchronous write."""
    env = os.environ.get(ENV_QUEUE_DEPTH)
    if env:
        depth = int(env)
    elif queue_depth is not None:
        depth = int(queue_depth)
    else:
        depth = 4
    return max(1, depth)


class AsyncCheckpointEngine:
    """Step-numbered async checkpoints: host snapshot + background write.

    API-compatible with :class:`apex_tpu.checkpoint.CheckpointManager`
    (``save``/``restore``/``latest_step``/``all_steps``/``should_save``/
    ``wait_until_finished``/``close``, context-managed), so
    ``run_resilient`` swaps between the two behind one name.  On top:

    - ``save`` returns the moment the host snapshot is enqueued; the
      bounded queue (``queue_depth`` — :func:`resolve_queue_depth`:
      env ``APEX_TPU_CKPT_QUEUE`` > arg > default 4, which absorbs a
      few intervals of write jitter, e.g. the first save's cold orbax
      setup) is the backpressure valve: a writer that falls behind
      stalls the NEXT save's enqueue, never unboundedly buffering
      snapshots in RAM.
    - ``drain_events()`` hands back completed phase records
      (``{"phase": "write"|"finalize", "step", "t0", "t1", ...}``,
      monotonic seconds) for the observer/span layer.
    - ``stats()`` is the cumulative ledger (saves, failures, snapshot/
      enqueue/write milliseconds, stall fraction).

    Step enumeration and the interval policy are resume-aware: a fresh
    engine on an existing directory continues the cadence from the
    newest complete step on disk.
    """

    def __init__(
        self,
        directory,
        *,
        max_to_keep: Optional[int] = None,
        save_interval_steps: int = 1,
        queue_depth: Optional[int] = None,
    ):
        if save_interval_steps < 1:
            raise ValueError("save_interval_steps must be >= 1")
        self._directory = os.path.abspath(os.fspath(directory))
        os.makedirs(self._directory, exist_ok=True)
        self._interval = int(save_interval_steps)
        self._max_to_keep = max_to_keep
        self._last_saved: Optional[int] = _ckpt.latest_step(self._directory)
        self._q: "queue.Queue" = queue.Queue(
            maxsize=resolve_queue_depth(queue_depth)
        )
        self._events: "collections.deque" = collections.deque(maxlen=1024)
        # one lock for everything the writer thread and the step path
        # both touch: _error, _stats, _ckptr, _phase, _first_save_t.
        # TrackedLock so the LOCKSAN lock-order graph sees it and
        # close() can name the holder when a drain times out.
        self._lock = TrackedLock("ckpt")
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._ckptr = None  # one StandardCheckpointer, writer-thread only
        self._phase = "idle"  # writer's current phase, for close() diag
        self._first_save_t: Optional[float] = None
        self._stats: Dict[str, float] = {
            "saves": 0.0,
            "writes": 0.0,
            "failures": 0.0,
            "snapshot_ms_total": 0.0,
            "enqueue_wait_ms_total": 0.0,
            "write_ms_total": 0.0,
            "finalize_ms_total": 0.0,
            "last_snapshot_ms": 0.0,
            "last_write_ms": 0.0,
        }

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="apex-tpu-ckpt-writer",
            )
            self._thread.start()

    def close(self, timeout: float = 120.0) -> None:
        """Drain pending writes, stop the writer, release orbax.
        Never raises (it runs from ``__exit__``, possibly during
        exception handling) — but a deferred write error is WARNED,
        not swallowed: without a later ``save``/finalize to raise it,
        close is the last place a lost final write can be reported.

        The drain is a BOUNDED wait: after ``timeout`` seconds the
        warning names what the writer was doing when it wedged — its
        current phase (``write step N`` / ``prune`` / ``bootstrap``),
        the queue backlog, and who holds the engine lock (TrackedLock
        state) — instead of a bare "still busy"."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None and self._thread.is_alive():
            # FIFO: queued snapshots are written before the sentinel
            # stops the loop — close() IS the shutdown drain
            while True:
                try:
                    self._q.put(_SENTINEL, timeout=0.5)
                    break
                except queue.Full:
                    if not self._thread.is_alive():
                        break
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # the daemon writer dies with the process; whatever is
                # still queued/mid-write never reaches disk — that must
                # not be silent (run_resilient drains via
                # wait_until_finished first, but a bare context-manager
                # user's last checkpoints are on the line here).  Name
                # the stuck phase so the postmortem starts at the right
                # layer (a wedged orbax write step vs. a slow prune vs.
                # a lock-holder that never released).
                import warnings

                # deliberately lock-free reads: if the writer wedged
                # WHILE holding the lock, taking it here would hang the
                # very diagnostic meant to explain the hang
                phase = self._phase
                holder = self._lock.holder
                warnings.warn(
                    f"checkpoint writer still busy after {timeout:g}s "
                    f"close() drain (stuck phase: {phase}; "
                    f"{self._q.qsize()} item(s) still queued; engine "
                    f"lock held by: {holder or 'nobody'}); pending "
                    "background writes will be lost when the process "
                    "exits",
                    RuntimeWarning,
                )
        elif self._ckptr is not None:
            self._close_ckptr()
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            import warnings

            warnings.warn(
                f"checkpoint write failed during close "
                f"({type(err).__name__}: {err}); the failed step is "
                "not on disk — resume falls back one interval",
                RuntimeWarning,
            )

    def _close_ckptr(self) -> None:
        # swap under the lock (both the writer's shutdown path and a
        # threadless close() reach here); the actual close — which may
        # block on orbax — runs outside it
        with self._lock:
            ckptr, self._ckptr = self._ckptr, None
        if ckptr is not None:
            try:
                ckptr.close()
            except Exception:
                pass

    # -- queries -----------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        """Newest COMPLETE step on disk (queued writes excluded — a
        step is not a checkpoint until its commit rename lands)."""
        return _ckpt.latest_step(self._directory)

    def all_steps(self) -> List[int]:
        return _ckpt.all_steps(self._directory)

    def should_save(self, step: int) -> bool:
        """Interval policy (orbax semantics: first save always, then
        ``interval`` steps after the last saved one)."""
        return (
            self._last_saved is None
            or step >= self._last_saved + self._interval
        )

    # -- io ----------------------------------------------------------------
    def save(self, step: int, state, *, force: bool = False) -> bool:
        """Snapshot ``state`` to host and enqueue the background write.

        Returns False when the interval policy skips the step.  Raises
        a deferred background-write error from a PREVIOUS save (one
        shot: the caller's retry re-enters with the error cleared and
        the current step is enqueued — the failed step falls back one
        interval, exactly the sync manager's documented semantics).
        """
        if self._closed:
            # a save after close() would silently resurrect a writer
            # nothing ever drains again — the drain-on-exit guarantee
            # only holds if the lifecycle stays closed
            raise RuntimeError("save() on a closed AsyncCheckpointEngine")
        self._raise_deferred()
        if not force and not self.should_save(step):
            return False
        t0 = time.monotonic()
        host = host_snapshot(state)
        t1 = time.monotonic()
        self._ensure_thread()
        # the enqueue wait is only known after put() returns, but the
        # writer may already hold the item by then — hand it a shared
        # slot instead.  The writer reads it when emitting the write
        # event (after the orbax save, long past the fill below), so
        # the event's step-path cost is snapshot AND enqueue.
        enq_slot: List[float] = []
        # the bounded put blocks when the writer is behind — it must
        # stay OUTSIDE the lock (the writer needs the same lock to
        # finish the write that frees the slot: holding it here is the
        # textbook race-lock-across-blocking deadlock)
        self._q.put((int(step), host, bool(force), t0, t1, enq_slot))
        t2 = time.monotonic()
        enq_slot.append((t2 - t1) * 1e3)
        self._last_saved = int(step)
        with self._lock:
            st = self._stats
            st["saves"] += 1.0
            st["snapshot_ms_total"] += (t1 - t0) * 1e3
            st["enqueue_wait_ms_total"] += (t2 - t1) * 1e3
            st["last_snapshot_ms"] = (t1 - t0) * 1e3
            if self._first_save_t is None:
                self._first_save_t = t0
        self._publish()
        return True

    def _raise_deferred(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def restore(self, step: Optional[int] = None, *, template=None):
        """Restore ``step`` (default: newest complete) — drains pending
        writes first so a just-enqueued save is restorable.  A deferred
        write error stays deferred (to the next ``save``/finalize): a
        lost write must not block restoring the previous complete step
        — that fall-back IS the failure contract."""
        self._q.join()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self._directory}"
                )
        return _ckpt.restore_step_dir(
            self._directory, int(step), template=template
        )

    def wait_until_finished(self) -> None:
        """The finalize barrier: block until every enqueued write has
        committed, then raise any deferred write error (cleared, like
        ``save`` — but a shutdown/preemption drain must never report
        success for a checkpoint that never reached disk)."""
        t0 = time.monotonic()
        self._q.join()
        dt = time.monotonic() - t0
        if dt > 1e-4:  # an actual wait, not the no-op fast path
            with self._lock:
                self._stats["finalize_ms_total"] += dt * 1e3
            self._events.append({
                "phase": "finalize", "step": self._last_saved,
                "t0": t0, "t1": t0 + dt,
            })
            self._publish()
        self._raise_deferred()

    # -- the background writer ---------------------------------------------
    def _writer_loop(self) -> None:
        try:
            with self._lock:
                self._phase = "bootstrap"
            import orbax.checkpoint as ocp

            with self._lock:
                if self._ckptr is None:
                    self._ckptr = ocp.StandardCheckpointer()
                self._phase = "idle"
        except BaseException as e:
            # bootstrap failed (orbax missing/broken): become a pure
            # drainer — ``q.join()`` callers must never deadlock on
            # items this writer can no longer write.  The error
            # surfaces through the normal deferral contract (next
            # save/finalize); close()'s sentinel ends the loop.
            with self._lock:
                self._error = e
                self._stats["failures"] += 1.0
                self._phase = "drain (bootstrap failed)"
            while True:
                item = self._q.get()
                if item is not _SENTINEL:
                    # every snapshot this drainer swallows is a LOST
                    # checkpoint: re-arm the error each time (a save()
                    # raising it clears it one-shot) so no later
                    # synchronization point can report success while
                    # writes are silently dropped.  Re-arm BEFORE
                    # task_done: a q.join() waiter must observe the
                    # error the moment the join releases.
                    with self._lock:
                        if self._error is None:
                            self._error = e
                        self._stats["failures"] += 1.0
                self._q.task_done()
                if item is _SENTINEL:
                    return
        while True:
            item = self._q.get()
            try:
                if item is _SENTINEL:
                    return
                step, host, force, t0, t1, enq_slot = item
                self._write_one(step, host, force, t0, t1, enq_slot)
            finally:
                self._q.task_done()
                if item is _SENTINEL:
                    self._close_ckptr()

    def _write_one(
        self, step, host, force, snap_t0, snap_t1, enq_slot=(),
    ) -> None:
        path = os.path.join(self._directory, str(step))
        w0 = time.monotonic()
        ok = True
        try:
            with self._lock:
                self._phase = f"write step {int(step)}"
            if self._commit_hook is not None:
                self._commit_hook(step)
            self._ckptr.save(path, host, force=force or os.path.exists(path))
            self._ckptr.wait_until_finished()
            with self._lock:
                self._phase = "prune"
            self._prune()
        except BaseException as e:  # deferred to the next save() call
            ok = False
            with self._lock:
                self._error = e
                self._stats["failures"] += 1.0
        w1 = time.monotonic()
        with self._lock:
            self._phase = "idle"
            st = self._stats
            if ok:
                st["writes"] += 1.0
                st["write_ms_total"] += (w1 - w0) * 1e3
                st["last_write_ms"] = (w1 - w0) * 1e3
        self._events.append({
            "phase": "write", "step": int(step), "ok": ok,
            "t0": w0, "t1": w1,
            "snapshot_t0": snap_t0, "snapshot_t1": snap_t1,
            "enqueue_ms": enq_slot[0] if enq_slot else 0.0,
        })
        self._publish()

    #: test hook: raises planted mid-write failures INSIDE the writer
    #: (after the snapshot, before the commit) — the on-disk shape of a
    #: host that died mid-save, without killing the test process
    _commit_hook = None

    def _prune(self) -> None:
        # failed-write debris first (runs on the writer thread between
        # writes, so nothing of OURS is in flight): any tmp staging
        # dir or markerless digit dir is a dead crash/kill leftover —
        # on the preemptible fleets this engine targets they would
        # otherwise accumulate one full-state payload per eviction.
        # Single-writer only: on a multi-process mesh the directory is
        # shared, and what looks like debris here may be another
        # host's LIVE staging dir (or a final dir whose commit marker
        # has not landed on a non-atomic fs) — there orbax owns its
        # own staging cleanup, so the GC stands down.
        if jax.process_count() == 1:
            try:
                entries = os.listdir(self._directory)
            except OSError:
                entries = []
            for name in entries:
                path = os.path.join(self._directory, name)
                if not os.path.isdir(path):
                    continue
                if ".orbax-checkpoint-tmp-" in name or (
                    name.isdigit()
                    and not _ckpt._is_complete_step_dir(path)
                ):
                    shutil.rmtree(path, ignore_errors=True)
        if self._max_to_keep is None:
            return
        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self._max_to_keep)]:
            shutil.rmtree(
                os.path.join(self._directory, str(s)), ignore_errors=True
            )

    # -- telemetry ---------------------------------------------------------
    def drain_events(self) -> List[dict]:
        """Completed phase records since the last drain (write spans
        land here from the writer thread; ``run_resilient`` forwards
        them to ``observer.on_checkpoint(step, info)``)."""
        out = []
        while True:
            try:
                out.append(self._events.popleft())
            except IndexError:
                return out

    #: below this much wall time since the first save the fraction is
    #: statistically meaningless (one snapshot over a near-zero
    #: denominator reads as a huge stall) — report 0.0 = "no evidence
    #: yet" instead of paging the watchdog on cold start
    MIN_STALL_WINDOW_S = 1.0

    def stall_fraction(self) -> float:
        """Fraction of wall time since the first save that the STEP
        PATH spent inside ``save`` (snapshot + enqueue wait) — the
        number the <1%-overhead acceptance gate pins.  Background
        write time is deliberately excluded: it overlaps training.
        0.0 until :data:`MIN_STALL_WINDOW_S` of wall time has accrued
        (a cold-start fraction over milliseconds is noise, not a
        stall)."""
        with self._lock:
            first_t = self._first_save_t
            stalled_ms = (
                self._stats["snapshot_ms_total"]
                + self._stats["enqueue_wait_ms_total"]
            )
        if first_t is None:
            return 0.0
        wall = time.monotonic() - first_t
        if wall < self.MIN_STALL_WINDOW_S:
            return 0.0
        return min(1.0, (stalled_ms / 1e3) / wall)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._stats)
        out["pending"] = float(self._q.qsize())
        out["stall_frac"] = self.stall_fraction()
        return out

    def _publish(self) -> None:
        from apex_tpu.observability.metrics import board

        with self._lock:
            st = dict(self._stats)
        board.set("goodput/ckpt/saves", st["saves"])
        board.set("goodput/ckpt/writes", st["writes"])
        board.set("goodput/ckpt/failures", st["failures"])
        board.set("goodput/ckpt/last_snapshot_ms", st["last_snapshot_ms"])
        board.set("goodput/ckpt/last_write_ms", st["last_write_ms"])
        board.set("goodput/ckpt/stall_frac", self.stall_fraction())
