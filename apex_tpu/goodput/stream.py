"""Deterministic, resumable streaming input — the batch side of goodput.

``run_resilient``'s data contract is ``batch_fn(step)``: step-indexed,
so rollback replay and auto-resume feed the SAME bytes for the same
step number.  :class:`ResumableStream` implements that contract over
the :class:`apex_tpu.data.DataLoader` stack:

- **deterministic** — batch ``k`` is a pure function of ``(seed,
  epoch, k)`` (the loader's shuffle orders are ``(seed, epoch)``-pure
  and sharded per rank), so two processes with the same config produce
  bit-identical streams;
- **O(1) seek** — a non-sequential step (rollback, resume in a fresh
  process) re-seeks via ``DataLoader.iter_from`` instead of replaying
  and discarding the prefix;
- **prefetching** — ``prefetch=N`` rides a
  :class:`~apex_tpu.data.DevicePrefetcher` behind the cursor (bounded
  backpressure, input-stall gauge on the board); the prefetcher is
  rebuilt on seek so its lookahead never leaks stale batches across a
  rollback;
- **checkpointable** — :meth:`state` is a flat dict of numpy scalars
  (a pytree leaf like any other), carried INSIDE the training state so
  every checkpoint pins the exact stream position plus the identity
  (seed / shard / batch geometry) it is only valid for.
  :func:`verify_stream_state` re-checks that identity on resume: a
  restored cursor silently applied to a reseeded or resharded loader
  would *look* fine and train on the wrong data — the mismatch must be
  loud.

See ``docs/goodput.md`` ("Resume semantics").
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "StreamStateError",
    "ResumableStream",
    "stream_state",
    "verify_stream_state",
]

_STATE_VERSION = 1

#: the identity fields a resumed cursor is only valid for — a mismatch
#: in any of them means the cursor indexes a DIFFERENT stream
_IDENTITY = ("seed", "rank", "world", "batch_size", "shuffle",
             "num_samples")


class StreamStateError(ValueError):
    """A restored stream state does not match the loader it is being
    resumed onto (wrong seed/shard/geometry) or is structurally
    invalid."""


def stream_state(loader, next_batch: int) -> Dict[str, np.ndarray]:
    """The full iterator state as a flat dict of numpy int64 scalars —
    a checkpointable pytree leaf.  ``next_batch`` is the global batch
    index the stream will yield NEXT (epoch and in-epoch position are
    derived, recorded for human readers and cross-checks)."""
    next_batch = int(next_batch)
    if next_batch < 0:
        raise StreamStateError(f"next_batch must be >= 0, got {next_batch}")
    epoch, in_epoch = divmod(next_batch, loader.batches_per_epoch)
    return {
        "version": np.asarray(_STATE_VERSION, np.int64),
        "next_batch": np.asarray(next_batch, np.int64),
        "epoch": np.asarray(epoch, np.int64),
        "batch_in_epoch": np.asarray(in_epoch, np.int64),
        "seed": np.asarray(loader.seed, np.int64),
        "rank": np.asarray(loader.rank, np.int64),
        "world": np.asarray(loader.world, np.int64),
        "batch_size": np.asarray(loader.batch_size, np.int64),
        "shuffle": np.asarray(int(loader.shuffle), np.int64),
        "num_samples": np.asarray(len(loader.dataset), np.int64),
    }


def verify_stream_state(loader, state: Dict[str, Any]) -> int:
    """Validate a restored state against ``loader`` and return the
    ``next_batch`` cursor.  Raises :class:`StreamStateError` naming
    every mismatched identity field — resuming a cursor onto a
    different stream must fail loudly, not train on the wrong data."""
    try:
        version = int(state["version"])
        next_batch = int(state["next_batch"])
    except (KeyError, TypeError, ValueError) as e:
        raise StreamStateError(f"malformed stream state: {e}") from e
    if version != _STATE_VERSION:
        raise StreamStateError(
            f"stream state version {version} != {_STATE_VERSION}"
        )
    expect = stream_state(loader, 0)
    mismatches = [
        f"{k}: saved={int(state[k])} loader={int(expect[k])}"
        for k in _IDENTITY
        if k in state and int(state[k]) != int(expect[k])
    ]
    missing = [k for k in _IDENTITY if k not in state]
    if missing:
        mismatches.append(f"missing fields: {missing}")
    if mismatches:
        raise StreamStateError(
            "restored stream state does not match this loader — the "
            "cursor indexes a different sample sequence: "
            + "; ".join(mismatches)
        )
    return next_batch


class ResumableStream:
    """Step-indexed ``batch_fn`` over a :class:`~apex_tpu.data.
    DataLoader` with O(1) reseek and optional device prefetch.

    >>> stream = ResumableStream(loader, prefetch=2)
    >>> run_resilient(step_fn, state, stream, directory=d, ...)
    >>> stream.close()

    Calling ``stream(step)`` yields the batch for global step ``step``
    (one loader batch per step).  Sequential calls ride one iterator
    (and its prefetcher); any jump — backwards after a rollback,
    forwards after a resume — re-seeks.  ``state(next_step)`` /
    :func:`verify_stream_state` round-trip the cursor through a
    checkpoint.
    """

    def __init__(self, loader, *, prefetch: int = 0, sharding=None):
        self.loader = loader
        self.prefetch = int(prefetch)
        self.sharding = sharding
        self._it = None
        self._pf = None
        self._expect: Optional[int] = None
        self.seeks = 0  # non-sequential repositionings (rollback/resume)

    # -- the batch_fn contract ---------------------------------------------
    def __call__(self, step: int):
        step = int(step)
        if step < 0:
            raise IndexError(f"batch step must be >= 0, got {step}")
        if self._it is None or step != self._expect:
            self._seek(step)
        batch = next(self._it)
        self._expect = step + 1
        return batch

    def _seek(self, step: int) -> None:
        if self._it is not None:
            self.seeks += 1
        self._close_prefetcher()
        src = self.loader.iter_from(step)
        if self.prefetch > 0:
            from apex_tpu.data import DevicePrefetcher

            self._pf = DevicePrefetcher(
                src, device=self.sharding, depth=self.prefetch
            )
            self._it = iter(self._pf)
        else:
            self._it = src
        self._expect = step

    # -- checkpoint round-trip ---------------------------------------------
    def state(self, next_step: Optional[int] = None) -> Dict[str, np.ndarray]:
        """The checkpointable cursor.  ``next_step`` defaults to the
        stream's own position (the step it would serve next)."""
        if next_step is None:
            next_step = self._expect if self._expect is not None else 0
        return stream_state(self.loader, next_step)

    def verify(self, state: Dict[str, Any]) -> int:
        """Validate a restored state against this stream's loader and
        return its ``next_batch`` cursor (raises on identity drift)."""
        return verify_stream_state(self.loader, state)

    def stall_fraction(self) -> float:
        """The prefetcher's input-stall fraction (0.0 without
        prefetch) — the host-side counterpart of the attribution
        layer's host-stall bucket."""
        return self._pf.stall_fraction if self._pf is not None else 0.0

    # -- lifecycle ---------------------------------------------------------
    def _close_prefetcher(self) -> None:
        if self._pf is not None:
            self._pf.close()
            self._pf = None

    def close(self) -> None:
        self._close_prefetcher()
        self._it = None
        self._expect = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
