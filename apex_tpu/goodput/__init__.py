"""``apex_tpu.goodput`` — the zero-stall I/O plane between trainer and host.

ROADMAP item 5: on a preemptible fleet the cheapest capacity is the
capacity you can lose at any moment, and what decides whether that is
viable is **goodput** — the fraction of executed steps that survive as
saved progress.  Two things erode it: checkpoint writes riding the
step path (stall per save), and input pipelines that cannot resume
mid-stream (replayed or skipped work per eviction).  This package
removes both:

- :mod:`apex_tpu.goodput.async_ckpt` —
  :class:`~apex_tpu.goodput.async_ckpt.AsyncCheckpointEngine`:
  copy-on-snapshot to host buffers (async device→host, overlapping
  the running step), a background writer driving the sharded orbax
  save with atomic step-dir commit, a barrier only at finalize, and a
  phase-event stream the span/health layers consume
  (``ckpt/snapshot`` / ``ckpt/write`` / ``ckpt/finalize`` on the
  Perfetto timeline; ``goodput/ckpt/stall_frac`` on the board).
- :mod:`apex_tpu.goodput.stream` —
  :class:`~apex_tpu.goodput.stream.ResumableStream`: a deterministic
  step-indexed ``batch_fn`` over the :mod:`apex_tpu.data` loader with
  O(1) seek, bounded-backpressure device prefetch, and a fully
  checkpointable cursor (:func:`~apex_tpu.goodput.stream.stream_state`
  / :func:`~apex_tpu.goodput.stream.verify_stream_state`) saved inside
  every checkpoint — resume continues the exact sample sequence, so a
  stormed run's loss trajectory is bit-identical to an uninterrupted
  one.

``run_resilient`` / ``TrainStep.fit`` adopt the engine by default
(``checkpoint="async"``); the proof rides ``tools/goodput_drill.py``
and ``bench.py --config goodput`` (the verify_tier1 GOODPUT gate: ≥99%
goodput under an ``APEX_TPU_CHAOS`` preemption storm, bit-exact
resumed losses, <1% checkpoint stall).  See ``docs/goodput.md``.
"""

from apex_tpu.goodput.async_ckpt import (  # noqa: F401
    AsyncCheckpointEngine,
    host_snapshot,
    resolve_queue_depth,
)
from apex_tpu.goodput.stream import (  # noqa: F401
    ResumableStream,
    StreamStateError,
    stream_state,
    verify_stream_state,
)

__all__ = [
    "AsyncCheckpointEngine",
    "host_snapshot",
    "resolve_queue_depth",
    "ResumableStream",
    "StreamStateError",
    "stream_state",
    "verify_stream_state",
]
