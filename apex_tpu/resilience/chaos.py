"""Deterministic fault injection — the test double for everything that can
go wrong in a long training run.

A *fault* names an injection site, the steps (or a seeded per-step
probability) at which it fires, and a mode (what failure to fake).  The
schedule is a pure function of ``(seed, site, mode, step)`` — a SHA-256
coin, not ``random`` — so every host of a multi-process job, and every
re-execution of a test, injects the exact same faults.

Sites and their modes (the **registered-site registry** — a spec
clause naming a site or mode outside it raises at parse time, so a
typo'd drill can never silently inject nothing and "pass"):

========================  ==========================================
``GRADS``                 ``nan`` / ``inf`` poison a gradient pytree
``CHECKPOINT_SAVE``       ``raise`` / ``partial`` (debris then raise)
``CHECKPOINT_RESTORE``    ``raise``
``COLLECTIVE``            ``raise`` / ``stall``
``RENDEZVOUS``            ``raise`` / ``stall``
``PREEMPTION``            SIGTERM to the current process
``SERVE_PREFILL``         ``raise`` / ``stall`` / ``nan`` (poison)
``SERVE_DECODE``          ``raise`` / ``stall`` / ``nan`` / ``inf``
``SERVE_ADMISSION``       ``raise`` / ``stall``
``SERVE_KV_ALLOC``        ``fail`` (forced alloc failure) / ``raise``
``SERVE_PREFIX_EVICT``    ``force`` (forced prefix-cache eviction)
``SERVE_DRAFT``           ``raise`` / ``stall`` / ``nan`` (poison)
========================  ==========================================

The ``serve.*`` sites live in the serving path
(:mod:`apex_tpu.serve.engine` / :mod:`apex_tpu.serve.scheduler`), so
ONE ``APEX_TPU_CHAOS`` spec drives training and serving drills through
the same parser, coin, and hit accounting.  Subsystems can extend the
registry with :func:`register_site`.

Activation is explicit (:func:`configure` / the :func:`inject` context
manager, used by tests) or ambient via ``APEX_TPU_CHAOS`` for real runs::

    APEX_TPU_CHAOS="grads:nan@3,7;checkpoint_save:raise@5;preemption@12"
    APEX_TPU_CHAOS="grads:nan:p=0.001;seed=42"

Hooks are host-side and fire only where training code calls them
(``apex_tpu.resilience.guards`` / ``runner`` / ``retry`` are the built-in
call sites); with no faults configured every hook is a cheap no-op.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import signal
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "GRADS",
    "CHECKPOINT_SAVE",
    "CHECKPOINT_RESTORE",
    "COLLECTIVE",
    "RENDEZVOUS",
    "PREEMPTION",
    "SERVE_PREFILL",
    "SERVE_DECODE",
    "SERVE_ADMISSION",
    "SERVE_KV_ALLOC",
    "SERVE_PREFIX_EVICT",
    "SERVE_DRAFT",
    "FLEET_REPLICA_CRASH",
    "FLEET_PREEMPT",
    "FLEET_ROUTER",
    "Fault",
    "InjectedFault",
    "register_site",
    "registered_sites",
    "site_modes",
    "configure",
    "clear",
    "inject",
    "faults",
    "active",
    "parse_spec",
    "corrupt_tree",
    "maybe_fail",
    "maybe_stall",
    "maybe_preempt",
]

GRADS = "grads"
CHECKPOINT_SAVE = "checkpoint_save"
CHECKPOINT_RESTORE = "checkpoint_restore"
COLLECTIVE = "collective"
RENDEZVOUS = "rendezvous"
PREEMPTION = "preemption"
#: serving-path sites (docs/serving.md "Failure semantics"): hooks
#: live in apex_tpu/serve/engine.py (prefill/decode) and scheduler.py
#: (admission / page allocation)
SERVE_PREFILL = "serve.prefill"
SERVE_DECODE = "serve.decode"
SERVE_ADMISSION = "serve.admission"
SERVE_KV_ALLOC = "serve.kv_alloc"
#: forces a full prefix-cache eviction sweep at a scheduler step (the
#: drill proving eviction under pressure never corrupts a borrowed
#: stream — borrowed pages are refcount-pinned and survive the sweep)
SERVE_PREFIX_EVICT = "serve.prefix_evict"
#: faults the speculative draft-decode program (docs/serving.md
#: "Speculative decoding"): ``raise`` makes the scheduler fall back to
#: plain decode for the round, ``nan`` poisons the draft proposals —
#: the verify step rejects every poisoned token, so a faulted draft
#: can slow a stream but NEVER corrupt it.  Indices are spec rounds.
SERVE_DRAFT = "serve.draft"
#: fleet-control-plane sites (docs/serving.md "Fleet operations"):
#: hooks live in apex_tpu/fleetctl — ``fleet.replica_crash`` kills a
#: replica mid-iteration (its live requests evacuate under the shared
#: retry budget), ``fleet.preempt`` delivers a SIGTERM-style preempt
#: notice (drain + migrate), ``fleet.router`` faults one routing
#: attempt (the request stays at the fleet door and re-routes next
#: tick).  Indices are fleet ticks.
FLEET_REPLICA_CRASH = "fleet.replica_crash"
FLEET_PREEMPT = "fleet.preempt"
FLEET_ROUTER = "fleet.router"

#: site -> (allowed modes, default mode).  parse_spec and Fault both
#: validate against this registry: an unknown site OR an unknown mode
#: raises instead of building a fault that never fires.
_SITE_REGISTRY: Dict[str, Tuple[Tuple[str, ...], str]] = {}


def register_site(
    site: str, modes: Tuple[str, ...], default_mode: Optional[str] = None,
) -> str:
    """Register an injection site and its legal modes (idempotent for
    an identical re-registration; conflicting modes raise).  Returns
    the site name so callers can do ``SITE = register_site(...)``."""
    if not site or not modes:
        raise ValueError("a chaos site needs a name and at least one mode")
    default_mode = default_mode or modes[0]
    if default_mode not in modes:
        raise ValueError(
            f"default mode {default_mode!r} not in modes {modes} "
            f"for site {site!r}"
        )
    spec = (tuple(modes), default_mode)
    prev = _SITE_REGISTRY.get(site)
    if prev is not None and prev != spec:
        raise ValueError(
            f"chaos site {site!r} already registered with modes "
            f"{prev[0]} (default {prev[1]!r})"
        )
    _SITE_REGISTRY[site] = spec
    return site


def registered_sites() -> Tuple[str, ...]:
    return tuple(_SITE_REGISTRY)


def site_modes(site: str) -> Tuple[str, ...]:
    """The legal modes of a registered site (KeyError on unknown)."""
    return _SITE_REGISTRY[site][0]


register_site(GRADS, ("nan", "inf"), "nan")
register_site(CHECKPOINT_SAVE, ("raise", "partial", "stall"), "raise")
register_site(CHECKPOINT_RESTORE, ("raise", "stall"), "raise")
register_site(COLLECTIVE, ("raise", "stall"), "raise")
register_site(RENDEZVOUS, ("raise", "stall"), "raise")
register_site(PREEMPTION, ("raise",), "raise")  # mode is vestigial
register_site(SERVE_PREFILL, ("raise", "stall", "nan"), "raise")
register_site(SERVE_DECODE, ("raise", "stall", "nan", "inf"), "raise")
register_site(SERVE_ADMISSION, ("raise", "stall"), "raise")
register_site(SERVE_KV_ALLOC, ("fail", "raise"), "fail")
register_site(SERVE_PREFIX_EVICT, ("force",), "force")
register_site(SERVE_DRAFT, ("raise", "stall", "nan"), "raise")
register_site(FLEET_REPLICA_CRASH, ("kill",), "kill")
register_site(FLEET_PREEMPT, ("notice",), "notice")
register_site(FLEET_ROUTER, ("raise",), "raise")


class InjectedFault(RuntimeError):
    """Raised by a chaos hook standing in for a real infrastructure error."""

    def __init__(self, site: str, step: int, mode: str):
        super().__init__(
            f"injected {mode!r} fault at site {site!r}, step {step}"
        )
        self.site = site
        self.step = step
        self.mode = mode


@dataclasses.dataclass(frozen=True)
class Fault:
    """One injection rule.

    ``steps`` wins over ``probability``; ``max_hits`` bounds how many times
    the rule fires over its lifetime (e.g. ``max_hits=1`` makes a save fail
    once and heal on retry).  ``stall_seconds`` applies to ``stall`` mode.
    """

    site: str
    steps: Tuple[int, ...] = ()
    probability: float = 0.0
    mode: str = "raise"
    max_hits: Optional[int] = None
    stall_seconds: float = 0.05

    def __post_init__(self):
        if self.site not in _SITE_REGISTRY:
            raise ValueError(
                f"unknown chaos site {self.site!r}; one of "
                f"{registered_sites()}"
            )
        modes = _SITE_REGISTRY[self.site][0]
        if self.mode not in modes:
            raise ValueError(
                f"unknown mode {self.mode!r} for chaos site "
                f"{self.site!r}; one of {modes}"
            )


_FAULTS: List[Fault] = []
_SEED: int = 0
_HITS: Dict[int, int] = {}  # id(index in _FAULTS) -> times fired
_ENV_LOADED = False


def configure(*new_faults: Fault, seed: int = 0) -> None:
    """Replace the active fault set (and reset hit counters)."""
    global _SEED
    _FAULTS[:] = list(new_faults)
    _SEED = seed
    _HITS.clear()


def clear() -> None:
    """Remove every active fault."""
    configure()


class inject:
    """Context manager: activate faults inside, restore the prior set after.

    >>> with chaos.inject(chaos.Fault(chaos.GRADS, steps=(3,), mode="nan")):
    ...     train()
    """

    def __init__(self, *new_faults: Fault, seed: int = 0):
        self._new = new_faults
        self._seed = seed

    def __enter__(self):
        self._prev = (list(_FAULTS), _SEED, dict(_HITS))
        configure(*self._new, seed=self._seed)
        return self

    def __exit__(self, *exc):
        prev_faults, prev_seed, prev_hits = self._prev
        configure(*prev_faults, seed=prev_seed)
        _HITS.update(prev_hits)


def _load_env() -> None:
    """One-shot pickup of ``APEX_TPU_CHAOS`` (real runs, no code changes)."""
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    spec = os.environ.get("APEX_TPU_CHAOS")
    if spec and not _FAULTS:
        env_faults, seed = parse_spec(spec)
        configure(*env_faults, seed=seed)


def parse_spec(spec: str) -> Tuple[Tuple[Fault, ...], int]:
    """Parse an ``APEX_TPU_CHAOS`` spec string.

    ``;``-separated clauses of ``site[:mode][:p=0.01][:xN][@s1,s2]`` plus
    an optional ``seed=N`` clause (``xN`` bounds the fault to N firings —
    a transient that heals on retry).  Examples::

        grads:nan@3,7               # NaN grads at steps 3 and 7
        checkpoint_save:raise:x1@5  # ONE save IO error at step 5 (heals)
        preemption@12               # SIGTERM at step 12
        grads:inf:p=0.001           # seeded 0.1%-per-step Inf burst
        serve.decode:nan@9          # poisoned logits at decode iter 9

    Sites and modes are validated against the registered-site registry
    — an unknown site (``grdas:...``) or a typo'd token that would
    otherwise be swallowed as a bogus mode (``grads:nan:p0.001``)
    raises ``ValueError`` naming the clause, instead of yielding a
    fault that silently never fires while a chaos drill "passes".
    """
    out: List[Fault] = []
    seed = 0
    for clause in filter(None, (c.strip() for c in spec.split(";"))):
        if clause.startswith("seed="):
            seed = int(clause[len("seed=") :])
            continue
        steps: Tuple[int, ...] = ()
        probability = 0.0
        max_hits: Optional[int] = None
        raw = clause
        if "@" in clause:
            clause, _, steplist = clause.partition("@")
            steps = tuple(int(s) for s in steplist.split(",") if s)
        parts = clause.split(":")
        site, rest = parts[0], parts[1:]
        if site not in _SITE_REGISTRY:
            raise ValueError(
                f"unknown chaos site {site!r} in spec clause {raw!r}; "
                f"registered sites: {registered_sites()}"
            )
        modes, default_mode = _SITE_REGISTRY[site]
        mode = None
        for token in rest:
            if token.startswith("p="):
                probability = float(token[2:])
            elif token.startswith("x") and token[1:].isdigit():
                max_hits = int(token[1:])
            elif token in modes:
                mode = token
            else:
                raise ValueError(
                    f"unknown token {token!r} in spec clause {raw!r}: "
                    f"not a mode of site {site!r} {modes}, a "
                    f"probability (p=F), or a hit bound (xN)"
                )
        if mode is None:
            mode = default_mode
        out.append(
            Fault(
                site=site,
                steps=steps,
                probability=probability,
                mode=mode,
                max_hits=max_hits,
            )
        )
    return tuple(out), seed


def faults() -> Tuple[Fault, ...]:
    _load_env()
    return tuple(_FAULTS)


def _coin(site: str, mode: str, step: int, p: float) -> bool:
    digest = hashlib.sha256(
        f"{_SEED}:{site}:{mode}:{step}".encode()
    ).digest()
    frac = int.from_bytes(digest[:8], "big") / 2.0**64
    return frac < p


def active(site: str, step: int) -> Optional[Fault]:
    """The fault scheduled at ``(site, step)``, if any (counts the hit)."""
    _load_env()
    for i, f in enumerate(_FAULTS):
        if f.site != site:
            continue
        if f.max_hits is not None and _HITS.get(i, 0) >= f.max_hits:
            continue
        # steps wins over probability (the Fault contract): an explicit
        # schedule pins the fault to exactly those steps.
        if f.steps:
            hit = step in f.steps
        else:
            hit = f.probability > 0.0 and _coin(
                f.site, f.mode, step, f.probability
            )
        if hit:
            _HITS[i] = _HITS.get(i, 0) + 1
            return f
    return None


# ---------------------------------------------------------------------------
# hooks
# ---------------------------------------------------------------------------


def corrupt_tree(tree, step: int, site: str = GRADS):
    """Return ``tree`` with its first leaf poisoned when scheduled.

    One leaf is enough to trip every downstream non-finite detector
    (``scale_with_overflow_check`` reduces over the whole tree) while
    keeping the rest of the pipeline realistic.  No-op when idle.
    """
    fault = active(site, step)
    if fault is None:
        return tree
    poison = jnp.nan if fault.mode == "nan" else jnp.inf
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if leaves:
        leaves[0] = jnp.full_like(leaves[0], poison)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def maybe_fail(site: str, step: int, partial_dir=None) -> None:
    """Raise :class:`InjectedFault` when a ``raise``/``partial`` fault is
    scheduled at ``(site, step)``; stall (and return) in ``stall`` mode.

    ``partial`` mode first drops orbax-style uncommitted debris
    (``<step>.orbax-checkpoint-tmp-*``) under ``partial_dir`` — the
    on-disk shape of a host that died mid-write — then raises.
    """
    fault = active(site, step)
    if fault is None:
        return
    if fault.mode == "stall":
        time.sleep(fault.stall_seconds)
        return
    if fault.mode == "partial" and partial_dir is not None:
        debris = os.path.join(
            os.fspath(partial_dir),
            f"{step}.orbax-checkpoint-tmp-{os.getpid()}",
        )
        os.makedirs(debris, exist_ok=True)
        with open(os.path.join(debris, "params"), "w") as f:
            f.write("torn write\n")
    raise InjectedFault(site, step, fault.mode)


def maybe_stall(site: str, step: int) -> float:
    """Sleep when a ``stall`` fault is scheduled; returns seconds slept."""
    fault = active(site, step)
    if fault is not None and fault.mode == "stall":
        time.sleep(fault.stall_seconds)
        return fault.stall_seconds
    return 0.0


def maybe_preempt(step: int) -> bool:
    """Deliver SIGTERM to this process when a preemption is scheduled.

    Goes through the real signal machinery so the handler installed by
    :class:`apex_tpu.resilience.runner.PreemptionHandler` is exercised
    exactly as a cloud preemption notice would exercise it.
    """
    if active(PREEMPTION, step) is None:
        return False
    os.kill(os.getpid(), signal.SIGTERM)
    return True
