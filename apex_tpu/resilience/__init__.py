"""Fault tolerance — make a run survive what production throws at it.

Four layers, composable or standalone:

- :mod:`apex_tpu.resilience.chaos` — deterministic, seedable fault
  injection (NaN grads, checkpoint I/O failure, collective stall/abort,
  host preemption), driven from tests or the ``APEX_TPU_CHAOS`` env var.
- :mod:`apex_tpu.resilience.guards` — a guarded optimizer step over
  ``amp_update``: overflow *and* grad-norm-spike detection with a
  consecutive-skip budget; bad steps are skipped device-side, params
  untouched.
- :mod:`apex_tpu.resilience.retry` — bounded-backoff retry for the
  distributed rendezvous (retry-then-raise, never silent single-process
  degrade) and checkpoint I/O.
- :mod:`apex_tpu.resilience.runner` — ``run_resilient``: SIGTERM-safe
  training loop with ``latest_step()`` auto-resume and skip-budget
  rollback to the last complete checkpoint.

See ``docs/resilience.md`` for the failure model and recovery semantics.
"""

from apex_tpu.resilience import chaos  # noqa: F401
from apex_tpu.resilience.guards import (  # noqa: F401
    GradGuard,
    GuardState,
    GuardVerdict,
    guard_metrics,
    guarded_amp_update,
)
from apex_tpu.resilience.retry import (  # noqa: F401
    RetryPolicy,
    add_retry_listener,
    remove_retry_listener,
    retry_call,
    robust_initialize_distributed,
)
from apex_tpu.resilience.runner import (  # noqa: F401
    ObserverFanout,
    PreemptionHandler,
    ResilientCheckpointManager,
    RunResult,
    run_resilient,
)

__all__ = [
    "chaos",
    "GradGuard",
    "GuardState",
    "GuardVerdict",
    "guard_metrics",
    "guarded_amp_update",
    "RetryPolicy",
    "add_retry_listener",
    "remove_retry_listener",
    "retry_call",
    "robust_initialize_distributed",
    "ObserverFanout",
    "PreemptionHandler",
    "ResilientCheckpointManager",
    "RunResult",
    "run_resilient",
]
