"""Preemption-safe auto-resume training loop.

:func:`run_resilient` composes the rest of the resilience stack into the
loop a production job actually runs:

- **auto-resume** — on startup, ``latest_step()`` of the checkpoint
  directory decides where training continues; a fresh directory starts at
  step 0.  Restarting the same command after any crash/preemption resumes
  from the last *complete* checkpoint (orbax commits atomically; an
  interrupted save is invisible to ``latest_step``).
- **guarded steps** — the caller's ``step_fn`` reports whether the step
  was skipped (e.g. the ``GuardVerdict`` from
  :func:`apex_tpu.resilience.guards.guarded_amp_update`); after
  ``rollback_after`` consecutive skips the loop restores the last
  checkpoint and replays, instead of skipping forever on corrupted state.
- **preemption** — SIGTERM (the cloud eviction notice) sets a flag via
  :class:`PreemptionHandler`; the loop finishes the in-flight step, writes
  a final checkpoint, and returns cleanly with ``preempted=True``.
- **retries** — checkpoint I/O goes through
  :class:`ResilientCheckpointManager`, which wraps save/restore in
  :func:`apex_tpu.resilience.retry.retry_call` and honors the chaos
  ``CHECKPOINT_SAVE`` / ``CHECKPOINT_RESTORE`` sites.

``step_fn(state, batch) -> (state, info)`` with ``info`` anything that has
a ``skipped`` entry/attribute (or None).  ``batch_fn(step) -> batch`` is
indexed by step so replay after rollback/resume feeds the same data.

The loop narrates itself to an optional ``observer`` (duck-typed; every
method optional): ``on_step(step, skipped, info)`` per executed step,
``on_rollback(step, anchor, skips, discarded)``, ``on_resume(step)``,
``on_preempt(step)``, ``on_checkpoint(step)`` when a save is enqueued
— and, on the default async engine, ``on_checkpoint(step, info)``
again when the background write/finalize completes, ``info`` carrying
the phase's monotonic span timings (the ``ckpt/*`` intervals on the
Perfetto timeline) — and ``on_retry(what, attempt, error)`` for
checkpoint-I/O retries (bridged from
:mod:`apex_tpu.resilience.retry` for the duration of the run).
``discarded`` is the EXACT count of accepted-but-unsaved steps the
rollback threw away — the runner tracks them against actual save
results, so interleaved skip/accept streaks inside the replay span are
priced correctly.  :class:`apex_tpu.observability.GoodputAccountant`
implements the whole protocol and turns the stream into a goodput
number.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Any, Callable, NamedTuple, Optional, Tuple

from apex_tpu.checkpoint import CheckpointManager
from apex_tpu.resilience import chaos
from apex_tpu.resilience import retry as _retry
from apex_tpu.resilience.retry import RetryPolicy, retry_call

__all__ = [
    "PreemptionHandler",
    "ResilientCheckpointManager",
    "ObserverFanout",
    "RunResult",
    "run_resilient",
]


class PreemptionHandler:
    """Context manager turning SIGTERM into a queryable flag.

    The handler only records the request (async-signal-safe); the training
    loop decides when to act — after the in-flight step, before the next.
    Outside the main thread (where CPython forbids ``signal.signal``) it
    degrades to a never-set flag instead of failing.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = signals
        self._prev = {}
        self._event = threading.Event()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def _on_signal(self, signum, frame):
        self._event.set()

    def __enter__(self):
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._on_signal)
            except ValueError:  # not the main thread
                pass
        return self

    def __exit__(self, *exc):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()


class ResilientCheckpointManager:
    """Checkpoint engine + retry + chaos, behind one manager surface.

    ``engine="async"`` (the default) rides the
    :class:`apex_tpu.goodput.AsyncCheckpointEngine` — copy-on-snapshot
    to host, background write, barrier only at finalize — so the step
    path never pays the write (docs/goodput.md).  ``engine="sync"``
    keeps the orbax :class:`apex_tpu.checkpoint.CheckpointManager`;
    its saves get the same **copy-on-snapshot isolation** here (the
    state is host-snapshotted ONCE before the enqueue), so a caller
    mutating or donating the state right after ``save`` returns can
    never corrupt the written checkpoint on either engine.

    Save/restore I/O errors are retried per ``policy`` and only then
    raised.  The chaos ``partial`` save mode drops orbax-style
    uncommitted debris (``<step>.orbax-checkpoint-tmp-*``) into the
    directory before failing — the on-disk shape of a host that died
    mid-write — which is exactly what ``latest_step`` must ignore.

    Scope note: saves are *async* on both engines — ``save`` returns
    after the enqueue, so the retry here covers the enqueue path (plus
    any deferred background-write error surfaced at the next ``save``
    call; retrying that call clears the stale error and re-queues the
    current step).  A background write that fails permanently loses
    that one step's checkpoint, never crash consistency: the
    incomplete step stays invisible to ``latest_step`` and resume
    falls back one interval.
    """

    def __init__(
        self,
        directory,
        *,
        max_to_keep: Optional[int] = None,
        save_interval_steps: int = 1,
        policy: Optional[RetryPolicy] = None,
        engine: str = "async",
    ):
        self._directory = os.path.abspath(os.fspath(directory))
        if engine == "async":
            from apex_tpu.goodput import AsyncCheckpointEngine

            self._inner = AsyncCheckpointEngine(
                self._directory,
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
            )
        elif engine == "sync":
            self._inner = CheckpointManager(
                self._directory,
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
            )
        else:
            raise ValueError(
                f"engine must be 'async' or 'sync', got {engine!r}"
            )
        #: which save engine backs this manager ("async" | "sync") —
        #: run_resilient keys its durability bookkeeping on it
        self.engine = engine
        self._policy = policy or RetryPolicy(backoff=0.05, max_backoff=1.0)

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self._inner.close()

    def wait_until_finished(self):
        self._inner.wait_until_finished()

    # -- delegated queries -------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._inner.latest_step()

    def all_steps(self):
        return self._inner.all_steps()

    def should_save(self, step: int) -> bool:
        return self._inner.should_save(step)

    # -- guarded io --------------------------------------------------------
    def drain_events(self):
        """Completed checkpoint phase events (async engine only; [] on
        sync) — ``run_resilient`` forwards them to ``on_checkpoint``."""
        drain = getattr(self._inner, "drain_events", None)
        return drain() if drain is not None else []

    def stats(self):
        """The async engine's cumulative ledger ({} on sync)."""
        stats = getattr(self._inner, "stats", None)
        return stats() if stats is not None else {}

    def save(self, step: int, state, *, force: bool = False) -> bool:
        if self.engine == "sync" and (
            force or self._inner.should_save(step)
        ):
            # copy-on-snapshot for the sync path too (the async engine
            # snapshots internally, inside its own stall accounting):
            # the orbax enqueue must never hold live caller buffers —
            # state mutated after save() returns stays out of the file.
            # Gated on the interval policy: run_resilient calls save on
            # every accepted step, and paying a full host copy of the
            # state on interval-skipped steps would be a step-path
            # stall, not isolation.  ONE snapshot, outside the retry
            # closure: retries re-use it.
            from apex_tpu.goodput import host_snapshot

            state = host_snapshot(state)

        def _save():
            chaos.maybe_fail(
                chaos.CHECKPOINT_SAVE, step, partial_dir=self._directory
            )
            return self._inner.save(step, state, force=force)

        return retry_call(
            _save,
            policy=self._policy,
            describe=f"checkpoint save (step {step})",
        )

    def restore(self, step: Optional[int] = None, *, template=None):
        def _restore():
            chaos.maybe_fail(
                chaos.CHECKPOINT_RESTORE,
                step if step is not None else (self.latest_step() or 0),
            )
            return self._inner.restore(step, template=template)

        return retry_call(
            _restore,
            policy=self._policy,
            describe=f"checkpoint restore (step {step})",
        )


class RunResult(NamedTuple):
    state: Any
    last_step: int  # last completed step index; -1 when nothing ran
    resumed_from: Optional[int]  # checkpoint step training continued from
    steps_run: int  # steps executed by THIS invocation
    skipped_steps: int  # steps the guard dropped (this invocation)
    rollbacks: int  # checkpoint rollbacks (this invocation)
    preempted: bool  # stopped early on SIGTERM


class ObserverFanout:
    """Compose several ``run_resilient`` observers into one.

    Each event forwards to every child that implements it, in order;
    observer errors propagate (the same contract as a single observer —
    a telemetry bug must not silently corrupt the ledgers).  ``None``
    entries are dropped so call sites can write
    ``ObserverFanout([goodput, watchdog, maybe_none])``.
    """

    def __init__(self, observers):
        self.observers = [o for o in observers if o is not None]

    def _fan(self, event: str, *args) -> None:
        for o in self.observers:
            fn = getattr(o, event, None)
            if fn is not None:
                fn(*args)

    def on_step(self, *args) -> None:
        self._fan("on_step", *args)

    def on_rollback(self, *args) -> None:
        self._fan("on_rollback", *args)

    def on_resume(self, *args) -> None:
        self._fan("on_resume", *args)

    def on_preempt(self, *args) -> None:
        self._fan("on_preempt", *args)

    def on_checkpoint(self, step, info=None) -> None:
        # per-child arity adaptation: a legacy 1-arg child still gets
        # the enqueue instants; only 2-arg children see phase records
        for o in self.observers:
            fn = getattr(o, "on_checkpoint", None)
            if fn is None:
                continue
            if info is None:
                fn(step)
            elif _takes_checkpoint_info(fn):
                fn(step, info)

    def on_retry(self, *args, **kwargs) -> None:
        for o in self.observers:
            fn = getattr(o, "on_retry", None)
            if fn is not None:
                fn(*args, **kwargs)


def _safe_dump(recorder, reason: str, label: str = "flight") -> None:
    """Write a recorder dump without masking the failure being dumped."""
    try:
        path = recorder.dump(reason)
        print(f"[{label}] black box written: {path}", flush=True)
    except Exception as e:
        import warnings

        warnings.warn(
            f"{label} dump failed ({type(e).__name__}: {e}) — "
            "continuing with the original failure",
            RuntimeWarning,
        )


def _notify(observer, event: str, *args) -> None:
    """Invoke ``observer.<event>(*args)`` if present.  Observer errors
    propagate — a telemetry bug must not silently corrupt the ledger it
    exists to keep honest."""
    if observer is None:
        return
    fn = getattr(observer, event, None)
    if fn is not None:
        fn(*args)


def _skipped(info) -> bool:
    if info is None:
        return False
    if hasattr(info, "skipped"):
        return bool(info.skipped)
    try:
        return bool(info["skipped"])
    except (TypeError, KeyError, IndexError):
        return False


def run_resilient(
    step_fn: Callable[[Any, Any], Tuple[Any, Any]],
    init_state: Any,
    batch_fn: Callable[[int], Any],
    *,
    directory,
    num_steps: int,
    save_interval_steps: int = 1,
    max_to_keep: Optional[int] = None,
    rollback_after: Optional[int] = None,
    max_rollbacks: int = 3,
    policy: Optional[RetryPolicy] = None,
    signals=(signal.SIGTERM,),
    observer: Any = None,
    flight: Any = None,
    spans: Any = None,
    checkpoint: str = "async",
) -> RunResult:
    """Drive ``step_fn`` for ``num_steps`` with auto-resume, preemption
    handling, checkpoint retries, and skip-budget rollback.

    ``checkpoint`` selects the save engine (docs/goodput.md):
    ``"async"`` (default) snapshots to host and writes in the
    background — the step path pays only the snapshot, in-flight
    writes drain at rollback anchoring / preemption / shutdown, and
    every completed write lands on the observer stream as
    ``on_checkpoint(step, info)`` with enqueue/write span timings;
    ``"sync"`` keeps the orbax manager on the step path.

    Idempotent by construction: call it again after any interruption and
    it continues from the last complete checkpoint.  Returns a
    :class:`RunResult`; ``preempted=True`` means SIGTERM arrived, the
    final checkpoint is on disk, and a relaunch will resume within one
    step of where training stopped.

    Rollback replays the same step-indexed data, so a *deterministic*
    skip cause (a permanently bad batch, not transient state corruption)
    would replay-and-skip forever; after ``max_rollbacks`` rollbacks the
    loop raises instead of livelocking.

    ``flight`` arms a :class:`apex_tpu.observability.flight.
    FlightRecorder` as crash forensics: it joins the observer fan-out
    (frames per step, events per rollback/resume/retry/preempt) and its
    black box is dumped on any unhandled exception — which includes the
    skip-budget ``RuntimeError`` above — and on SIGTERM/preemption.
    When ``flight`` is None, ``APEX_TPU_FLIGHT=N[:DIR]`` arms one from
    the environment with no code changes (no sources attached: frames
    then carry steps/skips/events only).

    ``spans`` arms a :class:`apex_tpu.observability.spans.SpanRecorder`
    the same way: it joins the observer fan-out (one ``train/step``
    span per step, rollback/resume/retry/checkpoint/preempt instants)
    and its record is dumped beside the flight black box on any
    unhandled exception.  When ``spans`` is None,
    ``APEX_TPU_SPANS=N[:DIR]`` arms one from the environment — an
    env-armed recorder additionally dumps at normal completion (a
    timeline of a *good* run is the baseline a postmortem compares
    against); an explicitly passed recorder stays with its caller,
    who decides when to export.
    """
    spans_env_armed = False
    if spans is None:
        from apex_tpu.observability.spans import SpanRecorder

        spans = SpanRecorder.from_env()
        spans_env_armed = spans is not None
    if flight is None:
        from apex_tpu.observability.flight import FlightRecorder

        flight = FlightRecorder.from_env()
    if flight is not None or spans is not None:
        observer = ObserverFanout([observer, flight, spans])
    on_retry = getattr(observer, "on_retry", None)
    if on_retry is not None:
        _retry.add_retry_listener(on_retry)
    try:
        result = _run_resilient_inner(
            step_fn, init_state, batch_fn, directory=directory,
            num_steps=num_steps, save_interval_steps=save_interval_steps,
            max_to_keep=max_to_keep, rollback_after=rollback_after,
            max_rollbacks=max_rollbacks, policy=policy, signals=signals,
            observer=observer, checkpoint=checkpoint,
        )
    except BaseException as e:
        # BaseException on purpose: KeyboardInterrupt / SystemExit are
        # exactly the deaths a black box exists for
        if flight is not None:
            _safe_dump(flight, f"{type(e).__name__}: {e}")
        if spans is not None:
            _safe_dump(spans, f"{type(e).__name__}: {e}", label="spans")
        raise
    finally:
        if on_retry is not None:
            _retry.remove_retry_listener(on_retry)
    if result.preempted:
        if flight is not None:
            _safe_dump(
                flight, f"preemption (SIGTERM) at step {result.last_step}"
            )
        if spans is not None:
            _safe_dump(
                spans,
                f"preemption (SIGTERM) at step {result.last_step}",
                label="spans",
            )
    elif spans_env_armed:
        _safe_dump(spans, "completed", label="spans")
    return result


#: memo for _takes_checkpoint_info, keyed on the underlying function
#: (bound methods are recreated per attribute access; their __func__
#: is stable) — the answer never changes per callable, and paying
#: inspect.signature per phase event per observer would put repeated
#: introspection on the step loop
_CKPT_INFO_ARITY: dict = {}


def _takes_checkpoint_info(fn) -> bool:
    """True if ``fn(step, info)`` is callable — the 2-arg
    ``on_checkpoint`` protocol.  Observers written to the pre-goodput
    protocol (``on_checkpoint(step)`` only) keep working: they get the
    enqueue instants and simply never see the phase records."""
    import inspect

    key = getattr(fn, "__func__", fn)
    try:
        return _CKPT_INFO_ARITY[key]
    except (KeyError, TypeError):  # TypeError: unhashable callable
        pass
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        out = True  # builtins/partials we can't introspect: assume new
    else:
        n = 0
        out = False
        for p in sig.parameters.values():
            if p.kind is p.VAR_POSITIONAL:
                out = True
                break
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                n += 1
        out = out or n >= 2
    try:
        _CKPT_INFO_ARITY[key] = out
    except TypeError:
        pass
    return out


def _drain_writes_best_effort(mgr, where: str) -> None:
    """Drain in-flight writes, but keep a mid-run drain from turning a
    single lost background write into a run abort: the anchor/forced
    save that follows falls back to the previous COMPLETE step — which
    is the failure contract — so warn and continue.  The SHUTDOWN
    drain deliberately does not use this: there the error must
    propagate."""
    try:
        mgr.wait_until_finished()
    except Exception as e:
        import warnings

        warnings.warn(
            f"checkpoint write failed, surfaced at {where} "
            f"({type(e).__name__}: {e}); falling back to the previous "
            "complete checkpoint",
            RuntimeWarning,
        )


def _drain_ckpt_events(mgr, observer):
    """Forward completed checkpoint phases (background writes,
    finalize barriers) onto the observer stream — the span layer
    renders them as ``ckpt/*`` intervals on the Perfetto timeline.
    Legacy 1-arg ``on_checkpoint`` observers are skipped, not crashed:
    the phase records are additive telemetry.  Returns the drained
    events for the caller's own bookkeeping (durability retirement)."""
    events = mgr.drain_events()
    if not events or observer is None:
        return events
    fn = getattr(observer, "on_checkpoint", None)
    if fn is None or not _takes_checkpoint_info(fn):
        return events
    for ev in events:
        fn(ev.get("step"), ev)
    return events


def _run_resilient_inner(
    step_fn, init_state, batch_fn, *, directory, num_steps,
    save_interval_steps, max_to_keep, rollback_after, max_rollbacks,
    policy, signals, observer, checkpoint,
) -> RunResult:
    state = init_state
    resumed_from = None
    steps_run = skipped_steps = rollbacks = 0
    consecutive_skips = 0
    # Accepted steps that a rollback might discard, reconciled against
    # the ACTUAL anchor at rollback time (not at save time: orbax saves
    # are async, so save() returning True only means enqueued).  On a
    # successful save we retain one full prior interval — exactly the
    # "a failed background write falls back one interval" failure mode
    # the ResilientCheckpointManager scope note documents — so the
    # discarded count stays exact through a lost background save;
    # memory stays bounded at ~two save intervals.
    unsaved_accepted = []
    prev_save_step = -1

    with ResilientCheckpointManager(
        directory,
        max_to_keep=max_to_keep,
        save_interval_steps=save_interval_steps,
        policy=policy,
        engine=checkpoint,
    ) as mgr, PreemptionHandler(signals=signals) as preempt:
        latest = mgr.latest_step()
        if latest is not None:
            state = mgr.restore(latest, template=state)
            resumed_from = latest
            _notify(observer, "on_resume", latest)
        start = (latest + 1) if latest is not None else 0
        completed = start - 1

        step = start
        while step < num_steps and not preempt.requested:
            state, info = step_fn(state, batch_fn(step))
            steps_run += 1
            # Simulated eviction lands "while the step runs": checking the
            # flag only after step_fn means the interrupted step still
            # completes and checkpoints, so a relaunch under the same
            # chaos spec (preemption@N fires again in the new process)
            # always makes at least one step of progress.
            chaos.maybe_preempt(step)
            was_skipped = _skipped(info)
            _notify(observer, "on_step", step, was_skipped, info)
            if was_skipped:
                # A skipped step is never checkpointed: its state is by
                # contract unchanged, and recording it would drag the
                # rollback anchor into the middle of the skip streak —
                # the replay must restart from the last ACCEPTED step.
                skipped_steps += 1
                consecutive_skips += 1
                if (
                    rollback_after is not None
                    and consecutive_skips >= rollback_after
                ):
                    if rollbacks >= max_rollbacks:
                        raise RuntimeError(
                            f"step {step}: skip budget exhausted again "
                            f"after {rollbacks} rollbacks — the failure "
                            "replays deterministically; refusing to "
                            "livelock"
                        )
                    _drain_writes_best_effort(mgr, "rollback anchoring")
                    _drain_ckpt_events(mgr, observer)
                    anchor = mgr.latest_step()
                    rollbacks += 1
                    streak = consecutive_skips
                    consecutive_skips = 0
                    anchor_val = anchor if anchor is not None else -1
                    discarded = sum(
                        1 for s in unsaved_accepted if s > anchor_val
                    )
                    # > anchor: discarded; <= anchor: proven durable —
                    # either way no longer at risk
                    unsaved_accepted = []
                    prev_save_step = anchor_val
                    _notify(
                        observer, "on_rollback", step, anchor_val,
                        streak, discarded,
                    )
                    if anchor is not None:
                        state = mgr.restore(anchor, template=init_state)
                        completed = anchor
                        step = anchor + 1
                    else:
                        # no checkpoint yet: restart from the initial state
                        state = init_state
                        completed = -1
                        step = 0
                    continue
            else:
                consecutive_skips = 0
                completed = step
                saved = mgr.save(step, state)
                unsaved_accepted.append(step)
                if saved:
                    if mgr.engine == "sync":
                        # no write-completion events on the sync orbax
                        # manager — keep its one-save-lag approximation:
                        # steps at or before the PREVIOUS save are
                        # presumed durable once this save is enqueued
                        unsaved_accepted = [
                            s for s in unsaved_accepted
                            if s > prev_save_step
                        ]
                        prev_save_step = step
                    # checkpoint ENQUEUED (saves are async): the
                    # event a timeline wants next to rollback anchors
                    _notify(observer, "on_checkpoint", step)
            # completed background writes land on the observer stream
            # as they finish — one cheap deque drain per step.  A
            # CONFIRMED commit is the async engine's durability signal
            # for retiring at-risk steps: an ENQUEUE is not durable —
            # with queue_depth > 1 an older in-flight write can still
            # fail, and `discarded` is documented as EXACT.
            for ev in _drain_ckpt_events(mgr, observer):
                if ev.get("phase") == "write" and ev.get("ok"):
                    durable = int(ev["step"])
                    unsaved_accepted = [
                        s for s in unsaved_accepted if s > durable
                    ]
            step += 1

        if preempt.requested:
            _notify(observer, "on_preempt", completed)
        if preempt.requested and completed >= 0:
            # Final checkpoint so a relaunch resumes within one step.  The
            # step may already be on disk when save_interval_steps == 1.
            # Barrier first (chaos COLLECTIVE site): every host agrees
            # training stopped at `completed` — but best-effort, because a
            # peer already torn down by the eviction must not keep THIS
            # host from reaching its final checkpoint.
            try:
                from apex_tpu.parallel import multihost

                multihost.host_barrier(f"resilient-stop-{completed}", completed)
            except Exception as e:
                import warnings

                warnings.warn(
                    f"pre-checkpoint host barrier failed ({type(e).__name__}:"
                    f" {e}); writing the final checkpoint anyway",
                    RuntimeWarning,
                )
            _drain_writes_best_effort(mgr, "pre-preemption-save drain")
            if completed not in mgr.all_steps():
                mgr.save(completed, state, force=True)
        # the shutdown drain: in-flight background writes commit before
        # the run returns (the finalize barrier — the ONLY blocking
        # point the async engine has).  This one PROPAGATES a deferred
        # write error: a run must never return success claiming a final
        # checkpoint that never reached disk.
        mgr.wait_until_finished()
        _drain_ckpt_events(mgr, observer)
        return RunResult(
            state=state,
            last_step=completed,
            resumed_from=resumed_from,
            steps_run=steps_run,
            skipped_steps=skipped_steps,
            rollbacks=rollbacks,
            preempted=preempt.requested,
        )
