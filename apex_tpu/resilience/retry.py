"""Retry/backoff wrappers for the two flakiest host-side operations of a
pod-scale run: the distributed rendezvous and checkpoint I/O.

The policy is deliberately boring — bounded attempts, exponential backoff,
then *raise*.  The one behavior change worth naming:
:func:`robust_initialize_distributed` replaces the bootstrap's historical
"warn and silently degrade to single-process" response to a failed pod
join with retry-then-raise, because N pod members each quietly training
their own divergent copy is strictly worse than a crashed job.

Chaos integration: every attempt consults
:mod:`apex_tpu.resilience.chaos` (``RENDEZVOUS`` site, step = attempt
index), so tests drive the fail-then-heal path without a real flaky
coordinator.
"""

from __future__ import annotations

import itertools
import time
import warnings
from typing import Callable, Optional, Sequence, Tuple, Type

from apex_tpu.resilience import chaos

__all__ = [
    "RetryPolicy",
    "retry_call",
    "robust_initialize_distributed",
    "add_retry_listener",
    "remove_retry_listener",
]

# Observability bridge: each about-to-be-retried failure is announced to
# the registered listeners as ``fn(what, attempt, error)`` (attempt is
# 0-based).  run_resilient registers its observer's ``on_retry`` here
# for the duration of a run, so retry churn lands in the goodput ledger
# (apex_tpu.observability.GoodputAccountant) without threading a
# callback through every call site.
_LISTENERS: list = []


def add_retry_listener(fn: Callable) -> None:
    _LISTENERS.append(fn)


def remove_retry_listener(fn: Callable) -> None:
    if fn in _LISTENERS:
        _LISTENERS.remove(fn)


class RetryPolicy:
    """Bounded exponential backoff: ``backoff * factor**attempt``, capped.

    ``max_attempts`` counts total tries (first try included), so
    ``max_attempts=1`` means no retry.  ``sleep`` is injectable for tests.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        backoff: float = 0.5,
        factor: float = 2.0,
        max_backoff: float = 30.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.backoff = backoff
        self.factor = factor
        self.max_backoff = max_backoff
        self.sleep = sleep

    def delay(self, attempt: int) -> float:
        return min(self.backoff * self.factor**attempt, self.max_backoff)


def retry_call(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    describe: str = "",
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``; retry per ``policy`` on ``retry_on``.

    Each failed attempt emits a ``RuntimeWarning`` naming the attempt and
    the error (a silent retry hides a sick filesystem until the run dies);
    the final failure re-raises the last exception unchanged.
    """
    policy = policy or RetryPolicy()
    what = describe or getattr(fn, "__name__", repr(fn))
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            last = e
            if attempt + 1 >= policy.max_attempts:
                break
            for listener in list(_LISTENERS):
                listener(what, attempt, e)
            pause = policy.delay(attempt)
            warnings.warn(
                f"{what} failed (attempt {attempt + 1}/"
                f"{policy.max_attempts}: {type(e).__name__}: {e}); "
                f"retrying in {pause:.2g}s",
                RuntimeWarning,
                stacklevel=2,
            )
            policy.sleep(pause)
    assert last is not None
    raise last


def robust_initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
    policy: Optional[RetryPolicy] = None,
) -> Tuple[int, int]:
    """Join the global JAX runtime, retrying a flaky rendezvous.

    Semantics vs :func:`apex_tpu.parallel.initialize_distributed`:

    - no cluster environment, no coordinator given → same benign
      single-process no-op, ``(0, 1)``, no retries burned;
    - cluster env present (or explicit coordinator) and the join fails →
      retry with backoff, then **raise** — never the reference's silent
      single-process degrade.
    """
    from apex_tpu.parallel import multihost

    policy = policy or RetryPolicy()
    attempts = itertools.count()  # chaos attempt index across retries

    def _join():
        chaos.maybe_fail(chaos.RENDEZVOUS, next(attempts))
        return multihost.initialize_distributed(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            strict=True,
        )

    return retry_call(
        _join,
        policy=policy,
        retry_on=(RuntimeError, chaos.InjectedFault),
        describe="distributed rendezvous",
    )
