"""Guarded optimizer step — skip-don't-poison for bad batches and blown-up
kernels.

Layers two detectors over :func:`apex_tpu.amp.scaler.amp_update`:

- **non-finite grads** — the scaler's fused ``found_inf`` flag (NaN/Inf
  anywhere in the gradient tree), exactly as plain ``amp_update``;
- **grad-norm spikes** — an EMA of the global gradient norm; a *finite*
  gradient whose norm exceeds ``spike_factor`` × EMA (after
  ``warmup_steps``) marks a poisoned batch that would pass the overflow
  check but still wreck the params.

Either detector skips the step the same way the scaler does: a
``where``-select over the param/opt-state trees, device-side and
branch-free — no host sync, no divergence between data-parallel replicas
(the flags are computed from all-reduced grads, so every replica selects
identically).  Only a true overflow feeds the loss-scale hysteresis; a
spike skip leaves the scale alone.

The guard also keeps a **consecutive-skip budget**: ``budget_exhausted``
turns True once ``max_consecutive_skips`` steps in a row were skipped,
which is the signal :func:`apex_tpu.resilience.runner.run_resilient` uses
to roll back to the last complete checkpoint instead of burning data
forever (a persistent blow-up is a bug or corrupted state, not a bad
batch).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.multi_tensor import global_norm

__all__ = [
    "GradGuard",
    "GuardState",
    "GuardVerdict",
    "guarded_amp_update",
    "guard_metrics",
]


class GuardState(NamedTuple):
    norm_ema: jax.Array  # f32: EMA of accepted global grad norms
    step: jax.Array  # i32: guarded steps seen (accepted or skipped)
    consecutive_skips: jax.Array  # i32
    total_skips: jax.Array  # i32


class GuardVerdict(NamedTuple):
    """Per-step diagnostics (device arrays; cheap to ignore)."""

    skipped: jax.Array  # f32 {0,1}: this step was dropped
    found_inf: jax.Array  # f32 {0,1}: non-finite grads
    spike: jax.Array  # bool: finite but > spike_factor x EMA
    grad_norm: jax.Array  # f32: unscaled global grad norm


class GradGuard:
    """Config + state factory for :func:`guarded_amp_update`.

    ``spike_factor`` trades false positives against containment: 10-20x is
    far outside the step-to-step variation of a healthy run but well
    inside what a corrupted batch produces.  ``warmup_steps`` suspends
    spike detection while the EMA is still learning the run's scale
    (overflow skipping is active from step 0).
    """

    def __init__(
        self,
        spike_factor: float = 20.0,
        ema_beta: float = 0.99,
        warmup_steps: int = 10,
        max_consecutive_skips: int = 10,
    ):
        if spike_factor <= 1.0:
            raise ValueError("spike_factor must be > 1")
        if not 0.0 < ema_beta < 1.0:
            raise ValueError("ema_beta must be in (0, 1)")
        self.spike_factor = spike_factor
        self.ema_beta = ema_beta
        self.warmup_steps = warmup_steps
        self.max_consecutive_skips = max_consecutive_skips

    def init(self) -> GuardState:
        return GuardState(
            norm_ema=jnp.zeros((), jnp.float32),
            step=jnp.zeros((), jnp.int32),
            consecutive_skips=jnp.zeros((), jnp.int32),
            total_skips=jnp.zeros((), jnp.int32),
        )

    def budget_exhausted(self, state: GuardState) -> jax.Array:
        """True once the consecutive-skip budget is spent (rollback cue)."""
        return state.consecutive_skips >= self.max_consecutive_skips


def guard_metrics(
    verdict: GuardVerdict, state: GuardState, guard: "GradGuard" = None
) -> dict:
    """The guard's device scalars, keyed for a
    :class:`apex_tpu.observability.MetricRegistry` (declare
    ``guard/skipped`` as a counter and the rest as gauges; feed the
    result to ``registry.update`` INSIDE the jitted step).

    Pass the :class:`GradGuard` itself to also get
    ``guard/budget_left`` — consecutive skips remaining before the
    rollback budget trips.  That is the countdown a flight-recorder
    frame needs to show HOW CLOSE to exhaustion the run was at death,
    not just that it skipped (``docs/observability.md``).
    """
    out = {
        "guard/skipped": verdict.skipped,
        "guard/found_inf": verdict.found_inf,
        "guard/spike": verdict.spike,
        "guard/grad_norm": verdict.grad_norm,
        "guard/norm_ema": state.norm_ema,
        "guard/consecutive_skips": state.consecutive_skips,
        "guard/total_skips": state.total_skips,
    }
    if guard is not None:
        out["guard/budget_left"] = jnp.maximum(
            guard.max_consecutive_skips - state.consecutive_skips, 0
        )
    return out


def guarded_amp_update(
    tx,
    scaler,
    guard: GradGuard,
    scaled_grads,
    opt_state,
    params,
    scaler_state,
    guard_state: GuardState,
) -> Tuple[Any, Any, Any, GuardState, GuardVerdict]:
    """``amp_update`` with spike detection and a consecutive-skip budget.

    Returns ``(params, opt_state, scaler_state, guard_state, verdict)``.
    On a skipped step params and opt state come back untouched (the same
    ``where``-select contract as ``amp_update``); the loss scale reacts
    only to genuine overflow, and the guard EMA only to accepted steps.
    """
    grads, found_inf = scaler.unscale(scaled_grads, scaler_state)
    norm = global_norm(grads)

    warm = guard_state.step >= guard.warmup_steps
    have_ema = guard_state.norm_ema > 0.0
    spike = (
        warm
        & have_ema
        & jnp.isfinite(norm)
        & (norm > guard.spike_factor * guard_state.norm_ema)
    )
    skip = (found_inf > 0.0) | spike
    accept = jnp.logical_not(skip)

    grads = jax.tree_util.tree_map(
        lambda g, p: g.astype(p.dtype), grads, params
    )
    updates, new_opt_state = tx.update(grads, opt_state, params)

    def sel(new, old):
        return jnp.where(skip, old, new)

    new_params = jax.tree_util.tree_map(
        lambda p, u: sel(p + u.astype(p.dtype), p), params, updates
    )
    new_opt_state = jax.tree_util.tree_map(sel, new_opt_state, opt_state)
    # Only genuine overflow feeds the scaler.  A spike skip must freeze the
    # whole scaler state — letting update() run would count the skipped step
    # as *clean* (growth_tracker += 1) and eventually grow the scale off a
    # step whose update was discarded.  spike and found_inf are mutually
    # exclusive (spike requires a finite norm), so the freeze never masks a
    # real overflow reaction.
    new_scaler_state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(spike, old, new),
        scaler.update(scaler_state, found_inf),
        scaler_state,
    )

    # EMA over accepted norms only (a skipped step must not teach the guard
    # that huge norms are normal); first accepted norm seeds it directly.
    seeded = jnp.where(have_ema, guard_state.norm_ema, norm)
    new_ema = jnp.where(
        accept,
        jnp.where(
            have_ema,
            guard.ema_beta * guard_state.norm_ema
            + (1.0 - guard.ema_beta) * norm,
            seeded,
        ),
        guard_state.norm_ema,
    )
    skip_i = skip.astype(jnp.int32)
    new_guard_state = GuardState(
        norm_ema=new_ema,
        step=guard_state.step + 1,
        consecutive_skips=jnp.where(
            skip, guard_state.consecutive_skips + 1, 0
        ),
        total_skips=guard_state.total_skips + skip_i,
    )
    verdict = GuardVerdict(
        skipped=skip.astype(jnp.float32),
        found_inf=found_inf,
        spike=spike,
        grad_norm=norm,
    )
    return new_params, new_opt_state, new_scaler_state, new_guard_state, verdict
