"""Paged single-query decode attention — public API and dispatch.

The serving counterpart of :mod:`apex_tpu.ops.attention`: one query row
per sequence against a KV history living in the block-pooled paged
cache (:mod:`apex_tpu.serve.cache`).  Two numerically-identical
implementations behind the usual :mod:`apex_tpu.ops._dispatch` policy:

- **jnp path** — gathers the live pages into a contiguous history and
  runs masked softmax attention; XLA-fused, the correctness reference,
  and what CPU serving uses by default (the gather is a device-side
  ``take``, no host transfer);
- **Pallas path** (:func:`apex_tpu.ops.pallas.decode_attention.
  paged_decode_fwd`) — reads the pages IN PLACE through
  scalar-prefetched page-table indexing: no gather materialization,
  O(live tokens) HBM traffic, with the per-layer query RoPE rotation
  and the int8-KV dequant fused into the same kernel.

Both paths share the semantics: positions ``>= lengths[b]`` are masked,
an idle slot (``lengths[b] == 0``) returns exactly zeros, and RoPE is
applied to the query INSIDE the attention op (the cached keys were
rotated at append time).  No backward: decode is inference-only, and
the op is wrapped in ``stop_gradient`` to make that explicit.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import _dispatch
from apex_tpu.ops.pallas.decode_attention import paged_decode_fwd
from apex_tpu.ops.pallas.flash_attention import MASK_VALUE
from apex_tpu.ops.rope import rotate_half

__all__ = [
    "paged_decode_attention",
    "paged_decode_attention_reference",
]


def paged_decode_attention_reference(
    q, k_pages, v_pages, page_table, lengths, *,
    scale: Optional[float] = None,
    k_scale=None, v_scale=None, rope_cos=None, rope_sin=None,
):
    """Gather-then-attend jnp composition — the correctness reference.

    Same signature and semantics as :func:`paged_decode_attention`.
    """
    b, h, d = q.shape
    page = k_pages.shape[2]
    np_ = page_table.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    if rope_cos is not None:
        cos = rope_cos.astype(jnp.float32)[:, None, :]  # (B, 1, D)
        sin = rope_sin.astype(jnp.float32)[:, None, :]
        qf = qf * cos + rotate_half(qf) * sin
    # gather: (B, NP, H, page, D) -> (B, H, NP*page, D)
    k = jnp.take(k_pages, page_table, axis=0).astype(jnp.float32)
    v = jnp.take(v_pages, page_table, axis=0).astype(jnp.float32)
    if k_scale is not None:
        k = k * jnp.take(k_scale, page_table, axis=0).astype(
            jnp.float32
        )[..., None]
        v = v * jnp.take(v_scale, page_table, axis=0).astype(
            jnp.float32
        )[..., None]
    k = jnp.moveaxis(k, 1, 2).reshape(b, h, np_ * page, d)
    v = jnp.moveaxis(v, 1, 2).reshape(b, h, np_ * page, d)
    s = jnp.einsum("bhd,bhtd->bht", qf, k) * scale
    pos = jnp.arange(np_ * page, dtype=jnp.int32)
    valid = pos[None, :] < lengths[:, None]  # (B, T)
    s = jnp.where(valid[:, None, :], s, MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bht,bhtd->bhd", p / jnp.maximum(l, 1e-30), v)
    # idle slots: softmax over an all-masked row would be a uniform
    # average of garbage pages — the contract is zeros
    o = jnp.where(lengths[:, None, None] > 0, o, 0.0)
    return o.astype(q.dtype)


def paged_decode_attention(
    q, k_pages, v_pages, page_table, lengths, *,
    scale: Optional[float] = None,
    k_scale=None, v_scale=None, rope_cos=None, rope_sin=None,
):
    """Single-query attention over the paged KV cache.

    - ``q`` (B, H, D): the current token's query rows (PRE-RoPE when
      ``rope_cos``/``rope_sin`` are given — the rotation fuses here);
    - ``k_pages``/``v_pages`` (P, H, page, D): the shared page pool
      (f32/bf16, or int8 codes with ``k_scale``/``v_scale`` (P, H,
      page) blockwise f32 scales — the ``parallel/comm.py`` codec
      layout at ``block = D``);
    - ``page_table`` (B, NP) int32; ``lengths`` (B,) int32: live KV
      positions per sequence including the current token.

    Returns (B, H, D) in ``q.dtype``.  Inference-only (no VJP;
    gradients are stopped).  Dispatch: the Pallas in-place page-walk
    kernel on TPU (or when forced), the gather-based jnp composition
    otherwise.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    args = (q, k_pages, v_pages, page_table, lengths)
    kw = dict(
        scale=scale, k_scale=k_scale, v_scale=v_scale,
        rope_cos=rope_cos, rope_sin=rope_sin,
    )
    if _dispatch.use_pallas():
        _dispatch.record_path("paged_decode_attention", "pallas")
        out = paged_decode_fwd(*args, **kw)
    else:
        _dispatch.record_path("paged_decode_attention", "jnp")
        out = paged_decode_attention_reference(*args, **kw)
    return jax.lax.stop_gradient(out)
