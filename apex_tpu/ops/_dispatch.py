"""Kernel dispatch policy: Pallas TPU kernels vs XLA-fused jnp.

The reference gates its CUDA extensions at import time (``setup.py`` build
flags + per-feature try-import probes).  Here every op has two
implementations with identical numerics:

- a **jnp path** — plain JAX the XLA compiler fuses; always available, the
  correctness reference, and what CPU tests exercise;
- a **Pallas path** — a hand-tiled TPU kernel used where fusion *structure*
  matters (row reductions, attention); selected automatically on TPU
  backends, or forced via :func:`set_use_pallas` (with ``interpret=True``
  under non-TPU backends so kernel math is testable on CPU).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_FORCE: Optional[bool] = None


def set_use_pallas(value: Optional[bool]) -> None:
    """Force (True/False) or restore auto (None) Pallas kernel selection."""
    global _FORCE
    _FORCE = value


def forced() -> Optional[bool]:
    """The current force state (None = auto) — lets ops apply shape
    heuristics only in auto mode while tests can still pin a path."""
    return _FORCE


def use_pallas() -> bool:
    if _FORCE is not None:
        return _FORCE
    if os.environ.get("APEX_TPU_DISABLE_PALLAS", "").lower() in ("1", "true", "yes"):
        return False
    return jax.default_backend() == "tpu"


def pallas_interpret() -> bool:
    """Interpret mode: needed whenever the backend is not a real TPU."""
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# Trace-time path triage (ADVICE r4: the pallas and jnp paths draw
# DIFFERENT dropout streams by documented contract, so when a shape or
# backend change silently flips the dispatch, reproducibility debugging
# needs to see which path a call actually took).
# --------------------------------------------------------------------------

_PATH_LOG: dict = {}


def record_path(op: str, path: str) -> None:
    """Record which implementation ``op`` selected ("pallas" | "jnp").

    Called by the dispatching ops at TRACE time — a cached jit execution
    does not re-trace and therefore does not re-record; the log answers
    "which path did the most recent trace of this op take", which is the
    question cross-backend reproducibility triage asks."""
    _PATH_LOG[op] = path


def last_paths() -> dict:
    """op name -> "pallas" | "jnp" for every op traced since import (or
    the last :func:`clear_paths`)."""
    return dict(_PATH_LOG)


def clear_paths() -> None:
    _PATH_LOG.clear()
