"""Fused softmax-cross-entropy with label smoothing.

Capability parity with ``apex/contrib/xentropy/softmax_xentropy.py`` ::
``SoftmaxCrossEntropyLoss`` backed by
``apex/contrib/csrc/xentropy/xentropy_kernel.cu``.

The CUDA kernel's win was computing loss and the softmax residual in one pass
(saving a logits-sized roundtrip) and fusing label smoothing.  The TPU
version keeps the same *interface* semantics via ``custom_vjp``: the forward
saves only ``(logsumexp, labels)`` — O(N) extra memory instead of an (N, V)
softmax — and the backward rebuilds ``softmax - target`` in one fused XLA
cluster.

Semantics (matching the reference):
- ``smoothing=0``: standard CE, loss_i = logsumexp_i - logit_i[label_i].
- ``smoothing=s``: target distribution puts ``1-s`` on the label and
  ``s/V`` on every class; loss_i = logsumexp_i - (1-s)*logit[label]
  - (s/V)*sum(logits).
- ``half_to_float``: compute/return the loss in f32 even for bf16/f16 logits
  (always true here — loss is f32; the *gradient* is cast back to the logits
  dtype).
- ``ignore_idx``: rows whose label equals it contribute zero loss and grad.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy_loss", "SoftmaxCrossEntropyLoss"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent_vjp(logits, labels, smoothing, ignore_idx):
    loss, _ = _xent_fwd(logits, labels, smoothing, ignore_idx)
    return loss


def softmax_cross_entropy_loss(logits, labels, smoothing=0.0, ignore_idx=-100):
    """Per-example smoothed CE loss; logits (N, V), labels (N,) int."""
    from apex_tpu.amp.lists import amp_cast

    return _xent_vjp(amp_cast("xentropy", logits), labels, smoothing, ignore_idx)


def _parts(logits, labels, smoothing):
    # f32 logsumexp by design (the reference kernel accumulates in
    # f32); named scope = policy-exempt for analysis' promotion lint
    with jax.named_scope("xent_f32_lse"):
        lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1, keepdims=True)
    lse = jnp.squeeze(m, -1) + jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1))
    n = logits.shape[0]
    label_logit = lf[jnp.arange(n), jnp.clip(labels, 0, logits.shape[1] - 1)]
    if smoothing > 0.0:
        v = logits.shape[1]
        mean_logit = jnp.mean(lf, axis=-1)
        nll = lse - (1.0 - smoothing) * label_logit - smoothing * mean_logit
    else:
        nll = lse - label_logit
    return nll, lse


def _xent_fwd(logits, labels, smoothing, ignore_idx):
    nll, lse = _parts(logits, labels, smoothing)
    valid = labels != ignore_idx
    loss = jnp.where(valid, nll, 0.0)
    return loss, (logits, labels, lse, valid)


def _xent_bwd(smoothing, ignore_idx, res, g):
    logits, labels, lse, valid = res
    with jax.named_scope("xent_f32_lse"):
        lf = logits.astype(jnp.float32)
    n, v = logits.shape
    softmax = jnp.exp(lf - lse[:, None])
    one_hot = jax.nn.one_hot(labels, v, dtype=jnp.float32)
    if smoothing > 0.0:
        target = (1.0 - smoothing) * one_hot + smoothing / v
    else:
        target = one_hot
    dlogits = (softmax - target) * g[:, None]
    dlogits = jnp.where(valid[:, None], dlogits, 0.0)
    return dlogits.astype(logits.dtype), None


_xent_vjp.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Drop-in shaped like the reference's module (static, stateless)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0, half_to_float=False):
        del half_to_float  # loss is always f32 (see module doc)
        return softmax_cross_entropy_loss(
            logits, labels, smoothing, ignore_idx=padding_idx
        )
