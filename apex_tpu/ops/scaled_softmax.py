"""Scaled (masked) softmax family — attention softmax fused ops.

Capability parity with the reference's megatron softmax kernels
(``csrc/megatron/scaled_masked_softmax*``,
``scaled_upper_triang_masked_softmax*``, ``generic_scaled_masked_softmax``)
and their Python wrapper ``apex/transformer/functional/fused_softmax.py`` ::
``ScaledSoftmax``, ``ScaledMaskedSoftmax``, ``ScaledUpperTriangMaskedSoftmax``,
``GenericScaledMaskedSoftmax``.

On TPU the scale→mask→softmax→(softmax-grad) chains are single XLA fusions —
there is no HBM roundtrip to eliminate, which was the CUDA kernels' entire
reason to exist.  Each op therefore ships as a ``custom_vjp`` jnp composition
(one fused HLO cluster, verified by the fusion test) whose backward matches
the reference kernel's: ``dx = scale * y * (g - sum(g*y, -1))``.  The
full fused-attention path (where fusion structure *does* matter on TPU) is
the Pallas flash attention in :mod:`apex_tpu.ops.flash_attention`.

Masking semantics follow the reference: ``mask`` is boolean with **True =
masked out**; masked positions receive ``-10000.0`` *after* scaling, and the
causal variant applies an upper-triangular mask over the last two dims.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = [
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
]

_MASK_FILL = -10000.0


def _amp(name, x):
    from apex_tpu.amp.lists import amp_cast

    return amp_cast(name, x)


def _softmax_fwd(x):
    # f32 exp/sum by design (reference kernel parity); the named scope
    # marks the widening policy-exempt for analysis' promotion lint
    with jax.named_scope("softmax_f32_stats"):
        xf = x.astype(jnp.float32)
        m = jnp.max(xf, axis=-1, keepdims=True)
        e = jnp.exp(xf - m)
        y = e / jnp.sum(e, axis=-1, keepdims=True)
    return y


def _softmax_bwd(y, g, scale):
    with jax.named_scope("softmax_f32_stats"):
        gf = g.astype(jnp.float32)
        yf = y.astype(jnp.float32)
        dx = yf * (gf - jnp.sum(gf * yf, axis=-1, keepdims=True))
    return (dx * scale).astype(g.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scaled_softmax_vjp(x, scale):
    return _softmax_fwd(x * scale).astype(x.dtype)


def scaled_softmax(x, scale):
    """softmax(x * scale) — ≙ ScaledSoftmax (scaled_softmax_cuda::fwd)."""
    return _scaled_softmax_vjp(_amp("scaled_softmax", x), scale)


def _ss_fwd(x, scale):
    y = _softmax_fwd(x * scale)
    return y.astype(x.dtype), y.astype(x.dtype)


def _ss_bwd(scale, y, g):
    return (_softmax_bwd(y, g, scale),)


_scaled_softmax_vjp.defvjp(_ss_fwd, _ss_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _scaled_masked_softmax_vjp(x, mask, scale):
    y, _ = _sms_fwd(x, mask, scale)
    return y


def scaled_masked_softmax(x, mask, scale):
    """softmax(mask_fill(x*scale)) over 4D (b, np, sq, sk).

    ≙ ScaledMaskedSoftmax (scaled_masked_softmax_cuda::fwd); ``mask`` is
    broadcastable boolean (b, 1, sq, sk), True = masked.
    """
    return _scaled_masked_softmax_vjp(
        _amp("scaled_masked_softmax", x), mask, scale
    )


def _sms_fwd(x, mask, scale):
    with jax.named_scope("softmax_f32_stats"):
        xs = x.astype(jnp.float32) * scale
    if mask is not None:
        xs = jnp.where(mask, _MASK_FILL, xs)
    y = _softmax_fwd(xs)
    if mask is not None:
        # Fully-masked rows produce exact zeros (≙ the reference kernel,
        # which special-cases all-masked rows) rather than a uniform
        # distribution over garbage.
        all_masked = jnp.all(mask, axis=-1, keepdims=True)
        y = jnp.where(all_masked, 0.0, y)
    return y.astype(x.dtype), y.astype(x.dtype)


def _sms_bwd(scale, y, g):
    # Masked lanes have y == 0 ⇒ dx == 0 there automatically (reference
    # backward likewise needs no mask input).
    return _softmax_bwd(y, g, scale), None


_scaled_masked_softmax_vjp.defvjp(_sms_fwd, _sms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _sutms_vjp(x, scale):
    y, _ = _sutms_fwd(x, scale)
    return y


def scaled_upper_triang_masked_softmax(x, scale):
    """Causal softmax over (b, sq, sk) — ≙ ScaledUpperTriangMaskedSoftmax."""
    return _sutms_vjp(_amp("scaled_softmax", x), scale)


def _causal_mask(sq, sk):
    r = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return c > r  # True = masked (strictly upper triangular)


def _sutms_fwd(x, scale):
    sq, sk = x.shape[-2], x.shape[-1]
    if sq != sk:
        # ≙ the reference wrapper's assertion; a top-left triangle over a
        # rectangular score matrix is silently-wrong causal masking.
        raise ValueError(
            f"scaled_upper_triang_masked_softmax requires square scores, got "
            f"sq={sq}, sk={sk}; use scaled_masked_softmax with an explicit "
            "mask for KV-cache decode shapes"
        )
    with jax.named_scope("softmax_f32_stats"):
        xs = x.astype(jnp.float32) * scale
    xs = jnp.where(_causal_mask(sq, sk), _MASK_FILL, xs)
    y = _softmax_fwd(xs)
    # Match the reference kernel: fully-masked rows yield exact zeros is NOT
    # the semantic here — -10000 fill keeps a proper distribution over the
    # allowed prefix; row 0 attends only to col 0.
    return y.astype(x.dtype), y.astype(x.dtype)


def _sutms_bwd(scale, y, g):
    return (_softmax_bwd(y, g, scale),)


_sutms_vjp.defvjp(_sutms_fwd, _sutms_bwd)


def generic_scaled_masked_softmax(x, mask, scale):
    """Arbitrary-shape masked softmax — ≙ generic_scaled_masked_softmax_cuda.

    Same math as :func:`scaled_masked_softmax` without the 4D/seq-length
    restrictions the CUDA kernel had (TPU path never had them).
    """
    return scaled_masked_softmax(x, mask, scale)
