"""Fused rotary position embedding (RoPE) forward/backward.

Capability parity with
``apex/transformer/functional/fused_rope.py`` ::
``fused_apply_rotary_pos_emb`` / ``fused_apply_rotary_pos_emb_cached``,
backed by ``csrc/megatron/fused_rotary_positional_embedding_cuda.cu``.

Layout follows the reference (Megatron ``sbhd``): ``t`` is
``(seq, batch, heads, head_dim)`` and ``freqs`` is ``(seq, 1, 1, rot_dim)``
with ``rot_dim <= head_dim``; only the first ``rot_dim`` channels rotate,
the tail passes through.  The rotation uses the "rotate_half" convention:

    y = t * cos(freqs) + rotate_half(t) * sin(freqs)

The backward is the exact transpose of the (linear-in-t) rotation:
``dt = g * cos + rotate_half^T(sin * g)`` with
``rotate_half^T(x) = (x2, -x1)`` — expressed via ``custom_vjp`` so autograd
never differentiates through cos/sin.  All math is fused by XLA into a
single elementwise cluster; there is no HBM-roundtrip win for a Pallas
kernel here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "rotate_half",
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
]


def rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate((-x2, x1), axis=-1)


def _apply(t, cos_, sin_):
    rot_dim = cos_.shape[-1]
    if rot_dim > t.shape[-1]:
        raise ValueError(
            f"rotary dim {rot_dim} exceeds head dim {t.shape[-1]}"
        )
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    # f32 rotation by design (reference kernel parity); named scope =
    # policy-exempt for analysis' promotion lint
    with jax.named_scope("rope_f32"):
        tf = t_rot.astype(jnp.float32)
    out = tf * cos_ + rotate_half(tf) * sin_
    out = out.astype(t.dtype)
    if t_pass.shape[-1] == 0:
        return out
    return jnp.concatenate((out, t_pass), axis=-1)


def _transpose_apply(g, cos_, sin_):
    """dt = cos ⊙ g + rotate_half^T(sin ⊙ g);  rotate_half^T(x) = (x2, -x1).

    The forward output (and hence the cotangent ``g``) carries ``t.dtype``,
    so the input grad is cast to ``g.dtype``.
    """
    tdtype = g.dtype
    rot_dim = cos_.shape[-1]
    g_rot, g_pass = g[..., :rot_dim], g[..., rot_dim:]
    with jax.named_scope("rope_f32"):
        gf = g_rot.astype(jnp.float32)
    sg = sin_ * gf
    sg1, sg2 = jnp.split(sg, 2, axis=-1)
    dt = gf * cos_ + jnp.concatenate((sg2, -sg1), axis=-1)
    dt = dt.astype(tdtype)
    if g_pass.shape[-1] != 0:
        dt = jnp.concatenate((dt, g_pass.astype(tdtype)), axis=-1)
    return dt


@jax.custom_vjp
def fused_apply_rotary_pos_emb(t, freqs):
    """≙ fused_apply_rotary_pos_emb (non-cached: freqs in radians)."""
    cos_ = jnp.cos(freqs).astype(jnp.float32)
    sin_ = jnp.sin(freqs).astype(jnp.float32)
    return _apply(t, cos_, sin_)


def _rope_fwd(t, freqs):
    cos_ = jnp.cos(freqs).astype(jnp.float32)
    sin_ = jnp.sin(freqs).astype(jnp.float32)
    return _apply(t, cos_, sin_), (cos_, sin_)


def _rope_bwd(res, g):
    cos_, sin_ = res
    return _transpose_apply(g, cos_, sin_), None


fused_apply_rotary_pos_emb.defvjp(_rope_fwd, _rope_bwd)


@jax.custom_vjp
def fused_apply_rotary_pos_emb_cached(t, cos_, sin_):
    """≙ fused_apply_rotary_pos_emb_cached (precomputed cos/sin tables).

    Gradients flow to ``t`` only; the tables are treated as constants (their
    cotangents are None), matching the reference kernel.
    """
    with jax.named_scope("rope_f32"):
        return _apply(
            t, cos_.astype(jnp.float32), sin_.astype(jnp.float32)
        )


def _rope_cached_fwd(t, cos_, sin_):
    with jax.named_scope("rope_f32"):
        cos_f = cos_.astype(jnp.float32)
        sin_f = sin_.astype(jnp.float32)
    return _apply(t, cos_f, sin_f), (cos_f, sin_f)


def _rope_cached_bwd(res, g):
    cos_f, sin_f = res
    return _transpose_apply(g, cos_f, sin_f), None, None


fused_apply_rotary_pos_emb_cached.defvjp(_rope_cached_fwd, _rope_cached_bwd)
