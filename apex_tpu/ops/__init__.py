"""Fused ops — the ``csrc/`` + wrapper layer of the framework.

Every op computes statistics in f32, preserves I/O dtype, and ships a
``custom_vjp`` backward matching the reference CUDA kernel's math.
"""

from apex_tpu.ops._dispatch import set_use_pallas, use_pallas  # noqa: F401
from apex_tpu.ops.attention import (  # noqa: F401
    flash_attention,
    fmha_qkvpacked,
    mha_reference,
)
from apex_tpu.ops.paged_attention import (  # noqa: F401
    paged_decode_attention,
    paged_decode_attention_reference,
)
from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)
from apex_tpu.ops.rope import (  # noqa: F401
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
    rotate_half,
)
from apex_tpu.ops.scaled_softmax import (  # noqa: F401
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
