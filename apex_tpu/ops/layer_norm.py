"""Fused LayerNorm / RMSNorm — functional API.

Capability parity with ``apex/normalization/fused_layer_norm.py`` ::
``fused_layer_norm_affine``, ``fused_layer_norm``, ``fused_rms_norm_affine``,
``fused_rms_norm`` and their autograd functions
(``FusedLayerNormAffineFunction`` etc., incl. the ``memory_efficient`` flag),
backed by ``csrc/layer_norm_cuda_kernel.cu`` in the reference.

Semantics (all paths):
- statistics and normalization computed in **f32** regardless of input dtype
  (the reference's "Mixed" = fp32-params/fp16-IO classes fall out of this:
  pass bf16/f16 ``x`` with f32 ``weight``);
- output dtype == input dtype; weight/bias grads in the weight's dtype;
- ``memory_efficient=True`` saves the forward *output* + rstd instead of the
  input + mean, recovering ``xhat`` in the backward (trades one divide for
  an activation buffer, exactly the reference's flag).

Dispatch: Pallas TPU kernels (:mod:`apex_tpu.ops.pallas.layer_norm`) when on
TPU and the normalized size is lane-aligned; XLA-fused jnp otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops import _dispatch

__all__ = [
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "fused_rms_norm",
    "fused_rms_norm_affine",
]

Shape = Union[int, Sequence[int]]


def _normalized_size(normalized_shape: Shape) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    return int(np.prod(tuple(normalized_shape)))


def _pallas_eligible(hidden: int) -> bool:
    return _dispatch.use_pallas() and hidden % 128 == 0 and hidden <= 65536


# ---------------------------------------------------------------------------
# jnp reference path (identical math to the Pallas kernels)
# ---------------------------------------------------------------------------


def _jnp_fwd(x2d, w, b, eps, rms):
    # f32 statistics by design (keep_batchnorm_fp32 analog); the named
    # scope marks the widening policy-exempt for analysis' promotion lint
    with jax.named_scope("ln_f32_stats"):
        xf = x2d.astype(jnp.float32)
        wf = w.astype(jnp.float32)
        bf = b.astype(jnp.float32)
    if rms:
        mu = jnp.zeros((xf.shape[0], 1), jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    else:
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        xc = xf - mu
        var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (xf - mu) * rstd
    y = xhat * wf + bf
    return y.astype(x2d.dtype), mu, rstd


def _jnp_bwd(x2d, w, b, mu, rstd, g, rms, x_is_output):
    with jax.named_scope("ln_f32_stats"):
        xf = x2d.astype(jnp.float32)
        wf = w.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        bf = b.astype(jnp.float32)
    if x_is_output:
        wsafe = jnp.where(wf == 0.0, 1.0, wf)
        xhat = jnp.where(wf == 0.0, 0.0, (xf - bf) / wsafe)
    else:
        xhat = (xf - mu) * rstd
    dyw = gf * wf
    c2 = jnp.mean(dyw * xhat, axis=-1, keepdims=True)
    if rms:
        dx = rstd * (dyw - xhat * c2)
    else:
        c1 = jnp.mean(dyw, axis=-1, keepdims=True)
        dx = rstd * (dyw - c1 - xhat * c2)
    dw = jnp.sum(gf * xhat, axis=0)
    db = jnp.sum(gf, axis=0)
    return dx.astype(x2d.dtype), dw, db


# ---------------------------------------------------------------------------
# custom_vjp core over flattened (rows, hidden)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _norm2d(x2d, w, b, eps, rms, memory_efficient):
    y, _, _ = _norm2d_fwd_impl(x2d, w, b, eps, rms)
    return y


def _norm2d_fwd_impl(x2d, w, b, eps, rms):
    hidden = x2d.shape[-1]
    op = "rms_norm" if rms else "layer_norm"
    if _pallas_eligible(hidden):
        from apex_tpu.ops.pallas import layer_norm as _k

        _dispatch.record_path(op, "pallas")
        return _k.layer_norm_fwd(x2d, w, b, eps=eps, rms=rms)
    _dispatch.record_path(op, "jnp")
    return _jnp_fwd(x2d, w, b, eps, rms)


def _norm2d_fwd(x2d, w, b, eps, rms, memory_efficient):
    y, mu, rstd = _norm2d_fwd_impl(x2d, w, b, eps, rms)
    if memory_efficient:
        res = (y, w, b, None, rstd)
    else:
        res = (x2d, w, b, mu, rstd)
    return y, res


def _norm2d_bwd(eps, rms, memory_efficient, res, g):
    x_or_y, w, b, mu, rstd = res
    hidden = x_or_y.shape[-1]
    if _pallas_eligible(hidden):
        from apex_tpu.ops.pallas import layer_norm as _k

        mu_in = mu if mu is not None else jnp.zeros_like(rstd)
        dx, dw, db = _k.layer_norm_bwd(
            x_or_y, w, b, mu_in, rstd, g, rms=rms, x_is_output=memory_efficient
        )
    else:
        dx, dw, db = _jnp_bwd(
            x_or_y, w, b, mu, rstd, g, rms, x_is_output=memory_efficient
        )
    return dx, dw.astype(w.dtype), db.astype(b.dtype)


_norm2d.defvjp(_norm2d_fwd, _norm2d_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _run(x, normalized_shape, w, b, eps, rms, memory_efficient):
    from apex_tpu.amp.lists import amp_cast

    x, w, b = amp_cast("rms_norm" if rms else "layer_norm", x, w, b)
    shape_t = (
        (normalized_shape,)
        if isinstance(normalized_shape, int)
        else tuple(normalized_shape)
    )
    hidden = _normalized_size(normalized_shape)
    if tuple(x.shape[-len(shape_t):]) != shape_t:
        raise ValueError(
            f"normalized_shape {normalized_shape} does not match the trailing "
            f"dimensions of input shape {x.shape}"
        )
    orig_shape = x.shape
    x2d = x.reshape(-1, hidden)
    if w is None:
        w = jnp.ones((hidden,), jnp.float32)
    else:
        w = w.reshape(hidden)
    if b is None:
        b = jnp.zeros((hidden,), jnp.float32)
    else:
        b = b.reshape(hidden)
    y = _norm2d(x2d, w, b, float(eps), bool(rms), bool(memory_efficient))
    return y.reshape(orig_shape)


def fused_layer_norm_affine(
    x,
    weight,
    bias,
    normalized_shape: Shape,
    eps: float = 1e-6,
    memory_efficient: bool = False,
):
    """≙ apex/normalization/fused_layer_norm.py :: fused_layer_norm_affine."""
    return _run(x, normalized_shape, weight, bias, eps, False, memory_efficient)


def fused_layer_norm(
    x,
    normalized_shape: Shape,
    eps: float = 1e-6,
    memory_efficient: bool = False,
):
    """Non-affine LayerNorm (≙ fused_layer_norm)."""
    return _run(x, normalized_shape, None, None, eps, False, memory_efficient)


def fused_rms_norm_affine(
    x,
    weight,
    normalized_shape: Shape,
    eps: float = 1e-6,
    memory_efficient: bool = False,
):
    """≙ apex/normalization/fused_layer_norm.py :: fused_rms_norm_affine."""
    return _run(x, normalized_shape, weight, None, eps, True, memory_efficient)


def fused_rms_norm(
    x,
    normalized_shape: Shape,
    eps: float = 1e-6,
    memory_efficient: bool = False,
):
    """Non-affine RMSNorm (≙ fused_rms_norm)."""
    return _run(x, normalized_shape, None, None, eps, True, memory_efficient)
