"""Flash attention — public API and dispatch.

Capability parity with the reference's two fused-attention families:

- ``apex/contrib/multihead_attn`` (``SelfMultiheadAttn``/``EncdecMultiheadAttn``
  autograd functions: QKV GEMM + scaled [masked] softmax + dropout + PV GEMM),
- ``apex/contrib/fmha`` (``fmha.py :: FMHAFun``, flash kernels for seq ≤ 512).

Two numerically-identical implementations (see apex_tpu.ops._dispatch):

- **jnp path** — plain composition XLA fuses; supports every feature; the
  correctness reference.  Its dropout draws from ``jax.random`` given
  ``dropout_rng``.
- **Pallas path** — online-softmax flash kernel
  (apex_tpu.ops.pallas.flash_attention), O(S) memory.  Supports additive
  bias (trainable via a dedicated dbias kernel), arbitrary seq lengths
  (padding + key masking), and fused attention dropout (counter-based
  in-kernel PRNG ≙ the reference's philox dropout; the mask stream
  differs from the jnp path's ``jax.random`` — both are valid dropout,
  deterministic given their seeds).

Interface dtype rules mirror the reference: compute in f32 inside the
kernel, outputs in the input dtype, logsumexp saved in f32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import _dispatch
from apex_tpu.ops.pallas import flash_attention as _pallas

__all__ = [
    "flash_attention",
    "flash_attention_with_lse",
    "mha_reference",
    "mha_reference_with_lse",
    "fmha_qkvpacked",
]

_LANES = 128


def _seq_pad(s: int) -> int:
    """Rows of padding that make ``s`` kernel-tileable: below a full lane
    block, to the f32 sublane quantum; above, to a 128 multiple so
    ``_auto_block`` finds a dividing power-of-two tile."""
    return (-s) % 8 if s < _LANES else (-s) % _LANES


def _format_bias(bias, b, h, sk, pad_q, pad_k, bias_grad):
    """(B?, H?, Sq?, Sk) bias -> the kernel's (G, RS, Sk) layout.

    A head-independent bias keeps G = bb (∈ {1, B}) and a
    query-independent (key-padding) bias keeps RS = 1, so the common
    (B, 1, 1, Sk) padding mask never materializes a (Sq, Sk) matrix —
    the kernel's index map folds b//(BH/G) and broadcasts the row.

    Padded keys are masked at PAD_VALUE — strictly below the user bias's
    MASK_VALUE clamp, so a row whose real keys are ALL masked still
    averages V over the real keys only (padded keys underflow out of its
    softmax).  Padded q rows (sliced off by callers) get zero bias rows.
    Both pads sit OUTSIDE the custom VJP, so autodiff slices the dbias
    back to the user's shape."""
    bb, bh_, bsq, bsk = bias.shape
    if bsk != sk:
        bias = jnp.broadcast_to(bias, (bb, bh_, bsq, sk))
    if bh_ == 1:
        bias_f = bias.reshape(bb, bsq, sk)
    else:
        bias_f = jnp.broadcast_to(bias, (b, h, bsq, sk)).reshape(
            b * h, bsq, sk
        )
    if not bias_grad:
        # Zero cotangent on this path; stop_gradient makes that explicit
        # so an unintended trainable bias fails loudly in tests (zero
        # grad) rather than appearing shape-dependent.
        bias_f = jax.lax.stop_gradient(bias_f)
    if pad_k:
        bias_f = jnp.pad(
            bias_f, ((0, 0), (0, 0), (0, pad_k)),
            constant_values=_pallas.PAD_VALUE,
        )
    if bsq != 1 and pad_q:
        bias_f = jnp.pad(bias_f, ((0, 0), (0, pad_q), (0, 0)))
    return bias_f


def _derive_dropout_seed(dropout_rng, dropout_p):
    """The ONE seed derivation for every fused-dropout kernel entry point
    (flash_attention and flash_attention_with_lse must stay in lockstep —
    tests/test_attention_fuzz.py pins this contract externally to
    regenerate the kernel keep mask)."""
    if dropout_p > 0.0:
        return jax.random.randint(
            dropout_rng, (1,), jnp.iinfo(jnp.int32).min,
            jnp.iinfo(jnp.int32).max, dtype=jnp.int32,
        )
    return jnp.zeros((1,), jnp.int32)


def _pallas_eligible(q, k, v, dropout_p, causal=False):
    sq, sk = q.shape[-2], k.shape[-2]
    # Arbitrary S is handled by padding to the next tileable size with the
    # padded keys masked at MASK_VALUE (≙ the reference's shape-general
    # softmax kernels, SURVEY §2.4 generic_scaled_masked_softmax).  One
    # corner stays on the jnp path: bottom-right causal with Sq > Sk AND a
    # padded Sk — fully-masked rows there average V over the real Sk, which
    # key-padding cannot express.
    if causal and sk < sq and _seq_pad(sk):
        return False
    if _dispatch.forced() is None and max(sq, sk) < 1024:
        # Auto mode: when BOTH sequence dims are short the (Sq, Sk) score
        # matrix is small and XLA's unfused composition wins — per-tile
        # grid overhead dominates the flash kernel when each (B, H) slice
        # is only a tile or two.  Measured on v5e BERT-Large (S=128,
        # D=64): XLA 0.53 MFU vs kernel 0.39; at S=2048 the kernel is
        # 1.7x FASTER (bench.py --config mha).  Either dim being long
        # routes to the kernel: its O(S) memory (no materialized score
        # matrix) is what keeps long-Sq/short-Sk cross-attention from
        # OOMing regardless of which side is long.
        return False
    return _dispatch.use_pallas()


def _flatten_bh(x):
    b, h, s, d = x.shape
    return x.reshape(b * h, s, d)


def _pad_head_dim(x):
    """Pad D only as far as Mosaic needs, not to a full 128-lane multiple.

    The kernels block over the whole head dim (no D grid), and Mosaic
    lowers an untiled trailing dim of any sublane-aligned size — so D = 64
    stays 64 (half the QK/PV FLOPs and HBM traffic of padding to 128;
    measured 2x end-to-end on the S=2048 MHA bench).  Only off-grid sizes
    pad: to 8 below 128, to a lane multiple above.  The arithmetic lives
    in ``pallas.flash_attention.padded_head_dim`` — the pure-int form
    the kernel analyzer and tuner share, so analysis can never assume a
    different padding than dispatch applies.
    """
    d = x.shape[-1]
    pad = _pallas.padded_head_dim(d) - d
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, bias, seed, scale, causal, causal_offset, bias_grad,
           dropout_p):
    o, _ = _flash_fwd(
        q, k, v, bias, seed, scale, causal, causal_offset, bias_grad,
        dropout_p,
    )
    return o


def _flash_fwd(q, k, v, bias, seed, scale, causal, causal_offset, bias_grad,
               dropout_p):
    o, lse = _pallas.flash_fwd(
        q, k, v, bias, scale=scale, causal=causal,
        causal_offset=causal_offset, dropout_p=dropout_p, dropout_seed=seed,
    )
    return o, (q, k, v, bias, seed, o, lse)


def _flash_bwd(scale, causal, causal_offset, bias_grad, dropout_p, res, g):
    import numpy as np

    q, k, v, bias, seed, o, lse = res
    dq, dk, dv = _pallas.flash_bwd(
        q, k, v, o, lse, g, bias, scale=scale, causal=causal,
        causal_offset=causal_offset, dropout_p=dropout_p, dropout_seed=seed,
    )
    if bias is None:
        dbias = None
    elif bias_grad:
        # Trainable bias (≙ reference self_attn_bias backward): a third
        # recompute pass reduces ds over the bias's broadcast group —
        # see pallas.flash_attention.flash_dbias.
        dbias = _pallas.flash_dbias(
            q, k, v, o, lse, g, bias, scale=scale, causal=causal,
            causal_offset=causal_offset, dropout_p=dropout_p,
            dropout_seed=seed,
        )
    else:
        # Bias as the reference's *additive mask* — non-trainable there;
        # zero cotangent.
        dbias = jnp.zeros_like(bias)
    # int32 seed: the cotangent for an integer primal is float0
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)


def _scores(q, k, bias, causal, scale):
    """Scaled (+bias, causal-masked) f32 score matrix — the shared head of
    both unfused reference compositions."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        # scores are f32 by design; scope = promotion-lint exempt
        with jax.named_scope("attn_f32_scores"):
            s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, _pallas.MASK_VALUE)
    return s


def mha_reference(
    q,
    k,
    v,
    bias=None,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_p: float = 0.0,
    dropout_rng=None,
):
    """Unfused attention in f32 — the golden composition the reference tests
    fuse against (≙ the torch compositions in apex/contrib/test/fmha etc.).

    Shapes: q (B,H,Sq,D), k/v (B,H,Sk,D), bias broadcastable to (B,H,Sq,Sk).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = _scores(q, k, bias, causal, scale)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_p > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def flash_attention(
    q,
    k,
    v,
    bias=None,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_p: float = 0.0,
    dropout_rng=None,
    bias_grad: bool = False,
):
    """Fused scaled-dot-product attention.

    q (B,H,Sq,D); k,v (B,H,Sk,D); optional additive ``bias`` of rank ≤ 4
    broadcastable to (B,H,Sq,Sk) (the reference's key-padding / additive
    attention mask — non-trainable by default, zero cotangent on the flash
    path).  For a *trainable* bias (e.g. relative position biases) pass
    ``bias_grad=True``: the flash path then runs a dedicated dbias kernel
    (≙ the reference's self_attn_bias fused backward); the jnp fallback
    differentiates naturally.  Arbitrary Sq/Sk are supported on the flash
    path by padding to the next tileable size with padded keys masked out
    (one corner excepted — see ``_pallas_eligible``).  ``dropout_p`` > 0
    with ``dropout_rng`` fuses probability dropout into the kernels
    (counter-based PRNG, deterministic in the rng; the jnp fallback's
    mask stream differs — both are valid dropout).  Returns (B,H,Sq,D)
    in the input dtype.
    """
    from apex_tpu.amp.lists import amp_cast

    q, k, v = amp_cast("attention", q, k, v)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if bias is not None:
        if bias.ndim < 4:
            bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
        # Clamp the torch-convention -inf additive mask to the finite
        # MASK_VALUE *before* dispatch, so the Pallas kernel (whose online
        # softmax would NaN on a fully--inf block) and the jnp fallback
        # (whose softmax would NaN on a fully--inf row) share semantics:
        # a fully-masked row yields a uniform average of V on both paths.
        bias = jnp.maximum(bias, _pallas.MASK_VALUE)
    if not _pallas_eligible(q, k, v, dropout_p, causal):
        _dispatch.record_path("flash_attention", "jnp")
        return mha_reference(
            q, k, v, bias, causal=causal, scale=scale,
            dropout_p=dropout_p, dropout_rng=dropout_rng,
        )
    if dropout_p > 0.0 and dropout_rng is None:
        raise ValueError("dropout_p > 0 requires dropout_rng")
    _dispatch.record_path("flash_attention", "pallas")
    seed = _derive_dropout_seed(dropout_rng, dropout_p)

    b, h, sq, d = q.shape
    sk = k.shape[-2]
    pad_q, pad_k = _seq_pad(sq), _seq_pad(sk)
    qf, kf, vf = (_pad_head_dim(_flatten_bh(x)) for x in (q, k, v))
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))
    bias_f = None
    if bias is not None:
        bias_f = _format_bias(bias, b, h, sk, pad_q, pad_k, bias_grad)
    elif pad_k:
        # No user bias but padded keys: mask them via the cheap RS=1, G=1
        # key-padding row (never materializes an (Sq, Sk) matrix).
        bias_f = jnp.concatenate(
            [
                jnp.zeros((sk,), jnp.float32),
                jnp.full((pad_k,), _pallas.PAD_VALUE, jnp.float32),
            ]
        ).reshape(1, 1, sk + pad_k)
    o = _flash(
        qf, kf, vf, bias_f, seed, scale, causal, sk - sq, bias_grad,
        dropout_p,
    )
    return o[:, :sq, :d].reshape(b, h, sq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_lse(q, k, v, bias, seed, scale, causal, dropout_p):
    return _flash_lse_fwd(q, k, v, bias, seed, scale, causal, dropout_p)[0]


def _flash_lse_fwd(q, k, v, bias, seed, scale, causal, dropout_p):
    o, lse = _pallas.flash_fwd(
        q, k, v, bias, scale=scale, causal=causal, dropout_p=dropout_p,
        dropout_seed=seed,
    )
    return (o, lse[..., 0]), (q, k, v, bias, seed, o, lse)


def _flash_lse_bwd(scale, causal, dropout_p, res, cts):
    import numpy as np

    q, k, v, bias, seed, o, lse = res
    do, dlse = cts
    # dlse folds as ds = p·(dp − (delta − dlse)): the dlse term enters
    # delta BEFORE the keep-mask multiplies dp, so it correctly bypasses
    # dropout (lse accumulates the full, undropped row sum).
    dq, dk, dv = _pallas.flash_bwd(
        q, k, v, o, lse, do, bias, scale=scale, causal=causal, dlse=dlse,
        dropout_p=dropout_p, dropout_seed=seed,
    )
    # the with-lse bias is the ADDITIVE-MASK form (≙ flash_attention's
    # bias_grad=False): zero cotangent
    dbias = None if bias is None else jnp.zeros_like(bias)
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(q, k, v, bias=None, *, causal=False,
                             scale=None, dropout_p: float = 0.0,
                             dropout_rng=None):
    """Fused attention returning ``(o, lse)`` — both differentiable.

    The building block for composed softmax schemes that need the row
    logsumexp downstream: ring attention merges per-hop ``(o, lse)`` pairs
    with the online-softmax rule and differentiates through the merge, so
    the backward here consumes BOTH cotangents (see
    ``pallas.flash_attention.flash_bwd``'s ``dlse`` folding).  No analog
    in the reference — its fused MHA never exposes the softmax statistics.

    q (B,H,Sq,D); k, v (B,H,Sk,D).  Returns o (B,H,Sq,D) in the input
    dtype and lse f32 (B,H,Sq).  Uses the Pallas kernels whenever the
    shape is eligible (interpret-mode off TPU), else a jnp composition
    with identical semantics.

    ``bias`` (broadcastable to (B, H, Sq, Sk), e.g. a (B, 1, 1, Sk)
    key-padding mask) is the ADDITIVE-MASK form — non-trainable, zero
    cotangent, clamped at MASK_VALUE like :func:`flash_attention`'s
    ``bias_grad=False`` path.  A row whose keys are ALL masked yields
    the uniform average of V with a finite (~MASK_VALUE-ish) lse, which
    merges to zero weight against any real block in ring composition.

    ``dropout_p`` > 0 (with ``dropout_rng``) applies fused probability
    dropout exactly as :func:`flash_attention` does: the PV contribution
    is masked + rescaled while ``lse`` stays the full undropped row
    statistic, and the dlse cotangent correctly bypasses the keep mask in
    backward.  The mask's element coordinates are LOCAL to this call —
    ring/Ulysses compositions that shard keys must fold the shard offset
    into ``dropout_rng`` themselves if they need cross-hop-independent
    masks.
    """
    from apex_tpu.amp.lists import amp_cast

    q, k, v = amp_cast("attention", q, k, v)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if dropout_p > 0.0 and dropout_rng is None:
        raise ValueError("dropout_p > 0 requires dropout_rng")
    b, h, sq, d = q.shape
    sk = k.shape[-2]
    if bias is not None:
        if bias.ndim < 4:
            bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
        # shared fully-masked-row semantics with the jnp path (see
        # flash_attention's dispatcher); the with-lse bias is ALWAYS the
        # additive-mask form, so stop_gradient here keeps the zero
        # cotangent identical on BOTH dispatch paths (the jnp fallback
        # would otherwise differentiate it naturally — a backend/shape-
        # dependent gradient)
        bias = jax.lax.stop_gradient(
            jnp.maximum(bias, _pallas.MASK_VALUE)
        )
    # Aligned shapes only (ring attention's shards are aligned): padding
    # would need the PAD_VALUE masking the flash dispatcher builds.
    if (
        not _seq_pad(sq)
        and not _seq_pad(sk)
        and _pallas_eligible(q, k, v, dropout_p, causal)
    ):
        _dispatch.record_path("flash_attention_with_lse", "pallas")
        seed = _derive_dropout_seed(dropout_rng, dropout_p)
        qf, kf, vf = (_pad_head_dim(_flatten_bh(x)) for x in (q, k, v))
        bias_f = (
            None if bias is None
            else _format_bias(bias, b, h, sk, 0, 0, bias_grad=False)
        )
        o, lse = _flash_lse(
            qf, kf, vf, bias_f, seed, scale, causal, dropout_p
        )
        return (
            o[..., :d].reshape(b, h, sq, d),
            lse.reshape(b, h, sq),
        )
    _dispatch.record_path("flash_attention_with_lse", "jnp")
    return mha_reference_with_lse(
        q, k, v, bias, causal=causal, scale=scale, dropout_p=dropout_p,
        dropout_rng=dropout_rng,
    )


def mha_reference_with_lse(q, k, v, bias=None, *, causal=False,
                           scale=None, dropout_p: float = 0.0,
                           dropout_rng=None):
    """jnp composition returning ``(o, lse)`` — the correctness reference
    for :func:`flash_attention_with_lse` (numerics identical to
    :func:`mha_reference` plus the row logsumexp).  ``bias`` is the
    additive-mask form (non-trainable upstream; here it differentiates
    naturally but callers pass it stop-gradiented).  Dropout masks the
    normalized probabilities only; ``lse`` stays the undropped row
    statistic (the kernel contract — the mask stream differs from the
    kernel's, both are valid dropout)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = _scores(q, k, bias, causal, scale)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pn = p / l
    if dropout_p > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_p > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p, pn.shape)
        pn = jnp.where(keep, pn / (1.0 - dropout_p), 0.0)
    o = jnp.einsum("bhqk,bhkd->bhqd", pn.astype(q.dtype), v)
    lse = (m + jnp.log(l))[..., 0]
    return o, lse


def fmha_qkvpacked(qkv, bias=None, *, causal=False, scale=None,
                   dropout_p=0.0, dropout_rng=None):
    """Packed-QKV entry point ≙ ``apex/contrib/fmha/fmha.py :: FMHAFun``
    (input (B, S, 3, H, D) as produced by a fused QKV projection)."""
    q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
    o = flash_attention(
        q, k, v, bias, causal=causal, scale=scale,
        dropout_p=dropout_p, dropout_rng=dropout_rng,
    )
    return jnp.moveaxis(o, 1, 2)  # (B, S, H, D)
