"""On-disk kernel tuning cache — measured tile winners as an artifact.

The ROADMAP called the source-level ``_TUNED_TILES`` /
``_TUNED_BLOCK_ROWS`` tables "half-implemented": committing sweep
winners required editing kernel source, so a bench run on a new shape
could never feed the next run's dispatch.  This module makes the
winners a real artifact:

- ``APEX_TPU_TUNE_CACHE=/path/to/cache.json`` is loaded ONCE on first
  lookup (trace time — the kernel entry points take tile sizes as
  static args, so dispatch never pays the file read twice);
- :func:`flash_tiles` / :func:`layer_norm_block_rows` are consulted by
  ``flash_attention._tuned_tile`` and ``layer_norm._block_rows``
  BEFORE their source tables, falling back source-table → heuristic
  exactly as before when no entry matches;
- ``tools/attn_tune.py --cache-out`` persists sweep winners with
  :func:`update_flash` (merge-write: one file accumulates shapes
  across runs).

Schema (JSON, one object)::

    {"version": 1,
     "flash_attention": [
        {"sq": 16384, "d": 128, "causal": true,
         "dtype": "bfloat16" | null,      # null = any dtype
         "backend": "TPU v5 lite" | null, # null = any; prefix-matched
         "tiles": {"fwd": [1024, 1024],
                   "bwd": [1024, 1024],
                   "bwd_dq": [1024, 1024]}}],
     "layer_norm": [
        {"hidden": 4096, "backend": null, "block_rows": 64}]}

Entries are keyed by (shape, dtype, causal, backend); ``backend`` is
matched by prefix against the local device kind (``"TPU v5"`` matches
``"TPU v5 lite"``) so one cache file can serve a heterogeneous fleet,
and ``null`` fields are wildcards.  The FIRST matching entry wins —
write more-specific entries above generic ones.  A malformed cache
file warns once and is ignored (dispatch must never break on a stale
artifact).
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Optional, Tuple

__all__ = [
    "ENV_VAR",
    "flash_tiles",
    "layer_norm_block_rows",
    "load",
    "update_flash",
    "update_layer_norm",
    "reset",
]

ENV_VAR = "APEX_TPU_TUNE_CACHE"

#: (path, parsed dict) of the last successful load — cleared by
#: :func:`reset` (tests) and re-checked when the env var changes.
_CACHE: Optional[tuple] = None


def reset() -> None:
    """Forget the loaded cache (next lookup re-reads the env/file)."""
    global _CACHE
    _CACHE = None


def _backend_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return ""


def load(path: Optional[str] = None) -> dict:
    """Parse ``path`` (default: ``$APEX_TPU_TUNE_CACHE``); ``{}`` when
    unset, missing, or malformed (malformed warns once per load)."""
    path = path or os.environ.get(ENV_VAR)
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise ValueError("cache root must be a JSON object")
        return data
    except (ValueError, OSError) as e:
        warnings.warn(
            f"ignoring malformed tuning cache {path!r}: {e}", stacklevel=2
        )
        return {}


def _cached() -> dict:
    global _CACHE
    path = os.environ.get(ENV_VAR) or ""
    if _CACHE is None or _CACHE[0] != path:
        _CACHE = (path, load(path or None))
    return _CACHE[1]


def _match(entry: dict, *, dtype: Optional[str], backend: str) -> bool:
    want_dtype = entry.get("dtype")
    if want_dtype is not None and dtype is not None and want_dtype != dtype:
        return False
    want_backend = entry.get("backend")
    if want_backend is not None and not backend.startswith(want_backend):
        return False
    return True


def flash_tiles(
    mode: str, sq: int, d: int, causal: bool, dtype=None,
) -> Optional[Tuple[int, int]]:
    """Cached (block_q, block_k) for a flash-attention call, or None.

    ``mode`` ∈ {"fwd", "bwd", "bwd_dq"} — the same keys as
    ``flash_attention._TUNED_TILES``.  ``dtype`` may be a jax dtype or
    name string; None skips the dtype filter.
    """
    entries = _cached().get("flash_attention")
    if not entries:
        return None
    if dtype is None:
        dtype_name = None
    else:
        try:  # normalizes np dtypes, jnp scalar TYPES, and strings alike
            import numpy as np

            dtype_name = np.dtype(dtype).name
        except (TypeError, ImportError):
            dtype_name = getattr(dtype, "name", None) or str(dtype)
    backend = _backend_kind()
    for entry in entries:
        if not isinstance(entry, dict):
            continue
        if entry.get("sq") != sq or entry.get("d") != d:
            continue
        if bool(entry.get("causal")) != bool(causal):
            continue
        if not _match(entry, dtype=dtype_name, backend=backend):
            continue
        pair = (entry.get("tiles") or {}).get(mode)
        if (
            isinstance(pair, (list, tuple)) and len(pair) == 2
            and all(isinstance(x, int) and x > 0 for x in pair)
        ):
            return (pair[0], pair[1])
    return None


def layer_norm_block_rows(hidden: int) -> Optional[int]:
    """Cached row-block size for a fused layer-norm call, or None."""
    entries = _cached().get("layer_norm")
    if not entries:
        return None
    backend = _backend_kind()
    for entry in entries:
        if not isinstance(entry, dict) or entry.get("hidden") != hidden:
            continue
        if not _match(entry, dtype=None, backend=backend):
            continue
        br = entry.get("block_rows")
        if isinstance(br, int) and br > 0:
            return br
    return None


def _merge_write(
    path: str, section: str, key_fields: tuple, entry: dict, merge=None,
):
    data = load(path) if os.path.exists(path) else {}
    data.setdefault("version", 1)
    entries = [e for e in data.get(section, []) if isinstance(e, dict)]
    kept = []
    for e in entries:
        if any(e.get(k) != entry.get(k) for k in key_fields):
            kept.append(e)
        elif merge is not None:
            # fold the displaced same-key entry into the new one (a
            # fwd-sweep winner must survive the bwd sweep's write)
            entry = merge(e, entry)
    data[section] = [entry] + kept
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    reset()


def update_flash(
    path: str, *, sq: int, d: int, causal: bool, tiles: dict,
    dtype: Optional[str] = None, backend: Optional[str] = None,
) -> None:
    """Merge one flash-attention winner into the cache at ``path``
    (atomic tmp+replace).  An existing entry with the same
    (sq, d, causal, dtype, backend) key keeps the tile MODES the new
    write doesn't carry — a fwd sweep and a later bwd sweep accumulate
    into one entry instead of clobbering each other."""

    def merge(old: dict, new: dict) -> dict:
        merged = dict(old.get("tiles") or {})
        merged.update(new["tiles"])
        return {**new, "tiles": merged}

    _merge_write(
        path, "flash_attention",
        ("sq", "d", "causal", "dtype", "backend"),
        {
            "sq": int(sq), "d": int(d), "causal": bool(causal),
            "dtype": dtype, "backend": backend,
            "tiles": {
                m: [int(p[0]), int(p[1])] for m, p in tiles.items() if p
            },
        },
        merge=merge,
    )


def update_layer_norm(
    path: str, *, hidden: int, block_rows: int,
    backend: Optional[str] = None,
) -> None:
    """Merge one layer-norm winner into the cache at ``path``."""
    _merge_write(
        path, "layer_norm", ("hidden", "backend"),
        {
            "hidden": int(hidden), "backend": backend,
            "block_rows": int(block_rows),
        },
    )
