"""Pallas TPU kernels — the ``csrc/`` analog of this framework.

Each module provides raw forward/backward kernels; dtype policy, custom_vjp
wiring, and jnp fallbacks live in the parent :mod:`apex_tpu.ops` modules.
"""
