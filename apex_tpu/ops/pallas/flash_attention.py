"""Pallas TPU flash attention — forward + backward kernels.

TPU-native equivalent of the reference's fused-attention natives:
``apex/contrib/csrc/multihead_attn/*.cu`` (strided-batched-GEMM + warp
softmax + dropout pipeline) and ``apex/contrib/csrc/fmha/`` (fixed-seqlen
flash kernels, seq ≤ 512).  Where those hand-schedule cuBLAS GEMMs and
softmax kernels per architecture, the TPU version is a single online-softmax
(flash) kernel family tiled for the MXU: never materializes the (Sq, Sk)
score matrix in HBM, carries running (max, sum, acc) in VMEM scratch across
the key-block grid dimension, and saves only the logsumexp for backward.

Unlike the reference's fmha (seq ∈ {128,256,384,512} hardcoded per kernel),
block shapes here are chosen at trace time and any Sq/Sk multiple of the
block size works; long-context is handled above this kernel by ring/context
parallelism (apex_tpu.transformer.context_parallel).

Layout: q (BH, Sq, D), k/v (BH, Sk, D) with batch*heads pre-flattened and D
sublane-aligned by the caller (apex_tpu.ops.attention): D <= 128 is only
padded to a multiple of 8 and the tile covers the whole head dim (D = 64
stays 64 — half the FLOPs/HBM of lane-padding it); D > 128 pads to a lane
multiple.
Bias, when present, is (G, RS, Sk) with G ∈ {1, B, BH} (BH % G == 0; the
index map folds the flattened batch-head index as b // (BH/G)) and
RS ∈ {1, Sq} — RS = 1 is the key-padding case, kept as a single row per
batch so the (Sq, Sk) mask matrix is never materialized in HBM.  Additive,
applied after scaling, same semantics as the reference's additive mask path
(``apex/contrib/multihead_attn`` ``mask_additive`` mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import pallas_interpret
from apex_tpu.ops.pallas import introspect, tune_cache

# pinned-jax compat: the class was TPUCompilerParams before the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

# Large negative finite (not -inf: keeps exp() well-defined in f32 after the
# running-max subtraction, same trick as the reference's softmax kernels).
MASK_VALUE = -1e9
# Strictly below MASK_VALUE: what padded-to-tile key columns carry.  A row
# whose REAL keys are all masked at MASK_VALUE then still softmaxes to a
# uniform average over the real keys only — exp(PAD_VALUE - MASK_VALUE)
# underflows to exactly 0 — matching the unpadded reference.  The kernels'
# defense clamp floors at PAD_VALUE (not MASK_VALUE) so the distinction
# survives into the score matrix.
PAD_VALUE = -1.5e9

_LANES = 128


def _dot_precision(dtype):
    """MXU precision for the in-kernel f32 dots.

    Inputs are cast to f32 before every dot; with DEFAULT precision the MXU
    does single-pass bf16 multiplies — right for bf16 inputs (their
    information fits), but for f32 inputs it loses ~8 mantissa bits vs the
    XLA reference path (which decomposes f32 dots into multi-pass form).
    HIGHEST matches the reference at f32; bf16 keeps the fast path.
    """
    return (
        jax.lax.Precision.HIGHEST
        if dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )



# Per-shape tuned tile sizes — ≙ the reference's per-shape kernel-traits
# tables (fmha's fixed-seqlen kernels / multihead_attn launch configs),
# and the same pattern as layer_norm._TUNED_BLOCK_ROWS.  A SOURCE-level
# table: commit tools/attn_tune.py winners here (the entry points are
# jitted with static tile args, so runtime mutation would not retrace
# already-compiled shapes); absent shapes fall back to the _auto_block
# heuristic.  Keys: (sq, d, causal) -> {"fwd": (bq, bk),
# "bwd": (bq, bk), "bwd_dq": (bq, bk)}; the "bwd_dq" pair feeds
# flash_bwd's independent dq-call tiles.
_TUNED_TILES: dict = {
    # tools/attn_tune.py on v5e, 2026-08-01 (onchip_r05.attn_tune.log +
    # attn_bwd_r05.log).  Long-context bench shape: fwd 30.1 -> 43.3
    # TFLOP/s, fwd+bwd 45.7 -> 60.2 at the shared (1024, 1024) winner;
    # bwd-only phase-2 confirmed the dq call's optimum coincides
    # (49.9 TFLOP/s).  The heuristic's (512, 512) loses ~25% at long
    # sequence: tile-grid fixed costs amortize all the way up to
    # 1024-wide blocks on this kernel.
    (16384, 128, True): {
        "fwd": (1024, 1024),
        "bwd": (1024, 1024),
        "bwd_dq": (1024, 1024),
    },
    # BASELINE #4 mha microbench shape: fwd 6.0 -> 6.9 TFLOP/s.  The
    # bwd pair is the best of the 9 bwd-only cells measured before the
    # tunnel dropped (9.2 TFLOP/s at (256, 1024) vs 3.8 at (128, 128));
    # the (512|1024, *) rows are unmeasured — re-sweep on the next
    # window if chasing the last few percent.
    (2048, 64, True): {
        "fwd": (1024, 1024),
        "bwd": (256, 1024),
    },
}


def _tuned_tile(mode, sq, sk, d, causal, dtype=None):
    """(bq, bk) from the tuning cache or the source table, or
    (None, None) → heuristic.

    Lookup order (docs/flash-roofline.md "tuning flow"): the on-disk
    ``APEX_TPU_TUNE_CACHE`` artifact (``tune_cache.flash_tiles`` —
    winners ``tools/attn_tune.py --cache-out`` persisted, keyed by
    (shape, dtype, causal, backend)) wins over the committed
    ``_TUNED_TILES`` source table.  Either way the table is keyed on
    the q-side shape; a tile is only returned if it divides the ACTUAL
    axis it will tile (the kernels have no partial-tile masking), so a
    self-attention-tuned entry can never hand a non-dividing bk to a
    cross-attention call's sk."""
    pair = tune_cache.flash_tiles(mode, sq, d, causal, dtype)
    if pair is None:
        pair = _TUNED_TILES.get((sq, d, causal), {}).get(mode)
    tq, tk = pair or (None, None)
    if tq and sq % tq:
        tq = None
    if tk and sk % tk:
        tk = None
    return tq, tk


def _resolve_tiles(mode, sq, sk, d, causal, dtype, block_q, block_k):
    """The ONE dispatch-time tile resolution — explicit override →
    tuning cache / ``_TUNED_TILES`` → ``_auto_block`` heuristic —
    shared by :func:`flash_fwd`, :func:`flash_bwd`, and the analyzer's
    :func:`kernel_specs` export, so analysis can never resolve a
    different tile than dispatch."""
    tq, tk = _tuned_tile(mode, sq, sk, d, causal, dtype)
    bq = min(block_q or tq, sq) if (block_q or tq) else _auto_block(sq, d)
    bk = min(block_k or tk, sk) if (block_k or tk) else _auto_block(sk, d)
    return bq, bk


def _resolve_dq_tiles(
    sq, sk, d, causal, dtype, block_q, block_k, bq, bk,
    block_q_dq, block_k_dq,
):
    """The dq call's independent tiles (see :func:`flash_bwd`): an
    explicit shared-tile choice suppresses the bwd_dq table entry so
    tuner phase-1 sweeps measure what they pin."""
    if block_q or block_k:
        tq_dq = tk_dq = None
    else:
        tq_dq, tk_dq = _tuned_tile("bwd_dq", sq, sk, d, causal, dtype)
    return (
        min(block_q_dq or tq_dq or bq, sq),
        min(block_k_dq or tk_dq or bk, sk),
    )


def padded_head_dim(d):
    """Kernel-side head dim for a model-side ``d`` — the pure-int form
    of ``ops.attention._pad_head_dim``'s padding contract (D ≤ 128
    pads to the sublane quantum, wider pads to a lane multiple); the
    analyzer and tuner derive kernel specs through this so they can
    never disagree with the dispatcher's padding."""
    return d + ((-d) % 8 if d <= _LANES else (-d) % _LANES)


def _auto_block(seq, d):
    """Default tile size: large enough to amortize per-tile grid overhead.

    At (128, 128) tiles a 2048-seq 128-batched-head causal case is ~33k
    tiles whose fixed cost dominates (~2x slower than unfused XLA on v5e);
    (512, 512) cuts the tile count 16x and is still < ~4 MB VMEM of f32
    score/accumulator buffers for d <= 128.  Wider heads halve the tile to
    keep VMEM bounded.  The kernels have no partial-tile masking, so the
    tile must divide seq exactly — fall through to smaller powers of two.
    """
    cap = 512 if d <= 128 else 256
    for b in (512, 256, 128):
        if b <= cap and b <= seq and seq % b == 0:
            return b
    return seq  # seq < 128 (callers guarantee seq % min(128, seq) == 0)

def _bias_spec(bias_shape, bh, bq, bk, order):
    """BlockSpec for a (G, RS, Sk) bias (module docstring's layout).

    ``order`` is the grid layout: "ij" = (b, qblock, kblock) grids
    (forward, dq), "ji" = (b, kblock, qblock) (dk/dv).
    """
    g, rs, _ = bias_shape
    if bh % g:
        raise ValueError(f"bias batch group {g} must divide BH={bh}")
    div = bh // g
    rb = bq if rs != 1 else 1
    if order == "ji":
        return pl.BlockSpec(
            (1, rb, bk),
            lambda b, j, i, _d=div, _rb=rb: (b // _d, i if _rb != 1 else 0, j),
        )
    return pl.BlockSpec(
        (1, rb, bk),
        lambda b, i, j, _d=div, _rb=rb: (b // _d, i if _rb != 1 else 0, j),
    )


def _dropout_keep_block(seed, bh, i, j, bq, bk, dropout_p):
    """Deterministic keep-mask for tile (i, j) of batch-head ``bh``.

    ≙ the reference's fused philox dropout (multihead_attn ``philox.cuh``/
    ``dropout.cuh``): a counter-based PRNG keyed on (seed, bh, element
    coordinates), so the SAME mask regenerates in every backward kernel
    with zero state.  The hardware PRNG (pltpu.prng_*) has no interpret-
    mode lowering, so this is a pure-uint32 murmur3-finalizer hash over
    the element index — portable, vectorized on the VPU, and independent
    of grid iteration order.  Keep probability = 1 - dropout_p.
    """
    u32 = jnp.uint32
    rows = jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0) + u32(i * bq)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1) + u32(j * bk)
    key = (
        seed.astype(jnp.uint32)
        + bh.astype(jnp.uint32) * u32(0x9E37_79B9)
    )

    def fmix(h, mul):
        h = h ^ (h >> u32(16))
        h = h * mul
        h = h ^ (h >> u32(13))
        h = h * u32(0x27D4_EB2F)
        h = h ^ (h >> u32(16))
        return h + key
    # Keyed two-round hash of the (row, col) PAIR — mix the row first,
    # then fold the column in and mix again.  A single linear row*C+col
    # counter would alias once a seq dim exceeded the constant (correlated
    # dropout at long context); hashing the coordinates separately leaves
    # only accidental (birthday-level) collisions at any Sq/Sk.
    h = fmix(rows ^ key, u32(0x85EB_CA6B))
    h = fmix(h ^ cols, u32(0xC2B2_AE35))
    threshold = u32(min(int(dropout_p * 2**32), 2**32 - 1))
    return h >= threshold


def _causal_mask_block(i, j, bq, bk, offset):
    # Bottom-right-aligned causal mask: query row r sees keys <= r + offset
    # where offset = Sk - Sq (matches jnp.tril(..., k=sk-sq) in the
    # reference composition; identical to the standard convention when
    # Sq == Sk).
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i * bq
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
    return rows + offset >= cols


# ---------------------------------------------------------------------------
# Call plans — the pallas_call arguments as pure functions of static
# parameters.  flash_fwd/flash_bwd dispatch through these, and
# kernel_specs() exports the SAME plans to the static analyzer
# (apex_tpu.analysis.kernels), so the analyzed specs can never drift
# from the dispatched ones.
# ---------------------------------------------------------------------------


def _fwd_plan(bh, sq, sk, d, dtype, *, bq, bk, bias_shape=None,
              has_seed=False):
    nq, nk = pl.cdiv(sq, bq), pl.cdiv(sk, bk)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
    ]
    in_names = ["q", "k", "v"]
    in_shapes = [(bh, sq, d), (bh, sk, d), (bh, sk, d)]
    in_dtypes = [dtype, dtype, dtype]
    if bias_shape is not None:
        in_specs.append(_bias_spec(bias_shape, bh, bq, bk, "ij"))
        in_names.append("bias")
        in_shapes.append(tuple(bias_shape))
        in_dtypes.append(jnp.float32)
    if has_seed:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        in_names.append("seed")
        in_shapes.append((1,))
        in_dtypes.append(jnp.int32)
    return dict(
        grid=(bh, nq, nk),
        in_specs=in_specs,
        in_names=in_names,
        in_shapes=in_shapes,
        in_dtypes=in_dtypes,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_names=["o", "lse"],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )


def _dkdv_plan(bh, sq, sk, d, dtypes, *, bq, bk, bias_shape=None,
               has_seed=False):
    """Grid (BH, nk, nq) — q innermost; dtypes = (q, k, v) dtypes."""
    qd, kd, vd = dtypes
    nq, nk = pl.cdiv(sq, bq), pl.cdiv(sk, bk)
    q_spec_i = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    k_spec_j = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    row_spec_i = pl.BlockSpec((1, bq, _LANES), lambda b, j, i: (b, i, 0))
    in_specs = [
        q_spec_i, k_spec_j, k_spec_j, q_spec_i, row_spec_i, row_spec_i,
    ]
    in_names = ["q", "k", "v", "do", "lse", "delta"]
    in_shapes = [
        (bh, sq, d), (bh, sk, d), (bh, sk, d), (bh, sq, d),
        (bh, sq, _LANES), (bh, sq, _LANES),
    ]
    in_dtypes = [qd, kd, vd, qd, jnp.float32, jnp.float32]
    if bias_shape is not None:
        in_specs.append(_bias_spec(bias_shape, bh, bq, bk, "ji"))
        in_names.append("bias")
        in_shapes.append(tuple(bias_shape))
        in_dtypes.append(jnp.float32)
    if has_seed:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        in_names.append("seed")
        in_shapes.append((1,))
        in_dtypes.append(jnp.int32)
    return dict(
        grid=(bh, nk, nq),
        in_specs=in_specs,
        in_names=in_names,
        in_shapes=in_shapes,
        in_dtypes=in_dtypes,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_names=["dk", "dv"],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), kd),
            jax.ShapeDtypeStruct((bh, sk, d), vd),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )


def _dq_plan(bh, sq, sk, d, dtypes, *, bq, bk, bias_shape=None,
             has_seed=False):
    """Grid (BH, nq, nk) — k innermost; dtypes = (q, k, v) dtypes."""
    qd, kd, vd = dtypes
    nq, nk = pl.cdiv(sq, bq), pl.cdiv(sk, bk)
    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0))
    in_specs = [q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
    in_names = ["q", "k", "v", "do", "lse", "delta"]
    in_shapes = [
        (bh, sq, d), (bh, sk, d), (bh, sk, d), (bh, sq, d),
        (bh, sq, _LANES), (bh, sq, _LANES),
    ]
    in_dtypes = [qd, kd, vd, qd, jnp.float32, jnp.float32]
    if bias_shape is not None:
        in_specs.append(_bias_spec(bias_shape, bh, bq, bk, "ij"))
        in_names.append("bias")
        in_shapes.append(tuple(bias_shape))
        in_dtypes.append(jnp.float32)
    if has_seed:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        in_names.append("seed")
        in_shapes.append((1,))
        in_dtypes.append(jnp.int32)
    return dict(
        grid=(bh, nq, nk),
        in_specs=in_specs,
        in_names=in_names,
        in_shapes=in_shapes,
        in_dtypes=in_dtypes,
        out_specs=[pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))],
        out_names=["dq"],
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), qd)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )


_plan_spec = introspect.from_plan


def kernel_specs(
    bh, sq, sk, d, *, dtype=jnp.bfloat16, causal=True, block_q=None,
    block_k=None, block_q_dq=None, block_k_dq=None, bias_shape=None,
    dropout=False, causal_offset=None, modes=("fwd", "dkdv", "dq"),
):
    """Export :class:`introspect.KernelSpec` records for a flash
    attention call — the static analyzer's view of exactly the
    pallas_calls :func:`flash_fwd` / :func:`flash_bwd` would dispatch
    at this configuration, without tracing or compiling anything.

    Tile sizes resolve exactly like dispatch does (explicit override →
    tuning cache → ``_TUNED_TILES`` → ``_auto_block``), so analyzing
    the DEFAULT config analyzes what the bench actually runs.  ``d``
    is the kernel-side head dim (callers pad via
    ``ops.attention._pad_head_dim``); ``bias_shape`` is the kernel's
    (G, RS, Sk) layout.  ``modes`` selects among "fwd", "dkdv", "dq".
    """
    dtype = jnp.dtype(dtype)
    offset = causal_offset if causal_offset is not None else sk - sq
    specs = []

    def causal_meta(q_axis, k_axis, bq, bk, include_fully_masked):
        if not causal:
            return None
        return {
            "q_axis": q_axis, "k_axis": k_axis, "bq": bq, "bk": bk,
            "offset": offset,
            "include_fully_masked": include_fully_masked,
        }

    common = dict(bias_shape=bias_shape, has_seed=dropout)
    if "fwd" in modes:
        bq, bk = _resolve_tiles(
            "fwd", sq, sk, d, causal, dtype, block_q, block_k
        )
        spec = _plan_spec(
            "flash_fwd",
            _fwd_plan(bh, sq, sk, d, dtype, bq=bq, bk=bk, **common),
            flops_per_cell=4.0 * bq * bk * d,
            # ONE (bq, bk) f32 score value at steady state: s is dead
            # once p = exp(s - m) is formed (elementwise, buffer
            # reusable), unlike the backward kernels where p must stay
            # live across the dp dot.  Matches the measured fact that
            # a (1024, 2048) fwd tile (8 MiB score) fits v5e
            # (docs/flash-roofline.md) — 2x here would wrongly prune
            # the ROADMAP's beyond-the-sweep-edge probe.
            intermediates=(((bq, bk), jnp.float32),),
            causal=causal_meta(1, 2, bq, bk, True),
        )
        spec.meta["matmul_dims"] = {"block_q": bq, "block_k": bk,
                                    "head_dim": d}
        specs.append(spec)
    if "dkdv" in modes or "dq" in modes:
        bq, bk = _resolve_tiles(
            "bwd", sq, sk, d, causal, dtype, block_q, block_k
        )
        bq_dq, bk_dq = _resolve_dq_tiles(
            sq, sk, d, causal, dtype, block_q, block_k, bq, bk,
            block_q_dq, block_k_dq,
        )
        dtypes = (dtype, dtype, dtype)
        if "dkdv" in modes:
            spec = _plan_spec(
                "flash_bwd_dkdv",
                _dkdv_plan(bh, sq, sk, d, dtypes, bq=bq, bk=bk, **common),
                # recompute s + (dv, dp, dk) dots = 4 MXU passes
                flops_per_cell=8.0 * bq * bk * d,
                # peak concurrent (bq, bk) f32 values is 2 (p stays
                # live across the dp dot; ds reuses dp's buffer) —
                # the measured (1024, 1024) v5e config must fit
                intermediates=(
                    ((bq, bk), jnp.float32), ((bq, bk), jnp.float32),
                ),
                causal=causal_meta(2, 1, bq, bk, True),
            )
            spec.meta["matmul_dims"] = {"block_q": bq, "block_k": bk,
                                        "head_dim": d}
            specs.append(spec)
        if "dq" in modes:
            spec = _plan_spec(
                "flash_bwd_dq",
                _dq_plan(
                    bh, sq, sk, d, dtypes, bq=bq_dq, bk=bk_dq, **common
                ),
                flops_per_cell=6.0 * bq_dq * bk_dq * d,
                intermediates=(
                    ((bq_dq, bk_dq), jnp.float32),
                    ((bq_dq, bk_dq), jnp.float32),
                ),
                causal=causal_meta(1, 2, bq_dq, bk_dq, False),
            )
            spec.meta["matmul_dims"] = {"block_q": bq_dq, "block_k": bk_dq,
                                        "head_dim": d}
            specs.append(spec)
    return specs


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _causal_block_live(i, j, bq, bk, offset, include_fully_masked):
    """Whether the (i, j) tile has any work under the causal mask.

    A tile is dead when every (row, col) in it violates the mask; skipping
    dead tiles halves the causal grid's compute (the reference's fmha
    kernels get the same effect from their triangular loop bounds).
    ``include_fully_masked`` additionally keeps tiles whose rows see NO key
    at all (Sq > Sk bottom-right alignment) — those rows still produce the
    uniform-average output / dv, so their tiles must run.
    """
    live = (i * bq + bq - 1 + offset) >= (j * bk)
    if include_fully_masked:
        live = live | ((i * bq + offset) < 0)
    return live


def _fwd_kernel(
    q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref, lse_ref,
    acc_ref, m_ref, l_ref,
    *, scale, causal, bq, bk, nk, offset, prec, dropout_p,
):
    bh = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = (
        _causal_block_live(i, j, bq, bk, offset, include_fully_masked=True)
        if causal
        else True
    )

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        s = s * scale
        if bias_ref is not None:
            # Defense-in-depth clamp (the public API pre-clamps): a -inf
            # bias would pin m_new at -inf and alpha = exp(-inf - -inf) =
            # NaN would poison the whole row.  Clamped, the finite-value
            # invariant below holds for direct flash_fwd callers too.  The
            # floor is PAD_VALUE (< MASK_VALUE) so padded key columns stay
            # strictly below masked real keys.  bias_ref[0] is (bq, bk) or
            # (1, bk) (key-padding row); broadcasting covers both.
            s = s + jnp.maximum(bias_ref[0].astype(jnp.float32), PAD_VALUE)
        if causal:
            s = jnp.where(
                _causal_mask_block(i, j, bq, bk, offset), s, MASK_VALUE
            )

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # The softmax DENOMINATOR accumulates the full p (dropout acts on
        # the normalized probabilities, not the row sum); only the PV
        # contribution is masked + 1/(1-p)-rescaled — elementwise, so it
        # commutes with the final /l normalization.
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            keep = _dropout_keep_block(
                seed_ref[0], bh, i, j, bq, bk, dropout_p
            )
            p_v = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        else:
            p_v = p
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p_v, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        # MASK_VALUE is finite, so even a fully-masked row has p = 1 at its
        # row max and l >= 1: no divide-by-zero, and such a row yields a
        # uniform average of V — identical to the jnp reference (softmax of
        # constant scores), not zeros.
        l = l_ref[:, :1]
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)[None]
        lse = m_ref[:, :1] + jnp.log(l)
        # lse carries a broadcast 128-lane dim — Mosaic requires the last
        # two block dims tile-aligned, so a (1, bq) row block is not
        # lowerable; (bq, 128) is (same layout as jax's reference TPU
        # flash attention).
        lse_ref[...] = jnp.broadcast_to(lse, (bq, _LANES))[None]


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "causal", "block_q", "block_k", "causal_offset",
        "dropout_p",
    ),
)
def flash_fwd(
    q, k, v, bias, *, scale, causal, block_q=None, block_k=None,
    causal_offset=None, dropout_p=0.0, dropout_seed=None,
):
    """Returns (o, lse).  q (BH,Sq,D), k/v (BH,Sk,D).

    lse is f32 (BH, Sq, 128) — the row logsumexp broadcast across a lane
    dim so its blocks are TPU-tileable; consumers read lane 0.

    ``causal_offset`` overrides the bottom-right alignment offset
    (default ``Sk - Sq``) — callers that pad Sq/Sk to tile multiples pass
    the UNPADDED ``sk - sq`` so valid rows keep their original mask.

    ``dropout_p`` > 0 fuses attention-probability dropout into the PV
    accumulation (≙ the reference's in-kernel philox dropout), keyed by
    the int32 scalar ``dropout_seed`` — the identical mask regenerates in
    every backward kernel from (seed, bh, element coords).
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _resolve_tiles(
        "fwd", sq, sk, d, causal, q.dtype, block_q, block_k
    )
    nk = pl.cdiv(sk, bk)
    offset = causal_offset if causal_offset is not None else sk - sq
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")

    plan = _fwd_plan(
        bh, sq, sk, d, q.dtype, bq=bq, bk=bk,
        bias_shape=None if bias is None else bias.shape,
        has_seed=dropout_p > 0.0,
    )
    args = [q, k, v]
    common = dict(
        scale=scale, causal=causal, bq=bq, bk=bk, nk=nk, offset=offset,
        prec=_dot_precision(q.dtype), dropout_p=dropout_p,
        has_bias=bias is not None, has_seed=dropout_p > 0.0,
    )
    if bias is not None:
        args.append(bias)
    # The seed operand exists ONLY on dropout runs, so the (on-chip
    # proven) no-dropout kernels keep their exact operand signature.
    if dropout_p > 0.0:
        args.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
    kernel = functools.partial(_fwd_entry, **common)

    return pl.pallas_call(
        kernel,
        grid=plan["grid"],
        in_specs=plan["in_specs"],
        out_specs=plan["out_specs"],
        out_shape=plan["out_shape"],
        scratch_shapes=plan["scratch_shapes"],
        compiler_params=_CompilerParams(
            dimension_semantics=plan["dimension_semantics"],
        ),
        interpret=pallas_interpret(),
    )(*args)


def _fwd_entry(*refs, has_bias, has_seed, **kw):
    """Adapter: optional bias/seed operands -> fixed kernel signature."""
    i = 3
    bias_ref = refs[i] if has_bias else None
    i += int(has_bias)
    seed_ref = refs[i] if has_seed else None
    i += int(has_seed)
    o_ref, lse_ref, acc, m, l = refs[i:]
    _fwd_kernel(
        refs[0], refs[1], refs[2], bias_ref, seed_ref, o_ref, lse_ref,
        acc, m, l, **kw
    )


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _recompute_p(
    q, k, bias_blk, lse, i, j, bq, bk, scale, causal, offset, prec, sk_total
):
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=prec,
    ) * scale
    if bias_blk is not None:
        # Same -inf clamp as the forward kernel, so the recomputed p
        # matches it bit-for-bit.
        s = s + jnp.maximum(bias_blk, PAD_VALUE)
    mask = None
    if causal:
        mask = _causal_mask_block(i, j, bq, bk, offset)
        s = jnp.where(mask, s, MASK_VALUE)
    p = jnp.exp(s - lse)
    if causal:
        # FULLY-masked rows (Sq > Sk bottom-right-aligned causal: rows with
        # row + offset < 0 see no keys) need exact handling: their saved
        # lse is MASK_VALUE + log(Sk), which f32 rounds back to MASK_VALUE
        # (ulp(1e9) = 64), so exp(s - lse) would give 1 instead of the true
        # uniform 1/Sk and inflate dv by Sk x.  Substitute the closed form;
        # rows with >= 1 real key are untouched (their lse is O(1) and the
        # masked entries' exp underflow to exactly 0).  This matches the
        # jnp reference, whose softmax over an all-MASK_VALUE row is
        # exactly uniform and backprops that row's cotangent into dv.
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i * bq
        fully_masked = (row_ids + offset) < 0
        p = jnp.where(fully_masked, 1.0 / sk_total, p)
    return p, mask


def _dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, seed_ref,
    dk_ref, dv_ref, dk_acc, dv_acc,
    *, scale, causal, bq, bk, nq, offset, prec, sk_total, dropout_p,
):
    bh = pl.program_id(0)
    i = pl.program_id(2)  # q-block index (inner loop)
    j = pl.program_id(1)  # k-block index

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # fully-masked q rows still contribute their uniform p to dv, so their
    # tiles stay live (include_fully_masked=True)
    live = (
        _causal_block_live(i, j, bq, bk, offset, include_fully_masked=True)
        if causal
        else True
    )

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        bias_blk = (
            None if bias_ref is None else bias_ref[0].astype(jnp.float32)
        )

        p, mask = _recompute_p(
            q, k, bias_blk, lse, i, j, bq, bk, scale, causal, offset, prec,
            sk_total,
        )
        # With fused dropout D = keep/(1-p): o = (D ⊙ p̃) V, so
        # dv = (D⊙p)ᵀ do and ds = p ⊙ (D⊙dp − delta) — delta already
        # carries the D factor through rowsum(do·o).  Mask regenerated
        # bit-identically from (seed, bh, coords).
        if dropout_p > 0.0:
            keep = _dropout_keep_block(
                seed_ref[0], bh, i, j, bq, bk, dropout_p
            )
            drop = jnp.where(keep, 1.0 / (1.0 - dropout_p), 0.0)
            p_v = p * drop
        else:
            drop = None
            p_v = p
        # dv += (D⊙p)^T @ do
        dv_acc[...] += jax.lax.dot_general(
            p_v, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        # dp = do @ v^T ; ds = p * (D⊙dp - delta)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        if drop is not None:
            dp = dp * drop
        ds = p * (dp - delta)
        if mask is not None:
            # the causal mask is a where() on s: no gradient flows through
            # the masked branch to q/k (dv, by contrast, takes the full p)
            ds = jnp.where(mask, ds, 0.0)
        # dk += ds^T @ q * scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        ) * scale

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)[None]
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)[None]


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, seed_ref,
    dq_ref, dq_acc,
    *, scale, causal, bq, bk, nk, offset, prec, sk_total, dropout_p,
):
    bh = pl.program_id(0)
    i = pl.program_id(1)  # q-block index
    j = pl.program_id(2)  # k-block index (inner loop)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    # dq of a fully-masked row is exactly 0 (the mask's where() blocks the
    # gradient), so those tiles are dead here — no include_fully_masked
    live = (
        _causal_block_live(i, j, bq, bk, offset, include_fully_masked=False)
        if causal
        else True
    )

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        bias_blk = (
            None if bias_ref is None else bias_ref[0].astype(jnp.float32)
        )

        p, mask = _recompute_p(
            q, k, bias_blk, lse, i, j, bq, bk, scale, causal, offset, prec,
            sk_total,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        if dropout_p > 0.0:
            keep = _dropout_keep_block(
                seed_ref[0], bh, i, j, bq, bk, dropout_p
            )
            dp = dp * jnp.where(keep, 1.0 / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        ) * scale

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)[None]


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "causal", "block_q", "block_k", "causal_offset",
        "dropout_p", "block_q_dq", "block_k_dq",
    ),
)
def flash_bwd(
    q, k, v, o, lse, do, bias, *, scale, causal, block_q=None, block_k=None,
    dlse=None, causal_offset=None, dropout_p=0.0, dropout_seed=None,
    block_q_dq=None, block_k_dq=None,
):
    """Returns (dq, dk, dv).  Recomputation backward: only lse was saved.

    ``dlse`` (f32, (BH, Sq)) is an optional cotangent for the forward's
    logsumexp output — used by consumers that differentiate through lse
    (ring attention's online-softmax merge).  The math folds it into the
    existing kernels: with p = exp(s - lse),

        ds_ij = p_ij * (dp_ij - delta_i) + p_ij * dlse_i
              = p_ij * (dp_ij - (delta_i - dlse_i)),

    so passing ``delta - dlse`` where the kernels expect delta yields the
    dq/dk that include the lse contribution; dv = pᵀ do is lse-independent.

    ``causal_offset`` serves padded-shape callers: the causal alignment
    uses the UNPADDED geometry (default: ``sk - sq``).  The fully-masked-
    row closed form keeps ``sk`` itself — callers never pad Sk in the
    Sq > Sk causal geometry where it applies (``_pallas_eligible``).

    ``block_q_dq``/``block_k_dq`` override the tile sizes of the **dq**
    pallas_call independently of the dkdv one (default: same as
    ``block_q``/``block_k``).  The two backward kernels iterate the
    grid transposed (dkdv: k-tiles outer, q inner; dq: q outer, k
    inner), so their optimal tiles can differ; ``tools/attn_tune.py
    --bwd-only`` sweeps them.  Safe under dropout: the keep-mask hash
    keys on absolute element coordinates, not tile geometry.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _resolve_tiles(
        "bwd", sq, sk, d, causal, q.dtype, block_q, block_k
    )
    nq, nk = pl.cdiv(sq, bq), pl.cdiv(sk, bk)
    bq_dq, bk_dq = _resolve_dq_tiles(
        sq, sk, d, causal, q.dtype, block_q, block_k, bq, bk,
        block_q_dq, block_k_dq,
    )
    nq_dq, nk_dq = pl.cdiv(sq, bq_dq), pl.cdiv(sk, bk_dq)
    offset = causal_offset if causal_offset is not None else sk - sq
    sk_total = sk
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    has_seed = dropout_p > 0.0
    seed_args = (
        [jnp.asarray(dropout_seed, jnp.int32).reshape(1)] if has_seed else []
    )

    # delta_i = rowsum(do * o) — the softmax-jacobian correction term
    # (≙ the reference bwd kernels' row reduction before the ds GEMM).
    # Broadcast over a 128-lane dim like lse so blocks are tile-aligned.
    delta_rows = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )
    if dlse is not None:
        delta_rows = delta_rows - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta_rows[..., None], lse.shape)

    common = [q, k, v, do, lse, delta]
    dtypes = (q.dtype, k.dtype, v.dtype)
    bias_shape = None if bias is None else bias.shape
    kern_kw = dict(
        scale=scale, causal=causal, bq=bq, bk=bk,
        prec=_dot_precision(q.dtype), sk_total=sk_total,
        dropout_p=dropout_p, has_bias=bias is not None, has_seed=has_seed,
    )

    # --- dk/dv: grid (BH, nk, nq), q innermost ---
    plan = _dkdv_plan(
        bh, sq, sk, d, dtypes, bq=bq, bk=bk, bias_shape=bias_shape,
        has_seed=has_seed,
    )
    args = list(common)
    if bias is not None:
        args.append(bias)
    args += seed_args
    dkdv_kernel = functools.partial(
        _dkdv_entry, nq=nq, offset=offset, **kern_kw
    )
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid=plan["grid"],
        in_specs=plan["in_specs"],
        out_specs=plan["out_specs"],
        out_shape=plan["out_shape"],
        scratch_shapes=plan["scratch_shapes"],
        compiler_params=_CompilerParams(
            dimension_semantics=plan["dimension_semantics"],
        ),
        interpret=pallas_interpret(),
    )(*args)

    # --- dq: grid (BH, nq, nk), k innermost; independent tile sizes ---
    kern_kw_dq = dict(kern_kw, bq=bq_dq, bk=bk_dq)
    plan = _dq_plan(
        bh, sq, sk, d, dtypes, bq=bq_dq, bk=bk_dq, bias_shape=bias_shape,
        has_seed=has_seed,
    )
    args = list(common)
    if bias is not None:
        args.append(bias)
    args += seed_args
    dq_kernel = functools.partial(
        _dq_entry, nk=nk_dq, offset=offset, **kern_kw_dq
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=plan["grid"],
        in_specs=plan["in_specs"],
        out_specs=plan["out_specs"][0],
        out_shape=plan["out_shape"][0],
        scratch_shapes=plan["scratch_shapes"],
        compiler_params=_CompilerParams(
            dimension_semantics=plan["dimension_semantics"],
        ),
        interpret=pallas_interpret(),
    )(*args)
    return dq, dk, dv


def _dkdv_entry(*refs, has_bias, has_seed, **kw):
    i = 6
    bias_ref = refs[i] if has_bias else None
    i += int(has_bias)
    seed_ref = refs[i] if has_seed else None
    i += int(has_seed)
    dk, dv, dka, dva = refs[i:]
    _dkdv_kernel(
        *refs[:6], bias_ref, seed_ref, dk, dv, dka, dva, **kw
    )


def _dq_entry(*refs, has_bias, has_seed, **kw):
    i = 6
    bias_ref = refs[i] if has_bias else None
    i += int(has_bias)
    seed_ref = refs[i] if has_seed else None
    i += int(has_seed)
    dq, dqa = refs[i:]
    _dq_kernel(*refs[:6], bias_ref, seed_ref, dq, dqa, **kw)


# ---------------------------------------------------------------------------
# Bias gradient (trainable additive bias, e.g. relative-position biases)
# ---------------------------------------------------------------------------


def _dbias_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, seed_ref,
    dbias_ref, acc_ref,
    *, scale, causal, bq, bk, offset, prec, sk_total, inner_total, rs1, div,
    dropout_p,
):
    j = pl.program_id(2)
    t = pl.program_id(3)
    # rs1 folds (q-block, group-member) into the inner grid dim; the full
    # per-row case keeps the q-block as its own (parallel) grid dim.
    i = (t // div) if rs1 else pl.program_id(1)
    # the flattened batch-head index this step works on (dropout seeding
    # must match the fwd/dq/dkdv kernels, which key on bh)
    bh_idx = pl.program_id(0) * div + (t % div if rs1 else t)

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # dbias = ds, and ds is 0 wherever the causal where() masks — including
    # every entry of a fully-masked row — so dead tiles stay dead here.
    live = (
        _causal_block_live(i, j, bq, bk, offset, include_fully_masked=False)
        if causal
        else True
    )

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        bias_blk = bias_ref[0].astype(jnp.float32)

        p, mask = _recompute_p(
            q, k, bias_blk, lse, i, j, bq, bk, scale, causal, offset, prec,
            sk_total,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        if dropout_p > 0.0:
            keep = _dropout_keep_block(
                seed_ref[0], bh_idx, i, j, bq, bk, dropout_p
            )
            dp = dp * jnp.where(keep, 1.0 / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        if rs1:
            # key-padding layout: dbias row g is the sum of ds over ALL q
            # rows of every group member — keep a broadcast row accumulator
            acc_ref[...] += jnp.broadcast_to(
                jnp.sum(ds, axis=0, keepdims=True), acc_ref.shape
            )
        else:
            acc_ref[...] += ds

    @pl.when(t == inner_total - 1)
    def _finalize():
        if rs1:
            dbias_ref[...] = acc_ref[:1].astype(dbias_ref.dtype)[None]
        else:
            dbias_ref[...] = acc_ref[...].astype(dbias_ref.dtype)[None]


def _dbias_entry(*refs, has_seed, **kw):
    i = 7
    seed_ref = refs[i] if has_seed else None
    i += int(has_seed)
    dbias_ref, acc_ref = refs[i:]
    _dbias_kernel(*refs[:7], seed_ref, dbias_ref, acc_ref, **kw)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "causal", "block_q", "block_k", "causal_offset",
        "dropout_p",
    ),
)
def flash_dbias(
    q, k, v, o, lse, do, bias, *, scale, causal, block_q=None, block_k=None,
    causal_offset=None, dropout_p=0.0, dropout_seed=None,
):
    """Gradient of the additive bias: dbias (same (G, RS, Sk) layout).

    ≙ the reference's trainable-bias fused MHA backward (SURVEY §2.6
    multihead_attn :: self_attn_bias additive-bias variants) — there a
    strided-batched GEMM epilogue accumulates ds into dbias; here a third
    recompute pass reduces ds over the bias's broadcast group:

        dbias[g, r, c] = Σ_{m ∈ group g} Σ_{rows folded by RS} ds[m·.., r, c]

    with ds = p · (dp − delta), exactly the dk/dv kernels' recomputation.
    The group reduction (BH/G members, and all Sq rows when RS = 1) runs
    in the innermost "arbitrary" grid dim accumulating in VMEM scratch, so
    nothing larger than the bias itself ever hits HBM.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    g, rs, _ = bias.shape
    if bh % g:
        raise ValueError(f"bias batch group {g} must divide BH={bh}")
    div = bh // g
    bq = min(block_q, sq) if block_q else _auto_block(sq, d)
    bk = min(block_k, sk) if block_k else _auto_block(sk, d)
    nq, nk = pl.cdiv(sq, bq), pl.cdiv(sk, bk)
    offset = causal_offset if causal_offset is not None else sk - sq
    rs1 = rs == 1
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    has_seed = dropout_p > 0.0

    delta_rows = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )
    delta = jnp.broadcast_to(delta_rows[..., None], lse.shape)

    if rs1:
        grid = (g, 1, nk, nq * div)
        inner_total = nq * div

        def bh_idx(b, _, j, t):
            return (b * div + t % div, t // div, 0)

        def row_idx(b, _, j, t):
            return (b * div + t % div, t // div, 0)

        bias_spec = pl.BlockSpec((1, 1, bk), lambda b, _, j, t: (b, 0, j))
        out_spec = pl.BlockSpec((1, 1, bk), lambda b, _, j, t: (b, 0, j))
        out_shape = jax.ShapeDtypeStruct((g, 1, sk), bias.dtype)
        acc_shape = pltpu.VMEM((8, bk), jnp.float32)
    else:
        grid = (g, nq, nk, div)
        inner_total = div

        def bh_idx(b, i, j, t):
            return (b * div + t, i, 0)

        def row_idx(b, i, j, t):
            return (b * div + t, i, 0)

        bias_spec = pl.BlockSpec((1, bq, bk), lambda b, i, j, t: (b, i, j))
        out_spec = pl.BlockSpec((1, bq, bk), lambda b, i, j, t: (b, i, j))
        out_shape = jax.ShapeDtypeStruct((g, sq, sk), bias.dtype)
        acc_shape = pltpu.VMEM((bq, bk), jnp.float32)

    def k_idx(b, i, j, t):
        return ((b * div + (t % div if rs1 else t)), j, 0)

    kernel = functools.partial(
        _dbias_entry, scale=scale, causal=causal, bq=bq, bk=bk,
        offset=offset, prec=_dot_precision(q.dtype), sk_total=sk,
        inner_total=inner_total, rs1=rs1, div=div, dropout_p=dropout_p,
        has_seed=has_seed,
    )
    in_specs = [
        pl.BlockSpec((1, bq, d), bh_idx),
        pl.BlockSpec((1, bk, d), k_idx),
        pl.BlockSpec((1, bk, d), k_idx),
        pl.BlockSpec((1, bq, d), bh_idx),
        pl.BlockSpec((1, bq, _LANES), row_idx),
        pl.BlockSpec((1, bq, _LANES), row_idx),
        bias_spec,
    ]
    args = [q, k, v, do, lse, delta, bias]
    if has_seed:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=out_shape,
        scratch_shapes=[acc_shape],
        compiler_params=_CompilerParams(
            dimension_semantics=(
                "parallel", "parallel", "parallel", "arbitrary"
            ),
        ),
        interpret=pallas_interpret(),
    )(*args)
