"""Pallas TPU kernels for fused LayerNorm / RMSNorm forward + backward.

TPU-native equivalent of the reference's ``csrc/layer_norm_cuda_kernel.cu``
(:: ``cuApplyLayerNorm``, ``cuApplyRMSNorm``, ``cuComputePartGradGammaBeta``,
``cuComputeGradInput``).  Where the CUDA kernels do a warp-shuffle Welford
reduction per row, the TPU kernels tile rows into VMEM blocks and let the VPU
reduce along lanes; statistics are computed in f32 regardless of I/O dtype
(the reference's "Mixed" classes).

Layout: input is pre-flattened to ``(rows, hidden)``; ``hidden`` must be a
multiple of 128 (lane width) for the Pallas path — callers fall back to the
jnp path otherwise.  Gamma/beta gradients are produced as per-block partial
sums ``(num_blocks, hidden)`` (≙ ``cuComputePartGradGammaBeta``) and reduced
by the caller.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import pallas_interpret
from apex_tpu.ops.pallas import introspect, tune_cache

_VMEM_BUDGET_PER_BUF = 360_000  # bytes of f32 per row-block buffer (heuristic)

# Per-hidden-size tuned row-block sizes, ≙ the reference FastLayerNorm's
# per-hidden-size kernel-traits table (apex/contrib/csrc/layer_norm/
# ln_kernel_traits.h): measured on a v5e chip with tools/ln_tune.py
# (rows=16384, bf16 I/O, fwd+bwd, serialized-scan timing; full table in
# docs/normalization.md).  Spread across block sizes is ~3-45% (small
# hidden sizes want the largest block; >=4096 is VMEM-capped lower).
# Absent sizes fall back to the VMEM-budget heuristic below.
_TUNED_BLOCK_ROWS: dict = {
    768: 256,
    1024: 256,
    1536: 128,
    2048: 256,
    3072: 256,
    4096: 64,
    5120: 32,
    6144: 64,
    8192: 64,
}


def _block_rows(rows: int, hidden: int) -> int:
    # same lookup order as flash_attention._tuned_tile: the on-disk
    # APEX_TPU_TUNE_CACHE artifact wins over the committed source
    # table, then the VMEM-budget heuristic
    br = tune_cache.layer_norm_block_rows(hidden)
    if br is None:
        br = _TUNED_BLOCK_ROWS.get(hidden)
    if br is None:
        br = (_VMEM_BUDGET_PER_BUF // max(hidden, 1)) // 8 * 8
        br = max(8, min(256, br))
    return min(br, max(8, (rows + 7) // 8 * 8))


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mu_ref, rstd_ref, *, eps, rms):
    x = x_ref[...].astype(jnp.float32)
    if rms:
        mu = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(x * x, axis=-1, keepdims=True)
    else:
        mu = jnp.mean(x, axis=-1, keepdims=True)
        xc = x - mu
        var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * rstd
    w = w_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    y = xhat * w + b
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _ln_bwd_kernel(
    x_ref,
    w_ref,
    b_ref,
    mu_ref,
    rstd_ref,
    g_ref,
    dx_ref,
    dwp_ref,
    dbp_ref,
    *,
    rows,
    block_rows,
    rms,
    x_is_output,
):
    i = pl.program_id(0)
    xw = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    rstd = rstd_ref[...]
    if x_is_output:
        # memory_efficient: recover xhat from the saved output y = xhat*w + b.
        b = b_ref[...].astype(jnp.float32)
        wsafe = jnp.where(w == 0.0, 1.0, w)
        xhat = jnp.where(w == 0.0, 0.0, (xw - b) / wsafe)
    else:
        mu = mu_ref[...]
        xhat = (xw - mu) * rstd
    dyw = g * w
    c2 = jnp.mean(dyw * xhat, axis=-1, keepdims=True)
    if rms:
        dx = rstd * (dyw - xhat * c2)
    else:
        c1 = jnp.mean(dyw, axis=-1, keepdims=True)
        dx = rstd * (dyw - c1 - xhat * c2)
    dx_ref[...] = dx.astype(dx_ref.dtype)

    # Per-block partial gamma/beta grads; mask grid-padding rows.  Partials
    # are written into sublane row 0 of an (1, 8, hidden) block — TPU block
    # shapes need the last two dims divisible by (8, 128).
    row_ids = jax.lax.broadcasted_iota(jnp.int32, xhat.shape, 0) + i * block_rows
    valid = row_ids < rows
    gm = jnp.where(valid, g, 0.0)
    xhm = jnp.where(valid, xhat, 0.0)
    hidden = xhat.shape[-1]
    zeros7 = jnp.zeros((1, 7, hidden), jnp.float32)
    dw_part = jnp.sum(gm * xhm, axis=0, keepdims=True)
    db_part = jnp.sum(gm, axis=0, keepdims=True)
    dwp_ref[...] = jnp.concatenate([dw_part[None], zeros7], axis=1)
    dbp_ref[...] = jnp.concatenate([db_part[None], zeros7], axis=1)


# ---------------------------------------------------------------------------
# Call plans — pallas_call arguments as pure functions of static
# parameters; dispatch and the static analyzer's kernel_specs() export
# share them (see flash_attention.py's plan section).
# ---------------------------------------------------------------------------


def _fwd_plan(rows, hidden, dtypes, *, br):
    xd, wd, bd = dtypes
    return dict(
        grid=(pl.cdiv(rows, br),),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        in_names=["x", "w", "b"],
        in_shapes=[(rows, hidden), (1, hidden), (1, hidden)],
        in_dtypes=[xd, wd, bd],
        out_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_names=["y", "mu", "rstd"],
        out_shape=[
            jax.ShapeDtypeStruct((rows, hidden), xd),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        scratch_shapes=[],
        dimension_semantics=("parallel",),
    )


def _bwd_plan(rows, hidden, dtypes, *, br):
    xd, wd, bd = dtypes
    nblocks = pl.cdiv(rows, br)
    return dict(
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        ],
        in_names=["x", "w", "b", "mu", "rstd", "g"],
        in_shapes=[
            (rows, hidden), (1, hidden), (1, hidden), (rows, 1),
            (rows, 1), (rows, hidden),
        ],
        in_dtypes=[xd, wd, bd, jnp.float32, jnp.float32, xd],
        out_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, 8, hidden), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 8, hidden), lambda i: (i, 0, 0)),
        ],
        out_names=["dx", "dw_partial", "db_partial"],
        out_shape=[
            jax.ShapeDtypeStruct((rows, hidden), xd),
            jax.ShapeDtypeStruct((nblocks, 8, hidden), jnp.float32),
            jax.ShapeDtypeStruct((nblocks, 8, hidden), jnp.float32),
        ],
        scratch_shapes=[],
        dimension_semantics=("parallel",),
    )


def kernel_specs(
    rows, hidden, *, dtype=jnp.bfloat16, block_rows=None,
    modes=("fwd", "bwd"),
):
    """Export :class:`introspect.KernelSpec` records for the fused
    layer-norm kernels at this configuration — the static analyzer's
    compile-free view (mirrors ``flash_attention.kernel_specs``).
    The row-block resolves exactly like dispatch (override → tuning
    cache → ``_TUNED_BLOCK_ROWS`` → VMEM heuristic)."""
    dtype = jnp.dtype(dtype)
    br = block_rows or _block_rows(rows, hidden)
    dtypes = (dtype, dtype, dtype)
    specs = []
    if "fwd" in modes:
        specs.append(introspect.from_plan(
            "layer_norm_fwd",
            _fwd_plan(rows, hidden, dtypes, br=br),
            # VPU row reductions: ~8 passes over the (br, hidden) block
            # (mean, var, rsqrt-normalize, scale+shift)
            flops_per_cell=8.0 * br * hidden,
        ))
    if "bwd" in modes:
        specs.append(introspect.from_plan(
            "layer_norm_bwd",
            _bwd_plan(rows, hidden, dtypes, br=br),
            flops_per_cell=12.0 * br * hidden,
            intermediates=(((br, hidden), jnp.float32),),
        ))
    return specs


@functools.partial(jax.jit, static_argnames=("eps", "rms", "block_rows"))
def layer_norm_fwd(x2d, w, b, *, eps: float, rms: bool, block_rows=None):
    """Returns (y, mu, rstd); mu/rstd are f32 of shape (rows, 1).

    ``block_rows`` overrides the tuned/heuristic row-block size (used by
    tools/ln_tune.py to build ``_TUNED_BLOCK_ROWS``)."""
    rows, hidden = x2d.shape
    br = block_rows or _block_rows(rows, hidden)
    plan = _fwd_plan(rows, hidden, (x2d.dtype, w.dtype, b.dtype), br=br)
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps, rms=rms),
        grid=plan["grid"],
        in_specs=plan["in_specs"],
        out_specs=plan["out_specs"],
        out_shape=plan["out_shape"],
        interpret=pallas_interpret(),
    )(x2d, w.reshape(1, hidden), b.reshape(1, hidden))


@functools.partial(
    jax.jit, static_argnames=("rms", "x_is_output", "block_rows")
)
def layer_norm_bwd(
    x2d, w, b, mu, rstd, g, *, rms: bool, x_is_output: bool, block_rows=None
):
    """Returns (dx, dw, db); dw/db are f32 of shape (hidden,).

    ``x_is_output=True`` is the memory_efficient path: ``x2d`` holds the saved
    forward *output* and xhat is recovered in-kernel (≙ the reference's
    ``memory_efficient`` template parameter).
    """
    rows, hidden = x2d.shape
    br = block_rows or _block_rows(rows, hidden)
    plan = _bwd_plan(rows, hidden, (x2d.dtype, w.dtype, b.dtype), br=br)
    kernel = functools.partial(
        _ln_bwd_kernel,
        rows=rows,
        block_rows=br,
        rms=rms,
        x_is_output=x_is_output,
    )
    dx, dwp, dbp = pl.pallas_call(
        kernel,
        grid=plan["grid"],
        in_specs=plan["in_specs"],
        out_specs=plan["out_specs"],
        out_shape=plan["out_shape"],
        interpret=pallas_interpret(),
    )(x2d, w.reshape(1, hidden), b.reshape(1, hidden), mu, rstd, g)
    return dx, jnp.sum(dwp[:, 0, :], axis=0), jnp.sum(dbp[:, 0, :], axis=0)
