"""Pallas TPU paged decode attention — the single-query serving kernel.

The serving half of ``flash_attention.py``: where the training kernel
tiles a (Sq, Sk) score matrix, autoregressive decode has exactly ONE
query row per sequence and a KV history that lives in the paged cache
(:mod:`apex_tpu.serve.cache`) — block-pooled pages scattered through a
shared pool, addressed by a per-sequence page table.  This kernel reads
the pages IN PLACE via scalar-prefetched page-table indexing
(``pltpu.PrefetchScalarGridSpec``: the BlockSpec index map looks the
page id up before the DMA issues), so decode attention never gathers
the history into a contiguous buffer — memory stays O(live tokens) and
the HBM traffic is exactly one read of each live page.

Reuses the flash-attention block machinery: the same online-softmax
(running max / sum / accumulator in VMEM scratch across the page grid
dimension), the same finite ``MASK_VALUE`` masking discipline, and the
same lane-broadcast scratch layout.  Differences, all decode-specific:

- the grid is ``(B, num_pages)`` — one program per (sequence, page);
  the query "tile" is the single (H, D) row, kept resident in VMEM for
  the whole page walk;
- **fused RoPE**: the query row is rotated in-kernel from per-sequence
  cos/sin rows, so the per-layer q-rotation costs no extra HBM
  round-trip (the cached keys were rotated at append time);
- **int8 KV**: pages may carry blockwise int8 codes (one f32 scale per
  (head, token) row, the ``parallel/comm.py`` codec's layout) —
  dequantized on the VPU right after the page DMA, so the wire/HBM
  format is int8 end to end;
- scores run on the VPU (a batched mat-vec cannot feed the MXU); decode
  attention is HBM-bound, so the page reads — not the flops — set the
  roofline.

Page layout is ``(P, H, page, D)`` (heads OUTSIDE the page dim): the
in-kernel q·K and p·V contractions are then head-batched over the
leading block axis with no transposes.  Positions ``>= length`` (the
padded tail of the last live page) mask at ``MASK_VALUE``; pages whose
base position is beyond ``length`` are dead and skipped entirely
(``pl.when``), so a sequence pays only ``ceil(length / page)`` page
reads.  A sequence with ``length == 0`` (an idle decode slot) produces
exactly zeros.

The jnp reference and the public dispatching wrapper live in
:mod:`apex_tpu.ops.paged_attention`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import pallas_interpret
from apex_tpu.ops.pallas import introspect
from apex_tpu.ops.pallas.flash_attention import (
    _CompilerParams,
    _LANES,
    MASK_VALUE,
)
# the ONE rotate_half (pure jnp split/concat — lowers fine inside the
# kernel body), so serving can never drift from the training rotation
from apex_tpu.ops.rope import rotate_half

__all__ = ["paged_decode_fwd", "kernel_specs"]


# ---------------------------------------------------------------------------
# Call plan — shared by dispatch and the static analyzer's
# kernel_specs() export (see flash_attention.py's plan section).
# ---------------------------------------------------------------------------


def _decode_plan(
    b, h, d, p_, page, np_, dtype, kv_dtype, *, has_scales, has_rope,
):
    in_specs = [
        pl.BlockSpec((1, 1, h, d), lambda b, j, pt, ln: (b, 0, 0, 0)),
        pl.BlockSpec(
            (1, h, page, d), lambda b, j, pt, ln: (pt[b, j], 0, 0, 0)
        ),
        pl.BlockSpec(
            (1, h, page, d), lambda b, j, pt, ln: (pt[b, j], 0, 0, 0)
        ),
    ]
    in_names = ["q", "k_pages", "v_pages"]
    in_shapes = [(b, 1, h, d), (p_, h, page, d), (p_, h, page, d)]
    in_dtypes = [dtype, kv_dtype, kv_dtype]
    if has_scales:
        in_specs += [
            pl.BlockSpec(
                (1, h, page), lambda b, j, pt, ln: (pt[b, j], 0, 0)
            ),
            pl.BlockSpec(
                (1, h, page), lambda b, j, pt, ln: (pt[b, j], 0, 0)
            ),
        ]
        in_names += ["k_scale", "v_scale"]
        in_shapes += [(p_, h, page), (p_, h, page)]
        in_dtypes += [jnp.float32, jnp.float32]
    if has_rope:
        in_specs += [
            pl.BlockSpec((1, 1, d), lambda b, j, pt, ln: (b, 0, 0)),
            pl.BlockSpec((1, 1, d), lambda b, j, pt, ln: (b, 0, 0)),
        ]
        in_names += ["rope_cos", "rope_sin"]
        in_shapes += [(b, 1, d), (b, 1, d)]
        in_dtypes += [dtype, dtype]
    return dict(
        grid=(b, np_),
        in_specs=in_specs,
        in_names=in_names,
        in_shapes=in_shapes,
        in_dtypes=in_dtypes,
        out_specs=[pl.BlockSpec(
            (1, 1, h, d), lambda b, j, pt, ln: (b, 0, 0, 0)
        )],
        out_names=["o"],
        out_shape=[jax.ShapeDtypeStruct((b, 1, h, d), dtype)],
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
        ],
        dimension_semantics=("parallel", "arbitrary"),
    )


def kernel_specs(
    b, h, d, *, pool_pages, page, pages_per_seq, dtype=jnp.bfloat16,
    kv_wire="f32", rope=True, page_table=None,
):
    """Export the paged-decode kernel's :class:`introspect.KernelSpec`
    without compiling.  The page-table indirection is resolved against
    ``page_table`` (B, pages_per_seq) when given, else a synthetic
    round-robin table over ``pool_pages`` — either way the index maps
    under analysis are the REAL scalar-prefetch maps, evaluated on a
    concrete table (the coverage pass proves every referenced page id
    stays inside the pool)."""
    import numpy as np

    dtype = jnp.dtype(dtype)
    kv_dtype = jnp.dtype(jnp.int8 if kv_wire == "int8" else dtype)
    if page_table is None:
        page_table = (
            np.arange(b * pages_per_seq).reshape(b, pages_per_seq)
            % max(pool_pages - 1, 1)
        ) + 1  # skip the reserved null page 0, like live allocations
    page_table = np.asarray(page_table)
    lengths = np.full((b,), pages_per_seq * page, np.int32)
    plan = _decode_plan(
        b, h, d, pool_pages, page, pages_per_seq, dtype, kv_dtype,
        has_scales=kv_wire == "int8", has_rope=rope,
    )
    # close the scalar-prefetch operands over the concrete table so the
    # analyzer can call maps with grid indices alone
    for key in ("in_specs", "out_specs"):
        plan[key] = [
            pl.BlockSpec(
                spec.block_shape,
                (lambda m: lambda b, j: m(b, j, page_table, lengths))(
                    spec.index_map
                ),
            )
            for spec in plan[key]
        ]
    spec = introspect.from_plan(
        "paged_decode_fwd",
        plan,
        # head-batched q.K and p.V mat-vecs on the VPU
        flops_per_cell=4.0 * h * page * d,
        intermediates=(((h, page), jnp.float32), ((h, page), jnp.float32)),
    )
    # no matmul_dims meta: the score/PV contractions here are
    # head-batched MAT-VECS on the VPU (module docstring) — the MXU
    # 128-alignment lint does not apply, decode is HBM-bound by design
    return [spec]


def _decode_kernel(
    pt_ref, len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, cos_ref, sin_ref,
    o_ref, acc_ref, m_ref, l_ref,
    *, scale, page, np_, rope,
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = len_ref[b]
    # dead page: every position in it is >= length (idle slots have
    # length 0 — ALL their pages are dead and the output is zeros)
    live = j * page < length

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # (H, D)
        if rope:
            cos = cos_ref[0].astype(jnp.float32)  # (1, D)
            sin = sin_ref[0].astype(jnp.float32)
            q = q * cos + rotate_half(q) * sin
        k = k_ref[0].astype(jnp.float32)  # (H, page, D)
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:
            # blockwise int8 codes: one f32 scale per (head, token) row
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        # head-batched mat-vec on the VPU: s[h, t] = q[h, :] . k[h, t, :]
        s = jax.lax.dot_general(
            q[:, None, :], k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )[:, 0, :] * scale  # (H, page)
        pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * page
        s = jnp.where(pos < length, s, MASK_VALUE)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # (H, page)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # o[h, d] += p[h, :] . v[h, :, d]
        pv = jax.lax.dot_general(
            p[:, None, :], v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )[:, 0, :]  # (H, D)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == np_ - 1)
    def _finalize():
        l = l_ref[:, :1]
        # an idle slot (length 0) never accumulated: l == 0 there, and
        # the contract is zeros, not 0/0
        o = jnp.where(l > 0, acc_ref[...] / jnp.maximum(l, 1e-30), 0.0)
        o_ref[...] = o.astype(o_ref.dtype)[None, None]


def _decode_entry(*refs, has_scales, has_rope, **kw):
    pt_ref, len_ref, q_ref, k_ref, v_ref = refs[:5]
    i = 5
    ks_ref = vs_ref = cos_ref = sin_ref = None
    if has_scales:
        ks_ref, vs_ref = refs[i], refs[i + 1]
        i += 2
    if has_rope:
        cos_ref, sin_ref = refs[i], refs[i + 1]
        i += 2
    o_ref, acc_ref, m_ref, l_ref = refs[i:]
    _decode_kernel(
        pt_ref, len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
        cos_ref, sin_ref, o_ref, acc_ref, m_ref, l_ref, **kw
    )


@functools.partial(jax.jit, static_argnames=("scale",))
def paged_decode_fwd(
    q, k_pages, v_pages, page_table, lengths, *,
    scale, k_scale=None, v_scale=None, rope_cos=None, rope_sin=None,
):
    """Single-query attention over the paged KV cache.

    - ``q`` (B, H, D): the current token's (pre-RoPE) query rows;
    - ``k_pages`` / ``v_pages`` (P, H, page, D): the shared page pool —
      f32/bf16, or int8 codes when ``k_scale``/``v_scale`` (P, H, page)
      carry the blockwise f32 scales;
    - ``page_table`` (B, NP) int32: page ids per sequence in context
      order (entries beyond the live count may point anywhere — dead
      pages are skipped by ``lengths``);
    - ``lengths`` (B,) int32: live KV positions per sequence, INCLUDING
      the current token (whose k/v the caller appended before calling);
    - ``rope_cos`` / ``rope_sin`` (B, D): the rotation rows of each
      sequence's current position — fused onto ``q`` in-kernel.

    Returns (B, H, D) in ``q.dtype``; rows with ``lengths == 0`` are
    exactly zero.
    """
    b, h, d = q.shape
    p_, _, page, _ = k_pages.shape
    np_ = page_table.shape[1]
    has_scales = k_scale is not None
    has_rope = rope_cos is not None
    if has_scales != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")
    if has_rope != (rope_sin is not None):
        raise ValueError("rope_cos and rope_sin must be given together")

    # q as (B, 1, H, D) so its block carries an (H, D) tile per program
    plan = _decode_plan(
        b, h, d, p_, page, np_, q.dtype, k_pages.dtype,
        has_scales=has_scales, has_rope=has_rope,
    )
    args = [q[:, None], k_pages, v_pages]
    if has_scales:
        args += [k_scale, v_scale]
    if has_rope:
        args += [rope_cos[:, None], rope_sin[:, None]]

    kernel = functools.partial(
        _decode_entry, scale=scale, page=page, np_=np_,
        rope=has_rope, has_scales=has_scales, has_rope=has_rope,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=plan["grid"],
        in_specs=plan["in_specs"],
        out_specs=plan["out_specs"][0],
        scratch_shapes=plan["scratch_shapes"],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=plan["out_shape"][0],
        compiler_params=_CompilerParams(
            dimension_semantics=plan["dimension_semantics"],
        ),
        interpret=pallas_interpret(),
    )(
        jnp.asarray(page_table, jnp.int32),
        jnp.asarray(lengths, jnp.int32),
        *args,
    )
    return out[:, 0]
