"""Introspectable kernel specs — the static mirror of a ``pallas_call``.

The Pallas kernels in this package are opaque to the graph linter: by
the time ``apex_tpu.analysis`` sees a step, each kernel is one
``custom-call`` whose BlockSpecs, grid, and scratch shapes are gone.
This module is the export side of the fix (ISSUE 10): every kernel
module builds its ``pallas_call`` arguments through a *plan* function
of static parameters only, and wraps the same plan into a
:class:`KernelSpec` — so the analyzer
(:mod:`apex_tpu.analysis.kernels`) reasons about exactly the specs the
real call dispatches, without compiling anything.

A :class:`KernelSpec` carries:

- the grid and per-operand :class:`BlockArg` records (full array
  shape, block shape, the REAL index-map callable, dtype) for inputs
  and outputs;
- scratch and declared in-kernel intermediate buffers (shape, dtype)
  — intermediates are the register-allocated values a pass cannot see
  in any BlockSpec (e.g. the f32 score tile of flash attention), and
  the dominant VMEM term at large tiles;
- ``dimension_semantics`` (which grid axes are "parallel" vs the
  sequential "arbitrary" axes the kernels accumulate over) — what the
  race pass needs to tell a reduction revisit from a genuine
  double-write;
- ``flops_per_cell`` and optional causal-tile geometry for the
  dead-tile and roofline passes.

Everything here is plain data + stdlib dataclasses; jax is only
touched by the kernel modules that build the specs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

__all__ = ["BlockArg", "KernelSpec", "from_plan"]


@dataclasses.dataclass(frozen=True)
class BlockArg:
    """One blocked operand of a kernel: an input or output array.

    ``block`` / ``index_map`` are ``None`` for operands that bypass the
    block pipeline (SMEM scalars, scalar-prefetch arguments) — those
    contribute nothing to the VMEM block model and are skipped by the
    coverage pass.  ``index_map`` takes the grid indices (python ints
    work) and returns the BLOCK offsets, exactly the callable handed to
    ``pl.BlockSpec``.
    """

    name: str
    shape: Tuple[int, ...]
    block: Optional[Tuple[int, ...]]
    index_map: Optional[Callable]
    dtype: str
    memory_space: str = "vmem"  # "vmem" | "smem"

    def block_bytes(self) -> int:
        if self.block is None:
            return 0
        n = 1
        for d in self.block:
            n *= int(d)
        return n * dtype_width(self.dtype)


@dataclasses.dataclass
class KernelSpec:
    """The analyzable shape of one ``pallas_call``.

    ``scratch`` and ``intermediates`` are ``((shape, dtype), ...)``
    tuples; ``causal`` (when set) is the tile geometry the dead-tile
    pass feeds to ``_causal_block_live``::

        {"q_axis": <grid axis of q blocks>, "k_axis": <grid axis of k
         blocks>, "bq": int, "bk": int, "offset": int,
         "include_fully_masked": bool}

    ``meta`` carries free-form facts passes key on (today:
    ``matmul_dims`` — the MXU contraction extents the tiling lint
    judges against the 128x128 systolic array).
    """

    name: str
    grid: Tuple[int, ...]
    inputs: Tuple[BlockArg, ...]
    outputs: Tuple[BlockArg, ...]
    scratch: Tuple[Tuple[Tuple[int, ...], str], ...] = ()
    dimension_semantics: Tuple[str, ...] = ()
    flops_per_cell: float = 0.0
    intermediates: Tuple[Tuple[Tuple[int, ...], str], ...] = ()
    causal: Optional[dict] = None
    meta: Dict = dataclasses.field(default_factory=dict)

    def cells(self) -> int:
        n = 1
        for g in self.grid:
            n *= int(g)
        return n

    def blocked(self) -> Tuple[BlockArg, ...]:
        return tuple(
            a for a in tuple(self.inputs) + tuple(self.outputs)
            if a.block is not None
        )


_WIDTHS = {
    # width-table KEY, not an f64 value in a traced path
    "float64": 8, "int64": 8, "uint64": 8,  # repo-lint: allow
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
    "bool": 1,
}


def dtype_width(dtype) -> int:
    """Bytes per element for a dtype or dtype-name string (stdlib-only
    so the analyzer never needs a live jax for arithmetic)."""
    name = getattr(dtype, "name", None) or str(dtype)
    try:
        return _WIDTHS[name]
    except KeyError:
        raise ValueError(f"unknown dtype {name!r} in a kernel spec")


def buffer_bytes(shape, dtype) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype_width(dtype)


def _dtype_name(dtype) -> str:
    import numpy as np

    return np.dtype(dtype).name


def from_plan(
    name: str,
    plan: dict,
    *,
    flops_per_cell: float = 0.0,
    intermediates=(),
    causal: Optional[dict] = None,
) -> KernelSpec:
    """Wrap a kernel module's *call plan* into a :class:`KernelSpec`.

    A plan is the dict the kernel modules build their ``pallas_call``
    from: ``grid``, ``in_specs``/``out_specs`` (``pl.BlockSpec``
    objects), ``in_names``/``in_shapes``/``in_dtypes``, ``out_names``,
    ``out_shape`` (``ShapeDtypeStruct``), ``scratch_shapes``
    (``pltpu.VMEM`` refs), and ``dimension_semantics``.  Sharing the
    plan between dispatch and export is the whole point: the analyzer
    sees the index maps the hardware runs.
    """

    def block_arg(arg_name, shape, spec, dtype):
        block = getattr(spec, "block_shape", None)
        if block is None:
            return BlockArg(
                name=arg_name, shape=tuple(int(x) for x in shape),
                block=None, index_map=None, dtype=_dtype_name(dtype),
                memory_space="smem",
            )
        return BlockArg(
            name=arg_name, shape=tuple(int(x) for x in shape),
            block=tuple(int(x) for x in block),
            index_map=spec.index_map, dtype=_dtype_name(dtype),
        )

    inputs = tuple(
        block_arg(n, s, spec, dt)
        for n, s, spec, dt in zip(
            plan["in_names"], plan["in_shapes"], plan["in_specs"],
            plan["in_dtypes"],
        )
    )
    outputs = tuple(
        block_arg(n, sd.shape, spec, sd.dtype)
        for n, sd, spec in zip(
            plan["out_names"], plan["out_shape"], plan["out_specs"]
        )
    )
    scratch = tuple(
        (tuple(int(x) for x in ref.shape), _dtype_name(ref.dtype))
        for ref in plan["scratch_shapes"]
    )
    return KernelSpec(
        name=name,
        grid=tuple(int(g) for g in plan["grid"]),
        inputs=inputs,
        outputs=outputs,
        scratch=scratch,
        dimension_semantics=tuple(plan["dimension_semantics"]),
        flops_per_cell=float(flops_per_cell),
        intermediates=tuple(
            (tuple(int(x) for x in shape), _dtype_name(dt))
            for shape, dt in intermediates
        ),
        causal=None if causal is None else dict(causal),
    )
