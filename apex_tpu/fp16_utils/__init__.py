"""Manual half-precision helpers — ≙ apex/fp16_utils.

``apex/fp16_utils/fp16util.py`` :: ``network_to_half``, ``BN_convert_float``,
``prep_param_lists``, ``master_params_to_model_params``,
``model_grads_to_master_grads``, ``tofp16`` and
``apex/fp16_utils/fp16_optimizer.py`` :: ``FP16_Optimizer`` (the pre-amp
manual API).  Functional pytree equivalents; ``FP16_Optimizer`` wraps an
optax transformation with master weights + a loss scaler.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu._tree_util import cast_floats, cast_like, to_f32
from apex_tpu.amp.scaler import DynamicLossScaler, StaticLossScaler, amp_update

__all__ = [
    "tofp16",
    "network_to_half",
    "BN_convert_float",
    "prep_param_lists",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "FP16_Optimizer",
]


def tofp16(tree, half_dtype=jnp.bfloat16):
    """Cast floating leaves to the half dtype (≙ tofp16 module cast)."""
    return cast_floats(tree, half_dtype)


def network_to_half(tree, half_dtype=jnp.bfloat16):
    """≙ network_to_half (BN params staying fp32 is the caller's layout
    choice here — normalization ops compute statistics in f32 regardless,
    see apex_tpu.ops)."""
    return tofp16(tree, half_dtype)


_BN_SCOPE_PREFIXES = ("batchnorm", "batch_norm", "syncbatchnorm", "bn")


def _is_bn_segment(seg: str, prefixes) -> bool:
    # anchored: the segment IS a BN scope name (optionally numbered,
    # flax-style "BatchNorm_0"/"bn_1"), never a substring hit like
    # "subnet" containing "bn"
    seg = seg.lower()
    return any(
        seg == p or seg.startswith(p + "_")
        for p in (q.lower() for q in prefixes)
    )


def BN_convert_float(tree, prefixes=_BN_SCOPE_PREFIXES):
    """≙ BN_convert_float: after a half cast, return BatchNorm parameters
    to fp32 for stable statistics.

    The torch original walks modules; the pytree analog upcasts every
    half-precision leaf that sits under a BatchNorm-named scope (a path
    segment equal to — or a numbered instance of — one of ``prefixes``;
    flax convention ``BatchNorm_0``/``bn_1``/``SyncBatchNorm_2``).  Other
    leaves untouched.
    """

    def convert(path, leaf):
        if not hasattr(leaf, "dtype") or leaf.dtype not in (
            jnp.float16, jnp.bfloat16
        ):
            return leaf
        segs = [
            str(getattr(p, "key", getattr(p, "name", p))) for p in path
        ]
        if any(_is_bn_segment(s, prefixes) for s in segs):
            return leaf.astype(jnp.float32)
        return leaf

    return jax.tree_util.tree_map_with_path(convert, tree)


def prep_param_lists(params) -> Tuple[Any, Any]:
    """≙ prep_param_lists: returns (model_params, fp32 master copies)."""
    return params, to_f32(params)


def master_params_to_model_params(model_params, master_params):
    """≙ master_params_to_model_params: cast masters into the model dtypes."""
    return cast_like(model_params, master_params)


def model_grads_to_master_grads(model_grads):
    """≙ model_grads_to_master_grads: grads to f32 for the master update."""
    return to_f32(model_grads)


class FP16_Optimizer:
    """≙ apex/fp16_utils/fp16_optimizer.py :: FP16_Optimizer.

    Wraps an optax transformation: holds fp32 masters + scaler state, and
    ``step`` runs unscale → overflow-skip → master update → model re-cast.

    >>> opt = FP16_Optimizer(fused_adam(1e-3), static_loss_scale=128.0)
    >>> state = opt.init(bf16_params)
    >>> params, state, overflow = opt.step(bf16_params, scaled_grads, state)
    """

    def __init__(
        self,
        tx: optax.GradientTransformation,
        static_loss_scale: float = 1.0,
        dynamic_loss_scale: bool = False,
        dynamic_loss_args: Optional[dict] = None,
    ):
        self.tx = tx
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            if static_loss_scale is None:
                raise ValueError(
                    "static_loss_scale must be a number; pass "
                    "dynamic_loss_scale=True for dynamic scaling"
                )
            self.loss_scaler = StaticLossScaler(float(static_loss_scale))

    def init(self, model_params):
        _, master = prep_param_lists(model_params)
        return {
            "master": master,
            "opt": self.tx.init(master),
            "scaler": self.loss_scaler.init(),
        }

    def scale_loss(self, loss, state):
        return self.loss_scaler.scale(loss, state["scaler"])

    def loss_scale(self, state):
        """Current numeric loss scale (≙ the reference's ``loss_scale``
        property; functional, so it reads the threaded state)."""
        return state["scaler"].loss_scale

    def step(self, model_params, scaled_grads, state):
        master, new_opt, new_scaler, found_inf = amp_update(
            self.tx,
            self.loss_scaler,
            scaled_grads,
            state["opt"],
            state["master"],
            state["scaler"],
        )
        new_model = master_params_to_model_params(model_params, master)
        return (
            new_model,
            {"master": master, "opt": new_opt, "scaler": new_scaler},
            found_inf,
        )

    # ≙ FP16_Optimizer.state_dict / load_state_dict
    def state_dict(self, state):
        return state

    def load_state_dict(self, _state, sd):
        return sd
