"""multi_tensor_apply — launch-amortization shim, TPU-native.

≙ ``apex/multi_tensor_apply/multi_tensor_apply.py`` :: ``MultiTensorApply``
and the global ``multi_tensor_applier`` instance, plus the ``apex_C``
flatten/unflatten pair (``csrc/flatten_unflatten.cpp``).

On GPU the point of ``multi_tensor_apply<depth>`` (csrc/multi_tensor_apply.cuh)
is to pack pointers of many tensors into one kernel launch.  Under ``jit``
a whole-pytree update already compiles to one XLA program, so the launch
count is O(1) by construction; this module keeps the *interface* so code
written against the reference's applier ports mechanically:

    multi_tensor_applier(op, noop_flag_unused, tensor_lists, *args)

``op`` here is any callable taking ``(*tensor_lists, *args)`` and returning
updated lists; the ``chunk_size`` / overflow-buffer machinery is accepted and
ignored (overflow detection lives in
:func:`apex_tpu.optimizers.multi_tensor.scale_with_overflow_check`).
"""

from __future__ import annotations

from typing import Any, List, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.multi_tensor import (  # noqa: F401  (re-export)
    axpby,
    global_norm,
    per_tensor_norm,
    scale_with_overflow_check,
)

__all__ = [
    "MultiTensorApply",
    "multi_tensor_applier",
    "flatten",
    "unflatten",
    "global_norm",
    "per_tensor_norm",
    "scale_with_overflow_check",
    "axpby",
]


class MultiTensorApply:
    """Callable shim ≙ MultiTensorApply.

    ``chunk_size`` is stored for API parity only — XLA tiles loops itself.
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists: Sequence[List[Any]], *args):
        return op(*tensor_lists, *args)


multi_tensor_applier = MultiTensorApply()


def flatten(tensors: Sequence[jax.Array]) -> jax.Array:
    """Concatenate a tensor list into one flat 1-D buffer.

    ≙ ``apex_C.flatten`` (csrc/flatten_unflatten.cpp) — the DDP flat-bucket
    primitive.  All inputs must share a dtype (as torch's
    ``flatten_dense_tensors`` requires).
    """
    if not tensors:
        return jnp.zeros((0,), jnp.float32)
    dtypes = {jnp.dtype(t.dtype) for t in tensors}
    if len(dtypes) != 1:
        raise ValueError(f"flatten requires a uniform dtype, got {sorted(map(str, dtypes))}")
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten(flat: jax.Array, like: Sequence[jax.Array]) -> List[jax.Array]:
    """Split a flat buffer back into views shaped like ``like``.

    ≙ ``apex_C.unflatten``.
    """
    sizes = [int(t.size) for t in like]
    total = sum(sizes)
    if flat.size != total:
        raise ValueError(f"flat buffer has {flat.size} elements, need {total}")
    out = []
    offset = 0
    for t, n in zip(like, sizes):
        out.append(jax.lax.dynamic_slice_in_dim(flat, offset, n, 0).reshape(t.shape))
        offset += n
    return out
