"""Checkpoint / resume subsystem.

≙ SURVEY §5 "Checkpoint / resume": the reference ships *pieces* —
``amp.state_dict()`` (loss-scaler state, ``apex/amp/frontend.py``),
``FP16_Optimizer.state_dict`` (master weights), torch optimizer
``state_dict``, and ``CudaRNGStatesTracker.get_states/set_states`` — and
leaves model/optimizer persistence to the caller (Megatron/NeMo).

The TPU-native design goes one step further and provides the engine too,
because on TPU the natural checkpoint unit is the *sharded jax.Array*:
orbax writes each shard from the host that owns it (multi-host safe,
async-capable), and restore re-shards to whatever mesh the template
carries — which is exactly what a (dp, pp, cp, tp) training state needs
and what no torch ``state_dict`` file can express.

Surface:

- :func:`save_checkpoint` / :func:`restore_checkpoint` — one-shot pytree
  save/restore (sharding-preserving; restore takes an optional template).
- :class:`CheckpointManager` — step-numbered checkpoints with
  ``max_to_keep`` / ``save_interval_steps`` retention and async save.
- :func:`snapshot_training_state` / :func:`restore_training_state` —
  bundle params + opt_state + amp scaler state + the per-mode RNG tracker
  (the four things the reference's pieces cover) into one tree.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
    "all_steps",
    "restore_step_dir",
    "CheckpointManager",
    "snapshot_training_state",
    "restore_training_state",
]


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _abspath(path) -> str:
    return os.path.abspath(os.fspath(path))


def _saveable(state):
    """Normalize leaves orbax's standard handler refuses: numpy SCALARS
    (``np.int64(7)`` — ``np.generic``, not ``np.ndarray``) become 0-d
    arrays.  They restore as 0-d ``np.ndarray`` — same value, and
    ``int()``/``np.asarray()`` consumers are unchanged."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, np.generic) else x, state
    )


# ---------------------------------------------------------------------------
# one-shot save / restore
# ---------------------------------------------------------------------------


def save_checkpoint(path, state, *, force: bool = False) -> None:
    """Write ``state`` (any pytree of arrays/scalars) to ``path``.

    Sharded ``jax.Array`` leaves are written distributed (each host writes
    the shards it owns); replicated leaves are written once.  ``force``
    overwrites an existing checkpoint at ``path``.
    """
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(_abspath(path), _saveable(state), force=force)


def restore_checkpoint(path, template: Optional[Any] = None):
    """Restore the pytree at ``path``.

    With ``template`` (a pytree of ``jax.ShapeDtypeStruct`` — with
    ``sharding`` set for sharded restore — or concrete arrays whose
    shape/dtype/sharding are used the same way), leaves come back on
    device with the template's shardings.  Without, leaves restore as
    host numpy arrays.
    """
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        if template is None:
            return ckptr.restore(_abspath(path))
        return ckptr.restore(_abspath(path), template)


def _manager_options(max_to_keep, save_interval_steps):
    ocp = _ocp()
    return ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        save_interval_steps=save_interval_steps,
        enable_async_checkpointing=True,
        create=True,
    )


def latest_step(directory) -> Optional[int]:
    """Newest COMPLETE step number under ``directory`` (None if
    absent/empty).

    Read-only and cheap: a plain directory scan — no manager is
    constructed, and a missing directory is NOT created (a typo'd resume
    path should look empty, not leave stray directories behind).
    """
    steps = all_steps(directory)
    return steps[-1] if steps else None


#: files whose presence at the top of a step directory proves the save
#: COMMITTED: orbax writes them inside the staging dir and the atomic
#: rename publishes them with everything else (``commit_success.txt``
#: is the marker orbax uses on filesystems without atomic rename).
_COMMIT_MARKERS = ("_CHECKPOINT_METADATA", "_METADATA", "commit_success.txt")


def _is_complete_step_dir(path: str) -> bool:
    """A step directory counts only with a commit marker on board.

    Orbax's own enumeration accepts ANY digit-named directory — which
    resurrects half-written steps after a crash that got as far as
    creating the directory (a non-atomic filesystem, a torn non-orbax
    write, debris renamed by hand).  Restoring such a step fails at
    best and silently loads garbage at worst; it must be invisible so
    resume falls back to the previous complete step.
    """
    if any(os.path.exists(os.path.join(path, m)) for m in _COMMIT_MARKERS):
        return True
    # manager layouts written by older orbax versions carry the marker
    # only inside the `default/` item dir, with nothing at step level —
    # a valid pre-existing checkpoint must not become invisible (resume
    # silently restarting from step 0 would overwrite prior progress)
    return any(
        os.path.exists(os.path.join(path, "default", m))
        for m in _COMMIT_MARKERS
    )


def all_steps(directory):
    """COMPLETE step numbers under ``directory`` (read-only; [] if
    absent).  Uncommitted debris (``*.orbax-checkpoint-tmp-*``) and
    half-written step dirs without a commit marker are ignored — the
    crash-consistency contract resume relies on."""
    directory = _abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(
        s
        for s in _ocp().utils.checkpoint_steps(directory)
        if _is_complete_step_dir(os.path.join(directory, str(s)))
    )


def restore_step_dir(directory, step: int, *, template=None):
    """Restore step ``step`` of ``directory``, layout-agnostic.

    Handles both on-disk shapes a step-numbered checkpoint tree can
    carry: the ``CheckpointManager`` layout (``<step>/default/...``)
    and the flat :class:`~apex_tpu.goodput.AsyncCheckpointEngine` /
    ``StandardCheckpointer`` layout (``<step>/...``) — so a run can
    switch engines between restarts and every reader (the serve
    example's train→serve handoff, ``run_resilient`` auto-resume)
    restores through ONE code path.
    """
    base = os.path.join(_abspath(directory), str(int(step)))
    if not _is_complete_step_dir(base):
        raise FileNotFoundError(
            f"step {step} under {directory} is missing or incomplete "
            "(no commit marker — a half-written checkpoint)"
        )
    # Disambiguate by where orbax put the item-level _METADATA: the
    # flat StandardCheckpointer layout carries it at the top of the
    # step dir, the manager layout only inside its `default/` item
    # dir.  Checking the marker (not just isdir) keeps a FLAT
    # checkpoint whose state tree has a top-level "default" key from
    # being misread as the nested layout.
    nested = os.path.join(base, "default")
    if os.path.exists(os.path.join(base, "_METADATA")):
        path = base
    elif os.path.isdir(nested):
        path = nested
    else:
        path = base
    return restore_checkpoint(path, template)


class CheckpointManager:
    """Step-numbered checkpoints with retention + async save.

    A thin, context-managed wrapper over ``orbax.CheckpointManager``:

    >>> with CheckpointManager(dir, max_to_keep=3, save_interval_steps=100) as mgr:
    ...     for step in range(n):
    ...         ...
    ...         mgr.save(step, state)          # async; respects interval
    ...     mgr.wait_until_finished()
    ...     state = mgr.restore(mgr.latest_step(), template=state)
    """

    def __init__(
        self,
        directory,
        *,
        max_to_keep: Optional[int] = None,
        save_interval_steps: int = 1,
    ):
        ocp = self._ocp = _ocp()
        self._mgr = ocp.CheckpointManager(
            _abspath(directory),
            options=_manager_options(max_to_keep, save_interval_steps),
        )

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self._mgr.close()

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    # -- io ----------------------------------------------------------------
    def save(self, step: int, state, *, force: bool = False) -> bool:
        """Queue an async save of ``state`` at ``step``.

        Returns False when skipped by ``save_interval_steps`` (≙ the
        caller-side ``if step % interval`` the reference leaves to users).
        """
        return self._mgr.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )

    def restore(self, step: Optional[int] = None, *, template=None):
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self._mgr.directory}"
                )
        # layout-agnostic: also restores flat step dirs written by the
        # async engine (a run may switch engines between restarts)
        return restore_step_dir(
            self._mgr.directory, step, template=template
        )

    def latest_step(self) -> Optional[int]:
        # the hardened module scan, not orbax's: half-written step
        # dirs (digit-named, no commit marker) must stay invisible
        return latest_step(self._mgr.directory)

    def all_steps(self):
        return all_steps(self._mgr.directory)

    def should_save(self, step: int) -> bool:
        return self._mgr.should_save(step)


# ---------------------------------------------------------------------------
# full-training-state bundling (the reference's four state_dict pieces)
# ---------------------------------------------------------------------------


def snapshot_training_state(
    params,
    opt_state=None,
    *,
    step: Optional[int] = None,
    amp_handle=None,
    amp_state=None,
    stream=None,
    extra=None,
):
    """Bundle everything needed to resume into one checkpointable tree.

    - ``params`` / ``opt_state``: the model + optimizer trees (sharded ok).
    - ``amp_handle``+``amp_state``: included via ``handle.state_dict`` ≙
      ``amp.state_dict()`` (loss scale, growth tracker, hysteresis).
      The amp *master weights* live inside ``amp_state.master_params``;
      pass that tree (or the whole AmpState) as ``extra`` if used.
    - RNG: the per-mode tracker keys (≙ ``CudaRNGStatesTracker.get_states``)
      are captured automatically.
    - ``stream``: the input-pipeline cursor
      (:meth:`apex_tpu.goodput.ResumableStream.state` /
      :func:`apex_tpu.goodput.stream_state`) — saved under
      ``"stream"`` so every checkpoint pins the exact sample sequence;
      validate it on resume with
      :func:`apex_tpu.goodput.verify_stream_state` (it lands in the
      restored dict, not the :func:`restore_training_state` tuple).
    """
    from apex_tpu.transformer.tensor_parallel.random import (
        get_tpu_rng_tracker,
    )

    state = {"params": params}
    if opt_state is not None:
        state["opt_state"] = opt_state
    if step is not None:
        state["step"] = np.asarray(step, np.int64)
    if amp_handle is not None and amp_state is not None:
        state["amp"] = amp_handle.state_dict(amp_state)
    rng = get_tpu_rng_tracker().get_states()
    if rng:
        state["rng"] = rng
    if stream is not None:
        state["stream"] = stream
    if extra is not None:
        state["extra"] = extra
    return state


def restore_training_state(
    restored: dict,
    *,
    amp_handle=None,
    amp_state=None,
):
    """Unpack a :func:`snapshot_training_state` tree after restore.

    Re-seats the RNG tracker streams and (optionally) the amp scaler
    state; returns ``(params, opt_state, step, amp_state, extra)`` with
    None for absent pieces.
    """
    from apex_tpu.transformer.tensor_parallel.random import (
        get_tpu_rng_tracker,
    )

    if "rng" in restored:
        get_tpu_rng_tracker().set_states(
            {k: jax.numpy.asarray(v) for k, v in restored["rng"].items()}
        )
    new_amp_state = None
    if amp_handle is not None and amp_state is not None and "amp" in restored:
        new_amp_state = amp_handle.load_state_dict(amp_state, restored["amp"])
    step = restored.get("step")
    return (
        restored.get("params"),
        restored.get("opt_state"),
        int(step) if step is not None else None,
        new_amp_state,
        restored.get("extra"),
    )
