"""Checkpoint / resume subsystem.

≙ SURVEY §5 "Checkpoint / resume": the reference ships *pieces* —
``amp.state_dict()`` (loss-scaler state, ``apex/amp/frontend.py``),
``FP16_Optimizer.state_dict`` (master weights), torch optimizer
``state_dict``, and ``CudaRNGStatesTracker.get_states/set_states`` — and
leaves model/optimizer persistence to the caller (Megatron/NeMo).

The TPU-native design goes one step further and provides the engine too,
because on TPU the natural checkpoint unit is the *sharded jax.Array*:
orbax writes each shard from the host that owns it (multi-host safe,
async-capable), and restore re-shards to whatever mesh the template
carries — which is exactly what a (dp, pp, cp, tp) training state needs
and what no torch ``state_dict`` file can express.

Surface:

- :func:`save_checkpoint` / :func:`restore_checkpoint` — one-shot pytree
  save/restore (sharding-preserving; restore takes an optional template).
- :class:`CheckpointManager` — step-numbered checkpoints with
  ``max_to_keep`` / ``save_interval_steps`` retention and async save.
- :func:`snapshot_training_state` / :func:`restore_training_state` —
  bundle params + opt_state + amp scaler state + the per-mode RNG tracker
  (the four things the reference's pieces cover) into one tree.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
    "all_steps",
    "CheckpointManager",
    "snapshot_training_state",
    "restore_training_state",
]


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _abspath(path) -> str:
    return os.path.abspath(os.fspath(path))


# ---------------------------------------------------------------------------
# one-shot save / restore
# ---------------------------------------------------------------------------


def save_checkpoint(path, state, *, force: bool = False) -> None:
    """Write ``state`` (any pytree of arrays/scalars) to ``path``.

    Sharded ``jax.Array`` leaves are written distributed (each host writes
    the shards it owns); replicated leaves are written once.  ``force``
    overwrites an existing checkpoint at ``path``.
    """
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(_abspath(path), state, force=force)


def restore_checkpoint(path, template: Optional[Any] = None):
    """Restore the pytree at ``path``.

    With ``template`` (a pytree of ``jax.ShapeDtypeStruct`` — with
    ``sharding`` set for sharded restore — or concrete arrays whose
    shape/dtype/sharding are used the same way), leaves come back on
    device with the template's shardings.  Without, leaves restore as
    host numpy arrays.
    """
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        if template is None:
            return ckptr.restore(_abspath(path))
        return ckptr.restore(_abspath(path), template)


def _manager_options(max_to_keep, save_interval_steps):
    ocp = _ocp()
    return ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        save_interval_steps=save_interval_steps,
        enable_async_checkpointing=True,
        create=True,
    )


def latest_step(directory) -> Optional[int]:
    """Newest step number under ``directory`` (None if absent/empty).

    Read-only and cheap: a plain directory scan — no manager is
    constructed, and a missing directory is NOT created (a typo'd resume
    path should look empty, not leave stray directories behind).
    """
    steps = all_steps(directory)
    return steps[-1] if steps else None


def all_steps(directory):
    """Step numbers under ``directory`` (read-only; [] if absent)."""
    if not os.path.isdir(_abspath(directory)):
        return []
    return sorted(_ocp().utils.checkpoint_steps(_abspath(directory)))


class CheckpointManager:
    """Step-numbered checkpoints with retention + async save.

    A thin, context-managed wrapper over ``orbax.CheckpointManager``:

    >>> with CheckpointManager(dir, max_to_keep=3, save_interval_steps=100) as mgr:
    ...     for step in range(n):
    ...         ...
    ...         mgr.save(step, state)          # async; respects interval
    ...     mgr.wait_until_finished()
    ...     state = mgr.restore(mgr.latest_step(), template=state)
    """

    def __init__(
        self,
        directory,
        *,
        max_to_keep: Optional[int] = None,
        save_interval_steps: int = 1,
    ):
        ocp = self._ocp = _ocp()
        self._mgr = ocp.CheckpointManager(
            _abspath(directory),
            options=_manager_options(max_to_keep, save_interval_steps),
        )

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self._mgr.close()

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    # -- io ----------------------------------------------------------------
    def save(self, step: int, state, *, force: bool = False) -> bool:
        """Queue an async save of ``state`` at ``step``.

        Returns False when skipped by ``save_interval_steps`` (≙ the
        caller-side ``if step % interval`` the reference leaves to users).
        """
        return self._mgr.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )

    def restore(self, step: Optional[int] = None, *, template=None):
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self._mgr.directory}"
                )
        args = (
            self._ocp.args.StandardRestore(template)
            if template is not None
            else None
        )
        return self._mgr.restore(step, args=args)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def should_save(self, step: int) -> bool:
        return self._mgr.should_save(step)


# ---------------------------------------------------------------------------
# full-training-state bundling (the reference's four state_dict pieces)
# ---------------------------------------------------------------------------


def snapshot_training_state(
    params,
    opt_state=None,
    *,
    step: Optional[int] = None,
    amp_handle=None,
    amp_state=None,
    extra=None,
):
    """Bundle everything needed to resume into one checkpointable tree.

    - ``params`` / ``opt_state``: the model + optimizer trees (sharded ok).
    - ``amp_handle``+``amp_state``: included via ``handle.state_dict`` ≙
      ``amp.state_dict()`` (loss scale, growth tracker, hysteresis).
      The amp *master weights* live inside ``amp_state.master_params``;
      pass that tree (or the whole AmpState) as ``extra`` if used.
    - RNG: the per-mode tracker keys (≙ ``CudaRNGStatesTracker.get_states``)
      are captured automatically.
    """
    from apex_tpu.transformer.tensor_parallel.random import (
        get_tpu_rng_tracker,
    )

    state = {"params": params}
    if opt_state is not None:
        state["opt_state"] = opt_state
    if step is not None:
        state["step"] = np.asarray(step, np.int64)
    if amp_handle is not None and amp_state is not None:
        state["amp"] = amp_handle.state_dict(amp_state)
    rng = get_tpu_rng_tracker().get_states()
    if rng:
        state["rng"] = rng
    if extra is not None:
        state["extra"] = extra
    return state


def restore_training_state(
    restored: dict,
    *,
    amp_handle=None,
    amp_state=None,
):
    """Unpack a :func:`snapshot_training_state` tree after restore.

    Re-seats the RNG tracker streams and (optionally) the amp scaler
    state; returns ``(params, opt_state, step, amp_state, extra)`` with
    None for absent pieces.
    """
    from apex_tpu.transformer.tensor_parallel.random import (
        get_tpu_rng_tracker,
    )

    if "rng" in restored:
        get_tpu_rng_tracker().set_states(
            {k: jax.numpy.asarray(v) for k, v in restored["rng"].items()}
        )
    new_amp_state = None
    if amp_handle is not None and amp_state is not None and "amp" in restored:
        new_amp_state = amp_handle.load_state_dict(amp_state, restored["amp"])
    step = restored.get("step")
    return (
        restored.get("params"),
        restored.get("opt_state"),
        int(step) if step is not None else None,
        new_amp_state,
        restored.get("extra"),
    )
