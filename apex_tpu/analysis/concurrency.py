"""Lock-discipline pass — static race/deadlock lint for threaded classes.

Three subsystems run real threads (the ``OpsServer`` scrape handlers,
the ``AsyncCheckpointEngine`` writer, the ``DevicePrefetcher`` worker)
and every one of them shares plain attributes with the main path.  The
GIL makes single bytecodes atomic and nothing else: ``self.n += 1``
from two threads loses increments, and a multi-field update observed
half-done is a torn read.  This pass proves lock discipline at the
source, per class:

1. **thread entrypoints** — ``threading.Thread(target=self._m)``
   targets, plus ``http.server``-style nested handler classes calling
   methods through a ``name = self`` alias (the ``OpsServer.start``
   shape) mark methods as thread bodies;
2. **a lightweight call graph** — ``self.m()`` edges close thread- and
   main-reachability over the class (main entry points are the public
   and dunder methods; ``__init__`` is construction, before the object
   is shared, and never counts as a mutation site);
3. **attribute census** — every ``self.x`` read/write per method, with
   ``with self._lock:`` nesting tracked (any attribute constructed as
   ``threading.Lock/RLock/Condition`` or ``TrackedLock`` counts, as
   does any ``self.*lock*`` name), read-modify-write shape
   (``+=`` / ``x = x op ...``) noted, and simple local aliases
   (``st = self._stats; st[k] += 1``) resolved back to the attribute.

An attribute reachable from both a thread body and the main path with
an unlocked write (outside ``__init__``) is ``race-unlocked-shared-
state``; when every offending write is a read-modify-write it is the
sharper ``race-nonatomic-counter``.  A ``with self.<lock>:`` region in
a main-path method that calls a blocking hand-off (``.put()`` /
``.join()`` / ``.result()``) while some thread body acquires the same
lock is the two-party deadlock shape, ``race-lock-across-blocking``.

Only classes that actually start threads are judged — a single-
threaded class mutating its own attributes is not a finding.  Waive an
audited site with ``# lint: allow(<rule-id>): <reason>`` on the line
of the flagged write (same syntax as the purity pass).

Runtime counterpart: :class:`apex_tpu.observability.TrackedLock`
(``APEX_TPU_LOCKSAN=1``) validates dynamically — lock-order cycles
across these same locks — what this pass claims statically.  Docs:
``docs/analysis.md`` "Concurrency & replay-purity passes".

Module level is stdlib-only with lazy findings imports, so
``tools/concurrency_lint.py`` can run it without importing jax.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LOCK_CTORS",
    "BLOCKING_CALLS",
    "analyze_class",
    "lint_source",
    "lint_sources",
    "concurrency_pass",
]

#: constructor names whose assignment marks an attribute as a lock
LOCK_CTORS = {
    "Lock", "RLock", "Condition",
    "threading.Lock", "threading.RLock", "threading.Condition",
    "TrackedLock",
}

#: method names whose call is a blocking hand-off when made under a
#: held lock (bounded-queue put, queue/thread join, future result)
BLOCKING_CALLS = {"put", "join", "result"}

from apex_tpu.analysis.purity import WAIVER_RE, _dotted  # noqa: E402
# (purity is stdlib-only at module level, so this import stays jax-free
# for the standalone tools/concurrency_lint.py loader)


def _lazy_finding(rule: str, rel: str, lineno: int, message: str):
    from apex_tpu.analysis.findings import make_finding

    return make_finding(rule, f"apex_tpu/{rel}:{lineno}", message)


@dataclasses.dataclass
class _Write:
    attr: str
    lineno: int
    locked: bool
    rmw: bool


@dataclasses.dataclass
class _Method:
    name: str
    writes: List[_Write] = dataclasses.field(default_factory=list)
    reads: Set[str] = dataclasses.field(default_factory=set)
    calls: Set[str] = dataclasses.field(default_factory=set)
    #: lock attrs this method acquires (with-block or .acquire())
    locks_used: Set[str] = dataclasses.field(default_factory=set)
    #: (lock attr, call text, lineno) — blocking calls under a lock
    blocking_under_lock: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list
    )
    thread_entry: bool = False


def _call_name(node: ast.Call) -> Optional[str]:
    return _dotted(node.func)


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = _call_name(value)
    return name is not None and (
        name in LOCK_CTORS or name.split(".")[-1] in LOCK_CTORS
    )


class _MethodVisitor(ast.NodeVisitor):
    """One method's attribute census, with lock nesting and aliasing."""

    def __init__(self, cls: "_ClassModel", method: _Method):
        self.cls = cls
        self.m = method
        self.lock_depth: List[str] = []  # stack of held lock attrs
        #: local name -> attr it aliases (``st = self._stats``)
        self.aliases: Dict[str, str] = {}
        #: local names bound to ``self`` (``ops = self``) — the
        #: http.server nested-handler discovery hook
        self.self_aliases: Set[str] = {"self"}

    # -- lock nesting ------------------------------------------------------
    def _lock_attr_of(self, expr: ast.AST) -> Optional[str]:
        name = _dotted(expr)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) != 2 or parts[0] not in self.self_aliases:
            return None
        attr = parts[1]
        if attr in self.cls.lock_attrs or "lock" in attr.lower():
            return attr
        return None

    def visit_With(self, node):
        held = []
        for item in node.items:
            attr = self._lock_attr_of(item.context_expr)
            if attr is not None:
                held.append(attr)
                self.m.locks_used.add(attr)
        self.lock_depth.extend(held)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held:
            self.lock_depth.pop()

    visit_AsyncWith = visit_With

    # -- writes/reads ------------------------------------------------------
    def _self_attr(
        self, node: ast.AST, for_write: bool = False,
    ) -> Optional[str]:
        """``self.x`` (or through a self-alias / a recorded local
        alias) -> attribute name, else None.  Subscripts resolve to
        their base (``self.x[k]`` mutates ``x``).  For writes, a bare
        local name never counts (rebinding ``st`` is not a write to
        ``self._stats``) — only subscripted aliases mutate through."""
        subscripted = False
        while isinstance(node, ast.Subscript):
            subscripted = True
            node = node.value
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ) and node.value.id in self.self_aliases:
            return node.attr
        if isinstance(node, ast.Name):
            if for_write and not subscripted:
                return None
            return self.aliases.get(node.id)
        return None

    def _record_write(self, target: ast.AST, lineno: int, rmw: bool):
        attr = self._self_attr(target, for_write=True)
        if attr is None:
            return
        self.m.writes.append(_Write(
            attr=attr, lineno=lineno, locked=bool(self.lock_depth),
            rmw=rmw,
        ))

    def visit_Assign(self, node):
        # alias tracking first: ``st = self._stats`` / ``ops = self``
        if len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            local = node.targets[0].id
            if isinstance(node.value, ast.Name) and \
                    node.value.id in self.self_aliases:
                self.self_aliases.add(local)
            else:
                src_attr = self._self_attr(node.value) if isinstance(
                    node.value, ast.Attribute
                ) else None
                if src_attr is not None:
                    self.aliases[local] = src_attr
                else:
                    self.aliases.pop(local, None)
        for tgt in node.targets:
            attr = self._self_attr(tgt, for_write=True)
            if attr is None:
                continue
            # x = self.x + 1 is a read-modify-write in assign clothing
            reads_self = any(
                self._self_attr(n) == attr
                for n in ast.walk(node.value)
                if isinstance(n, (ast.Attribute, ast.Subscript))
            )
            self._record_write(tgt, node.lineno, rmw=reads_self)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record_write(node.target, node.lineno, rmw=True)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and \
                node.value.id in self.self_aliases:
            self.m.reads.add(node.attr)
        self.generic_visit(node)

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node):
        name = _call_name(node)
        if name is not None:
            parts = name.split(".")
            if len(parts) == 2 and parts[0] in self.self_aliases:
                self.m.calls.add(parts[1])
            # thread entry: threading.Thread(target=self._m)
            if parts[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        tname = _dotted(kw.value)
                        tparts = (tname or "").split(".")
                        if len(tparts) == 2 and \
                                tparts[0] in self.self_aliases:
                            self.cls.thread_targets.add(tparts[1])
            # self.<lock>.acquire() counts as using the lock
            if parts[-1] == "acquire" and len(parts) == 3 and \
                    parts[0] in self.self_aliases:
                lk = parts[1]
                if lk in self.cls.lock_attrs or "lock" in lk.lower():
                    self.m.locks_used.add(lk)
            # blocking hand-off under a held lock
            if parts[-1] in BLOCKING_CALLS and self.lock_depth:
                self.m.blocking_under_lock.append(
                    (self.lock_depth[-1], name, node.lineno)
                )
        self.generic_visit(node)

    # -- nested defs/classes -----------------------------------------------
    def visit_FunctionDef(self, node):
        # a closure inside the method: same thread context, keep
        # walking (e.g. a helper defined in save())
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        # the http.server shape: a handler class nested in a method,
        # whose methods run on SERVER threads and reach back through a
        # ``name = self`` alias — every ``alias.m()`` call inside it
        # marks ``m`` as a thread entrypoint
        outer_aliases = self.self_aliases - {"self"}
        if not outer_aliases:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _call_name(sub)
                if name is None:
                    continue
                parts = name.split(".")
                if len(parts) == 2 and parts[0] in outer_aliases:
                    self.cls.thread_targets.add(parts[1])


class _ClassModel:
    def __init__(self, node: ast.ClassDef, rel: str, lines: List[str]):
        self.name = node.name
        self.rel = rel
        self.lines = lines
        self.lock_attrs: Set[str] = set()
        self.thread_targets: Set[str] = set()
        self.methods: Dict[str, _Method] = {}
        self._node = node

    def build(self) -> "_ClassModel":
        # pass 1: lock attributes (any method may create one)
        for sub in ast.walk(self._node):
            if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                for tgt in sub.targets:
                    name = _dotted(tgt)
                    if name and name.startswith("self."):
                        self.lock_attrs.add(name.split(".", 1)[1])
        # pass 2: per-method census (also discovers thread targets)
        for stmt in self._node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m = _Method(name=stmt.name)
                self.methods[stmt.name] = m
                _MethodVisitor(self, m).visit(stmt)
        for tname in self.thread_targets:
            if tname in self.methods:
                self.methods[tname].thread_entry = True
        return self

    # -- reachability ------------------------------------------------------
    def _closure(self, seeds: Set[str]) -> Set[str]:
        out, frontier = set(seeds), list(seeds)
        while frontier:
            m = self.methods.get(frontier.pop())
            if m is None:
                continue
            for callee in m.calls:
                if callee in self.methods and callee not in out:
                    out.add(callee)
                    frontier.append(callee)
        return out

    def thread_reachable(self) -> Set[str]:
        return self._closure({
            n for n, m in self.methods.items() if m.thread_entry
        })

    def main_reachable(self) -> Set[str]:
        # main entry points: public methods and dunders (the API the
        # constructing thread calls); private helpers join via the
        # call-graph closure.  __init__ runs before the object is
        # shared, so its writes never count — but it IS main path for
        # reachability of what it calls.
        seeds = {
            n for n in self.methods
            if not n.startswith("_")
            or (n.startswith("__") and n.endswith("__"))
        }
        return self._closure(seeds)

    # -- judgement ---------------------------------------------------------
    def findings(self) -> list:
        if not any(m.thread_entry for m in self.methods.values()):
            return []
        threaded = self.thread_reachable()
        mainside = self.main_reachable()
        out = []
        out.extend(self._race_findings(threaded, mainside))
        out.extend(self._blocking_findings(threaded, mainside))
        return out

    def _accesses(self, attr: str, methods: Set[str]) -> bool:
        for name in methods:
            m = self.methods[name]
            if name != "__init__" and (
                attr in m.reads
                or any(w.attr == attr for w in m.writes)
            ):
                return True
        return False

    def _waived(self, lineno: int, rule: str) -> bool:
        if not 1 <= lineno <= len(self.lines):
            return False
        return rule in WAIVER_RE.findall(self.lines[lineno - 1])

    def _race_findings(self, threaded, mainside) -> list:
        # attr -> unlocked writes outside __init__
        unlocked: Dict[str, List[Tuple[str, _Write]]] = {}
        for name, m in self.methods.items():
            if name == "__init__":
                continue
            for w in m.writes:
                if not w.locked and w.attr not in self.lock_attrs:
                    unlocked.setdefault(w.attr, []).append((name, w))
        out = []
        for attr in sorted(unlocked):
            if not (
                self._accesses(attr, threaded)
                and self._accesses(attr, mainside)
            ):
                continue
            sites = unlocked[attr]
            if all(self._waived(w.lineno, "race-nonatomic-counter")
                   or self._waived(w.lineno, "race-unlocked-shared-state")
                   for _, w in sites):
                continue
            all_rmw = all(w.rmw for _, w in sites)
            rule = (
                "race-nonatomic-counter" if all_rmw
                else "race-unlocked-shared-state"
            )
            where = ", ".join(
                f"{n}():{w.lineno}" for n, w in sites[:4]
            ) + ("..." if len(sites) > 4 else "")
            t_entry = sorted(
                n for n, m in self.methods.items() if m.thread_entry
            )
            out.append(_lazy_finding(
                rule, self.rel, sites[0][1].lineno,
                f"{self.name}.{attr} is written without a lock at "
                f"{where} but is reachable from both the thread "
                f"body ({'/'.join(t_entry)}) and the main path"
                + (" (read-modify-write)" if all_rmw else ""),
            ))
        return out

    def _blocking_findings(self, threaded, mainside) -> list:
        # locks the thread side needs to make progress
        consumer_locks: Set[str] = set()
        for name in threaded:
            consumer_locks |= self.methods[name].locks_used
        out = []
        for name in sorted(mainside):
            for lock, call, lineno in \
                    self.methods[name].blocking_under_lock:
                if lock not in consumer_locks:
                    continue
                if self._waived(lineno, "race-lock-across-blocking"):
                    continue
                out.append(_lazy_finding(
                    "race-lock-across-blocking", self.rel, lineno,
                    f"{self.name}.{name}() holds self.{lock} across "
                    f"blocking '{call}()' while the thread side also "
                    f"acquires self.{lock} — a wedged consumer "
                    "deadlocks the holder",
                ))
        return out


def analyze_class(node: ast.ClassDef, rel: str, lines: List[str]) -> list:
    return _ClassModel(node, rel, lines).build().findings()


def lint_source(src: str, rel: str) -> list:
    """Lock-discipline findings for one module's source text."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    lines = src.splitlines()
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.extend(analyze_class(node, rel, lines))
    return out


def lint_sources(sources) -> list:
    """Findings over ``[(rel, src), ...]`` — every module, every
    class; single-threaded classes judge to zero by construction."""
    out = []
    for rel, src in sources:
        out.extend(lint_source(src, rel))
    return out


def concurrency_pass(graph) -> list:
    """The ``PASSES``-registered entry point over
    ``StepGraph.sources`` (silent when the substrate is absent)."""
    if getattr(graph, "sources", None) is None:
        return []
    return lint_sources(graph.sources)
