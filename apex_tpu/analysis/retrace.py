"""Retrace sentinel — catches silent recompilation across steps.

``jax.jit`` retraces (and XLA recompiles) whenever a call's *abstract
signature* changes: a leaf's shape or dtype, the pytree structure, a
weak-type flag, or a static python value.  In a training loop that is
almost always a bug — a ragged final batch, a python int threaded
through the step, a state tree whose structure depends on a flag — and
it costs a full compile (seconds to minutes) every occurrence, usually
discovered as "step 1000 was mysteriously slow".

:class:`RetraceSentinel` hashes the abstract signature of every
observed call and emits a ``retrace`` finding the moment a NEW
signature appears after the allowed budget (default: the first trace is
free, everything after flags).  It never touches device data — hashing
is pure host-side metadata, safe to run every step.

    sentinel = RetraceSentinel()
    for step in range(n):
        batch = next(it)
        f = sentinel.observe(state, batch)   # None or a Finding
        state = train_step(state, batch)
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax

from apex_tpu.analysis.findings import Finding, make_finding

__all__ = ["abstract_signature", "RetraceSentinel"]


def _leaf_key(leaf: Any) -> Tuple:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        weak = bool(getattr(leaf, "weak_type", False))
        return ("array", tuple(shape), str(dtype), weak)
    # a non-array leaf is a static value: its VALUE is part of the
    # signature (a changing python scalar retraces every call)
    return ("static", repr(leaf))


def abstract_signature(*args, **kwargs) -> Tuple:
    """Hashable abstract signature of a call: pytree structure plus
    (shape, dtype, weak_type) per array leaf and ``repr`` per static
    leaf — exactly the things a changed value of forces a retrace."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef),) + tuple(_leaf_key(l) for l in leaves)


class RetraceSentinel:
    """Flags calls whose abstract signature changed after the budget.

    ``allowed`` is the number of DISTINCT signatures that are expected
    (default 1: one trace, then steady state).  A ragged final batch
    can legitimately add one — pass ``allowed=2`` if the input pipeline
    pads all but the tail.
    """

    def __init__(self, allowed: int = 1, name: str = "step"):
        if allowed < 1:
            raise ValueError("allowed must be >= 1")
        self.allowed = allowed
        self.name = name
        self._signatures: List[Tuple] = []
        self.findings: List[Finding] = []
        self.calls = 0

    @property
    def signatures(self) -> int:
        """Distinct abstract signatures seen so far."""
        return len(self._signatures)

    @property
    def retraces(self) -> int:
        """Signatures beyond the allowed budget (each one a compile)."""
        return max(0, len(self._signatures) - self.allowed)

    def observe(self, *args, **kwargs) -> Optional[Finding]:
        """Record one call's signature; return a ``retrace`` finding if
        it is a NEW signature past the allowed budget, else None."""
        self.calls += 1
        sig = abstract_signature(*args, **kwargs)
        if sig in self._signatures:
            return None
        self._signatures.append(sig)
        if len(self._signatures) <= self.allowed:
            return None
        # name the leaves that differ from the previous signature so the
        # finding points at the culprit, not just "something changed"
        prev, cur = self._signatures[-2], sig
        diffs = []
        if prev[0] != cur[0]:
            diffs.append("pytree structure changed")
        for i, (a, b) in enumerate(zip(prev[1:], cur[1:])):
            if a != b:
                diffs.append(f"leaf {i}: {a} -> {b}")
        if len(prev) != len(cur):
            diffs.append(f"leaf count {len(prev) - 1} -> {len(cur) - 1}")
        finding = make_finding(
            "retrace",
            path=f"{self.name} call #{self.calls}",
            message=(
                f"abstract signature #{len(self._signatures)} (allowed "
                f"{self.allowed}) — this call RECOMPILES: "
                + ("; ".join(diffs[:4]) or "signature changed")
            ),
        )
        self.findings.append(finding)
        return finding
