"""Sharding-conformance + resharding passes — prove the dp×tp plan
compiled, before it runs.

A declared sharding plan is a *promise*: every large param/optimizer
leaf carries its PartitionSpec in the compiled module, and the step
body contains exactly the collectives the plan predicts — no silent
full replication (GSPMD quietly replicates anything the propagation
can't decide, and a replicated optimizer state is the difference
between fitting and OOM), and no unplanned weight all-gathers (the
signature of a spec that didn't survive propagation: XLA re-gathers
the full tensor every step and the "sharded" run is secretly paying
replicated wire traffic).  These passes check both promises against
the optimized HLO:

- :func:`sharding_pass` — **spec conformance**.  Intent is a
  regex→PartitionSpec rule table (:func:`match_partition_rules`, the
  ``fmengine``/EasyLM idiom — the same tables a trainer entry point
  feeds to ``jax.jit``'s ``in_shardings``) matched against each ENTRY
  parameter's jax arg path (the ``op_name`` metadata GSPMD carries
  into the module).  A leaf above ``min_bytes`` whose intended spec is
  sharded but whose compiled sharding is ``{replicated}`` is
  ``sharding-replicated`` (ERROR); a compiled tiling that disagrees
  with the intended per-dim factors is ``sharding-mismatch``.
- :func:`reshard_pass` — **no unintended resharding**.  Intent is a
  per-mesh-axis collective plan (kind, axis, count, bytes, wire
  dtypes — what :meth:`apex_tpu.parallel.DistributedDataParallel
  .collective_plan` and the ZeRO optimizers declare); every compiled
  collective is attributed to a mesh axis by its replica groups and
  checked off against the plan.  A collective the plan doesn't
  predict (above a small latency tolerance) is ``reshard-unplanned``;
  a planned entry whose compiled count/bytes/dtypes drifted is
  ``reshard-plan``.

Both passes skip silently when their intent (``expect_sharding`` /
``expect_plan``) is absent, and the conformance pass degrades to a
``sharding-unverified`` WARNING when the module compiled single-device
(``num_partitions=1``) while the plan names a real mesh — a "clean"
verdict must never claim a property nobody could check.

Plan schema (the ``expect_sharding`` intent)::

    {
        "mesh": {"dp": 2, "tp": 4},          # axis order matters
        "rules": [                            # first match wins
            (r"embed|wte|wpe", P("tp", None)),
            (r"mlp/kernel",    P(None, "tp")),
            (r".*",            P()),          # explicit catch-all
        ],
        "min_bytes": 1 << 20,                 # ignore small leaves
    }

and the ``expect_plan`` intent::

    {
        "mesh": {"dp": 2, "tp": 4},
        "collectives": [
            {"kind": "all-reduce", "axis": "dp",
             "bytes": [0, 4 << 20], "dtypes": ["f32"]},
            {"kind": "all-to-all", "axis": "dp", "count": 2,
             "dtypes": ["s8"]},
        ],
        "allow_unplanned_bytes": 4096,        # latency-sized tolerance
    }

See ``docs/analysis.md`` "Sharding & memory passes".
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.analysis import hlo as hlo_lib
from apex_tpu.analysis.findings import Finding, make_finding

__all__ = [
    "DEFAULT_MIN_BYTES",
    "DEFAULT_UNPLANNED_TOLERANCE",
    "normalize_param_path",
    "match_partition_rules",
    "tree_paths",
    "spec_dim_factors",
    "mesh_axis_groups",
    "infer_collective_axis",
    "plan_table",
    "sharding_pass",
    "reshard_pass",
]

#: leaves under 1 MiB replicate for free — biases, LN scales, scalars;
#: the conformance gate is about the tensors that decide whether the
#: model fits
DEFAULT_MIN_BYTES = 1 << 20

#: unplanned collectives at or under this payload are latency-sized
#: bookkeeping (loss pmeans, metric rows, guard scalars), not a
#: resharded weight
DEFAULT_UNPLANNED_TOLERANCE = 4096


# ---------------------------------------------------------------------------
# rule tables (the match_partition_rules idiom)
# ---------------------------------------------------------------------------


def normalize_param_path(op_name: str) -> str:
    """GSPMD's parameter ``op_name`` metadata (``state[\\'params\\']
    [\\'w\\']``, ``batch[0]``, ``scaler_state.loss_scale``) → a
    ``/``-joined path (``state/params/w``, ``batch/0``,
    ``scaler_state/loss_scale``) that partition-rule regexes match
    against — the same separator :func:`match_partition_rules` uses on
    live pytrees, so ONE rule table serves both."""
    s = op_name.replace("\\'", "'").replace('\\"', '"')
    s = re.sub(r"\[['\"]?([^]'\"]*)['\"]?\]", r"/\1", s)
    s = s.replace(".", "/")
    return s.strip("/")


def tree_paths(tree, sep: str = "/") -> List[Tuple[str, Any]]:
    """``[(path, leaf), ...]`` with dict keys / sequence indices /
    attribute names joined by ``sep`` — the naming
    :func:`match_partition_rules` and :func:`normalize_param_path`
    share."""
    import jax

    out = []
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for keypath, leaf in flat:
        parts = []
        for k in keypath:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            elif hasattr(k, "name"):
                parts.append(str(k.name))
            else:  # pragma: no cover - exotic key types
                parts.append(str(k))
        out.append((sep.join(parts), leaf))
    return out


def match_partition_rules(rules, params, sep: str = "/"):
    """Pytree of PartitionSpec from regex rules — the
    ``fmengine``/EasyLM ``match_partition_rules`` idiom (SNIPPETS.md
    [2]): first rule whose regex ``re.search``-matches the leaf's
    ``/``-joined path wins; scalar and single-element leaves are never
    partitioned (spec ``P()``); a leaf no rule covers raises (a plan
    with holes is not a plan).

    The SAME table drives both surfaces: feed the result to
    ``jax.jit(in_shardings=...)`` (via ``NamedSharding``) when
    building the step, and pass the raw ``rules`` as
    ``expect_sharding["rules"]`` to :func:`apex_tpu.analysis.check` to
    prove the compiled module kept them.
    """
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec

    def pick(path: str, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return PartitionSpec()
        for rule, spec in rules:
            if re.search(rule, path) is not None:
                return spec
        raise ValueError(f"partition rule not found for param: {path}")

    flat = tree_paths(params, sep=sep)
    specs = [pick(path, leaf) for path, leaf in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


def spec_dim_factors(spec, mesh: Dict[str, int], rank: int) -> List[int]:
    """Shards-per-dim a PartitionSpec implies on a rank-``rank`` leaf
    under ``mesh`` (axis → size): ``P(None, "tp")`` on rank 2 with
    ``tp=4`` → ``[1, 4]``; tuple entries multiply
    (``P(("dp", "tp"))`` → ``[8]``)."""
    entries: Sequence = tuple(spec) if spec is not None else ()
    factors = []
    for d in range(rank):
        e = entries[d] if d < len(entries) else None
        if e is None:
            factors.append(1)
        elif isinstance(e, (tuple, list)):
            f = 1
            for axis in e:
                f *= int(mesh.get(axis, 1))
            factors.append(f)
        else:
            factors.append(int(mesh.get(e, 1)))
    return factors


# ---------------------------------------------------------------------------
# mesh-axis attribution of replica groups
# ---------------------------------------------------------------------------


def mesh_axis_groups(mesh: Dict[str, int]) -> Dict[str, frozenset]:
    """Canonical replica-group sets per mesh axis (+ ``"all"`` for the
    whole mesh), assuming row-major device ids over the axis order —
    jax's ``Mesh(devices.reshape(sizes), axes)`` layout.  Each value
    is a frozenset of frozensets of device ids; a collective whose
    printed ``replica_groups`` equal one of these belongs to that
    axis.  Distinguishes dp from tp even at equal sizes (dp=2×tp=2),
    where group SIZE alone is ambiguous."""
    axes = list(mesh)
    sizes = [int(mesh[a]) for a in axes]
    total = 1
    for s in sizes:
        total *= s
    out: Dict[str, frozenset] = {
        "all": frozenset([frozenset(range(total))])
    }
    if total <= 1:
        return out
    for i, axis in enumerate(axes):
        inner = 1  # product of sizes after (minor to) axis i
        for s in sizes[i + 1:]:
            inner *= s
        outer = total // (sizes[i] * inner)
        groups = []
        for o in range(outer):
            for j in range(inner):
                groups.append(frozenset(
                    o * sizes[i] * inner + k * inner + j
                    for k in range(sizes[i])
                ))
        out[axis] = frozenset(groups)
    return out


def infer_collective_axis(
    coll: dict, axis_groups: Dict[str, frozenset], mesh: Dict[str, int]
) -> Optional[str]:
    """Mesh axis a compiled collective spans, from its replica groups.
    Exact group-membership match first (unambiguous even at dp=tp);
    fall back to a unique group-size match when only the iota form
    printed; None when nothing matches (a reshard across a device set
    the mesh doesn't explain — inherently unplanned)."""
    groups = coll.get("groups")
    if groups:
        canon = frozenset(frozenset(g) for g in groups)
        # named axes take precedence: on a 1-axis mesh the axis's
        # groups EQUAL the whole-mesh groups, and the plan names the
        # axis ("dp"), not "all"
        for axis, expected in axis_groups.items():
            if axis != "all" and canon == expected:
                return axis
        if canon == axis_groups["all"]:
            return "all"
        return None
    size = coll.get("group_size")
    if size is None:
        return "all"  # no groups printed = every device participates
    by_size = [
        a for a, s in mesh.items() if int(s) == size
    ]
    total = 1
    for s in mesh.values():
        total *= int(s)
    if size == total:
        return "all"
    return by_size[0] if len(by_size) == 1 else None


# ---------------------------------------------------------------------------
# spec conformance
# ---------------------------------------------------------------------------


def _intended_spec(rules, path: str):
    for rule, spec in rules:
        if re.search(rule, path) is not None:
            return spec
    return None


def plan_table(
    hlo_text: str,
    expect_sharding: Optional[dict] = None,
) -> List[dict]:
    """The human-readable shard plan: one row per ENTRY parameter with
    its compiled sharding, global bytes, intended spec (when a rule
    table is given) and a conformance verdict — what
    ``tools/shard_report.py`` renders and the ``--json`` artifact's
    ``shard_plan`` section carries."""
    spec = expect_sharding or {}
    mesh = dict(spec.get("mesh") or {})
    rules = list(spec.get("rules") or ())
    rows = []
    for p in hlo_lib.parameter_shardings(hlo_text):
        path = normalize_param_path(p["op_name"])
        parsed = hlo_lib.parse_sharding(p["sharding"])
        intended = _intended_spec(rules, path) if path else None
        want = None
        verdict = "unchecked"
        if intended is not None:
            rank = len(hlo_lib.shape_dims(p["shape"]))
            want = spec_dim_factors(intended, mesh, rank)
            have = parsed["dims"] or [1] * rank
            have = have + [1] * (rank - len(have))
            if parsed["kind"] in ("unknown", "manual"):
                verdict = "unchecked"
            elif all(f == 1 for f in want):
                verdict = (
                    "ok" if parsed["kind"] == "replicated" else "mismatch"
                )
            elif parsed["kind"] == "replicated":
                verdict = "replicated"
            else:
                verdict = "ok" if have == want else "mismatch"
        rows.append({
            "param": p["param"],
            "name": path or p["name"],
            "shape": p["shape"],
            "global_bytes": p["global_bytes"],
            "sharding": p["sharding"] or "(none)",
            "intended": str(intended) if intended is not None else None,
            "factors": want,
            "verdict": verdict,
        })
    return rows


def sharding_pass(graph) -> List[Finding]:
    """Spec conformance: every parameter above ``min_bytes`` whose
    rule-table spec shards it must carry that tiling in the compiled
    module.  See the module docstring for the intent schema."""
    if graph.hlo_text is None or not graph.expect_sharding:
        return []
    spec = graph.expect_sharding
    mesh = dict(spec.get("mesh") or {})
    min_bytes = int(spec.get("min_bytes", DEFAULT_MIN_BYTES))
    mesh_size = 1
    for s in mesh.values():
        mesh_size *= int(s)
    npart = hlo_lib.num_partitions(graph.hlo_text)
    if mesh_size > 1 and npart < mesh_size:
        return [make_finding(
            "sharding-unverified",
            path="module header",
            message=(
                f"the plan names a {mesh_size}-device mesh "
                f"({'x'.join(f'{a}={s}' for a, s in mesh.items())}) but "
                f"the module compiled with num_partitions={npart} — "
                "sharding conformance cannot be proven on this compile"
            ),
        )]
    out: List[Finding] = []
    for row in plan_table(graph.hlo_text, spec):
        if row["verdict"] in ("ok", "unchecked"):
            continue
        if row["global_bytes"] < min_bytes:
            continue
        mb = row["global_bytes"] / (1 << 20)
        if row["verdict"] == "replicated":
            out.append(make_finding(
                "sharding-replicated",
                path=row["name"],
                message=(
                    f"{mb:.1f} MiB leaf compiled fully REPLICATED; the "
                    f"plan shards it as {row['intended']} "
                    f"(x{max(row['factors'] or [1])} memory per device "
                    "wasted)"
                ),
            ))
        else:
            out.append(make_finding(
                "sharding-mismatch",
                path=row["name"],
                message=(
                    f"compiled sharding '{row['sharding']}' disagrees "
                    f"with the declared {row['intended']} "
                    f"(want per-dim factors {row['factors']})"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# resharding (per-mesh-axis collective plan)
# ---------------------------------------------------------------------------


def reshard_pass(graph) -> List[Finding]:
    """No unintended resharding: every compiled collective must be
    predicted by the declared per-axis plan; every plan entry with
    explicit count/bytes/dtypes must match the compiled aggregate for
    its (kind, axis).  See the module docstring for the plan schema."""
    if graph.hlo_text is None or not graph.expect_plan:
        return []
    plan = graph.expect_plan
    mesh = dict(plan.get("mesh") or {})
    entries = list(plan.get("collectives") or ())
    tol = int(plan.get(
        "allow_unplanned_bytes", DEFAULT_UNPLANNED_TOLERANCE
    ))
    axis_groups = mesh_axis_groups(mesh)
    actual: Dict[Tuple[str, Optional[str]], dict] = {}
    for coll in hlo_lib.collective_instructions(graph.hlo_text):
        axis = infer_collective_axis(coll, axis_groups, mesh)
        rec = actual.setdefault((coll["kind"], axis), {
            "count": 0, "bytes": 0, "dtypes": set(), "ops": [],
        })
        rec["count"] += 1
        rec["bytes"] += coll["bytes"]
        rec["dtypes"] |= coll["dtypes"]
        rec["ops"].append(coll["op_name"] or coll["name"])
    out: List[Finding] = []
    planned_keys = set()
    for entry in entries:
        key = (entry["kind"], entry.get("axis", "all"))
        planned_keys.add(key)
        got = actual.get(key, {
            "count": 0, "bytes": 0, "dtypes": set(), "ops": [],
        })
        loc = f"{key[0]}@{key[1]}"
        if "count" in entry and entry["count"] is not None \
                and got["count"] != entry["count"]:
            out.append(make_finding(
                "reshard-plan",
                path=loc,
                message=(
                    f"plan promises {entry['count']} '{key[0]}' on axis "
                    f"'{key[1]}', compiled HLO has {got['count']}"
                ),
            ))
        if "bytes" in entry and entry["bytes"] is not None:
            want = entry["bytes"]
            lo, hi = (want, want) if isinstance(want, int) else want
            if not (lo <= got["bytes"] <= hi):
                out.append(make_finding(
                    "reshard-plan",
                    path=loc,
                    message=(
                        f"'{key[0]}' on axis '{key[1]}' moves "
                        f"{got['bytes']} bytes, plan allows "
                        f"[{lo}, {hi}]"
                    ),
                ))
        if "dtypes" in entry and entry["dtypes"] is not None:
            allowed = set(entry["dtypes"])
            extra = got["dtypes"] - allowed
            if extra:
                out.append(make_finding(
                    "reshard-plan",
                    path=loc,
                    message=(
                        f"'{key[0]}' on axis '{key[1]}' payload carries "
                        f"{sorted(extra)} beyond the planned wire "
                        f"{sorted(allowed)}"
                    ),
                ))
    for key, got in actual.items():
        if key in planned_keys or got["bytes"] <= tol:
            continue
        ops = "; ".join(sorted(set(got["ops"]))[:3])
        out.append(make_finding(
            "reshard-unplanned",
            path=f"{key[0]}@{key[1]}",
            message=(
                f"{got['count']} '{key[0]}' collective(s) on axis "
                f"'{key[1]}' moving {got['bytes']} bytes that the "
                f"declared plan does not predict (from: {ops}) — a "
                "weight re-gather here means the sharding did not "
                "survive propagation"
            ),
        ))
    return out
