"""Static analysis of step programs — a jaxpr/HLO graph linter.

Fused kernels, quantized collectives, and AMP policies only pay off if
the *compiled* step graph has the structure we intend.  This package
proves it statically, before a single step runs:

- **transfer lint** — no host↔device transfers or python callbacks
  inside the step (jaxpr callbacks + compiled-HLO infeed/outfeed/
  send-recv/callback custom-calls).
- **promotion lint** — no silent dtype widening past the active
  ``amp`` policy, and no f64 anywhere.
- **donation lint** — every ``donate_argnums`` buffer is actually
  aliased in the compiled buffer assignment (a dropped donation
  silently doubles memory).
- **retrace sentinel** — :class:`RetraceSentinel` flags recompilation
  across steps by hashing abstract call signatures.
- **collective consistency** — the compiled collective schedule
  matches the comm engine's promise (count / bytes / wire dtype),
  on the shared HLO parser that ``apex_tpu.parallel.comm`` and
  ``tools/comm_structure.py`` also read through.

Surfaces::

    from apex_tpu import analysis

    report = analysis.check(step_fn, *args, policy=policy,
                            donate_argnums=(0,),
                            expect_collectives={"all-reduce": 2})
    assert report.ok(), report.render()

plus ``tools/graph_lint.py`` (CLI, JSON artifacts, the
``verify_tier1.sh`` gate) and ``bench.py --lint``.  Findings publish
onto the observability board via :func:`publish_report`, so lint
results ride the same JSONL telemetry as MFU/goodput.  Rule catalog
and fix hints: ``docs/analysis.md``.
"""

from __future__ import annotations

import warnings as _warnings
from typing import Optional

import jax

from apex_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    RULES,
    WARNING,
    Finding,
    Report,
    make_finding,
)
from apex_tpu.analysis.retrace import (  # noqa: F401
    RetraceSentinel,
    abstract_signature,
)
from apex_tpu.analysis.passes import (  # noqa: F401
    PASSES,
    StepGraph,
    iter_eqns,
)
from apex_tpu.analysis import hlo  # noqa: F401

__all__ = [
    "check",
    "lint_jaxpr",
    "lint_hlo",
    "publish_report",
    "Finding",
    "Report",
    "RULES",
    "ERROR",
    "WARNING",
    "INFO",
    "make_finding",
    "RetraceSentinel",
    "abstract_signature",
    "StepGraph",
    "PASSES",
    "iter_eqns",
    "hlo",
]


#: passes that only have a jaxpr substrate — they cannot run (and are
#: dropped from a report's rules_run, so the gap is visible) when
#: tracing failed and only compiled HLO is available
_JAXPR_ONLY = ("promotion",)


def _select(rules) -> tuple:
    if rules is None:
        return tuple(PASSES)
    unknown = [r for r in rules if r not in PASSES]
    if unknown:
        raise ValueError(
            f"unknown analysis pass(es) {unknown}; have {sorted(PASSES)}"
        )
    return tuple(rules)


def _run(graph: StepGraph, rules, target: str) -> Report:
    selected = _select(rules)
    if graph.jaxpr is None:
        # a jaxpr-only pass that cannot run must not be REPORTED as run
        # — a "clean" verdict would claim a property nobody checked
        selected = tuple(r for r in selected if r not in _JAXPR_ONLY)
    report = Report(target=target, rules_run=selected)
    for name in selected:
        report.extend(PASSES[name](graph))
    return report


def check(
    fn,
    *args,
    rules=None,
    policy=None,
    donate_argnums=None,
    static_argnums=None,
    expect_collectives=None,
    publish: bool = False,
    name: Optional[str] = None,
    **kwargs,
) -> Report:
    """Trace, lower, and compile ``fn`` on ``args``; run the selected
    analysis passes over its jaxpr AND optimized HLO; return a
    :class:`Report`.

    ``fn`` may be a plain callable (it is jitted here, with
    ``donate_argnums``/``static_argnums`` applied) or an
    already-``jax.jit``-wrapped function (used as-is; pass
    ``donate_argnums`` anyway so the donation lint knows the intent —
    jit objects don't expose it).  ``policy`` (an ``amp.Policy``,
    ``Properties``, or a bare dtype) arms the promotion-widen rule;
    ``expect_collectives`` arms the collective-consistency rule
    (see :func:`apex_tpu.analysis.passes.collective_pass` for the
    expectation schema).  Compilation happens once, AOT — nothing is
    executed and no buffer is consumed (donation only affects the
    compiled program's aliasing, not tracing).

    ``publish=True`` gauges the finding counts onto the observability
    board so the report rides the JSONL telemetry stream.
    """
    if hasattr(fn, "lower"):
        jitted = fn
    else:
        jitted = jax.jit(
            fn,
            donate_argnums=tuple(donate_argnums or ()),
            static_argnums=tuple(static_argnums or ()),
        )
    target = name or getattr(fn, "__name__", None) or repr(fn)

    jaxpr = None
    try:
        jaxpr = jax.make_jaxpr(
            jitted, static_argnums=tuple(static_argnums or ())
        )(*args, **kwargs)
    except TypeError:
        # some wrapped callables reject make_jaxpr's re-wrapping; the
        # HLO-level passes still run
        pass

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        compiled = jitted.lower(*args, **kwargs).compile()
    hlo_text = compiled.as_text()

    donated = None
    if donate_argnums is not None:
        donated = 0
        for i in tuple(donate_argnums):
            donated += len(jax.tree_util.tree_leaves(args[i]))

    graph = StepGraph(
        jaxpr=jaxpr,
        hlo_text=hlo_text,
        policy=policy,
        donated=donated,
        donated_argnums=tuple(donate_argnums or ()),
        compile_warnings=tuple(str(w.message) for w in caught),
        expect_collectives=expect_collectives,
    )
    report = _run(graph, rules, target)
    if publish:
        publish_report(report)
    return report


def lint_jaxpr(jaxpr, *, policy=None, rules=None, name: str = "") -> Report:
    """Run the jaxpr-level passes (transfer callbacks, promotion) over
    an already-traced ``ClosedJaxpr`` — for callers that trace once and
    lint alongside other uses of the jaxpr."""
    graph = StepGraph(jaxpr=jaxpr, policy=policy)
    wanted = rules if rules is not None else ("transfer", "promotion")
    return _run(graph, wanted, name or "jaxpr")


def lint_hlo(
    hlo_text: str,
    *,
    donated: Optional[int] = None,
    expect_collectives=None,
    rules=None,
    name: str = "",
) -> Report:
    """Run the HLO-level passes (host transfers, donation aliasing,
    collective consistency) over compiled-module text — for callers
    that already paid the compile (``bench.py --lint`` reuses the
    ``--hlo-out`` executable's text instead of compiling twice)."""
    graph = StepGraph(
        hlo_text=hlo_text,
        donated=donated,
        expect_collectives=expect_collectives,
    )
    wanted = rules if rules is not None else (
        "transfer", "donation", "collective"
    )
    return _run(graph, wanted, name or "hlo")


def publish_report(report: Report, prefix: str = "analysis") -> None:
    """Gauge a report's finding counts onto the observability board
    (``{prefix}/errors``, ``{prefix}/warnings``, and per-rule
    ``{prefix}/rule/<id>``), so lint results ride the same JSONL
    telemetry stream as MFU/goodput — mirror of
    ``comm.publish_collective_summary``."""
    try:
        from apex_tpu.observability.metrics import board
    except ImportError:  # pragma: no cover - partial install
        return
    board.set(f"{prefix}/target", report.target)
    board.set(f"{prefix}/errors", len(report.errors()))
    board.set(f"{prefix}/warnings", len(report.warnings()))
    for rule, count in report.counts().items():
        board.set(f"{prefix}/rule/{rule}", count)
