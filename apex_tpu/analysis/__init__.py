"""Static analysis of step programs — a jaxpr/HLO graph linter.

Fused kernels, quantized collectives, and AMP policies only pay off if
the *compiled* step graph has the structure we intend.  This package
proves it statically, before a single step runs:

- **transfer lint** — no host↔device transfers or python callbacks
  inside the step (jaxpr callbacks + compiled-HLO infeed/outfeed/
  send-recv/callback custom-calls).
- **promotion lint** — no silent dtype widening past the active
  ``amp`` policy, and no f64 anywhere.
- **donation lint** — every ``donate_argnums`` buffer is actually
  aliased in the compiled buffer assignment (a dropped donation
  silently doubles memory).
- **retrace sentinel** — :class:`RetraceSentinel` flags recompilation
  across steps by hashing abstract call signatures.
- **collective consistency** — the compiled collective schedule
  matches the comm engine's promise (count / bytes / wire dtype),
  on the shared HLO parser that ``apex_tpu.parallel.comm`` and
  ``tools/comm_structure.py`` also read through.
- **sharding conformance** — every large param/optimizer leaf carries
  its declared PartitionSpec in the compiled module (silent full
  replication = ERROR), from regex→PartitionSpec rule tables
  (:mod:`apex_tpu.analysis.sharding`).
- **resharding** — no collective in the step body the declared
  per-mesh-axis plan (kind / axis / bytes / wire dtype) doesn't
  predict — the "verify the TP wire plan" pass.
- **memory budget** — a static per-buffer live-range peak-HBM
  estimate with top-K attribution and a budget gate
  (:mod:`apex_tpu.analysis.memory`): OOM is a lint ERROR before the
  first step runs.
- **kernel passes** — the shipped Pallas kernels themselves
  (:mod:`apex_tpu.analysis.kernels`): per-config VMEM footprint vs
  the backend budget, tiling/MXU alignment, index-map grid
  coverage/race, causal dead-tile waste, and a compile-free roofline
  that ranks attention tile configs for ``tools/attn_tune.py
  --prune``.

Surfaces::

    from apex_tpu import analysis

    report = analysis.check(step_fn, *args, policy=policy,
                            donate_argnums=(0,),
                            expect_collectives={"all-reduce": 2})
    assert report.ok(), report.render()

plus ``tools/graph_lint.py`` (CLI, JSON artifacts, the
``verify_tier1.sh`` gate) and ``bench.py --lint``.  Findings publish
onto the observability board via :func:`publish_report`, so lint
results ride the same JSONL telemetry as MFU/goodput.  Rule catalog
and fix hints: ``docs/analysis.md``.
"""

from __future__ import annotations

import warnings as _warnings
from typing import Optional

import jax

from apex_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    RULES,
    WARNING,
    Finding,
    Report,
    make_finding,
)
from apex_tpu.analysis.retrace import (  # noqa: F401
    RetraceSentinel,
    abstract_signature,
)
from apex_tpu.analysis.passes import (  # noqa: F401
    PASSES,
    StepGraph,
    iter_eqns,
)
from apex_tpu.analysis import concurrency  # noqa: F401
from apex_tpu.analysis import hlo  # noqa: F401
from apex_tpu.analysis import kernels  # noqa: F401
from apex_tpu.analysis import memory  # noqa: F401
from apex_tpu.analysis import purity  # noqa: F401
from apex_tpu.analysis import sharding  # noqa: F401
from apex_tpu.analysis.sharding import (  # noqa: F401
    match_partition_rules,
)

__all__ = [
    "check",
    "lint_jaxpr",
    "lint_hlo",
    "lint_package",
    "publish_report",
    "attach_shard_sections",
    "Finding",
    "Report",
    "RULES",
    "ERROR",
    "WARNING",
    "INFO",
    "make_finding",
    "RetraceSentinel",
    "abstract_signature",
    "StepGraph",
    "PASSES",
    "iter_eqns",
    "concurrency",
    "hlo",
    "kernels",
    "memory",
    "purity",
    "sharding",
    "match_partition_rules",
]


#: passes that only have a jaxpr substrate — they cannot run (and are
#: dropped from a report's rules_run, so the gap is visible) when
#: tracing failed and only compiled HLO is available
_JAXPR_ONLY = ("promotion",)

#: passes whose substrate is SOURCE text (StepGraph.sources), not a
#: traced/compiled program — same drop-when-absent contract
_SOURCE_ONLY = ("concurrency", "purity")


def _select(rules) -> tuple:
    if rules is None:
        return tuple(PASSES)
    unknown = [r for r in rules if r not in PASSES]
    if unknown:
        raise ValueError(
            f"unknown analysis pass(es) {unknown}; have {sorted(PASSES)}"
        )
    return tuple(rules)


def _run(graph: StepGraph, rules, target: str) -> Report:
    import time as _time

    selected = _select(rules)
    if graph.jaxpr is None:
        # a jaxpr-only pass that cannot run must not be REPORTED as run
        # — a "clean" verdict would claim a property nobody checked
        selected = tuple(r for r in selected if r not in _JAXPR_ONLY)
    if graph.sources is None:
        selected = tuple(r for r in selected if r not in _SOURCE_ONLY)
    report = Report(target=target, rules_run=selected)
    for name in selected:
        t0 = _time.perf_counter()
        report.extend(PASSES[name](graph))
        report.pass_timings[name] = (_time.perf_counter() - t0) * 1e3
    return report


def check(
    fn,
    *args,
    rules=None,
    policy=None,
    donate_argnums=None,
    static_argnums=None,
    expect_collectives=None,
    expect_sharding=None,
    expect_plan=None,
    hbm_budget=None,
    publish: bool = False,
    name: Optional[str] = None,
    **kwargs,
) -> Report:
    """Trace, lower, and compile ``fn`` on ``args``; run the selected
    analysis passes over its jaxpr AND optimized HLO; return a
    :class:`Report`.

    ``fn`` may be a plain callable (it is jitted here, with
    ``donate_argnums``/``static_argnums`` applied) or an
    already-``jax.jit``-wrapped function (used as-is; pass
    ``donate_argnums`` anyway so the donation lint knows the intent —
    jit objects don't expose it).  ``policy`` (an ``amp.Policy``,
    ``Properties``, or a bare dtype) arms the promotion-widen rule;
    ``expect_collectives`` arms the collective-consistency rule
    (see :func:`apex_tpu.analysis.passes.collective_pass` for the
    expectation schema); ``expect_sharding`` (mesh + regex→
    PartitionSpec rules) arms spec conformance, ``expect_plan`` (the
    per-mesh-axis collective plan) arms the resharding rule, and
    ``hbm_budget`` (bytes) arms the static peak-HBM gate — schemas in
    :mod:`apex_tpu.analysis.sharding` and :mod:`apex_tpu.analysis
    .memory`.  Compilation happens once, AOT — nothing is
    executed and no buffer is consumed (donation only affects the
    compiled program's aliasing, not tracing).

    ``publish=True`` gauges the finding counts onto the observability
    board so the report rides the JSONL telemetry stream.
    """
    if hasattr(fn, "lower"):
        jitted = fn
    else:
        jitted = jax.jit(
            fn,
            donate_argnums=tuple(donate_argnums or ()),
            static_argnums=tuple(static_argnums or ()),
        )
    target = name or getattr(fn, "__name__", None) or repr(fn)

    jaxpr = None
    try:
        jaxpr = jax.make_jaxpr(
            jitted, static_argnums=tuple(static_argnums or ())
        )(*args, **kwargs)
    except TypeError:
        # some wrapped callables reject make_jaxpr's re-wrapping; the
        # HLO-level passes still run
        pass

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        compiled = jitted.lower(*args, **kwargs).compile()
    hlo_text = compiled.as_text()

    donated = None
    if donate_argnums is not None:
        donated = 0
        for i in tuple(donate_argnums):
            donated += len(jax.tree_util.tree_leaves(args[i]))

    graph = StepGraph(
        jaxpr=jaxpr,
        hlo_text=hlo_text,
        policy=policy,
        donated=donated,
        donated_argnums=tuple(donate_argnums or ()),
        compile_warnings=tuple(str(w.message) for w in caught),
        expect_collectives=expect_collectives,
        expect_sharding=expect_sharding,
        expect_plan=expect_plan,
        hbm_budget=hbm_budget,
    )
    report = _run(graph, rules, target)
    report.hlo_text = hlo_text
    if publish:
        publish_report(report)
    return report


def lint_jaxpr(jaxpr, *, policy=None, rules=None, name: str = "") -> Report:
    """Run the jaxpr-level passes (transfer callbacks, promotion) over
    an already-traced ``ClosedJaxpr`` — for callers that trace once and
    lint alongside other uses of the jaxpr."""
    graph = StepGraph(jaxpr=jaxpr, policy=policy)
    wanted = rules if rules is not None else ("transfer", "promotion")
    return _run(graph, wanted, name or "jaxpr")


def lint_hlo(
    hlo_text: str,
    *,
    donated: Optional[int] = None,
    expect_collectives=None,
    expect_sharding=None,
    expect_plan=None,
    hbm_budget=None,
    rules=None,
    name: str = "",
) -> Report:
    """Run the HLO-level passes (host transfers, donation aliasing,
    collective consistency, sharding conformance, resharding, memory
    budget) over compiled-module text — for callers that already paid
    the compile (``bench.py --lint`` reuses the ``--hlo-out``
    executable's text instead of compiling twice; the serve engine
    lints the executable it just built)."""
    graph = StepGraph(
        hlo_text=hlo_text,
        donated=donated,
        expect_collectives=expect_collectives,
        expect_sharding=expect_sharding,
        expect_plan=expect_plan,
        hbm_budget=hbm_budget,
    )
    wanted = rules if rules is not None else (
        "transfer", "donation", "collective",
        "sharding", "reshard", "memory",
    )
    report = _run(graph, wanted, name or "hlo")
    report.hlo_text = hlo_text
    return report


def lint_package(
    root: Optional[str] = None,
    rules=("concurrency", "purity"),
    name: str = "apex_tpu",
) -> Report:
    """Run the HOST-SIDE source passes (lock discipline, replay
    purity — docs/analysis.md "Concurrency & replay-purity passes")
    over the package source tree.  The substrate is
    ``StepGraph.sources`` — every ``.py`` under ``root`` (default: the
    installed ``apex_tpu`` package) — so the same ``_run`` machinery
    times the passes and the same Report/RULES schema carries the
    findings as every graph pass.  ``tools/concurrency_lint.py`` is
    the CLI (jax-free, via standalone module loading); ``bench.py
    --lint`` emits the ERROR count as ``concurrency_lint_errors``."""
    graph = StepGraph(sources=purity.collect_sources(root))
    report = _run(graph, rules, name)
    report.sections["files_scanned"] = len(graph.sources)
    return report


def attach_shard_sections(
    report: Report,
    programs,
    expect_sharding: Optional[dict] = None,
    publish: bool = True,
) -> Report:
    """Fill the report's artifact ``sections`` with the sharding/memory
    intelligence of one or more compiled programs: ``peak_hbm_bytes``
    (max over the programs — they execute sequentially and hand
    buffers over), per-program and per-category breakdowns, and the
    ``shard_plan`` parameter table.  ``programs`` is ``[(name,
    hlo_text), ...]`` — pass each sub-report's ``.hlo_text`` so no
    second compile is paid.  ``publish=True`` gauges the peak onto the
    observability board (``analysis/peak_hbm_bytes``), the source the
    :class:`~apex_tpu.observability.health.MemoryBudgetRule` watchdog
    judges.  Used by ``tools/graph_lint.py``, ``tools/shard_report.py``
    and the serve engine's ``lint()``.
    """
    peaks, cats, rows = {}, {}, []
    programs = [(n, t) for n, t in programs]
    #: kept for renderers (tools/shard_report.py) that want the raw
    #: per-program HLO back without a second compile
    report.programs = programs
    for prog_name, text in programs:
        if not text:
            continue
        est = memory.estimate_peak(text)
        peaks[prog_name] = est["peak_bytes"]
        if est["peak_bytes"] == max(peaks.values()):
            cats = est["by_category"]
        for row in sharding.plan_table(text, expect_sharding or {}):
            rows.append({"program": prog_name, **row})
    peak = max(peaks.values()) if peaks else 0
    report.sections["peak_hbm_bytes"] = peak
    report.sections["peak_hbm_by_program"] = peaks
    report.sections["peak_hbm_by_category"] = cats
    report.sections["shard_plan"] = rows
    if publish:
        memory.publish_peak({"peak_bytes": peak, "by_category": cats})
        try:
            from apex_tpu.observability.metrics import board
        except ImportError:  # pragma: no cover - partial install
            return report
        verdicts: dict = {}
        for row in rows:
            verdicts[row["verdict"]] = verdicts.get(row["verdict"], 0) + 1
        board.set("analysis/shard_plan/rows", len(rows))
        for verdict, count in verdicts.items():
            board.set(f"analysis/shard_plan/{verdict}", count)
    return report


def publish_report(report: Report, prefix: str = "analysis") -> None:
    """Gauge a report's finding counts onto the observability board
    (``{prefix}/errors``, ``{prefix}/warnings``, per-rule
    ``{prefix}/rule/<id>``, and per-pass ``{prefix}/pass_ms/<name>``
    timings), so lint results ride the same JSONL telemetry stream as
    MFU/goodput — mirror of ``comm.publish_collective_summary``.

    Counts are deduplicated by (rule, location): when two passes (or
    the jaxpr and HLO substrates of one check) report the same defect
    at the same site, the board counts one defect, not one per pass —
    the raw per-pass findings stay on the report itself.
    """
    try:
        from apex_tpu.observability.metrics import board
    except ImportError:  # pragma: no cover - partial install
        return
    unique = report.deduped()
    board.set(f"{prefix}/target", report.target)
    board.set(
        f"{prefix}/errors",
        sum(1 for f in unique if f.severity == ERROR),
    )
    board.set(
        f"{prefix}/warnings",
        sum(1 for f in unique if f.severity == WARNING),
    )
    counts = {}
    for f in unique:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    for rule, count in counts.items():
        board.set(f"{prefix}/rule/{rule}", count)
    for name, ms in report.pass_timings.items():
        board.set(f"{prefix}/pass_ms/{name}", round(ms, 3))
