"""The analysis passes — each one proves a structural property of a
traced/compiled step, or emits findings that say exactly where it fails.

A pass is a function ``(StepGraph) -> list[Finding]`` registered in
:data:`PASSES`.  :func:`apex_tpu.analysis.check` builds the
:class:`StepGraph` (jaxpr + compiled HLO + intent: amp policy, donation
plan, collective expectations) and runs the selected passes; the
framework is deliberately dumb — all the knowledge lives in passes, so
the next rule is a ~30-line function plus a :data:`findings.RULES`
catalog row.

Jaxpr-level passes (transfer callbacks, promotion) walk the closed
jaxpr RECURSIVELY through pjit/scan/while/cond sub-jaxprs — a transfer
buried in a scan body is still a transfer every iteration.  HLO-level
passes (host transfers, donation aliasing, collective consistency) read
the optimized module text through :mod:`apex_tpu.analysis.hlo`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.core as jax_core
import jax.numpy as jnp

from apex_tpu.analysis import hlo as hlo_lib
from apex_tpu.analysis.findings import Finding, make_finding

__all__ = [
    "StepGraph",
    "PASSES",
    "iter_eqns",
    "transfer_pass",
    "promotion_pass",
    "donation_pass",
    "collective_pass",
]


@dataclasses.dataclass
class StepGraph:
    """Everything a pass may inspect about one step function.

    ``jaxpr``/``hlo_text`` may individually be None (e.g. ``lint_hlo``
    has no jaxpr); passes skip silently when their substrate is absent.
    The remaining fields carry INTENT — what the program is supposed to
    look like — without which the corresponding pass has nothing to
    prove and stays quiet.
    """

    jaxpr: Optional[Any] = None          # jax.core.ClosedJaxpr
    hlo_text: Optional[str] = None
    policy: Optional[Any] = None         # amp.Policy / dtype-carrying obj
    donated: Optional[int] = None        # expected donated leaf count
    donated_argnums: tuple = ()
    compile_warnings: tuple = ()         # str(w) captured at compile()
    expect_collectives: Optional[dict] = None
    #: sharding-conformance intent: {"mesh": {axis: size}, "rules":
    #: [(regex, PartitionSpec)], "min_bytes": int} — see
    #: apex_tpu.analysis.sharding
    expect_sharding: Optional[dict] = None
    #: per-mesh-axis collective plan: {"mesh": ..., "collectives":
    #: [{kind, axis, count?, bytes?, dtypes?}], "allow_unplanned_bytes"}
    expect_plan: Optional[dict] = None
    #: static peak-HBM budget in bytes (apex_tpu.analysis.memory)
    hbm_budget: Optional[int] = None
    #: source substrate for the host-side passes: [(package-relative
    #: path, source text), ...] — built by
    #: apex_tpu.analysis.purity.collect_sources; the concurrency and
    #: purity passes skip silently when this is None (graph-only runs)
    sources: Optional[list] = None


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _sub_jaxprs(params: dict):
    for v in params.values():
        if isinstance(v, jax_core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax_core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                if isinstance(item, jax_core.ClosedJaxpr):
                    yield item.jaxpr
                elif isinstance(item, jax_core.Jaxpr):
                    yield item


def iter_eqns(jaxpr):
    """Yield every eqn of a (Closed)Jaxpr, recursing into sub-jaxprs
    (pjit, scan, while, cond branches, custom_vjp calls, ...)."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _eqn_path(eqn) -> str:
    """name_stack + file:line — the op path findings point at."""
    try:
        from jax._src import source_info_util

        src = source_info_util.summarize(eqn.source_info)
    except Exception:  # pragma: no cover - jax internals moved
        src = ""
    ns = str(getattr(eqn.source_info, "name_stack", "") or "")
    if ns and src:
        return f"{ns} ({src})"
    return ns or src or str(eqn.primitive)


# ---------------------------------------------------------------------------
# transfer lint
# ---------------------------------------------------------------------------

#: primitives whose execution leaves the device for the host python
#: runtime — one round-trip per step (or per scan iteration)
_CALLBACK_PRIMITIVES = frozenset({
    "debug_callback",   # jax.debug.print / jax.debug.callback
    "pure_callback",
    "io_callback",
    "callback",
    "outside_call",     # legacy host_callback
    "host_callback_call",
})


def transfer_pass(graph: StepGraph) -> List[Finding]:
    """No host↔device transfers inside the step.

    Jaxpr level: callback primitives (each one a device→host→device
    round-trip that serializes dispatch).  HLO level: infeed/outfeed,
    host send/recv, python-callback custom-calls that survived into the
    compiled module.
    """
    out: List[Finding] = []
    if graph.jaxpr is not None:
        for eqn in iter_eqns(graph.jaxpr):
            if eqn.primitive.name in _CALLBACK_PRIMITIVES:
                out.append(make_finding(
                    "transfer-callback",
                    path=_eqn_path(eqn),
                    message=(
                        f"'{eqn.primitive.name}' traced into the step — "
                        "a host round-trip every execution"
                    ),
                ))
    if graph.hlo_text is not None:
        for name, why in hlo_lib.host_transfer_ops(graph.hlo_text):
            out.append(make_finding(
                "transfer-hlo-host",
                path=name,
                message=f"compiled HLO op is a host transfer: {why}",
            ))
    return out


# ---------------------------------------------------------------------------
# promotion lint
# ---------------------------------------------------------------------------

_WIDE_FLOATS = {"float64", "complex128"}

#: a named_scope containing one of these tokens marks a region as
#: intentionally higher-precision (f32 accumulation, master weights) —
#: widening inside it is policy-exempt, not silent
_ALLOW_SCOPE_TOKENS = ("f32", "fp32", "master", "highp")

_FLOAT_ORDER = {"bfloat16": 2, "float16": 2, "float32": 4, "float64": 8}


def _compute_dtype(policy) -> Optional[Any]:
    if policy is None:
        return None
    dt = getattr(policy, "compute_dtype", policy)
    try:
        return jnp.dtype(dt)
    except TypeError:
        return None


def _scope_allows(eqn) -> bool:
    ns = str(getattr(eqn.source_info, "name_stack", "") or "").lower()
    return any(tok in ns for tok in _ALLOW_SCOPE_TOKENS)


#: a widening convert consumed ONLY by these primitives is jnp's own
#: accumulate-in-f32-then-narrow reduction idiom (jnp.sum on bf16
#: upcasts internally) — by-design precision, not a silent promotion
_REDUCTION_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min",
    "reduce_and", "reduce_or", "argmax", "argmin",
    "cumsum", "cumprod", "cummax", "cummin",
})


def promotion_pass(graph: StepGraph) -> List[Finding]:
    """No silent dtype widening.

    - ``promotion-f64`` (always on): any eqn producing f64/c128, or an
      f64 literal operand — TPUs emulate f64, and one literal is enough
      to drag a whole subgraph wide.
    - ``promotion-widen`` (needs a half-precision ``policy``): a value
      of the policy's compute dtype converted to a wider float OUTSIDE
      a named scope that declares the widening intentional
      (:data:`_ALLOW_SCOPE_TOKENS`).  JAX materializes silent
      promotions (bf16 array meeting a non-weak f32 array) as exactly
      such a ``convert_element_type`` eqn.  Converts whose every
      consumer is a reduction are exempt — that is jnp's internal
      accumulate-wide idiom (:data:`_REDUCTION_PRIMS`), the behavior a
      policy WANTS.

    Findings deduplicate per op path: one site widening 100 leaves in a
    tree_map is one finding (with a count), not 100.
    """
    if graph.jaxpr is None:
        return []
    compute = _compute_dtype(graph.policy)
    check_widen = compute is not None and jnp.dtype(compute).itemsize < 4
    sites: Dict[tuple, List] = {}  # (rule, path) -> [message, count]

    def visit(jaxpr):
        if isinstance(jaxpr, jax_core.ClosedJaxpr):
            jaxpr = jaxpr.jaxpr
        # per-level consumer map: var -> primitive names that read it
        consumers: Dict[Any, set] = {}
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if not isinstance(v, jax_core.Literal):
                    consumers.setdefault(v, set()).add(eqn.primitive.name)
        escaping = set(jaxpr.outvars)
        for eqn in jaxpr.eqns:
            _check_eqn(eqn, consumers, escaping)
            for sub in _sub_jaxprs(eqn.params):
                visit(sub)

    def _check_eqn(eqn, consumers, escaping):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and str(dt) in _WIDE_FLOATS:
                key = ("promotion-f64", _eqn_path(eqn))
                rec = sites.setdefault(key, [
                    f"'{eqn.primitive.name}' produces {dt}", 0])
                rec[1] += 1
                break
        for v in eqn.invars:
            if isinstance(v, jax_core.Literal):
                dt = getattr(getattr(v, "aval", None), "dtype", None)
                if dt is not None and str(dt) in _WIDE_FLOATS:
                    key = ("promotion-f64", _eqn_path(eqn))
                    rec = sites.setdefault(key, [
                        f"f64 literal feeds '{eqn.primitive.name}'", 0])
                    rec[1] += 1
                    break
        if (
            check_widen
            and eqn.primitive.name == "convert_element_type"
            and not _scope_allows(eqn)
        ):
            src = getattr(eqn.invars[0], "aval", None)
            dst = getattr(eqn.outvars[0], "aval", None)
            src_dt = getattr(src, "dtype", None)
            dst_dt = getattr(dst, "dtype", None)
            if (
                src_dt is not None and dst_dt is not None
                and str(src_dt) == str(compute)
                and _FLOAT_ORDER.get(str(dst_dt), 0)
                > _FLOAT_ORDER.get(str(src_dt), 99)
            ):
                out_v = eqn.outvars[0]
                used_by = consumers.get(out_v, set())
                if (
                    used_by
                    and used_by <= _REDUCTION_PRIMS
                    and out_v not in escaping
                ):
                    return  # jnp's accumulate-wide reduction idiom
                key = ("promotion-widen", _eqn_path(eqn))
                rec = sites.setdefault(key, [
                    f"{src_dt} -> {dst_dt} past compute dtype "
                    f"{jnp.dtype(compute).name}", 0])
                rec[1] += 1

    visit(graph.jaxpr)
    out = []
    for (rule, path), (msg, count) in sites.items():
        if count > 1:
            msg += f" ({count} values at this site)"
        out.append(make_finding(rule, path=path, message=msg))
    return out


# ---------------------------------------------------------------------------
# donation lint
# ---------------------------------------------------------------------------


def donation_pass(graph: StepGraph) -> List[Finding]:
    """Every buffer declared in ``donate_argnums`` must be aliased in
    the compiled buffer assignment; a dropped donation means XLA kept
    BOTH copies live (for an optimizer state, that's 2x memory).

    Ground truth is the module header's ``input_output_alias`` —
    :func:`apex_tpu.analysis.hlo.input_output_aliases` — compared
    against the number of leaves in the donated arguments.  The
    "donated buffers were not usable" warning captured at compile time
    (when present) names the exact shapes for the finding.
    """
    if graph.hlo_text is None or graph.donated is None:
        return []
    aliased = hlo_lib.input_output_aliases(graph.hlo_text)
    dropped = graph.donated - len(aliased)
    if dropped <= 0:
        return []
    detail = ""
    for w in graph.compile_warnings:
        if "donated" in w:
            detail = " — " + w.splitlines()[0]
            break
    argnums = (
        f" (donate_argnums={tuple(graph.donated_argnums)})"
        if graph.donated_argnums else ""
    )
    return [make_finding(
        "donation-dropped",
        path="input_output_alias",
        message=(
            f"{dropped} of {graph.donated} donated buffers were NOT "
            f"aliased by XLA{argnums}; each holds a duplicate "
            f"allocation{detail}"
        ),
    )]


# ---------------------------------------------------------------------------
# collective consistency
# ---------------------------------------------------------------------------


def _normalize_expectation(spec) -> dict:
    if isinstance(spec, int):
        return {"count": spec}
    return dict(spec)


def collective_pass(graph: StepGraph) -> List[Finding]:
    """The compiled collective schedule matches the comm engine's
    promise: per-kind count, payload bytes, and wire dtype.

    ``expect_collectives`` maps an HLO collective kind (``all-reduce``,
    ``all-gather``, ``reduce-scatter``, ``all-to-all``,
    ``collective-permute``) to either a bare count or a dict with any
    of ``count``, ``bytes`` (exact, or ``[lo, hi]`` bounds), and
    ``dtypes`` (the complete allowed payload-dtype set, e.g.
    ``["s8", "f32"]`` for an int8 wire whose scales ride along).  Kinds
    present in the HLO but absent from the expectation are ignored —
    assert on what the engine promises, not on XLA's whole schedule.
    """
    if graph.hlo_text is None or not graph.expect_collectives:
        return []
    summary = hlo_lib.collective_summary(graph.hlo_text)
    dtypes = hlo_lib.collective_dtypes(graph.hlo_text)
    out: List[Finding] = []
    for kind, raw in graph.expect_collectives.items():
        spec = _normalize_expectation(raw)
        actual = summary.get(kind, {"count": 0, "bytes": 0})
        if "count" in spec and actual["count"] != spec["count"]:
            out.append(make_finding(
                "collective-count",
                path=kind,
                message=(
                    f"expected {spec['count']} '{kind}' collective(s), "
                    f"compiled HLO has {actual['count']}"
                ),
            ))
        if "bytes" in spec:
            want = spec["bytes"]
            lo, hi = (want, want) if isinstance(want, int) else want
            if not (lo <= actual["bytes"] <= hi):
                out.append(make_finding(
                    "collective-bytes",
                    path=kind,
                    message=(
                        f"'{kind}' moves {actual['bytes']} bytes, "
                        f"expected within [{lo}, {hi}]"
                    ),
                ))
        if "dtypes" in spec:
            allowed = set(spec["dtypes"])
            got = dtypes.get(kind, set())
            extra = got - allowed
            if extra:
                out.append(make_finding(
                    "collective-dtype",
                    path=kind,
                    message=(
                        f"'{kind}' payload carries {sorted(extra)} "
                        f"beyond the wire's allowed {sorted(allowed)}"
                    ),
                ))
    return out


from apex_tpu.analysis.concurrency import concurrency_pass  # noqa: E402
from apex_tpu.analysis.memory import memory_pass  # noqa: E402
from apex_tpu.analysis.purity import purity_pass  # noqa: E402
from apex_tpu.analysis.sharding import (  # noqa: E402
    reshard_pass,
    sharding_pass,
)

#: pass name -> implementation; ``rules=`` selects by these names (the
#: retrace rule is runtime-only — see analysis.RetraceSentinel).  The
#: sharding/reshard/memory passes live in their own modules
#: (apex_tpu/analysis/sharding.py, .../memory.py) and are quiet until
#: their intent (expect_sharding / expect_plan / hbm_budget) is given.
#: The concurrency/purity passes read the SOURCE substrate
#: (StepGraph.sources) and are quiet without it.
PASSES: Dict[str, Callable[[StepGraph], List[Finding]]] = {
    "transfer": transfer_pass,
    "promotion": promotion_pass,
    "donation": donation_pass,
    "collective": collective_pass,
    "sharding": sharding_pass,
    "reshard": reshard_pass,
    "memory": memory_pass,
    "concurrency": concurrency_pass,
    "purity": purity_pass,
}
