"""Replay-purity pass — source-level determinism lint for the host side.

Three acceptance gates (SERVE, GOODPUT, FLEET in
``tools/verify_tier1.sh``) rest on **bit-identical replay**: the same
request stream / chaos storm / fleet drill must produce the same
decisions, the same losses, the same timeline on every run.  The
device half of that proof is the graph linter's job; this pass proves
the HOST half at the source line, before anything runs, by walking the
AST of the declared replay-critical modules (:data:`REPLAY_CRITICAL`)
and flagging the four ways host code silently picks up
run-to-run-varying state:

- ``replay-wall-clock`` — ``time.time()`` / ``datetime.now()`` where
  only ``time.monotonic`` or the drills' virtual clock are legal;
- ``replay-unseeded-rng`` — module-level ``random.*`` /
  ``np.random.*`` draws from hidden global RNG state (seeded
  generator objects and ``jax.random`` keys pass);
- ``replay-set-order`` — iteration over a ``set`` feeding
  scheduling/ordering decisions (hash-seed dependent order);
- ``replay-env-read`` — ``os.environ`` reads inside step/tick bodies
  (construction-time reads — ``__init__`` / ``from_env`` /
  ``resolve_*`` — are configuration, and pass).

:data:`REPLAY_CRITICAL` is the single source of truth for "what is
replay-critical": ``tools/repo_lint.py`` delegates its host-side
wall-clock rule to it, and ``tools/concurrency_lint.py`` runs this
pass over exactly these modules.

An audited site is waived in-line with
``# lint: allow(<rule-id>): <reason>`` on the offending line — the
reason is mandatory by convention and reviewed like any other code.

The module body is deliberately stdlib-only and import-free at module
level (findings are imported lazily inside functions), so
``tools/repo_lint.py`` can load it standalone — no jax, no package
import — exactly like it loads ``findings.py``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "REPLAY_CRITICAL",
    "WALL_CLOCK_PATTERNS",
    "WAIVER_RE",
    "is_replay_critical",
    "collect_sources",
    "lint_source",
    "lint_sources",
    "purity_pass",
]

#: replay-critical module prefixes, relative to the package root
#: (``apex_tpu/``), "/"-separated.  A prefix ending in "/" covers the
#: whole subpackage.  THE single source of truth: the purity pass, the
#: ``tools/repo_lint.py`` host-side wall-clock rule, and
#: ``docs/analysis.md`` all read this tuple.
REPLAY_CRITICAL: Tuple[str, ...] = (
    "serve/",
    "goodput/stream.py",
    "resilience/runner.py",
    "fleetctl/",
)

#: the source-level wall-clock fingerprints ``tools/repo_lint.py``
#: reuses for its line-regex scan of the same modules (the AST walk
#: below is the authoritative detector; the regexes are the cheap
#: no-jax mirror)
WALL_CLOCK_PATTERNS: Tuple[str, ...] = (
    r"\btime\.time\(\)",
    r"\bdatetime\.(?:now|utcnow|today)\b",
)

#: ``# lint: allow(rule-id): reason`` waives that rule on that line
WAIVER_RE = re.compile(r"lint:\s*allow\(([a-z0-9-]+)\)")

#: wall-clock dotted calls (resolved through plain-name attribute
#: chains; ``time.monotonic`` / ``time.perf_counter`` are the legal
#: duration clocks and never match)
_WALL_CLOCK_CALLS = {
    "time.time",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
}

#: np.random constructors that yield SEEDED generator objects — calls
#: THROUGH these are fine, calls to any other np.random.* function hit
#: the hidden global RNG
_SEEDED_NP_CTORS = {
    "default_rng",
    "RandomState",
    "Generator",
    "SeedSequence",
    "Philox",
    "PCG64",
    "PCG64DXSM",
    "MT19937",
}

#: random-module names that are seeded-object constructors, not draws
_SEEDED_RANDOM_CTORS = {"Random", "SystemRandom", "seed"}

#: enclosing-function shapes where an os.environ read is construction-
#: time configuration, not a per-step dependency
_ENV_OK_FUNCS = ("__init__", "from_env", "main")
_ENV_OK_PREFIXES = ("resolve", "_resolve")


def is_replay_critical(rel: str) -> bool:
    """True when ``rel`` (package-relative path, either separator) is
    inside a :data:`REPLAY_CRITICAL` prefix."""
    rel = rel.replace(os.sep, "/")
    return any(
        rel == p or (p.endswith("/") and rel.startswith(p))
        for p in REPLAY_CRITICAL
    )


def collect_sources(
    root: Optional[str] = None, only_replay: bool = False,
) -> List[Tuple[str, str]]:
    """``[(package-relative path, source text), ...]`` for every ``.py``
    under the package — the substrate both source passes walk
    (``StepGraph.sources``).  ``only_replay=True`` keeps just the
    :data:`REPLAY_CRITICAL` files."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if only_replay and not is_replay_critical(rel):
                continue
            with open(path, encoding="utf-8") as f:
                out.append((rel, f.read()))
    return out


def _finding(rule: str, rel: str, lineno: int, message: str):
    # lazy: keeps this module loadable standalone (no package import)
    # for tools/repo_lint.py, which only reads the constants above
    from apex_tpu.analysis.findings import make_finding

    return make_finding(rule, f"apex_tpu/{rel}:{lineno}", message)


def _waived(lines: List[str], lineno: int, rule: str) -> bool:
    if not 1 <= lineno <= len(lines):
        return False
    return rule in WAIVER_RE.findall(lines[lineno - 1])


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a plain Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_set_expr(node: ast.AST, set_names: Set[str]) -> bool:
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.Call):
        fn = _dotted(node.func)
        return fn in ("set", "frozenset")
    name = _dotted(node)
    return name is not None and name in set_names


class _PurityVisitor(ast.NodeVisitor):
    def __init__(self, rel: str, lines: List[str]):
        self.rel = rel
        self.lines = lines
        self.findings: list = []
        #: names statically known to hold a set in the current scope
        #: (locals assigned set()/``{...}``; ``self.x = set()`` anywhere
        #: in the file contributes ``self.x``)
        self.set_names: Set[str] = set()
        self.func_stack: List[str] = []

    # -- helpers -----------------------------------------------------------
    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        if _waived(self.lines, node.lineno, rule):
            return
        self.findings.append(
            _finding(rule, self.rel, node.lineno, message)
        )

    def _env_context_ok(self) -> bool:
        if not self.func_stack:
            return True  # module level = import-time configuration
        name = self.func_stack[-1]
        return (
            name in _ENV_OK_FUNCS
            or name.startswith(_ENV_OK_PREFIXES)
            or "env" in name
        )

    # -- scope tracking ----------------------------------------------------
    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        outer = set(self.set_names)
        self.generic_visit(node)
        # locals die with the scope; self.* survive (prefixed names)
        self.set_names = outer | {
            n for n in self.set_names if "." in n
        }
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        for tgt in node.targets:
            name = _dotted(tgt)
            if name is None:
                continue
            if _is_set_expr(node.value, self.set_names):
                self.set_names.add(name)
            else:
                self.set_names.discard(name)
        self.generic_visit(node)

    # -- the rules ---------------------------------------------------------
    def visit_Call(self, node):
        fn = _dotted(node.func)
        if fn:
            if fn in _WALL_CLOCK_CALLS:
                self._emit(
                    "replay-wall-clock", node,
                    f"wall-clock read '{fn}()' in replay-critical "
                    f"module apex_tpu/{self.rel} — only time.monotonic"
                    "/the virtual clock are replay-pure",
                )
            self._check_rng(fn, node)
            if fn in ("os.getenv", "os.environ.get"):
                self._check_env(fn, node)
        self.generic_visit(node)

    def _check_rng(self, fn: str, node: ast.Call) -> None:
        parts = fn.split(".")
        if parts[0] == "random" and len(parts) == 2:
            if parts[1] not in _SEEDED_RANDOM_CTORS:
                self._emit(
                    "replay-unseeded-rng", node,
                    f"'{fn}()' draws from the module-level RNG — "
                    "hidden global state breaks bit-identical replay",
                )
        elif (
            len(parts) == 3
            and parts[0] in ("np", "numpy")
            and parts[1] == "random"
            and parts[2] not in _SEEDED_NP_CTORS
        ):
            self._emit(
                "replay-unseeded-rng", node,
                f"'{fn}()' draws from numpy's global RNG — thread a "
                "seeded default_rng(seed) generator instead",
            )

    def _check_env(self, fn: str, node: ast.AST) -> None:
        if self._env_context_ok():
            return
        self._emit(
            "replay-env-read", node,
            f"os.environ read ('{fn}') inside "
            f"'{self.func_stack[-1]}' — per-step env reads make "
            "replay depend on live process state",
        )

    def visit_Subscript(self, node):
        if _dotted(node.value) == "os.environ":
            self._check_env("os.environ[...]", node)
        self.generic_visit(node)

    def _check_iter(self, iter_node: ast.AST) -> None:
        if _is_set_expr(iter_node, self.set_names):
            what = _dotted(iter_node) or "a set expression"
            self._emit(
                "replay-set-order", iter_node,
                f"iteration over set '{what}' — hash-seed-dependent "
                "order feeding host logic in a replay-critical module",
            )

    def visit_For(self, node):
        self._check_iter(node.iter)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def visit_comprehension(self, node):
        self._check_iter(node.iter)
        self.generic_visit(node)


def _collect_self_sets(tree: ast.AST) -> Set[str]:
    """``self.x`` names assigned a set anywhere in the file — a set
    attribute built in ``__init__`` and iterated in ``step()`` must
    still flag, so attribute set-ness is file-global."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                name = _dotted(tgt)
                if name and name.startswith("self.") and _is_set_expr(
                    node.value, set()
                ):
                    names.add(name)
    return names


def lint_source(src: str, rel: str) -> list:
    """Purity findings for one replay-critical module's source text.
    ``rel`` is the package-relative path (used for the finding path and
    the :func:`is_replay_critical` gate — a non-critical path returns
    no findings)."""
    if not is_replay_critical(rel):
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [_finding(
            "replay-wall-clock", rel, e.lineno or 0,
            f"unparseable replay-critical module: {e.msg}",
        )]
    visitor = _PurityVisitor(rel, src.splitlines())
    visitor.set_names |= _collect_self_sets(tree)
    visitor.visit(tree)
    return visitor.findings


def lint_sources(sources) -> list:
    """Findings over ``[(rel, src), ...]`` (only replay-critical
    entries contribute)."""
    out = []
    for rel, src in sources:
        out.extend(lint_source(src, rel))
    return out


def purity_pass(graph) -> list:
    """The ``PASSES``-registered entry point: walks
    ``StepGraph.sources`` (skips silently when the substrate is
    absent, like every other pass)."""
    if getattr(graph, "sources", None) is None:
        return []
    return lint_sources(graph.sources)
