"""Static analysis of Pallas kernels — lint + cost model, no compile.

The graph passes (:mod:`apex_tpu.analysis.passes`) see a compiled step
in which every Pallas kernel is one opaque custom-call; this module
analyzes the kernels THEMSELVES, from the
:class:`~apex_tpu.ops.pallas.introspect.KernelSpec` records the kernel
modules export off their own call plans
(``flash_attention.kernel_specs`` / ``layer_norm.kernel_specs`` /
``decode_attention.kernel_specs``).  Nothing traces or compiles: a
config is judged in microseconds, which is what lets
``tools/attn_tune.py --prune`` reject most of a sweep grid before the
hardware sees it.

Five passes, same :class:`~apex_tpu.analysis.findings.Finding`
currency as every other pass:

- **VMEM footprint** (``kernel-vmem-overflow``) — double-buffered
  input/output blocks + scratch + declared in-kernel intermediates at
  true dtype widths, gated against the backend's VMEM
  (:func:`apex_tpu.observability.meter.vmem_bytes_for`).
- **tiling alignment** (``kernel-tile-misaligned``) — block dims vs
  the (sublane, 128-lane) tile quantum for the operand dtype (a dim
  covering its whole array axis is exempt: Mosaic lowers untiled
  full-extent trailing dims), ragged tails (these kernels have no
  partial-tile masking, so a non-dividing block silently mis-indexes),
  and MXU-feeding extents that aren't 128 multiples (a 96-wide score
  tile wastes a quarter of every systolic pass).
- **grid coverage / race** (``kernel-grid-oob``,
  ``kernel-block-race``) — the REAL index maps evaluated over the
  grid: block offsets out of range, and two grid cells that differ
  along a *parallel* axis writing the same output block (revisits
  along the sequential "arbitrary" axes are the kernels' documented
  accumulate-in-scratch pattern, not a race).
- **causal dead tiles** (``kernel-dead-tiles``) — reuses
  ``_causal_block_live``'s math to report the wasted-FLOP fraction a
  config pays on partially-masked tiles (a naive whole-seq tile wastes
  ~50% of its MXU work on the masked triangle).
- **roofline verdict** — static FLOPs and HBM bytes (the byte model
  replays Pallas's pipeline: a block is re-fetched exactly when its
  index-map output changes across the row-major grid walk) give
  arithmetic intensity against :mod:`~apex_tpu.observability.meter`'s
  shared peak table, a compute/memory/grid bound verdict, and a
  predicted ceiling — the ranking signal the tuner prunes with.

Absolute predicted TFLOP/s are optimistic (the model has no
software-pipeline stalls); the *ranking* across tile configs is what
is validated against the recorded v5e sweep
(``tests/data/attn_sweep_r05.json``).
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.analysis.findings import (
    ERROR,
    Finding,
    Report,
    make_finding,
)
from apex_tpu.ops.pallas.introspect import (
    BlockArg,
    KernelSpec,
    buffer_bytes,
    dtype_width,
)

__all__ = [
    "KERNEL_PASSES",
    "analyze",
    "analyze_default_kernels",
    "default_kernel_specs",
    "dead_tile_stats",
    "predict_config",
    "publish_kernel_report",
    "roofline",
    "vmem_footprint",
]

_LANES = 128
#: minimum sublane count by dtype width (the pallas guide's tile table)
_SUBLANE = {1: 32, 2: 16, 4: 8, 8: 8}

#: fixed cost per grid step (DMA issue, accumulator init/flush, causal
#: offset bookkeeping).  Calibrated on the recorded v5e sweeps: at the
#: mha shape a (128, 128) causal grid is ~33k tiles whose fixed cost
#: dominates, and the model must reproduce the measured ordering
#: (large tiles win at both recorded shapes) — see
#: tests/test_kernel_analysis.py::test_prune_recorded_sweep.
_GRID_STEP_SECONDS = 3e-7

#: full-grid index-map evaluation cap; beyond it the coverage/byte
#: passes sample axis extremes / probe dependence instead of walking
#: every cell (a (128, 128)-tile long-context grid is 131k cells)
_COVERAGE_CELL_CAP = 32768

KERNEL_PASSES = (
    "kernel-vmem", "kernel-tiling", "kernel-coverage", "kernel-dead-tiles",
)


# ---------------------------------------------------------------------------
# VMEM footprint model
# ---------------------------------------------------------------------------


def vmem_footprint(spec: KernelSpec) -> Dict[str, int]:
    """Per-config VMEM bytes: ``block_bytes`` (input/output blocks,
    x2 for the pipeline's double buffering), ``scratch_bytes``,
    ``intermediate_bytes`` (declared in-kernel values — e.g. the f32
    score tile), and their ``total_bytes``.

    ``block_bytes + scratch_bytes`` is the part reconstructable from
    the pallas_call arguments alone — the model-vs-interpret agreement
    test pins it against a captured real call; intermediates ride only
    the overflow gate."""
    blocks = 2 * sum(a.block_bytes() for a in spec.blocked())
    scratch = sum(buffer_bytes(s, dt) for s, dt in spec.scratch)
    inter = sum(buffer_bytes(s, dt) for s, dt in spec.intermediates)
    return {
        "block_bytes": blocks,
        "scratch_bytes": scratch,
        "intermediate_bytes": inter,
        "total_bytes": blocks + scratch + inter,
    }


def _vmem_pass(spec: KernelSpec, budget: int) -> List[Finding]:
    fp = vmem_footprint(spec)
    if fp["total_bytes"] <= budget:
        return []
    return [make_finding(
        "kernel-vmem-overflow",
        path=spec.name,
        message=(
            f"config needs ~{fp['total_bytes'] / (1 << 20):.1f} MiB VMEM "
            f"(blocks x2 {fp['block_bytes'] / (1 << 20):.1f} + scratch "
            f"{fp['scratch_bytes'] / (1 << 20):.1f} + intermediates "
            f"{fp['intermediate_bytes'] / (1 << 20):.1f}) against a "
            f"{budget / (1 << 20):.1f} MiB budget"
        ),
    )]


# ---------------------------------------------------------------------------
# Tiling-alignment lint
# ---------------------------------------------------------------------------


def _tiling_pass(spec: KernelSpec) -> List[Finding]:
    out: List[Finding] = []
    for arg in spec.blocked():
        block, shape = arg.block, arg.shape
        width = dtype_width(arg.dtype)
        sublane = _SUBLANE.get(width, 8)
        # ragged tails: the kernels have no partial-tile masking
        for dim, (b, s) in enumerate(zip(block, shape)):
            if b <= 0:
                out.append(make_finding(
                    "kernel-tile-misaligned",
                    path=f"{spec.name}/{arg.name}",
                    message=f"block dim {dim} is {b}",
                ))
            elif s % b:
                out.append(make_finding(
                    "kernel-tile-misaligned",
                    path=f"{spec.name}/{arg.name}",
                    message=(
                        f"block dim {dim} ({b}) does not divide the "
                        f"array axis ({s}) — these kernels have no "
                        f"partial-tile masking, the ragged tail would "
                        f"read/write out of range"
                    ),
                ))
        # (sublane, lane) quantum on the last two dims; a block covering
        # its WHOLE axis is exempt (Mosaic lowers untiled full-extent
        # dims — how d=64 heads stay 64 instead of lane-padding)
        if len(block) >= 1:
            last_b, last_s = block[-1], shape[-1]
            if last_b != last_s and last_b % _LANES:
                out.append(make_finding(
                    "kernel-tile-misaligned",
                    path=f"{spec.name}/{arg.name}",
                    message=(
                        f"trailing block dim {last_b} is neither the "
                        f"full axis ({last_s}) nor a {_LANES}-lane "
                        f"multiple"
                    ),
                ))
        if len(block) >= 2:
            sub_b, sub_s = block[-2], shape[-2]
            if sub_b != sub_s and sub_b % sublane:
                out.append(make_finding(
                    "kernel-tile-misaligned",
                    path=f"{spec.name}/{arg.name}",
                    message=(
                        f"sublane block dim {sub_b} is neither the full "
                        f"axis ({sub_s}) nor a multiple of the "
                        f"{arg.dtype} sublane quantum ({sublane})"
                    ),
                ))
    # MXU utilization: contraction extents the exporter declares
    for name, extent in (spec.meta.get("matmul_dims") or {}).items():
        if name == "head_dim":
            # the head dim covers its whole (caller-padded) axis by the
            # _pad_head_dim contract; only a broken pad is a finding
            if extent % 8:
                out.append(make_finding(
                    "kernel-tile-misaligned",
                    path=f"{spec.name}/{name}",
                    message=(
                        f"head dim {extent} is not sublane-aligned — "
                        f"the caller-side _pad_head_dim contract is "
                        f"broken"
                    ),
                ))
            continue
        if extent % _LANES:
            out.append(make_finding(
                "kernel-tile-misaligned",
                path=f"{spec.name}/{name}",
                severity="warning",
                message=(
                    f"MXU contraction extent {name}={extent} is not a "
                    f"{_LANES} multiple — the 128x128 systolic array "
                    f"pads every pass to the next tile and the "
                    f"remainder lanes do dead work"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# Grid coverage / race
# ---------------------------------------------------------------------------


def _grid_cells(grid: Tuple[int, ...]) -> Iterable[Tuple[int, ...]]:
    """Every cell when the grid is small; otherwise the axis-extreme
    lattice {0, mid, max}^n (the kernels' affine-ish index maps take
    their extrema at axis extremes)."""
    total = 1
    for g in grid:
        total *= g
    if total <= _COVERAGE_CELL_CAP:
        yield from np.ndindex(*grid)
        return
    axes = [sorted({0, g // 2, g - 1}) for g in grid]
    yield from itertools.product(*axes)


def _eval_map(arg: BlockArg, cell) -> Optional[Tuple[int, ...]]:
    idx = arg.index_map(*cell)
    if not isinstance(idx, tuple):
        idx = (idx,)
    return tuple(int(x) for x in idx)


def _coverage_pass(spec: KernelSpec) -> List[Finding]:
    out: List[Finding] = []
    sem = spec.dimension_semantics or ()
    parallel_axes = [i for i, s in enumerate(sem) if s == "parallel"]
    cells = list(_grid_cells(spec.grid))
    for arg in spec.blocked():
        nblocks = [
            max(1, -(-s // b)) for s, b in zip(arg.shape, arg.block)
        ]
        oob_reported = False
        writers: Dict[Tuple[int, ...], Tuple[int, ...]] = {}
        raced = False
        is_output = arg in spec.outputs
        for cell in cells:
            try:
                idx = _eval_map(arg, cell)
            except Exception as e:  # a map that cannot evaluate IS a bug
                out.append(make_finding(
                    "kernel-grid-oob",
                    path=f"{spec.name}/{arg.name}",
                    message=(
                        f"index map failed at grid cell {cell}: "
                        f"{type(e).__name__}: {e}"
                    ),
                ))
                oob_reported = True
                break
            if len(idx) != len(arg.block):
                out.append(make_finding(
                    "kernel-grid-oob",
                    path=f"{spec.name}/{arg.name}",
                    message=(
                        f"index map returns rank {len(idx)} for a rank "
                        f"{len(arg.block)} block"
                    ),
                ))
                oob_reported = True
                break
            if not oob_reported and any(
                i < 0 or i >= nb for i, nb in zip(idx, nblocks)
            ):
                out.append(make_finding(
                    "kernel-grid-oob",
                    path=f"{spec.name}/{arg.name}",
                    message=(
                        f"grid cell {cell} maps to block offset {idx} "
                        f"outside the {tuple(nblocks)} block grid of "
                        f"shape {arg.shape}"
                    ),
                ))
                oob_reported = True
            if is_output and not raced:
                pcoord = tuple(cell[a] for a in parallel_axes)
                prev = writers.get(idx)
                if prev is None:
                    writers[idx] = pcoord
                elif prev != pcoord:
                    out.append(make_finding(
                        "kernel-block-race",
                        path=f"{spec.name}/{arg.name}",
                        message=(
                            f"grid cells at parallel coordinates "
                            f"{prev} and {pcoord} both write output "
                            f"block {idx} — parallel grid dims carry "
                            f"no accumulation semantics, the second "
                            f"write clobbers the first in an "
                            f"unspecified order"
                        ),
                    ))
                    raced = True
    return out


# ---------------------------------------------------------------------------
# Causal dead-tile accounting
# ---------------------------------------------------------------------------


def dead_tile_stats(spec: KernelSpec) -> Optional[Dict[str, float]]:
    """Live/dead tile counts and the wasted-FLOP fraction of the live
    tiles under the causal mask (``None`` for non-causal specs).

    Reuses ``_causal_block_live``'s liveness rule, so the accounting
    and the kernels' ``pl.when`` skip can never disagree."""
    if not spec.causal:
        return None
    from apex_tpu.ops.pallas.flash_attention import _causal_block_live

    c = spec.causal
    bq, bk, offset = c["bq"], c["bk"], c["offset"]
    nq = spec.grid[c["q_axis"]]
    nk = spec.grid[c["k_axis"]]
    include = bool(c.get("include_fully_masked"))

    i = np.arange(nq)[:, None]
    j = np.arange(nk)[None, :]
    live = np.asarray(_causal_block_live(i, j, bq, bk, offset, include))
    live_tiles = int(live.sum())

    # unmasked (= productive) elements per tile: rows r of tile i see
    # clip(r + offset + 1 - j*bk, 0, bk) columns of tile j
    rows = np.arange(bq)[None, None, :]
    allowed = np.clip(
        i[:, :, None] * bq + rows + offset + 1 - (j * bk)[:, :, None],
        0, bk,
    ).sum(axis=-1)
    unmasked = float((allowed * live).sum())
    executed = float(live_tiles) * bq * bk
    waste = 0.0 if executed == 0 else max(0.0, 1.0 - unmasked / executed)
    return {
        "total_tiles": float(nq * nk),
        "live_tiles": float(live_tiles),
        "dead_tiles": float(nq * nk - live_tiles),
        "waste_fraction": waste,
    }


def _dead_tile_pass(
    spec: KernelSpec, threshold: float
) -> Tuple[List[Finding], Optional[Dict[str, float]]]:
    stats = dead_tile_stats(spec)
    if stats is None or stats["waste_fraction"] <= threshold:
        return [], stats
    return [make_finding(
        "kernel-dead-tiles",
        path=spec.name,
        message=(
            f"{stats['waste_fraction']:.0%} of the live tiles' FLOPs "
            f"fall on causally-masked elements at this tile shape "
            f"({int(stats['live_tiles'])}/{int(stats['total_tiles'])} "
            f"tiles live) — above the {threshold:.0%} bound"
        ),
    )], stats


# ---------------------------------------------------------------------------
# Compile-free roofline / cost model
# ---------------------------------------------------------------------------


def _live_cells(spec: KernelSpec) -> float:
    """Grid cells that execute their compute body (causal dead tiles
    are ``pl.when``-skipped; every cell still pays DMA + grid cost)."""
    total = float(spec.cells())
    stats = dead_tile_stats(spec)
    if stats is None or stats["total_tiles"] == 0:
        return total
    return total * stats["live_tiles"] / stats["total_tiles"]


def _fetch_count(arg: BlockArg, grid: Tuple[int, ...]) -> int:
    """How many times the pipeline re-fetches this operand's block over
    the row-major grid walk — exact (simulated) on small grids, else
    the dependence-probe bound: a map depending on axes up to ``a``
    re-fetches once per distinct prefix, i.e. ``prod(grid[:a+1])``."""
    total = 1
    for g in grid:
        total *= g
    if total <= _COVERAGE_CELL_CAP:
        fetches, prev = 0, None
        for cell in np.ndindex(*grid):
            idx = _eval_map(arg, cell)
            if idx != prev:
                fetches += 1
                prev = idx
        return fetches
    base = tuple(0 for _ in grid)
    ref = _eval_map(arg, base)
    deepest = -1
    for a, g in enumerate(grid):
        if g <= 1:
            continue
        probe = list(base)
        probe[a] = g - 1
        if _eval_map(arg, tuple(probe)) != ref:
            deepest = a
    count = 1
    for g in grid[: deepest + 1]:
        count *= g
    return count


def roofline(
    spec: KernelSpec, device_kind: Optional[str] = None
) -> Dict[str, float]:
    """Static FLOPs/bytes → arithmetic intensity, ceiling, bound
    verdict, and a predicted time/TFLOP/s for this config, against
    :mod:`apex_tpu.observability.meter`'s shared peak table."""
    from apex_tpu.observability import meter

    kind = device_kind if device_kind is not None else _local_device_kind()
    peak_flops = meter.peak_flops_for(kind)
    peak_bw = meter.peak_hbm_bandwidth_for(kind)

    flops = spec.flops_per_cell * _live_cells(spec)
    bytes_moved = sum(
        _fetch_count(a, spec.grid) * a.block_bytes()
        for a in spec.blocked()
    )
    compute_s = flops / peak_flops
    memory_s = bytes_moved / peak_bw
    grid_s = spec.cells() * _GRID_STEP_SECONDS
    time_s = max(compute_s, memory_s) + grid_s
    ai = flops / bytes_moved if bytes_moved else math.inf
    bound = "grid"
    if grid_s < max(compute_s, memory_s):
        bound = "compute" if compute_s >= memory_s else "memory"
    return {
        "flops": flops,
        "bytes": float(bytes_moved),
        "arithmetic_intensity": ai,
        "ceiling_tflops": min(peak_flops, ai * peak_bw) / 1e12,
        "predicted_time_s": time_s,
        "predicted_tflops": (flops / time_s / 1e12) if time_s else 0.0,
        "bound": bound,
        "grid_cells": float(spec.cells()),
    }


def _local_device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return ""


def _local_vmem_budget(device_kind: Optional[str]) -> int:
    from apex_tpu.observability import meter

    kind = device_kind if device_kind is not None else _local_device_kind()
    return meter.vmem_bytes_for(kind)


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def analyze(
    specs,
    *,
    device_kind: Optional[str] = None,
    vmem_budget: Optional[int] = None,
    dead_tile_threshold: float = 0.25,
    name: str = "",
) -> Report:
    """Run every kernel pass over one :class:`KernelSpec` (or a
    sequence — e.g. the fwd+dkdv+dq triple of one flash config) and
    return a :class:`~apex_tpu.analysis.findings.Report` whose
    ``sections["kernels"]`` carries the per-kernel VMEM footprint,
    roofline verdict, and dead-tile accounting."""
    import time as _time

    if isinstance(specs, KernelSpec):
        specs = [specs]
    specs = list(specs)
    budget = (
        vmem_budget if vmem_budget is not None
        else _local_vmem_budget(device_kind)
    )
    report = Report(
        target=name or "+".join(s.name for s in specs),
        rules_run=KERNEL_PASSES,
    )
    kernels_section: List[dict] = []
    timings = {p: 0.0 for p in KERNEL_PASSES}
    for spec in specs:
        entry = {
            "name": spec.name,
            "grid": list(spec.grid),
            "vmem": vmem_footprint(spec),
            "vmem_budget_bytes": budget,
        }
        for pass_name, fn in (
            ("kernel-vmem", lambda s: _vmem_pass(s, budget)),
            ("kernel-tiling", _tiling_pass),
            ("kernel-coverage", _coverage_pass),
        ):
            t0 = _time.perf_counter()
            report.extend(fn(spec))
            timings[pass_name] += (_time.perf_counter() - t0) * 1e3
        t0 = _time.perf_counter()
        findings, stats = _dead_tile_pass(spec, dead_tile_threshold)
        report.extend(findings)
        timings["kernel-dead-tiles"] += (_time.perf_counter() - t0) * 1e3
        if stats is not None:
            entry["dead_tiles"] = stats
        entry["roofline"] = roofline(spec, device_kind)
        kernels_section.append(entry)
    report.pass_timings.update(timings)
    report.sections["kernels"] = kernels_section
    return report


def predict_config(
    specs: Sequence[KernelSpec],
    *,
    device_kind: Optional[str] = None,
    vmem_budget: Optional[int] = None,
) -> Dict[str, object]:
    """One candidate config's verdict for the tuner: ``feasible``
    (no ERROR finding from the vmem/tiling/coverage passes),
    ``time_s``/``flops``/``tflops`` summed over the config's kernels
    (a step dispatches them back to back), and the report itself."""
    report = analyze(
        specs, device_kind=device_kind, vmem_budget=vmem_budget
    )
    time_s = flops = 0.0
    for entry in report.sections["kernels"]:
        time_s += entry["roofline"]["predicted_time_s"]
        flops += entry["roofline"]["flops"]
    return {
        "feasible": not report.errors(),
        "time_s": time_s,
        "flops": flops,
        "tflops": (flops / time_s / 1e12) if time_s else 0.0,
        "report": report,
    }


# ---------------------------------------------------------------------------
# The three shipped kernels at their default configs — the CI surface
# ---------------------------------------------------------------------------


def default_kernel_specs() -> List[Tuple[str, List[KernelSpec]]]:
    """(label, specs) for the shipped kernels at the configs the bench
    actually dispatches: flash attention at the long-context bench
    shape (tuned tiles resolve exactly as dispatch would), fused
    layer-norm at the BERT row/hidden shape, and paged decode at the
    ``ServeConfig`` pool defaults."""
    from apex_tpu.ops.pallas import decode_attention as da
    from apex_tpu.ops.pallas import flash_attention as fa
    from apex_tpu.ops.pallas import layer_norm as ln

    # bench.py --config long_attn: b=1 h=8 s=16384 d=128 causal
    flash = fa.kernel_specs(8, 16384, 16384, 128, causal=True)
    # tools/ln_tune.py's measurement shape: 16384 rows, BERT hidden
    norm = ln.kernel_specs(16384, 1024)
    # serve.ServeConfig defaults: page_size=16, num_pages=128,
    # max_batch=4, max_pages_per_seq=8; a 128-wide 8-head attention
    decode = da.kernel_specs(
        4, 8, 128, pool_pages=128, page=16, pages_per_seq=8,
    )
    return [
        ("flash_attention", flash),
        ("layer_norm", norm),
        ("decode_attention", decode),
    ]


def analyze_default_kernels(
    *,
    device_kind: Optional[str] = None,
    vmem_budget: Optional[int] = None,
    dead_tile_threshold: float = 0.25,
) -> Report:
    """Analyze all three shipped kernels at their default configs into
    one merged report — the ``tools/kernel_lint.py`` /
    ``verify_tier1.sh`` LINT / ``bench.py --lint`` surface."""
    merged: Optional[Report] = None
    kernels_section: List[dict] = []
    for label, specs in default_kernel_specs():
        rep = analyze(
            specs, device_kind=device_kind, vmem_budget=vmem_budget,
            dead_tile_threshold=dead_tile_threshold, name=label,
        )
        for entry in rep.sections["kernels"]:
            kernels_section.append({"config": label, **entry})
        if merged is None:
            merged = rep
        else:
            merged.merge(rep)
    assert merged is not None
    merged.target = "kernels"
    merged.sections["kernels"] = kernels_section
    return merged


def publish_kernel_report(report: Report) -> None:
    """Gauge the kernel verdicts onto the observability board
    (``analysis/kernels/...``) beside the graph-lint counts, so kernel
    regressions ride the same JSONL telemetry: per-kernel VMEM bytes,
    predicted TFLOP/s, dead-tile waste, plus the standard
    errors/warnings/rule counters from
    :func:`apex_tpu.analysis.publish_report`."""
    from apex_tpu.analysis import publish_report

    publish_report(report, prefix="analysis/kernels")
    try:
        from apex_tpu.observability.metrics import board
    except ImportError:  # pragma: no cover - partial install
        return
    for entry in report.sections.get("kernels", []):
        key = entry["name"]
        board.set(
            f"analysis/kernels/{key}/vmem_bytes",
            entry["vmem"]["total_bytes"],
        )
        board.set(
            f"analysis/kernels/{key}/predicted_tflops",
            round(entry["roofline"]["predicted_tflops"], 3),
        )
        if "dead_tiles" in entry:
            board.set(
                f"analysis/kernels/{key}/dead_tile_waste",
                round(entry["dead_tiles"]["waste_fraction"], 4),
            )
