"""Findings — the structured currency of every analysis pass.

A pass never prints: it returns :class:`Finding` records (rule id,
severity, op path, message, fix hint) that a :class:`Report` aggregates.
The CLI (``tools/graph_lint.py``), the benchmark harness (``bench.py
--lint``), the CI gate (``tools/verify_tier1.sh``), and the test
fixtures (``tests/test_analysis.py``) all consume the same records, so
"what did the linter say" has exactly one schema.

The rule catalog (:data:`RULES`) is the single source of truth for rule
ids, default severities, and fix hints — ``docs/analysis.md`` documents
it row by row, and a pass emitting an uncataloged rule id is a bug
(:func:`make_finding` raises).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

__all__ = [
    "ERROR",
    "WARNING",
    "INFO",
    "RULES",
    "Finding",
    "Report",
    "make_finding",
]

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 2, WARNING: 1, INFO: 0}

#: rule id -> (default severity, what it means, how to fix it).
#: Rule ids are namespaced ``<pass>-<defect>``; ``rules=("transfer",)``
#: selects every rule of the transfer pass.
RULES: Dict[str, Tuple[str, str, str]] = {
    "transfer-callback": (
        ERROR,
        "host callback primitive traced into the step "
        "(jax.debug.print / pure_callback / io_callback): every "
        "execution round-trips device->host",
        "move host I/O out of the jitted step; accumulate device-side "
        "via observability.MetricRegistry and fetch on a cadence",
    ),
    "transfer-hlo-host": (
        ERROR,
        "compiled HLO contains a host transfer op (infeed/outfeed, "
        "host send/recv, or a python-callback custom-call)",
        "the step program must be self-contained on device; feed data "
        "as arguments and read results from outputs",
    ),
    "promotion-f64": (
        ERROR,
        "an op inside the step produces float64 — on TPU every f64 op "
        "is emulated and silently doubles memory and wire bytes",
        "drop the f64 literal / enable-x64 dependence; use f32 "
        "(or the amp policy's compute dtype) explicitly",
    ),
    "promotion-widen": (
        WARNING,
        "value widened past the active amp policy's compute dtype "
        "(e.g. bf16 -> f32) — a silent promotion defeats the policy's "
        "memory/MXU savings",
        "if accidental, keep literals weakly typed (python floats) or "
        "cast them to the compute dtype; if intentional accumulation, "
        "wrap the region in jax.named_scope containing 'f32' "
        "(e.g. 'f32_accum') to mark it policy-exempt",
    ),
    "donation-dropped": (
        ERROR,
        "buffers declared in donate_argnums were NOT aliased by XLA "
        "in the compiled buffer assignment — the step silently holds "
        "two copies (e.g. doubled optimizer memory)",
        "make donated inputs match an output's shape/dtype/layout "
        "exactly (return the updated buffer, keep dtypes stable), or "
        "drop them from donate_argnums",
    ),
    "retrace": (
        ERROR,
        "the step recompiled mid-run: its abstract signature (tree "
        "structure / shapes / dtypes / static values) changed across "
        "calls, paying a full XLA compile each time",
        "pad inputs to a fixed shape, hoist changing python values out "
        "of the step or mark them static, and keep the state tree "
        "structure constant",
    ),
    "collective-count": (
        ERROR,
        "compiled collective count differs from the comm engine's "
        "promise (e.g. a chunked sync should compile to exactly 2K "
        "collectives)",
        "check wire/chunks knobs against docs/comm.md; a fused or "
        "duplicated collective means XLA restructured the sync",
    ),
    "collective-bytes": (
        ERROR,
        "collective payload bytes differ from the promised wire plan "
        "(quantized wires must shrink bytes, not just relabel dtypes)",
        "verify the wire format actually applied (int8 payloads carry "
        "codes+scales); compare against comm.ring_wire_bytes",
    ),
    "collective-dtype": (
        ERROR,
        "a collective moves a wider dtype than the configured wire "
        "format (e.g. f32 payloads where wire='int8' was requested)",
        "ensure encode happens before the collective; a stray cast "
        "upstream re-widens the payload",
    ),
    "sharding-replicated": (
        ERROR,
        "a large param/optimizer-state leaf the plan shards compiled "
        "FULLY REPLICATED — GSPMD silently replicates anything "
        "propagation can't decide, and every device pays the whole "
        "tensor",
        "pass the leaf's NamedSharding via in_shardings (build the "
        "tree with analysis.sharding.match_partition_rules) and make "
        "sure no with_sharding_constraint downstream contradicts it",
    ),
    "sharding-mismatch": (
        ERROR,
        "a leaf's compiled tiling disagrees with its declared "
        "PartitionSpec — the plan did not survive compilation (wrong "
        "axis, transposed factors, or a constraint overrode it)",
        "align the rule table with the in_shardings actually passed; "
        "check with_sharding_constraint calls inside the step for "
        "conflicting specs",
    ),
    "sharding-unverified": (
        WARNING,
        "the plan names a multi-device mesh but the module compiled "
        "single-partition — conformance cannot be proven on this "
        "compile (a clean verdict here would be a lie)",
        "compile on the real mesh (or mock it: "
        "XLA_FLAGS=--xla_force_host_platform_device_count=N) before "
        "trusting the plan",
    ),
    "reshard-unplanned": (
        ERROR,
        "the step body contains a collective the declared plan does "
        "not predict — the signature of a weight all-gather or "
        "reshard XLA inserted because a spec didn't survive "
        "propagation (a 'sharded' run secretly paying replicated "
        "wire traffic every step)",
        "trace the named op back to its source; either fix the "
        "sharding so the gather disappears, or declare it in the "
        "plan if the reshard is intentional",
    ),
    "reshard-plan": (
        ERROR,
        "a planned collective's compiled count/bytes/wire dtype "
        "drifted from the declaration (e.g. a K-chunk int8 sync that "
        "compiled to f32 payloads, or twice the promised bytes)",
        "compare against the engine's declared plan "
        "(DistributedDataParallel.collective_plan / the ZeRO "
        "optimizers'); check wire/chunks knobs against docs/comm.md",
    ),
    "memory-budget": (
        ERROR,
        "the static peak-HBM estimate of the compiled step exceeds "
        "the configured budget — the program OOMs before the first "
        "step produces a number",
        "shard the top-attributed buffers (the finding names them), "
        "donate the update buffers, lower the batch/context, or "
        "raise the budget if the device really has the headroom",
    ),
    "sharding-implicit-replication": (
        WARNING,
        "a pjit/jit call site passes in_shardings=None — every array "
        "arrives fully replicated and GSPMD must re-derive (or "
        "silently skip) the partitioning the caller intended",
        "pass explicit in_shardings (build the spec tree with "
        "analysis.sharding.match_partition_rules) so the plan is "
        "declared, and lintable, at the call site",
    ),
    "sharding-missing-constraint": (
        WARNING,
        "a pjit/shard_map region with large contractions never pins "
        "an intermediate with with_sharding_constraint — GSPMD must "
        "guess activation layouts, and a wrong guess inserts "
        "resharding collectives mid-step",
        "pin the big intermediates (post-attention, post-MLP) with "
        "jax.lax.with_sharding_constraint; verify with "
        "tools/shard_report.py",
    ),
    "kernel-vmem-overflow": (
        ERROR,
        "a Pallas kernel config's static VMEM footprint "
        "(double-buffered input/output blocks + scratch + in-kernel "
        "intermediates at true dtype widths) exceeds the backend's "
        "on-chip VMEM — Mosaic either fails to lower or spills, and "
        "either way the config is dead on arrival",
        "shrink block_q/block_k (the f32 score tile is the dominant "
        "term: bytes ~ 4*block_q*block_k); tools/attn_tune.py --prune "
        "drops such cells before they waste a compile",
    ),
    "kernel-tile-misaligned": (
        ERROR,
        "a kernel block shape violates the TPU tile quantum (last dim "
        "a 128-lane multiple, second-to-last a dtype-sublane "
        "multiple, full-axis blocks exempt), leaves a ragged tail the "
        "kernel has no masking for, or feeds the 128x128 MXU a "
        "non-128 contraction extent (sub-tile passes do dead work)",
        "pick power-of-two tiles >= 128 that divide the padded "
        "sequence; the caller-side padding contracts are "
        "ops.attention._seq_pad / _pad_head_dim",
    ),
    "kernel-grid-oob": (
        ERROR,
        "a kernel BlockSpec index map, evaluated over the full grid, "
        "produces a block offset outside the operand's block grid — "
        "the DMA would read or write out of the array's bounds",
        "fix the index map's arithmetic (or the grid extent that "
        "drives it); the finding names the first offending grid cell",
    ),
    "kernel-block-race": (
        ERROR,
        "two grid cells that differ along a PARALLEL grid dimension "
        "write the same output block — parallel dims carry no "
        "ordering or accumulation semantics, so the result depends on "
        "scheduling (revisits along 'arbitrary' dims accumulating in "
        "scratch are the sanctioned pattern and do not flag)",
        "make the racing grid axis 'arbitrary' in dimension_semantics "
        "and accumulate in VMEM scratch with a final-iteration write, "
        "or give each parallel cell a distinct output block",
    ),
    "kernel-dead-tiles": (
        WARNING,
        "a causal kernel config wastes more than the configured "
        "fraction of its live-tile FLOPs on masked elements — tiles "
        "straddling the causal boundary pay full matmuls for a "
        "triangle of zeros (a whole-seq tile wastes ~50%)",
        "smaller (or rectangular) tiles track the causal boundary "
        "more tightly; weigh against per-tile grid overhead with "
        "tools/attn_tune.py --prune --dry-run's predicted ranking",
    ),
    "kernel-hardcoded-block": (
        WARNING,
        "a call site passes a literal block_q=/block_k= tile size, "
        "bypassing the tuned-tile lookup (APEX_TPU_TUNE_CACHE -> "
        "_TUNED_TILES -> heuristic) — the number was right on one "
        "chip/shape and silently wrong everywhere else",
        "drop the literal so dispatch consults the tuning cache, or "
        "commit the measured winner via tools/attn_tune.py "
        "--cache-out / the _TUNED_TILES table",
    ),
    "race-unlocked-shared-state": (
        ERROR,
        "an attribute reachable from both a thread body and the main "
        "path is written without holding the class's lock — a torn or "
        "stale read is a scheduling accident away, and the GIL only "
        "protects single bytecodes, not invariants spanning fields",
        "guard every mutation with the class's lock (use "
        "observability.TrackedLock so the runtime sanitizer sees it); "
        "keep blocking calls (queue put/join) OUTSIDE the held region",
    ),
    "race-nonatomic-counter": (
        ERROR,
        "a read-modify-write counter (x += 1 and friends) is updated "
        "from both a thread body and the main path without a lock — "
        "the load/store pair is not atomic, so concurrent updates "
        "silently lose increments",
        "wrap the update in the class's lock (a TrackedLock keeps the "
        "sanitizer's lock-order graph complete), or move the counter "
        "to the single owning thread",
    ),
    "race-lock-across-blocking": (
        ERROR,
        "a lock is held across a blocking hand-off (bounded-queue "
        "put/join, future result) while a consumer thread needs the "
        "same lock to make progress — the classic two-party deadlock "
        "shape: the holder waits on the queue, the drainer waits on "
        "the lock",
        "shrink the critical section so the blocking call happens "
        "after release; snapshot what the hand-off needs under the "
        "lock, then put/join outside it",
    ),
    "replay-wall-clock": (
        ERROR,
        "a wall-clock read (time.time / datetime.now) in a "
        "replay-critical module — bit-identical replay (the SERVE/"
        "GOODPUT/FLEET gates) requires every time source to be "
        "time.monotonic or the drill's virtual clock; wall time "
        "diverges across runs and hosts",
        "use time.monotonic() (durations) or the injected virtual "
        "clock (scheduling); waive an audited telemetry-only site "
        "with '# lint: allow(replay-wall-clock): <reason>'",
    ),
    "replay-unseeded-rng": (
        ERROR,
        "module-level RNG (random.*, np.random.*) in a replay-critical "
        "module draws from hidden global state — two replays of the "
        "same request stream sample different numbers, breaking "
        "bit-identical replay",
        "thread an explicit seeded generator (np.random.default_rng("
        "seed), random.Random(seed), or jax.random keys) through the "
        "call path; never the module-level functions",
    ),
    "replay-set-order": (
        ERROR,
        "iteration over a set feeds a scheduling/ordering decision in "
        "a replay-critical module — set order is hash-seed dependent "
        "(PYTHONHASHSEED), so admission/eviction order differs across "
        "processes and replay diverges",
        "iterate sorted(the_set) (or keep an explicitly ordered "
        "list/dict — dicts preserve insertion order) wherever the "
        "order can influence scheduling",
    ),
    "replay-env-read": (
        ERROR,
        "os.environ is read inside a step/tick body of a "
        "replay-critical module — per-step environment reads make the "
        "replayed run depend on live process state instead of the "
        "recorded configuration",
        "resolve env knobs ONCE at construction (__init__ / from_env /"
        " a resolve_* helper) and carry the value; the step path "
        "reads only captured config",
    ),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect: rule id + severity + where + what + how to fix."""

    rule: str
    severity: str
    path: str  # op path: name_stack, HLO op name, or file:line
    message: str
    hint: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        loc = f" @ {self.path}" if self.path else ""
        hint = f"\n    fix: {self.hint}" if self.hint else ""
        return f"[{self.severity.upper()}] {self.rule}{loc}: " \
               f"{self.message}{hint}"


def make_finding(
    rule: str,
    path: str,
    message: str,
    severity: Optional[str] = None,
    hint: Optional[str] = None,
) -> Finding:
    """Build a :class:`Finding` with catalog defaults for severity/hint.

    Raises ``KeyError`` on a rule id missing from :data:`RULES` — passes
    may not invent rules the catalog (and docs) don't know.
    """
    default_sev, _desc, default_hint = RULES[rule]
    return Finding(
        rule=rule,
        severity=severity or default_sev,
        path=path,
        message=message,
        hint=default_hint if hint is None else hint,
    )


class Report:
    """Ordered collection of findings from one ``check()`` run."""

    def __init__(
        self,
        findings: Optional[List[Finding]] = None,
        target: str = "",
        rules_run: Tuple[str, ...] = (),
    ):
        self.findings: List[Finding] = list(findings or [])
        self.target = target
        self.rules_run = tuple(rules_run)
        #: pass name -> milliseconds spent, filled by the check runner
        #: (one entry per rules_run pass, pinned in tests)
        self.pass_timings: Dict[str, float] = {}
        #: extra top-level artifact sections (peak_hbm_bytes,
        #: shard_plan, ...) merged into :meth:`to_json` — see
        #: ``analysis.attach_shard_sections``
        self.sections: Dict[str, object] = {}
        #: the optimized-HLO text the HLO-level passes read (set by
        #: check()/lint_hlo; None for pure-jaxpr reports) — kept so
        #: artifact builders don't pay a second compile
        self.hlo_text: Optional[str] = None

    def extend(self, findings) -> None:
        self.findings.extend(findings)

    def merge(self, other: "Report") -> "Report":
        """Fold another report's findings AND bookkeeping (pass
        timings summed per pass, rules_run unioned) into this one —
        what multi-program surfaces (``tools/graph_lint.py``,
        ``engine.lint()``) use instead of a bare ``extend`` that
        would drop the second report's timing/pass record."""
        self.findings.extend(other.findings)
        for name in other.rules_run:
            if name not in self.rules_run:
                self.rules_run = self.rules_run + (name,)
        for name, ms in other.pass_timings.items():
            self.pass_timings[name] = self.pass_timings.get(name, 0.0) + ms
        return self

    def deduped(self) -> List[Finding]:
        """Findings unique by (rule, location) — two passes (or two
        substrates of one pass) reporting the same defect at the same
        site count once.  Order preserved; first occurrence wins."""
        seen, out = set(), []
        for f in self.findings:
            key = (f.rule, f.path)
            if key in seen:
                continue
            seen.add(key)
            out.append(f)
        return out

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    def by_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def rule_ids(self):
        return sorted({f.rule for f in self.findings})

    def ok(self, fail_on: str = ERROR) -> bool:
        """True when no finding reaches ``fail_on`` severity."""
        bar = _SEVERITY_ORDER[fail_on]
        return not any(
            _SEVERITY_ORDER[f.severity] >= bar for f in self.findings
        )

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        out = {
            "target": self.target,
            "rules_run": list(self.rules_run),
            "errors": len(self.errors()),
            "warnings": len(self.warnings()),
            "pass_timings": dict(self.pass_timings),
            "findings": [f.to_json() for f in self.findings],
        }
        for key, value in self.sections.items():
            out.setdefault(key, value)
        return out

    def to_json_line(self) -> str:
        return json.dumps(self.to_json())

    def render(self) -> str:
        head = f"graph lint: {self.target or '<step>'} — " \
               f"{len(self.errors())} error(s), " \
               f"{len(self.warnings())} warning(s)"
        if not self.findings:
            return head + " — clean"
        return "\n".join([head] + [f"  {f.render()}" for f in self.findings])

    def __repr__(self):
        return (
            f"Report(target={self.target!r}, errors={len(self.errors())}, "
            f"warnings={len(self.warnings())}, rules={self.rule_ids()})"
        )
