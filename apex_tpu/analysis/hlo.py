"""Optimized-HLO text parsing — the ONE implementation every consumer
of compiled-program structure reads through.

Grew out of the gradient-sync engine's verification hooks
(``apex_tpu/parallel/comm.py``, which now re-exports from here) and the
``tools/comm_structure.py`` artifact generator's overlap scanner; the
analysis passes (:mod:`apex_tpu.analysis.passes`) added buffer-donation
aliasing and host-transfer scans.  Everything operates on the text of
``jit(fn).lower(...).compile().as_text()`` — the backend-agnostic way
to audit what XLA actually scheduled (GSPMD prints the same collective
structure on the CPU mesh as on a pod; see ``tools/comm_structure.py``).

Contents:

- :func:`shape_bytes` / :func:`async_start_result` — HLO shape-string
  arithmetic.
- :func:`collective_summary` / :func:`collective_dtypes` /
  :func:`ring_wire_bytes` — per-kind collective counts, payload bytes
  and dtypes, and the ring-algorithm traffic model.
- :func:`overlap_collect` — which collectives' schedule windows overlap
  compute (the serial-bytes model's refinement).
- :func:`input_output_aliases` — the buffer-donation aliasing XLA
  actually committed to (the donation lint's ground truth).
- :func:`host_transfer_ops` — infeed/outfeed/host send-recv/callback
  custom-calls (the transfer lint's HLO-level ground truth).
- :func:`parse_computations` / :func:`instruction_flops` /
  :func:`instruction_bytes` — the per-instruction reader + cost
  primitives behind step-time attribution
  (:mod:`apex_tpu.observability.attribution`): every instruction as a
  structured record, and the FLOP/byte estimate of one instruction
  from its printed shapes (XLA prints operand shapes inline at every
  use site, so no cross-reference pass is needed).
- :func:`parameter_shardings` / :func:`parse_sharding` /
  :func:`num_partitions` — the GSPMD sharding each ENTRY parameter
  actually compiled with (the sharding-conformance pass's ground
  truth: a ``sharding={replicated}`` on a tensor the plan shards is
  the silent-replication defect).
- :func:`collective_instructions` / :func:`replica_group_size` —
  every collective as a structured record (kind, payload bytes,
  dtypes, replica groups, jax op path), for the per-mesh-axis
  resharding pass.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

__all__ = [
    "DTYPE_BYTES",
    "COLLECTIVE_KINDS",
    "shape_bytes",
    "async_start_result",
    "collective_summary",
    "collective_dtypes",
    "ring_wire_bytes",
    "overlap_collect",
    "input_output_aliases",
    "host_transfer_ops",
    "parse_computations",
    "shape_dims",
    "shape_elements",
    "instruction_flops",
    "instruction_bytes",
    "num_partitions",
    "parameter_shardings",
    "parse_sharding",
    "collective_instructions",
    "replica_group_size",
]

DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1, "u64": 8, "u32": 4,
    "u16": 2, "u8": 1, "pred": 1,
}

COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_KINDS_ALT = "|".join(COLLECTIVE_KINDS)

# shape alternative allows one level of tuple nesting: variadic combined
# async ops (XLA's collective combiners) print ((op0, op1), (res0, res1))
# — a flat [^)]* would stop at the first ')' and silently drop the op
_DEF_RE = re.compile(
    r"(?:ROOT\s+)?%?[\w.-]+\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|[^\s]+)\s+"
    rf"({_KINDS_ALT})(-start|-done)?\("
)


def shape_bytes(shape: str) -> int:
    """bytes of an HLO shape string like 'bf16[8,128,1024]' (tuples:
    sum of elements)."""
    total = 0
    for dt, dims in re.findall(r"(\w+)\[([0-9,]*)\]", shape):
        if dt not in DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * DTYPE_BYTES[dt]
    return total


def async_start_result(shape: str) -> str:
    """Result element of an async ``-start`` op's tuple shape
    ``(operand(s), result(s)[, contexts...])`` — the second TOP-LEVEL
    element, which for a variadic combined op is itself a tuple whose
    arrays all count.  Depth tracking covers ALL bracket kinds: shape
    strings carry commas inside dims (``[8,128]``) and layouts
    (``{1,0}``), not just nested tuples."""
    if not shape.startswith("("):
        return shape
    parts, depth, cur = [], 0, []
    for ch in shape[1:-1]:
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
            continue
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        cur.append(ch)
    parts.append("".join(cur))
    return parts[1] if len(parts) > 1 else parts[0]


def collective_summary(hlo_text: str) -> dict:
    """Per-kind ``{count, bytes}`` for every collective in optimized HLO.

    Bytes are the shape printed at each op's definition site — the
    RESULT: the full buffer for all-gather/all-to-all, the local shard
    for reduce-scatter (feed :func:`ring_wire_bytes` for a
    notation-normalized traffic number).  Async ``-start``/``-done``
    pairs count once, at ``-start``, with the result element of the
    start tuple.
    """
    out = {}
    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line.strip())
        if not m:
            continue
        shape, kind, variant = m.group(1), m.group(2), m.group(3)
        if variant == "-done":
            # async pairs are counted once, at -start
            continue
        if variant == "-start":
            # -start returns (operand(s), result(s)[, contexts]); keep
            # only the result element so bytes match the sync form
            shape = async_start_result(shape)
        rec = out.setdefault(kind, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += shape_bytes(shape)
    return out


def collective_dtypes(hlo_text: str) -> Dict[str, set]:
    """Per-kind set of element dtypes each collective's result moves —
    the collective-consistency pass checks these against the configured
    wire format (an int8 wire must move s8/f32-scale payloads, never a
    full-width f32 gradient buffer)."""
    out: Dict[str, set] = {}
    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line.strip())
        if not m:
            continue
        shape, kind, variant = m.group(1), m.group(2), m.group(3)
        if variant == "-done":
            continue
        if variant == "-start":
            shape = async_start_result(shape)
        dts = out.setdefault(kind, set())
        for dt, _dims in re.findall(r"(\w+)\[([0-9,]*)\]", shape):
            if dt in DTYPE_BYTES:
                dts.add(dt)
    return out


def ring_wire_bytes(summary: dict, world: int) -> float:
    """Per-chip wire traffic (bytes sent) implied by a
    :func:`collective_summary`, under ring algorithms — normalized for
    XLA's result-shape notation so f32 and quantized paths compare
    apples-to-apples: reduce-scatter prints the SHARD (traffic =
    ``(world-1) * shard``), all-gather/all-to-all print the FULL buffer
    (traffic = ``(world-1)/world * full``), all-reduce streams twice.
    """
    t = 0.0
    for kind, rec in summary.items():
        b = rec["bytes"]
        if kind == "all-reduce":
            t += 2.0 * b * (world - 1) / world
        elif kind == "reduce-scatter":
            t += b * (world - 1)
        elif kind in ("all-gather", "all-to-all"):
            t += b * (world - 1) / world
        elif kind == "collective-permute":
            t += b  # one hop
    return t


# ---------------------------------------------------------------------------
# schedule-overlap windows (from tools/comm_structure.py)
# ---------------------------------------------------------------------------

_COMPUTE_OP_RE = re.compile(
    r"=\s*(?:\([^=]*\)|\S+)\s+(?:fusion|convolution|custom-call|dot)\("
)

_START_RE = re.compile(
    r"%?([\w.-]+)\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|[^\s]+)\s+"
    rf"(?:{_KINDS_ALT})-start\("
)
_DONE_RE = re.compile(rf"(?:{_KINDS_ALT})-done\(\s*%?([\w.-]+)")
_SYNC_RE = re.compile(
    r"%?([\w.-]+)\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|[^\s]+)\s+"
    rf"(?:{_KINDS_ALT})\("
)


def overlap_collect(hlo_text: str) -> dict:
    """Which collectives' windows overlap compute (VERDICT r4 #6).

    The serial-bytes model (:func:`ring_wire_bytes`) assumes every
    collective blocks; XLA actually schedules collectives concurrently
    with independent compute, so that number is an upper bound.  This
    pass walks the optimized HLO in program order and measures each
    collective's *window*:

    * async ``-start``/``-done`` pairs (TPU-scheduled HLO): the window
      is start→done; compute issued inside it is overlap the scheduler
      already committed to.
    * sync collectives (CPU HLO prints these even where the TPU backend
      would go async): the window is the op→its first consumer; compute
      ops strictly inside are provably independent of the result (they
      issue before anything uses it), so an async backend can hide the
      collective behind them — the *overlappable* fraction.

    A collective is counted overlapped if ≥1 compute op (post-fusion:
    ``fusion``/``dot``/``convolution``/``custom-call``) issues inside
    its window.  Returns {"async_pairs", "async_bytes", "sync_count",
    "sync_bytes", "overlapped_count", "overlapped_bytes"} where the
    overlapped columns span both forms.
    """
    open_async = {}  # name -> [bytes, saw_compute]
    open_sync = {}   # name -> [bytes, saw_compute]
    out = {
        "async_pairs": 0, "async_bytes": 0,
        "sync_count": 0, "sync_bytes": 0,
        "overlapped_count": 0, "overlapped_bytes": 0,
    }

    def _close(b, saw):
        if saw:
            out["overlapped_count"] += 1
            out["overlapped_bytes"] += b

    for line in hlo_text.splitlines():
        line = line.strip()
        # close sync windows at their first consumer BEFORE counting
        # this line's compute (compute at first-use is not overlap)
        if open_sync:
            rhs = line.split("=", 1)[1] if "=" in line else line
            # sigil-optional, like the definition regexes above: HLO may
            # print operand names with or without '%'
            for name in [
                n for n in open_sync
                if re.search(
                    r"(?<![\w.%-])%?" + re.escape(n) + r"(?![\w.-])", rhs
                )
            ]:
                _close(*open_sync.pop(name))
        m = _START_RE.search(line)
        if m:
            out["async_pairs"] += 1
            b = shape_bytes(async_start_result(m.group(2)))
            out["async_bytes"] += b
            open_async[m.group(1)] = [b, False]
            continue
        m = _DONE_RE.search(line)
        if m and m.group(1) in open_async:
            _close(*open_async.pop(m.group(1)))
            continue
        m = _SYNC_RE.search(line)
        if m:
            out["sync_count"] += 1
            b = shape_bytes(m.group(2))
            out["sync_bytes"] += b
            open_sync[m.group(1)] = [b, False]
            continue
        if _COMPUTE_OP_RE.search(line):
            for rec in open_async.values():
                rec[1] = True
            for rec in open_sync.values():
                rec[1] = True
    # windows that never closed in-text (result only consumed across a
    # computation boundary / ROOT): their window extends to the end of
    # the region, so trailing compute counts
    for b, saw in list(open_async.values()) + list(open_sync.values()):
        _close(b, saw)
    return out


# ---------------------------------------------------------------------------
# buffer-donation aliasing (the donation lint's ground truth)
# ---------------------------------------------------------------------------


def input_output_aliases(hlo_text: str) -> List[Tuple[int, str]]:
    """Parse the module header's ``input_output_alias={ {0}: (2, {},
    may-alias), ... }`` into ``[(param_number, output_index_str), ...]``.

    This is the aliasing XLA COMMITTED to: a ``donate_argnums`` entry
    that does not appear here kept both buffers live.  Absent header
    (nothing aliased) returns ``[]``.
    """
    key = "input_output_alias={"
    start = hlo_text.find(key)
    if start < 0:
        return []
    # balanced-brace span: output indices are themselves brace-wrapped
    i, depth = start + len(key) - 1, 0
    end = i
    for j in range(i, len(hlo_text)):
        ch = hlo_text[j]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                end = j
                break
    body = hlo_text[start + len(key):end]
    out = []
    for m in re.finditer(r"\{([0-9, ]*)\}\s*:\s*\(\s*(\d+)\s*,", body):
        out.append((int(m.group(2)), m.group(1).strip()))
    return out


# ---------------------------------------------------------------------------
# host transfers (the transfer lint's HLO-level ground truth)
# ---------------------------------------------------------------------------

_INSTR_NAME_RE = re.compile(r"^(?:ROOT\s+)?%?([\w.-]+)\s*=")

#: custom-call targets that round-trip through the host python runtime
_CALLBACK_TARGETS = (
    "xla_python_cpu_callback",
    "xla_ffi_python_cpu_callback",
    "xla_python_gpu_callback",
    "CallbackToHost",
)


def host_transfer_ops(hlo_text: str) -> List[Tuple[str, str]]:
    """``[(op_name, why), ...]`` for every op in the HLO that moves data
    between host and device: infeed/outfeed, send/recv marked
    ``is_host_transfer=true``, and python-callback custom-calls."""
    out = []
    for line in hlo_text.splitlines():
        line = line.strip()
        nm = _INSTR_NAME_RE.match(line)
        name = nm.group(1) if nm else "<unnamed>"
        if re.search(
            r"=\s*(?:\((?:[^()]|\([^()]*\))*\)|[^\s]+)\s+"
            r"(infeed|outfeed)\(", line
        ):
            kind = re.search(r"\s(infeed|outfeed)\(", line).group(1)
            out.append((name, kind))
            continue
        if re.search(r"\s(send|recv|send-done|recv-done)\(", line) and \
                "is_host_transfer=true" in line:
            out.append((name, "host send/recv"))
            continue
        if "custom-call" in line:
            tgt = re.search(r'custom_call_target="([^"]+)"', line)
            if tgt and any(t in tgt.group(1) for t in _CALLBACK_TARGETS):
                out.append((name, f"callback custom-call ({tgt.group(1)})"))
    return out


# ---------------------------------------------------------------------------
# per-instruction reader + cost primitives (step-time attribution)
# ---------------------------------------------------------------------------

#: computation header: ``%name (params) -> shape {`` / ``ENTRY %name ...``
_COMP_HEADER_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.-]+)\s+\(.*\)\s*->\s*\S.*\{\s*$"
)

_INSTR_HEAD_RE = re.compile(
    r"^(?:ROOT\s+)?%?([\w.-]+)\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|[^\s]+)\s+"
    r"([\w-]+)\("
)

_SHAPE_IN_TEXT_RE = re.compile(r"(\w+)\[([0-9,]*)\]")

_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')

#: attrs that reference other computations, per container opcode
_CALLED_COMP_RE = re.compile(
    r"(?:calls|to_apply|body|condition|true_computation|"
    r"false_computation)=%?([\w.-]+)"
)


def shape_dims(shape: str) -> List[int]:
    """Dims of the FIRST array in an HLO shape string (``'f32[8,128]
    {1,0}'`` → ``[8, 128]``; scalars → ``[]``; tuples → first element)."""
    m = _SHAPE_IN_TEXT_RE.search(shape)
    if not m:
        return []
    return [int(d) for d in m.group(2).split(",") if d]


def shape_elements(shape: str) -> int:
    """Element count of the first array in a shape string."""
    n = 1
    for d in shape_dims(shape):
        n *= d
    return n


def _balanced_span(text: str, start: int) -> int:
    """Index just past the ')' matching the '(' at ``start``."""
    depth = 0
    for i in range(start, len(text)):
        ch = text[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def parse_computations(hlo_text: str):
    """``(computations, entry_name)`` — every instruction as a record.

    ``computations`` maps computation name → list of instruction dicts
    in program order; each record carries ``name``, ``shape`` (result
    shape string), ``opcode``, ``operands`` (list of operand shape
    strings, as printed inline at the use site), ``operand_names``
    (the ``%name`` tokens of the operand list — the def-use edges the
    memory live-range walk follows), ``op_name`` (the jax source path
    from metadata — named scopes land here), ``called`` (referenced
    computation names for fusion/call/while/conditional), and
    ``attrs`` (the raw text after the operand list, for
    opcode-specific parsing like ``lhs_contracting_dims``).
    """
    comps: Dict[str, List[dict]] = {}
    entry = None
    current: Optional[List[dict]] = None
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if not line:
            continue
        hm = _COMP_HEADER_RE.match(line)
        if hm and " = " not in line.split("{", 1)[0]:
            name = hm.group(2)
            current = comps.setdefault(name, [])
            if hm.group(1):
                entry = name
            continue
        if line == "}":
            current = None
            continue
        im = _INSTR_HEAD_RE.match(line)
        if im is None or current is None:
            continue
        name, shape, opcode = im.group(1), im.group(2), im.group(3)
        open_paren = im.end() - 1
        close = _balanced_span(line, open_paren)
        operand_text = line[open_paren + 1:close - 1]
        attrs = line[close:]
        onm = _OP_NAME_RE.search(attrs)
        current.append({
            "name": name,
            "shape": shape,
            "opcode": opcode,
            "operands": [
                f"{dt}[{dims}]"
                for dt, dims in _SHAPE_IN_TEXT_RE.findall(operand_text)
            ],
            "operand_names": re.findall(r"%([\w.-]+)", operand_text),
            "op_name": onm.group(1) if onm else "",
            "called": _CALLED_COMP_RE.findall(attrs),
            "attrs": attrs,
            "root": line.startswith("ROOT"),
        })
    if entry is None and comps:
        # un-ENTRY'd fragments (tests, hand-written snippets): the last
        # computation is the outermost by HLO printing convention
        entry = next(reversed(comps))
    return comps, entry


#: 1-FLOP-per-element transcendentals/arithmetic (coarse on purpose —
#: attribution consumes relative shares, not absolute cycle counts)
_ELEMENTWISE_OPS = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "power", "remainder", "atan2", "and", "or", "xor", "not",
    "negate", "abs", "sign", "compare", "select", "clamp", "convert",
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "tanh", "logistic", "sqrt", "rsqrt", "cbrt", "sine", "cosine",
    "floor", "ceil", "round-nearest-afz", "round-nearest-even",
    "is-finite", "shift-left", "shift-right-arithmetic",
    "shift-right-logical", "popcnt", "count-leading-zeros",
    "stochastic-convert", "erf",
))

#: pure data movement / bookkeeping: 0 FLOPs, bytes still count
_ZERO_FLOP_OPS = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "reshape", "transpose", "broadcast", "copy",
    "copy-start", "copy-done", "slice", "dynamic-slice",
    "dynamic-update-slice", "concatenate", "gather", "iota", "pad",
    "reverse", "rng", "rng-bit-generator", "after-all", "domain",
    "partition-id", "replica-id", "opt-barrier", "send", "recv",
    "send-done", "recv-done", "infeed", "outfeed", "custom-call",
))

_CONTRACTING_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")


def instruction_flops(instr: dict) -> float:
    """Estimated FLOPs of ONE leaf instruction from its printed shapes.

    - ``dot``: ``2 * result_elements * contracted_elements`` (the lhs
      contracting dims, parsed from the attrs; batch dims are already
      inside the result product).
    - ``convolution``: ``2 * result_elements * kernel_elements /
      out_features`` (out-feature index from ``dim_labels``).
    - elementwise/transcendental: one FLOP per result element.
    - ``reduce``/``reduce-window``: one FLOP per INPUT element.
    - data movement, parameters, collectives, custom-calls: 0 (a
      custom-call's interior is invisible in HLO text; its measured
      time still lands in the right bucket via the trace source).

    Container ops (fusion/call/while/conditional) are costed by the
    caller over their ``called`` computations — see
    :mod:`apex_tpu.observability.attribution`.
    """
    opcode = instr["opcode"]
    if opcode in _ZERO_FLOP_OPS or opcode.startswith(
        ("all-", "reduce-scatter", "collective-")
    ):
        return 0.0
    result_elems = shape_elements(instr["shape"])
    if opcode == "dot":
        contracted = 1
        m = _CONTRACTING_RE.search(instr["attrs"])
        if m and instr["operands"]:
            lhs_dims = shape_dims(instr["operands"][0])
            for idx in m.group(1).split(","):
                if idx and int(idx) < len(lhs_dims):
                    contracted *= lhs_dims[int(idx)]
        return 2.0 * result_elems * contracted
    if opcode == "convolution":
        if len(instr["operands"]) > 1:
            kernel = instr["operands"][1]
            k_elems = shape_elements(kernel)
            out_features = 1
            m = _DIM_LABELS_RE.search(instr["attrs"])
            if m:
                o_idx = m.group(2).find("o")
                kd = shape_dims(kernel)
                if 0 <= o_idx < len(kd):
                    out_features = kd[o_idx]
            elif shape_dims(instr["shape"]):
                out_features = shape_dims(instr["shape"])[-1]
            return 2.0 * result_elems * k_elems / max(1, out_features)
        return 0.0
    if opcode in ("reduce", "reduce-window", "scatter", "sort",
                  "select-and-scatter"):
        src = instr["operands"][0] if instr["operands"] else instr["shape"]
        return float(shape_elements(src))
    if opcode in _ELEMENTWISE_OPS:
        return float(result_elems)
    if opcode in ("map", "fusion", "call", "while", "conditional"):
        return 0.0  # containers: costed over their called computations
    return float(result_elems)  # unknown op: one FLOP/element floor


def instruction_bytes(instr: dict) -> int:
    """HBM-traffic estimate of one instruction: result + operand bytes
    as printed (for a fusion this is exactly the boundary traffic — its
    interior never touches HBM, which is the point of fusing).
    Pointer-shuffling ops (tuple plumbing, bitcasts) move nothing."""
    if instr["opcode"] in (
        "parameter", "constant", "tuple", "get-tuple-element",
        "bitcast", "after-all", "opt-barrier",
    ):
        return 0
    total = shape_bytes(instr["shape"])
    for op_shape in instr["operands"]:
        total += shape_bytes(op_shape)
    return total


# ---------------------------------------------------------------------------
# GSPMD parameter shardings (the sharding-conformance pass's ground truth)
# ---------------------------------------------------------------------------

_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")

_PARAM_RE = re.compile(
    r"^(?:ROOT\s+)?%?([\w.-]+)\s*=\s*(\S+)\s+parameter\((\d+)\)"
)
_SHARDING_ATTR_RE = re.compile(r"sharding=\{([^}]*(?:\{[^}]*\}[^}]*)*)\}")
_TILE_DEVICES_RE = re.compile(r"devices=\[([0-9,]+)\]")


def num_partitions(hlo_text: str) -> int:
    """``num_partitions`` from the module header (1 when absent — a
    single-device compile carries no SPMD structure to verify)."""
    m = _NUM_PARTITIONS_RE.search(hlo_text)
    return int(m.group(1)) if m else 1


def parse_sharding(sharding: Optional[str]) -> dict:
    """Structure one HLO sharding attribute string.

    Returns ``{"kind": "replicated" | "maximal" | "tiled" | "manual" |
    "unknown", "dims": [shards-per-data-dim, ...]}``.  Handles the
    GSPMD print variants::

        replicated
        maximal device=3
        devices=[2,4]<=[8]                          # plain tiling
        devices=[2,1,4]<=[8] last_tile_dim_replicate  # partial replication
        devices=[1,4,2]<=[2,4]T(1,0) last_tile_dim_replicate
        devices=[...] last_tile_dims={manual}       # shard_map interiors

    Trailing subgroup dims (``last_tile_dim_replicate`` /
    ``last_tile_dims={...}``) are dropped from ``dims`` so the result
    is shards-per-DATA-dim — multiply a parameter's printed (local)
    shape by ``dims`` to recover the global logical shape.
    """
    if not sharding:
        return {"kind": "unknown", "dims": []}
    s = sharding.strip()
    if s.startswith("replicated"):
        return {"kind": "replicated", "dims": []}
    if s.startswith("maximal"):
        return {"kind": "maximal", "dims": []}
    m = _TILE_DEVICES_RE.search(s)
    if not m:
        return {"kind": "unknown", "dims": []}
    dims = [int(d) for d in m.group(1).split(",") if d]
    drop = 0
    if "last_tile_dim_replicate" in s:
        drop = 1
    sub = re.search(r"last_tile_dims=\{([^}]*)\}", s)
    if sub:
        drop = len([t for t in sub.group(1).split(",") if t.strip()])
        if "manual" in sub.group(1):
            return {"kind": "manual", "dims": dims[: len(dims) - drop]}
    if drop:
        dims = dims[: len(dims) - drop]
    kind = "tiled"
    if all(d == 1 for d in dims):
        kind = "replicated"  # tiled-in-name-only: one shard per dim
    return {"kind": kind, "dims": dims}


def parameter_shardings(hlo_text: str) -> List[dict]:
    """Every ENTRY-computation parameter as ``{"param": number,
    "name": instr name, "shape": local shard shape string, "op_name":
    jax arg path from metadata ('' when absent), "sharding": raw
    sharding attribute or None, "bytes": local bytes, "global_bytes":
    logical (unsharded) bytes}``, ordered by parameter number.

    The printed shape is the per-device SHARD; ``global_bytes``
    multiplies it back up by the tile counts (replicated parameters
    print the full shape, so local == global there).
    """
    # parse_computations drops the parameter NUMBER (it lives inside
    # the operand parens), so scan entry lines directly
    numbered: List[dict] = []
    in_entry = False
    for raw in hlo_text.splitlines():
        line = raw.strip()
        hm = _COMP_HEADER_RE.match(line)
        if hm and " = " not in line.split("{", 1)[0]:
            in_entry = bool(hm.group(1))
            continue
        if line == "}":
            in_entry = False
            continue
        if not in_entry:
            continue
        m = _PARAM_RE.match(line)
        if not m:
            continue
        name, shape, number = m.group(1), m.group(2), int(m.group(3))
        sh = _SHARDING_ATTR_RE.search(line)
        onm = _OP_NAME_RE.search(line)
        local = shape_bytes(shape)
        parsed = parse_sharding(sh.group(1) if sh else None)
        factor = 1
        for d in parsed["dims"]:
            factor *= d
        numbered.append({
            "param": number,
            "name": name,
            "shape": shape,
            "op_name": onm.group(1) if onm else "",
            "sharding": sh.group(1) if sh else None,
            "bytes": local,
            "global_bytes": local * max(1, factor),
        })
    numbered.sort(key=lambda r: r["param"])
    return numbered


# ---------------------------------------------------------------------------
# per-collective records (the resharding pass's ground truth)
# ---------------------------------------------------------------------------

_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)


def replica_group_size(line: str) -> Optional[int]:
    """Participant count per replica group of one collective line —
    the mesh-axis size the collective spans.  Handles the explicit
    ``{{0,1},{2,3}}`` print and the iota ``[G,S]<=[N]`` form (group
    count G x size S).  None when the op prints no groups (a
    full-world collective on some backends)."""
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        return int(m.group(2))
    m = _REPLICA_GROUPS_RE.search(line)
    if not m:
        return None
    first = m.group(1).split("}", 1)[0].lstrip("{")
    ids = [t for t in first.split(",") if t.strip()]
    return len(ids)


def _replica_groups(line: str) -> Optional[List[List[int]]]:
    """Replica groups of one collective line as explicit id lists, or
    None when the op prints none.  Handles both the explicit
    ``{{0,1},{2,3}}`` print and XLA's compact iota/V2 form
    ``[G,S]<=[dims](T(perm))`` — ``iota(prod(dims)).reshape(dims)
    .transpose(perm).reshape(G, S)``, rows = groups — so axis
    attribution stays exact (not size-based) even where two mesh axes
    share a size and only the iota form was printed."""
    m = _REPLICA_GROUPS_RE.search(line)
    if m:
        groups = []
        for grp in re.findall(r"\{([0-9, ]*)\}", "{" + m.group(1) + "}"):
            ids = [int(t) for t in grp.split(",") if t.strip()]
            if ids:
                groups.append(ids)
        return groups or None
    m = _IOTA_GROUPS_RE.search(line)
    if not m:
        return None
    g, s = int(m.group(1)), int(m.group(2))
    dims = [int(d) for d in m.group(3).split(",") if d]
    total = 1
    for d in dims:
        total *= d
    if total != g * s:
        return None  # malformed print: refuse to guess
    ids = list(range(total))
    if m.group(4):
        perm = [int(p) for p in m.group(4).split(",") if p]
        if sorted(perm) != list(range(len(dims))):
            return None
        # index math of reshape(dims).transpose(perm).flatten()
        strides = [0] * len(dims)
        acc = 1
        for i in range(len(dims) - 1, -1, -1):
            strides[i] = acc
            acc *= dims[i]
        out_dims = [dims[p] for p in perm]
        out_strides = [strides[p] for p in perm]
        ids = []
        idx = [0] * len(out_dims)
        for _ in range(total):
            ids.append(sum(i * st for i, st in zip(idx, out_strides)))
            for ax in range(len(out_dims) - 1, -1, -1):
                idx[ax] += 1
                if idx[ax] < out_dims[ax]:
                    break
                idx[ax] = 0
    return [ids[i * s:(i + 1) * s] for i in range(g)]


def collective_instructions(hlo_text: str) -> List[dict]:
    """Every collective in the module as ``{"name", "kind", "shape",
    "bytes", "dtypes", "group_size", "groups", "op_name"}``, in
    program order.  Async ``-start``/``-done`` pairs count once (at
    ``-start``, with the result element of the start tuple), matching
    :func:`collective_summary`'s counting."""
    out = []
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _DEF_RE.match(line)
        if not m:
            continue
        shape, kind, variant = m.group(1), m.group(2), m.group(3)
        if variant == "-done":
            continue
        if variant == "-start":
            shape = async_start_result(shape)
        nm = _INSTR_NAME_RE.match(line)
        onm = _OP_NAME_RE.search(line)
        dtypes = set()
        for dt, _dims in re.findall(r"(\w+)\[([0-9,]*)\]", shape):
            if dt in DTYPE_BYTES:
                dtypes.add(dt)
        out.append({
            "name": nm.group(1) if nm else "<unnamed>",
            "kind": kind,
            "shape": shape,
            "bytes": shape_bytes(shape),
            "dtypes": dtypes,
            "group_size": replica_group_size(line),
            "groups": _replica_groups(line),
            "op_name": onm.group(1) if onm else "",
        })
    return out
