"""Static peak-HBM estimate + budget gate — OOM as a lint ERROR.

An out-of-memory abort is the most expensive possible way to learn
that a plan doesn't fit: it costs a full compile, a device
allocation storm, and (on a shared pod) everyone else's queue slot.
The compiled module already contains everything needed to know
*before the first step runs*: scheduled HLO (``is_scheduled=true``)
prints instructions in execution order, every definition site carries
its result shape, and every use site names its operands — a classic
linear-scan live-range walk over that text gives a per-buffer
lifetime, and the running sum's maximum is the static peak.

The estimate is deliberately a *model*, not a byte-exact replay of
XLA's buffer assignment (which fuses allocations, colors slices, and
rematerializes): it counts

- **parameters** at their full printed (per-device shard) size, live
  from entry — params, optimizer state, the serve KV page pool
  (static shape, so the pool is budgeted exactly);
- **instruction results** (post-fusion: a fusion's interior never
  materializes, which is the point of fusing) from definition to last
  use — the activations and collective scratch;
- **zero-cost aliases** (tuples, bitcasts, get-tuple-element) at 0;
- **called computations** (while/conditional/call bodies) once,
  recursively, at their call site.

That model is an upper-ish bound on what a non-rematerializing
schedule needs and tracks XLA's own ``temp`` accounting closely
enough to gate on: the point is catching the 2x of a dropped
donation, the Nx of a silently replicated optimizer state, or a KV
pool that never fit — not the last 2%.

Surfaces: :func:`estimate_peak` (the raw estimate + top-K buffer
attribution), :func:`memory_pass` (the ``memory-budget`` lint rule —
``hbm_budget`` bytes on the :class:`~apex_tpu.analysis.passes
.StepGraph`), :func:`publish_peak` (board gauges the
:class:`~apex_tpu.observability.health.MemoryBudgetRule` watchdog
reads), ``tools/shard_report.py`` (the human-readable breakdown) and
the serve engine's build-time gate
(``ServeConfig(hbm_budget_bytes=...)``).
"""

from __future__ import annotations

import functools
import re
from typing import Dict, List, Optional

from apex_tpu.analysis import hlo as hlo_lib
from apex_tpu.analysis.findings import Finding, make_finding

__all__ = [
    "BUFFER_CATEGORIES",
    "categorize_buffer",
    "estimate_peak",
    "memory_pass",
    "publish_peak",
]

#: attribution buckets, in the order reports print them
BUFFER_CATEGORIES = (
    "params", "optimizer", "kv_cache", "inputs", "args",
    "activations", "collective", "constants",
)

#: ops whose "result" is a pointer re-labelling, not an allocation
_ALIAS_OPS = frozenset((
    "tuple", "get-tuple-element", "bitcast", "after-all", "opt-barrier",
    "domain", "parameter",  # parameters are costed separately, up front
))

_OPT_RE = re.compile(
    r"opt|adam|lamb|momentum|velocity|master|\bm\b|\bv\b|nu\b|mu\b",
    re.IGNORECASE,
)
_PARAM_RE = re.compile(
    r"param|weight|kernel|embed|wte|wpe|scale|bias|\bw\b|\bb\b",
    re.IGNORECASE,
)
_KV_RE = re.compile(r"kv|cache|pages|pool", re.IGNORECASE)
_INPUT_RE = re.compile(
    r"batch|input|tokens|ids|\bx\b|\by\b|label", re.IGNORECASE
)


def categorize_buffer(opcode: str, op_name: str) -> str:
    """One of :data:`BUFFER_CATEGORIES` for a buffer, from its opcode
    and jax path metadata.  Parameters classify by their arg-path name
    (``state/opt/...`` → optimizer, ``kv_pages`` → kv_cache, ...);
    results classify by opcode (collectives → collective scratch,
    everything else → activations)."""
    if opcode == "parameter":
        path = op_name or ""
        if _OPT_RE.search(path):
            return "optimizer"
        if _KV_RE.search(path):
            return "kv_cache"
        if _PARAM_RE.search(path):
            return "params"
        if _INPUT_RE.search(path):
            return "inputs"
        return "args"
    if opcode == "constant":
        return "constants"
    if opcode.startswith(("all-", "reduce-scatter", "collective-")):
        return "collective"
    return "activations"


def _computation_peak(comps, name, memo) -> int:
    """Peak transient bytes of one (non-entry) computation body —
    while/conditional/call interiors, recursively."""
    if name in memo:
        return memo[name]
    memo[name] = 0  # cycle guard
    instrs = comps.get(name, [])
    peak, live = 0, 0
    last_use: Dict[str, int] = {}
    for i, ins in enumerate(instrs):
        for op in ins["operand_names"]:
            last_use[op] = i
    frees: Dict[int, List[int]] = {}
    for i, ins in enumerate(instrs):
        size = 0 if ins["opcode"] in _ALIAS_OPS else \
            hlo_lib.shape_bytes(ins["shape"])
        inner = 0
        if ins["opcode"] in ("while", "conditional", "call"):
            inner = max(
                (_computation_peak(comps, c, memo) for c in ins["called"]),
                default=0,
            )
        live += size
        peak = max(peak, live + inner)
        end = last_use.get(ins["name"], i)
        frees.setdefault(end, []).append(size)
        for s in frees.pop(i, []):
            live -= s
    memo[name] = peak
    return peak


def estimate_peak(hlo_text: str, top_k: int = 10) -> dict:
    """Linear-scan live-range peak over the scheduled ENTRY computation.

    Returns ``{"peak_bytes", "peak_index", "param_bytes",
    "by_category": {category: bytes-at-peak},
    "buffers": [{"name", "bytes", "category", "op_name", "defined",
    "freed"}, ...]}`` — ``buffers`` is the top-K live AT the peak
    instruction, largest first (the attribution a budget-overflow
    finding prints).

    Memoized on the module text (small LRU): the memory pass, the
    board publish, the artifact sections, and the shard-report
    renderer all read the same compiled program — one parse serves
    them all.
    """
    est = _estimate_peak_cached(hlo_text, top_k)
    # shallow-copy the mutable tiers so one consumer's edits can't
    # poison the cache for the next
    out = dict(est)
    out["by_category"] = dict(est["by_category"])
    out["buffers"] = [dict(b) for b in est["buffers"]]
    return out


@functools.lru_cache(maxsize=4)
def _estimate_peak_cached(hlo_text: str, top_k: int) -> dict:
    comps, entry = hlo_lib.parse_computations(hlo_text)
    instrs = comps.get(entry, [])
    aliased_params = {
        p for p, _out in hlo_lib.input_output_aliases(hlo_text)
    }
    params = {p["name"]: p for p in hlo_lib.parameter_shardings(hlo_text)}

    last_use: Dict[str, int] = {}
    for i, ins in enumerate(instrs):
        for op in ins["operand_names"]:
            last_use[op] = i
    end_idx = len(instrs) - 1

    records = []  # (name, bytes, category, op_name, defined, freed)
    for i, ins in enumerate(instrs):
        if ins["opcode"] == "parameter":
            p = params.get(ins["name"])
            size = p["bytes"] if p else hlo_lib.shape_bytes(ins["shape"])
            cat = categorize_buffer("parameter", p["op_name"] if p else "")
            # donated (aliased) parameters are reused by an output, so
            # they stay live to the end regardless of last read
            freed = end_idx if (p and p["param"] in aliased_params) \
                else last_use.get(ins["name"], end_idx)
            records.append((ins["name"], size, cat, (p or {}).get(
                "op_name", ""), i, freed))
            continue
        size = 0 if ins["opcode"] in _ALIAS_OPS else \
            hlo_lib.shape_bytes(ins["shape"])
        if size == 0 and ins["opcode"] not in (
            "while", "conditional", "call"
        ):
            continue
        freed = end_idx if ins.get("root") else \
            last_use.get(ins["name"], i)
        records.append((
            ins["name"], size, categorize_buffer(
                ins["opcode"], ins["op_name"]
            ), ins["op_name"], i, freed,
        ))

    inner_memo: Dict[str, int] = {}
    inner_at: Dict[int, int] = {}
    for i, ins in enumerate(instrs):
        if ins["opcode"] in ("while", "conditional", "call"):
            inner_at[i] = max(
                (_computation_peak(comps, c, inner_memo)
                 for c in ins["called"]),
                default=0,
            )

    allocs: Dict[int, List[int]] = {}
    frees: Dict[int, List[int]] = {}
    for ridx, (_n, size, _c, _o, defined, freed) in enumerate(records):
        allocs.setdefault(defined, []).append(ridx)
        frees.setdefault(freed, []).append(ridx)
    live_set: set = set()
    live, peak, peak_idx, peak_set = 0, 0, 0, set()
    for i in range(len(instrs)):
        for ridx in allocs.get(i, []):
            live += records[ridx][1]
            live_set.add(ridx)
        here = live + inner_at.get(i, 0)
        if here > peak:
            peak, peak_idx, peak_set = here, i, set(live_set)
        for ridx in frees.get(i, []):
            live -= records[ridx][1]
            live_set.discard(ridx)

    by_cat: Dict[str, int] = {}
    at_peak = sorted(
        (records[r] for r in peak_set), key=lambda r: -r[1]
    )
    for _n, size, cat, _o, _d, _f in at_peak:
        by_cat[cat] = by_cat.get(cat, 0) + size
    return {
        "peak_bytes": int(peak),
        "peak_index": int(peak_idx),
        "param_bytes": int(sum(p["bytes"] for p in params.values())),
        "by_category": by_cat,
        "buffers": [
            {
                "name": n, "bytes": int(s), "category": c,
                "op_name": o, "defined": d, "freed": f,
            }
            for n, s, c, o, d, f in at_peak[:top_k]
        ],
    }


def memory_pass(graph) -> List[Finding]:
    """The budget gate: when the :class:`StepGraph` carries an
    ``hbm_budget`` (bytes), a static peak above it is a
    ``memory-budget`` ERROR naming the top live buffers — OOM caught
    at lint time, with attribution, instead of at step 0 with a stack
    trace."""
    if graph.hlo_text is None or graph.hbm_budget is None:
        return []
    budget = int(graph.hbm_budget)
    est = estimate_peak(graph.hlo_text)
    if est["peak_bytes"] <= budget:
        return []
    top = ", ".join(
        f"{b['category']}:{b['name']}={b['bytes'] / (1 << 20):.1f}MiB"
        for b in est["buffers"][:5]
    )
    return [make_finding(
        "memory-budget",
        path=f"instruction #{est['peak_index']}",
        message=(
            f"static peak HBM {est['peak_bytes'] / (1 << 20):.1f} MiB "
            f"exceeds the {budget / (1 << 20):.1f} MiB budget "
            f"(top live buffers: {top})"
        ),
    )]


def publish_peak(est: dict, prefix: str = "analysis") -> None:
    """Gauge a peak estimate onto the observability board
    (``analysis/peak_hbm_bytes`` + per-category breakdown) — the
    source the :class:`~apex_tpu.observability.health
    .MemoryBudgetRule` watchdog judges, and one more section of the
    ``--metrics-out`` JSONL."""
    try:
        from apex_tpu.observability.metrics import board
    except ImportError:  # pragma: no cover - partial install
        return
    board.set(f"{prefix}/peak_hbm_bytes", est["peak_bytes"])
    for cat, size in est["by_category"].items():
        board.set(f"{prefix}/peak_hbm/{cat}", size)
