"""BERT — the north-star benchmark model (BASELINE config #3).

A Megatron-style BERT encoder built entirely from apex_tpu components, so
the benchmark exercises the framework end to end:

- embeddings: :class:`~apex_tpu.transformer.tensor_parallel.VocabParallelEmbedding`
  (vocab row-sharded over tp) + learned position/type embeddings,
- attention: Column/RowParallelLinear QKV/out projections around the Pallas
  flash-attention kernel (heads sharded over tp),
- MLP: the canonical Column(4H, gather=False) → GELU → Row(H) pair,
- norms: fused LayerNorm (Pallas), post-LN like original BERT,
- loss: vocab-parallel softmax cross-entropy (no logits gather).

Reference analogs: ``apex/transformer/testing/standalone_bert.py`` (the
reference's in-repo BERT fixture) and the Megatron BERT recipe its tensor/
pipeline layers were built for (SURVEY §2.3, §6).

Layout is Megatron's seq-first ``(S, B, H)`` so Megatron sequence
parallelism (activations sharded along S between TP regions) composes: with
``sequence_parallel=True`` every hidden tensor entering/leaving a layer is
the local ``(S/tp, B, H)`` shard and the Column/Row layers all-gather /
reduce-scatter at the boundaries (SURVEY §3.4).

Weight tying: the MLM decoder reuses the word-embedding matrix.  Modules
stay functional — tying happens in :func:`bert_pretrain_loss`, which reads
the embedding shard out of the param tree (≙ Megatron sharing
``word_embeddings.weight`` with the output layer through the embedding
group).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from apex_tpu import parallel_state as ps
from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm_affine
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import _tp_world, sharded_init
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide

__all__ = [
    "BertConfig",
    "BertLayer",
    "BertEncoderCore",
    "BertModel",
    "BertForPreTraining",
    "bert_pretrain_loss",
    "bert_large_config",
]

_TP = ps.TENSOR_PARALLEL_AXIS

# one list with pipeline_parallel's "sums" remat wrapper (defined there —
# infra does not import the model layer); re-exported for convenience
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: E402
    SUMS_SAVE_NAMES,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    # compute dtype (params stay f32 — the grad-accum-fusion analog: wgrad
    # cotangents land in f32 because params are f32; see tensor_parallel
    # module docs)
    dtype: Any = jnp.bfloat16
    sequence_parallel: bool = False
    remat: bool = False  # jax.checkpoint each layer (activation ckpt analog)
    # "full" recomputes the whole layer in backward (min memory, ~1.33x
    # compute); "dots" saves every dense (no-batch-dim) matmul output and
    # recomputes only attention internals + elementwise (softmax/GELU) —
    # ~0.6% extra FLOPs on BERT-Large, the MFU-preserving default.
    # "sums" saves the same BYTES as "dots" but picks the tensors backward
    # actually consumes: qkv, fc1 (wgrad/recompute inputs) and the two
    # post-residual sums (LayerNorm-backward inputs) instead of the raw
    # out-proj/fc2 matmul outputs.  Under "dots" those raw outputs have
    # two consumers (the remat save + the bias/residual add), which
    # forces XLA to materialize them and run the adds as separate
    # bandwidth-bound kLoop fusions (measured ~6% of the v5e BERT-Large
    # step, docs/mfu.md); single-consumer raw outputs let the epilogue
    # fuse into the matmul.  Extra recompute vs "dots": gelu + 2 LN
    # forwards per layer (elementwise).
    remat_policy: str = "full"
    # Always recompute the attention core (scores/softmax/PV) in backward,
    # regardless of remat_policy: an inner nothing_saveable checkpoint.
    # Under "dots" this drops the f32 (B,H,S,S) score saves — the largest
    # per-layer buffer at short seq — for ~2% extra FLOPs (flash-style).
    remat_attention: bool = False
    # jax.checkpoint's prevent_cse for the per-layer remat.  None = auto:
    # False under scan_layers (documented safe there) and True unrolled
    # (where CSE could merge the recompute with the forward and keep the
    # saves alive).  Setting False explicitly on the unrolled path is a
    # *performance* choice, not a correctness one — values are identical;
    # XLA may then keep forward activations instead of recomputing when
    # HBM allows (measured v5e BERT-Large b128: 316 ms vs 371 ms honest
    # recompute) at the cost of the checkpoint's memory guarantee.
    remat_prevent_cse: Optional[bool] = None
    # True: nn.scan over layers (one trace, compile time flat in depth,
    # params stacked (L, ...)) — required for the pipeline-stage use.
    # False: unrolled Python loop — XLA schedules each layer separately, so
    # remat-saved activations stay ordinary op outputs instead of being
    # copied into (L, ...) stacked buffers through dynamic-update-slice
    # (measured v5e, BERT-Large b128: the stacking pass costs ~1/3 of the
    # step); the MFU choice for single-host training.
    scan_layers: bool = True

    def __post_init__(self):
        if self.remat_policy not in ("full", "dots", "sums"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r} "
                "(options are 'full', 'dots', 'sums')"
            )


def bert_large_config(**overrides) -> BertConfig:
    """BERT-Large (≈336M params), the BASELINE.json north-star shape."""
    return BertConfig(**overrides)


def _per_rank_dropout_rng(module: nn.Module, rank_local: bool):
    """Dropout key, folded with the tp rank when the tensor is RANK-LOCAL
    (SP sequence shard, or tp-sharded attention heads) — ≙ Megatron's
    model-parallel RNG stream, which seeds dropout differently per tp rank
    inside sharded regions.  For REPLICATED tensors the key must stay
    identical across ranks (folding would desynchronize the replicated
    activations), so ``rank_local=False`` returns the shared key.
    """
    from apex_tpu.transformer.tensor_parallel.random import to_per_rank_key

    rng = module.make_rng("dropout")
    if rank_local and _tp_world(_TP) > 1:
        rng = to_per_rank_key(rng)
    return rng


class _LayerNorm(nn.Module):
    size: int
    eps: float
    # True when this LN runs inside the sequence-parallel region: its
    # params are tp-replicated but see only an S/tp shard per rank, so
    # their grads need the tp psum (allreduce_sequence_parallel_gradients)
    sequence_parallel: bool = False

    @nn.compact
    def __call__(self, x):
        w = self.param("scale", nn.initializers.ones, (self.size,))
        b = self.param("bias", nn.initializers.zeros, (self.size,))
        if self.sequence_parallel:
            ps.register_sequence_parallel_param(self.path + ("scale",))
            ps.register_sequence_parallel_param(self.path + ("bias",))
        return fused_layer_norm_affine(x, w, b, (self.size,), eps=self.eps)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_bias=None, *, deterministic=True):
        with jax.named_scope("bert_self_attention"):
            return self._attend(x, attention_bias, deterministic)

    def _attend(self, x, attention_bias, deterministic):
        cfg = self.cfg
        h = cfg.hidden_size
        world = _tp_world(_TP)
        heads_local = divide(cfg.num_heads, world)
        head_dim = divide(h, cfg.num_heads)

        qkv = ColumnParallelLinear(
            h, 3 * h, gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="qkv",
        )(x)
        # inert unless remat_policy="sums" selects it by name
        qkv = checkpoint_name(qkv, "bert_qkv")
        s = qkv.shape[0]  # full sequence after the SP gather inside Column
        b = qkv.shape[1]
        # Global QKV column layout is (heads, 3, head_dim) — per-head
        # interleaved, the Megatron convention — so column-sharding the
        # output dim over tp hands each rank whole (q, k, v) triples for
        # its heads and the math is tp-invariant.  (A (3, heads, d) layout
        # would shard into "rank 0 owns q of all heads", breaking tp>1.)
        qkv = qkv.reshape(s, b, heads_local, 3, head_dim)
        q, k, v = (
            jnp.transpose(qkv[:, :, :, i], (1, 2, 0, 3)) for i in range(3)
        )
        p = 0.0 if deterministic else cfg.attention_dropout
        # q/k/v are head-SHARDED over tp: each rank's heads need their own
        # dropout mask, so the key is rank-local whenever tp > 1
        rng = _per_rank_dropout_rng(self, True) if p > 0.0 else None

        def core(q, k, v, bias):
            return flash_attention(
                q, k, v, bias, scale=head_dim**-0.5,
                dropout_p=p, dropout_rng=rng,
            )

        if cfg.remat_attention:
            core = jax.checkpoint(
                core, policy=jax.checkpoint_policies.nothing_saveable
            )
        ctx = core(q, k, v, attention_bias)
        ctx = jnp.transpose(ctx, (2, 0, 1, 3)).reshape(s, b, heads_local * head_dim)
        return RowParallelLinear(
            h, h, input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="out",
        )(ctx)


class BertMlp(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x):
        with jax.named_scope("bert_mlp"):
            return self._mlp(x)

    def _mlp(self, x):
        cfg = self.cfg
        y = ColumnParallelLinear(
            cfg.hidden_size, cfg.intermediate_size, gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="fc1",
        )(x)
        y = checkpoint_name(y, "bert_fc1")
        y = jax.nn.gelu(y, approximate=True)
        return RowParallelLinear(
            cfg.intermediate_size, cfg.hidden_size, input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="fc2",
        )(y)


class BertLayer(nn.Module):
    """Post-LN transformer block (original BERT residual order)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_bias=None, *, deterministic=True):
        cfg = self.cfg
        attn = BertSelfAttention(cfg, name="attention")(
            x, attention_bias, deterministic=deterministic
        )
        if not deterministic and cfg.hidden_dropout > 0.0:
            # under SP the activations are sequence shards (rank-local
            # masks); otherwise they are replicated (shared mask required)
            attn = nn.Dropout(cfg.hidden_dropout)(
                attn, deterministic=False,
                rng=_per_rank_dropout_rng(self, cfg.sequence_parallel),
            )
        x = _LayerNorm(
            cfg.hidden_size, cfg.layer_norm_eps,
            sequence_parallel=cfg.sequence_parallel, name="ln_attn",
        )(checkpoint_name(x + attn, "bert_sum_attn"))
        mlp = BertMlp(cfg, name="mlp")(x)
        if not deterministic and cfg.hidden_dropout > 0.0:
            mlp = nn.Dropout(cfg.hidden_dropout)(
                mlp, deterministic=False,
                rng=_per_rank_dropout_rng(self, cfg.sequence_parallel),
            )
        return _LayerNorm(
            cfg.hidden_size, cfg.layer_norm_eps,
            sequence_parallel=cfg.sequence_parallel, name="ln_mlp",
        )(checkpoint_name(x + mlp, "bert_sum_mlp"))


class _BlockStep(nn.Module):
    """One scan step: carry = hidden states; bias broadcast to all steps."""

    cfg: BertConfig
    deterministic: bool

    @nn.compact
    def __call__(self, x, attention_bias):
        y = BertLayer(self.cfg, name="layer")(
            x, attention_bias, deterministic=self.deterministic
        )
        return y, None


class BertEncoderCore(nn.Module):
    """A homogeneous stack of ``num_layers`` BertLayers.

    Scanned over the layer dim (params stacked ``(L, ...)``) so 24 layers
    trace once — XLA sees a rolled loop, keeping compile time flat in depth.
    Also the pipeline-stage module: a pp stage is a BertEncoderCore with
    ``num_layers = L/pp`` (homogeneous stages, the Megatron layout).
    """

    cfg: BertConfig
    num_layers: int

    @nn.compact
    def __call__(self, x, attention_bias=None, *, deterministic=True):
        step = _BlockStep
        if self.cfg.remat:
            # activation checkpointing per layer ≙ tensor_parallel.random
            # .checkpoint (recompute-in-backward; PRNG replay is automatic
            # in JAX — keys are values, not stateful generators).  "sums":
            # same bytes as "dots", chosen so every raw matmul output is
            # single-consumer (epilogues fuse); see BertConfig.
            from apex_tpu.transformer.pipeline_parallel.schedules import (
                resolve_remat_policy,
            )

            policy = resolve_remat_policy(self.cfg.remat_policy)
            # prevent_cse=False is documented safe only under scan/pmap
            # differentiation; on the unrolled path the layer is
            # differentiated directly under jit, where CSE could merge the
            # backward recompute with the forward and silently defeat the
            # checkpoint, so auto mode keeps it True there (see
            # BertConfig.remat_prevent_cse for the explicit override).
            prevent_cse = self.cfg.remat_prevent_cse
            if prevent_cse is None:
                prevent_cse = not self.cfg.scan_layers
            step = nn.remat(step, prevent_cse=prevent_cse, policy=policy)
        if not self.cfg.scan_layers:
            for i in range(self.num_layers):
                x, _ = step(self.cfg, deterministic, name=f"layer_{i}")(
                    x, attention_bias
                )
            return x
        scanned = nn.scan(
            step,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=self.num_layers,
            in_axes=nn.broadcast,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        y, _ = scanned(self.cfg, deterministic, name="layers")(
            x, attention_bias
        )
        return y


class BertEmbeddings(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, *, deterministic=True):
        cfg = self.cfg
        s, b = input_ids.shape  # seq-first (S, B)
        sp = cfg.sequence_parallel and _tp_world(_TP) > 1
        # Megatron's SP embedding order: the vocab-parallel lookup
        # reduce-SCATTERS its psum along the sequence dim, so the SP
        # regime starts here and pos/type/LN run on the S/tp shard.  (A
        # full-seq embedding block followed by a slice would be WRONG, not
        # just slower: the slice's backward zeroes other shards' cotangent
        # rows, so cross-(seq-shard, vocab-shard) embedding-gradient
        # contributions would be silently dropped — each rank's lookup
        # only covers its own vocab rows.)
        word = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="word_embeddings",
        )(input_ids)
        local_s = word.shape[0]  # S/tp under SP, S otherwise
        start = 0
        if sp:
            # dynamic_slice CLAMPS an out-of-range start — guard the table
            # size so a too-long sequence fails loudly instead of silently
            # reusing the last position rows on high ranks
            tp = _tp_world(_TP)
            if tp * local_s > cfg.max_position_embeddings:
                raise ValueError(
                    f"global sequence tp*S_local = {tp}*{local_s} exceeds "
                    f"max_position_embeddings "
                    f"({cfg.max_position_embeddings})"
                )
            start = jax.lax.axis_index(_TP) * local_s
            ps.register_sequence_parallel_param(
                self.path + ("position_embeddings",)
            )
        pos_tab = self.param(
            "position_embeddings",
            nn.initializers.normal(stddev=0.02),
            (cfg.max_position_embeddings, cfg.hidden_size),
        )
        rows = jax.lax.dynamic_slice_in_dim(pos_tab, start, local_s, 0)
        word = word + rows[:, None, :].astype(cfg.dtype)
        if cfg.type_vocab_size:
            tt = (
                jnp.zeros_like(input_ids)
                if token_type_ids is None
                else token_type_ids
            )
            if sp:
                tt = jax.lax.dynamic_slice_in_dim(tt, start, local_s, 0)
                ps.register_sequence_parallel_param(
                    self.path + ("token_type_embeddings",)
                )
            type_tab = self.param(
                "token_type_embeddings",
                nn.initializers.normal(stddev=0.02),
                (cfg.type_vocab_size, cfg.hidden_size),
            )
            word = word + jnp.take(type_tab, tt, axis=0).astype(cfg.dtype)
        out = _LayerNorm(
            cfg.hidden_size, cfg.layer_norm_eps,
            sequence_parallel=cfg.sequence_parallel, name="ln",
        )(word)
        if not deterministic and cfg.hidden_dropout > 0.0:
            out = nn.Dropout(cfg.hidden_dropout)(
                out, deterministic=False,
                rng=_per_rank_dropout_rng(self, sp),
            )
        return out


class BertModel(nn.Module):
    """Embeddings + encoder.  Returns (S[, /tp], B, H) sequence output."""

    cfg: BertConfig

    @nn.compact
    def __call__(
        self, input_ids, token_type_ids=None, attention_mask=None,
        *, deterministic=True,
    ):
        cfg = self.cfg
        bias = None
        if attention_mask is not None:
            # (B, S) with 1 = keep (BERT convention) → additive (B,1,1,S)
            bias = jnp.where(
                attention_mask.astype(bool), 0.0, -1e9
            )[:, None, None, :].astype(jnp.float32)
        x = BertEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, deterministic=deterministic
        )
        return BertEncoderCore(cfg, cfg.num_layers, name="encoder")(
            x, bias, deterministic=deterministic
        )


class BertForPreTraining(nn.Module):
    """BERT + MLM transform + NSP pooler (heads' logits are computed in
    :func:`bert_pretrain_loss` so the MLM decoder can tie to the embedding).
    Returns ``(mlm_hidden, nsp_logits)``.
    """

    cfg: BertConfig

    @nn.compact
    def __call__(
        self, input_ids, token_type_ids=None, attention_mask=None,
        *, deterministic=True,
    ):
        cfg = self.cfg
        seq = BertModel(cfg, name="bert")(
            input_ids, token_type_ids, attention_mask,
            deterministic=deterministic,
        )
        sp = cfg.sequence_parallel and _tp_world(_TP) > 1
        # NSP pooler on [CLS] (position 0).  Under SP the pooler is
        # REPLICATED computation on the gathered sequence, so its gather
        # must split (not reduce-scatter) the cotangent — the Megatron
        # ``tensor_parallel_output_grad=False`` case; a reduce-scatter
        # here would feed the encoder tp× the NSP gradient.
        seq_full = (
            gather_from_sequence_parallel_region(
                seq, tensor_parallel_output_grad=False
            )
            if sp
            else seq
        )
        pooled = jnp.tanh(
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="pooler")(
                seq_full[0]
            )
        )
        nsp_logits = nn.Dense(2, dtype=cfg.dtype, name="nsp_head")(pooled)
        # MLM transform: dense + GELU + LN (the BERT "cls/predictions"
        # transform).  Runs in the SP (sequence-sharded) layout — per-token
        # math, so each rank transforms only its S/tp shard (Megatron's
        # order) — then gathers for the vocab-sharded decoder matmul.  The
        # gather's reduce-scatter backward sums the decoder's vocab-partial
        # cotangents into the true per-shard cotangent; the transform's
        # params sit between gather and matmul in the partial-cotangent
        # region, hence the sequence-parallel grad marking.
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_dense")(seq)
        h = jax.nn.gelu(h, approximate=True)
        h = _LayerNorm(
            cfg.hidden_size, cfg.layer_norm_eps,
            sequence_parallel=sp, name="mlm_ln",
        )(h)
        if sp:
            ps.register_sequence_parallel_param(
                self.path + ("mlm_dense", "kernel")
            )
            ps.register_sequence_parallel_param(
                self.path + ("mlm_dense", "bias")
            )
            h = gather_from_sequence_parallel_region(h)
        # vocab-sharded decoder bias (the tied decoder weight is read from
        # the embedding table in bert_pretrain_loss)
        per = divide(cfg.vocab_size, _tp_world(_TP))
        mlm_bias = self.param(
            "mlm_bias",
            sharded_init(nn.initializers.zeros, (cfg.vocab_size,), 0),
            (per,),
        )
        return (h, mlm_bias), nsp_logits


def bert_pretrain_loss(
    params,
    model: BertForPreTraining,
    batch,
    *,
    deterministic: bool = True,
    rngs: Optional[dict] = None,
    mlm_loss_chunks: Optional[int] = None,
):
    """MLM + NSP loss (the phase-1 pretraining objective).

    ``batch``: dict with ``input_ids``/``token_type_ids``/``attention_mask``
    (S-first ids (S, B) / mask (B, S)), ``mlm_labels`` (S, B; -1 = unmasked,
    ignored), ``nsp_labels`` (B,).  MLM decoder weight is tied to
    ``bert/embeddings/word_embeddings/weight`` (vocab-sharded ⇒ logits are
    vocab-parallel and feed vocab_parallel_cross_entropy directly — no
    logits gather, ≙ _VocabParallelCrossEntropy).

    **Masked-position gather (the reference recipe's input format).**  When
    the batch carries the fixed-K triple ``mlm_positions`` (K, B) /
    ``mlm_label_ids`` (K, B) / ``mlm_weights`` (K, B; 1.0 = real
    prediction, 0.0 = pad), the MLM head runs only on the K gathered rows
    per sequence — the BERT ``max_predictions_per_seq`` recipe
    (masked_lm_positions/masked_lm_ids/masked_lm_weights in the reference's
    BERT pretraining input), which at phase-1 shapes (S=128, K=20) removes
    ~84% of the decoder-matmul + cross-entropy work.  The dense
    ``mlm_labels`` path remains for full-sequence scoring;
    :func:`apex_tpu.data.pack_mlm_predictions` converts dense labels to the
    triple.

    ``mlm_loss_chunks``: split the (S·B, V) logits matmul + cross entropy
    into this many row chunks, each rematerialized in backward — the full
    f32 logits tensor (2 GB at batch 128 / BERT-Large vocab) never exists;
    peak is 1/chunks of it, for one extra decoder-matmul pass (~3% of
    step FLOPs).  None/1 = unchunked.
    """
    (h, mlm_bias), nsp_logits = model.apply(
        params,
        batch["input_ids"],
        batch.get("token_type_ids"),
        batch.get("attention_mask"),
        deterministic=deterministic,
        rngs=rngs,
    )
    embed = params["params"]["bert"]["embeddings"]["word_embeddings"]["weight"]
    positions = batch.get("mlm_positions")
    if positions is not None:
        # (S, B, H) -> (K, B, H); backward is a scatter-add into dh.  h is
        # full-S in both layouts (the SP path gathered inside the model),
        # so the gather is rank-local and the tp grad boundaries below are
        # unchanged.
        h = jnp.take_along_axis(h, positions[:, :, None], axis=0)
        # pack_mlm_predictions pads label ids with 0, but a hand-built
        # triple may use the dense path's -1 ignore convention; an
        # out-of-range id would NaN the xent gather and survive the
        # weight-0 multiply, so clamp exactly as the dense path does.
        labels = jnp.maximum(batch["mlm_label_ids"], 0)
        weights = batch["mlm_weights"].astype(jnp.float32)
    else:
        labels = batch["mlm_labels"]
        weights = (labels >= 0).astype(jnp.float32)
        labels = jnp.maximum(labels, 0)
    if not model.cfg.sequence_parallel and ps.axis_is_bound(_TP):
        # ≙ Megatron's copy_to_tensor_model_parallel_region before the
        # vocab-sharded logits matmul: identity forward, psum backward.
        # The decoder cotangent d h = d logits_r @ W_r is PARTIAL per tp
        # rank (each rank's vocab shard); without this psum every param
        # between the loss and the next collective boundary (mlm
        # transform, final layer norms, last-layer weights) silently gets
        # partial/mixed gradients at tp > 1.  (Under SP the MLM gather's
        # reduce-scatter backward performs this sum instead.)
        h = copy_to_tensor_model_parallel_region(h)
    with jax.named_scope("mlm_logits_xent"):
        dec = jnp.transpose(embed).astype(model.cfg.dtype)

        def rows_loss(h_rows, l_rows, w_rows):
            logits = (
                jnp.matmul(
                    h_rows.astype(model.cfg.dtype), dec,
                    preferred_element_type=jnp.float32,
                )
                + mlm_bias
            )
            losses = vocab_parallel_cross_entropy(
                logits.astype(jnp.float32), l_rows
            )
            return jnp.sum(losses * w_rows), jnp.sum(w_rows)

        nc = mlm_loss_chunks or 1
        if nc > 1:
            rows = labels.size
            if rows % nc:
                raise ValueError(
                    f"mlm_loss_chunks={nc} must divide the number of "
                    f"MLM prediction rows ({rows})"
                )
            hc = h.reshape(nc, rows // nc, h.shape[-1])
            lc = labels.reshape(nc, rows // nc)
            wc = weights.reshape(nc, rows // nc)
            # Statically unrolled (not lax.map/scan): scan's backward stacks
            # the per-chunk dh cotangents into an (nc, rows/nc, H) buffer
            # through dynamic-update-slice — an extra full pass over dh that
            # the unrolled form doesn't pay (measured ~2% of the BERT-Large
            # bench step).  nc is small and static, so HLO growth is trivial.
            chunk_fn = jax.checkpoint(rows_loss)
            total = jnp.float32(0.0)
            count = jnp.float32(0.0)
            for i in range(nc):
                s, c = chunk_fn(hc[i], lc[i], wc[i])
                total = total + s
                count = count + c
            mlm_loss = total / jnp.maximum(count, 1.0)
        else:
            total, count = rows_loss(
                h.reshape(-1, h.shape[-1]), labels.reshape(-1),
                weights.reshape(-1),
            )
            mlm_loss = total / jnp.maximum(count, 1.0)

    nsp_labels = batch.get("nsp_labels")
    nsp_loss = 0.0
    if nsp_labels is not None:
        logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), axis=-1)
        nsp_loss = -jnp.mean(
            jnp.take_along_axis(logp, nsp_labels[:, None], axis=-1)
        )
    return mlm_loss + nsp_loss
