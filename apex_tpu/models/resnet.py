"""ResNet-50 — the ImageNet AMP / DDP+SyncBN benchmark model
(BASELINE configs #1 and #2; ≙ ``examples/imagenet/main_amp.py``'s
torchvision resnet50 + ``apex.parallel.SyncBatchNorm``).

NHWC layout throughout (the TPU-native conv layout: channels on the lane
dim feeds the MXU's convolution tiling directly; the reference's NCHW is a
CUDA convention its groupbn/bottleneck contrib kernels then work around
with "channels_last" variants).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

__all__ = ["ResNetConfig", "ResNet", "resnet50", "resnet50_config"]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    use_syncbn: bool = False  # dp-wide batch statistics (config #2)
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5


def resnet50_config(**overrides) -> ResNetConfig:
    return ResNetConfig(**overrides)


class _Norm(nn.Module):
    cfg: ResNetConfig
    scale_init: Any = nn.initializers.ones

    @nn.compact
    def __call__(self, x, train: bool):
        # keep_batchnorm_fp32 semantics (amp O2): statistics in f32 always.
        if self.cfg.use_syncbn:
            # SyncBatchNorm keeps torch's momentum convention
            # (running = (1-m)*running + m*batch) — flip flax's.
            return SyncBatchNorm(
                features=x.shape[-1],
                use_running_average=not train,
                momentum=1.0 - self.cfg.bn_momentum,
                eps=self.cfg.bn_eps,
                dtype=self.cfg.dtype,
                scale_init=self.scale_init,
            )(x)
        return nn.BatchNorm(
            use_running_average=not train,
            momentum=self.cfg.bn_momentum,
            epsilon=self.cfg.bn_eps,
            dtype=self.cfg.dtype,
            scale_init=self.scale_init,
        )(x)


class BottleneckBlock(nn.Module):
    cfg: ResNetConfig
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = _Norm(cfg, name="bn1")(y, train)
        y = nn.relu(y)
        y = conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            name="conv2",
        )(y)
        y = _Norm(cfg, name="bn2")(y, train)
        y = nn.relu(y)
        y = conv(4 * self.filters, (1, 1), name="conv3")(y)
        # zero-init the last BN scale (the standard ResNet-50 recipe the
        # reference example trains with: residual branch starts at identity)
        y = _Norm(cfg, scale_init=nn.initializers.zeros, name="bn3")(y, train)
        if residual.shape != y.shape:
            residual = conv(
                4 * self.filters, (1, 1),
                strides=(self.strides, self.strides), name="downsample_conv",
            )(residual)
            residual = _Norm(cfg, name="downsample_bn")(residual, train)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Input (N, H, W, 3) → logits (N, num_classes)."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = nn.Conv(
            cfg.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=cfg.dtype, name="conv_stem",
        )(x)
        x = _Norm(cfg, name="bn_stem")(x, train)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                x = BottleneckBlock(
                    cfg,
                    filters=cfg.width * 2**i,
                    strides=2 if (j == 0 and i > 0) else 1,
                    name=f"stage{i}_block{j}",
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))
        # classifier head in f32 (loss numerics)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="fc")(x)


def resnet50(**overrides) -> ResNet:
    return ResNet(resnet50_config(**overrides))
