"""GPT — the tensor-parallel decoder block benchmark (BASELINE config #5).

≙ ``apex/transformer/testing/standalone_gpt.py`` (the reference's GPT
fixture) — a Megatron-style pre-LN causal decoder built from the same
apex_tpu parts as BERT: Column/Row parallel projections, Pallas flash
attention (causal), fused RoPE, fused LayerNorm, vocab-parallel CE.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from apex_tpu import _compat
from apex_tpu import parallel_state as ps
from apex_tpu.models.bert import _LayerNorm
from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.rope import fused_apply_rotary_pos_emb_cached
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import _tp_world
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide

__all__ = ["GptConfig", "GptBlock", "GptModel", "gpt_lm_loss",
           "gpt_lm_loss_cp"]

_TP = ps.TENSOR_PARALLEL_AXIS
_CP = ps.CONTEXT_PARALLEL_AXIS


def _cp_world(cfg) -> int:
    """Bound cp-axis size when context parallelism is configured, else 1."""
    if cfg.context_parallel and ps.axis_is_bound(_CP):
        return _compat.axis_size(_CP)
    return 1


def _cp_shard_rows(table, cfg, s_local):
    """This cp rank's ``s_local`` rows of a GLOBAL per-position table
    (RoPE cos/sin, learned position embeddings).  Contiguous layout:
    rows [rank·s_local, ...).  Zigzag ("ring_zigzag"): the concatenation
    of global chunks ``rank`` and ``2cp−1−rank`` (chunk = s_local/2
    rows), matching :func:`context_parallel.zigzag_split`."""
    rank = jax.lax.axis_index(_CP)
    if cfg.context_parallel == "ring_zigzag":
        from apex_tpu.transformer.context_parallel import zigzag_shard

        cp = _compat.axis_size(_CP)
        # chunk math runs on the GLOBAL SEQUENCE (cp·s_local rows), not
        # the full table — a learned-position table longer than the
        # sequence (max_seq_len > S) must be trimmed first
        return zigzag_shard(table[: cp * s_local], rank, cp, axis=0)
    return jax.lax.dynamic_slice_in_dim(table, rank * s_local, s_local, 0)


def _rope_cos_sin(seq_len: int, dim: int, base: float = 10000.0):
    """Cached cos/sin tables (S, D) in the rotate_half (GPT-NeoX) layout
    the fused RoPE kernel expects."""
    inv = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    freqs = jnp.outer(jnp.arange(seq_len, dtype=jnp.float32), inv)
    emb = jnp.concatenate((freqs, freqs), axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


@dataclasses.dataclass(frozen=True)
class GptConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 12
    num_heads: int = 16
    intermediate_size: int = 4096
    max_seq_len: int = 2048
    layer_norm_eps: float = 1e-5
    rotary: bool = True
    dtype: Any = jnp.bfloat16
    sequence_parallel: bool = False
    # Context parallelism (long-context attention over the cp mesh axis,
    # apex_tpu.transformer.context_parallel): None, "ring" (ppermute'd KV
    # blocks, O(S_local) memory), "ring_zigzag" (same ring with the
    # causal-load-balanced zigzag layout: this rank's S/cp rows are
    # global chunks [rank; 2cp-1-rank] — shard inputs with
    # context_parallel.zigzag_split) or "ulysses" (head<->sequence
    # all-to-all).  The model's sequence inputs are then the cp rank's
    # S/cp shard; RoPE/positions index GLOBAL positions in either layout.
    # Mutually exclusive with sequence_parallel (the sequence dim is
    # already sharded).  Gradients: treat cp like a data axis — pmean
    # over cp alongside dp (every param's grad covers only local tokens'
    # paths); use gpt_lm_loss_cp for the shifted next-token loss across
    # shard boundaries.
    context_parallel: Optional[str] = None
    remat: bool = False
    # Per-layer checkpoint policy when remat=True — same taxonomy as
    # BertConfig: "full" recomputes everything, "dots" saves no-batch-dim
    # matmul outputs, "sums" saves only the gpt_{qkv,fc1,sum_attn,
    # sum_mlp} named tags (epilogue-fusion friendly: every raw matmul
    # output stays single-consumer).
    remat_policy: str = "full"
    # MoE: num_experts > 0 replaces the dense MLP with a SwitchMoe block
    # (experts sharded over the dp/ep axis, apex_tpu.transformer.moe); the
    # per-layer aux losses are sown into the "losses" collection and folded
    # into gpt_lm_loss with moe_aux_coef.
    num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    def __post_init__(self):
        if self.context_parallel not in (None, "ring", "ring_zigzag",
                                         "ulysses"):
            raise ValueError(
                f"context_parallel must be None, 'ring', 'ring_zigzag' "
                f"or 'ulysses', got {self.context_parallel!r}"
            )
        if self.context_parallel and self.sequence_parallel:
            raise ValueError(
                "context_parallel and sequence_parallel are mutually "
                "exclusive: both shard the sequence dimension"
            )
        if self.remat_policy not in ("full", "dots", "sums"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r} "
                "(expected 'full', 'dots' or 'sums')"
            )


class GptBlock(nn.Module):
    """Pre-LN decoder block: x + attn(LN(x)); x + mlp(LN(x))."""

    cfg: GptConfig

    @nn.compact
    def __call__(self, x, *, deterministic=True):
        cfg = self.cfg
        h = cfg.hidden_size
        world = _tp_world(_TP)
        heads_local = divide(cfg.num_heads, world)
        head_dim = divide(h, cfg.num_heads)

        y = _LayerNorm(
            h, cfg.layer_norm_eps,
            sequence_parallel=cfg.sequence_parallel, name="ln_attn",
        )(x)
        qkv = ColumnParallelLinear(
            h, 3 * h, gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="qkv",
        )(y)
        # inert unless remat_policy="sums" selects it by name (the same
        # epilogue-fusion-friendly save set as the BERT blocks)
        qkv = checkpoint_name(qkv, "gpt_qkv")
        s, b = qkv.shape[0], qkv.shape[1]
        # per-head-interleaved (heads, 3, head_dim) column layout — see
        # BertSelfAttention: required for tp-invariant column sharding
        qkv = qkv.reshape(s, b, heads_local, 3, head_dim)
        q, k, v = (
            jnp.transpose(qkv[:, :, :, i], (1, 2, 0, 3)) for i in range(3)
        )
        cp = _cp_world(cfg)
        if cfg.rotary:
            # under cp, s is the LOCAL shard: RoPE must use the global
            # positions of this rank's shard (contiguous [rank·s, ...),
            # or the two zigzag chunks)
            cos, sin = _rope_cos_sin(s * cp, head_dim)
            if cp > 1:
                cos, sin = (
                    _cp_shard_rows(t, cfg, s) for t in (cos, sin)
                )
            q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
            k = fused_apply_rotary_pos_emb_cached(k, cos, sin)
        if cp > 1:
            from apex_tpu.transformer.context_parallel import (
                ring_attention,
                ulysses_attention,
            )

            if cfg.context_parallel == "ulysses":
                ctx = ulysses_attention(
                    q, k, v, causal=True, scale=head_dim**-0.5
                )
            else:
                ctx = ring_attention(
                    q, k, v, causal=True, scale=head_dim**-0.5,
                    layout=(
                        "zigzag"
                        if cfg.context_parallel == "ring_zigzag"
                        else "contiguous"
                    ),
                )
        else:
            ctx = flash_attention(q, k, v, causal=True, scale=head_dim**-0.5)
        ctx = jnp.transpose(ctx, (2, 0, 1, 3)).reshape(s, b, heads_local * head_dim)
        attn = RowParallelLinear(
            h, h, input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="out",
        )(ctx)
        x = checkpoint_name(x + attn, "gpt_sum_attn")

        y = _LayerNorm(
            h, cfg.layer_norm_eps,
            sequence_parallel=cfg.sequence_parallel, name="ln_mlp",
        )(x)
        if cfg.num_experts:
            from apex_tpu.transformer.moe import MoeConfig, SwitchMoe

            # Routing happens on this rank's local (possibly SP-sharded)
            # tokens; expert weights shard over dp/ep and are replicated
            # across tp.  Without SP at tp > 1 the full sequence is routed
            # identically on every tp rank (correct, redundant) — enable
            # sequence_parallel to split that work.
            # NOTE: the aux coefficient has ONE owner — gpt_lm_loss
            # applies cfg.moe_aux_coef; SwitchMoe returns the raw aux.
            y, aux = SwitchMoe(
                MoeConfig(
                    hidden_size=h,
                    ffn_hidden_size=cfg.intermediate_size,
                    num_experts=cfg.num_experts,
                    top_k=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel,
                    context_parallel=bool(cfg.context_parallel),
                ),
                name="moe",
            )(y)
            self.sow("losses", "moe_aux", aux)
        else:
            y = ColumnParallelLinear(
                h, cfg.intermediate_size, gather_output=False,
                sequence_parallel_enabled=cfg.sequence_parallel,
                dtype=cfg.dtype, name="fc1",
            )(y)
            y = checkpoint_name(y, "gpt_fc1")
            y = jax.nn.gelu(y, approximate=True)
            y = RowParallelLinear(
                cfg.intermediate_size, h, input_is_parallel=True,
                sequence_parallel_enabled=cfg.sequence_parallel,
                dtype=cfg.dtype, name="fc2",
            )(y)
        return checkpoint_name(x + y, "gpt_sum_mlp")


class _GptStep(nn.Module):
    cfg: GptConfig
    deterministic: bool

    @nn.compact
    def __call__(self, x):
        return GptBlock(self.cfg, name="block")(
            x, deterministic=self.deterministic
        ), None


class GptModel(nn.Module):
    """Embedding + scanned decoder stack + final LN.  Seq-first (S, B)."""

    cfg: GptConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic=True):
        cfg = self.cfg
        x = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            sequence_parallel_enabled=cfg.sequence_parallel,
            dtype=cfg.dtype, name="word_embeddings",
        )(input_ids)
        if not cfg.rotary:
            pos = self.param(
                "position_embeddings",
                nn.initializers.normal(stddev=0.02),
                (cfg.max_seq_len, cfg.hidden_size),
            )
            start = 0
            rows = None
            if cfg.sequence_parallel and _tp_world(_TP) > 1:
                # x is the SP seq shard [rank·S/tp, (rank+1)·S/tp): slice
                # the matching positions, and mark the table tp-partial.
                # Guard the table size: dynamic_slice CLAMPS out-of-range
                # starts, which would silently reuse rows on high ranks.
                tp = _tp_world(_TP)
                if tp * x.shape[0] > cfg.max_seq_len:
                    raise ValueError(
                        f"global sequence tp*S_local = {tp}*{x.shape[0]} "
                        f"exceeds max_seq_len ({cfg.max_seq_len})"
                    )
                start = jax.lax.axis_index(_TP) * x.shape[0]
                ps.register_sequence_parallel_param(
                    self.path + ("position_embeddings",)
                )
            elif _cp_world(cfg) > 1:
                # cp shard: global positions of this rank's shard
                # (contiguous or zigzag); grads need no marking — cp is
                # synced like a data axis (pmean).  The global length
                # must fit the table: dynamic_slice CLAMPS out-of-range
                # starts, which would silently reuse the last rows on
                # high ranks instead of failing.
                cp = _cp_world(cfg)
                if cp * x.shape[0] > cfg.max_seq_len:
                    raise ValueError(
                        f"global sequence cp*S_local = {cp}*{x.shape[0]} "
                        f"exceeds max_seq_len ({cfg.max_seq_len})"
                    )
                rows = _cp_shard_rows(pos, cfg, x.shape[0])
            if rows is None:
                rows = jax.lax.dynamic_slice_in_dim(
                    pos, start, x.shape[0], 0
                )
            x = x + rows[:, None, :].astype(cfg.dtype)
        step = _GptStep
        if cfg.remat:
            from apex_tpu.transformer.pipeline_parallel.schedules import (
                resolve_remat_policy,
            )

            step = nn.remat(
                step, prevent_cse=False,
                policy=resolve_remat_policy(cfg.remat_policy),
            )
        scanned = nn.scan(
            step,
            variable_axes={"params": 0, "losses": 0},
            split_rngs={"params": True, "dropout": True},
            length=cfg.num_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = scanned(cfg, deterministic, name="layers")(x)
        x = _LayerNorm(
            cfg.hidden_size, cfg.layer_norm_eps,
            sequence_parallel=cfg.sequence_parallel, name="ln_f",
        )(x)
        if cfg.sequence_parallel and _tp_world(_TP) > 1:
            x = gather_from_sequence_parallel_region(x)
        return x


def _apply_with_moe_aux(params, model: GptModel, input_ids, deterministic):
    """Model forward returning ``(h, aux_total)``.

    For MoE configs this strips any "losses" collection that leaked into
    the variables (flax init returns sown collections): apply would
    APPEND fresh aux to the stale init-time values — double-counting —
    and the stale leaves would receive gradients/optimizer updates as if
    they were parameters.  The per-layer sown aux values are averaged and
    scaled by ``cfg.moe_aux_coef``.
    """
    if not model.cfg.num_experts:
        return (
            model.apply(params, input_ids, deterministic=deterministic),
            0.0,
        )
    variables = {k: v for k, v in params.items() if k != "losses"}
    h, sown = model.apply(
        variables, input_ids, deterministic=deterministic,
        mutable=["losses"],
    )
    aux = jax.tree_util.tree_leaves(sown.get("losses", {}))
    aux_total = (
        model.cfg.moe_aux_coef * sum(jnp.mean(a) for a in aux)
        if aux
        else 0.0
    )
    return h, aux_total


def _tied_vocab_logits(params, model: GptModel, h, *, sp_gathered: bool):
    """Vocab-parallel logits through the tied embedding decoder.

    ``sp_gathered``: True when ``h`` arrived through a sequence-dim
    gather whose reduce-scatter backward already sums the vocab-partial
    cotangent — otherwise the Megatron ``copy_to`` boundary (identity
    fwd / psum bwd) is inserted here so upstream params get full grads
    at tp > 1.
    """
    if not sp_gathered and ps.axis_is_bound(_TP):
        h = copy_to_tensor_model_parallel_region(h)
    embed = params["params"]["word_embeddings"]["weight"]
    return jnp.matmul(
        h.astype(model.cfg.dtype),
        jnp.transpose(embed).astype(model.cfg.dtype),
        preferred_element_type=jnp.float32,
    )


def gpt_lm_loss(params, model: GptModel, input_ids, *, deterministic=True):
    """Next-token CE with the decoder tied to the embedding (vocab-parallel
    logits — no gather, ≙ vocab_parallel_cross_entropy usage in Megatron).

    With ``cfg.num_experts > 0`` the per-layer MoE aux losses (sown into
    the "losses" collection) are averaged and added with
    ``cfg.moe_aux_coef``."""
    if _cp_world(model.cfg) > 1:
        raise ValueError(
            "the sequence is context-parallel sharded: use gpt_lm_loss_cp "
            "(the next-token shift crosses cp shard boundaries)"
        )
    h, aux_total = _apply_with_moe_aux(params, model, input_ids, deterministic)
    logits = _tied_vocab_logits(
        params, model, h, sp_gathered=model.cfg.sequence_parallel
    )
    # shift: predict token t+1 from position t
    losses = vocab_parallel_cross_entropy(
        logits[:-1].astype(jnp.float32), input_ids[1:]
    )
    return jnp.mean(losses) + aux_total


def gpt_lm_loss_cp(
    params,
    model: GptModel,
    input_ids_local,
    *,
    axis_name: str = _CP,
    deterministic: bool = True,
):
    """Next-token CE for a context-parallel-sharded sequence.

    ``input_ids_local``: ``(S_local, B)`` — this cp rank's shard of the
    global sequence in the model's configured layout: contiguous (rank r
    holds rows [r·S_local, ...)) for ``context_parallel="ring"`` /
    ``"ulysses"``, or the zigzag pair (global chunks ``r`` and
    ``2cp−1−r``, see ``context_parallel.zigzag_split``) for
    ``"ring_zigzag"``.  The next-token shift crosses shard boundaries
    with ``ppermute`` fetches; the global last position has no target
    and is masked (on the last rank for contiguous, rank 0's hi half for
    zigzag).  Returns the global-token-mean loss, replicated over cp
    (summed with psum, so it equals the unsharded :func:`gpt_lm_loss`
    value).  Gradient sync: treat cp like a data axis — ``pmean``
    gradients over cp (alongside dp) before the optimizer step.
    """
    # aux values are cp-replicated (SwitchMoe pmeans its stats over cp)
    h, aux_total = _apply_with_moe_aux(
        params, model, input_ids_local, deterministic
    )
    # no SP under cp, so the copy_to boundary always applies at tp > 1
    logits = _tied_vocab_logits(params, model, h, sp_gathered=False)
    world = _compat.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    valid = jnp.ones(
        (input_ids_local.shape[0], input_ids_local.shape[1]), jnp.float32
    )
    if model.cfg.context_parallel == "ring_zigzag":
        # local rows = [chunk rank; chunk 2cp−1−rank].  Boundary targets:
        # chunk r's last row predicts chunk r+1's first token — that is
        # rank r+1's lo-first, EXCEPT chunk cp−1 whose successor (chunk
        # cp) is this same rank's OWN hi-first.  Chunk 2cp−1−r's last row
        # predicts chunk 2cp−r's first token = rank r−1's hi-first; for
        # rank 0 the hi chunk is the global end (masked).
        sc = input_ids_local.shape[0] // 2
        lo, hi = input_ids_local[:sc], input_ids_local[sc:]
        lo_first_next = jax.lax.ppermute(
            lo[:1], axis_name,
            [((i + 1) % world, i) for i in range(world)],
        )
        lo_boundary = jnp.where(
            jnp.equal(rank, world - 1), hi[:1], lo_first_next
        )
        hi_boundary = jax.lax.ppermute(
            hi[:1], axis_name,
            [(i, (i + 1) % world) for i in range(world)],
        )
        targets = jnp.concatenate(
            [lo[1:], lo_boundary, hi[1:], hi_boundary], axis=0
        )
        # global final position = chunk 2cp−1's last row = rank 0's last
        rank0 = jnp.equal(rank, 0).astype(valid.dtype)
        valid = valid.at[-1].set(1.0 - rank0)
    else:
        # target for local position i is local token i+1; for the last
        # local position it is the next rank's FIRST token (one ring hop
        # backwards)
        first_next = jax.lax.ppermute(
            input_ids_local[:1],
            axis_name,
            [((i + 1) % world, i) for i in range(world)],
        )
        targets = jnp.concatenate(
            [input_ids_local[1:], first_next], axis=0
        )
        # the global final position (last rank's last row): no successor
        last_rank = jnp.equal(rank, world - 1).astype(valid.dtype)
        valid = valid.at[-1].set(1.0 - last_rank)
    losses = vocab_parallel_cross_entropy(
        logits.astype(jnp.float32), targets
    )  # (S_local, B)
    local_sum = jnp.sum(losses * valid)
    local_count = jnp.sum(valid)
    ce = jax.lax.psum(local_sum, axis_name) / jax.lax.psum(
        local_count, axis_name
    )
    return ce + aux_total
