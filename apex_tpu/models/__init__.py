"""Reference models for the benchmark configs (BASELINE.md).

- :mod:`apex_tpu.models.bert` — BERT-Large pretrain (north star, config #3)
- :mod:`apex_tpu.models.gpt` — tensor-parallel GPT (config #5)
- :mod:`apex_tpu.models.resnet` — ResNet-50 amp / DDP+SyncBN (configs #1, #2)
"""

from apex_tpu.models.bert import (  # noqa: F401
    BertConfig,
    BertEncoderCore,
    BertForPreTraining,
    BertLayer,
    BertModel,
    bert_large_config,
    bert_pretrain_loss,
)
from apex_tpu.models.gpt import (  # noqa: F401
    GptBlock,
    GptConfig,
    GptModel,
    gpt_lm_loss,
)
from apex_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNetConfig,
    resnet50,
    resnet50_config,
)
