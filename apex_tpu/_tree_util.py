"""Shared pytree dtype-casting helpers used by amp, fp16_utils, parallel."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["cast_floats", "to_f32", "cast_like"]


def cast_floats(tree, dtype):
    """Cast floating-point leaves to ``dtype``; other leaves untouched."""

    def f(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(f, tree)


def to_f32(tree):
    """fp32 copies of floating leaves (master weights / master grads);
    integer leaves (step counters etc.) pass through untouched."""
    return cast_floats(tree, jnp.float32)


def cast_like(ref_tree, tree):
    """Cast each floating leaf of ``tree`` to the dtype of the matching
    ``ref_tree`` leaf (master→model copy); non-float leaves untouched."""

    def f(r, x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(r.dtype)
        return x

    return jax.tree_util.tree_map(f, ref_tree, tree)
