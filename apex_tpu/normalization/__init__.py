"""Fused normalization modules (flax).

Capability parity with ``apex/normalization/fused_layer_norm.py`` ::
``FusedLayerNorm``, ``FusedRMSNorm``, ``MixedFusedLayerNorm``,
``MixedFusedRMSNorm``.  The "Mixed" classes in the reference keep parameters
in fp32 with fp16 I/O; here that is simply ``param_dtype=float32`` (the
default) with bf16 inputs — the functional core always computes statistics
in f32 — so ``MixedFused*`` are exact aliases.
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)

from apex_tpu.normalization.instance_norm import (  # noqa: E402
    InstanceNorm3d,
    InstanceNorm3dNVFuser,
    instance_norm,
)

__all__ = [
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
    "InstanceNorm3d",
    "InstanceNorm3dNVFuser",
    "instance_norm",
]

Shape = Union[int, Sequence[int]]


def _as_tuple(normalized_shape: Shape):
    if isinstance(normalized_shape, int):
        return (normalized_shape,)
    return tuple(normalized_shape)


class FusedLayerNorm(nn.Module):
    """≙ apex.normalization.FusedLayerNorm (elementwise_affine flag incl.)."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _as_tuple(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param(
                "weight", nn.initializers.ones, shape, self.param_dtype
            )
            bias = self.param(
                "bias", nn.initializers.zeros, shape, self.param_dtype
            )
            return fused_layer_norm_affine(
                x, weight, bias, shape, self.eps, self.memory_efficient
            )
        return fused_layer_norm(x, shape, self.eps, self.memory_efficient)


class FusedRMSNorm(nn.Module):
    """≙ apex.normalization.FusedRMSNorm."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _as_tuple(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param(
                "weight", nn.initializers.ones, shape, self.param_dtype
            )
            return fused_rms_norm_affine(
                x, weight, shape, self.eps, self.memory_efficient
            )
        return fused_rms_norm(x, shape, self.eps, self.memory_efficient)


# fp32 params + low-precision IO is the default behavior here (see module
# docstring) — the Mixed classes are aliases kept for API parity.
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
