"""InstanceNorm3d — ≙ ``apex/normalization/instance_norm.py`` ::
``InstanceNorm3dNVFuser``.

The reference wraps an NVFuser-compiled instance-norm kernel for 5D
``(N, C, D, H, W)`` inputs with optional affine and running stats.  On TPU
the op is a per-(sample, channel) row reduction XLA fuses on its own —
no hand kernel needed — so the value to reproduce is the *semantics*:

- channels-LAST layout ``(N, D, H, W, C)`` (TPU-native; the reference's
  ``channels_last`` ctor flag is the default here, and a
  ``channels_first`` flag accepts torch-layout input for parity),
- statistics over the spatial dims per (n, c), always computed in f32,
- ``affine``: per-channel γ/β,
- ``track_running_stats``: EMA of mean/var used at eval time (torch
  momentum convention: ``running = (1-m)·running + m·batch``),
- output dtype == input dtype.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["instance_norm", "InstanceNorm3d", "InstanceNorm3dNVFuser"]


def instance_norm(x, weight=None, bias=None, eps: float = 1e-5,
                  mean=None, var=None):
    """Functional instance norm over ``(N, *spatial, C)``.

    Stats are per (sample, channel) over all spatial dims, in f32 —
    unless precomputed ``mean``/``var`` (shape ``(N, C)`` or ``(C,)``)
    are given (the eval-time running-stats path).
    """
    xf = x.astype(jnp.float32)
    axes = tuple(range(1, x.ndim - 1))
    if mean is None:
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
    else:
        bshape = (
            (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
            if mean.ndim == 2
            else (1,) * (x.ndim - 1) + (x.shape[-1],)
        )
        mean = mean.astype(jnp.float32).reshape(bshape)
        var = var.astype(jnp.float32).reshape(bshape)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


class InstanceNorm3d(nn.Module):
    """Flax module ≙ ``InstanceNorm3dNVFuser(num_features, ...)``.

    Call with ``use_running_average=False`` during training (default).
    Running stats live in the ``batch_stats`` collection like flax BN.
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1  # torch convention
    affine: bool = True
    track_running_stats: bool = False
    channels_first: bool = False  # accept torch (N, C, D, H, W) input
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        if self.channels_first:
            x = jnp.moveaxis(x, 1, -1)
        if x.shape[-1] != self.num_features:
            raise ValueError(
                f"expected {self.num_features} channels, got {x.shape[-1]}"
            )
        w = (
            self.param("scale", nn.initializers.ones,
                       (self.num_features,), self.param_dtype)
            if self.affine
            else None
        )
        b = (
            self.param("bias", nn.initializers.zeros,
                       (self.num_features,), self.param_dtype)
            if self.affine
            else None
        )
        use_ra = bool(use_running_average) and self.track_running_stats
        if self.track_running_stats:
            ra_mean = self.variable(
                "batch_stats", "mean",
                lambda: jnp.zeros((self.num_features,), jnp.float32),
            )
            ra_var = self.variable(
                "batch_stats", "var",
                lambda: jnp.ones((self.num_features,), jnp.float32),
            )
        if use_ra:
            y = instance_norm(
                x, w, b, eps=self.eps,
                mean=ra_mean.value, var=ra_var.value,
            )
        else:
            y = instance_norm(x, w, b, eps=self.eps)
            if self.track_running_stats and not self.is_initializing():
                axes = tuple(range(1, x.ndim - 1))
                xf = x.astype(jnp.float32)
                n = 1
                for a in axes:
                    n *= x.shape[a]
                bm = jnp.mean(jnp.mean(xf, axis=axes), axis=0)
                # torch feeds the EMA the UNBIASED sample variance
                bv = jnp.mean(jnp.var(xf, axis=axes), axis=0) * (
                    n / max(n - 1, 1)
                )
                m = self.momentum
                ra_mean.value = (1 - m) * ra_mean.value + m * bm
                ra_var.value = (1 - m) * ra_var.value + m * bv
        if self.channels_first:
            y = jnp.moveaxis(y, -1, 1)
        return y


# reference-name alias (the NVFuser suffix names the CUDA codegen backend,
# meaningless on TPU)
InstanceNorm3dNVFuser = InstanceNorm3d
