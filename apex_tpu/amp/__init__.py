"""Mixed precision — ≙ apex/amp (policies, loss scaling, master weights)."""

from apex_tpu.amp import lists  # noqa: F401
from apex_tpu.amp.frontend import (  # noqa: F401
    AmpHandle,
    AmpState,
    initialize,
    load_state_dict,
    master_params,
    scale_loss,
    state_dict,
)
from apex_tpu.amp.policy import Policy, Properties, opt_levels  # noqa: F401
from apex_tpu.amp.scaler import (  # noqa: F401
    DynamicLossScaler,
    LossScaleState,
    StaticLossScaler,
    amp_update,
)
