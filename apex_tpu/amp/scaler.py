"""Loss scalers — static and dynamic with hysteresis.

≙ ``apex/amp/scaler.py`` :: ``LossScaler`` +
``apex/fp16_utils/loss_scaler.py`` :: ``DynamicLossScaler`` + the device-side
``csrc/update_scale_hysteresis.cu`` :: ``update_scale_hysteresis_cuda``.

Everything is functional and jit-safe: the scaler owns no Python state; its
state is a small pytree threaded through the step.  Overflow detection rides
the fused scale pass (:func:`apex_tpu.optimizers.scale_with_overflow_check`,
the ``noop_flag`` convention of ``multi_tensor_scale_kernel.cu``), and the
conditional step-skip is a ``where``-select over the param/opt-state trees —
no host sync, matching the reference's device-side ``noop`` design.

Update rule (hysteresis semantics of ``update_scale_hysteresis.cu``):
- overflow: ``hysteresis -= 1``; once exhausted, ``scale *= backoff_factor``
  and the growth counter resets;
- clean step: ``growth_tracker += 1``; at ``growth_interval`` consecutive
  clean steps, ``scale *= growth_factor``, trackers reset, hysteresis
  restored.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.multi_tensor import scale_with_overflow_check

__all__ = ["LossScaleState", "DynamicLossScaler", "StaticLossScaler", "amp_update"]


class LossScaleState(NamedTuple):
    loss_scale: jax.Array  # f32 scalar
    growth_tracker: jax.Array  # i32: consecutive clean steps
    hysteresis: jax.Array  # i32: tolerated overflows before backoff


class DynamicLossScaler:
    """≙ LossScaler(loss_scale="dynamic") — 2**16 start, x2/2000, /2."""

    def __init__(
        self,
        init_scale: float = 2.0**16,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        growth_interval: int = 2000,
        hysteresis: int = 1,
        min_loss_scale: float = 1.0,
        max_loss_scale: float = 2.0**24,
    ):
        self.init_scale = init_scale
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self.hysteresis = hysteresis
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = max_loss_scale

    def init(self) -> LossScaleState:
        return LossScaleState(
            loss_scale=jnp.asarray(self.init_scale, jnp.float32),
            growth_tracker=jnp.zeros((), jnp.int32),
            hysteresis=jnp.asarray(self.hysteresis, jnp.int32),
        )

    @staticmethod
    def metrics(state: LossScaleState) -> dict:
        """The scaler's device scalars, keyed for a
        :class:`apex_tpu.observability.MetricRegistry` (gauges; feed to
        ``registry.update`` inside the jitted step).  The scale and its
        hysteresis trackers are the earliest public symptom of numeric
        trouble — a scale walking down means overflows are recurring
        before any loss divergence is visible."""
        return {
            "amp/loss_scale": state.loss_scale,
            "amp/growth_tracker": state.growth_tracker,
            "amp/hysteresis": state.hysteresis,
        }

    def scale(self, loss, state: LossScaleState):
        """≙ scale_loss ctx-mgr entry (apex/amp/handle.py :: scale_loss).

        The multiply happens in f32: a 2**16 scale cast to fp16 would be
        inf (fp16 max is 65504).  The scaled loss is returned in f32; its
        gradients still arrive in each param's dtype.
        """
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(self, grads, state: LossScaleState) -> Tuple[Any, jax.Array]:
        """Fused (1/scale)·grads + found_inf flag; grads emerge in f32.

        Overflow detection and the divide run in f32 regardless of grad
        dtype (the reference kernel reads fp16 grads but computes in f32).
        """
        return scale_with_overflow_check(
            grads, 1.0 / state.loss_scale, out_dtype=jnp.float32
        )

    def update(self, state: LossScaleState, found_inf) -> LossScaleState:
        """≙ update_scale_hysteresis_cuda (device-side, no host sync)."""
        overflow = found_inf > 0.0
        new_hyst = jnp.where(
            overflow, jnp.maximum(state.hysteresis - 1, 0), state.hysteresis
        )
        do_backoff = overflow & (new_hyst <= 0)
        backed_off = jnp.clip(
            state.loss_scale * self.backoff_factor,
            self.min_loss_scale,
            self.max_loss_scale,
        )
        tracker = jnp.where(overflow, 0, state.growth_tracker + 1)
        do_growth = jnp.logical_not(overflow) & (tracker >= self.growth_interval)
        grown = jnp.clip(
            state.loss_scale * self.growth_factor,
            self.min_loss_scale,
            self.max_loss_scale,
        )
        new_scale = jnp.where(
            do_backoff, backed_off, jnp.where(do_growth, grown, state.loss_scale)
        )
        tracker = jnp.where(do_growth, 0, tracker)
        # clean step or completed backoff: hysteresis restored to full (the
        # reference kernel resets the tracker on every non-overflow step, so
        # isolated rare overflows never accumulate into a backoff)
        new_hyst = jnp.where(
            do_backoff | jnp.logical_not(overflow),
            jnp.asarray(self.hysteresis, jnp.int32),
            new_hyst,
        )
        return LossScaleState(
            loss_scale=new_scale, growth_tracker=tracker, hysteresis=new_hyst
        )


class StaticLossScaler(DynamicLossScaler):
    """≙ LossScaler(loss_scale=<const>) — fixed scale, still flags overflow."""

    def __init__(self, loss_scale: float = 1.0):
        super().__init__(init_scale=loss_scale)

    def update(self, state: LossScaleState, found_inf) -> LossScaleState:
        return state


def amp_update(tx, scaler, scaled_grads, opt_state, params, scaler_state):
    """One fused mixed-precision optimizer step with overflow skip.

    ≙ the patched ``optimizer.step`` from
    ``apex/amp/_process_optimizer.py`` :: ``_process_optimizer``: unscale,
    check overflow, apply-or-skip, adjust the scale.  Returns
    ``(new_params, new_opt_state, new_scaler_state, found_inf)``; on
    overflow params and opt state are returned untouched (step skipped)
    and only the scaler state moves — all branch-free on device.
    """
    grads, found_inf = scaler.unscale(scaled_grads, scaler_state)
    # Re-align grad dtypes with the params so a generic optax tx whose state
    # dtype follows its inputs (e.g. optax.adam over bf16 params) returns
    # state of the same dtype it was initialized with — otherwise lax.scan
    # carries mismatch.  The fused_* optimizers accumulate in f32 internally
    # either way.
    grads = jax.tree_util.tree_map(
        lambda g, p: g.astype(p.dtype), grads, params
    )
    updates, new_opt_state = tx.update(grads, opt_state, params)
    def sel(new, old):
        return jnp.where(found_inf == 0.0, new, old)

    new_params = jax.tree_util.tree_map(
        lambda p, u: sel(p + u.astype(p.dtype), p), params, updates
    )
    new_opt_state = jax.tree_util.tree_map(sel, new_opt_state, opt_state)
    new_scaler_state = scaler.update(scaler_state, found_inf)
    return new_params, new_opt_state, new_scaler_state, found_inf
