"""Per-op AMP cast policy — the O1 patch-table semantics.

≙ ``apex/amp/lists/{functional_overrides,torch_overrides,tensor_overrides}``:
the reference's O1 monkey-patches every listed torch function so GEMM-class
ops run in fp16, reduction/loss-class ops run in fp32, and multi-input ops
promote to the widest input dtype.  The TPU-native analog patches nothing —
this repo's public ops *consult* the active policy at trace time via
:func:`amp_cast` at their entry, so the same op-category table is applied
structurally inside jit.

Activate with ``with amp.lists.o1_patch(half_dtype): ...`` around the traced
forward (or via ``AmpHandle.patch_functions()``).  With no active policy
every hook is an identity — zero cost and zero behavior change.

Note the trace-time caveat (inherent to any O1 implementation over a traced
runtime, and analogous to the reference patching process-globally at
``amp.initialize`` time): a ``jit``-cached function keeps the policy it was
traced under; activate the context before the first traced call.
"""

from apex_tpu.amp.lists._registry import (
    CastPolicy,
    active_policy,
    amp_cast,
    category,
    o1_patch,
    register,
)
from apex_tpu.amp.lists.functional_overrides import (
    CASTS,
    FP16_FUNCS,
    FP32_FUNCS,
)

__all__ = [
    "CastPolicy",
    "active_policy",
    "amp_cast",
    "category",
    "o1_patch",
    "register",
    "FP16_FUNCS",
    "FP32_FUNCS",
    "CASTS",
]
