"""The op-category table for this framework's public ops.

≙ ``apex/amp/lists/functional_overrides.py`` :: ``FP16_FUNCS`` /
``FP32_FUNCS`` / ``CASTS``.  The reference lists torch.nn.functional names;
here the names are this repo's op entry points (each calls
``amp_cast(<name>, ...)`` on its tensor inputs).

Categories follow the reference's rationale:
- **half** (FP16_FUNCS): GEMM/conv-class compute — tensor-core (MXU) ops
  where half precision is free accuracy-wise and 2x+ throughput;
- **fp32** (FP32_FUNCS): reductions, losses, softmax/log/exp — ops whose
  numerics degrade in half precision;
- **promote** (CASTS): multi-input elementwise ops — widest input dtype
  wins so mixed half/f32 operands don't silently truncate.
"""

from apex_tpu.amp.lists._registry import register

# GEMM / conv class → half
FP16_FUNCS = [
    "attention",
    "mlp",
    "fused_dense",
    "fused_dense_gelu_dense",
    "conv_bias_relu",
    "rnn_gemm",
]

# numerics-sensitive → fp32.  focal_loss carries no amp_cast hook: it
# computes and returns f32 unconditionally (structurally fp32).
FP32_FUNCS = [
    "layer_norm",
    "rms_norm",
    "scaled_softmax",
    "scaled_masked_softmax",
    "xentropy",
    "focal_loss",
    "group_norm",
]

# multi-input elementwise → promote to widest.  "add" has no single entry
# point in this repo — it is the generic promote rule available to user
# code via ``amp_cast("add", a, b)``.
CASTS = [
    "add",
    "index_mul_2d",
]

for _name in FP16_FUNCS:
    register(_name, "half")
for _name in FP32_FUNCS:
    register(_name, "fp32")
for _name in CASTS:
    register(_name, "promote")
