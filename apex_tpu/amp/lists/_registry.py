"""The op→category registry and the trace-time cast hook."""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Any, Iterator, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "CastPolicy",
    "register",
    "category",
    "o1_patch",
    "active_policy",
    "amp_cast",
]

# op name -> "half" | "fp32" | "promote"
_CATEGORY: dict = {}

_VALID = ("half", "fp32", "promote")


def register(name: str, cat: str) -> None:
    """Add/override an op's cast category (≙ editing the override lists)."""
    if cat not in _VALID:
        raise ValueError(f"category must be one of {_VALID}, got {cat!r}")
    _CATEGORY[name] = cat


def category(name: str) -> Optional[str]:
    return _CATEGORY.get(name)


@dataclasses.dataclass(frozen=True)
class CastPolicy:
    """Active O1 policy: which dtype 'half' ops cast to."""

    half_dtype: Any = jnp.bfloat16


_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "apex_tpu_amp_op_policy", default=None
)


def active_policy() -> Optional[CastPolicy]:
    return _ACTIVE.get()


@contextlib.contextmanager
def o1_patch(half_dtype=jnp.bfloat16) -> Iterator[None]:
    """Activate per-op casting (≙ ``patch_torch_functions=True``)."""
    token = _ACTIVE.set(CastPolicy(half_dtype))
    try:
        yield
    finally:
        _ACTIVE.reset(token)


def _is_float_array(x) -> bool:
    return isinstance(
        x, (jax.Array, jnp.ndarray)
    ) and jnp.issubdtype(jnp.result_type(x), jnp.floating)


def amp_cast(op_name: str, *arrays):
    """Cast ``arrays`` per the active policy and ``op_name``'s category.

    Identity when no policy is active or the op is unregistered.  Non-array
    / non-float leaves (None, ints, bools) pass through untouched.  Returns
    a single value for a single input, else a tuple.
    """
    pol = _ACTIVE.get()
    cat = _CATEGORY.get(op_name)
    if pol is None or cat is None:
        return arrays[0] if len(arrays) == 1 else arrays

    if cat == "half":
        target = pol.half_dtype
    elif cat == "fp32":
        target = jnp.float32
    else:  # promote: widest floating dtype among the inputs wins
        floats = [jnp.result_type(a) for a in arrays if _is_float_array(a)]
        target = jnp.result_type(*floats) if floats else None

    def cast(x):
        if target is not None and _is_float_array(x):
            return x.astype(target)
        return x

    out = tuple(cast(a) for a in arrays)
    return out[0] if len(out) == 1 else out
