"""amp.initialize-shaped frontend.

≙ ``apex/amp/frontend.py`` :: ``initialize`` + ``apex/amp/handle.py`` ::
``scale_loss`` / ``AmpHandle`` + ``state_dict`` plumbing
(``apex/amp/_amp_state.py``).

The reference mutates a torch model/optimizer in place; the JAX version is
functional: ``initialize`` resolves an opt level to a :class:`Properties`,
casts the params per ``cast_model_type``, and returns an :class:`AmpHandle`
bundling the policy, the loss scaler, (optionally) fp32 master params, and a
fused ``step`` that reproduces the patched-optimizer semantics (unscale →
overflow check → apply-or-skip → scale update) in one jittable call.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu._tree_util import cast_like, to_f32
from apex_tpu.amp.policy import Policy, Properties, opt_levels
from apex_tpu.amp.scaler import (
    DynamicLossScaler,
    LossScaleState,
    StaticLossScaler,
    amp_update,
)

__all__ = ["initialize", "AmpHandle", "AmpState", "scale_loss"]


class AmpState(NamedTuple):
    """Threaded training state: opt state + scaler state (+ f32 masters)."""

    opt_state: Any
    scaler_state: LossScaleState
    master_params: Optional[Any]  # fp32 copies when properties.master_weights


class AmpHandle:
    def __init__(self, properties: Properties, tx: optax.GradientTransformation):
        self.properties = properties
        self.policy: Policy = properties.policy()
        ls = properties.loss_scale
        if ls == "dynamic":
            self.scaler = DynamicLossScaler()
        else:
            self.scaler = StaticLossScaler(float(ls))
        self.tx = tx

    # -- state -----------------------------------------------------------
    def init(self, params) -> AmpState:
        master = None
        if self.properties.master_weights:
            master = to_f32(params)
        opt_params = master if master is not None else params
        return AmpState(
            opt_state=self.tx.init(opt_params),
            scaler_state=self.scaler.init(),
            master_params=master,
        )

    # -- loss scaling ----------------------------------------------------
    def scale_loss(self, loss, state: AmpState):
        """≙ the `with amp.scale_loss(loss, opt) as scaled:` entry."""
        return self.scaler.scale(loss, state.scaler_state)

    # -- O1 per-op casting (≙ patch_torch_functions) ---------------------
    def patch_functions(self):
        """Context manager activating the per-op cast registry
        (:mod:`apex_tpu.amp.lists`) with this handle's half dtype — the O1
        patch-table semantics.  Wrap the traced forward:

            with handle.patch_functions():
                loss = loss_fn(params, batch)

        O0/O2/O3 keep their whole-tree policies; per the reference's table
        only O1 patches functions, so this raises on other levels to keep
        opt-level semantics distinguishable.
        """
        if self.properties.opt_level != "O1":
            raise RuntimeError(
                "patch_functions() is the O1 mechanism (reference: "
                "patch_torch_functions=True only at O1); current level is "
                f"{self.properties.opt_level}"
            )
        from apex_tpu.amp import lists

        return lists.o1_patch(self.properties.compute_dtype)

    # -- the patched optimizer.step --------------------------------------
    def step(self, params, scaled_grads, state: AmpState):
        """Returns (new_params, new_state, found_inf).

        With master weights (O2): the fp32 masters take the update; model
        params are re-cast from the masters (≙ master→model copy in
        ``_process_optimizer``).  Without: params update in their own dtype.
        """
        if state.master_params is not None:
            new_master, new_opt, new_scaler, found_inf = amp_update(
                self.tx,
                self.scaler,
                scaled_grads,
                state.opt_state,
                state.master_params,
                state.scaler_state,
            )
            new_params = cast_like(params, new_master)
            return (
                new_params,
                AmpState(new_opt, new_scaler, new_master),
                found_inf,
            )
        new_params, new_opt, new_scaler, found_inf = amp_update(
            self.tx,
            self.scaler,
            scaled_grads,
            state.opt_state,
            params,
            state.scaler_state,
        )
        return new_params, AmpState(new_opt, new_scaler, None), found_inf

    # -- persistence (≙ amp.state_dict / load_state_dict) ----------------
    def state_dict(self, state: AmpState) -> dict:
        return {
            "loss_scale": state.scaler_state.loss_scale,
            "growth_tracker": state.scaler_state.growth_tracker,
            "hysteresis": state.scaler_state.hysteresis,
        }

    def load_state_dict(self, state: AmpState, sd: dict) -> AmpState:
        return state._replace(
            scaler_state=LossScaleState(
                loss_scale=jnp.asarray(sd["loss_scale"], jnp.float32),
                growth_tracker=jnp.asarray(sd["growth_tracker"], jnp.int32),
                hysteresis=jnp.asarray(sd["hysteresis"], jnp.int32),
            )
        )


def initialize(
    params,
    tx: optax.GradientTransformation,
    opt_level: str = "O1",
    half_dtype=jnp.bfloat16,
    cast_model_type=None,
    keep_batchnorm_fp32: Optional[bool] = None,
    master_weights: Optional[bool] = None,
    loss_scale: Union[float, str, None] = None,
):
    """≙ amp.initialize(model, optimizer, opt_level=..., **overrides).

    Returns ``(cast_params, handle)``; per-kwarg overrides refine the opt
    level exactly as the reference's ``initialize`` kwargs override its
    ``opt_levels`` table.
    """
    levels = opt_levels(half_dtype)
    if opt_level not in levels:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r} "
            "(options are 'O0', 'O1', 'O2', 'O3')"
        )
    props = levels[opt_level]
    overrides = {}
    if cast_model_type is not None:
        overrides["cast_model_type"] = cast_model_type
    if keep_batchnorm_fp32 is not None:
        overrides["keep_batchnorm_fp32"] = keep_batchnorm_fp32
    if master_weights is not None:
        overrides["master_weights"] = master_weights
    if loss_scale is not None:
        overrides["loss_scale"] = loss_scale
    if overrides:
        import dataclasses

        props = dataclasses.replace(props, **overrides)
    handle = AmpHandle(props, tx)
    cast_params = handle.policy.cast_to_param(params)
    return cast_params, handle


def scale_loss(loss, handle: AmpHandle, state: AmpState):
    """Free-function parity alias for ``amp.scale_loss``."""
    return handle.scale_loss(loss, state)


def master_params(params, state: AmpState):
    """≙ ``apex.amp.master_params(optimizer)``: the fp32 view the optimizer
    actually steps — the master copies when the opt level keeps them (O2),
    else the model params themselves."""
    return state.master_params if state.master_params is not None else params


def state_dict(handle: AmpHandle, state: AmpState) -> dict:
    """≙ module-level ``apex.amp.state_dict()`` (scaler state for
    checkpointing); the handle method, free-function shaped."""
    return handle.state_dict(state)


def load_state_dict(handle: AmpHandle, state: AmpState, sd: dict) -> AmpState:
    """≙ module-level ``apex.amp.load_state_dict(sd)``."""
    return handle.load_state_dict(state, sd)
