"""Precision policies — the O0–O3 opt-level table.

≙ ``apex/amp/frontend.py`` :: ``opt_levels`` dict + ``Properties``.  The
reference's per-op torch monkey-patching (O1) has no JAX analog — and needs
none: under XLA the policy is applied *structurally*: parameters live in
``param_dtype``, the model casts inputs/params to ``compute_dtype`` at entry
(one ``policy.cast_to_compute`` call), and XLA keeps GEMMs in bf16 on the MXU
while accumulating in f32.  ``keep_batchnorm_fp32`` maps to normalization
layers computing statistics in f32 — which every op in
:mod:`apex_tpu.ops` already does unconditionally.

On TPU the native half dtype is **bfloat16**: its f32-range exponent makes
loss scaling unnecessary, so O1/O2 default to ``loss_scale=1.0`` with bf16.
``float16`` remains selectable (``half_dtype=jnp.float16``) together with the
dynamic scaler for numerical-parity testing of the reference's fp16
semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax.numpy as jnp

from apex_tpu._tree_util import cast_floats

__all__ = ["Properties", "opt_levels", "Policy"]


@dataclasses.dataclass(frozen=True)
class Policy:
    """jmp-style dtype triple; the mechanical core of an opt level."""

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    def cast_to_param(self, tree):
        return cast_floats(tree, self.param_dtype)

    def cast_to_compute(self, tree):
        return cast_floats(tree, self.compute_dtype)

    def cast_to_output(self, tree):
        return cast_floats(tree, self.output_dtype)


@dataclasses.dataclass(frozen=True)
class Properties:
    """≙ apex/amp/frontend.py :: Properties (resolved opt-level config)."""

    opt_level: str
    cast_model_type: Optional[Any]  # O2/O3: params stored in half
    compute_dtype: Any  # O1+: math in half (patch_torch_functions analog)
    keep_batchnorm_fp32: bool
    master_weights: bool
    loss_scale: Union[float, str]  # number or "dynamic"

    def policy(self) -> Policy:
        param_dtype = self.cast_model_type or jnp.float32
        return Policy(
            param_dtype=param_dtype,
            compute_dtype=self.compute_dtype,
            output_dtype=jnp.float32,
        )


def opt_levels(half_dtype=jnp.bfloat16) -> dict:
    """The O0–O3 table, parameterized by the half dtype.

    With bf16 (TPU default) the dynamic-loss-scale defaults collapse to 1.0;
    with fp16 they reproduce the reference's ("dynamic" for O1/O2, 1.0 for
    O3).
    """
    fp16 = half_dtype == jnp.float16
    dyn = "dynamic" if fp16 else 1.0
    return {
        "O0": Properties(
            opt_level="O0",
            cast_model_type=None,
            compute_dtype=jnp.float32,
            keep_batchnorm_fp32=False,
            master_weights=False,
            loss_scale=1.0,
        ),
        "O1": Properties(
            opt_level="O1",
            cast_model_type=None,
            compute_dtype=half_dtype,
            keep_batchnorm_fp32=True,
            master_weights=False,
            loss_scale=dyn,
        ),
        "O2": Properties(
            opt_level="O2",
            cast_model_type=half_dtype,
            compute_dtype=half_dtype,
            keep_batchnorm_fp32=True,
            master_weights=True,
            loss_scale=dyn,
        ),
        "O3": Properties(
            opt_level="O3",
            cast_model_type=half_dtype,
            compute_dtype=half_dtype,
            keep_batchnorm_fp32=False,
            master_weights=False,
            loss_scale=1.0,
        ),
    }
