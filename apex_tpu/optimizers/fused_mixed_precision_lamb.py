"""FusedMixedPrecisionLamb — LAMB with in-optimizer f32 master params.

≙ ``apex/optimizers/fused_mixed_precision_lamb.py``: the reference variant
keeps an fp32 master copy of fp16 model params *inside the optimizer*,
runs the (multi_tensor) LAMB math on the masters, and writes the halved
result back to the model params — so training code that owns only half
params still gets full-precision accumulation.

TPU-native shape: an ``optax.GradientTransformation`` whose state carries
the f32 masters next to the LAMB moments.  ``update`` computes the LAMB
step on the masters (f32, via :func:`apex_tpu.optimizers.fused_lamb`),
advances them, and returns ``new_half(master) - param`` as the update so
``optax.apply_updates`` leaves the model params exactly equal to the
rounded masters — no drift between the two copies.

When params are already f32 this degrades to plain :func:`fused_lamb`
with an extra (pointless but harmless) master copy; prefer ``fused_lamb``
then.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers.fused_lamb import fused_lamb

__all__ = ["FusedMixedPrecisionLamb", "fused_mixed_precision_lamb"]


class MixedPrecisionLambState(NamedTuple):
    masters: Any  # f32 copies of the (possibly half) model params
    inner: Any  # FusedLAMBState of the wrapped LAMB


def fused_mixed_precision_lamb(*args, **kwargs) -> optax.GradientTransformation:
    """Same signature as :func:`fused_lamb` (lr, betas, eps, weight_decay,
    bias_correction, grad_averaging, adam_w_mode, max_grad_norm,
    use_nvlamb, ...)."""
    inner = fused_lamb(*args, **kwargs)

    def to_f32(tree):
        return jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32)
            if jnp.issubdtype(p.dtype, jnp.floating)
            else p,
            tree,
        )

    def init(params):
        masters = to_f32(params)
        return MixedPrecisionLambState(
            masters=masters, inner=inner.init(masters)
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError(
                "fused_mixed_precision_lamb requires params for the update"
            )
        with jax.named_scope("fused_mp_lamb_update"):
            grads32 = to_f32(grads)
            m_updates, inner_state = inner.update(
                grads32, state.inner, state.masters
            )
            masters = jax.tree_util.tree_map(
                jnp.add, state.masters, m_updates
            )
            # model param := round(master); emitted as a delta so
            # optax.apply_updates / tree add reproduces it exactly
            updates = jax.tree_util.tree_map(
                lambda m, p: m.astype(p.dtype) - p, masters, params
            )
        return updates, MixedPrecisionLambState(
            masters=masters, inner=inner_state
        )

    return optax.GradientTransformation(init, update)


class FusedMixedPrecisionLamb:
    """apex-shaped stateful wrapper (≙ the reference class ctor)."""

    def __init__(self, params, **kwargs):
        self._tx = fused_mixed_precision_lamb(**kwargs)
        self.state = self._tx.init(params)
        self._step = jax.jit(self._tx.update)

    def step(self, grads, params):
        updates, self.state = self._step(grads, self.state, params)
        return jax.tree_util.tree_map(jnp.add, params, updates)
