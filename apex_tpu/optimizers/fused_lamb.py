"""Fused LAMB — ≙ apex/optimizers/fused_lamb.py :: FusedLAMB.

Backed in the reference by ``csrc/multi_tensor_lamb.cu`` ::
``LAMBStage1Functor`` / ``LAMBStage2Functor`` with the global grad norm from
``multi_tensor_l2norm`` (SURVEY.md §3.2 traces the full call stack).  The
exact semantics reproduced here:

1. global_grad_norm = sqrt(Σ‖g‖²) over **all** params;
2. stage 1 — grads divided by ``clipped_ratio =
   max(global_grad_norm / max_grad_norm, 1)``; moments
   ``m ← β₁m + (1-β₁ if grad_averaging else 1)·g``,
   ``v ← β₂v + (1-β₂)·g²`` with optional bias correction;
   update ``u = m̂/(√v̂ + eps) + wd·p`` (decoupled/AdamW style when
   ``adam_w_mode``, else L2 into the grad);
3. stage 2 — per-tensor trust ratio ``r = ‖p‖/‖u‖`` applied only when both
   norms are nonzero, and — unless ``use_nvlamb`` — only for params with
   nonzero weight decay; ``p ← p − lr·r·u``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers.multi_tensor import global_norm

__all__ = ["fused_lamb", "FusedLAMB"]

ScalarOrSchedule = Union[float, optax.Schedule]


class FusedLAMBState(NamedTuple):
    count: jax.Array
    m: Any
    v: Any


def fused_lamb(
    learning_rate: ScalarOrSchedule = 1e-3,
    beta1: float = 0.9,
    beta2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    grad_averaging: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    *,
    state_dtype=jnp.float32,
) -> optax.GradientTransformation:
    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=state_dtype)  # noqa: E731
        return FusedLAMBState(
            count=jnp.zeros((), jnp.int32),
            m=jax.tree_util.tree_map(zeros, params),
            v=jax.tree_util.tree_map(zeros, params),
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params for the update")
        with jax.named_scope("fused_lamb_update"):
            return _update(grads, state, params)

    def _update(grads, state, params):
        count = state.count + 1
        # schedules are evaluated at the 0-based step (optax convention)
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - beta1**cf if bias_correction else 1.0
        bc2 = 1.0 - beta2**cf if bias_correction else 1.0
        beta3 = (1.0 - beta1) if grad_averaging else 1.0

        # global grad-norm clip (stage 1 preamble)
        gnorm = global_norm(grads)
        clip_ratio = jnp.where(
            (max_grad_norm > 0.0) & (gnorm > max_grad_norm),
            gnorm / max_grad_norm,
            1.0,
        )
        tm = jax.tree_util.tree_map

        def eff_grad(g, p):
            gf = g.astype(jnp.float32) / clip_ratio
            if not adam_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)
            return gf

        gf = tm(eff_grad, grads, params)
        m_new = tm(lambda m, g: beta1 * m + beta3 * g, state.m, gf)
        v_new = tm(lambda v, g: beta2 * v + (1.0 - beta2) * g * g, state.v, gf)

        def upd(m, v, p):
            pf = p.astype(jnp.float32)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                u = u + weight_decay * pf
            # stage 2: per-tensor trust ratio
            w_norm = jnp.sqrt(jnp.sum(pf * pf))
            u_norm = jnp.sqrt(jnp.sum(u * u))
            ratio = jnp.where(
                (w_norm > 0.0) & (u_norm > 0.0), w_norm / u_norm, 1.0
            )
            if not use_nvlamb and weight_decay == 0.0:
                ratio = 1.0  # vanilla LAMB skips adaptation for wd==0 groups
            return (-lr * ratio * u).astype(p.dtype)

        updates = tm(upd, m_new, v_new, params)
        return updates, FusedLAMBState(count=count, m=m_new, v=v_new)

    return optax.GradientTransformation(init, update)


class FusedLAMB:
    """apex-shaped stateful wrapper."""

    def __init__(
        self,
        params,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        bias_correction: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
    ):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        self.tx = fused_lamb(
            learning_rate=lr,
            beta1=betas[0],
            beta2=betas[1],
            eps=eps,
            weight_decay=weight_decay,
            bias_correction=bias_correction,
            grad_averaging=grad_averaging,
            adam_w_mode=adam_w_mode,
            max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb,
        )
        self.state = self.tx.init(params)

        def _step(g, s, p):
            updates, ns = self.tx.update(g, s, p)
            return optax.apply_updates(p, updates), ns

        self._step = jax.jit(_step)

    def step(self, grads, params):
        params, self.state = self._step(grads, self.state, params)
        return params
