"""Cross-tensor reductions — the ``multi_tensor_apply`` analog.

The reference batches many tensors into single CUDA kernel launches
(``csrc/multi_tensor_apply.cuh`` :: ``multi_tensor_apply<depth>``,
``csrc/amp_C_frontend.cpp`` :: ``multi_tensor_l2norm``/``multi_tensor_scale``
etc.) purely to amortize launch overhead.  Under ``jit`` a whole-pytree
update is already a single XLA program, so the launch-amortization property
is free; what this module provides is the reference's *cross-tensor reduction
semantics* — global and per-tensor L2 norms, inf/nan detection fused into
scaling (the ``noop_flag`` convention dynamic loss scaling relies on) — as
fused jnp reductions over pytrees.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "global_norm",
    "per_tensor_norm",
    "scale_with_overflow_check",
    "axpby",
]

PyTree = Any


def global_norm(tree: PyTree) -> jax.Array:
    """sqrt(Σ‖leaf‖²) over all leaves, accumulated in f32.

    ≙ ``amp_C.multi_tensor_l2norm(..., per_tensor=False)`` + the host-side
    sqrt(Σ partial²) in ``FusedLAMB.step``.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)


def per_tensor_norm(tree: PyTree) -> PyTree:
    """‖leaf‖₂ per leaf (f32 scalars), same treedef.

    ≙ ``amp_C.multi_tensor_l2norm(..., per_tensor=True)`` (the LAMB
    trust-ratio input).
    """
    return jax.tree_util.tree_map(
        lambda x: jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32)))), tree
    )


def scale_with_overflow_check(
    tree: PyTree, scale, out_dtype: Optional[jnp.dtype] = None
) -> Tuple[PyTree, jax.Array]:
    """``out = tree * scale`` plus a fused inf/nan flag.

    ≙ ``csrc/multi_tensor_scale_kernel.cu`` :: ``ScaleFunctor`` — the amp
    unscale primitive: one pass that both scales and writes ``noop_flag``
    when any element is non-finite.  Returns ``(scaled_tree, found_inf)``
    with ``found_inf`` a f32 scalar in {0.0, 1.0}.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flags = []
    out = []
    for x in leaves:
        xf = x.astype(jnp.float32)
        flags.append(jnp.logical_not(jnp.all(jnp.isfinite(xf))))
        y = xf * scale
        out.append(y.astype(out_dtype) if out_dtype is not None else y.astype(x.dtype))
    found_inf = jnp.any(jnp.stack(flags)).astype(jnp.float32) if flags else jnp.zeros((), jnp.float32)
    return jax.tree_util.tree_unflatten(treedef, out), found_inf


def axpby(a, x_tree: PyTree, b, y_tree: PyTree, out_dtype=None) -> PyTree:
    """``a*x + b*y`` leafwise — ≙ multi_tensor_axpby (master-grad merge)."""

    def f(x, y):
        r = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        return r.astype(out_dtype if out_dtype is not None else x.dtype)

    return jax.tree_util.tree_map(f, x_tree, y_tree)
