"""Fused Adam / AdamW — ≙ apex/optimizers/fused_adam.py :: FusedAdam.

Backed in the reference by ``csrc/multi_tensor_adam.cu`` :: ``AdamFunctor``
with ``ADAM_MODE_0`` (L2: grad += wd*p before the moments) and
``ADAM_MODE_1`` (AdamW: decoupled decay added to the update) selected by
``adam_w_mode``.  One jitted pytree update = one XLA program = the
launch-amortization the multi-tensor kernel bought on GPU.

State (m, v) is kept in f32 by default regardless of param dtype (the
reference runs fp32 master params through this optimizer under amp O2).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_adam", "FusedAdam"]

ScalarOrSchedule = Union[float, optax.Schedule]


class FusedAdamState(NamedTuple):
    count: jax.Array  # int32 step counter (1-based after first update)
    m: Any
    v: Any


def _lr_at(lr: ScalarOrSchedule, prev_count):
    """Evaluate a schedule at the 0-based step (optax convention: the first
    update sees lr(0)), or pass a constant through."""
    return lr(prev_count) if callable(lr) else lr


def fused_adam(
    learning_rate: ScalarOrSchedule = 1e-3,
    beta1: float = 0.9,
    beta2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
    *,
    state_dtype=jnp.float32,
) -> optax.GradientTransformation:
    """optax-style fused Adam(W) matching the reference kernel's math."""

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=state_dtype)  # noqa: E731
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            m=jax.tree_util.tree_map(zeros, params),
            v=jax.tree_util.tree_map(zeros, params),
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params for the update")
        count = state.count + 1
        lr = _lr_at(learning_rate, state.count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - beta1**cf
            bc2 = 1.0 - beta2**cf
        else:
            bc1 = bc2 = 1.0

        tm = jax.tree_util.tree_map

        def eff_grad(g, p):
            gf = g.astype(jnp.float32)
            if not adam_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)  # ADAM_MODE_0
            return gf

        gf = tm(eff_grad, grads, params)
        m_new = tm(lambda m, g: beta1 * m + (1.0 - beta1) * g, state.m, gf)
        v_new = tm(lambda v, g: beta2 * v + (1.0 - beta2) * g * g, state.v, gf)

        def upd(m, v, p):
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                u = u + weight_decay * p.astype(jnp.float32)  # ADAM_MODE_1
            return (-lr * u).astype(p.dtype)

        updates = tm(upd, m_new, v_new, params)
        return updates, FusedAdamState(count=count, m=m_new, v=v_new)

    return optax.GradientTransformation(init, update)


class FusedAdam:
    """apex-shaped stateful wrapper (``FusedAdam(params).step(grads)``)."""

    def __init__(
        self,
        params,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        adam_w_mode: bool = True,
        bias_correction: bool = True,
        amsgrad: bool = False,
    ):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.tx = fused_adam(
            learning_rate=lr,
            beta1=betas[0],
            beta2=betas[1],
            eps=eps,
            weight_decay=weight_decay,
            adam_w_mode=adam_w_mode,
            bias_correction=bias_correction,
        )
        self.state = self.tx.init(params)
        self._step = jax.jit(
            lambda g, s, p: _apply(self.tx, g, s, p)
        )

    def step(self, grads, params):
        params, self.state = self._step(grads, self.state, params)
        return params


def _apply(tx, grads, state, params):
    updates, new_state = tx.update(grads, state, params)
    return optax.apply_updates(params, updates), new_state
