"""Fused optimizers — ≙ apex/optimizers + apex/contrib/clip_grad + LARC.

Two API shapes per optimizer:
- lowercase factory (``fused_adam(...)``) → ``optax.GradientTransformation``
  for functional training loops;
- CamelCase class (``FusedAdam(params, ...)``) → apex-shaped stateful
  wrapper with a jitted ``.step(grads, params)``.
"""

from apex_tpu.optimizers.clip_grad import clip_grad_norm  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import (  # noqa: F401
    FusedAdagrad,
    fused_adagrad,
)
from apex_tpu.optimizers.fused_adam import FusedAdam, fused_adam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB, fused_lamb  # noqa: F401
from apex_tpu.optimizers.fused_mixed_precision_lamb import (  # noqa: F401
    FusedMixedPrecisionLamb,
    fused_mixed_precision_lamb,
)
from apex_tpu.optimizers.fused_novograd import (  # noqa: F401
    FusedNovoGrad,
    fused_novograd,
)
from apex_tpu.optimizers.fused_sgd import FusedSGD, fused_sgd  # noqa: F401
from apex_tpu.optimizers.larc import LARC, larc  # noqa: F401
from apex_tpu.optimizers.multi_tensor import (  # noqa: F401
    axpby,
    global_norm,
    per_tensor_norm,
    scale_with_overflow_check,
)

#: name → optax-style factory — the registry `apex_tpu.train.TrainConfig
#: (optimizer="adam")` resolves through.  The ZeRO-twin mapping (which of
#: these the trainer can shard across replicas) lives with the trainer
#: (`apex_tpu.train.trainer.ZERO_TWINS`).
FACTORIES = {
    "adagrad": fused_adagrad,
    "adam": fused_adam,
    "lamb": fused_lamb,
    "novograd": fused_novograd,
    "sgd": fused_sgd,
}


def by_name(name: str):
    """The lowercase optimizer factory registered under ``name``; raises
    with the available names on a miss (a typo'd optimizer must fail the
    build loudly, not fall back)."""
    try:
        return FACTORIES[name]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {name!r}; have {sorted(FACTORIES)}"
        ) from None
