"""LARC — layer-wise adaptive rate clipping.

≙ ``apex/parallel/LARC.py`` :: ``LARC`` (trust_coefficient, clip mode, eps).
The reference wraps a torch optimizer and rescales ``p.grad`` in-place before
the inner ``step``; here it is an optax transformation chained *before* the
inner optimizer:

    local_lr = trust_coefficient · ‖p‖ / (‖g‖ + wd·‖p‖ + eps)
    clip:     g ← g · min(local_lr / lr, 1)
    scale:    g ← g · local_lr

Params with ‖p‖ == 0 or ‖g‖ == 0 pass through unscaled (reference guard).
The reference folds the wrapped group's weight decay into the gradient
before scaling and zeroes it for the inner step; pass the same
``weight_decay`` here and set the inner optimizer's decay to 0 to match.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["larc", "LARC"]


class LARCState(NamedTuple):
    count: jax.Array


def larc(
    learning_rate: Union[float, optax.Schedule],
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    def init(params):
        del params
        return LARCState(count=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("larc requires params for the update")
        # current-step lr, as the reference reads the group's live lr
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )

        def leaf(g, p):
            gf = g.astype(jnp.float32)
            pf = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(pf * pf))
            g_norm = jnp.sqrt(jnp.sum(gf * gf))
            local_lr = (
                trust_coefficient * p_norm / (g_norm + weight_decay * p_norm + eps)
            )
            if clip:
                scale = jnp.minimum(local_lr / lr, 1.0)
            else:
                scale = local_lr
            adapted = (gf + weight_decay * pf) * scale
            # zero param or zero grad: pass through untouched (reference
            # applies both the wd fold-in and the scaling only inside the
            # nonzero-norms branch)
            active = (p_norm > 0.0) & (g_norm > 0.0)
            return jnp.where(active, adapted, gf).astype(g.dtype)

        out = jax.tree_util.tree_map(leaf, grads, params)
        return out, LARCState(count=state.count + 1)

    return optax.GradientTransformation(init, update)


class LARC:
    """apex-shaped wrapper: ``LARC(inner_tx, lr).init/update`` like optax."""

    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        learning_rate: float,
        trust_coefficient: float = 0.02,
        clip: bool = True,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
    ):
        self.tx = optax.chain(
            larc(learning_rate, trust_coefficient, clip, eps, weight_decay),
            optimizer,
        )

    def init(self, params):
        return self.tx.init(params)

    def update(self, grads, state, params=None):
        return self.tx.update(grads, state, params)
