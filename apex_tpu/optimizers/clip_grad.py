"""Fused gradient clipping — ≙ apex/contrib/clip_grad/clip_grad.py ::
``clip_grad_norm_`` (drop-in for ``torch.nn.utils.clip_grad_norm_`` built on
``multi_tensor_l2norm`` + ``multi_tensor_scale``).

Functional: returns the clipped tree and the pre-clip total norm (the
reference returns the norm and scales in place).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.multi_tensor import global_norm

__all__ = ["clip_grad_norm", "clip_grad_norm_"]


def clip_grad_norm(
    grads: Any, max_norm: float, norm_type: float = 2.0
) -> Tuple[Any, jax.Array]:
    """Clip a gradient pytree to ``max_norm`` total norm.

    ``norm_type=2`` uses the fused global L2 norm; ``inf`` uses max-abs
    (≙ the reference's non-fused fallback path for other norm types).
    """
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == 2.0:
        total = global_norm(grads)
    elif norm_type == float("inf"):
        total = (
            jnp.max(jnp.stack([jnp.max(jnp.abs(x)) for x in leaves]))
            if leaves
            else jnp.zeros((), jnp.float32)
        )
    else:
        total = (
            sum(
                jnp.sum(jnp.abs(x.astype(jnp.float32)) ** norm_type)
                for x in leaves
            )
            ** (1.0 / norm_type)
            if leaves
            else jnp.zeros((), jnp.float32)
        )
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda x: (x.astype(jnp.float32) * scale).astype(x.dtype), grads
    )
    return clipped, total


# the reference's exact name (apex/contrib/clip_grad :: clip_grad_norm_ —
# torch's trailing-underscore in-place convention; pure here, same math)
clip_grad_norm_ = clip_grad_norm
