"""Fused Adagrad — ≙ apex/optimizers/fused_adagrad.py :: FusedAdagrad.

Backed in the reference by ``csrc/multi_tensor_adagrad.cu`` ::
``AdagradFunctor``:

    h  += g²
    p  -= lr · g / (√h + eps)   [+ lr·wd·p  decoupled if adagrad_w_mode,
                                 else wd folded into g first]
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_adagrad", "FusedAdagrad"]


class FusedAdagradState(NamedTuple):
    count: jax.Array
    sum: Any


def fused_adagrad(
    learning_rate: Union[float, optax.Schedule] = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
    *,
    state_dtype=jnp.float32,
) -> optax.GradientTransformation:
    def init(params):
        return FusedAdagradState(
            count=jnp.zeros((), jnp.int32),
            sum=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=state_dtype), params
            ),
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params for the update")
        count = state.count + 1
        # schedules are evaluated at the 0-based step (optax convention)
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )
        tm = jax.tree_util.tree_map

        def eff_grad(g, p):
            gf = g.astype(jnp.float32)
            if not adagrad_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)
            return gf

        gf = tm(eff_grad, grads, params)
        h_new = tm(lambda h, g: h + g * g, state.sum, gf)

        def upd(g, h, p):
            u = g / (jnp.sqrt(h) + eps)
            if adagrad_w_mode and weight_decay != 0.0:
                u = u + weight_decay * p.astype(jnp.float32)
            return (-lr * u).astype(p.dtype)

        updates = tm(upd, gf, h_new, params)
        return updates, FusedAdagradState(count=count, sum=h_new)

    return optax.GradientTransformation(init, update)


class FusedAdagrad:
    """apex-shaped stateful wrapper."""

    def __init__(
        self,
        params,
        lr: float = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
    ):
        self.tx = fused_adagrad(
            learning_rate=lr,
            eps=eps,
            weight_decay=weight_decay,
            adagrad_w_mode=adagrad_w_mode,
        )
        self.state = self.tx.init(params)

        def _step(g, s, p):
            updates, ns = self.tx.update(g, s, p)
            return optax.apply_updates(p, updates), ns

        self._step = jax.jit(_step)

    def step(self, grads, params):
        params, self.state = self._step(grads, self.state, params)
        return params
