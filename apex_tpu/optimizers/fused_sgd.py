"""Fused SGD — ≙ apex/optimizers/fused_sgd.py :: FusedSGD.

Backed in the reference by ``csrc/multi_tensor_sgd_kernel.cu`` ::
``SGDFunctor`` (momentum/dampening/nesterov/weight-decay over tensor lists;
the fp16-model+fp32-master list variants are the amp integration, which here
lives in :mod:`apex_tpu.amp` instead).  Matches ``torch.optim.SGD`` math:

    d = g + wd*p
    buf = momentum*buf + (1-dampening)*d         (first step: buf = d)
    update = d + momentum*buf   if nesterov else buf
    p -= lr * update
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_sgd", "FusedSGD"]


class FusedSGDState(NamedTuple):
    count: jax.Array
    momentum_buf: Any


def fused_sgd(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    *,
    state_dtype=jnp.float32,
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires momentum > 0 and zero dampening")

    def init(params):
        if momentum == 0.0:
            buf = None
        else:
            buf = jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=state_dtype), params
            )
        return FusedSGDState(count=jnp.zeros((), jnp.int32), momentum_buf=buf)

    def update(grads, state, params=None):
        count = state.count + 1
        # schedules are evaluated at the 0-based step (optax convention)
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )
        tm = jax.tree_util.tree_map

        def eff_grad(g, p):
            d = g.astype(jnp.float32)
            if weight_decay != 0.0:
                d = d + weight_decay * p.astype(jnp.float32)
            return d

        if params is None:
            if weight_decay != 0.0:
                raise ValueError("fused_sgd with weight_decay requires params")
            d = tm(lambda g: g.astype(jnp.float32), grads)
        else:
            d = tm(eff_grad, grads, params)

        # updates are applied to the params, so they carry the *param* dtype
        # (bf16 grads must not truncate fp32 master-weight updates)
        out_tree = params if params is not None else grads

        if momentum == 0.0:
            updates = tm(lambda di, o: (-lr * di).astype(o.dtype), d, out_tree)
            return updates, FusedSGDState(count=count, momentum_buf=None)

        first = (count == 1).astype(jnp.float32)

        def new_buf(buf, di):
            # first step: buf = d (torch semantics), else EMA with dampening
            return first * di + (1.0 - first) * (
                momentum * buf + (1.0 - dampening) * di
            )

        buf_new = tm(new_buf, state.momentum_buf, d)
        if nesterov:
            upd = tm(lambda di, b: di + momentum * b, d, buf_new)
        else:
            upd = buf_new
        updates = tm(lambda u, o: (-lr * u).astype(o.dtype), upd, out_tree)
        return updates, FusedSGDState(count=count, momentum_buf=buf_new)

    return optax.GradientTransformation(init, update)


class FusedSGD:
    """apex-shaped stateful wrapper."""

    def __init__(
        self,
        params,
        lr: float = 1e-3,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
    ):
        self.tx = fused_sgd(
            learning_rate=lr,
            momentum=momentum,
            dampening=dampening,
            weight_decay=weight_decay,
            nesterov=nesterov,
        )
        self.state = self.tx.init(params)

        def _step(g, s, p):
            updates, ns = self.tx.update(g, s, p)
            return optax.apply_updates(p, updates), ns

        self._step = jax.jit(_step)

    def step(self, grads, params):
        params, self.state = self._step(grads, self.state, params)
        return params
