"""Fused NovoGrad — ≙ apex/optimizers/fused_novograd.py :: FusedNovoGrad.

Backed in the reference by ``csrc/multi_tensor_novograd.cu`` ::
``NovoGradFunctor`` with a **per-tensor** (layer-wise) second moment:

    v_t  = β₂·v_{t-1} + (1-β₂)·‖g_t‖²        (scalar per tensor;
                                              first step: v_1 = ‖g_1‖²
                                              unless init_zero)
    u    = g_t / (√v_t + eps)  [+ wd·p  if reg_inside_moment]
    m_t  = β₁·m_{t-1} + (1-β₁ if grad_averaging else 1)·u
    p   -= lr · (m_t [+ wd·p  if not reg_inside_moment])
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_novograd", "FusedNovoGrad"]


class FusedNovoGradState(NamedTuple):
    count: jax.Array
    m: Any
    v: Any  # scalar per tensor


def fused_novograd(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    beta1: float = 0.95,
    beta2: float = 0.98,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    init_zero: bool = False,
    reg_inside_moment: bool = False,
    *,
    state_dtype=jnp.float32,
) -> optax.GradientTransformation:
    def init(params):
        return FusedNovoGradState(
            count=jnp.zeros((), jnp.int32),
            m=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=state_dtype), params
            ),
            v=jax.tree_util.tree_map(
                lambda p: jnp.zeros((), state_dtype), params
            ),
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params for the update")
        count = state.count + 1
        # schedules are evaluated at the 0-based step (optax convention)
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )
        beta3 = (1.0 - beta1) if grad_averaging else 1.0
        first = (count == 1).astype(jnp.float32)
        tm = jax.tree_util.tree_map

        def new_v(v, g):
            gn2 = jnp.sum(jnp.square(g.astype(jnp.float32)))
            ema = beta2 * v + (1.0 - beta2) * gn2
            if init_zero:
                return ema
            return first * gn2 + (1.0 - first) * ema

        v_new = tm(new_v, state.v, grads)

        def new_m(m, g, v, p):
            u = g.astype(jnp.float32) / (jnp.sqrt(v) + eps)
            if reg_inside_moment and weight_decay != 0.0:
                u = u + weight_decay * p.astype(jnp.float32)
            return beta1 * m + beta3 * u

        m_new = tm(new_m, state.m, grads, v_new, params)

        def upd(m, p):
            u = m
            if not reg_inside_moment and weight_decay != 0.0:
                u = u + weight_decay * p.astype(jnp.float32)
            return (-lr * u).astype(p.dtype)

        updates = tm(upd, m_new, params)
        return updates, FusedNovoGradState(count=count, m=m_new, v=v_new)

    return optax.GradientTransformation(init, update)


class FusedNovoGrad:
    """apex-shaped stateful wrapper."""

    def __init__(
        self,
        params,
        lr: float = 1e-3,
        betas=(0.95, 0.98),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        grad_averaging: bool = True,
        init_zero: bool = False,
        reg_inside_moment: bool = False,
    ):
        self.tx = fused_novograd(
            learning_rate=lr,
            beta1=betas[0],
            beta2=betas[1],
            eps=eps,
            weight_decay=weight_decay,
            grad_averaging=grad_averaging,
            init_zero=init_zero,
            reg_inside_moment=reg_inside_moment,
        )
        self.state = self.tx.init(params)

        def _step(g, s, p):
            updates, ns = self.tx.update(g, s, p)
            return optax.apply_updates(p, updates), ns

        self._step = jax.jit(_step)

    def step(self, grads, params):
        params, self.state = self._step(grads, self.state, params)
        return params
