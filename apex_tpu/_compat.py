"""Pinned-jax compatibility shims.

The library is written against the current jax surface — ``jax.shard_map``
with vma (``check_vma``) semantics, ``jax.lax.axis_size`` and
``jax.lax.pcast`` — but must run on the pinned release (jax 0.4.37 at the
time of writing), where ``shard_map`` still lives in
``jax.experimental.shard_map`` with ``check_rep`` semantics and the two lax
helpers do not exist yet.  This module is the single place that knows the
difference:

- :func:`shard_map` — top-level ``jax.shard_map`` when present, otherwise
  the experimental one.  The ``check_vma`` keyword is translated: on the
  old API the replication checker predates the vma rewrite machinery and
  rejects (or mis-handles) code that is valid under vma typing, so both
  ``check_vma=True`` and ``False`` map to ``check_rep=False`` — collectives
  are unchanged, only the static replication *checker* is off.
- :func:`axis_size` — ``jax.lax.axis_size`` when present, else
  ``lax.psum(1, axis)``, which constant-folds to the bound size and raises
  the same ``NameError`` on an unbound name.
- :func:`pcast` — native when present.  On pre-vma jax values inside
  ``shard_map`` carry no replication type, and autodiff never inserts the
  implicit cross-shard psum that ``to='varying'`` exists to suppress, so
  the cast is an identity there.  Code that *relies* on the vma auto-psum
  (grads of replicated inputs) must psum explicitly when
  :data:`HAS_VMA` is False — see
  ``apex_tpu.parallel.distributed.DistributedDataParallel``.

:func:`install` grafts the missing names onto ``jax`` / ``jax.lax`` so the
examples, tools and tests — which use the modern spellings directly — run
unmodified on the pinned release.  It runs once at ``import apex_tpu``.
"""

from __future__ import annotations

import jax

__all__ = ["HAS_VMA", "shard_map", "axis_size", "pcast", "install"]

_NATIVE_SHARD_MAP = getattr(jax, "shard_map", None)


def _native_has_vma() -> bool:
    # jax.shard_map existing is NOT enough: some releases promoted the name
    # before the vma rewrite landed.  Probe the signature for check_vma —
    # the keyword and the typing machinery shipped together.
    if _NATIVE_SHARD_MAP is None:
        return False
    try:
        import inspect

        return "check_vma" in inspect.signature(_NATIVE_SHARD_MAP).parameters
    except (TypeError, ValueError):  # C-accelerated / unsignaturable wrapper
        return True


#: True on jax releases with vma-typed shard_map (``jax.shard_map`` accepts
#: ``check_vma``).  Pre-vma releases have no implicit psum in the transpose
#: of replicated inputs — gradient-sync code keys manual psums off this
#: flag.
HAS_VMA = _native_has_vma()

if not HAS_VMA:
    if _NATIVE_SHARD_MAP is not None:
        # promoted-but-pre-vma window: the top-level function exists but
        # speaks check_rep; route it through the same translation as the
        # experimental one.
        _experimental_shard_map = _NATIVE_SHARD_MAP
    else:
        from jax.experimental.shard_map import (
            shard_map as _experimental_shard_map,
        )

# Bind natives ONCE, before install() grafts the fallbacks onto jax.lax —
# a dynamic getattr inside the fallbacks would find the graft itself and
# recurse.
_NATIVE_AXIS_SIZE = getattr(jax.lax, "axis_size", None)
_NATIVE_PCAST = getattr(jax.lax, "pcast", None)


def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
    """``jax.shard_map`` with ``check_vma`` accepted on every jax."""
    if HAS_VMA:
        return _NATIVE_SHARD_MAP(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    kwargs.pop("check_vma", None)
    if kwargs:
        # Refuse rather than silently run with different semantics on the
        # pinned release — the divergence this layer exists to prevent.
        raise TypeError(
            "shard_map compat fallback does not support kwargs "
            f"{sorted(kwargs)} on jax {jax.__version__}"
        )
    return _experimental_shard_map(
        f, mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def axis_size(axis_name):
    """Size of a bound mesh axis; ``NameError`` when unbound."""
    if _NATIVE_AXIS_SIZE is not None:
        return _NATIVE_AXIS_SIZE(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axis_name, *, to):
    """``jax.lax.pcast`` (vma re-typing); identity on pre-vma jax."""
    if _NATIVE_PCAST is not None:
        return _NATIVE_PCAST(x, axis_name, to=to)
    if to not in ("varying", "invariant"):
        raise ValueError(f"pcast: unknown target {to!r}")
    return x


def install() -> None:
    """Graft the modern spellings onto ``jax`` / ``jax.lax`` when absent.

    Idempotent; touches nothing on releases that already ship the names.
    Lets test/example/tool code keep the one modern spelling
    (``jax.shard_map`` / ``jax.lax.axis_size`` / ``jax.lax.pcast``)
    everywhere.
    """
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = axis_size
    if not hasattr(jax.lax, "pcast"):
        jax.lax.pcast = pcast


install()
