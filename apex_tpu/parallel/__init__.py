"""Data-parallel layer — ≙ apex/parallel.

- :mod:`apex_tpu.parallel.comm` — the ONE gradient-sync engine (wire
  formats f32/bf16/int8, chunked overlap, HLO verification hooks) that
  DDP and the ZeRO optimizers share (see ``docs/comm.md``);
- :class:`DistributedDataParallel`, :func:`all_reduce_gradients`,
  :class:`Reducer` (≙ apex/parallel/distributed.py);
- :class:`SyncBatchNorm`, :func:`convert_syncbn_model`
  (≙ optimized_sync_batchnorm*.py + csrc/syncbn);
- :class:`LARC` (≙ apex/parallel/LARC.py — re-exported from optimizers);
- :class:`DistributedFusedAdam` / :class:`DistributedFusedLAMB`
  (≙ apex/contrib/optimizers ZeRO-sharded updates).

``apex/parallel/multiproc.py`` (the one-node process spawner) has no
analog: a single SPMD program drives every device.  Multi-host jobs join
the global runtime through :func:`initialize_distributed`
(``apex_tpu.parallel.multihost`` — ≙ ``torch.distributed
.init_process_group``), after which every mesh collective spans hosts;
``initialize_model_parallel(dcn_data_parallel=True)`` lays dp across DCN
and keeps model axes on ICI.
"""

from apex_tpu.optimizers.larc import LARC, larc  # noqa: F401
from apex_tpu.parallel import comm  # noqa: F401  (the shared sync engine)
from apex_tpu.parallel.comm import (  # noqa: F401
    all_gather_flat,
    collective_summary,
    reduce_scatter_flat,
    sync_gradients,
)
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    Reducer,
    all_reduce_gradients,
)
from apex_tpu.parallel.distributed_fused_optimizers import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.parallel.quantized import (  # noqa: F401
    quantized_all_reduce_gradients,
)
from apex_tpu.parallel.multihost import (  # noqa: F401
    distributed_is_initialized,
    finalize_distributed,
    initialize_distributed,
)
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
)
