"""Data-parallel layer — ≙ apex/parallel.

- :class:`DistributedDataParallel`, :func:`all_reduce_gradients`,
  :class:`Reducer` (≙ apex/parallel/distributed.py);
- :class:`SyncBatchNorm`, :func:`convert_syncbn_model`
  (≙ optimized_sync_batchnorm*.py + csrc/syncbn);
- :class:`LARC` (≙ apex/parallel/LARC.py — re-exported from optimizers);
- :class:`DistributedFusedAdam` / :class:`DistributedFusedLAMB`
  (≙ apex/contrib/optimizers ZeRO-sharded updates).

``apex/parallel/multiproc.py`` (the one-node process spawner) has no
analog: a single SPMD program drives every device, and multi-host jobs are
launched by the cluster runtime (``jax.distributed.initialize``).
"""

from apex_tpu.optimizers.larc import LARC, larc  # noqa: F401
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    Reducer,
    all_reduce_gradients,
)
from apex_tpu.parallel.distributed_fused_optimizers import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
)
