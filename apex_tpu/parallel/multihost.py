"""Multi-host bootstrap — ≙ ``torch.distributed.init_process_group`` +
``apex/parallel/multiproc.py``'s role in the reference stack.

The reference builds its communication world from NCCL process groups that
every rank must join explicitly.  JAX is SPMD: each *host process* joins a
single global runtime (``jax.distributed.initialize``), after which
``jax.devices()`` returns the GLOBAL device list and every collective in
this library (``psum`` / ``all_gather`` / ``psum_scatter`` / ``ppermute``
over mesh axes) spans hosts automatically — ICI within a slice, DCN
across slices.  There are no per-group objects to manage; the mesh axes of
:func:`apex_tpu.parallel_state.initialize_model_parallel` play that role.

Typical multi-host entry::

    from apex_tpu.parallel import initialize_distributed
    from apex_tpu import parallel_state as ps

    initialize_distributed()                      # env-autodetected (TPU pods)
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size=8,             # tp inside a host: ICI
        dcn_data_parallel=True,                   # dp outermost: across DCN
    )

On Cloud TPU the coordinator/process count/process id are discovered from
the TPU metadata, so ``initialize_distributed()`` takes no arguments
there; for CPU/GPU clusters pass them explicitly (≙ the reference's
``init_method="env://"`` rendezvous).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax

__all__ = [
    "initialize_distributed",
    "distributed_is_initialized",
    "finalize_distributed",
    "cluster_env_hints",
    "host_barrier",
    "host_id",
    "host_count",
]

_INITIALIZED = False

#: Env vars whose presence means "this process was launched into a cluster"
#: — the discriminator between a benign single-process run and a pod join
#: that actually failed.
_CLUSTER_ENV_HINTS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "SLURM_JOB_NUM_NODES",
)


def cluster_env_hints() -> Tuple[str, ...]:
    """Names of the cluster-environment variables set for this process.

    Non-empty means a failed ``jax.distributed.initialize`` is a real
    error (a pod member degrading to single-process), not a laptop run.
    """
    import os

    return tuple(k for k in _CLUSTER_ENV_HINTS if os.environ.get(k))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
    strict: bool = False,
) -> Tuple[int, int]:
    """Join the global JAX runtime; returns ``(process_index, process_count)``.

    ≙ ``torch.distributed.init_process_group(backend="nccl", ...)``.  Safe
    to call unconditionally: a single-process run (no coordinator given,
    no cluster env detected) is a no-op that reports ``(0, 1)``, so the
    same training script works from one chip to a pod — the reference
    needs its launcher to decide; here the runtime does.

    Not to be confused with
    ``apex_tpu.transformer.testing.commons.initialize_distributed`` (a
    test-fixture shim that builds and returns a *Mesh*, mirroring the
    reference's testing commons of the same name) — this one joins the
    process runtime and returns rank info.

    ``strict=True`` turns the "cluster env hints present but the join
    failed" path from a ``RuntimeWarning`` into a raised ``RuntimeError``
    — the contract :func:`apex_tpu.resilience.retry
    .robust_initialize_distributed` needs to retry the rendezvous instead
    of letting a pod member silently degrade to single-process.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return jax.process_index(), jax.process_count()
    # NOTE: jax.distributed.initialize must run before anything touches the
    # XLA backend (even jax.devices/process_count), so the explicit path
    # goes first and unconditionally.
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        _INITIALIZED = True
    else:
        try:
            # Autodetect (TPU pod metadata / cluster env).  Raises when no
            # cluster environment is present (the one-process case) or the
            # backend is already live — both leave the runtime as-is.
            # Explicit world parameters are forwarded so a caller-supplied
            # rank/size is never silently overridden by env autodetect.
            jax.distributed.initialize(
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids,
            )
            _INITIALIZED = True
        except Exception as e:
            # Distinguish "no cluster env" (fine: single-process) from
            # "cluster env present but the join failed" — the latter would
            # otherwise silently degrade a pod job into N independent
            # single-process runs training divergent copies.
            hints = cluster_env_hints()
            if hints:
                msg = (
                    "cluster environment detected "
                    f"({', '.join(hints)}) but jax.distributed.initialize "
                    f"failed ({type(e).__name__}: {e})"
                )
                if strict:
                    raise RuntimeError(msg) from e
                import warnings

                warnings.warn(
                    msg + "; continuing SINGLE-process — multi-host "
                    "collectives will NOT span hosts",
                    RuntimeWarning,
                    stacklevel=2,
                )
            elif strict and (
                num_processes is not None or process_id is not None
            ):
                raise RuntimeError(
                    "explicit rendezvous parameters given but "
                    f"initialization failed ({type(e).__name__}: {e})"
                ) from e
    return jax.process_index(), jax.process_count()


def distributed_is_initialized() -> bool:
    """Whether this process joined a (multi-process) JAX runtime.

    Deliberately does NOT touch the XLA backend (no ``jax.devices()`` /
    ``process_count()``): the guard pattern ``if not
    distributed_is_initialized(): initialize_distributed(...)`` must stay
    legal, and backend init before ``jax.distributed.initialize`` is an
    error.  Consults this module's flag plus the runtime's own client
    state (covers users who called ``jax.distributed.initialize``
    directly).
    """
    if _INITIALIZED:
        return True
    try:
        from jax._src import distributed as _jax_distributed

        return _jax_distributed.global_state.client is not None
    except Exception:
        return False


def host_barrier(tag: str, step: int = 0) -> None:
    """Block until every process reaches the barrier named ``tag``.

    A no-op in a single-process run; multi-process it is
    ``multihost_utils.sync_global_devices`` — the host-side collective a
    resilient loop uses to agree "everyone stopped at step N" before the
    final checkpoint (see :func:`apex_tpu.resilience.runner.run_resilient`).

    This is the chaos ``COLLECTIVE`` site: an injected ``raise`` fault
    stands in for a collective abort (propagates — a real abort kills the
    job), ``stall`` for a slow straggler (sleeps, then proceeds).
    """
    from apex_tpu.resilience import chaos

    chaos.maybe_fail(chaos.COLLECTIVE, step)
    if distributed_is_initialized():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def host_id() -> int:
    """This process's rank in the host fleet (``jax.process_index``;
    0 in a single-process run).

    The label the observability layer stamps on everything host-scoped:
    fleet-aggregation rows, flight-recorder dumps, straggler health
    events.  Safe on a torn-down runtime — a dying process writing its
    flight dump must not crash on the label — degrading to 0.
    """
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def host_count() -> int:
    """Number of host processes in the fleet (1 single-process; same
    degradation contract as :func:`host_id`)."""
    try:
        return int(jax.process_count())
    except Exception:
        return 1


def finalize_distributed() -> None:
    """≙ ``torch.distributed.destroy_process_group`` (idempotent).

    Teardown is best-effort: when ``jax.distributed.shutdown`` raises
    mid-teardown (coordinator already gone, socket torn down by a
    preemption notice, ...) the module still resets its state and only
    *warns* — a dying run must be able to reach its final checkpoint
    instead of tripping over distributed cleanup, and a later
    re-initialize must not be wedged by a stale ``_INITIALIZED`` flag.
    """
    global _INITIALIZED
    if _INITIALIZED:
        try:
            jax.distributed.shutdown()
        except Exception as e:
            import warnings

            warnings.warn(
                "jax.distributed.shutdown failed mid-teardown "
                f"({type(e).__name__}: {e}); distributed state reset anyway",
                RuntimeWarning,
                stacklevel=2,
            )
        finally:
            _INITIALIZED = False
