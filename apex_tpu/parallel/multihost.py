"""Multi-host bootstrap — ≙ ``torch.distributed.init_process_group`` +
``apex/parallel/multiproc.py``'s role in the reference stack.

The reference builds its communication world from NCCL process groups that
every rank must join explicitly.  JAX is SPMD: each *host process* joins a
single global runtime (``jax.distributed.initialize``), after which
``jax.devices()`` returns the GLOBAL device list and every collective in
this library (``psum`` / ``all_gather`` / ``psum_scatter`` / ``ppermute``
over mesh axes) spans hosts automatically — ICI within a slice, DCN
across slices.  There are no per-group objects to manage; the mesh axes of
:func:`apex_tpu.parallel_state.initialize_model_parallel` play that role.

Typical multi-host entry::

    from apex_tpu.parallel import initialize_distributed
    from apex_tpu import parallel_state as ps

    initialize_distributed()                      # env-autodetected (TPU pods)
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size=8,             # tp inside a host: ICI
        dcn_data_parallel=True,                   # dp outermost: across DCN
    )

On Cloud TPU the coordinator/process count/process id are discovered from
the TPU metadata, so ``initialize_distributed()`` takes no arguments
there; for CPU/GPU clusters pass them explicitly (≙ the reference's
``init_method="env://"`` rendezvous).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax

__all__ = [
    "initialize_distributed",
    "distributed_is_initialized",
    "finalize_distributed",
]

_INITIALIZED = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> Tuple[int, int]:
    """Join the global JAX runtime; returns ``(process_index, process_count)``.

    ≙ ``torch.distributed.init_process_group(backend="nccl", ...)``.  Safe
    to call unconditionally: a single-process run (no coordinator given,
    no cluster env detected) is a no-op that reports ``(0, 1)``, so the
    same training script works from one chip to a pod — the reference
    needs its launcher to decide; here the runtime does.

    Not to be confused with
    ``apex_tpu.transformer.testing.commons.initialize_distributed`` (a
    test-fixture shim that builds and returns a *Mesh*, mirroring the
    reference's testing commons of the same name) — this one joins the
    process runtime and returns rank info.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return jax.process_index(), jax.process_count()
    # NOTE: jax.distributed.initialize must run before anything touches the
    # XLA backend (even jax.devices/process_count), so the explicit path
    # goes first and unconditionally.
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        _INITIALIZED = True
    else:
        try:
            # Autodetect (TPU pod metadata / cluster env).  Raises when no
            # cluster environment is present (the one-process case) or the
            # backend is already live — both leave the runtime as-is.
            jax.distributed.initialize()
            _INITIALIZED = True
        except Exception as e:
            # Distinguish "no cluster env" (fine: single-process) from
            # "cluster env present but the join failed" — the latter would
            # otherwise silently degrade a pod job into N independent
            # single-process runs training divergent copies.
            import os

            hints = [
                k
                for k in (
                    "JAX_COORDINATOR_ADDRESS",
                    "COORDINATOR_ADDRESS",
                    "MEGASCALE_COORDINATOR_ADDRESS",
                    "SLURM_JOB_NUM_NODES",
                )
                if os.environ.get(k)
            ]
            if hints:
                import warnings

                warnings.warn(
                    "cluster environment detected "
                    f"({', '.join(hints)}) but jax.distributed.initialize "
                    f"failed ({type(e).__name__}: {e}); continuing "
                    "SINGLE-process — multi-host collectives will NOT span "
                    "hosts",
                    RuntimeWarning,
                    stacklevel=2,
                )
    return jax.process_index(), jax.process_count()


def distributed_is_initialized() -> bool:
    """Whether this process joined a (multi-process) JAX runtime.

    Deliberately does NOT touch the XLA backend (no ``jax.devices()`` /
    ``process_count()``): the guard pattern ``if not
    distributed_is_initialized(): initialize_distributed(...)`` must stay
    legal, and backend init before ``jax.distributed.initialize`` is an
    error.  Consults this module's flag plus the runtime's own client
    state (covers users who called ``jax.distributed.initialize``
    directly).
    """
    if _INITIALIZED:
        return True
    try:
        from jax._src import distributed as _jax_distributed

        return _jax_distributed.global_state.client is not None
    except Exception:
        return False


def finalize_distributed() -> None:
    """≙ ``torch.distributed.destroy_process_group`` (idempotent)."""
    global _INITIALIZED
    if _INITIALIZED:
        try:
            jax.distributed.shutdown()
        finally:
            _INITIALIZED = False
