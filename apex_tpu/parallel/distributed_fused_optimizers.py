"""ZeRO-style weight-update-sharded optimizers.

≙ ``apex/contrib/optimizers/distributed_fused_adam.py`` ::
``DistributedFusedAdam`` and ``.../distributed_fused_lamb.py`` ::
``DistributedFusedLamb`` (grads reduce-scattered over the data-parallel
group, shard-local fused update, params all-gathered; the technique TPU
literature calls automatic cross-replica sharding of the weight update —
see PAPERS.md).

Mapping to XLA collectives (inside ``shard_map`` over the ``dp`` axis):

- the reference's two-level NCCL reduce-scatter pipeline
  (``_pipeline_block_reductions``) → one ``jax.lax.psum_scatter`` over a
  flat f32 buffer (XLA schedules/overlaps);
- shard-local ``multi_tensor_adam``/``multi_tensor_lamb`` → elementwise
  update on the shard, with LAMB's per-tensor norms via ``segment_sum``
  over leaf-id segments + ``psum`` (the shard boundary does not align with
  tensor boundaries, exactly like the reference's flat buffer);
- param all-gather (``full_ar=False`` path) → ``jax.lax.all_gather(...,
  tiled=True)``.

Optimizer state (m, v, and the f32 ``master`` params) lives permanently
sharded: global arrays of shape ``(padded_size,)`` with sharding
``P("dp")`` — each device owns ``padded_size // world`` elements, the
1/N memory footprint that is the point of ZeRO.

The ``master`` shard is the AUTHORITATIVE param value (classic ZeRO
master weights): the update applies to it in f32 every step, and the
all-gathered replicated tree is only the working copy the next
forward/backward reads.  That is what makes a lossy ``param_wire``
safe — a bf16 gather rounds the working copy, never the accumulator,
so updates smaller than a wire ulp still accumulate instead of being
re-rounded away step after step.  (Consequence: edits to the replicated
params tree between steps are ignored; reinitialize via :meth:`init`
to reset the masters.)

Both collectives run through :mod:`apex_tpu.parallel.comm` (the engine
shared with ``DistributedDataParallel`` — see ``docs/comm.md``):
``wire="bf16" | "int8"`` swaps the f32 wire for a quantized one (f32
shard-local accumulation either way; ~2x / ~4x fewer sync bytes — the
analog of the reference LAMB's ``fp16 compressed allgather`` knob, which
r0 recorded as having "no XLA analog": it does now), and ``chunks=K``
splits the flat buffer so XLA can overlap chunk N's collective with
chunk N-1's dequant/optimizer math.  ``param_wire`` overrides the wire
for the param all-gather alone — it sets the precision of the WORKING
copy the forward/backward reads (the f32 masters below are never
rounded), so ``wire="int8", param_wire="bf16"`` is the recommended
aggressive setting: grads tolerate coarse wires, activations want the
params at >= bf16.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import _compat
from apex_tpu import parallel_state as ps
from apex_tpu._tree_util import to_f32
from apex_tpu.parallel import comm

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB"]


class _FlatSpec(NamedTuple):
    flat_size: int
    padded_size: int
    shard_size: int
    world: int
    n_leaves: int
    unravel: Any  # host closure flat f32 -> param tree
    segment_ids: np.ndarray  # (padded_size,) int32 leaf index, pad -> n_leaves


def _make_spec(params, world: int) -> _FlatSpec:
    flat, unravel = ravel_pytree(
        jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    )
    flat_size = flat.size
    shard = -(-flat_size // world)  # ceil
    padded = shard * world
    leaves = jax.tree_util.tree_leaves(params)
    seg = np.full((padded,), len(leaves), np.int32)
    off = 0
    for i, leaf in enumerate(leaves):
        seg[off : off + leaf.size] = i
        off += leaf.size
    return _FlatSpec(
        flat_size=flat_size,
        padded_size=padded,
        shard_size=shard,
        world=world,
        n_leaves=len(leaves),
        unravel=unravel,
        segment_ids=seg,
    )


def _flatten_pad(tree, spec: _FlatSpec):
    flat, _ = ravel_pytree(to_f32(tree))
    return jnp.pad(flat, (0, spec.padded_size - spec.flat_size))


class _DistributedFusedBase:
    def __init__(
        self,
        axis_name: str = ps.DATA_PARALLEL_AXIS,
        wire: str = "f32",
        chunks: int | None = None,
        block: int = comm.DEFAULT_BLOCK,
        param_wire: str | None = None,
    ):
        self.axis_name = axis_name
        self.wire = comm.check_wire(wire)
        self.chunks = chunks
        self.block = block
        self.param_wire = (
            comm.check_wire(param_wire) if param_wire is not None else None
        )
        self._spec: _FlatSpec | None = None

    # -- host-side ------------------------------------------------------
    def init(self, params, world: int | None = None):
        """Returns the sharded state pytree (place with sharding P(dp));
        ``state.master`` is seeded with the flattened f32 params — the
        authoritative copy every later update applies to."""
        world = world or ps.get_data_parallel_world_size()
        self._spec = _make_spec(params, world)
        state = self._init_state(self._spec)
        return state._replace(master=_flatten_pad(params, self._spec))

    def state_sharding(self, mesh=None):
        """NamedShardings for the state (flat arrays sharded over dp)."""
        mesh = mesh or ps.get_mesh()
        flat_sh = NamedSharding(mesh, P(self.axis_name))
        return jax.tree_util.tree_map(
            lambda x: flat_sh if getattr(x, "ndim", 0) == 1 else NamedSharding(mesh, P()),
            self._init_state(self._spec),
        )

    @property
    def spec(self) -> _FlatSpec:
        if self._spec is None:
            raise RuntimeError("call init(params) first")
        return self._spec

    def collective_plan(self) -> dict:
        """The per-mesh-axis collective plan one sharded step promises
        (``analysis.sharding.reshard_pass`` schema): the chunked
        grad reduce-scatter at ``wire``, the param all-gather at
        ``param_wire or wire``, and the small norm/loss all-reduces —
        via :func:`apex_tpu.parallel.comm.zero_plan` on this
        optimizer's own flat spec.  Call after :meth:`init`."""
        spec = self.spec
        return {
            "mesh": {self.axis_name: spec.world},
            "collectives": comm.zero_plan(
                spec.flat_size, spec.world, self.axis_name,
                wire=self.wire, param_wire=self.param_wire,
                chunks=self.chunks, block=self.block,
            ),
        }

    # -- device-side (inside shard_map over the dp axis) ----------------
    def reduce_scatter_grads(self, grads, gradient_average: bool = True):
        """Local grads tree -> my reduced flat shard (f32), via the comm
        engine's (possibly quantized, possibly chunked) reduce-scatter
        with f32 shard-local accumulation."""
        spec = self.spec
        flat = _flatten_pad(grads, spec)
        shard = comm.reduce_scatter_flat(
            flat, self.axis_name,
            wire=self.wire, chunks=self.chunks, block=self.block,
        )
        if gradient_average:
            shard = shard / spec.world
        return shard

    def my_param_shard(self, params):
        spec = self.spec
        flat = _flatten_pad(params, spec)
        rank = jax.lax.axis_index(self.axis_name)
        return jax.lax.dynamic_slice(flat, (rank * spec.shard_size,), (spec.shard_size,))

    def my_segment_ids(self):
        spec = self.spec
        rank = jax.lax.axis_index(self.axis_name)
        seg = jnp.asarray(spec.segment_ids)
        return jax.lax.dynamic_slice(seg, (rank * spec.shard_size,), (spec.shard_size,))

    def gather_params(self, new_param_shard, params_template):
        """All-gather updated shards and rebuild the (dtype-cast) tree.

        Runs at ``param_wire`` (default: follow ``wire``); every rank
        decodes the same payloads — its own included — so params stay
        bit-identical across replicas whatever the wire."""
        spec = self.spec
        flat = comm.all_gather_flat(
            new_param_shard, self.axis_name,
            wire=self.param_wire or self.wire,
            chunks=self.chunks, block=self.block,
        )
        tree = spec.unravel(flat[: spec.flat_size])
        return jax.tree_util.tree_map(
            lambda t, x: x.astype(t.dtype), params_template, tree
        )

    def update_inside_shard_map(self, grads, state, params,
                                gradient_average: bool = True):
        """Full sharded step: returns (new_params, new_state).

        ``grads`` must be *local* per-shard gradients (not yet reduced):
        under ``check_vma=True`` shard_map, mark params varying first
        (``_compat.pcast(p, axis, to='varying')``) or jax's autodiff will
        have already all-reduced them and the reduce-scatter here would
        double-count.

        The update applies to ``state.master`` (the f32 shard), never to
        the possibly-wire-rounded ``params`` — ``params`` only supplies
        the tree structure/dtypes for the gathered working copy.
        """
        g_shard = self.reduce_scatter_grads(grads, gradient_average)
        new_p_shard, new_state = self._shard_update(
            g_shard, state, state.master
        )
        new_state = new_state._replace(master=new_p_shard)
        return self.gather_params(new_p_shard, params), new_state

    def update_with_norm(self, grads, state, params,
                         gradient_average: bool = True):
        """:meth:`update_inside_shard_map` that also returns the global
        L2 norm of the reduced (averaged) gradient — measured on the
        reduce-scattered shards, so it costs one extra scalar psum and
        nothing else.  The shards partition the flat buffer exactly, so
        the psum of per-shard square-sums is the exact norm of the
        gradient the update consumed (per ``axis_name`` group: with an
        additional tp axis the flat buffer duplicates tp-replicated
        leaves, so callers wanting a global norm there must account for
        it — :class:`apex_tpu.train.Trainer` refuses that combination).
        """
        g_shard = self.reduce_scatter_grads(grads, gradient_average)
        norm = jnp.sqrt(
            jax.lax.psum(jnp.sum(g_shard * g_shard), self.axis_name)
        )
        new_p_shard, new_state = self._shard_update(
            g_shard, state, state.master
        )
        new_state = new_state._replace(master=new_p_shard)
        return self.gather_params(new_p_shard, params), new_state, norm

    # -- convenience ----------------------------------------------------
    def make_train_step(self, loss_fn, mesh=None):
        """jitted SPMD step: (params, state, batch) -> (params, state, loss).

        ``batch`` sharded over dp; params replicated; state sharded.

        Runs with ``check_vma=False`` (classic manual-collective semantics):
        gradients stay *local* per shard so the communication pattern is a
        true reduce-scatter + all-gather — the ZeRO structure the reference
        implements — rather than the full grad all-reduce jax's vma
        autodiff would otherwise insert for replicated params.
        """
        mesh = mesh or ps.get_mesh()

        def _step(params, state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            loss = jax.lax.pmean(loss, self.axis_name)
            params, state = self.update_inside_shard_map(grads, state, params)
            return params, state, loss

        state_spec = jax.tree_util.tree_map(
            lambda x: P(self.axis_name) if getattr(x, "ndim", 0) == 1 else P(),
            self._init_state(self.spec),
        )
        smapped = _compat.shard_map(
            _step,
            mesh=mesh,
            in_specs=(P(), state_spec, P(self.axis_name)),
            out_specs=(P(), state_spec, P()),
            check_vma=False,
        )
        return jax.jit(smapped)


class _AdamState(NamedTuple):
    count: jax.Array
    m: jax.Array  # (padded,) sharded over dp
    v: jax.Array
    master: jax.Array  # (padded,) f32 authoritative params, sharded over dp


class DistributedFusedAdam(_DistributedFusedBase):
    """≙ apex.contrib.optimizers.DistributedFusedAdam (ZeRO Adam(W))."""

    def __init__(
        self,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        adam_w_mode: bool = True,
        bias_correction: bool = True,
        axis_name: str = ps.DATA_PARALLEL_AXIS,
        wire: str = "f32",
        chunks: int | None = None,
        block: int = comm.DEFAULT_BLOCK,
        param_wire: str | None = None,
    ):
        super().__init__(axis_name, wire=wire, chunks=chunks, block=block,
                         param_wire=param_wire)
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction

    def _init_state(self, spec: _FlatSpec):
        return _AdamState(
            count=jnp.zeros((), jnp.int32),
            m=jnp.zeros((spec.padded_size,), jnp.float32),
            v=jnp.zeros((spec.padded_size,), jnp.float32),
            master=jnp.zeros((spec.padded_size,), jnp.float32),
        )

    def _shard_update(self, g, state: _AdamState, p):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - self.beta1**cf if self.bias_correction else 1.0
        bc2 = 1.0 - self.beta2**cf if self.bias_correction else 1.0
        if not self.adam_w_mode and self.weight_decay != 0.0:
            g = g + self.weight_decay * p
        m = self.beta1 * state.m + (1.0 - self.beta1) * g
        v = self.beta2 * state.v + (1.0 - self.beta2) * g * g
        u = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and self.weight_decay != 0.0:
            u = u + self.weight_decay * p
        return p - self.lr * u, _AdamState(
            count=count, m=m, v=v, master=state.master
        )


class _LambState(NamedTuple):
    count: jax.Array
    m: jax.Array
    v: jax.Array
    master: jax.Array  # (padded,) f32 authoritative params, sharded over dp


class DistributedFusedLAMB(_DistributedFusedBase):
    """≙ apex.contrib.optimizers.DistributedFusedLAMB (ZeRO LAMB).

    The reference's ``clip_after_ar`` (clip by the global grad norm after
    the all-reduce), per-tensor trust ratios across shard boundaries, and
    nvlamb gating are reproduced; its fp16 compressed-allgather knob maps
    to ``param_wire="bf16"`` (and grads go further: ``wire="int8"`` —
    see ``docs/comm.md``).
    """

    def __init__(
        self,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        bias_correction: bool = True,
        grad_averaging: bool = True,
        adam_w_mode: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        axis_name: str = ps.DATA_PARALLEL_AXIS,
        wire: str = "f32",
        chunks: int | None = None,
        block: int = comm.DEFAULT_BLOCK,
        param_wire: str | None = None,
    ):
        super().__init__(axis_name, wire=wire, chunks=chunks, block=block,
                         param_wire=param_wire)
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.grad_averaging = grad_averaging
        self.adam_w_mode = adam_w_mode
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def _init_state(self, spec: _FlatSpec):
        return _LambState(
            count=jnp.zeros((), jnp.int32),
            m=jnp.zeros((spec.padded_size,), jnp.float32),
            v=jnp.zeros((spec.padded_size,), jnp.float32),
            master=jnp.zeros((spec.padded_size,), jnp.float32),
        )

    def _shard_update(self, g, state: _LambState, p):
        spec = self.spec
        seg = self.my_segment_ids()
        nseg = spec.n_leaves + 1  # +1 = padding segment
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - self.beta1**cf if self.bias_correction else 1.0
        bc2 = 1.0 - self.beta2**cf if self.bias_correction else 1.0
        beta3 = (1.0 - self.beta1) if self.grad_averaging else 1.0

        # global grad norm over all shards (clip_after_ar semantics)
        gnorm = jnp.sqrt(
            jax.lax.psum(jnp.sum(g * g), self.axis_name)
        )
        clip_ratio = jnp.where(
            (self.max_grad_norm > 0.0) & (gnorm > self.max_grad_norm),
            gnorm / self.max_grad_norm,
            1.0,
        )
        g = g / clip_ratio
        if not self.adam_w_mode and self.weight_decay != 0.0:
            g = g + self.weight_decay * p

        m = self.beta1 * state.m + beta3 * g
        v = self.beta2 * state.v + (1.0 - self.beta2) * g * g
        u = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and self.weight_decay != 0.0:
            u = u + self.weight_decay * p

        # per-tensor norms across shard boundaries: segment partials + psum
        w_sq = jax.ops.segment_sum(p * p, seg, num_segments=nseg)
        u_sq = jax.ops.segment_sum(u * u, seg, num_segments=nseg)
        w_norm = jnp.sqrt(jax.lax.psum(w_sq, self.axis_name))
        u_norm = jnp.sqrt(jax.lax.psum(u_sq, self.axis_name))
        ratio = jnp.where((w_norm > 0.0) & (u_norm > 0.0), w_norm / u_norm, 1.0)
        if not self.use_nvlamb and self.weight_decay == 0.0:
            ratio = jnp.ones_like(ratio)
        r = ratio[seg]
        return p - self.lr * r * u, _LambState(
            count=count, m=m, v=v, master=state.master
        )
