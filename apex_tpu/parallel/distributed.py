"""Data parallelism — ≙ apex/parallel/distributed.py.

The reference's ``DistributedDataParallel`` flattens gradients into
~``message_size`` buckets and overlaps NCCL all-reduce with backward via
grad-accumulator hooks (SURVEY.md §3.3).  Under XLA none of that machinery
exists or is needed: gradients of a jitted step are all-reduced with
``psum`` over the ``dp`` mesh axis, and the XLA scheduler overlaps the
collectives with remaining backward compute (the bucketing/ready-order
capture is the compiler's job).  What this module keeps is the *semantics
surface*: gradient averaging, predivide factors (for large world sizes where
pre-division avoids overflow in half precision), a ``delay_allreduce``-style
no-op escape, and the ``Reducer`` manual-reduction helper.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = ["all_reduce_gradients", "DistributedDataParallel", "Reducer"]


def all_reduce_gradients(
    grads: Any,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    gradient_average: bool = True,
    gradient_predivide_factor: Optional[float] = None,
):
    """psum gradients over the data-parallel axis (call inside shard_map).

    ≙ the flat_dist_call all-reduce + ``gradient_average`` /
    ``gradient_predivide_factor`` handling in
    apex/parallel/distributed.py :: DistributedDataParallel.
    """
    world = _compat.axis_size(axis_name)

    def f(g):
        gf = g
        if gradient_predivide_factor is not None:
            gf = gf / gradient_predivide_factor
        gf = jax.lax.psum(gf, axis_name)
        if gradient_average:
            post = (
                world / gradient_predivide_factor
                if gradient_predivide_factor is not None
                else world
            )
            gf = gf / post
        return gf

    with jax.named_scope("ddp_allreduce"):
        return jax.tree_util.tree_map(f, grads)


class DistributedDataParallel:
    """Wraps a loss function for data-parallel training.

    ≙ ``apex.parallel.DistributedDataParallel(model, message_size=...,
    gradient_average=..., gradient_predivide_factor=...)``.  The
    ``message_size``/``allreduce_trigger_params`` bucketing knobs have no
    analog (XLA fuses and schedules collectives); ``delay_allreduce`` maps
    to ``delay_allreduce=True`` → the wrapper skips the psum so the caller
    reduces manually (e.g. once after gradient accumulation).

    Usage::

        ddp = DistributedDataParallel(loss_fn)
        step = ddp.make_step(tx, mesh)           # jitted SPMD train step
        params, opt_state, loss = step(params, opt_state, batch)

    or, inside your own ``shard_map``::

        loss, grads = ddp.value_and_grad(params, batch)
    """

    def __init__(
        self,
        loss_fn: Callable,
        axis_name: str = ps.DATA_PARALLEL_AXIS,
        gradient_average: bool = True,
        gradient_predivide_factor: Optional[float] = None,
        delay_allreduce: bool = False,
    ):
        self.loss_fn = loss_fn
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.delay_allreduce = delay_allreduce

    def value_and_grad(self, params, *batch):
        """Per-shard loss + dp-reduced grads; call inside shard_map.

        Under jax's shard_map vma semantics, differentiating w.r.t.
        *replicated* params already inserts the cross-shard psum in the
        transpose (the bucketed all-reduce the reference implements by
        hand).  The fast path therefore only divides for averaging.  The
        ``delay_allreduce`` / predivide paths need genuinely *local* grads,
        so params are marked varying (``pcast to='varying'``) first, which
        suppresses the automatic psum.
        """
        if self.delay_allreduce or self.gradient_predivide_factor is not None:
            params_v = jax.tree_util.tree_map(
                lambda p: _compat.pcast(p, self.axis_name, to="varying"),
                params,
            )
            loss, grads = jax.value_and_grad(self.loss_fn)(params_v, *batch)
            if not self.delay_allreduce:
                grads = all_reduce_gradients(
                    grads,
                    self.axis_name,
                    self.gradient_average,
                    self.gradient_predivide_factor,
                )
                loss = jax.lax.pmean(loss, self.axis_name)
            return loss, grads
        loss, grads = jax.value_and_grad(self.loss_fn)(params, *batch)
        if not _compat.HAS_VMA:
            # pre-vma jax inserts no implicit psum in the transpose of
            # replicated params — reduce by hand to keep the fast-path
            # contract (grads arrive dp-summed) identical across releases
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, self.axis_name), grads
            )
        if self.gradient_average:
            world = _compat.axis_size(self.axis_name)
            grads = jax.tree_util.tree_map(lambda g: g / world, grads)
            loss = jax.lax.pmean(loss, self.axis_name)
        return loss, grads

    def make_step(self, tx, mesh=None):
        """Build a jitted SPMD train step: batch sharded over dp, params
        replicated, grads psummed, optimizer applied identically on every
        device."""
        mesh = mesh or ps.get_mesh()

        def _step(params, opt_state, batch):
            loss, grads = self.value_and_grad(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates
            )
            return params, opt_state, loss

        batch_spec = P(self.axis_name)
        smapped = _compat.shard_map(
            _step,
            mesh=mesh,
            in_specs=(P(), P(), batch_spec),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(smapped)


class Reducer:
    """Manual-reduction helper — ≙ apex/parallel/distributed.py :: Reducer.

    ``broadcast_params`` is a no-op under SPMD (all replicas trace the same
    init); ``reduce`` psums a pytree on demand.
    """

    def __init__(self, axis_name: str = ps.DATA_PARALLEL_AXIS):
        self.axis_name = axis_name

    def broadcast_params(self, params):
        return params  # replicated by construction

    def reduce(self, tree, average: bool = True):
        world = _compat.axis_size(self.axis_name)

        def f(x):
            s = jax.lax.psum(x, self.axis_name)
            return s / world if average else s

        return jax.tree_util.tree_map(f, tree)
