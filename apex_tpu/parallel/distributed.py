"""Data parallelism — ≙ apex/parallel/distributed.py.

The reference's ``DistributedDataParallel`` flattens gradients into
~``message_size`` buckets and overlaps NCCL all-reduce with backward via
grad-accumulator hooks (SURVEY.md §3.3).  Under XLA none of that machinery
exists or is needed: gradients of a jitted step are all-reduced with
``psum`` over the ``dp`` mesh axis, and the XLA scheduler overlaps the
collectives with remaining backward compute (the bucketing/ready-order
capture is the compiler's job).  What this module keeps is the *semantics
surface*: gradient averaging, predivide factors (for large world sizes where
pre-division avoids overflow in half precision), ``delay_allreduce`` /
``no_sync`` gradient accumulation, and the ``Reducer`` manual-reduction
helper.

Gradient sync itself is delegated to :mod:`apex_tpu.parallel.comm` (see
``docs/comm.md``): ``wire="bf16"|"int8"`` swaps the exact psum for a
bucketed quantized reduce-scatter + all-gather, and ``chunks=K`` splits
the bucket so XLA can overlap chunk collectives with dequant/optimizer
math — the same engine the ZeRO optimizers use.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import _compat
from apex_tpu import parallel_state as ps
from apex_tpu.parallel import comm

__all__ = ["all_reduce_gradients", "DistributedDataParallel", "Reducer"]


def all_reduce_gradients(
    grads: Any,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    gradient_average: bool = True,
    gradient_predivide_factor: Optional[float] = None,
):
    """psum gradients over the data-parallel axis (call inside shard_map).

    ≙ the flat_dist_call all-reduce + ``gradient_average`` /
    ``gradient_predivide_factor`` handling in
    apex/parallel/distributed.py :: DistributedDataParallel.  This is the
    EXACT (bit-reproducible) path; :func:`apex_tpu.parallel.comm
    .sync_gradients` layers wire formats and chunking on the same
    semantics.
    """
    world = _compat.axis_size(axis_name)

    def f(g):
        gf = g
        if gradient_predivide_factor is not None:
            gf = gf / gradient_predivide_factor
        gf = jax.lax.psum(gf, axis_name)
        if gradient_average:
            post = (
                world / gradient_predivide_factor
                if gradient_predivide_factor is not None
                else world
            )
            gf = gf / post
        return gf

    with jax.named_scope("ddp_allreduce"):
        return jax.tree_util.tree_map(f, grads)


class DistributedDataParallel:
    """Wraps a loss function for data-parallel training.

    ≙ ``apex.parallel.DistributedDataParallel(model, message_size=...,
    gradient_average=..., gradient_predivide_factor=...)``.  The
    ``message_size``/``allreduce_trigger_params`` bucketing knobs have no
    analog (XLA fuses and schedules collectives); ``delay_allreduce`` maps
    to ``delay_allreduce=True`` → the wrapper skips the psum so the caller
    reduces manually, and :meth:`no_sync` gives the torch-DDP-style scoped
    version: grads stay local inside the context, the caller pays ONE
    (possibly quantized) sync on the accumulation-boundary step.

    ``wire``/``chunks``/``block``/``min_size`` are the
    :mod:`apex_tpu.parallel.comm` engine knobs (``docs/comm.md``):
    ``wire="int8"`` cuts sync bytes ~4x at ~1/127-of-block-max gradient
    error, ``chunks`` splits the bucket for collective/compute overlap.
    The default (``wire="f32"``, no chunking) is the exact psum.

    Usage::

        ddp = DistributedDataParallel(loss_fn)
        step = ddp.make_step(tx, mesh)           # jitted SPMD train step
        params, opt_state, loss = step(params, opt_state, batch)

    or, inside your own ``shard_map``::

        loss, grads = ddp.value_and_grad(params, batch)

    Gradient accumulation, either scoped (all microbatches LOCAL, one
    engine sync on the summed tree)::

        with ddp.no_sync():
            _, g1 = ddp.value_and_grad(params, microbatch1)  # local
            _, g2 = ddp.value_and_grad(params, microbatch2)  # local
        acc = jax.tree_util.tree_map(lambda a, b: a + b, g1, g2)
        grads = ddp.all_reduce_gradients(acc)                # ONE sync

    or prebuilt: :meth:`accum_value_and_grad` scans ``(K, ...)``-stacked
    microbatches for you, and ``ddp.make_step(tx, mesh, accum_steps=K)``
    wraps that in a full jitted train step.
    """

    def __init__(
        self,
        loss_fn: Callable,
        axis_name: str = ps.DATA_PARALLEL_AXIS,
        gradient_average: bool = True,
        gradient_predivide_factor: Optional[float] = None,
        delay_allreduce: bool = False,
        wire: str = "f32",
        chunks: Optional[int] = None,
        block: int = comm.DEFAULT_BLOCK,
        min_size: int = 1024,
    ):
        self.loss_fn = loss_fn
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.delay_allreduce = delay_allreduce
        self.wire = comm.check_wire(wire)
        self.chunks = chunks
        self.block = block
        self.min_size = min_size
        self._no_sync = False

    @contextlib.contextmanager
    def no_sync(self):
        """Inside this context :meth:`value_and_grad` returns LOCAL
        (unsynced) grads — Apex's ``delay_allreduce`` as a scope, torch
        DDP's ``no_sync()`` by name.  Accumulate across microbatches,
        then sync once (:meth:`all_reduce_gradients`) on the boundary
        step.  Trace-time state: enter it around the tracing of the
        microbatch, not inside traced control flow."""
        prev = self._no_sync
        self._no_sync = True
        try:
            yield
        finally:
            self._no_sync = prev

    def collective_plan(self, params, world: int) -> dict:
        """The per-mesh-axis collective plan this wrapper's step
        promises — ``{"mesh": {axis: world}, "collectives": [...]}``
        in the schema of :func:`apex_tpu.analysis.sharding
        .reshard_pass`, built by :func:`apex_tpu.parallel.comm
        .sync_plan` from the same wire/chunks/min_size knobs the
        traced sync uses.  Feed it to ``analysis.check(...,
        expect_plan=...)`` (or ``tools/graph_lint.py`` does, for the
        resilient target) to prove the compiled step contains ONLY
        the declared gradient sync — an extra weight all-gather is a
        ``reshard-unplanned`` ERROR."""
        return {
            "mesh": {self.axis_name: int(world)},
            "collectives": comm.sync_plan(
                params, world, self.axis_name,
                wire=self.wire, chunks=self.chunks, block=self.block,
                min_size=self.min_size,
            ),
        }

    def all_reduce_gradients(self, grads):
        """Sync a (local) gradient tree with this wrapper's engine
        config — the one comms layer shared with the ZeRO optimizers
        (:func:`apex_tpu.parallel.comm.sync_gradients`)."""
        return comm.sync_gradients(
            grads,
            self.axis_name,
            wire=self.wire,
            chunks=self.chunks,
            block=self.block,
            min_size=self.min_size,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
        )

    def accum_value_and_grad(self, params, *batch):
        """K-microbatch gradient accumulation (call inside shard_map):
        every ``batch`` leaf carries a leading ``(K, ...)`` microbatch
        axis; microbatch grads accumulate LOCALLY inside a ``lax.scan``
        (``no_sync`` semantics) and ONE engine sync runs on the
        boundary.  Returns ``(loss, grads)`` — the dp-mean of the mean
        microbatch loss, and the synced tree; with ``gradient_average``
        the accumulated sum is divided by K first, so the result matches
        one big-batch step over the same rows (equal microbatches)."""
        k = jax.tree_util.tree_leaves(batch)[0].shape[0]

        def micro(acc, mb):
            with self.no_sync():
                l, g = self.value_and_grad(params, *mb)
            return jax.tree_util.tree_map(jnp.add, acc, g), l

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.result_type(p)), params
        )
        acc, losses = jax.lax.scan(micro, zeros, batch)
        if self.gradient_average:
            acc = jax.tree_util.tree_map(lambda g: g / k, acc)
        grads = self.all_reduce_gradients(acc)
        loss = jax.lax.pmean(jnp.mean(losses), self.axis_name)
        return loss, grads

    def _wants_manual_sync(self) -> bool:
        return (
            self.delay_allreduce
            or self._no_sync
            or self.gradient_predivide_factor is not None
            or self.wire != "f32"
            or comm.chunks_requested(self.chunks)
        )

    def value_and_grad(self, params, *batch):
        """Per-shard loss + dp-reduced grads; call inside shard_map.

        Under jax's shard_map vma semantics, differentiating w.r.t.
        *replicated* params already inserts the cross-shard psum in the
        transpose (the bucketed all-reduce the reference implements by
        hand).  The fast path therefore only divides for averaging.  The
        ``delay_allreduce`` / ``no_sync`` / predivide / non-f32-wire
        paths need genuinely *local* grads, so params are marked varying
        (``pcast to='varying'``) first, which suppresses the automatic
        psum; sync (when not delayed) then runs through the comm engine.
        """
        if self._wants_manual_sync():
            params_v = jax.tree_util.tree_map(
                lambda p: _compat.pcast(p, self.axis_name, to="varying"),
                params,
            )
            loss, grads = jax.value_and_grad(self.loss_fn)(params_v, *batch)
            if not (self.delay_allreduce or self._no_sync):
                grads = self.all_reduce_gradients(grads)
                loss = jax.lax.pmean(loss, self.axis_name)
            return loss, grads
        loss, grads = jax.value_and_grad(self.loss_fn)(params, *batch)
        if not _compat.HAS_VMA:
            # pre-vma jax inserts no implicit psum in the transpose of
            # replicated params — reduce by hand to keep the fast-path
            # contract (grads arrive dp-summed) identical across releases
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, self.axis_name), grads
            )
        if self.gradient_average:
            world = _compat.axis_size(self.axis_name)
            grads = jax.tree_util.tree_map(lambda g: g / world, grads)
            loss = jax.lax.pmean(loss, self.axis_name)
        return loss, grads

    def make_step(self, tx, mesh=None, accum_steps: int = 1):
        """Build a jitted SPMD train step: batch sharded over dp, params
        replicated, grads synced via the engine, optimizer applied
        identically on every device.

        ``accum_steps=K > 1`` adds gradient accumulation: batch leaves
        carry a leading ``(K, ...)`` microbatch axis, microbatch grads
        accumulate LOCALLY inside a ``lax.scan`` (``no_sync``
        semantics), and the one engine sync runs on the boundary —
        K microbatches, one wire payment.
        """
        mesh = mesh or ps.get_mesh()
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

        def _step(params, opt_state, batch):
            if accum_steps == 1:
                loss, grads = self.value_and_grad(params, batch)
            else:
                loss, grads = self.accum_value_and_grad(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates
            )
            return params, opt_state, loss

        batch_spec = (
            P(self.axis_name)
            if accum_steps == 1
            else P(None, self.axis_name)  # (K, per-rank batch, ...)
        )
        smapped = _compat.shard_map(
            _step,
            mesh=mesh,
            in_specs=(P(), P(), batch_spec),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(smapped)


class Reducer:
    """Manual-reduction helper — ≙ apex/parallel/distributed.py :: Reducer.

    ``broadcast_params`` is a no-op under SPMD (all replicas trace the same
    init); ``reduce`` psums a pytree on demand.
    """

    def __init__(self, axis_name: str = ps.DATA_PARALLEL_AXIS):
        self.axis_name = axis_name

    def broadcast_params(self, params):
        return params  # replicated by construction

    def reduce(self, tree, average: bool = True):
        world = _compat.axis_size(self.axis_name)

        def f(x):
            s = jax.lax.psum(x, self.axis_name)
            return s / world if average else s

        return jax.tree_util.tree_map(f, tree)
