"""Quantized-wire gradient all-reduce over the data-parallel axis.

Beyond the reference (apex syncs f32/f16 gradients over NCCL at full
width).  Pattern: EQuARX — Efficient Quantized AllReduce in XLA
(arxiv 2506.17615) — which shows a blockwise-scaled int8 wire format for
the all-reduce's two phases at minor quality cost.  This is an
independent TPU-native implementation of that idea with jax collectives:

    reduce-scatter phase   all_to_all(int8 chunks + f32 scales)
                           -> local dequant-accumulate in f32
    all-gather phase       all_gather(int8 reduced shard + scale)

Wire bytes per chip ≈ 1/4 of an f32 ring all-reduce (int8 payload both
phases, plus one f32 scale per chunk), which is the lever when gradient
sync rides DCN between hosts or competes with compute for ICI.

Accuracy: values are scaled per (rank-chunk) by max|g|/127, so each of
the two quantization stages contributes at most ~0.8% relative error
w.r.t. its chunk's max — fine for SGD/Adam-class updates (gradient
noise dominates), measurably NOT bit-identical to the exact psum.  Use
the plain :func:`apex_tpu.parallel.all_reduce_gradients` when exact
reproducibility across world sizes matters.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu import parallel_state as ps

__all__ = ["quantized_all_reduce_gradients"]

_QMAX = 127.0


def _quantize(x):
    """(int8 codes, f32 scale) with scale = max|x|/127 per leading row."""
    scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / _QMAX
    scale = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(jnp.round(x / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def _pack(q, scale):
    """Append the f32 scale's 4 raw bytes to each int8 row, so codes and
    scale ride ONE collective (the module targets the latency-bound DCN
    path — a second tiny scale collective per leaf would erode the win)."""
    sbytes = jax.lax.bitcast_convert_type(
        scale.astype(jnp.float32), jnp.int8
    ).reshape(*q.shape[:-1], 4)
    return jnp.concatenate([q, sbytes], axis=-1)


def _unpack(payload):
    q, sbytes = payload[..., :-4], payload[..., -4:]
    # int8[..., 4] -> f32[...]: restore the keepdims the scale had
    scale = jax.lax.bitcast_convert_type(sbytes, jnp.float32)[..., None]
    return q, scale


def _qar_leaf(g, axis_name, world):
    """Raw SUM over the axis (averaging is a post-scale at the caller —
    constant scaling commutes exactly with max/127 quantization)."""
    n = g.size
    flat = g.reshape(-1).astype(jnp.float32)
    pad = (-n) % world
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    chunks = flat.reshape(world, -1)  # row j = the shard rank j will own

    # phase 1 (reduce-scatter shape): one all_to_all of int8 codes with
    # the scale packed in, then dequant-accumulate this rank's shard
    recv = jax.lax.all_to_all(
        _pack(*_quantize(chunks)), axis_name, 0, 0, tiled=False
    )
    q_recv, s_recv = _unpack(recv)
    shard = jnp.sum(q_recv.astype(jnp.float32) * s_recv, axis=0)

    # phase 2: re-quantize the reduced shard, one all_gather of all shards
    gathered = jax.lax.all_gather(_pack(*_quantize(shard)), axis_name)
    q_all, s_all = _unpack(gathered)  # (world, chunk), (world, 1)
    out = (q_all.astype(jnp.float32) * s_all).reshape(-1)
    if pad:
        out = out[:n]
    return out.reshape(g.shape).astype(g.dtype)


def quantized_all_reduce_gradients(
    grads: Any,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    gradient_average: bool = True,
    gradient_predivide_factor=None,
    min_size: int = 1024,
):
    """int8-wire gradient sync over ``axis_name`` (call inside
    shard_map); a drop-in for :func:`parallel.all_reduce_gradients`
    (same kwargs incl. ``gradient_predivide_factor``) when wire
    bandwidth — not exactness — is the constraint.

    Leaves smaller than ``min_size`` elements go through the exact psum:
    their wire cost is dominated by latency, and tiny tensors (biases,
    LN scales) are the most scale-sensitive.
    """
    world = jax.lax.axis_size(axis_name)
    post = 1.0
    if gradient_average:
        post = (
            world / gradient_predivide_factor
            if gradient_predivide_factor is not None
            else world
        )

    def f(g):
        if gradient_predivide_factor is not None:
            # max/127 scaling makes predivision a numerical no-op inside
            # the quantized path, but honoring it keeps half-precision
            # INPUT grads from overflowing before the cast, exactly as
            # in all_reduce_gradients
            g = g / gradient_predivide_factor
        if g.size < min_size or world == 1:
            gf = jax.lax.psum(g, axis_name)
            return gf / post if gradient_average else gf
        out = _qar_leaf(g, axis_name, world)
        return out / post if gradient_average else out

    with jax.named_scope("ddp_quantized_allreduce"):
        return jax.tree_util.tree_map(f, grads)
