"""Quantized-wire gradient all-reduce over the data-parallel axis.

Beyond the reference (apex syncs f32/f16 gradients over NCCL at full
width).  Pattern: EQuARX — Efficient Quantized AllReduce in XLA
(arxiv 2506.17615) — which shows a blockwise-scaled int8 wire format for
the all-reduce's two phases at minor quality cost.  This is an
independent TPU-native implementation of that idea with jax collectives.

Since the comm-layer refactor the actual machinery — the blockwise int8
codec, the bucketed reduce-scatter/all-gather, chunking, and the HLO
verification hooks — lives in :mod:`apex_tpu.parallel.comm` (see
``docs/comm.md``), where the ZeRO optimizers share it.  This module
keeps the historical entry point with its historical contract:

Structure: every eligible gradient leaf is flattened into ONE bucket, so
the whole tree costs exactly two collectives —

    reduce-scatter phase   one all_to_all of int8 codes + packed scales
                           -> local dequant-accumulate in f32
    all-gather phase       one all_gather of the re-quantized shard

— not two per leaf (DDP-style bucketing; per-collective latency on DCN
would otherwise erode the bandwidth win).  Quantization is per-BLOCK
(``block`` elements share one f32 max/127 scale), so mixed-magnitude
tensors in the bucket don't share scales; wire bytes ≈ 1/4 of the f32
psum (+4/block for scales).

Accuracy: with ``gradient_average=True`` (the DDP default) worst-case
element error is ≈ 1/127 of the element's BLOCK max — the reduce-scatter
stage sums ``world`` half-ulp errors but averaging divides them right
back down, and the re-quantize stage adds one more half-ulp.  With
``gradient_average=False`` the absolute error of the SUM scales with
``world`` (each rank contributes its own half-ulp), just as the sum
itself does.  Either way this is NOT bit-identical to the exact psum:
use :func:`apex_tpu.parallel.all_reduce_gradients` when exact
reproducibility matters.
"""

from __future__ import annotations

from typing import Any, Optional

from apex_tpu import parallel_state as ps
from apex_tpu.parallel import comm

__all__ = ["quantized_all_reduce_gradients"]


def quantized_all_reduce_gradients(
    grads: Any,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    gradient_average: bool = True,
    gradient_predivide_factor=None,
    min_size: int = 1024,
    block: int = 256,
    chunks: Optional[int] = 1,
):
    """int8-wire gradient sync over ``axis_name`` (call inside
    shard_map); a drop-in for :func:`parallel.all_reduce_gradients`
    (same kwargs incl. ``gradient_predivide_factor``) when wire
    bandwidth — not exactness — is the constraint.

    Leaves smaller than ``min_size`` elements go through the exact psum
    (their wire cost is latency-dominated and tiny tensors — biases, LN
    scales — are the most noise-sensitive); everything else shares one
    bucket.  ``block`` elements share one quantization scale.
    ``chunks=1`` (the default) keeps the historical exactly-two-
    collectives contract; pass ``chunks=None`` for the comm layer's
    overlap heuristic, or any K explicitly (``APEX_TPU_COMM_CHUNKS``
    overrides either).  Equivalent to
    :func:`apex_tpu.parallel.comm.sync_gradients` with ``wire="int8"``.
    """
    return comm.sync_gradients(
        grads,
        axis_name,
        wire="int8",
        chunks=chunks,
        block=block,
        min_size=min_size,
        gradient_average=gradient_average,
        gradient_predivide_factor=gradient_predivide_factor,
    )
