"""Quantized-wire gradient all-reduce over the data-parallel axis.

Beyond the reference (apex syncs f32/f16 gradients over NCCL at full
width).  Pattern: EQuARX — Efficient Quantized AllReduce in XLA
(arxiv 2506.17615) — which shows a blockwise-scaled int8 wire format for
the all-reduce's two phases at minor quality cost.  This is an
independent TPU-native implementation of that idea with jax collectives.

Structure: every eligible gradient leaf is flattened into ONE bucket, so
the whole tree costs exactly two collectives —

    reduce-scatter phase   one all_to_all of int8 codes + packed scales
                           -> local dequant-accumulate in f32
    all-gather phase       one all_gather of the re-quantized shard

— not two per leaf (DDP-style bucketing; per-collective latency on DCN
would otherwise erode the bandwidth win).  Quantization is per-BLOCK
(``block`` elements share one f32 max/127 scale), so mixed-magnitude
tensors in the bucket don't share scales; wire bytes ≈ 1/4 of the f32
psum (+4/block for scales).

Accuracy: with ``gradient_average=True`` (the DDP default) worst-case
element error is ≈ 1/127 of the element's BLOCK max — the reduce-scatter
stage sums ``world`` half-ulp errors but averaging divides them right
back down, and the re-quantize stage adds one more half-ulp.  With
``gradient_average=False`` the absolute error of the SUM scales with
``world`` (each rank contributes its own half-ulp), just as the sum
itself does.  Either way this is NOT bit-identical to the exact psum:
use :func:`apex_tpu.parallel.all_reduce_gradients` when exact
reproducibility matters.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = ["quantized_all_reduce_gradients"]

_QMAX = 127.0


def _quantize_blocks(x, block):
    """x (..., n·block) -> int8 codes (same shape) + f32 scales
    (..., n) with scale = max|block|/127."""
    shape = x.shape
    xb = x.reshape(*shape[:-1], -1, block)
    scale = jnp.max(jnp.abs(xb), axis=-1, keepdims=True) / _QMAX
    scale = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(jnp.round(xb / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q.reshape(shape), scale[..., 0]


def _dequantize_blocks(q, scale, block):
    shape = q.shape
    xb = q.reshape(*shape[:-1], -1, block).astype(jnp.float32)
    return (xb * scale[..., None]).reshape(shape)


def _pack(q, scale):
    """Append the scales' raw bytes to the int8 codes, so codes and
    scales ride ONE collective."""
    sbytes = jax.lax.bitcast_convert_type(
        scale.astype(jnp.float32), jnp.int8
    ).reshape(*q.shape[:-1], -1)
    return jnp.concatenate([q, sbytes], axis=-1)


def _unpack(payload, n_codes):
    q, sbytes = payload[..., :n_codes], payload[..., n_codes:]
    scale = jax.lax.bitcast_convert_type(
        sbytes.reshape(*sbytes.shape[:-1], -1, 4), jnp.float32
    )
    return q, scale


def _qar_flat(flat, axis_name, world, block):
    """Raw SUM of a flat f32 vector over the axis in two int8-wire
    collectives (averaging is a post-scale at the caller — constant
    scaling commutes exactly with max/127 quantization)."""
    n = flat.shape[0]
    pad = (-n) % (world * block)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    chunks = flat.reshape(world, -1)  # row j = the shard rank j will own
    csize = chunks.shape[1]

    # phase 1 (reduce-scatter shape): one all_to_all, dequant-accumulate
    recv = jax.lax.all_to_all(
        _pack(*_quantize_blocks(chunks, block)), axis_name, 0, 0,
        tiled=False,
    )
    q_recv, s_recv = _unpack(recv, csize)
    shard = jnp.sum(_dequantize_blocks(q_recv, s_recv, block), axis=0)

    # phase 2: re-quantize the reduced shard, one all_gather
    gathered = jax.lax.all_gather(
        _pack(*_quantize_blocks(shard, block)), axis_name
    )
    q_all, s_all = _unpack(gathered, csize)
    out = _dequantize_blocks(q_all, s_all, block).reshape(-1)
    if pad:
        out = out[:n]
    return out


def quantized_all_reduce_gradients(
    grads: Any,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    gradient_average: bool = True,
    gradient_predivide_factor=None,
    min_size: int = 1024,
    block: int = 256,
):
    """int8-wire gradient sync over ``axis_name`` (call inside
    shard_map); a drop-in for :func:`parallel.all_reduce_gradients`
    (same kwargs incl. ``gradient_predivide_factor``) when wire
    bandwidth — not exactness — is the constraint.

    Leaves smaller than ``min_size`` elements go through the exact psum
    (their wire cost is latency-dominated and tiny tensors — biases, LN
    scales — are the most noise-sensitive); everything else shares one
    bucket and exactly two collectives.  ``block`` elements share one
    quantization scale.
    """
    world = _compat.axis_size(axis_name)
    post = 1.0
    if gradient_average:
        post = (
            world / gradient_predivide_factor
            if gradient_predivide_factor is not None
            else world
        )

    def pre(g):
        if gradient_predivide_factor is not None:
            # a numerical no-op inside the quantized path (constant
            # scaling commutes with max/127 quantization), but it keeps
            # half-precision INPUT grads from overflowing before the
            # cast, exactly as in all_reduce_gradients
            return g / gradient_predivide_factor
        return g

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    with jax.named_scope("ddp_quantized_allreduce"):
        out = []
        big = [
            i for i, l in enumerate(leaves)
            if l.size >= min_size and world > 1
        ]
        if big:
            flat = jnp.concatenate(
                [pre(leaves[i]).reshape(-1).astype(jnp.float32)
                 for i in big]
            )
            synced = _qar_flat(flat, axis_name, world, block) / post
            offs = 0
            synced_by_idx = {}
            for i in big:
                n = leaves[i].size
                synced_by_idx[i] = (
                    synced[offs:offs + n]
                    .reshape(leaves[i].shape)
                    .astype(leaves[i].dtype)
                )
                offs += n
        for i, l in enumerate(leaves):
            if big and i in synced_by_idx:
                out.append(synced_by_idx[i])
            else:
                out.append(jax.lax.psum(pre(l), axis_name) / post)
        return jax.tree_util.tree_unflatten(treedef, out)
