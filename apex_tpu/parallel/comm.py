"""One gradient-sync engine for DP all-reduce and ZeRO weight-update
sharding — wire format x chunking x verification, in one place.

Both :class:`apex_tpu.parallel.DistributedDataParallel` and the ZeRO
optimizers (:class:`apex_tpu.parallel.DistributedFusedAdam` /
``DistributedFusedLAMB``) call into this module, so the dominant
off-chip cost of the data-parallel step — gradient synchronization — is
tuned in exactly one place.  Three independent knobs:

**Wire format** (``wire="f32" | "bf16" | "int8"``).  ``f32`` is the
exact path (``psum`` / ``psum_scatter`` / ``all_gather``).  ``bf16``
halves wire bytes; ``int8`` is the blockwise-scaled code of EQuARX
(arXiv 2506.17615, generalized from ``parallel/quantized.py``): every
``block`` elements share one f32 ``max/127`` scale, and the scales'
raw bytes ride the same payload as the codes so each phase stays ONE
collective.  Whatever the wire, per-shard accumulation happens in f32
(codes are decoded before the sum), so only the wire — never the
reduction — loses precision.  Wire bytes: 4 / 2 / ~1.016 per element
(int8 pays 4 bytes per ``block`` for the scale).

**Chunking** (``chunks=K``).  The flat buffer is split into K
near-equal chunks synced in an unrolled loop, so XLA may schedule chunk
N's collective concurrently with chunk N-1's dequant / optimizer math
(the overlap the reference's bucketed NCCL pipeline builds by hand).
``K`` defaults to a bandwidth/latency heuristic seeded from the
``tools/comm_structure.py`` ICI model (v5e, 90 GB/s per chip on one
mesh axis): target ~4 MiB of wire per chunk, i.e. ~45 us of streaming —
two orders of magnitude above per-collective launch latency, so the
latency overhead of splitting stays in the noise while buffers >= 8 MiB
get at least two overlap windows.  ``APEX_TPU_COMM_CHUNKS`` overrides
everything (read at trace time — retrace to apply).

**Verification hooks**.  :func:`collective_summary` /
:func:`compiled_collectives` read every collective (count + bytes) out
of compiled HLO and :func:`ring_wire_bytes` turns them into per-chip
wire traffic under ring algorithms — so "exactly 2K collectives per
sync, ~1/4 the bytes" is a regression test (``tests/test_comm.py``),
not a docstring.  The parser itself lives with the static-analysis
subsystem (``apex_tpu/analysis/hlo.py``): ``tools/comm_structure.py``,
the ``analysis`` collective-consistency pass, and these hooks all read
compiled HLO through ONE implementation.

**Telemetry**.  Every sync publishes its plan — wire format, payload
bytes, collective count, chunk count — as gauges on the observability
board (``apex_tpu.observability.metrics.board``) at trace time, and
:func:`publish_collective_summary` pushes a parsed-HLO summary the same
way, so a live ``--metrics-out`` JSONL carries continuously measured
wire traffic next to MFU/goodput instead of a one-time HLO assertion
(``docs/observability.md``).

See ``docs/comm.md`` for the full model, tuning guidance, and when NOT
to quantize.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = [
    "WIRE_FORMATS",
    "DEFAULT_BLOCK",
    "sync_gradients",
    "reduce_scatter_flat",
    "all_gather_flat",
    "all_gather_rows",
    "resolve_chunks",
    "chunks_requested",
    "wire_bytes_per_element",
    "quantize_blocks",
    "dequantize_blocks",
    "pack_int8",
    "unpack_int8",
    "wire_payload_bytes",
    "sync_plan",
    "zero_plan",
    "collective_summary",
    "compiled_collectives",
    "ring_wire_bytes",
    "publish_collective_summary",
]

WIRE_FORMATS = ("f32", "bf16", "int8")

_QMAX = 127.0
DEFAULT_BLOCK = 256

#: Chunking heuristic target: ~4 MiB of wire per chunk = ~45 us at the
#: tools/comm_structure.py ICI model's 90 GB/s — bandwidth-dominated,
#: yet small enough that a >= 8 MiB sync gets overlap windows.
TARGET_CHUNK_BYTES = 4 << 20
_MAX_HEURISTIC_CHUNKS = 16
_MAX_CHUNKS = 64
ENV_CHUNKS = "APEX_TPU_COMM_CHUNKS"


def check_wire(wire: str) -> str:
    if wire not in WIRE_FORMATS:
        raise ValueError(
            f"wire must be one of {WIRE_FORMATS}, got {wire!r}"
        )
    return wire


def wire_bytes_per_element(wire: str, block: int = DEFAULT_BLOCK) -> float:
    """Wire bytes one f32 element costs under ``wire`` (int8 includes
    the amortized 4-byte/block scale)."""
    check_wire(wire)
    if wire == "f32":
        return 4.0
    if wire == "bf16":
        return 2.0
    return 1.0 + 4.0 / block


def chunks_requested(chunks: Optional[int]) -> bool:
    """True when chunking was explicitly asked for (arg or env) rather
    than left to the heuristic."""
    return chunks is not None or bool(os.environ.get(ENV_CHUNKS))


def resolve_chunks(wire_nbytes: int, chunks: Optional[int] = None) -> int:
    """Chunk count K: env ``APEX_TPU_COMM_CHUNKS`` > explicit ``chunks``
    > the bandwidth/latency heuristic (ceil(bytes / 4 MiB), capped at
    16).  Always >= 1."""
    env = os.environ.get(ENV_CHUNKS)
    if env:
        k = int(env)
    elif chunks is not None:
        k = int(chunks)
    else:
        k = min(
            -(-max(int(wire_nbytes), 1) // TARGET_CHUNK_BYTES),
            _MAX_HEURISTIC_CHUNKS,
        )
    return max(1, min(k, _MAX_CHUNKS))


def _chunk_bounds(n: int, k: int, align: int = 1):
    """Up to K near-equal (lo, hi) spans covering [0, n); interior edges
    round up to ``align`` (quantized wires align to ``block`` so only
    the final chunk can carry a padded tail block) and empty spans drop,
    so ragged sizes, k > n, and n < k*align are all safe — a buffer too
    small to fill K aligned chunks just gets fewer."""
    bounds, prev = [], 0
    for i in range(1, k + 1):
        edge = n if i == k else min(n, -(-((i * n) // k) // align) * align)
        if edge > prev:
            bounds.append((prev, edge))
        prev = max(prev, edge)
    return bounds


def _publish_stats(prefix: str, **stats) -> None:
    """Gauge the plan of a sync onto the observability board.

    Host-side and trace-time only (the values are static per compiled
    program): retracing republishes, steady-state steps never touch it.
    Import is deferred so the comm engine stays importable even if the
    observability package is stripped from a deployment.
    """
    try:
        from apex_tpu.observability.metrics import board
    except ImportError:  # pragma: no cover - partial install
        return
    for key, value in stats.items():
        board.set(f"{prefix}/{key}", value)


# ---------------------------------------------------------------------------
# blockwise int8 codec (generalized from parallel/quantized.py)
# ---------------------------------------------------------------------------


def _padded_len(n: int, block: int) -> int:
    return n + (-n) % block


def quantize_blocks(x, block: int = DEFAULT_BLOCK):
    """``x (..., n)`` f32 -> int8 codes ``(..., n_pad)`` + f32 scales
    ``(..., n_pad/block)`` with ``scale = max|block|/127``.

    Tail-safe: ``n`` need not divide ``block`` — the tail is zero-padded
    into its own block internally (padding zeros never raise a block
    max, so real elements keep their scale).  Zero-safe: an all-zero
    block gets scale 1.0 — never 0 or a subnormal — so the dequant path
    cannot produce NaN/Inf from ``0/0`` or overflow from ``x/tiny``.
    """
    n = x.shape[-1]
    pad = (-n) % block
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((*x.shape[:-1], pad), x.dtype)], axis=-1
        )
    xb = x.reshape(*x.shape[:-1], -1, block)
    m = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.maximum(m / _QMAX, jnp.finfo(jnp.float32).tiny)
    scale = jnp.where(m > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xb / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q.reshape(*x.shape[:-1], n + pad), scale[..., 0]


def dequantize_blocks(q, scale, block: int = DEFAULT_BLOCK,
                      n: Optional[int] = None):
    """Inverse of :func:`quantize_blocks`; ``n`` slices the zero-pad
    back off.  Dequantized values sit exactly on the int8 grid, so a
    second quantize/dequantize round-trip is bit-identical (the
    fixed-point property ``tests/test_quantized_allreduce.py`` pins)."""
    shape = q.shape
    xb = q.reshape(*shape[:-1], -1, block).astype(jnp.float32)
    out = (xb * scale[..., None]).reshape(shape)
    if n is not None and n != shape[-1]:
        out = out[..., :n]
    return out


def pack_int8(q, scale):
    """Append the f32 scales' raw bytes to the int8 codes so codes and
    scales ride ONE collective payload."""
    sbytes = jax.lax.bitcast_convert_type(
        scale.astype(jnp.float32), jnp.int8
    ).reshape(*q.shape[:-1], -1)
    return jnp.concatenate([q, sbytes], axis=-1)


def unpack_int8(payload, n: int, block: int = DEFAULT_BLOCK):
    """Split a packed payload back into (codes, scales) for ``n`` real
    elements quantized at ``block``."""
    n_pad = _padded_len(n, block)
    q, sbytes = payload[..., :n_pad], payload[..., n_pad:]
    scale = jax.lax.bitcast_convert_type(
        sbytes.reshape(*sbytes.shape[:-1], -1, 4), jnp.float32
    )
    return q, scale


def _encode(x, wire: str, block: int):
    """f32 ``(..., n)`` -> wire payload (same leading shape)."""
    if wire == "f32":
        return x
    if wire == "bf16":
        return x.astype(jnp.bfloat16)
    return pack_int8(*quantize_blocks(x, block))


def _decode(payload, wire: str, block: int, n: int):
    """Wire payload -> f32 ``(..., n)``."""
    if wire == "f32":
        return payload
    if wire == "bf16":
        return payload.astype(jnp.float32)
    q, scale = unpack_int8(payload, n, block)
    return dequantize_blocks(q, scale, block, n)


# ---------------------------------------------------------------------------
# flat-buffer collectives (the ZeRO building blocks)
# ---------------------------------------------------------------------------


def reduce_scatter_flat(
    flat,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    *,
    wire: str = "f32",
    chunks: Optional[int] = None,
    block: int = DEFAULT_BLOCK,
):
    """SUM-reduce a flat f32 buffer over ``axis_name`` and return my
    contiguous shard (``flat.size / world`` elements, f32).

    ``flat.size`` must divide the axis size.  ``wire="f32"`` lowers to
    ``psum_scatter``; quantized wires use one ``all_to_all`` of encoded
    payloads per chunk with f32 shard-local dequant-accumulate.  Call
    inside ``shard_map``.
    """
    check_wire(wire)
    world = _compat.axis_size(axis_name)
    n = flat.shape[0]
    if n == 0 or world == 1:
        return flat.astype(jnp.float32)
    if n % world:
        raise ValueError(f"flat size {n} not divisible by world {world}")
    shard = n // world
    k = min(
        resolve_chunks(int(n * wire_bytes_per_element(wire, block)), chunks),
        shard,
    )
    rows = flat.reshape(world, shard).astype(jnp.float32)
    bounds = _chunk_bounds(shard, k, 1 if wire == "f32" else block)
    _publish_stats(
        "comm/rs", wire=wire, world=world, elements=n,
        chunks=len(bounds), collectives=len(bounds),
        wire_bytes=int(n * wire_bytes_per_element(wire, block)),
    )
    outs = []
    with jax.named_scope(f"comm_rs_{wire}"):
        for lo, hi in bounds:
            seg = rows[:, lo:hi]  # row j = rank j's slice of this chunk
            if wire == "f32":
                outs.append(
                    jax.lax.psum_scatter(
                        seg.reshape(-1), axis_name,
                        scatter_dimension=0, tiled=True,
                    )
                )
            else:
                recv = jax.lax.all_to_all(
                    _encode(seg, wire, block), axis_name, 0, 0, tiled=False
                )
                outs.append(
                    jnp.sum(_decode(recv, wire, block, hi - lo), axis=0)
                )
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)


def all_gather_flat(
    shard,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    *,
    wire: str = "f32",
    chunks: Optional[int] = None,
    block: int = DEFAULT_BLOCK,
):
    """All-gather per-rank contiguous shards back into the full flat f32
    buffer (``world * shard.size`` elements, rank-major).

    Quantized wires encode the local shard and every rank decodes the
    SAME payloads — including its own — so the gathered buffer is
    bit-identical across replicas (the invariant that keeps ZeRO params
    replicated).  Call inside ``shard_map``.
    """
    check_wire(wire)
    world = _compat.axis_size(axis_name)
    s = shard.shape[0]
    if s == 0 or world == 1:
        return shard.astype(jnp.float32)
    k = min(
        resolve_chunks(
            int(world * s * wire_bytes_per_element(wire, block)), chunks
        ),
        s,
    )
    shard = shard.astype(jnp.float32)
    bounds = _chunk_bounds(s, k, 1 if wire == "f32" else block)
    _publish_stats(
        "comm/ag", wire=wire, world=world, elements=world * s,
        chunks=len(bounds), collectives=len(bounds),
        wire_bytes=int(world * s * wire_bytes_per_element(wire, block)),
    )
    parts = []
    with jax.named_scope(f"comm_ag_{wire}"):
        for lo, hi in bounds:
            g = jax.lax.all_gather(
                _encode(shard[lo:hi], wire, block), axis_name,
                axis=0, tiled=False,
            )
            parts.append(_decode(g, wire, block, hi - lo))  # (world, cs)
    full = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
    return full.reshape(-1)


def all_gather_rows(
    row,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    *,
    wire: str = "f32",
):
    """All-gather each participant's metrics row into a ``(world, n)``
    f32 matrix — the fleet-aggregation collective
    (:class:`apex_tpu.observability.fleet.FleetAggregator`).

    Call inside ``shard_map`` with one ``(n,)`` row per participant on
    ``axis_name``; every participant gets the identical matrix back
    (row ``j`` = participant ``j``'s values).  One collective per call
    — telemetry rows are tiny (tens of floats), so chunking would be
    pure launch overhead — riding the same engine as the gradient
    path, so it shows in ``collective_summary`` and the board gauges
    (``comm/fleet/*``) like any other wire traffic.
    """
    check_wire(wire)
    world = _compat.axis_size(axis_name)
    flat = row.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    _publish_stats(
        "comm/fleet", wire=wire, world=world, elements=world * n,
        collectives=1,
        wire_bytes=int(world * n * wire_bytes_per_element(wire)),
    )
    with jax.named_scope("comm_fleet_rows"):
        full = all_gather_flat(flat, axis_name, wire=wire, chunks=1)
    return full.reshape(world, n)


# ---------------------------------------------------------------------------
# tree-level gradient sync (the DDP entry point)
# ---------------------------------------------------------------------------


def sync_gradients(
    grads: Any,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    *,
    wire: str = "f32",
    chunks: Optional[int] = None,
    block: int = DEFAULT_BLOCK,
    min_size: int = 1024,
    gradient_average: bool = True,
    gradient_predivide_factor: Optional[float] = None,
):
    """Sync a gradient pytree over ``axis_name`` (call inside
    ``shard_map``) with the engine's wire/chunking knobs; a drop-in for
    :func:`apex_tpu.parallel.all_reduce_gradients` (same averaging /
    predivide semantics).

    ``wire="f32"`` with no chunking request is the exact per-leaf psum.
    Otherwise every leaf of >= ``min_size`` elements joins ONE flat
    bucket synced as a chunked reduce-scatter + all-gather (2K
    collectives total, independent of leaf count); leaves under
    ``min_size`` — biases, LN scales: latency-dominated and the most
    noise-sensitive — always ride the exact psum.
    """
    check_wire(wire)
    world = _compat.axis_size(axis_name)
    post = 1.0
    if gradient_average:
        post = (
            world / gradient_predivide_factor
            if gradient_predivide_factor is not None
            else world
        )

    def pre(g):
        # a numerical no-op inside the quantized path (constant scaling
        # commutes with max/127 quantization), but it keeps
        # half-precision INPUT grads from overflowing before the cast,
        # exactly as in all_reduce_gradients
        if gradient_predivide_factor is not None:
            return g / gradient_predivide_factor
        return g

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    big = [
        i for i, l in enumerate(leaves)
        if l.size >= min_size and l.size > 0 and world > 1
    ]
    resolved = None
    if big:
        nbytes = int(
            sum(leaves[i].size for i in big)
            * wire_bytes_per_element(wire, block)
        )
        resolved = resolve_chunks(nbytes, chunks)
    bucketed = bool(big) and (
        wire != "f32" or (chunks_requested(chunks) and resolved > 1)
    )
    big_set = set(big) if bucketed else set()
    bucket_elems = sum(leaves[i].size for i in big_set)
    psum_bytes = sum(
        leaves[i].size * 4 for i in range(len(leaves)) if i not in big_set
    )
    _publish_stats(
        "comm/sync", wire=wire, world=world,
        bucket_elements=int(bucket_elems),
        chunks=int(resolved or 1),
        psum_leaves=len(leaves) - len(big_set),
        wire_bytes=int(
            bucket_elems * wire_bytes_per_element(wire, block) + psum_bytes
        ),
    )
    synced_by_idx = {}
    out = []
    with jax.named_scope(f"comm_sync_{wire}"):
        if bucketed:
            flat = jnp.concatenate(
                [pre(leaves[i]).reshape(-1).astype(jnp.float32)
                 for i in big]
            )
            n = flat.shape[0]
            padded = n + (-n) % world
            if padded != n:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((padded - n,), jnp.float32)]
                )
            my_shard = reduce_scatter_flat(
                flat, axis_name, wire=wire, chunks=resolved, block=block
            )
            synced = all_gather_flat(
                my_shard, axis_name, wire=wire, chunks=resolved, block=block
            )[:n] / post
            offs = 0
            for i in big:
                sz = leaves[i].size
                synced_by_idx[i] = (
                    synced[offs:offs + sz]
                    .reshape(leaves[i].shape)
                    .astype(leaves[i].dtype)
                )
                offs += sz
        for i, l in enumerate(leaves):
            if i in synced_by_idx:
                out.append(synced_by_idx[i])
            else:
                out.append(jax.lax.psum(pre(l), axis_name) / post)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# declared collective plans (the analysis reshard pass's intent)
#
# Each sync path above PROMISES a collective structure; these helpers
# write that promise down as the per-mesh-axis plan schema of
# apex_tpu.analysis.sharding.reshard_pass, mirroring the exact routing
# decisions (bucketing, chunk bounds, wire payloads) the traced code
# makes — so "the compiled step contains only the collectives the
# engine planned" is machine-checkable, not a docstring.
# ---------------------------------------------------------------------------


def wire_payload_bytes(n: int, wire: str, block: int = DEFAULT_BLOCK) -> int:
    """EXACT encoded payload bytes of ``n`` f32 elements under
    ``wire`` — including the int8 path's block zero-pad and packed f32
    scales (:func:`pack_int8`), so plan bounds match the compiled
    payload shapes byte-for-byte."""
    check_wire(wire)
    if wire == "f32":
        return n * 4
    if wire == "bf16":
        return n * 2
    n_pad = _padded_len(n, block)
    return n_pad + 4 * (n_pad // block)


def _wire_dtypes(wire: str):
    return {"f32": ["f32"], "bf16": ["bf16"], "int8": ["s8"]}[wire]


def _bound(estimate: int, slack: int = 1024):
    """[0, hi] byte bounds around an exact-model estimate: generous
    enough for layout padding / a stray scalar riding along, tight
    enough that a doubled sync or an un-encoded payload busts it."""
    return [0, int(estimate + max(slack, estimate // 4))]


def sync_plan(
    grads: Any,
    world: int,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    *,
    wire: str = "f32",
    chunks: Optional[int] = None,
    block: int = DEFAULT_BLOCK,
    min_size: int = 1024,
    extra_allreduce_bytes: int = 64,
) -> list:
    """The collective plan :func:`sync_gradients` promises for this
    gradient tree — a list of ``{"kind", "axis", "count", "bytes",
    "dtypes"}`` entries (``count`` None where XLA's combiner may
    legally merge).  ``extra_allreduce_bytes`` widens the exact-psum
    entry for the scalar all-reduces that ride the same axis in a real
    step (loss pmean, guard flags).

    Mirrors the routing in :func:`sync_gradients` exactly: same
    bucketing predicate, same :func:`resolve_chunks` /
    ``_chunk_bounds`` arithmetic, same wire payload model — change one
    without the other and the reshard pass fails, which is the point.
    """
    check_wire(wire)
    leaves = jax.tree_util.tree_leaves(grads)
    sizes = [int(getattr(l, "size", l)) for l in leaves]
    if world <= 1:
        return []
    big = [s for s in sizes if s >= min_size and s > 0]
    resolved = None
    if big:
        nbytes = int(sum(big) * wire_bytes_per_element(wire, block))
        resolved = resolve_chunks(nbytes, chunks)
    bucketed = bool(big) and (
        wire != "f32" or (chunks_requested(chunks) and resolved > 1)
    )
    entries = []
    psum_elems = sum(
        s for s in sizes if not (bucketed and s >= min_size and s > 0)
    )
    if bucketed:
        n = sum(big)
        padded = n + (-n) % world
        shard = padded // world
        align = 1 if wire == "f32" else block
        k = min(resolved, shard)
        bounds = _chunk_bounds(shard, k, align)
        count = len(bounds)
        if wire == "f32":
            # psum_scatter prints the SHARD as its result shape
            entries.append({
                "kind": "reduce-scatter", "axis": axis_name,
                "count": count, "bytes": _bound(shard * 4),
                "dtypes": _wire_dtypes(wire),
            })
        else:
            # encoded (world, chunk) payloads through all_to_all
            a2a = sum(
                world * wire_payload_bytes(hi - lo, wire, block)
                for lo, hi in bounds
            )
            entries.append({
                "kind": "all-to-all", "axis": axis_name,
                "count": count, "bytes": _bound(a2a),
                "dtypes": _wire_dtypes(wire),
            })
        ag = sum(
            world * wire_payload_bytes(hi - lo, wire, block)
            for lo, hi in bounds
        )
        entries.append({
            "kind": "all-gather", "axis": axis_name,
            "count": count, "bytes": _bound(ag),
            "dtypes": _wire_dtypes(wire),
        })
    if psum_elems or extra_allreduce_bytes:
        entries.append({
            "kind": "all-reduce", "axis": axis_name,
            "count": None,
            "bytes": _bound(psum_elems * 4 + extra_allreduce_bytes),
            "dtypes": ["f32"],
        })
    return entries


def zero_plan(
    n_elements: int,
    world: int,
    axis_name: str = ps.DATA_PARALLEL_AXIS,
    *,
    wire: str = "f32",
    param_wire: Optional[str] = None,
    chunks: Optional[int] = None,
    block: int = DEFAULT_BLOCK,
    extra_allreduce_bytes: int = 256,
) -> list:
    """The plan a ZeRO step (:meth:`_DistributedFusedBase
    .update_inside_shard_map`) promises for ``n_elements`` flat f32
    params: a chunked reduce-scatter of grads at ``wire``, a chunked
    all-gather of updated shards at ``param_wire or wire``, plus the
    small all-reduces of the loss pmean / LAMB per-tensor norms."""
    check_wire(wire)
    if world <= 1:
        return []
    padded = n_elements + (-n_elements) % world
    shard = padded // world
    entries = []

    def _one(w, gather: bool):
        align = 1 if w == "f32" else block
        # mirror reduce_scatter_flat/all_gather_flat's resolve inputs:
        # the scatter sizes the full padded buffer, the gather its
        # world x shard result
        n_for_chunks = world * shard if gather else padded
        k = min(resolve_chunks(
            int(n_for_chunks * wire_bytes_per_element(w, block)), chunks,
        ), shard)
        bounds = _chunk_bounds(shard, k, align)
        count = len(bounds)
        if gather or w != "f32":
            payload = sum(
                world * wire_payload_bytes(hi - lo, w, block)
                for lo, hi in bounds
            )
            kind = "all-gather" if gather else "all-to-all"
            return {
                "kind": kind, "axis": axis_name, "count": count,
                "bytes": _bound(payload), "dtypes": _wire_dtypes(w),
            }
        return {
            "kind": "reduce-scatter", "axis": axis_name, "count": count,
            "bytes": _bound(shard * 4), "dtypes": _wire_dtypes(w),
        }

    entries.append(_one(wire, gather=False))
    entries.append(_one(param_wire or wire, gather=True))
    entries.append({
        "kind": "all-reduce", "axis": axis_name, "count": None,
        "bytes": _bound(extra_allreduce_bytes), "dtypes": ["f32"],
    })
    return entries


# ---------------------------------------------------------------------------
# verification hooks: collectives + wire bytes out of compiled HLO
#
# The HLO text parser itself lives with the static-analysis subsystem
# (apex_tpu/analysis/hlo.py) — ONE implementation shared by these
# hooks, the analysis passes' collective-consistency rule, and
# tools/comm_structure.py.  The names below remain this module's public
# API (tests/test_comm.py and downstream callers import them here).
# ---------------------------------------------------------------------------

from apex_tpu.analysis.hlo import (  # noqa: E402
    collective_summary,
    ring_wire_bytes,
)


def compiled_collectives(fn, *args, **kwargs) -> dict:
    """:func:`collective_summary` of a jitted callable compiled on
    ``args`` — the hook regression tests assert on.  ``fn`` must carry
    ``.lower`` (i.e. be ``jax.jit``-wrapped)."""
    hlo = fn.lower(*args, **kwargs).compile().as_text()
    return collective_summary(hlo)


def publish_collective_summary(
    summary: dict, world: Optional[int] = None, prefix: str = "comm/hlo"
) -> None:
    """Gauge a :func:`collective_summary` onto the observability board.

    Per-kind ``{prefix}/<kind>_count`` / ``{prefix}/<kind>_bytes``
    gauges plus — when ``world`` is given — the ring-model
    ``{prefix}/ring_wire_bytes``, so a compiled program's MEASURED
    collective structure rides the same telemetry stream as the
    trace-time plan (``docs/observability.md``).
    """
    stats = {}
    for kind, rec in summary.items():
        key = kind.replace("-", "_")
        stats[f"{key}_count"] = rec["count"]
        stats[f"{key}_bytes"] = rec["bytes"]
    if world is not None:
        stats["ring_wire_bytes"] = ring_wire_bytes(summary, world)
    _publish_stats(prefix, **stats)
