"""SyncBatchNorm — cross-replica batch normalization.

≙ ``apex/parallel/optimized_sync_batchnorm.py`` (+ the device math in
``csrc/syncbn.cpp`` / ``welford.cu``): per-replica statistics are combined
across the data-parallel group before normalizing, so small per-device
batches still see full-batch statistics.

The CUDA path does a single-pass Welford per replica then a
``welford_parallel`` combine of (mean, var, count) triples gathered over
NCCL.  The TPU version computes per-replica (Σx, Σx², n) in f32 and psums
them over the ``dp`` mesh axis — algebraically identical to the Welford
combine for equal counts, and f32 accumulation covers the stability concern
the two-pass trick addresses.  When no ``dp`` axis is bound (single device
or GSPMD-only tracing), it degrades to plain BatchNorm exactly like the
reference with ``world_size == 1``.

``channel_last`` in the reference is a memory-format flag; here layouts are
XLA's concern and the module just reduces over all non-channel axes.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import parallel_state as ps

__all__ = ["SyncBatchNorm", "convert_syncbn_model"]


def _axis_bound(axis_name: str) -> bool:
    from apex_tpu.parallel_state import bound_axis_size

    return bound_axis_size(axis_name) > 1


class SyncBatchNorm(nn.Module):
    """Drop-in for ``flax.linen.BatchNorm`` with dp-wide statistics.

    Args mirror the reference module: ``momentum`` here is the running-stat
    EMA decay (reference keeps torch's convention ``running = (1-m)*running
    + m*batch``; pass ``momentum=0.1`` for identical updates),
    ``use_running_average`` selects eval mode (≙ ``self.training`` flip).
    """

    features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    axis_name: str = ps.DATA_PARALLEL_AXIS
    use_running_average: Optional[bool] = None
    dtype: Any = None
    param_dtype: Any = jnp.float32
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        feat = self.features
        if x.shape[-1] != feat:
            raise ValueError(
                f"SyncBatchNorm expects channels-last input with "
                f"{feat} channels, got shape {x.shape}"
            )
        reduce_axes = tuple(range(x.ndim - 1))
        xf = x.astype(jnp.float32)

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((feat,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((feat,), jnp.float32)
        )

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # per-replica partials
            n_local = jnp.asarray(
                xf.size // feat, jnp.float32
            )
            s1 = jnp.sum(xf, axis=reduce_axes)
            s2 = jnp.sum(xf * xf, axis=reduce_axes)
            if _axis_bound(self.axis_name):
                # ≙ syncbn.welford_parallel combine over the DP group
                n = jax.lax.psum(n_local, self.axis_name)
                s1 = jax.lax.psum(s1, self.axis_name)
                s2 = jax.lax.psum(s2, self.axis_name)
            else:
                n = n_local
            mean = s1 / n
            var = s2 / n - mean * mean
            if not self.is_initializing():
                m = self.momentum
                # unbiased var for the running stat (torch/apex convention)
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = (1.0 - m) * ra_mean.value + m * mean
                ra_var.value = (1.0 - m) * ra_var.value + m * unbiased

        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            scale = self.param(
                "scale", self.scale_init, (feat,), self.param_dtype
            )
            bias = self.param(
                "bias", self.bias_init, (feat,), self.param_dtype
            )
            y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
        return y.astype(self.dtype or x.dtype)


def convert_syncbn_model(module: nn.Module) -> nn.Module:
    """≙ apex/parallel/__init__.py :: convert_syncbn_model.

    Flax modules are immutable definitions, so in-place conversion (the
    torch approach: walk children, swap BatchNorm instances) cannot exist.
    This helper instead rebuilds a module whose ``nn.BatchNorm`` fields are
    replaced by :class:`SyncBatchNorm` when possible, and raises with
    guidance otherwise — declare ``SyncBatchNorm`` directly in new models.
    """
    if isinstance(module, nn.BatchNorm):
        return SyncBatchNorm(
            features=module.num_features
            if hasattr(module, "num_features")
            else module.__dict__.get("features"),
            eps=module.epsilon,
            momentum=1.0 - module.momentum,
            affine=module.use_scale and module.use_bias,
        )
    raise TypeError(
        "convert_syncbn_model can only convert a flax.linen.BatchNorm "
        "instance; for composite models declare apex_tpu.parallel."
        "SyncBatchNorm in the model definition instead (flax modules are "
        "immutable)"
    )
