"""Fused RNN cells — ≙ ``apex/RNN/`` (``RNNBackend.py``, ``cells.py``,
``models.py``; deprecated upstream, kept for capability parity).

The reference fuses the per-timestep cell math into hand kernels; on TPU the
idiomatic fusion vehicle is ``lax.scan`` — the cell body is traced once,
XLA fuses the gate math into the two GEMMs, and the scan compiles to a
single rolled loop (no per-step dispatch, the launch-amortization property
the reference buys with CUDA).

Models mirror the reference surface: ``RNNReLU``, ``RNNTanh``, ``LSTM``,
``GRU``, ``mLSTM`` (multiplicative LSTM, models.py :: ``mLSTMRNNCell``).
Layout is time-first ``(T, B, H)`` like the reference (torch RNN default).
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["RNNReLU", "RNNTanh", "LSTM", "GRU", "mLSTM"]


def _dense(x, w, b=None):
    from apex_tpu.amp.lists import amp_cast

    x, w, b = amp_cast("rnn_gemm", x, w, b)
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    if b is not None:
        y = y + b
    return y.astype(x.dtype)


class _ScanRNNBase(nn.Module):
    """Shared scan harness ≙ RNNBackend.py :: forward over time.

    Subclass contract: ``n_gates``, ``_cell(carry, scan_inputs, params)``,
    ``_init_carry(batch)``, ``_carry_output(carry)``; optionally
    ``_layer_params`` (extra per-layer weights) and ``_scan_inputs``
    (what gets fed to the scan per step — default: the hoisted input GEMM,
    one big (T·B, din)×(din, gates) MXU matmul instead of T small ones).
    """

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    dtype: jnp.dtype = jnp.float32

    # subclass contract
    n_gates: int = 1

    def _cell(self, carry, scan_inputs, layer_params):
        raise NotImplementedError

    def _init_carry(self, batch):
        raise NotImplementedError

    def _carry_output(self, carry):
        raise NotImplementedError

    def _layer_params(self, layer, din):
        return None

    def _scan_inputs(self, h, w_ih, b_ih, extra):
        return _dense(h, w_ih, b_ih)

    @nn.compact
    def __call__(self, x, initial_state=None):
        """x: (T, B, input_size) → (outputs (T, B, H), final_state)."""
        h = x.astype(self.dtype)
        finals = []
        for layer in range(self.num_layers):
            din = self.input_size if layer == 0 else self.hidden_size
            g = self.n_gates * self.hidden_size
            w_ih = self.param(
                f"w_ih_{layer}", nn.initializers.lecun_normal(), (din, g)
            ).astype(self.dtype)
            w_hh = self.param(
                f"w_hh_{layer}", nn.initializers.orthogonal(), (self.hidden_size, g)
            ).astype(self.dtype)
            b_ih = (
                self.param(f"b_ih_{layer}", nn.initializers.zeros, (g,)).astype(self.dtype)
                if self.bias
                else None
            )
            extra = self._layer_params(layer, din)
            carry = (
                self._init_carry(h.shape[1])
                if initial_state is None
                else jax.tree_util.tree_map(lambda s: s[layer], initial_state)
            )
            xs = self._scan_inputs(h, w_ih, b_ih, extra)

            def step(carry, inp, _w_hh=w_hh, _extra=extra):
                carry = self._cell(carry, inp, (_w_hh, _extra))
                return carry, self._carry_output(carry)

            carry, out = jax.lax.scan(step, carry, xs)
            finals.append(carry)
            h = out
        final_state = jax.tree_util.tree_map(lambda *xs_: jnp.stack(xs_), *finals)
        return h, final_state


class _ElmanBase(_ScanRNNBase):
    n_gates: int = 1
    activation: Callable = jax.nn.tanh

    def _init_carry(self, batch):
        return jnp.zeros((batch, self.hidden_size), self.dtype)

    def _carry_output(self, carry):
        return carry

    def _cell(self, h, gx, params):
        w_hh, _ = params
        return self.activation(gx + _dense(h, w_hh))


class RNNTanh(_ElmanBase):
    """≙ apex.RNN.models.RNNTanh."""

    activation: Callable = jax.nn.tanh


class RNNReLU(_ElmanBase):
    """≙ apex.RNN.models.RNNReLU."""

    activation: Callable = jax.nn.relu


class LSTM(_ScanRNNBase):
    """≙ apex.RNN.models.LSTM — gate order (i, f, g, o) like torch."""

    n_gates: int = 4

    def _init_carry(self, batch):
        z = jnp.zeros((batch, self.hidden_size), self.dtype)
        return (z, z)

    def _carry_output(self, carry):
        return carry[0]

    def _cell(self, carry, gx, params):
        w_hh, _ = params
        h, c = carry
        gates = gx + _dense(h, w_hh)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c)


class GRU(_ScanRNNBase):
    """≙ apex.RNN.models.GRU — gate order (r, z, n) like torch."""

    n_gates: int = 3

    def _init_carry(self, batch):
        return jnp.zeros((batch, self.hidden_size), self.dtype)

    def _carry_output(self, carry):
        return carry

    def _cell(self, h, gx, params):
        w_hh, _ = params
        gh = _dense(h, w_hh)
        rx, zx, nx = jnp.split(gx, 3, axis=-1)
        rh, zh, nh = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        n = jnp.tanh(nx + r * nh)
        return (1.0 - z) * n + z * h


class mLSTM(_ScanRNNBase):
    """Multiplicative LSTM — ≙ apex.RNN.cells :: mLSTMRNNCell.

    ``m = (x·W_mx) ⊙ (h·W_mh)`` replaces ``h`` as the recurrent input to
    the four LSTM gates; the scan consumes (mx_t, gates_x_t) pairs (both
    input-side GEMMs hoisted out of the loop).
    """

    n_gates: int = 4

    def _layer_params(self, layer, din):
        w_mx = self.param(
            f"w_mx_{layer}", nn.initializers.lecun_normal(), (din, self.hidden_size)
        ).astype(self.dtype)
        w_mh = self.param(
            f"w_mh_{layer}", nn.initializers.orthogonal(), (self.hidden_size, self.hidden_size)
        ).astype(self.dtype)
        return (w_mx, w_mh)

    def _scan_inputs(self, h, w_ih, b_ih, extra):
        w_mx, _ = extra
        return (_dense(h, w_mx), _dense(h, w_ih, b_ih))

    def _init_carry(self, batch):
        z = jnp.zeros((batch, self.hidden_size), self.dtype)
        return (z, z)

    def _carry_output(self, carry):
        return carry[0]

    def _cell(self, carry, scan_inputs, params):
        w_hh, (_, w_mh) = params
        h, c = carry
        mx_t, gx_t = scan_inputs
        m = mx_t * _dense(h, w_mh)
        gates = gx_t + _dense(m, w_hh)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c)
