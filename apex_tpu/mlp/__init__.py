"""Fused MLP — ≙ ``apex/mlp/mlp.py`` :: ``MLP`` / ``MlpFunction``.

The reference chains cuBLAS GEMMs with hand-fused bias+ReLU/sigmoid epilogues
(``csrc/mlp.cpp`` :: ``mlp_forward_cuda``/``mlp_backward_cuda``) and manages
its own workspace.  On TPU the whole chain — GEMM, bias add, activation —
is a single XLA fusion cluster landing on the MXU; the module below is the
API-parity surface, and :func:`mlp_function` is the functional core
(≙ ``MlpFunction.apply``).
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["MLP", "mlp_function"]

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_function(
    x: jax.Array,
    weights: Sequence[jax.Array],
    biases: Sequence[jax.Array],
    activation: str = "relu",
) -> jax.Array:
    """(…, in) → (…, out) through len(weights) fused GEMM+bias+act stages.

    Weights use the JAX layout ``(in, out)``; the activation is applied
    after every layer *except the last* (reference semantics: ``MLP``
    applies the nonlinearity between layers only).
    """
    if activation not in _ACTIVATIONS:
        raise ValueError(f"activation must be one of {sorted(_ACTIVATIONS)}")
    if not weights:
        raise ValueError("mlp_function requires at least one weight matrix")
    from apex_tpu.amp.lists import amp_cast

    cast = amp_cast("mlp", x, *weights, *biases)
    x = cast[0]
    weights = cast[1 : 1 + len(weights)]
    biases = cast[1 + len(weights) :]
    act = _ACTIVATIONS[activation]
    h = x
    last = len(weights) - 1
    for i, w in enumerate(weights):
        h = jnp.dot(h, w, preferred_element_type=jnp.float32)
        if biases and biases[i] is not None:
            h = h + biases[i]
        h = h.astype(x.dtype)
        if i != last:
            h = act(h)
    return h


class MLP(nn.Module):
    """≙ apex.mlp.MLP(mlp_sizes, bias=True, activation='relu').

    ``mlp_sizes`` lists every layer width *including* the input width,
    exactly like the reference ctor.
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    kernel_init: Callable = nn.initializers.lecun_normal()
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if len(self.mlp_sizes) < 2:
            raise ValueError("mlp_sizes needs at least (in, out)")
        if x.shape[-1] != self.mlp_sizes[0]:
            raise ValueError(
                f"input width {x.shape[-1]} != mlp_sizes[0]={self.mlp_sizes[0]}"
            )
        weights, biases = [], []
        for i, (din, dout) in enumerate(zip(self.mlp_sizes[:-1], self.mlp_sizes[1:])):
            weights.append(
                self.param(f"kernel_{i}", self.kernel_init, (din, dout)).astype(self.dtype)
            )
            biases.append(
                self.param(f"bias_{i}", nn.initializers.zeros, (dout,)).astype(self.dtype)
                if self.bias
                else None
            )
        return mlp_function(x.astype(self.dtype), weights, biases, self.activation)
