"""≙ tests/L0/run_fused_layer_norm/test_fused_layer_norm.py.

Golden = unfused jnp composition of the same math (the reference compares
against torch.nn.LayerNorm and a manual RMSNorm), across shapes, dtypes,
affine flags, and memory_efficient; gradients compared against autodiff of
the unfused reference.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops
from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm


def ref_layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) / jnp.sqrt(var + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def ref_rms_norm(x, w, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf / jnp.sqrt(ms + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    return y.astype(x.dtype)


def tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(
        rtol=2e-5, atol=2e-5
    )


SHAPES = [(16, 64), (4, 7, 96), (3, 1, 2, 160)]


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("memory_efficient", [False, True])
def test_layer_norm_affine_fwd_bwd(shape, dtype, memory_efficient):
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    hidden = shape[-1]
    x = jax.random.normal(k1, shape, dtype)
    w = (1.0 + 0.1 * jax.random.normal(k2, (hidden,))).astype(jnp.float32)
    b = (0.1 * jax.random.normal(k3, (hidden,))).astype(jnp.float32)
    eps = 1e-5

    fused = ops.fused_layer_norm_affine(x, w, b, hidden, eps, memory_efficient)
    ref = ref_layer_norm(x, w, b, eps)
    np.testing.assert_allclose(
        np.asarray(fused, np.float32), np.asarray(ref, np.float32), **tol(dtype)
    )

    def loss_fused(x, w, b):
        y = ops.fused_layer_norm_affine(x, w, b, hidden, eps, memory_efficient)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def loss_ref(x, w, b):
        y = ref_layer_norm(x, w, b, eps)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32),
            np.asarray(r, np.float32),
            **tol(dtype),
        )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("memory_efficient", [False, True])
def test_rms_norm_affine_fwd_bwd(dtype, memory_efficient):
    key = jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(key)
    shape, hidden = (8, 33, 128), 128
    x = jax.random.normal(k1, shape, dtype)
    w = (1.0 + 0.1 * jax.random.normal(k2, (hidden,))).astype(jnp.float32)
    eps = 1e-6

    fused = ops.fused_rms_norm_affine(x, w, hidden, eps, memory_efficient)
    ref = ref_rms_norm(x, w, eps)
    np.testing.assert_allclose(
        np.asarray(fused, np.float32), np.asarray(ref, np.float32), **tol(dtype)
    )

    gf = jax.grad(
        lambda x, w: jnp.sum(
            ops.fused_rms_norm_affine(x, w, hidden, eps, memory_efficient)
            .astype(jnp.float32) ** 2
        ),
        argnums=(0, 1),
    )(x, w)
    gr = jax.grad(
        lambda x, w: jnp.sum(ref_rms_norm(x, w, eps).astype(jnp.float32) ** 2),
        argnums=(0, 1),
    )(x, w)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(r, np.float32), **tol(dtype)
        )


def test_non_affine_variants():
    x = jax.random.normal(jax.random.PRNGKey(2), (10, 48))
    np.testing.assert_allclose(
        np.asarray(ops.fused_layer_norm(x, 48)),
        np.asarray(ref_layer_norm(x, None, None, 1e-6)),
        rtol=1e-5,
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(ops.fused_rms_norm(x, 48)),
        np.asarray(ref_rms_norm(x, None, 1e-6)),
        rtol=1e-5,
        atol=1e-5,
    )


def test_multidim_normalized_shape():
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 4, 6))
    w = jnp.ones((4, 6))
    b = jnp.zeros((4, 6))
    got = ops.fused_layer_norm_affine(x, w, b, (4, 6), 1e-5)
    ref = ref_layer_norm(x.reshape(5, 24), w.reshape(24), b.reshape(24), 1e-5)
    np.testing.assert_allclose(
        np.asarray(got).reshape(5, 24), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("memory_efficient", [False, True])
@pytest.mark.parametrize("rms", [False, True])
def test_pallas_kernel_matches_jnp_path(memory_efficient, rms):
    """Run the Pallas kernels in interpret mode on CPU; must match jnp path."""
    key = jax.random.PRNGKey(4)
    k1, k2, k3 = jax.random.split(key, 3)
    shape, hidden = (33, 256), 256  # odd rows exercise grid remainder masking
    x = jax.random.normal(k1, shape, jnp.float32)
    w = 1.0 + 0.1 * jax.random.normal(k2, (hidden,))
    b = 0.1 * jax.random.normal(k3, (hidden,))

    if rms:
        f = lambda x, w, b: ops.fused_rms_norm_affine(  # noqa: E731
            x, w, hidden, 1e-5, memory_efficient
        )
    else:
        f = lambda x, w, b: ops.fused_layer_norm_affine(  # noqa: E731
            x, w, b, hidden, 1e-5, memory_efficient
        )

    def run():
        y = f(x, w, b)
        g = jax.grad(lambda *a: jnp.sum(f(*a) ** 2), argnums=(0, 1, 2))(x, w, b)
        return y, g

    ops.set_use_pallas(False)
    try:
        y_ref, g_ref = run()
    finally:
        ops.set_use_pallas(None)
    ops.set_use_pallas(True)  # interpret mode on CPU
    try:
        y_pl, g_pl = run()
    finally:
        ops.set_use_pallas(None)

    np.testing.assert_allclose(
        np.asarray(y_pl), np.asarray(y_ref), rtol=1e-5, atol=1e-5
    )
    for a, r in zip(g_pl, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-4
        )


def test_flax_modules():
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 64))
    ln = FusedLayerNorm(64)
    params = ln.init(jax.random.PRNGKey(0), x)
    y = ln.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(ref_layer_norm(x, jnp.ones(64), jnp.zeros(64), 1e-5)),
        rtol=1e-5,
        atol=1e-5,
    )
    rn = FusedRMSNorm(64, elementwise_affine=False)
    params = rn.init(jax.random.PRNGKey(0), x)
    y = rn.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(ref_rms_norm(x, None, 1e-5)),
        rtol=1e-5,
        atol=1e-5,
    )
