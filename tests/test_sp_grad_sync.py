"""Sequence-parallel gradient synchronization.

Under Megatron SP at tp > 1, tp-replicated params used inside the
sequence-sharded region (layer norms, RowParallel biases, position
embeddings, MoE router/experts) get PARTIAL per-rank gradients — each tp
rank's backward covers only its S/tp sequence shard.  Megatron-LM fixes
this with a trainer-side allreduce; :func:`allreduce_sequence_parallel_
gradients` is that helper, driven by the param paths the modules register.

Load-bearing invariant tested here: tp=2 + SP grads, after the helper,
equal the unsharded model's grads (tp-degree-invariant init makes the
params identical) — and WITHOUT the helper the per-rank grads genuinely
differ, so the sync is proven necessary, not vacuous.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.models.bert import (
    BertConfig,
    BertForPreTraining,
    bert_pretrain_loss,
)
from apex_tpu.models.gpt import GptConfig, GptModel, gpt_lm_loss
from apex_tpu.transformer.tensor_parallel import (
    allreduce_sequence_parallel_gradients,
)

S, B = 8, 2
GPT_KW = dict(
    vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, max_seq_len=16, dtype=jnp.float32,
)
TOL = dict(rtol=2e-4, atol=1e-5)


def _ids():
    return jax.random.randint(jax.random.PRNGKey(7), (S, B), 0, 64)


def _run_tp2(f, *args):
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=2)
    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(),) * len(args),
            out_specs=P(), check_vma=False,
        )
    )(*args)
    ps.destroy_model_parallel()
    return out


def test_gpt_sp_grads_match_unsharded():
    """Dense GPT (learned positions): LN / Row-bias / pos-emb grads under
    tp=2+SP equal the unsharded grads only after the tp psum."""
    cfg_sp = GptConfig(sequence_parallel=True, rotary=False, **GPT_KW)
    m_sp = GptModel(cfg_sp)
    ids = _ids()

    def f(key, ids):
        params = m_sp.init(key, ids)
        loss, grads = jax.value_and_grad(
            lambda p: gpt_lm_loss(p, m_sp, ids)
        )(params)
        g = grads["params"]
        raw = (
            g["layers"]["block"]["ln_attn"]["scale"],
            g["layers"]["block"]["out"]["bias"],
            g["ln_f"]["scale"],
            g["position_embeddings"],
        )
        synced = allreduce_sequence_parallel_gradients(grads)
        gs = synced["params"]
        return (
            loss,
            raw,
            (
                gs["layers"]["block"]["ln_attn"]["scale"],
                gs["layers"]["block"]["out"]["bias"],
                gs["ln_f"]["scale"],
                gs["position_embeddings"],
            ),
        )

    # out_specs P() replicates; raw per-rank grads differ across tp, so
    # return them summed manually for the "partial ≠ total" check instead:
    # here we re-run with out_specs P() only on synced values.
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=2)
    loss, raw, synced = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P()),
            out_specs=(P(), P(ps.TENSOR_PARALLEL_AXIS), P()),
            check_vma=False,
        )
    )(jax.random.PRNGKey(0), ids)
    ps.destroy_model_parallel()

    # unsharded reference (tp-degree-invariant init: same key, same params)
    cfg_ref = GptConfig(sequence_parallel=False, rotary=False, **GPT_KW)
    m_ref = GptModel(cfg_ref)
    params = m_ref.init(jax.random.PRNGKey(0), ids)
    loss_ref, grads_ref = jax.value_and_grad(
        lambda p: gpt_lm_loss(p, m_ref, ids)
    )(params)
    gr = grads_ref["params"]
    ref = (
        gr["layers"]["block"]["ln_attn"]["scale"],
        gr["layers"]["block"]["out"]["bias"],
        gr["ln_f"]["scale"],
        gr["position_embeddings"],
    )

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    names = ("ln_attn.scale", "out.bias", "ln_f.scale", "pos_emb")
    for name, s, r, partial in zip(names, synced, ref, raw):
        np.testing.assert_allclose(
            np.asarray(s), np.asarray(r), err_msg=name, **TOL
        )
        # the per-rank partials (stacked over tp along axis 0) must (a)
        # differ between ranks and (b) sum to the true grad
        p = np.asarray(partial).reshape(2, *np.asarray(s).shape)
        assert not np.allclose(p[0], p[1]), f"{name}: partials identical"
        np.testing.assert_allclose(
            p[0] + p[1], np.asarray(r), err_msg=f"{name} partial sum", **TOL
        )


def test_gpt_moe_sp_grads_match_unsharded(eight_devices):
    """MoE GPT under tp=2 + SP: sync_moe_gradients(sequence_parallel_axis=
    "tp") makes router/expert grads match the unsharded model."""
    from apex_tpu.transformer.moe import sync_moe_gradients

    # capacity_factor = num_experts ⇒ per-rank capacity covers every local
    # token, so no drops anywhere and the SP routing is exactly equivalent
    # to unsharded routing (drop PATTERNS are otherwise legitimately
    # shard-local — capacity is per S/tp shard under SP)
    kw = dict(GPT_KW, num_experts=8, moe_capacity_factor=8.0)
    cfg_sp = GptConfig(sequence_parallel=True, rotary=True, **kw)
    m_sp = GptModel(cfg_sp)
    ids = _ids()

    def f(key, ids):
        params = m_sp.init(key, ids)
        loss, grads = jax.value_and_grad(
            lambda p: gpt_lm_loss(p, m_sp, ids)
        )(params)
        grads = sync_moe_gradients(
            grads, average=True,
            sequence_parallel_axis=ps.TENSOR_PARALLEL_AXIS,
        )
        g = grads["params"]["layers"]["block"]
        e1 = jax.lax.all_gather(
            g["moe"]["expert_w1"], ps.DATA_PARALLEL_AXIS, axis=1, tiled=True
        )  # (L, E, H, F): gather the dp-sharded expert dim back
        e2 = jax.lax.all_gather(
            g["moe"]["expert_w2"], ps.DATA_PARALLEL_AXIS, axis=1, tiled=True
        )
        return loss, g["moe"]["router"], e1, e2, g["ln_mlp"]["scale"]

    loss, router, e1, e2, ln = _run_tp2(f, jax.random.PRNGKey(0), ids)

    cfg_ref = GptConfig(sequence_parallel=False, rotary=True, **kw)
    m_ref = GptModel(cfg_ref)
    params = m_ref.init(jax.random.PRNGKey(0), ids)
    loss_ref, grads_ref = jax.value_and_grad(
        lambda p: gpt_lm_loss(p, m_ref, ids)
    )(params)
    g = grads_ref["params"]["layers"]["block"]

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(router), np.asarray(g["moe"]["router"]),
        err_msg="router", **TOL
    )
    np.testing.assert_allclose(
        np.asarray(e1), np.asarray(g["moe"]["expert_w1"]),
        err_msg="expert_w1", **TOL
    )
    np.testing.assert_allclose(
        np.asarray(e2), np.asarray(g["moe"]["expert_w2"]),
        err_msg="expert_w2", **TOL
    )
    np.testing.assert_allclose(
        np.asarray(ln), np.asarray(g["ln_mlp"]["scale"]),
        err_msg="ln_mlp", **TOL
    )


def test_bert_sp_grads_match_unsharded():
    """BERT tp=2+SP: encoder LN grads (inside the SP region) need the tp
    psum; embedding-region and head params (outside it) must NOT get it."""
    kw = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position_embeddings=16,
        dtype=jnp.float32, type_vocab_size=2,
    )
    m_sp = BertForPreTraining(BertConfig(sequence_parallel=True, **kw))
    ids = _ids()
    batch = {
        "input_ids": ids,
        "token_type_ids": jnp.zeros_like(ids),
        "attention_mask": jnp.ones((B, S), jnp.int32),
        "mlm_labels": jnp.where(ids % 5 == 0, ids, -1),
        "nsp_labels": jnp.zeros((B,), jnp.int32),
    }

    def f(key, batch):
        params = m_sp.init(key, batch["input_ids"])
        loss, grads = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m_sp, batch)
        )(params)
        grads = allreduce_sequence_parallel_gradients(grads)
        g = grads["params"]
        enc = g["bert"]["encoder"]["layers"]["layer"]
        return (
            loss,
            enc["ln_attn"]["scale"],
            enc["mlp"]["fc2"]["bias"],
            g["bert"]["embeddings"]["ln"]["scale"],
            g["bert"]["embeddings"]["position_embeddings"],
            g["mlm_ln"]["scale"],
            g["mlm_dense"]["kernel"],
            g["pooler"]["kernel"],
            g["nsp_head"]["kernel"],
        )

    out = _run_tp2(f, jax.random.PRNGKey(0), batch)
    (loss, ln_attn, fc2_bias, emb_ln, pos, mlm_ln, mlm_dense, pooler,
     nsp_head) = out

    m_ref = BertForPreTraining(BertConfig(sequence_parallel=False, **kw))
    params = m_ref.init(jax.random.PRNGKey(0), batch["input_ids"])
    loss_ref, grads_ref = jax.value_and_grad(
        lambda p: bert_pretrain_loss(p, m_ref, batch)
    )(params)
    g = grads_ref["params"]
    enc = g["bert"]["encoder"]["layers"]["layer"]

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    for name, got, want in (
        ("ln_attn.scale", ln_attn, enc["ln_attn"]["scale"]),
        ("fc2.bias", fc2_bias, enc["mlp"]["fc2"]["bias"]),
        ("embeddings.ln.scale", emb_ln, g["bert"]["embeddings"]["ln"]["scale"]),
        ("pos_emb", pos, g["bert"]["embeddings"]["position_embeddings"]),
        ("mlm_ln.scale", mlm_ln, g["mlm_ln"]["scale"]),
        ("mlm_dense.kernel", mlm_dense, g["mlm_dense"]["kernel"]),
        ("pooler.kernel", pooler, g["pooler"]["kernel"]),
        ("nsp_head.kernel", nsp_head, g["nsp_head"]["kernel"]),
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), err_msg=name, **TOL
        )


def test_gpt_tp_noSP_grads_match_unsharded():
    """tp=2 WITHOUT SP: the copy_to boundary before the vocab-sharded
    decoder matmul must make ln_f / last-segment grads exactly the
    unsharded ones per rank — no gradient sync needed at all."""
    cfg = GptConfig(sequence_parallel=False, rotary=True, **GPT_KW)
    m = GptModel(cfg)
    ids = _ids()

    def f(key, ids):
        params = m.init(key, ids)
        _, grads = jax.value_and_grad(
            lambda p: gpt_lm_loss(p, m, ids)
        )(params)
        g = grads["params"]
        return (
            g["ln_f"]["scale"],
            g["layers"]["block"]["ln_mlp"]["scale"],
            g["layers"]["block"]["out"]["bias"],
        )

    out = _run_tp2(f, jax.random.PRNGKey(0), ids)

    params = m.init(jax.random.PRNGKey(0), ids)
    _, grads_ref = jax.value_and_grad(lambda p: gpt_lm_loss(p, m, ids))(
        params
    )
    g = grads_ref["params"]
    for name, got, want in (
        ("ln_f.scale", out[0], g["ln_f"]["scale"]),
        ("ln_mlp.scale", out[1], g["layers"]["block"]["ln_mlp"]["scale"]),
        ("out.bias", out[2], g["layers"]["block"]["out"]["bias"]),
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), err_msg=name, **TOL
        )


def test_bert_tp_noSP_head_grads_match_unsharded():
    """tp=2 without SP: BERT heads + mlm transform grads are per-rank
    correct thanks to the loss-side copy_to boundary."""
    kw = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position_embeddings=16,
        dtype=jnp.float32, type_vocab_size=2,
    )
    m = BertForPreTraining(BertConfig(sequence_parallel=False, **kw))
    ids = _ids()
    batch = {
        "input_ids": ids,
        "token_type_ids": jnp.zeros_like(ids),
        "attention_mask": jnp.ones((B, S), jnp.int32),
        "mlm_labels": jnp.where(ids % 5 == 0, ids, -1),
        "nsp_labels": jnp.zeros((B,), jnp.int32),
    }

    def f(key, batch):
        params = m.init(key, batch["input_ids"])
        _, grads = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m, batch)
        )(params)
        g = grads["params"]
        enc = g["bert"]["encoder"]["layers"]["layer"]
        return (
            g["mlm_ln"]["scale"],
            g["mlm_dense"]["kernel"],
            g["pooler"]["kernel"],
            enc["ln_mlp"]["scale"],
        )

    out = _run_tp2(f, jax.random.PRNGKey(0), batch)

    params = m.init(jax.random.PRNGKey(0), batch["input_ids"])
    _, grads_ref = jax.value_and_grad(
        lambda p: bert_pretrain_loss(p, m, batch)
    )(params)
    g = grads_ref["params"]
    enc = g["bert"]["encoder"]["layers"]["layer"]
    for name, got, want in (
        ("mlm_ln.scale", out[0], g["mlm_ln"]["scale"]),
        ("mlm_dense.kernel", out[1], g["mlm_dense"]["kernel"]),
        ("pooler.kernel", out[2], g["pooler"]["kernel"]),
        ("enc.ln_mlp.scale", out[3], enc["ln_mlp"]["scale"]),
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), err_msg=name, **TOL
        )


def test_sp_dropout_masks_differ_per_rank():
    """Dropout RNG: under SP each rank's sequence shard must get its OWN
    mask (≙ Megatron's per-tp-rank model-parallel RNG stream); without SP
    the replicated activations must get the SAME mask on every rank."""
    from apex_tpu.models.bert import BertEmbeddings

    kw = dict(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=4,
        intermediate_size=64, max_position_embeddings=16,
        dtype=jnp.float32, type_vocab_size=2, hidden_dropout=0.5,
    )
    ids = _ids()

    def run(sp):
        m = BertEmbeddings(BertConfig(sequence_parallel=sp, **kw))

        def f(key, ids):
            params = m.init(key, ids)
            out = m.apply(
                params, ids, deterministic=False,
                rngs={"dropout": jax.random.PRNGKey(3)},
            )
            # stack each rank's shard (SP) / full copy (non-SP) over tp
            return out

        mesh = ps.initialize_model_parallel(tensor_model_parallel_size=2)
        out = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(), P()),
                out_specs=P(ps.TENSOR_PARALLEL_AXIS), check_vma=False,
            )
        )(jax.random.PRNGKey(0), ids)
        ps.destroy_model_parallel()
        return np.asarray(out)

    # SP: out is (S, B, H) = 2 stacked (S/2, B, H) shards; dropout zeros
    # mark the mask — the two ranks' zero PATTERNS must differ
    out_sp = run(True)
    z = (out_sp == 0.0).reshape(2, -1)
    assert z[0].any() and z[1].any(), "dropout produced no zeros at p=0.5"
    assert not np.array_equal(z[0], z[1]), (
        "SP dropout masks identical across tp ranks (correlated dropout)"
    )

    # non-SP: out stacked (2S, B, H) = two replicated copies; the copies
    # (values AND masks) must be bit-identical or the replicated
    # activation streams diverge
    out_rep = run(False)
    halves = out_rep.reshape(2, -1)
    np.testing.assert_array_equal(halves[0], halves[1])


def test_registry_cleared_on_destroy():
    ps.register_sequence_parallel_param(("a", "b"))
    assert ("a", "b") in ps.sequence_parallel_param_paths()
    ps.destroy_model_parallel()
    assert not ps.sequence_parallel_param_paths()


def test_registry_scoped_to_mesh_epoch():
    """Marks made under one mesh die with it; marks made before a mesh
    init don't leak into it (advisor r2: cross-model contamination)."""
    ps.destroy_model_parallel()
    ps.register_sequence_parallel_param(("meshless", "w"))
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=1)
    assert not ps.sequence_parallel_param_paths(), (
        "meshless-era mark leaked into the fresh mesh epoch"
    )
    ps.register_sequence_parallel_param(("model_a", "scale"))
    assert ("model_a", "scale") in ps.sequence_parallel_param_paths()
    ps.destroy_model_parallel()
    assert not ps.sequence_parallel_param_paths(), (
        "mark survived destroy_model_parallel"
    )


def test_strict_raises_on_stale_registry():
    """A registered path absent from the grad tree (renamed model / stale
    registry) must raise, not silently skip the psum (VERDICT r2 item 6)."""
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=2)
    ps.register_sequence_parallel_param(("old_name", "scale"))
    grads = {"params": {"new_name": {"scale": jnp.ones((4,))}}}

    def f(grads):
        return allreduce_sequence_parallel_gradients(grads)

    with pytest.raises(ValueError, match="old_name/scale"):
        jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(),), out_specs=P(),
                check_vma=False,
            )
        )(grads)
    ps.destroy_model_parallel()


def test_strict_false_allows_partial_tree():
    """strict=False keeps the old permissive behavior for intentionally
    partial trees (e.g. one pipeline stage's grads)."""
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=2)
    ps.register_sequence_parallel_param(("other_model", "scale"))
    ps.register_sequence_parallel_param(("mine", "scale"))
    grads = {"params": {"mine": {"scale": jnp.ones((4,))}}}

    def f(grads):
        return allreduce_sequence_parallel_gradients(grads, strict=False)

    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False,
        )
    )(grads)
    # matched path is psum'd over tp=2
    np.testing.assert_allclose(
        np.asarray(out["params"]["mine"]["scale"]), 2.0 * np.ones((4,))
    )
    ps.destroy_model_parallel()


def test_reregistration_on_retrace():
    """destroy → re-initialize → re-trace repopulates the registry (the
    lifecycle the docstring contracts): same model traced in a second mesh
    epoch syncs correctly again."""
    cfg = GptConfig(sequence_parallel=True, rotary=False, **GPT_KW)
    m = GptModel(cfg)
    ids = _ids()

    def f(key, ids):
        params = m.init(key, ids)
        _, grads = jax.value_and_grad(lambda p: gpt_lm_loss(p, m, ids))(
            params
        )
        grads = allreduce_sequence_parallel_gradients(grads)
        return grads["params"]["ln_f"]["scale"]

    first = _run_tp2(f, jax.random.PRNGKey(0), ids)
    assert not ps.sequence_parallel_param_paths()  # epoch ended clean
    second = _run_tp2(f, jax.random.PRNGKey(0), ids)
    np.testing.assert_allclose(np.asarray(first), np.asarray(second))
