"""Resilience subsystem: chaos injection, guarded steps, retry, auto-resume.

The three end-to-end acceptance paths:

- an injected NaN step is skipped with params bit-identical (guards);
- a simulated preemption (real SIGTERM through the signal machinery)
  checkpoints, and a relaunch resumes within one step (runner);
- a failed-then-healed rendezvous succeeds via retry instead of silently
  degrading to single-process (retry + multihost strict mode).
"""

import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.optimizers import fused_sgd
from apex_tpu.resilience import (
    GradGuard,
    PreemptionHandler,
    ResilientCheckpointManager,
    RetryPolicy,
    chaos,
    guarded_amp_update,
    retry_call,
    robust_initialize_distributed,
    run_resilient,
)


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.clear()
    yield
    chaos.clear()


def _bits(tree):
    return [
        (np.asarray(x).dtype.str, np.asarray(x).tobytes())
        for x in jax.tree_util.tree_leaves(tree)
    ]


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_step_schedule_and_max_hits():
    f = chaos.Fault(chaos.GRADS, steps=(3, 5), mode="nan", max_hits=1)
    chaos.configure(f)
    assert chaos.active(chaos.GRADS, 2) is None
    assert chaos.active(chaos.GRADS, 3) is f  # first hit
    assert chaos.active(chaos.GRADS, 5) is None  # budget spent
    assert chaos.active(chaos.CHECKPOINT_SAVE, 3) is None  # wrong site


@pytest.mark.chaos
def test_chaos_probability_is_deterministic():
    f = chaos.Fault(chaos.GRADS, probability=0.5, mode="nan")
    chaos.configure(f, seed=7)
    first = [chaos.active(chaos.GRADS, s) is not None for s in range(64)]
    chaos.configure(f, seed=7)
    again = [chaos.active(chaos.GRADS, s) is not None for s in range(64)]
    assert first == again
    assert any(first) and not all(first)  # a real coin, not a constant
    chaos.configure(f, seed=8)
    other = [chaos.active(chaos.GRADS, s) is not None for s in range(64)]
    assert first != other  # seed moves the schedule


@pytest.mark.chaos
def test_chaos_parse_spec():
    faults, seed = chaos.parse_spec(
        "grads:nan@3,7;checkpoint_save:raise:x1@5;preemption@12;"
        "collective:stall:p=0.25;seed=42"
    )
    assert seed == 42
    by_site = {f.site: f for f in faults}
    assert by_site[chaos.GRADS].steps == (3, 7)
    assert by_site[chaos.GRADS].mode == "nan"
    assert by_site[chaos.GRADS].max_hits is None
    assert by_site[chaos.CHECKPOINT_SAVE].steps == (5,)
    assert by_site[chaos.CHECKPOINT_SAVE].max_hits == 1
    assert by_site[chaos.PREEMPTION].mode == "raise"
    assert by_site[chaos.COLLECTIVE].mode == "stall"
    assert by_site[chaos.COLLECTIVE].probability == 0.25


@pytest.mark.chaos
def test_chaos_corrupt_tree_and_inject_restores():
    tree = {"w": jnp.ones((4,)), "b": jnp.zeros((2,))}
    with chaos.inject(chaos.Fault(chaos.GRADS, steps=(1,), mode="nan")):
        same = chaos.corrupt_tree(tree, 0)
        assert _bits(same) == _bits(tree)
        bad = chaos.corrupt_tree(tree, 1)
        assert not np.all(np.isfinite(np.asarray(bad["w"]))) or not np.all(
            np.isfinite(np.asarray(bad["b"]))
        )
    assert chaos.faults() == ()  # restored on exit


@pytest.mark.chaos
def test_chaos_steps_win_over_probability():
    """An explicit step schedule pins the fault to exactly those steps —
    a also-set probability must not add extra firings."""
    f = chaos.Fault(chaos.GRADS, steps=(3,), probability=1.0, mode="nan")
    chaos.configure(f)
    fired = [s for s in range(10) if chaos.active(chaos.GRADS, s)]
    assert fired == [3]


@pytest.mark.chaos
def test_host_barrier_is_collective_chaos_site():
    """host_barrier: single-process no-op, chaos stall returns, chaos
    raise propagates (a collective abort kills the job)."""
    from apex_tpu.parallel import multihost

    multihost.host_barrier("clean", 0)  # no faults: plain no-op
    with chaos.inject(
        chaos.Fault(
            chaos.COLLECTIVE, steps=(1,), mode="stall", stall_seconds=0.01
        ),
        chaos.Fault(chaos.COLLECTIVE, steps=(2,), mode="raise"),
    ):
        multihost.host_barrier("stalls-then-proceeds", 1)
        with pytest.raises(chaos.InjectedFault):
            multihost.host_barrier("aborts", 2)


@pytest.mark.chaos
def test_chaos_unknown_site_rejected():
    with pytest.raises(ValueError):
        chaos.Fault("not_a_site", steps=(1,))


@pytest.mark.chaos
def test_parse_spec_rejects_unknown_site():
    """The registered-site registry (ISSUE 14 satellite): a typo'd
    site in an APEX_TPU_CHAOS spec must raise naming the clause and
    the registry — never build a fault that silently fires nowhere
    while a drill 'passes'."""
    with pytest.raises(ValueError, match=r"grdas.*registered sites"):
        chaos.parse_spec("grdas:nan@3")


@pytest.mark.chaos
def test_parse_spec_rejects_typod_token_as_bogus_mode():
    """The silent-miss bug: 'p0.001' (missing '=') used to be
    swallowed as a MODE, overwriting 'nan' and leaving a fault with
    no steps and probability 0.0 — registered, never firing.  Now it
    raises naming the token."""
    with pytest.raises(ValueError, match=r"p0\.001"):
        chaos.parse_spec("grads:nan:p0.001")
    # a mode that exists on another site is still rejected HERE
    with pytest.raises(ValueError, match="partial"):
        chaos.parse_spec("grads:partial@3")


@pytest.mark.chaos
def test_serve_sites_registered_with_modes():
    sites = chaos.registered_sites()
    for site in (chaos.SERVE_PREFILL, chaos.SERVE_DECODE,
                 chaos.SERVE_ADMISSION, chaos.SERVE_KV_ALLOC):
        assert site in sites
    assert "nan" in chaos.site_modes(chaos.SERVE_DECODE)
    assert "fail" in chaos.site_modes(chaos.SERVE_KV_ALLOC)
    # one spec drives train AND serve through the same parser
    faults, _ = chaos.parse_spec(
        "grads:nan@3;serve.decode:nan@5;serve.kv_alloc@2"
    )
    assert [f.site for f in faults] == [
        chaos.GRADS, chaos.SERVE_DECODE, chaos.SERVE_KV_ALLOC,
    ]
    assert faults[2].mode == "fail"  # the site's registered default


@pytest.mark.chaos
def test_register_site_conflicts_rejected():
    chaos.register_site("unit.test_site", ("raise",), "raise")
    # identical re-registration is idempotent
    chaos.register_site("unit.test_site", ("raise",), "raise")
    with pytest.raises(ValueError, match="already registered"):
        chaos.register_site("unit.test_site", ("raise", "stall"))
    with pytest.raises(ValueError, match="default mode"):
        chaos.register_site("unit.other_site", ("raise",), "stall")


# ---------------------------------------------------------------------------
# guarded step
# ---------------------------------------------------------------------------


def _guarded_setup(init_scale=4.0):
    tx = fused_sgd(0.1)
    params = {"w": jnp.asarray([1.0, 2.0, 3.0, 4.0], jnp.float32)}
    scaler = amp.DynamicLossScaler(init_scale=init_scale, hysteresis=1)
    guard = GradGuard(spike_factor=10.0, warmup_steps=2, ema_beta=0.5,
                      max_consecutive_skips=3)
    return tx, params, scaler, guard, tx.init(params), scaler.init(), guard.init()


@pytest.mark.chaos
def test_injected_nan_step_skipped_params_untouched():
    """Acceptance: a NaN burst skips the step; params/opt bit-identical."""
    tx, params, scaler, guard, ostate, sstate, gstate = _guarded_setup()
    good = {"w": jnp.full((4,), 4.0)}  # unscales to 1.0

    with chaos.inject(chaos.Fault(chaos.GRADS, steps=(2,), mode="nan")):
        for step in range(5):
            grads = chaos.corrupt_tree(good, step)
            p_bits, o_bits = _bits(params), _bits(ostate)
            params, ostate, sstate, gstate, verdict = guarded_amp_update(
                tx, scaler, guard, grads, ostate, params, sstate, gstate
            )
            if step == 2:
                assert float(verdict.skipped) == 1.0
                assert float(verdict.found_inf) == 1.0
                assert _bits(params) == p_bits  # bit-identical
                assert _bits(ostate) == o_bits
            else:
                assert float(verdict.skipped) == 0.0
                assert _bits(params) != p_bits  # training moved
    assert int(gstate.total_skips) == 1
    assert int(gstate.step) == 5


def test_spike_skip_is_not_an_overflow():
    """A finite 1000x grad spike skips the step but leaves the loss scale
    alone (only real overflow feeds the hysteresis)."""
    tx, params, scaler, guard, ostate, sstate, gstate = _guarded_setup()
    good = {"w": jnp.full((4,), 4.0)}
    for _ in range(3):  # past warmup; EMA learns the healthy norm
        params, ostate, sstate, gstate, v = guarded_amp_update(
            tx, scaler, guard, good, ostate, params, sstate, gstate
        )
        assert float(v.skipped) == 0.0
    scale_before = float(sstate.loss_scale)
    p_bits, s_bits = _bits(params), _bits(sstate)
    spike = {"w": jnp.full((4,), 4000.0)}  # finite, 1000x
    params, ostate, sstate, gstate, v = guarded_amp_update(
        tx, scaler, guard, spike, ostate, params, sstate, gstate
    )
    assert bool(v.spike)
    assert float(v.found_inf) == 0.0
    assert float(v.skipped) == 1.0
    assert _bits(params) == p_bits
    assert _bits(sstate) == s_bits  # WHOLE scaler state frozen: a spike
    # skip must not tick growth_tracker toward a scale growth either
    assert float(sstate.loss_scale) == scale_before
    # EMA untouched by the skipped step: the same spike still skips
    params, ostate, sstate, gstate, v = guarded_amp_update(
        tx, scaler, guard, spike, ostate, params, sstate, gstate
    )
    assert float(v.skipped) == 1.0
    assert int(gstate.consecutive_skips) == 2


def test_guard_budget_exhaustion_and_reset():
    tx, params, scaler, guard, ostate, sstate, gstate = _guarded_setup()
    good = {"w": jnp.full((4,), 4.0)}
    bad = {"w": jnp.asarray([jnp.nan, 0.0, 0.0, 0.0])}
    for _ in range(3):
        params, ostate, sstate, gstate, _ = guarded_amp_update(
            tx, scaler, guard, bad, ostate, params, sstate, gstate
        )
    assert bool(guard.budget_exhausted(gstate))
    params, ostate, sstate, gstate, _ = guarded_amp_update(
        tx, scaler, guard, good, ostate, params, sstate, gstate
    )
    assert not bool(guard.budget_exhausted(gstate))
    assert int(gstate.consecutive_skips) == 0
    assert int(gstate.total_skips) == 3


def test_guarded_update_is_jittable():
    tx, params, scaler, guard, ostate, sstate, gstate = _guarded_setup()

    @jax.jit
    def step(g, o, p, s, gs):
        return guarded_amp_update(tx, scaler, guard, g, o, p, s, gs)

    good = {"w": jnp.full((4,), 4.0)}
    p1, o1, s1, g1, v = step(good, ostate, params, sstate, gstate)
    assert float(v.skipped) == 0.0
    bad = {"w": jnp.full((4,), jnp.inf)}
    p2, _, _, _, v2 = step(bad, o1, p1, s1, g1)
    assert float(v2.skipped) == 1.0
    assert _bits(p2) == _bits(p1)


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------


def test_retry_call_heals_and_backs_off():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    policy = RetryPolicy(
        max_attempts=4, backoff=0.1, factor=2.0, sleep=sleeps.append
    )
    with pytest.warns(RuntimeWarning, match="retrying"):
        assert retry_call(flaky, policy=policy) == "ok"
    assert calls["n"] == 3
    assert sleeps == [0.1, 0.2]  # exponential


def test_retry_call_raises_after_budget():
    def always():
        raise OSError("down")

    policy = RetryPolicy(max_attempts=3, backoff=0.0, sleep=lambda _: None)
    with pytest.warns(RuntimeWarning):
        with pytest.raises(OSError, match="down"):
            retry_call(always, policy=policy)


@pytest.mark.chaos
def test_rendezvous_fails_then_heals_via_retry(monkeypatch):
    """Acceptance: two injected rendezvous failures, third attempt joins —
    no silent single-process degrade, no exception."""
    from apex_tpu.parallel import multihost

    policy = RetryPolicy(max_attempts=3, backoff=0.0, sleep=lambda _: None)
    with chaos.inject(
        chaos.Fault(chaos.RENDEZVOUS, steps=(0, 1), mode="raise")
    ):
        with pytest.warns(RuntimeWarning, match="rendezvous"):
            idx, count = robust_initialize_distributed(policy=policy)
    # this harness has no cluster env: the healed attempt is the benign
    # single-process join
    assert (idx, count) == (0, 1)
    assert not multihost.distributed_is_initialized()


@pytest.mark.chaos
def test_rendezvous_exhausted_raises_not_degrades(monkeypatch):
    policy = RetryPolicy(max_attempts=2, backoff=0.0, sleep=lambda _: None)
    with chaos.inject(
        chaos.Fault(chaos.RENDEZVOUS, steps=(0, 1, 2, 3), mode="raise")
    ):
        with pytest.warns(RuntimeWarning):
            with pytest.raises(chaos.InjectedFault):
                robust_initialize_distributed(policy=policy)


def test_robust_rendezvous_strict_on_real_failure(monkeypatch):
    """With cluster hints present and a join that fails then heals, the
    retry path lands on the joined runtime instead of degrading."""
    from apex_tpu.parallel import multihost

    attempts = {"n": 0}

    def fake_initialize(*a, **k):
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise RuntimeError("coordinator unreachable")

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    policy = RetryPolicy(max_attempts=3, backoff=0.0, sleep=lambda _: None)
    try:
        with pytest.warns(RuntimeWarning, match="rendezvous"):
            idx, count = robust_initialize_distributed(policy=policy)
        assert attempts["n"] == 3
        assert (idx, count) == (0, 1)  # single-process fake backend
        assert multihost.distributed_is_initialized()
    finally:
        multihost._INITIALIZED = False


# ---------------------------------------------------------------------------
# runner: auto-resume, preemption, rollback, checkpoint retry
# ---------------------------------------------------------------------------


def _counting_job():
    """A deterministic toy job: state counts accepted steps and folds the
    batch value in, so any divergence between runs is visible bitwise."""

    def batch_fn(step):
        return jnp.asarray(float(step + 1), jnp.float32)

    def step_fn(state, batch):
        return (
            {"acc": state["acc"] + batch, "n": state["n"] + 1},
            {"skipped": False},
        )

    return {"acc": jnp.zeros((), jnp.float32), "n": jnp.zeros((), jnp.int32)}, (
        step_fn,
        batch_fn,
    )


def test_run_resilient_fresh_run_completes(tmp_path):
    init, (step_fn, batch_fn) = _counting_job()
    res = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path, num_steps=5
    )
    assert res.last_step == 4
    assert res.steps_run == 5
    assert res.resumed_from is None
    assert not res.preempted
    assert float(res.state["acc"]) == sum(range(1, 6))
    with ResilientCheckpointManager(tmp_path) as mgr:
        assert mgr.latest_step() == 4


def test_run_resilient_auto_resumes_without_rerunning(tmp_path):
    init, (step_fn, batch_fn) = _counting_job()
    run_resilient(step_fn, init, batch_fn, directory=tmp_path, num_steps=3)
    res = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path, num_steps=6
    )
    assert res.resumed_from == 2
    assert res.steps_run == 3  # only the new steps
    assert float(res.state["acc"]) == sum(range(1, 7))


@pytest.mark.chaos
def test_preemption_checkpoints_and_resumes_within_one_step(tmp_path):
    """Acceptance: SIGTERM lands while step 5 runs (an off-interval step)
    -> the in-flight step completes, a final checkpoint is forced, and a
    relaunch resumes exactly one step later with a final state bitwise
    identical to an uninterrupted run."""
    init, (step_fn, batch_fn) = _counting_job()
    with chaos.inject(chaos.Fault(chaos.PREEMPTION, steps=(5,))):
        res1 = run_resilient(
            step_fn, init, batch_fn, directory=tmp_path, num_steps=10,
            save_interval_steps=2,
        )
    assert res1.preempted
    assert res1.last_step == 5  # the interrupted step still completed
    assert res1.steps_run == 6
    with ResilientCheckpointManager(tmp_path) as mgr:
        # 5 is off-interval (saves land on 0,2,4): the forced final
        # checkpoint must cover it anyway
        assert mgr.latest_step() == 5

    res2 = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path, num_steps=10,
        save_interval_steps=2,
    )
    assert res2.resumed_from == 5  # within one step of the preemption
    assert res2.steps_run == 4
    assert not res2.preempted

    ref = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path / "uninterrupted",
        num_steps=10,
    )
    assert _bits(res2.state) == _bits(ref.state)


@pytest.mark.chaos
def test_preemption_spec_cannot_livelock_resume(tmp_path):
    """Relaunching under the SAME chaos spec (preemption fires again in
    the new process) still makes progress every launch — the simulated
    eviction lands after the step computes, never before."""
    init, (step_fn, batch_fn) = _counting_job()
    fault = chaos.Fault(chaos.PREEMPTION, steps=(4,))
    with chaos.inject(fault):
        res1 = run_resilient(
            step_fn, init, batch_fn, directory=tmp_path, num_steps=8
        )
    assert res1.preempted and res1.last_step == 4
    # relaunch with the fault still configured: resumes PAST the fault
    # step (start=5 > 4, so it never re-fires) and completes
    with chaos.inject(fault):
        res2 = run_resilient(
            step_fn, init, batch_fn, directory=tmp_path, num_steps=8
        )
    assert res2.resumed_from == 4
    assert not res2.preempted
    assert res2.last_step == 7


def test_preemption_handler_sets_flag_and_restores(tmp_path):
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as h:
        assert not h.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.requested
    assert signal.getsignal(signal.SIGTERM) is prev


@pytest.mark.chaos
def test_rollback_after_consecutive_skips(tmp_path):
    """Three poisoned steps in a row exhaust the budget; the loop rolls
    back to the last complete checkpoint and replays the (now healed)
    steps."""
    init, (step_fn, batch_fn) = _counting_job()

    def guarded_step(state, batch):
        step = int(state["n"])
        poisoned = chaos.active(chaos.GRADS, step) is not None
        if poisoned:
            return state, {"skipped": True}  # step dropped, state frozen
        return step_fn(state, batch)

    # fault fires once per step 5,6,7 then is exhausted (the transient heals)
    with chaos.inject(
        chaos.Fault(chaos.GRADS, steps=(5, 6, 7), mode="nan", max_hits=3)
    ):
        res = run_resilient(
            guarded_step, init, batch_fn, directory=tmp_path, num_steps=10,
            rollback_after=3,
        )
    assert res.rollbacks == 1
    assert res.skipped_steps == 3
    assert res.last_step == 9
    # replayed cleanly: same state as a faultless run
    ref = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path / "ref", num_steps=10
    )
    assert _bits(res.state) == _bits(ref.state)


@pytest.mark.chaos
def test_rollback_budget_refuses_to_livelock(tmp_path):
    """A deterministic skip cause (fault with unbounded hits) would
    replay-and-skip forever; max_rollbacks converts that into an error."""
    init, (step_fn, batch_fn) = _counting_job()

    def guarded_step(state, batch):
        if chaos.active(chaos.GRADS, int(state["n"])) is not None:
            return state, {"skipped": True}
        return step_fn(state, batch)

    with chaos.inject(
        chaos.Fault(chaos.GRADS, steps=(5, 6, 7), mode="nan")  # no max_hits
    ):
        with pytest.raises(RuntimeError, match="livelock"):
            run_resilient(
                guarded_step, init, batch_fn, directory=tmp_path,
                num_steps=10, rollback_after=3, max_rollbacks=2,
            )


@pytest.mark.chaos
def test_checkpoint_save_fault_heals_on_retry(tmp_path):
    init, (step_fn, batch_fn) = _counting_job()
    policy = RetryPolicy(max_attempts=3, backoff=0.0, sleep=lambda _: None)
    with chaos.inject(
        chaos.Fault(chaos.CHECKPOINT_SAVE, steps=(2,), mode="raise", max_hits=1)
    ):
        with pytest.warns(RuntimeWarning, match="checkpoint save"):
            res = run_resilient(
                step_fn, init, batch_fn, directory=tmp_path, num_steps=4,
                policy=policy,
            )
    assert res.last_step == 3
    with ResilientCheckpointManager(tmp_path) as mgr:
        assert mgr.all_steps() == [0, 1, 2, 3]  # step 2 made it via retry


@pytest.mark.chaos
def test_partial_mode_drops_orbax_debris(tmp_path):
    """chaos ``partial`` save mode must actually create orbax-style
    uncommitted staging debris before raising — pinned directly on
    maybe_fail (the engine's background GC collects such debris, so
    integration tests can't assert its creation without racing)."""
    with chaos.inject(
        chaos.Fault(chaos.CHECKPOINT_SAVE, steps=(3,), mode="partial")
    ):
        with pytest.raises(chaos.InjectedFault):
            chaos.maybe_fail(chaos.CHECKPOINT_SAVE, 3, partial_dir=tmp_path)
    debris = [p for p in os.listdir(tmp_path)
              if p.startswith("3.orbax-checkpoint-tmp-")]
    assert debris, os.listdir(tmp_path)
    # the debris carries a payload file (a torn write, not an empty dir)
    assert os.listdir(tmp_path / debris[0])


@pytest.mark.chaos
def test_interrupted_save_never_corrupts_latest(tmp_path):
    """Acceptance (crash consistency): a save that dies mid-write (debris
    on disk, exception raised, retries exhausted) leaves latest_step()
    pointing at the previous COMPLETE checkpoint, and restore from it
    works; the relaunch then finishes the run."""
    init, (step_fn, batch_fn) = _counting_job()
    policy = RetryPolicy(max_attempts=2, backoff=0.0, sleep=lambda _: None)
    with chaos.inject(
        chaos.Fault(chaos.CHECKPOINT_SAVE, steps=(3,), mode="partial")
    ):
        with pytest.warns(RuntimeWarning):
            with pytest.raises(chaos.InjectedFault):
                run_resilient(
                    step_fn, init, batch_fn, directory=tmp_path,
                    num_steps=6, policy=policy,
                )
    # The torn write left orbax-style debris behind — unless the async
    # engine's writer-thread GC already collected it (a background
    # write completing after the fault prunes dead staging dirs, which
    # is a race this test must not depend on).  Plant debris of both
    # shapes so enumeration provably ignores it either way.
    (tmp_path / "4.orbax-checkpoint-tmp-99").mkdir(exist_ok=True)
    (tmp_path / "5").mkdir(exist_ok=True)  # digit-named, no commit marker
    with ResilientCheckpointManager(tmp_path) as mgr:
        assert mgr.latest_step() == 2
        assert mgr.all_steps() == [0, 1, 2]
        out = mgr.restore(2, template=init)
        assert int(out["n"]) == 3  # three steps applied

    res = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path, num_steps=6
    )
    assert res.resumed_from == 2
    assert res.last_step == 5
    ref = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path / "ref", num_steps=6
    )
    assert _bits(res.state) == _bits(ref.state)


@pytest.mark.chaos
def test_restore_fault_heals_on_retry(tmp_path):
    init, (step_fn, batch_fn) = _counting_job()
    run_resilient(step_fn, init, batch_fn, directory=tmp_path, num_steps=3)
    policy = RetryPolicy(max_attempts=2, backoff=0.0, sleep=lambda _: None)
    with chaos.inject(
        chaos.Fault(
            chaos.CHECKPOINT_RESTORE, steps=(2,), mode="raise", max_hits=1
        )
    ):
        with pytest.warns(RuntimeWarning, match="checkpoint restore"):
            res = run_resilient(
                step_fn, init, batch_fn, directory=tmp_path, num_steps=5,
                policy=policy,
            )
    assert res.resumed_from == 2
    assert res.last_step == 4
