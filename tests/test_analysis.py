"""Tests for the static-analysis subsystem (``apex_tpu/analysis/``).

Each pass gets a known-bad fixture (planted host transfer, dropped
donation, silent amp promotion, f64 literal, retrace, wrong collective
count) asserted to produce EXACTLY the expected rule id, plus a
clean-step fixture asserted to produce zero findings — the acceptance
contract of ISSUE 4, and the same properties ``tools/graph_lint.py``
gates in ``tools/verify_tier1.sh``.
"""

import json

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import analysis
from apex_tpu.analysis import hlo as hlo_lib


# ---------------------------------------------------------------------------
# transfer lint
# ---------------------------------------------------------------------------


def test_planted_debug_print_is_caught():
    def step(x):
        jax.debug.print("loss={x}", x=x.sum())
        return x * 2.0

    report = analysis.check(step, jnp.zeros((8,), jnp.float32))
    assert "transfer-callback" in report.rule_ids()
    # the callback also survives into compiled HLO as a custom-call
    assert "transfer-hlo-host" in report.rule_ids()
    assert not report.ok()


def test_planted_pure_callback_is_caught():
    def step(x):
        y = jax.pure_callback(
            lambda v: v * 2, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )
        return y + 1.0

    report = analysis.check(
        step, jnp.zeros((4,), jnp.float32), rules=("transfer",)
    )
    assert "transfer-callback" in report.rule_ids()


def test_callback_inside_scan_body_is_caught():
    """A transfer buried in a scan body fires every iteration — the
    recursive jaxpr walk must find it."""
    def step(x):
        def body(c, _):
            jax.debug.print("c={c}", c=c[0])
            return c + 1.0, None
        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    report = analysis.check(
        step, jnp.zeros((4,), jnp.float32), rules=("transfer",)
    )
    assert "transfer-callback" in report.rule_ids()


# ---------------------------------------------------------------------------
# promotion lint
# ---------------------------------------------------------------------------


def test_planted_silent_promotion_is_caught():
    """bf16 activations meeting a NON-weak f32 constant silently widen
    the whole downstream subgraph — the classic amp leak."""
    def step(x):
        return (x * jnp.float32(2.0)).sum()

    report = analysis.check(
        step, jnp.zeros((8,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.rule_ids() == ["promotion-widen"]


def test_weak_literal_does_not_flag():
    """A python-float literal is weakly typed: bf16 * 2.0 stays bf16 —
    nothing to flag."""
    def step(x):
        return (x * 2.0).sum()

    report = analysis.check(
        step, jnp.zeros((8,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.findings == []


def test_named_scope_marks_widening_intentional():
    def step(x):
        with jax.named_scope("f32_accum"):
            acc = x.astype(jnp.float32)
        return (acc * acc).sum()

    report = analysis.check(
        step, jnp.zeros((8,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.findings == []


def test_reduction_upcast_idiom_is_exempt():
    """jnp.sum on bf16 internally accumulates in f32 then narrows —
    by-design precision, not a silent promotion."""
    def step(x):
        return jnp.sum(x)

    report = analysis.check(
        step, jnp.zeros((64,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.findings == []


def test_planted_f64_is_caught():
    from jax.experimental import enable_x64

    with enable_x64():
        jaxpr = jax.make_jaxpr(
            lambda x: x * jnp.float64(3.0)
        )(jnp.zeros((4,), jnp.float64))
    report = analysis.lint_jaxpr(jaxpr)
    assert report.rule_ids() == ["promotion-f64"]
    assert not report.ok()


# ---------------------------------------------------------------------------
# donation lint
# ---------------------------------------------------------------------------


def test_planted_dropped_donation_is_caught():
    # both donated buffers are size-reduced away: no output matches,
    # XLA cannot alias either one
    def step(x, y):
        return jnp.sum(x) + jnp.sum(y)

    report = analysis.check(
        step, jnp.zeros((64,), jnp.float32), jnp.ones((32,), jnp.float32),
        donate_argnums=(0, 1),
    )
    assert report.rule_ids() == ["donation-dropped"]
    finding = report.by_rule("donation-dropped")[0]
    assert "2 of 2" in finding.message


def test_clean_donation_passes():
    def step(state):
        return {k: v + 1.0 for k, v in state.items()}

    state = {"w": jnp.zeros((16, 16)), "m": jnp.zeros((16, 16))}
    report = analysis.check(step, state, donate_argnums=(0,))
    assert report.findings == []


def test_input_output_alias_parser():
    header = (
        "HloModule jit_f, is_scheduled=true, input_output_alias={ "
        "{0}: (0, {}, may-alias), {1, 2}: (3, {}, must-alias) }, "
        "entry_computation_layout={(f32[8]{0})->f32[8]{0}}"
    )
    aliases = hlo_lib.input_output_aliases(header)
    assert aliases == [(0, "0"), (3, "1, 2")]
    assert hlo_lib.input_output_aliases("HloModule jit_g") == []


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------


def test_retrace_flagged_on_shape_change():
    s = analysis.RetraceSentinel()
    assert s.observe(jnp.zeros((8,), jnp.float32)) is None
    assert s.observe(jnp.zeros((8,), jnp.float32)) is None  # same sig
    finding = s.observe(jnp.zeros((16,), jnp.float32))  # planted retrace
    assert finding is not None and finding.rule == "retrace"
    assert s.retraces == 1
    assert "leaf 0" in finding.message


def test_retrace_flagged_on_static_value_change():
    s = analysis.RetraceSentinel()
    assert s.observe(jnp.zeros((4,)), flag=True) is None
    f = s.observe(jnp.zeros((4,)), flag=False)
    assert f is not None and f.rule == "retrace"


def test_retrace_allowed_budget():
    s = analysis.RetraceSentinel(allowed=2)
    assert s.observe(jnp.zeros((8,))) is None
    assert s.observe(jnp.zeros((7,))) is None  # ragged tail, budgeted
    assert s.observe(jnp.zeros((6,))) is not None


def test_retrace_steady_state_never_flags():
    s = analysis.RetraceSentinel()
    for _ in range(10):
        assert s.observe({"w": jnp.zeros((4, 4))}, jnp.zeros((4,))) is None
    assert s.retraces == 0 and s.calls == 10


# ---------------------------------------------------------------------------
# collective consistency
# ---------------------------------------------------------------------------

_AR_HLO = """
ENTRY %main {
  %p0 = f32[8,128]{1,0} parameter(0)
  %ar = f32[8,128]{1,0} all-reduce(%p0), replica_groups={{0,1}}
  ROOT %out = f32[8,128]{1,0} add(%ar, %ar)
}
"""


def test_planted_wrong_collective_count_is_caught():
    report = analysis.lint_hlo(
        _AR_HLO, expect_collectives={"all-reduce": 2}
    )
    assert report.rule_ids() == ["collective-count"]


def test_collective_dtype_and_bytes_checks():
    report = analysis.lint_hlo(
        _AR_HLO,
        expect_collectives={
            "all-reduce": {"count": 1, "dtypes": ["s8"], "bytes": 17}
        },
    )
    assert report.rule_ids() == ["collective-bytes", "collective-dtype"]
    clean = analysis.lint_hlo(
        _AR_HLO,
        expect_collectives={
            "all-reduce": {
                "count": 1, "dtypes": ["f32"], "bytes": 8 * 128 * 4,
            }
        },
    )
    assert clean.findings == []


def test_collective_count_live_on_mesh(eight_devices):
    """End to end on a real compiled program: one psum over the
    8-device mesh must be exactly one all-reduce."""
    mesh = Mesh(eight_devices, ("dp",))

    def step(x):
        return jax.lax.psum(x, "dp")

    fn = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
            check_vma=False,
        )
    )
    x = jnp.zeros((8, 16), jnp.float32)
    ok = analysis.check(fn, x, expect_collectives={"all-reduce": 1})
    assert ok.findings == []
    bad = analysis.check(fn, x, expect_collectives={"all-reduce": 3})
    assert bad.rule_ids() == ["collective-count"]


# ---------------------------------------------------------------------------
# host-transfer HLO scan
# ---------------------------------------------------------------------------


def test_host_transfer_ops_scan():
    hlo = """
ENTRY %main {
  %tok = token[] after-all()
  %in = ((f32[8]{0}), token[]) infeed(%tok)
  %cc = () custom-call(s64[] %c, f32[8]{0} %x), custom_call_target="xla_python_cpu_callback", api_version=API_VERSION_STATUS_RETURNING
  %send = (f32[8]{0}, u32[], token[]) send(%x, %tok), channel_id=1, is_host_transfer=true
  %benign = f32[8]{0} custom-call(%x), custom_call_target="Sharding"
}
"""
    found = hlo_lib.host_transfer_ops(hlo)
    kinds = sorted(why for _name, why in found)
    assert len(found) == 3
    assert kinds[0] == "callback custom-call (xla_python_cpu_callback)"
    assert "host send/recv" in kinds
    assert "infeed" in kinds


# ---------------------------------------------------------------------------
# the clean-step fixture: a full guarded train step with zero findings
# ---------------------------------------------------------------------------


def test_clean_step_produces_zero_findings():
    """A well-formed train step — donated state, policy-conformant
    dtypes, no callbacks — must come back clean on every pass."""
    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state, batch)
        return (
            {k: state[k] - 0.1 * grads[k] for k in state},
            loss,
        )

    state = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    batch = (jnp.ones((16, 8)), jnp.ones((16, 4)))
    report = analysis.check(
        step, state, batch,
        policy=jnp.float32, donate_argnums=(0,),
        name="clean_step",
    )
    assert report.findings == [], report.render()
    assert report.ok() and report.ok(fail_on="warning")


# ---------------------------------------------------------------------------
# report plumbing: JSON schema, catalog integrity, board publishing
# ---------------------------------------------------------------------------


def test_every_rule_is_cataloged_and_catalog_is_complete():
    assert set(analysis.RULES) == {
        "transfer-callback", "transfer-hlo-host",
        "promotion-f64", "promotion-widen",
        "donation-dropped", "retrace",
        "collective-count", "collective-bytes", "collective-dtype",
    }
    for rule, (sev, desc, hint) in analysis.RULES.items():
        assert sev in (analysis.ERROR, analysis.WARNING, analysis.INFO)
        assert desc and hint
    with pytest.raises(KeyError):
        analysis.make_finding("not-a-rule", path="", message="")


def test_report_json_roundtrip_and_severity_gate():
    f1 = analysis.make_finding("promotion-widen", path="p", message="m")
    f2 = analysis.make_finding("donation-dropped", path="q", message="n")
    report = analysis.Report([f1, f2], target="t", rules_run=("promotion",))
    blob = json.loads(report.to_json_line())
    assert blob["target"] == "t"
    assert blob["errors"] == 1 and blob["warnings"] == 1
    assert blob["findings"][0]["rule"] == "promotion-widen"
    assert not report.ok()  # one error
    warn_only = analysis.Report([f1])
    assert warn_only.ok()  # warnings pass the default gate
    assert not warn_only.ok(fail_on="warning")


def test_publish_report_rides_the_board():
    from apex_tpu.observability.metrics import board

    board.clear()
    report = analysis.Report(
        [analysis.make_finding("retrace", path="", message="x")],
        target="pub",
    )
    analysis.publish_report(report)
    snap = board.snapshot()
    assert snap["analysis/errors"] == 1
    assert snap["analysis/warnings"] == 0
    assert snap["analysis/rule/retrace"] == 1
    board.clear()


def test_unknown_rule_selector_raises():
    with pytest.raises(ValueError):
        analysis.check(lambda x: x, jnp.zeros(()), rules=("bogus",))


# ---------------------------------------------------------------------------
# the lint passes on our own codebase (ISSUE 4 satellite: contrib/ops)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["layer_norm", "softmax", "xentropy",
                                  "focal_loss", "group_norm"])
def test_own_ops_are_promotion_clean_under_bf16(name):
    """The promotion lint must pass on our own fused ops and contrib
    stubs: their f32 accumulation regions are marked policy-exempt
    (named scopes), so a bf16 policy sees zero findings."""
    from apex_tpu import ops
    from apex_tpu.contrib.focal_loss import sigmoid_focal_loss
    from apex_tpu.contrib.group_norm import group_norm

    bf = jnp.bfloat16
    x = jnp.ones((4, 64), bf)
    builders = {
        "layer_norm": lambda: jax.make_jaxpr(
            lambda x: jax.grad(
                lambda xx: ops.fused_layer_norm_affine(
                    xx, jnp.ones((64,), bf), jnp.zeros((64,), bf), 64
                ).sum()
            )(x).sum()
        )(x),
        "softmax": lambda: jax.make_jaxpr(
            lambda s: jax.grad(
                lambda ss: ops.scaled_masked_softmax(
                    ss, ss > 2, 2.0
                ).sum()
            )(s).sum()
        )(jnp.ones((2, 2, 8, 8), bf)),
        "xentropy": lambda: jax.make_jaxpr(
            lambda l: jax.grad(
                lambda ll: ops.softmax_cross_entropy_loss(
                    ll, jnp.zeros((8,), jnp.int32)
                ).sum()
            )(l).sum()
        )(jnp.ones((8, 32), bf)),
        "focal_loss": lambda: jax.make_jaxpr(
            lambda l: sigmoid_focal_loss(l, jnp.zeros((4, 10), bf)).sum()
        )(jnp.ones((4, 10), bf)),
        "group_norm": lambda: jax.make_jaxpr(
            lambda x: group_norm(x.reshape(4, 8, 8), 4).sum()
        )(x),
    }
    report = analysis.lint_jaxpr(
        builders[name](), policy=bf, name=f"ops/{name}"
    )
    assert report.findings == [], report.render()
