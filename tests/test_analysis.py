"""Tests for the static-analysis subsystem (``apex_tpu/analysis/``).

Each pass gets a known-bad fixture (planted host transfer, dropped
donation, silent amp promotion, f64 literal, retrace, wrong collective
count) asserted to produce EXACTLY the expected rule id, plus a
clean-step fixture asserted to produce zero findings — the acceptance
contract of ISSUE 4, and the same properties ``tools/graph_lint.py``
gates in ``tools/verify_tier1.sh``.
"""

import json

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import analysis
from apex_tpu.analysis import hlo as hlo_lib


# ---------------------------------------------------------------------------
# transfer lint
# ---------------------------------------------------------------------------


def test_planted_debug_print_is_caught():
    def step(x):
        jax.debug.print("loss={x}", x=x.sum())
        return x * 2.0

    report = analysis.check(step, jnp.zeros((8,), jnp.float32))
    assert "transfer-callback" in report.rule_ids()
    # the callback also survives into compiled HLO as a custom-call
    assert "transfer-hlo-host" in report.rule_ids()
    assert not report.ok()


def test_planted_pure_callback_is_caught():
    def step(x):
        y = jax.pure_callback(
            lambda v: v * 2, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )
        return y + 1.0

    report = analysis.check(
        step, jnp.zeros((4,), jnp.float32), rules=("transfer",)
    )
    assert "transfer-callback" in report.rule_ids()


def test_callback_inside_scan_body_is_caught():
    """A transfer buried in a scan body fires every iteration — the
    recursive jaxpr walk must find it."""
    def step(x):
        def body(c, _):
            jax.debug.print("c={c}", c=c[0])
            return c + 1.0, None
        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    report = analysis.check(
        step, jnp.zeros((4,), jnp.float32), rules=("transfer",)
    )
    assert "transfer-callback" in report.rule_ids()


# ---------------------------------------------------------------------------
# promotion lint
# ---------------------------------------------------------------------------


def test_planted_silent_promotion_is_caught():
    """bf16 activations meeting a NON-weak f32 constant silently widen
    the whole downstream subgraph — the classic amp leak."""
    def step(x):
        return (x * jnp.float32(2.0)).sum()

    report = analysis.check(
        step, jnp.zeros((8,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.rule_ids() == ["promotion-widen"]


def test_weak_literal_does_not_flag():
    """A python-float literal is weakly typed: bf16 * 2.0 stays bf16 —
    nothing to flag."""
    def step(x):
        return (x * 2.0).sum()

    report = analysis.check(
        step, jnp.zeros((8,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.findings == []


def test_named_scope_marks_widening_intentional():
    def step(x):
        with jax.named_scope("f32_accum"):
            acc = x.astype(jnp.float32)
        return (acc * acc).sum()

    report = analysis.check(
        step, jnp.zeros((8,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.findings == []


def test_reduction_upcast_idiom_is_exempt():
    """jnp.sum on bf16 internally accumulates in f32 then narrows —
    by-design precision, not a silent promotion."""
    def step(x):
        return jnp.sum(x)

    report = analysis.check(
        step, jnp.zeros((64,), jnp.bfloat16), policy=jnp.bfloat16
    )
    assert report.findings == []


def test_planted_f64_is_caught():
    from jax.experimental import enable_x64

    with enable_x64():
        jaxpr = jax.make_jaxpr(
            lambda x: x * jnp.float64(3.0)
        )(jnp.zeros((4,), jnp.float64))
    report = analysis.lint_jaxpr(jaxpr)
    assert report.rule_ids() == ["promotion-f64"]
    assert not report.ok()


# ---------------------------------------------------------------------------
# donation lint
# ---------------------------------------------------------------------------


def test_planted_dropped_donation_is_caught():
    # both donated buffers are size-reduced away: no output matches,
    # XLA cannot alias either one
    def step(x, y):
        return jnp.sum(x) + jnp.sum(y)

    report = analysis.check(
        step, jnp.zeros((64,), jnp.float32), jnp.ones((32,), jnp.float32),
        donate_argnums=(0, 1),
    )
    assert report.rule_ids() == ["donation-dropped"]
    finding = report.by_rule("donation-dropped")[0]
    assert "2 of 2" in finding.message


def test_clean_donation_passes():
    def step(state):
        return {k: v + 1.0 for k, v in state.items()}

    state = {"w": jnp.zeros((16, 16)), "m": jnp.zeros((16, 16))}
    report = analysis.check(step, state, donate_argnums=(0,))
    assert report.findings == []


def test_input_output_alias_parser():
    header = (
        "HloModule jit_f, is_scheduled=true, input_output_alias={ "
        "{0}: (0, {}, may-alias), {1, 2}: (3, {}, must-alias) }, "
        "entry_computation_layout={(f32[8]{0})->f32[8]{0}}"
    )
    aliases = hlo_lib.input_output_aliases(header)
    assert aliases == [(0, "0"), (3, "1, 2")]
    assert hlo_lib.input_output_aliases("HloModule jit_g") == []


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------


def test_retrace_flagged_on_shape_change():
    s = analysis.RetraceSentinel()
    assert s.observe(jnp.zeros((8,), jnp.float32)) is None
    assert s.observe(jnp.zeros((8,), jnp.float32)) is None  # same sig
    finding = s.observe(jnp.zeros((16,), jnp.float32))  # planted retrace
    assert finding is not None and finding.rule == "retrace"
    assert s.retraces == 1
    assert "leaf 0" in finding.message


def test_retrace_flagged_on_static_value_change():
    s = analysis.RetraceSentinel()
    assert s.observe(jnp.zeros((4,)), flag=True) is None
    f = s.observe(jnp.zeros((4,)), flag=False)
    assert f is not None and f.rule == "retrace"


def test_retrace_allowed_budget():
    s = analysis.RetraceSentinel(allowed=2)
    assert s.observe(jnp.zeros((8,))) is None
    assert s.observe(jnp.zeros((7,))) is None  # ragged tail, budgeted
    assert s.observe(jnp.zeros((6,))) is not None


def test_retrace_steady_state_never_flags():
    s = analysis.RetraceSentinel()
    for _ in range(10):
        assert s.observe({"w": jnp.zeros((4, 4))}, jnp.zeros((4,))) is None
    assert s.retraces == 0 and s.calls == 10


# ---------------------------------------------------------------------------
# collective consistency
# ---------------------------------------------------------------------------

_AR_HLO = """
ENTRY %main {
  %p0 = f32[8,128]{1,0} parameter(0)
  %ar = f32[8,128]{1,0} all-reduce(%p0), replica_groups={{0,1}}
  ROOT %out = f32[8,128]{1,0} add(%ar, %ar)
}
"""


def test_planted_wrong_collective_count_is_caught():
    report = analysis.lint_hlo(
        _AR_HLO, expect_collectives={"all-reduce": 2}
    )
    assert report.rule_ids() == ["collective-count"]


def test_collective_dtype_and_bytes_checks():
    report = analysis.lint_hlo(
        _AR_HLO,
        expect_collectives={
            "all-reduce": {"count": 1, "dtypes": ["s8"], "bytes": 17}
        },
    )
    assert report.rule_ids() == ["collective-bytes", "collective-dtype"]
    clean = analysis.lint_hlo(
        _AR_HLO,
        expect_collectives={
            "all-reduce": {
                "count": 1, "dtypes": ["f32"], "bytes": 8 * 128 * 4,
            }
        },
    )
    assert clean.findings == []


def test_collective_count_live_on_mesh(eight_devices):
    """End to end on a real compiled program: one psum over the
    8-device mesh must be exactly one all-reduce."""
    mesh = Mesh(eight_devices, ("dp",))

    def step(x):
        return jax.lax.psum(x, "dp")

    fn = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
            check_vma=False,
        )
    )
    x = jnp.zeros((8, 16), jnp.float32)
    ok = analysis.check(fn, x, expect_collectives={"all-reduce": 1})
    assert ok.findings == []
    bad = analysis.check(fn, x, expect_collectives={"all-reduce": 3})
    assert bad.rule_ids() == ["collective-count"]


# ---------------------------------------------------------------------------
# host-transfer HLO scan
# ---------------------------------------------------------------------------


def test_host_transfer_ops_scan():
    hlo = """
ENTRY %main {
  %tok = token[] after-all()
  %in = ((f32[8]{0}), token[]) infeed(%tok)
  %cc = () custom-call(s64[] %c, f32[8]{0} %x), custom_call_target="xla_python_cpu_callback", api_version=API_VERSION_STATUS_RETURNING
  %send = (f32[8]{0}, u32[], token[]) send(%x, %tok), channel_id=1, is_host_transfer=true
  %benign = f32[8]{0} custom-call(%x), custom_call_target="Sharding"
}
"""
    found = hlo_lib.host_transfer_ops(hlo)
    kinds = sorted(why for _name, why in found)
    assert len(found) == 3
    assert kinds[0] == "callback custom-call (xla_python_cpu_callback)"
    assert "host send/recv" in kinds
    assert "infeed" in kinds


# ---------------------------------------------------------------------------
# the clean-step fixture: a full guarded train step with zero findings
# ---------------------------------------------------------------------------


def test_clean_step_produces_zero_findings():
    """A well-formed train step — donated state, policy-conformant
    dtypes, no callbacks — must come back clean on every pass."""
    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state, batch)
        return (
            {k: state[k] - 0.1 * grads[k] for k in state},
            loss,
        )

    state = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    batch = (jnp.ones((16, 8)), jnp.ones((16, 4)))
    report = analysis.check(
        step, state, batch,
        policy=jnp.float32, donate_argnums=(0,),
        name="clean_step",
    )
    assert report.findings == [], report.render()
    assert report.ok() and report.ok(fail_on="warning")


# ---------------------------------------------------------------------------
# report plumbing: JSON schema, catalog integrity, board publishing
# ---------------------------------------------------------------------------


def test_every_rule_is_cataloged_and_catalog_is_complete():
    assert set(analysis.RULES) == {
        "transfer-callback", "transfer-hlo-host",
        "promotion-f64", "promotion-widen",
        "donation-dropped", "retrace",
        "collective-count", "collective-bytes", "collective-dtype",
        "sharding-replicated", "sharding-mismatch",
        "sharding-unverified", "reshard-unplanned", "reshard-plan",
        "memory-budget", "sharding-implicit-replication",
        "sharding-missing-constraint",
        "kernel-vmem-overflow", "kernel-tile-misaligned",
        "kernel-grid-oob", "kernel-block-race", "kernel-dead-tiles",
        "kernel-hardcoded-block",
        "race-unlocked-shared-state", "race-nonatomic-counter",
        "race-lock-across-blocking",
        "replay-wall-clock", "replay-unseeded-rng",
        "replay-set-order", "replay-env-read",
    }
    for rule, (sev, desc, hint) in analysis.RULES.items():
        assert sev in (analysis.ERROR, analysis.WARNING, analysis.INFO)
        assert desc and hint
    with pytest.raises(KeyError):
        analysis.make_finding("not-a-rule", path="", message="")


def test_report_json_roundtrip_and_severity_gate():
    f1 = analysis.make_finding("promotion-widen", path="p", message="m")
    f2 = analysis.make_finding("donation-dropped", path="q", message="n")
    report = analysis.Report([f1, f2], target="t", rules_run=("promotion",))
    blob = json.loads(report.to_json_line())
    assert blob["target"] == "t"
    assert blob["errors"] == 1 and blob["warnings"] == 1
    assert blob["findings"][0]["rule"] == "promotion-widen"
    assert not report.ok()  # one error
    warn_only = analysis.Report([f1])
    assert warn_only.ok()  # warnings pass the default gate
    assert not warn_only.ok(fail_on="warning")


def test_publish_report_rides_the_board():
    from apex_tpu.observability.metrics import board

    board.clear()
    report = analysis.Report(
        [analysis.make_finding("retrace", path="", message="x")],
        target="pub",
    )
    analysis.publish_report(report)
    snap = board.snapshot()
    assert snap["analysis/errors"] == 1
    assert snap["analysis/warnings"] == 0
    assert snap["analysis/rule/retrace"] == 1
    board.clear()


def test_unknown_rule_selector_raises():
    with pytest.raises(ValueError):
        analysis.check(lambda x: x, jnp.zeros(()), rules=("bogus",))


# ---------------------------------------------------------------------------
# the lint passes on our own codebase (ISSUE 4 satellite: contrib/ops)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["layer_norm", "softmax", "xentropy",
                                  "focal_loss", "group_norm"])
def test_own_ops_are_promotion_clean_under_bf16(name):
    """The promotion lint must pass on our own fused ops and contrib
    stubs: their f32 accumulation regions are marked policy-exempt
    (named scopes), so a bf16 policy sees zero findings."""
    from apex_tpu import ops
    from apex_tpu.contrib.focal_loss import sigmoid_focal_loss
    from apex_tpu.contrib.group_norm import group_norm

    bf = jnp.bfloat16
    x = jnp.ones((4, 64), bf)
    builders = {
        "layer_norm": lambda: jax.make_jaxpr(
            lambda x: jax.grad(
                lambda xx: ops.fused_layer_norm_affine(
                    xx, jnp.ones((64,), bf), jnp.zeros((64,), bf), 64
                ).sum()
            )(x).sum()
        )(x),
        "softmax": lambda: jax.make_jaxpr(
            lambda s: jax.grad(
                lambda ss: ops.scaled_masked_softmax(
                    ss, ss > 2, 2.0
                ).sum()
            )(s).sum()
        )(jnp.ones((2, 2, 8, 8), bf)),
        "xentropy": lambda: jax.make_jaxpr(
            lambda l: jax.grad(
                lambda ll: ops.softmax_cross_entropy_loss(
                    ll, jnp.zeros((8,), jnp.int32)
                ).sum()
            )(l).sum()
        )(jnp.ones((8, 32), bf)),
        "focal_loss": lambda: jax.make_jaxpr(
            lambda l: sigmoid_focal_loss(l, jnp.zeros((4, 10), bf)).sum()
        )(jnp.ones((4, 10), bf)),
        "group_norm": lambda: jax.make_jaxpr(
            lambda x: group_norm(x.reshape(4, 8, 8), 4).sum()
        )(x),
    }
    report = analysis.lint_jaxpr(
        builders[name](), policy=bf, name=f"ops/{name}"
    )
    assert report.findings == [], report.render()


# ---------------------------------------------------------------------------
# sharding & memory passes (ISSUE 9): rule tables, spec conformance,
# resharding plan, static peak-HBM budget
# ---------------------------------------------------------------------------

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding  # noqa: E402

from apex_tpu.analysis import memory as memory_lib  # noqa: E402
from apex_tpu.analysis import sharding as sharding_lib  # noqa: E402


def _dp_tp_mesh(eight_devices):
    return Mesh(np.array(eight_devices[:4]).reshape(2, 2), ("dp", "tp"))


_DPTP = {"dp": 2, "tp": 2}


class TestRuleTables:
    def test_match_partition_rules_first_match_and_scalar_exempt(self):
        rules = [(r"\bw$", P(None, "tp")), (r".*", P())]
        params = {
            "w": jnp.zeros((8, 8)),
            "b": jnp.zeros((8,)),
            "count": jnp.zeros(()),  # scalar: never partitioned
        }
        specs = analysis.match_partition_rules(rules, params)
        assert specs["w"] == P(None, "tp")
        assert specs["b"] == P()
        assert specs["count"] == P()

    def test_match_partition_rules_hole_raises(self):
        with pytest.raises(ValueError, match="partition rule not found"):
            analysis.match_partition_rules(
                [(r"\bw$", P())], {"other": jnp.zeros((4, 4))}
            )

    def test_normalize_param_path_matches_tree_paths(self):
        """ONE rule table serves the live pytree and the compiled
        module: HLO op_name metadata normalizes to the same /-joined
        path tree_paths produces."""
        assert sharding_lib.normalize_param_path(
            "state[\\'params\\'][\\'w\\']"
        ) == "state/params/w"
        assert sharding_lib.normalize_param_path("batch[0]") == "batch/0"
        assert sharding_lib.normalize_param_path(
            "scaler_state.loss_scale"
        ) == "scaler_state/loss_scale"
        paths = [p for p, _l in sharding_lib.tree_paths(
            {"state": {"params": {"w": jnp.zeros((2,))}}}
        )]
        assert paths == ["state/params/w"]

    def test_parse_sharding_variants(self):
        ps_ = hlo_lib.parse_sharding
        assert ps_("replicated")["kind"] == "replicated"
        assert ps_("maximal device=3")["kind"] == "maximal"
        assert ps_("devices=[2,4]<=[8]") == {
            "kind": "tiled", "dims": [2, 4]}
        assert ps_(
            "devices=[1,4,2]<=[2,4]T(1,0) last_tile_dim_replicate"
        ) == {"kind": "tiled", "dims": [1, 4]}
        # tiled-in-name-only = replicated
        assert ps_(
            "devices=[1,1,8]<=[8] last_tile_dim_replicate"
        )["kind"] == "replicated"
        assert ps_(None)["kind"] == "unknown"

    def test_mesh_axis_groups_row_major(self):
        groups = sharding_lib.mesh_axis_groups({"dp": 2, "tp": 4})
        assert groups["tp"] == frozenset([
            frozenset({0, 1, 2, 3}), frozenset({4, 5, 6, 7})])
        assert groups["dp"] == frozenset([
            frozenset({0, 4}), frozenset({1, 5}),
            frozenset({2, 6}), frozenset({3, 7})])
        assert groups["all"] == frozenset([frozenset(range(8))])

    def test_iota_replica_groups_disambiguate_equal_axes(self):
        """XLA's compact iota form must still attribute axes EXACTLY
        at dp=tp=2, where group size alone is ambiguous: the minor
        (tp) axis prints untransposed rows, the major (dp) axis a
        T(1,0) iota — both must resolve, never fall back to None."""
        mesh = {"dp": 2, "tp": 2}
        groups = sharding_lib.mesh_axis_groups(mesh)

        def _coll(line):
            recs = hlo_lib.collective_instructions(
                "ENTRY %main {\n  " + line + "\n}"
            )
            assert len(recs) == 1
            return recs[0]

        tp = _coll("%ar = f32[8]{0} all-reduce(f32[8]{0} %x), "
                   "replica_groups=[2,2]<=[4], to_apply=%add")
        assert tp["groups"] == [[0, 1], [2, 3]]
        assert sharding_lib.infer_collective_axis(
            tp, groups, mesh) == "tp"
        dp = _coll("%ar = f32[8]{0} all-reduce(f32[8]{0} %x), "
                   "replica_groups=[2,2]<=[2,2]T(1,0), to_apply=%add")
        assert dp["groups"] == [[0, 2], [1, 3]]
        assert sharding_lib.infer_collective_axis(
            dp, groups, mesh) == "dp"
        allg = _coll("%ar = f32[8]{0} all-reduce(f32[8]{0} %x), "
                     "replica_groups=[1,4]<=[4], to_apply=%add")
        assert sharding_lib.infer_collective_axis(
            allg, groups, mesh) == "all"


class TestShardingConformance:
    RULES = [(r"\bw$", P(None, "tp")), (r"\bb$", P()), (r"^x", P("dp", None))]

    def _step(self):
        def step(params, x):
            return jnp.tanh(x @ params["w"] + params["b"]).sum()
        params = {
            "w": jnp.zeros((64, 64), jnp.float32),
            "b": jnp.zeros((64,), jnp.float32),
        }
        return step, params, jnp.zeros((8, 64), jnp.float32)

    def test_planted_replicated_large_param_is_caught(self, eight_devices):
        """The headline defect: the plan shards w over tp but the call
        site replicates it — silent full replication is an ERROR."""
        mesh = _dp_tp_mesh(eight_devices)
        step, params, x = self._step()
        fn = jax.jit(step, in_shardings=(
            NamedSharding(mesh, P()), NamedSharding(mesh, P("dp", None))))
        report = analysis.check(
            fn, params, x,
            expect_sharding={
                "mesh": _DPTP, "rules": self.RULES, "min_bytes": 1 << 10,
            },
            rules=("sharding",),
        )
        assert report.rule_ids() == ["sharding-replicated"]
        assert not report.ok()
        assert "params/w" in report.findings[0].path

    def test_planted_wrong_axis_is_mismatch(self, eight_devices):
        mesh = _dp_tp_mesh(eight_devices)
        step, params, x = self._step()
        wrong = {"w": NamedSharding(mesh, P("tp", None)),  # transposed
                 "b": NamedSharding(mesh, P())}
        fn = jax.jit(step, in_shardings=(
            wrong, NamedSharding(mesh, P("dp", None))))
        report = analysis.check(
            fn, params, x,
            expect_sharding={
                "mesh": _DPTP, "rules": self.RULES, "min_bytes": 1 << 10,
            },
            rules=("sharding",),
        )
        assert report.rule_ids() == ["sharding-mismatch"]

    def test_clean_conformant_step(self, eight_devices):
        mesh = _dp_tp_mesh(eight_devices)
        step, params, x = self._step()
        good = {"w": NamedSharding(mesh, P(None, "tp")),
                "b": NamedSharding(mesh, P())}
        fn = jax.jit(step, in_shardings=(
            good, NamedSharding(mesh, P("dp", None))))
        report = analysis.check(
            fn, params, x,
            expect_sharding={
                "mesh": _DPTP, "rules": self.RULES, "min_bytes": 1 << 10,
            },
            rules=("sharding",),
        )
        assert report.findings == [], report.render()

    def test_single_device_compile_is_unverified_not_clean(self):
        """A plan naming a real mesh checked against a 1-partition
        compile must WARN, not pass — nobody proved anything."""
        step, params, x = self._step()
        report = analysis.check(
            jax.jit(step), params, x,
            expect_sharding={
                "mesh": _DPTP, "rules": self.RULES, "min_bytes": 1 << 10,
            },
            rules=("sharding",),
        )
        assert report.rule_ids() == ["sharding-unverified"]
        assert report.ok()  # warning severity: visible, not fatal
        assert not report.ok(fail_on="warning")


class TestReshardPlan:
    def test_planted_unplanned_weight_all_gather(self, eight_devices):
        """The signature of a spec that didn't survive propagation:
        a weight all-gather the plan does not predict."""
        mesh = _dp_tp_mesh(eight_devices)

        def bad(w, x):
            wfull = jax.lax.all_gather(w, "tp", axis=0, tiled=True)
            y = jnp.einsum("bk,kn->bn", x, wfull)
            return jax.lax.psum(y, "tp")

        fn = jax.jit(jax.shard_map(
            bad, mesh=mesh,
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None), check_vma=False,
        ))
        plan = {"mesh": _DPTP, "collectives": [
            {"kind": "all-reduce", "axis": "tp", "dtypes": ["f32"]},
        ]}
        report = analysis.check(
            fn, jnp.zeros((64, 32)), jnp.zeros((8, 64)),
            expect_plan=plan, rules=("reshard",),
        )
        assert report.rule_ids() == ["reshard-unplanned"]
        f = report.findings[0]
        assert "all-gather" in f.path and "tp" in f.path

    def test_planted_wire_drift(self, eight_devices):
        """A plan promising an int8 wire must fail when the compiled
        payload is f32 — the quantization didn't apply."""
        mesh = _dp_tp_mesh(eight_devices)

        def step(w, x):
            return jax.lax.psum(jnp.einsum("bk,kn->bn", x, w), "tp")

        fn = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None), check_vma=False,
        ))
        plan = {"mesh": _DPTP, "collectives": [
            {"kind": "all-reduce", "axis": "tp", "dtypes": ["s8"]},
        ]}
        report = analysis.check(
            fn, jnp.zeros((64, 32)), jnp.zeros((8, 32)),
            expect_plan=plan, rules=("reshard",),
        )
        assert report.rule_ids() == ["reshard-plan"]

    def test_ddp_declared_plan_matches_compiled(self, eight_devices):
        """The engine's OWN declaration (collective_plan) verifies the
        engine's OWN compiled sync — the live 8-device check beside
        the existing collective one, for f32 and the int8 wire."""
        from apex_tpu import parallel_state as ps
        from apex_tpu.parallel import DistributedDataParallel

        mesh = ps.initialize_model_parallel()
        world = ps.get_data_parallel_world_size()
        params = {"w": jnp.zeros((64, 64), jnp.float32),
                  "b": jnp.zeros((8,), jnp.float32)}
        batch = (jnp.ones((16, 64)), jnp.ones((16, 64)))
        for wire in ("f32", "int8"):
            ddp = DistributedDataParallel(
                lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
                wire=wire,
            )
            fn = jax.jit(jax.shard_map(
                lambda p, b: ddp.value_and_grad(p, b), mesh=mesh,
                in_specs=(P(), P("dp")), out_specs=(P(), P()),
            ))
            plan = ddp.collective_plan(params, world)
            report = analysis.check(
                fn, params, batch, expect_plan=plan,
                rules=("reshard",), name=f"ddp/{wire}",
            )
            assert report.findings == [], (wire, report.render())
            if wire == "int8":
                kinds = {e["kind"] for e in plan["collectives"]}
                assert kinds == {"all-to-all", "all-gather", "all-reduce"}

    def test_zero_declared_plan_matches_compiled(self, eight_devices):
        """The ZeRO optimizer's own declaration verifies its own
        compiled step: int8 grad reduce-scatter (all-to-all on the
        wire), f32 param all-gather.  (A bf16 param_wire is exactly
        what the pass is FOR on the CPU backend: XLA legally hoists
        the decode before the gather there, doubling wire bytes —
        reshard-plan fires — so the clean pin uses wires that hold.)"""
        from apex_tpu import parallel_state as ps
        from apex_tpu.parallel import DistributedFusedAdam

        mesh = ps.initialize_model_parallel()
        world = ps.get_data_parallel_world_size()
        params = {"w": jnp.zeros((64, 64), jnp.float32),
                  "b": jnp.zeros((8,), jnp.float32)}
        batch = (jnp.ones((16, 64)), jnp.ones((16, 64)))
        tx = DistributedFusedAdam(wire="int8", param_wire="f32")
        state = tx.init(params, world)
        step = tx.make_train_step(
            lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2), mesh
        )
        plan = tx.collective_plan()
        report = analysis.check(
            step, params, state, batch, expect_plan=plan,
            rules=("reshard",), name="zero/int8",
        )
        assert report.findings == [], report.render()


class TestMemoryBudget:
    _HLO = """
HloModule jit_f, is_scheduled=true

ENTRY %main (p0: f32[256,64], p1: f32[64,64]) -> f32[256,64] {
  %p0 = f32[256,64]{1,0} parameter(0), metadata={op_name="state[\\'params\\'][\\'w\\']"}
  %p1 = f32[64,64]{1,0} parameter(1), metadata={op_name="state[\\'opt\\'].m[\\'w\\']"}
  %dot = f32[256,64]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %exp = f32[256,64]{1,0} exponential(f32[256,64]{1,0} %dot)
  ROOT %add = f32[256,64]{1,0} add(f32[256,64]{1,0} %exp, f32[256,64]{1,0} %p0)
}
"""

    def test_estimate_peak_on_fixture(self):
        """Hand-checkable live ranges: p1 dies feeding %dot, p0 lives
        to the ROOT (its last use), %dot dies feeding %exp — the peak
        is p0 + two activations at instruction 3/4."""
        big = 256 * 64 * 4  # p0 / dot / exp / add are 64 KiB each
        est = memory_lib.estimate_peak(self._HLO)
        assert est["peak_bytes"] == 3 * big
        cats = est["by_category"]
        assert cats["params"] == big          # p0, alive at the peak
        assert cats["activations"] == 2 * big
        assert "optimizer" not in cats        # p1 died at %dot
        names = [b["name"] for b in est["buffers"]]
        assert "p0" in names
        # the arg-path classifier puts optimizer state in its bucket
        assert memory_lib.categorize_buffer(
            "parameter", "state['opt'].m['w']"
        ) == "optimizer"
        assert memory_lib.categorize_buffer(
            "parameter", "kv_pages"
        ) == "kv_cache"

    def test_planted_budget_overflow_is_caught(self):
        report = analysis.lint_hlo(
            self._HLO, hbm_budget=100_000, rules=("memory",)
        )
        assert report.rule_ids() == ["memory-budget"]
        f = report.findings[0]
        assert "params:p0" in f.message  # top-buffer attribution
        clean = analysis.lint_hlo(
            self._HLO, hbm_budget=10 << 20, rules=("memory",)
        )
        assert clean.findings == []

    def test_live_budget_overflow_on_compiled_step(self):
        def step(x):
            return (x @ x.T).sum()

        report = analysis.check(
            step, jnp.zeros((128, 128), jnp.float32), hbm_budget=1024,
            rules=("memory",),
        )
        assert report.rule_ids() == ["memory-budget"]

    def test_memory_budget_watchdog_rule(self):
        from apex_tpu.observability import MemoryBudgetRule
        from apex_tpu.observability.metrics import board

        board.clear()
        rule = MemoryBudgetRule(budget_bytes=1000)
        assert rule.evaluate(None, 0) == []  # no estimate published
        memory_lib.publish_peak(
            {"peak_bytes": 950, "by_category": {"params": 950}}
        )
        (warn,) = rule.evaluate(None, 1)
        assert warn.severity == "warn"
        memory_lib.publish_peak({"peak_bytes": 2000, "by_category": {}})
        (crit,) = rule.evaluate(None, 2)
        assert crit.severity == "critical"
        assert board.get("analysis/peak_hbm_bytes") == 2000
        with pytest.raises(ValueError):
            MemoryBudgetRule(budget_bytes=0)
        board.clear()


class TestCleanDpTpStep:
    def test_clean_dp_tp_step_proves_whole_plan(self, eight_devices):
        """The acceptance fixture: a dp=2 x tp=2 step with declared
        rule table, collective plan, and budget — every sharding/
        memory pass runs and the clean step yields ZERO findings."""
        mesh = _dp_tp_mesh(eight_devices)
        B, K, N = 8, 32, 16
        rules = [(r"\bw$", P("tp", None)), (r"\bx$", P("dp", "tp"))]

        def step(w, x):
            y = jax.lax.psum(jnp.einsum("bk,kn->bn", x, w), "tp")
            return jax.lax.pmean(jnp.mean(y * y), ("dp", "tp"))

        fn = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("tp", None), P("dp", "tp")),
            out_specs=P(), check_vma=False,
        ))
        plan = {"mesh": _DPTP, "collectives": [
            {"kind": "all-reduce", "axis": "tp", "count": 1,
             "bytes": [0, (B // 2) * N * 4 + 64], "dtypes": ["f32"]},
        ]}
        report = analysis.check(
            fn, jnp.zeros((K, N), jnp.float32),
            jnp.zeros((B, K), jnp.float32),
            expect_sharding={
                "mesh": _DPTP, "rules": rules, "min_bytes": 0,
            },
            expect_plan=plan,
            hbm_budget=10 << 20,
        )
        assert report.findings == [], report.render()
        for name in ("sharding", "reshard", "memory"):
            assert name in report.rules_run
            assert name in report.pass_timings


# ---------------------------------------------------------------------------
# report plumbing for the new passes: dedupe, timings, merge, sections
# ---------------------------------------------------------------------------


def test_publish_report_dedupes_same_rule_and_location():
    """Two passes emitting the same (rule, location) — e.g. the jaxpr
    and HLO substrates of one defect — must gauge ONE defect onto the
    board (the ISSUE 9 bugfix), while the report keeps both raw
    findings for rendering."""
    from apex_tpu.observability.metrics import board

    board.clear()
    dup1 = analysis.make_finding("retrace", path="site_a", message="m1")
    dup2 = analysis.make_finding("retrace", path="site_a", message="m2")
    other = analysis.make_finding("retrace", path="site_b", message="m3")
    report = analysis.Report([dup1, dup2, other], target="dedupe")
    report.pass_timings["retrace"] = 1.25
    analysis.publish_report(report)
    snap = board.snapshot()
    assert snap["analysis/rule/retrace"] == 2  # a+b, not 3
    assert snap["analysis/errors"] == 2
    assert snap["analysis/pass_ms/retrace"] == 1.25
    assert len(report.findings) == 3  # raw findings untouched
    board.clear()


def test_pass_timings_cover_rules_run_and_survive_to_json():
    report = analysis.check(lambda x: x * 2.0, jnp.zeros((4,)))
    assert set(report.pass_timings) == set(report.rules_run)
    assert all(ms >= 0.0 for ms in report.pass_timings.values())
    blob = json.loads(report.to_json_line())
    assert set(blob["pass_timings"]) == set(report.rules_run)


def test_report_merge_sums_timings_and_unions_rules():
    a = analysis.Report(target="a", rules_run=("transfer",))
    a.pass_timings = {"transfer": 1.0}
    b = analysis.Report(
        [analysis.make_finding("retrace", path="p", message="m")],
        target="b", rules_run=("transfer", "memory"),
    )
    b.pass_timings = {"transfer": 2.0, "memory": 0.5}
    a.merge(b)
    assert a.pass_timings == {"transfer": 3.0, "memory": 0.5}
    assert a.rules_run == ("transfer", "memory")
    assert len(a.findings) == 1


def test_attach_shard_sections_rides_to_json():
    hlo = TestMemoryBudget._HLO
    report = analysis.lint_hlo(hlo, rules=("memory",), name="fixture")
    analysis.attach_shard_sections(
        report, [("fixture", hlo)], publish=True
    )
    blob = report.to_json()
    assert blob["peak_hbm_bytes"] > 0
    assert blob["peak_hbm_by_program"] == {
        "fixture": blob["peak_hbm_bytes"]}
    assert {r["name"] for r in blob["shard_plan"]} == {
        "state/params/w", "state/opt/m/w"}
    from apex_tpu.observability.metrics import board

    assert board.get("analysis/peak_hbm_bytes") == blob["peak_hbm_bytes"]
    board.clear()


# ---------------------------------------------------------------------------
# repo_lint source rules (the satellite): in_shardings=None, missing
# with_sharding_constraint
# ---------------------------------------------------------------------------


def test_repo_lint_sharding_source_rules():
    from tools import repo_lint

    implicit = [
        "def build(step):",
        "    return pjit(step, in_shardings=None, out_shardings=None)",
    ]
    got = repo_lint._sharding_violations("x/m.py", implicit, jitted=True)
    assert len(got) == 1 and got[0][1] == 2
    assert "replicated" in got[0][3]

    unpinned = [
        "y = jnp.einsum('bk,kn->bn', x, w)",
        "fn = shard_map(step, mesh=mesh, in_specs=specs)",
    ]
    got = repo_lint._sharding_violations("x/m.py", unpinned, jitted=True)
    assert len(got) == 1 and "with_sharding_constraint" in got[0][4]

    # pinning ANY intermediate waives the call-site rule
    pinned = unpinned + [
        "y = jax.lax.with_sharding_constraint(y, spec)",
    ]
    assert repo_lint._sharding_violations("x/m.py", pinned, True) == []
    # host-side files are out of scope
    assert repo_lint._sharding_violations(
        "x/m.py", implicit + unpinned, jitted=False
    ) == []
    # the waiver comment works like every other repo_lint rule
    waived = [
        "fn = pjit(step, in_shardings=None)  # repo-lint: allow tests",
    ]
    assert repo_lint._sharding_violations("x/m.py", waived, True) == []


def test_bench_shard_lint_line_passes_schema():
    """The `graph_lint_shard_errors` line bench.py --lint emits rides
    the standard bench-record contract tools/bench_diff.py enforces."""
    from tools import bench_diff

    rec = {
        "metric": "graph_lint_shard_errors",
        "value": 0.0,
        "unit": "sharding/reshard/memory ERROR findings (bert_lamb "
                "step; peak_hbm=123.4MiB; docs/analysis.md)",
        "vs_baseline": None,
    }
    assert bench_diff.check_schema([rec]) == []
