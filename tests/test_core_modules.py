"""Tests for multi_tensor_apply shim, MLP, FusedDense, RNN, weight norm.

Mirrors the reference's pattern (SURVEY §4): golden = the unfused
composition of the same math (reference tests ``run_mlp/``,
``run_fused_dense/``; torch.nn reference for RNN cells).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import (
    MultiTensorApply,
    flatten,
    multi_tensor_applier,
    unflatten,
)


class TestMultiTensorApply:
    def test_flatten_roundtrip(self):
        ts = [jnp.arange(6.0).reshape(2, 3), jnp.ones((4,)), jnp.zeros((2, 2))]
        flat = flatten(ts)
        assert flat.shape == (14,)
        back = unflatten(flat, ts)
        for a, b in zip(ts, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_flatten_dtype_mismatch(self):
        with pytest.raises(ValueError):
            flatten([jnp.ones((2,), jnp.float32), jnp.ones((2,), jnp.bfloat16)])

    def test_applier_shim(self):
        applier = MultiTensorApply(2048 * 32)

        def op(xs, ys, alpha):
            return [x + alpha * y for x, y in zip(xs, ys)]

        xs = [jnp.ones((3,)), jnp.zeros((2,))]
        ys = [jnp.ones((3,)), jnp.ones((2,))]
        out = applier(op, None, [xs, ys], 2.0)
        np.testing.assert_allclose(np.asarray(out[0]), 3.0)
        np.testing.assert_allclose(np.asarray(out[1]), 2.0)
        assert multi_tensor_applier.chunk_size == 2048 * 32


class TestMLP:
    @pytest.mark.parametrize("activation", ["relu", "sigmoid", "none"])
    @pytest.mark.parametrize("bias", [True, False])
    def test_vs_unfused(self, activation, bias):
        from apex_tpu.mlp import MLP

        sizes = (16, 32, 8)
        m = MLP(mlp_sizes=sizes, bias=bias, activation=activation)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
        params = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(params, x)
        assert y.shape == (4, 8)

        # unfused reference composition
        p = params["params"]
        h = x @ p["kernel_0"]
        if bias:
            h = h + p["bias_0"]
        act = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "none": lambda v: v}[
            activation
        ]
        h = act(h)
        ref = h @ p["kernel_1"]
        if bias:
            ref = ref + p["bias_1"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_bad_sizes(self):
        from apex_tpu.mlp import MLP

        x = jnp.ones((2, 7))
        with pytest.raises(ValueError):
            MLP(mlp_sizes=(16, 8)).init(jax.random.PRNGKey(0), x)


class TestFusedDense:
    def test_dense_vs_unfused(self):
        from apex_tpu.fused_dense import FusedDense

        m = FusedDense(in_features=12, out_features=20)
        x = jax.random.normal(jax.random.PRNGKey(0), (5, 12))
        params = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(params, x)
        p = params["params"]
        ref = x @ p["kernel"] + p["bias"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_gelu_dense_vs_unfused(self):
        from apex_tpu.fused_dense import FusedDenseGeluDense

        m = FusedDenseGeluDense(in_features=8, intermediate_features=32, out_features=8)
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 8))
        params = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(params, x)
        p = params["params"]
        h = jax.nn.gelu(x @ p["kernel_1"] + p["bias_1"], approximate=True)
        ref = h @ p["kernel_2"] + p["bias_2"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_bf16_io(self):
        from apex_tpu.fused_dense import FusedDense

        m = FusedDense(in_features=4, out_features=4, dtype=jnp.bfloat16)
        x = jnp.ones((2, 4), jnp.bfloat16)
        params = m.init(jax.random.PRNGKey(0), x)
        assert m.apply(params, x).dtype == jnp.bfloat16


def _torch_lstm_reference(x, params, hidden_size):
    """Pure-numpy LSTM replaying our gate order (i,f,g,o) for one layer."""
    T, B, _ = x.shape
    w_ih, w_hh, b_ih = params["w_ih_0"], params["w_hh_0"], params["b_ih_0"]
    h = np.zeros((B, hidden_size), np.float32)
    c = np.zeros((B, hidden_size), np.float32)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    outs = []
    for t in range(T):
        gates = x[t] @ w_ih + b_ih + h @ w_hh
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs)


class TestRNN:
    def test_lstm_vs_loop_reference(self):
        from apex_tpu.RNN import LSTM

        m = LSTM(input_size=6, hidden_size=10)
        x = jax.random.normal(jax.random.PRNGKey(0), (5, 3, 6))
        params = m.init(jax.random.PRNGKey(1), x)
        out, (h, c) = m.apply(params, x)
        assert out.shape == (5, 3, 10)
        assert h.shape == (1, 3, 10)
        np_params = {k: np.asarray(v) for k, v in params["params"].items()}
        ref = _torch_lstm_reference(np.asarray(x), np_params, 10)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("cls_name", ["RNNReLU", "RNNTanh", "GRU", "mLSTM"])
    def test_shapes_and_grad(self, cls_name):
        import apex_tpu.RNN as R

        m = getattr(R, cls_name)(input_size=4, hidden_size=8, num_layers=2)
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 2, 4))
        params = m.init(jax.random.PRNGKey(1), x)
        out, state = m.apply(params, x)
        assert out.shape == (3, 2, 8)

        def loss(p):
            o, _ = m.apply(p, x)
            return jnp.sum(o**2)

        grads = jax.grad(loss)(params)
        gnorm = sum(
            float(jnp.sum(jnp.abs(g))) for g in jax.tree_util.tree_leaves(grads)
        )
        assert np.isfinite(gnorm) and gnorm > 0


class TestWeightNorm:
    def test_checkpoint_transforms_roundtrip(self):
        from apex_tpu.reparameterization import apply_weight_norm, remove_weight_norm

        params = {
            "layer": {"kernel": np.asarray(
                jax.random.normal(jax.random.PRNGKey(0), (4, 6))
            ), "bias": np.zeros((6,), np.float32)}
        }
        params = jax.tree_util.tree_map(jnp.asarray, params)
        split = apply_weight_norm(params, dim=1)
        assert "kernel_g" in split["layer"] and "kernel_v" in split["layer"]
        merged = remove_weight_norm(split, dim=1)
        np.testing.assert_allclose(
            np.asarray(merged["layer"]["kernel"]),
            np.asarray(params["layer"]["kernel"]),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_wrapper_module(self):
        import flax.linen as nn

        from apex_tpu.reparameterization import WeightNorm

        m = WeightNorm(nn.Dense(features=6))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4))
        params = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(params, x)
        assert y.shape == (2, 6)
        # reparameterized kernel has unit norm per output unit scaled by g
        leaves = jax.tree_util.tree_leaves_with_path(params)
        names = {jax.tree_util.keystr(p) for p, _ in leaves}
        assert any("scale" in n for n in names), names


class TestReviewRegressions:
    def test_elman_activation_override_respected(self):
        from apex_tpu.RNN import RNNTanh

        m = RNNTanh(input_size=4, hidden_size=8, activation=jax.nn.relu)
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 2, 4)) * 10
        params = m.init(jax.random.PRNGKey(1), x)
        out, _ = m.apply(params, x)
        # relu output is non-negative and unbounded; tanh would be in (-1, 1)
        assert float(jnp.min(out)) >= 0.0
        assert float(jnp.max(out)) > 1.0 or float(jnp.max(out)) == 0.0

    def test_weight_norm_transforms_accept_numpy_and_frozen(self):
        import flax.core

        from apex_tpu.reparameterization import apply_weight_norm, remove_weight_norm

        tree = flax.core.freeze(
            {"layer": {"kernel": np.ones((4, 6), np.float32)}}
        )
        split = apply_weight_norm(tree)
        assert "kernel_g" in split["layer"]
        merged = remove_weight_norm(split)
        np.testing.assert_allclose(
            np.asarray(merged["layer"]["kernel"]), np.ones((4, 6)), rtol=1e-6
        )

    def test_to_wrapper_params_loads_plain_checkpoint(self):
        import flax.linen as nn

        from apex_tpu.reparameterization import WeightNorm, to_wrapper_params

        dense = nn.Dense(features=6)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4))
        plain = dense.init(jax.random.PRNGKey(1), x)
        y_plain = dense.apply(plain, x)

        wrapped = WeightNorm(dense)
        wn_params = to_wrapper_params(plain)
        y_wrapped = wrapped.apply(wn_params, x)
        # initial wrapped output must equal the plain layer's output
        np.testing.assert_allclose(
            np.asarray(y_wrapped), np.asarray(y_plain), rtol=1e-5, atol=1e-5
        )

    def test_autocast_varargs_shape(self):
        from apex_tpu._autocast_utils import _cast_if_autocast_enabled

        x, y = jnp.ones((2, 2)), jnp.arange(3)
        # no policy: identity (autocast disabled semantics)
        ox, oy = _cast_if_autocast_enabled(x, y)
        assert ox.dtype == jnp.float32 and oy.dtype == jnp.int32
        ox, oy = _cast_if_autocast_enabled(x, y, policy=jnp.bfloat16)
        assert ox.dtype == jnp.bfloat16 and oy.dtype == jnp.int32
