"""Evoformer pair-stack under DAP — ≙ the model-side surface of
``apex/contrib/openfold_triton`` (gated pair-biased attention, triangle
attention/multiplicative updates, dap.py sharding equivalence)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.contrib.openfold import (
    EvoformerPairBlock,
    GatedAttention,
    TriangleAttention,
    TriangleMultiplicativeUpdate,
)
from apex_tpu.ops import _dispatch


@pytest.fixture
def force_pallas():
    _dispatch.set_use_pallas(True)
    yield
    _dispatch.set_use_pallas(None)


def _pair(key, n=16, d=8):
    return jax.random.normal(key, (n, n, d))


def test_gated_attention_matches_manual_composition():
    """The module is exactly: sigmoid-gated attention with additive bias
    feeding a zero-init output projection (output zero at init ⇒
    residual-safe), with q/k/v bias-free — the openfold mha contract."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8))
    bias = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 8, 8))
    mod = GatedAttention(heads=2)
    params = mod.init(jax.random.PRNGKey(2), x, bias)
    # zero-init out projection: output must be exactly zero at init
    np.testing.assert_array_equal(
        np.asarray(mod.apply(params, x, bias)), 0.0
    )
    # with a non-trivial out kernel the composition must match manual math
    params = jax.tree.map(
        lambda p: jax.random.normal(jax.random.PRNGKey(3), p.shape) * 0.1,
        params,
    )
    got = mod.apply(params, x, bias)
    pr = params["params"]
    b, s, d = x.shape
    h, dh = 2, d // 2

    def split_heads(t):
        return t.reshape(b, s, h, dh).transpose(0, 2, 1, 3)

    q = split_heads(x @ pr["q"]["kernel"])
    k = split_heads(x @ pr["k"]["kernel"])
    v = split_heads(x @ pr["v"]["kernel"])
    s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(dh) + bias
    o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s_, axis=-1), v)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
    gate = jax.nn.sigmoid(x @ pr["gate"]["kernel"] + pr["gate"]["bias"])
    want = (gate * o) @ pr["out"]["kernel"] + pr["out"]["bias"]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_triangle_attention_bias_is_trainable(force_pallas):
    """The pair-derived triangle bias must receive gradient through the
    flash path's dedicated dbias kernel (bias_grad=True) — the capability
    the reference fuses in openfold_triton mha.py's backward."""
    z = _pair(jax.random.PRNGKey(0), n=8, d=8)
    mod = TriangleAttention(heads=2)
    params = mod.init(jax.random.PRNGKey(1), z)
    # break the zero-init symmetry so the loss actually depends on the
    # attention output (zero out-kernel would zero most grads)
    params = jax.tree.map(
        lambda p: jax.random.normal(jax.random.PRNGKey(2), p.shape) * 0.1,
        params,
    )

    def loss(p):
        return jnp.sum(mod.apply(p, z) ** 2)

    g = jax.grad(loss)(params)["params"]["tri_bias"]["kernel"]
    assert float(jnp.abs(g).max()) > 0.0

    # and the flash-path grads equal the jnp-path grads
    _dispatch.set_use_pallas(False)
    g_ref = jax.grad(loss)(params)["params"]["tri_bias"]["kernel"]
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("mode", ["outgoing", "incoming"])
def test_triangle_multiplicative_update_math(mode):
    """The contraction orientation: outgoing sums a[i,k]b[j,k], incoming
    sums a[k,i]b[k,j] (AF2 Algs 11/12)."""
    z = _pair(jax.random.PRNGKey(0), n=6, d=4)
    mod = TriangleMultiplicativeUpdate(mode=mode, hidden=4)
    params = mod.init(jax.random.PRNGKey(1), z)
    pr = params["params"]

    def ln(x, p):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]

    z_ln = ln(z, {k: pr[f"ln_in_{k}"] for k in ("scale", "bias")})

    def gated(name):
        p = z_ln @ pr[name]["kernel"] + pr[name]["bias"]
        g = jax.nn.sigmoid(
            z_ln @ pr[name + "_gate"]["kernel"] + pr[name + "_gate"]["bias"]
        )
        return g * p

    a, b = gated("a"), gated("b")
    x = (
        jnp.einsum("ikc,jkc->ijc", a, b)
        if mode == "outgoing"
        else jnp.einsum("kic,kjc->ijc", a, b)
    )
    x = ln(x, {k: pr[f"ln_out_{k}"] for k in ("scale", "bias")})
    x = x @ pr["out"]["kernel"] + pr["out"]["bias"]
    gate = jax.nn.sigmoid(
        z_ln @ pr["gate"]["kernel"] + pr["gate"]["bias"]
    )
    want = gate * x
    got = mod.apply(params, z)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def _randomize(params, key, scale=0.1):
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    return jax.tree.unflatten(
        treedef,
        [jax.random.normal(k, p.shape) * scale for k, p in zip(keys, leaves)],
    )


@pytest.mark.parametrize("mode", ["outgoing", "incoming"])
def test_triangle_multiplicative_update_dap_matches(eight_devices, mode):
    """DAP forms (outgoing: all-gather one operand; incoming: local einsum
    + psum_scatter) equal the unsharded contraction."""
    n, d, dap = 8, 4, 4
    z = _pair(jax.random.PRNGKey(0), n=n, d=d)
    ref = TriangleMultiplicativeUpdate(mode=mode, hidden=d)
    params = _randomize(ref.init(jax.random.PRNGKey(1), z), jax.random.PRNGKey(2))
    want = ref.apply(params, z)

    mesh = ps.initialize_model_parallel(devices=jax.devices()[:dap])
    sharded = TriangleMultiplicativeUpdate(
        mode=mode, hidden=d, axis_name="dp"
    )

    got = jax.jit(
        jax.shard_map(
            lambda zz: sharded.apply(params, zz),
            mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
            check_vma=False,
        )
    )(z)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_evoformer_pair_block_dap_matches_unsharded(eight_devices):
    """Full pair block (tri-mul out/in, tri-att start/end, transition):
    the 4-way DAP run must equal the unsharded golden — the reference
    dap.py equivalence contract, now over the whole openfold pair stack."""
    n, d, h, dap = 8, 8, 2, 4
    z = _pair(jax.random.PRNGKey(0), n=n, d=d)
    ref = EvoformerPairBlock(dim=d, heads=h)
    params = _randomize(ref.init(jax.random.PRNGKey(1), z), jax.random.PRNGKey(2))
    want = ref.apply(params, z)

    mesh = ps.initialize_model_parallel(devices=jax.devices()[:dap])
    sharded = EvoformerPairBlock(dim=d, heads=h, axis_name="dp")
    got = jax.jit(
        jax.shard_map(
            lambda zz: sharded.apply(params, zz),
            mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
            check_vma=False,
        )
    )(z)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_evoformer_pair_block_dap_grads_match(eight_devices):
    """Gradients through the DAP collectives (all_gather / psum_scatter /
    all_to_all) equal the unsharded gradients — the property that makes
    the sharded pair stack trainable, not just runnable."""
    n, d, h, dap = 8, 8, 2, 4
    z = _pair(jax.random.PRNGKey(0), n=n, d=d)
    ref = EvoformerPairBlock(dim=d, heads=h)
    params = _randomize(ref.init(jax.random.PRNGKey(1), z), jax.random.PRNGKey(2))

    g_ref = jax.grad(lambda p: jnp.sum(ref.apply(p, z) ** 2))(params)

    mesh = ps.initialize_model_parallel(devices=jax.devices()[:dap])
    sharded = EvoformerPairBlock(dim=d, heads=h, axis_name="dp")

    def sharded_loss(p, zz):
        # LOCAL loss term: its grad w.r.t. the replicated params is this
        # rank's contribution; the explicit psum below sums them into the
        # global gradient (the DDP contract).  Putting the psum on the
        # LOSS instead would scale grads by the axis size — psum's
        # transpose is psum, so each rank's unit cotangent becomes
        # world-many.
        return jnp.sum(sharded.apply(p, zz) ** 2)

    def grads(p, zz):
        g = jax.grad(sharded_loss)(p, zz)
        return jax.tree.map(lambda t: jax.lax.psum(t, "dp"), g)

    g_sh = jax.jit(
        jax.shard_map(
            grads, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False,
        )
    )(params, z)
    for path, a in jax.tree_util.tree_flatten_with_path(g_sh)[0]:
        b = g_ref
        for k in path:
            b = b[k.key]
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5,
            err_msg=str(path),
        )


def test_outer_product_mean_math():
    """o[i,j] = Linear(flatten(mean_s a[s,i] x b[s,j])) with zero-init
    output projection (residual-safe)."""
    from apex_tpu.contrib.openfold import OuterProductMean

    s, r, c = 4, 6, 8
    m = jax.random.normal(jax.random.PRNGKey(0), (s, r, c))
    mod = OuterProductMean(hidden=3)
    params = mod.init(jax.random.PRNGKey(1), m, 5)
    np.testing.assert_array_equal(np.asarray(mod.apply(params, m, 5)), 0.0)

    params = _randomize(params, jax.random.PRNGKey(2))
    got = mod.apply(params, m, 5)
    pr = params["params"]

    def ln(x, p):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]

    m_ln = ln(m, {k: pr[f"ln_{k}"] for k in ("scale", "bias")})
    a = m_ln @ pr["a"]["kernel"] + pr["a"]["bias"]
    b = m_ln @ pr["b"]["kernel"] + pr["b"]["bias"]
    o = jnp.einsum("sic,sjd->ijcd", a, b) / s
    o = o.reshape(r, r, 9)
    want = o @ pr["out"]["kernel"] + pr["out"]["bias"]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_evoformer_block_dap_matches_unsharded(eight_devices):
    """Full evoformer block (MSA row/col attention, transition, outer
    product mean, pair stack): 4-way DAP == unsharded, both reps."""
    from apex_tpu.contrib.openfold import EvoformerBlock

    s, r, cm, cz, h, dap = 8, 8, 8, 8, 2, 4
    m = jax.random.normal(jax.random.PRNGKey(0), (s, r, cm))
    z = jax.random.normal(jax.random.PRNGKey(1), (r, r, cz))
    ref = EvoformerBlock(msa_dim=cm, pair_dim=cz, heads=h)
    params = _randomize(
        ref.init(jax.random.PRNGKey(2), m, z), jax.random.PRNGKey(3)
    )
    want_m, want_z = ref.apply(params, m, z)

    mesh = ps.initialize_model_parallel(devices=jax.devices()[:dap])
    sh = EvoformerBlock(
        msa_dim=cm, pair_dim=cz, heads=h, axis_name="dp"
    )
    got_m, got_z = jax.jit(
        jax.shard_map(
            lambda mm, zz: sh.apply(params, mm, zz),
            mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")), check_vma=False,
        )
    )(m, z)
    np.testing.assert_allclose(
        np.asarray(got_m), np.asarray(want_m), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(got_z), np.asarray(want_z), rtol=2e-5, atol=2e-5
    )


def test_evoformer_block_dap_grads_match(eight_devices):
    """Gradients through the full MSA+pair block's DAP collectives
    (incl. the outer-product-mean psum_scatter) == unsharded."""
    from apex_tpu.contrib.openfold import EvoformerBlock

    s, r, cm, cz, h, dap = 8, 8, 8, 8, 2, 4
    m = jax.random.normal(jax.random.PRNGKey(0), (s, r, cm))
    z = jax.random.normal(jax.random.PRNGKey(1), (r, r, cz))
    ref = EvoformerBlock(msa_dim=cm, pair_dim=cz, heads=h)
    params = _randomize(
        ref.init(jax.random.PRNGKey(2), m, z), jax.random.PRNGKey(3)
    )

    def ref_loss(p):
        om, oz = ref.apply(p, m, z)
        return jnp.sum(om**2) + jnp.sum(oz**2)

    g_ref = jax.grad(ref_loss)(params)

    mesh = ps.initialize_model_parallel(devices=jax.devices()[:dap])
    sh = EvoformerBlock(
        msa_dim=cm, pair_dim=cz, heads=h, axis_name="dp"
    )

    def sharded_loss(p, mm, zz):
        om, oz = sh.apply(p, mm, zz)
        return jnp.sum(om**2) + jnp.sum(oz**2)

    def grads(p, mm, zz):
        g = jax.grad(sharded_loss)(p, mm, zz)
        return jax.tree.map(lambda t: jax.lax.psum(t, "dp"), g)

    g_sh = jax.jit(
        jax.shard_map(
            grads, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
            out_specs=P(), check_vma=False,
        )
    )(params, m, z)
    for path, a in jax.tree_util.tree_flatten_with_path(g_sh)[0]:
        b = g_ref
        for k in path:
            b = b[k.key]
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5,
            err_msg=str(path),
        )
