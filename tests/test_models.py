"""Model-level tests: BERT/GPT tp+sp invariance (≙ the reference's
standalone_gpt/standalone_bert pipeline smoke tests, test_gpt_minimal /
test_bert_minimal), ResNet forward, and the driver entry points."""

import importlib.util

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.models import (
    BertConfig,
    BertForPreTraining,
    GptConfig,
    GptModel,
    bert_pretrain_loss,
    gpt_lm_loss,
    resnet50,
)

BERT_KW = dict(
    vocab_size=128, hidden_size=64, num_layers=2, num_heads=8,
    intermediate_size=128, max_position_embeddings=64, dtype=jnp.float32,
)
S, B = 16, 2


def _bert_batch():
    ids = jax.random.randint(jax.random.PRNGKey(42), (S, B), 0, 128)
    return {
        "input_ids": ids,
        "attention_mask": jnp.ones((B, S), jnp.int32),
        "mlm_labels": jnp.where(ids % 5 == 0, ids, -1),
        "nsp_labels": jnp.zeros((B,), jnp.int32),
    }


def _pack_batch(batch, k):
    """Packed batch + the raw (positions, ids, weights) triple."""
    from apex_tpu.data import pack_mlm_predictions

    pos, ids, w = pack_mlm_predictions(batch["mlm_labels"], k)
    packed = dict(
        batch, mlm_positions=jnp.asarray(pos),
        mlm_label_ids=jnp.asarray(ids), mlm_weights=jnp.asarray(w),
    )
    return packed, (pos, ids, w)


def _sharded_bert_loss(sp, tp=8, packed=False):
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=tp)
    m = BertForPreTraining(BertConfig(sequence_parallel=sp, **BERT_KW))
    batch = _bert_batch()
    if packed:
        batch, _ = _pack_batch(batch, 8)

    def f(key, batch):
        params = m.init(key, batch["input_ids"])
        return bert_pretrain_loss(params, m, batch)

    return float(
        jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                check_vma=False,
            )
        )(jax.random.PRNGKey(0), batch)
    )


class TestBert:
    def test_unsharded_loss_and_grads(self):
        m = BertForPreTraining(BertConfig(**BERT_KW))
        batch = _bert_batch()
        params = m.init(jax.random.PRNGKey(0), batch["input_ids"])
        loss = bert_pretrain_loss(params, m, batch)
        grads = jax.grad(lambda p: bert_pretrain_loss(p, m, batch))(params)
        assert np.isfinite(float(loss))
        assert all(
            bool(jnp.all(jnp.isfinite(g)))
            for g in jax.tree_util.tree_leaves(grads)
        )

    def test_chunked_mlm_loss_matches_unchunked(self):
        """mlm_loss_chunks must not change values or grads — only memory."""
        m = BertForPreTraining(BertConfig(**BERT_KW))
        batch = _bert_batch()
        params = m.init(jax.random.PRNGKey(0), batch["input_ids"])
        l1, g1 = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m, batch)
        )(params)
        l4, g4 = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m, batch, mlm_loss_chunks=4)
        )(params)
        np.testing.assert_allclose(float(l1), float(l4), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=2e-4, atol=2e-5,
            ),
            g1, g4,
        )
        with pytest.raises(ValueError):
            bert_pretrain_loss(params, m, batch, mlm_loss_chunks=7)

    def test_packed_mlm_matches_dense(self):
        """The fixed-K masked-position path (mlm_positions/label_ids/
        weights, ≙ the reference recipe's max_predictions_per_seq input)
        must reproduce the dense-label loss and grads exactly when K covers
        every masked position."""
        m = BertForPreTraining(BertConfig(**BERT_KW))
        batch = _bert_batch()
        params = m.init(jax.random.PRNGKey(0), batch["input_ids"])
        n_masked = int(jnp.max(jnp.sum(batch["mlm_labels"] >= 0, axis=0)))
        packed, (pos, ids, w) = _pack_batch(batch, n_masked)
        assert int(w.sum()) == int(jnp.sum(batch["mlm_labels"] >= 0))
        l1, g1 = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m, batch)
        )(params)
        l2, g2 = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m, packed)
        )(params)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=2e-4, atol=2e-5,
            ),
            g1, g2,
        )

    def test_packed_mlm_truncates_and_chunks(self):
        """K smaller than the masked count truncates in position order (the
        reference behavior); chunking composes with the packed path."""
        from apex_tpu.data import pack_mlm_predictions

        m = BertForPreTraining(BertConfig(**BERT_KW))
        batch = _bert_batch()
        params = m.init(jax.random.PRNGKey(0), batch["input_ids"])
        packed, (pos, ids, w) = _pack_batch(batch, 2)
        assert pos.shape == (2, B) and w.sum() <= 2 * B
        # truncation keeps the first masked positions per sequence
        labels_np = np.asarray(batch["mlm_labels"])
        for b in range(B):
            want = np.nonzero(labels_np[:, b] >= 0)[0][:2]
            got = pos[: len(want), b]
            np.testing.assert_array_equal(got, want)
        l1 = bert_pretrain_loss(params, m, packed)
        l2 = bert_pretrain_loss(params, m, packed, mlm_loss_chunks=2)
        assert np.isfinite(float(l1))
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
        # K > S keeps the documented fixed-(K, B) shape, zero-padded
        pos, ids, w = pack_mlm_predictions(batch["mlm_labels"], S + 4)
        assert pos.shape == ids.shape == w.shape == (S + 4, B)
        assert not w[S:].any() and not pos[S:].any()
        assert int(w.sum()) == int(jnp.sum(batch["mlm_labels"] >= 0))

    @pytest.fixture(scope="class")
    def no_remat_reference(self):
        """(params, loss, grads) of the no-remat model — shared across the
        policy parametrizations (policy-independent, compile once)."""
        m_ref = BertForPreTraining(BertConfig(**BERT_KW))
        batch = _bert_batch()
        params = m_ref.init(jax.random.PRNGKey(0), batch["input_ids"])
        l_r, g_r = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m_ref, batch)
        )(params)
        return params, l_r, g_r

    @pytest.mark.parametrize("policy", ["full", "dots", "sums"])
    def test_remat_policy_preserves_values(self, policy, no_remat_reference):
        """Remat policies (incl. the named-saves 'sums' policy that frees
        raw matmul outputs for epilogue fusion) are pure schedule knobs:
        loss and grads must match the no-remat model exactly."""
        params, l_r, g_r = no_remat_reference
        m_pol = BertForPreTraining(
            BertConfig(remat=True, remat_policy=policy, **BERT_KW)
        )
        batch = _bert_batch()
        l_p, g_p = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m_pol, batch)
        )(params)
        np.testing.assert_allclose(float(l_r), float(l_p), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=1e-5, atol=1e-6,
            ),
            g_r, g_p,
        )

    def test_unrolled_matches_scanned(self):
        """scan_layers / remat_attention are pure layout+schedule knobs:
        same params (modulo the (L, ...) stacking axis), same loss, same
        grads as the scanned encoder."""
        m_scan = BertForPreTraining(BertConfig(**BERT_KW))
        m_unroll = BertForPreTraining(
            BertConfig(
                scan_layers=False, remat=True, remat_policy="dots",
                remat_attention=True, **BERT_KW,
            )
        )
        batch = _bert_batch()
        params_s = m_scan.init(jax.random.PRNGKey(0), batch["input_ids"])

        # restack the scanned (L, ...) params into per-layer trees
        def to_unrolled(ps_tree):
            enc = ps_tree["params"]["bert"]["encoder"]["layers"]["layer"]
            L = BERT_KW["num_layers"]
            out = dict(ps_tree["params"]["bert"]["encoder"])
            del out["layers"]
            for i in range(L):
                out[f"layer_{i}"] = {
                    "layer": jax.tree_util.tree_map(lambda x: x[i], enc)
                }
            new = jax.tree_util.tree_map(lambda x: x, ps_tree)  # copy
            new["params"]["bert"]["encoder"] = out
            return new

        params_u = to_unrolled(params_s)
        # sanity: the unrolled model accepts the restacked tree
        l_s, g_s = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m_scan, batch)
        )(params_s)
        l_u, g_u = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, m_unroll, batch)
        )(params_u)
        np.testing.assert_allclose(float(l_s), float(l_u), rtol=1e-5)
        # compare grads on the shared (non-encoder) subtrees and on the
        # restacked encoder layers
        np.testing.assert_allclose(
            np.asarray(g_s["params"]["mlm_bias"]),
            np.asarray(g_u["params"]["mlm_bias"]),
            rtol=1e-4, atol=1e-6,
        )
        enc_s = g_s["params"]["bert"]["encoder"]["layers"]["layer"]
        for i in range(BERT_KW["num_layers"]):
            want = jax.tree_util.tree_map(lambda x: x[i], enc_s)
            got = g_u["params"]["bert"]["encoder"][f"layer_{i}"]["layer"]
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5
                ),
                want, got,
            )

    def test_tp_matches_unsharded(self, eight_devices):
        """sharded_init + per-head QKV layout ⇒ tp changes nothing."""
        l_tp = _sharded_bert_loss(sp=False)
        ps.destroy_model_parallel()
        m1 = BertForPreTraining(BertConfig(**BERT_KW))
        batch = _bert_batch()
        p1 = m1.init(jax.random.PRNGKey(0), batch["input_ids"])
        l1 = float(bert_pretrain_loss(p1, m1, batch))
        assert abs(l_tp - l1) < 2e-3, (l_tp, l1)

    def test_sp_matches_tp(self, eight_devices):
        l_tp = _sharded_bert_loss(sp=False)
        ps.destroy_model_parallel()
        l_sp = _sharded_bert_loss(sp=True)
        assert abs(l_tp - l_sp) < 1e-4, (l_tp, l_sp)

    def test_packed_mlm_tp_sp_matches_unsharded(self, eight_devices):
        """The masked-position gather sits above the tp/SP grad boundaries
        (copy_to / SP gather), so the packed loss must agree across
        unsharded, tp, and tp+SP runs."""
        m1 = BertForPreTraining(BertConfig(**BERT_KW))
        batch, _ = _pack_batch(_bert_batch(), 8)
        p1 = m1.init(jax.random.PRNGKey(0), batch["input_ids"])
        l1 = float(bert_pretrain_loss(p1, m1, batch))
        l_tp = _sharded_bert_loss(sp=False, packed=True)
        ps.destroy_model_parallel()
        l_sp = _sharded_bert_loss(sp=True, packed=True)
        assert abs(l_tp - l1) < 2e-3, (l_tp, l1)
        assert abs(l_sp - l_tp) < 1e-4, (l_sp, l_tp)

    def test_training_descends(self):
        m = BertForPreTraining(BertConfig(**BERT_KW))
        batch = _bert_batch()
        params = m.init(jax.random.PRNGKey(0), batch["input_ids"])

        from apex_tpu.optimizers import fused_lamb

        tx = fused_lamb(learning_rate=5e-3)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt):
            loss, grads = jax.value_and_grad(
                lambda p: bert_pretrain_loss(p, m, batch)
            )(params)
            upd, opt = tx.update(grads, opt, params)
            return jax.tree_util.tree_map(jnp.add, params, upd), opt, loss

        params, opt, l0 = step(params, opt)
        for _ in range(10):
            params, opt, loss = step(params, opt)
        assert float(loss) < float(l0)


class TestGpt:
    def test_tp_sp_matches_unsharded(self, eight_devices):
        kw = dict(
            vocab_size=128, hidden_size=64, num_layers=2, num_heads=8,
            intermediate_size=128, max_seq_len=64, dtype=jnp.float32,
        )
        ids = jax.random.randint(jax.random.PRNGKey(7), (S, B), 0, 128)
        m1 = GptModel(GptConfig(**kw))
        p1 = m1.init(jax.random.PRNGKey(1), ids)
        l1 = float(gpt_lm_loss(p1, m1, ids))

        mesh = ps.initialize_model_parallel(tensor_model_parallel_size=8)
        m8 = GptModel(GptConfig(sequence_parallel=True, **kw))

        def f(key, ids):
            params = m8.init(key, ids)
            return gpt_lm_loss(params, m8, ids)

        l8 = float(
            jax.jit(
                jax.shard_map(
                    f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                    check_vma=False,
                )
            )(jax.random.PRNGKey(1), ids)
        )
        assert abs(l1 - l8) < 2e-3, (l1, l8)

    @pytest.mark.parametrize("policy", ["dots", "sums"])
    def test_gpt_remat_policy_preserves_values(self, policy):
        """remat=True with 'dots'/'sums' reproduces the no-remat loss and
        grads (the gpt_* named tags mirror the BERT sums save set)."""
        kw = dict(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, max_seq_len=16, dtype=jnp.float32,
        )
        ids = jax.random.randint(jax.random.PRNGKey(2), (16, 2), 0, 64)

        def loss_and_grads(**extra):
            m = GptModel(GptConfig(**kw, **extra))
            params = m.init(jax.random.PRNGKey(3), ids)
            return jax.value_and_grad(
                lambda p: gpt_lm_loss(p, m, ids)
            )(params)

        l_ref, g_ref = loss_and_grads()
        l_p, g_p = loss_and_grads(remat=True, remat_policy=policy)
        np.testing.assert_allclose(float(l_ref), float(l_p), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            g_ref, g_p,
        )

    def test_causality(self):
        """Changing a future token must not change earlier losses' inputs:
        logits at position t depend only on ids[:t+1]."""
        kw = dict(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=4,
            intermediate_size=64, max_seq_len=32, dtype=jnp.float32,
        )
        m = GptModel(GptConfig(**kw))
        ids = jax.random.randint(jax.random.PRNGKey(0), (8, 1), 0, 64)
        params = m.init(jax.random.PRNGKey(1), ids)
        h1 = m.apply(params, ids)
        ids2 = ids.at[-1, 0].set((ids[-1, 0] + 1) % 64)
        h2 = m.apply(params, ids2)
        np.testing.assert_allclose(
            np.asarray(h1[:-1]), np.asarray(h2[:-1]), atol=1e-5
        )


class TestResNet:
    def test_forward_and_grad(self):
        m = resnet50(num_classes=10, dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
        variables = m.init(jax.random.PRNGKey(1), x, train=False)
        logits, new_state = m.apply(
            x=x, train=True, mutable=["batch_stats"], variables=variables
        )
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_syncbn_variant_runs(self, eight_devices):
        mesh = ps.initialize_model_parallel()  # dp=8
        m = resnet50(num_classes=4, dtype=jnp.float32, use_syncbn=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 16, 16, 3))

        def f(key, x):
            variables = m.init(key, x, train=False)
            logits, _ = m.apply(
                x=x, train=True, mutable=["batch_stats"], variables=variables
            )
            return logits

        logits = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P("dp"),
                check_vma=False,
            )
        )(jax.random.PRNGKey(1), x)
        assert logits.shape == (16, 4)
        assert bool(jnp.all(jnp.isfinite(logits)))


class TestGraftEntry:
    def _load(self):
        spec = importlib.util.spec_from_file_location(
            "__graft_entry__", "/root/repo/__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_dryrun_multichip(self, eight_devices):
        ge = self._load()
        ge.dryrun_multichip(8)
