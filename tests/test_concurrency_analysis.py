"""Concurrency & replay-purity analyzer tests (docs/analysis.md
"Concurrency & replay-purity passes").

Three layers, mirroring the subsystem:

- planted-defect fixtures — one per new rule id, each a minimal class/
  module shaped like the real defect the rule exists for (the OpsServer
  nested-handler alias, the ``st = self._stats`` alias, the
  lock-across-queue-put deadlock), plus clean twins pinned at zero
  findings;
- the runtime sanitizer — TrackedLock lock-order graph, cycle
  detection, unarmed no-op, close() diagnostics;
- regression tests for the races the pass found in the shipped code
  (OpsServer scrape counters, AsyncCheckpointEngine stats ledger,
  DevicePrefetcher producer wait) — each exercises the actual race
  window deterministically (``sys.setswitchinterval`` + exact-count
  assertions) so a revert of the lock fix fails loudly.
"""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from apex_tpu import analysis
from apex_tpu.analysis import concurrency, purity
from apex_tpu.observability import locks as locks_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


def _conc(src, rel="goodput/planted.py"):
    return concurrency.lint_source(textwrap.dedent(src), rel)


def _pure(src, rel="serve/planted.py"):
    return purity.lint_source(textwrap.dedent(src), rel)


# ---------------------------------------------------------------------------
# planted fixtures: the lock-discipline rules
# ---------------------------------------------------------------------------


def test_planted_unlocked_shared_state_is_caught():
    findings = _conc("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._status = "idle"
                self._t = threading.Thread(target=self._worker)
                self._t.start()

            def _worker(self):
                self._status = "running"

            def status(self):
                return self._status
    """)
    assert _rules(findings) == {"race-unlocked-shared-state"}
    (f,) = findings
    assert "_status" in f.message and "_worker" in f.message


def test_planted_nonatomic_counter_is_caught():
    findings = _conc("""
        import threading

        class Counter:
            def __init__(self):
                self.n = 0
                threading.Thread(target=self._tick).start()

            def _tick(self):
                self.n += 1

            def value(self):
                return self.n
    """)
    assert _rules(findings) == {"race-nonatomic-counter"}
    assert "read-modify-write" in findings[0].message


def test_planted_stats_alias_rmw_is_caught():
    # the exact async_ckpt shape the pass was built for: mutation
    # through a local alias of the shared dict
    findings = _conc("""
        import threading

        class Ledger:
            def __init__(self):
                self._stats = {"saves": 0.0}
                threading.Thread(target=self._worker).start()

            def _worker(self):
                st = self._stats
                st["saves"] += 1.0

            def stats(self):
                return dict(self._stats)
    """)
    assert _rules(findings) == {"race-nonatomic-counter"}
    assert "_stats" in findings[0].message


def test_alias_rebind_is_not_a_write():
    # rebinding the local alias is NOT a mutation of the attribute
    findings = _conc("""
        import threading

        class Ok:
            def __init__(self):
                self._stats = {}
                threading.Thread(target=self._worker).start()

            def _worker(self):
                st = self._stats
                st = {}
                st["k"] = 1

            def stats(self):
                return dict(self._stats)
    """)
    assert findings == []


def test_planted_http_handler_alias_is_caught():
    # the OpsServer shape: a nested http.server handler class reaching
    # back through an ``ops = self`` alias — its calls are thread
    # entrypoints even though no threading.Thread names them
    findings = _conc("""
        import http.server

        class Server:
            def __init__(self):
                self.scrapes = 0

            def scrape(self):
                self.scrapes += 1
                return "ok"

            def start(self):
                ops = self

                class Handler(http.server.BaseHTTPRequestHandler):
                    def do_GET(self):
                        ops.scrape()
    """)
    assert _rules(findings) == {"race-nonatomic-counter"}
    assert "scrapes" in findings[0].message


def test_planted_lock_across_blocking_is_caught():
    findings = _conc("""
        import queue
        import threading

        class Pipeline:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue(maxsize=1)
                threading.Thread(target=self._worker).start()

            def _worker(self):
                item = self._q.get()
                with self._lock:
                    self._handle(item)

            def _handle(self, item):
                pass

            def submit(self, item):
                with self._lock:
                    self._q.put(item)
    """)
    assert "race-lock-across-blocking" in _rules(findings)
    (f,) = [x for x in findings if x.rule == "race-lock-across-blocking"]
    assert "submit" in f.message and "_lock" in f.message


def test_clean_locked_class_zero_findings():
    # the same shapes, disciplined: every shared write under the lock,
    # the blocking put outside it
    findings = _conc("""
        import queue
        import threading

        class Clean:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue(maxsize=1)
                self._stats = {"n": 0.0}
                self._status = "idle"
                threading.Thread(target=self._worker).start()

            def _worker(self):
                item = self._q.get()
                with self._lock:
                    self._stats["n"] += 1.0
                    self._status = "running"

            def submit(self, item):
                self._q.put(item)
                with self._lock:
                    self._stats["n"] += 1.0

            def stats(self):
                with self._lock:
                    return dict(self._stats)
    """)
    assert findings == []


def test_single_threaded_class_never_judged():
    # no thread entry -> not judged, however sloppy the mutation
    findings = _conc("""
        class Plain:
            def bump(self):
                self.n += 1

            def read(self):
                return self.n
    """)
    assert findings == []


def test_race_waiver_is_honored():
    findings = _conc("""
        import threading

        class Waived:
            def __init__(self):
                self.n = 0
                threading.Thread(target=self._tick).start()

            def _tick(self):
                self.n += 1  # lint: allow(race-nonatomic-counter): test-only approximate counter

            def value(self):
                return self.n
    """)
    assert findings == []


# ---------------------------------------------------------------------------
# planted fixtures: the replay-purity rules
# ---------------------------------------------------------------------------


def test_planted_wall_clock_is_caught():
    findings = _pure("""
        import time

        def tick():
            return time.time()
    """)
    assert _rules(findings) == {"replay-wall-clock"}


def test_planted_datetime_now_is_caught():
    findings = _pure("""
        import datetime

        def stamp():
            return datetime.datetime.now()
    """)
    assert _rules(findings) == {"replay-wall-clock"}


def test_planted_unseeded_rng_is_caught():
    findings = _pure("""
        import random
        import numpy as np

        def jitter():
            return random.random() + np.random.rand()
    """)
    assert _rules(findings) == {"replay-unseeded-rng"}
    assert len(findings) == 2


def test_seeded_rng_passes():
    findings = _pure("""
        import random
        import numpy as np

        def make(seed):
            rng = np.random.default_rng(seed)
            r = random.Random(seed)
            return rng.normal(), r.random()
    """)
    assert findings == []


def test_planted_set_order_is_caught():
    findings = _pure("""
        class Router:
            def __init__(self):
                self._peers = set()

            def pick(self):
                for p in self._peers:
                    return p
    """)
    assert _rules(findings) == {"replay-set-order"}


def test_sorted_set_iteration_passes():
    # iterating a LIST (or sorted(...)) is deterministic — only the
    # raw set iteration flags
    findings = _pure("""
        class Router:
            def __init__(self):
                self._peers = []

            def pick(self):
                for p in self._peers:
                    return p
    """)
    assert findings == []


def test_planted_env_read_is_caught():
    findings = _pure("""
        import os

        class Engine:
            def step(self):
                return os.environ["APEX_TPU_MODE"]
    """)
    assert _rules(findings) == {"replay-env-read"}


def test_env_read_in_init_passes():
    findings = _pure("""
        import os

        class Engine:
            def __init__(self):
                self.mode = os.environ.get("APEX_TPU_MODE", "run")

        def resolve_depth():
            return os.getenv("APEX_TPU_DEPTH")
    """)
    assert findings == []


def test_purity_waiver_is_honored():
    findings = _pure("""
        import time

        def banner():
            return time.time()  # lint: allow(replay-wall-clock): display-only timestamp
    """)
    assert findings == []


def test_non_replay_critical_module_not_judged():
    src = "import time\n\ndef t():\n    return time.time()\n"
    assert purity.lint_source(src, "observability/meter.py") == []
    assert purity.is_replay_critical("serve/engine.py")
    assert purity.is_replay_critical("goodput/stream.py")
    assert not purity.is_replay_critical("goodput/async_ckpt.py")


# ---------------------------------------------------------------------------
# pass registration + the shipped codebase stays clean
# ---------------------------------------------------------------------------


def test_lint_package_on_planted_tree(tmp_path):
    (tmp_path / "serve").mkdir()
    (tmp_path / "serve" / "bad.py").write_text(
        "import time\n\ndef t():\n    return time.time()\n"
    )
    (tmp_path / "worker.py").write_text(textwrap.dedent("""
        import threading

        class W:
            def __init__(self):
                self.n = 0
                threading.Thread(target=self._tick).start()

            def _tick(self):
                self.n += 1

            def value(self):
                return self.n
    """))
    report = analysis.lint_package(root=str(tmp_path), name="planted")
    assert "replay-wall-clock" in report.rule_ids()
    assert "race-nonatomic-counter" in report.rule_ids()
    assert not report.ok()
    # the passes were timed like any other pass
    assert set(report.pass_timings) == {"concurrency", "purity"}
    assert report.sections["files_scanned"] == 2


def test_shipped_package_is_lint_clean():
    # THE acceptance pin: zero concurrency/purity ERRORs over the real
    # package, with no waivers doing the work (grep proves the shipped
    # tree carries no race waivers at all)
    report = analysis.lint_package()
    assert report.errors() == [], report.render()
    for rel, src in purity.collect_sources():
        assert "lint: allow(race-" not in src, rel


def test_source_passes_dropped_without_sources():
    # a jaxpr-only StepGraph must not pretend the source passes ran
    import jax
    import jax.numpy as jnp

    jaxpr = jax.make_jaxpr(lambda x: x * 2)(jnp.ones((2,)))
    report = analysis.lint_jaxpr(jaxpr, name="toy")
    assert "concurrency" not in report.rules_run
    assert "purity" not in report.rules_run


def test_concurrency_lint_cli_jax_free(tmp_path):
    # the CLI must run (and pass) with jax imports hard-broken — the
    # whole point of the standalone loader
    out = tmp_path / "clint.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(tmp_path / "poison")
    (tmp_path / "poison" / "jax").mkdir(parents=True)
    (tmp_path / "poison" / "jax" / "__init__.py").write_text(
        "raise ImportError('jax must not be imported by the lint CLI')\n"
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "concurrency_lint.py"),
         "--json", str(out)],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    art = json.loads(out.read_text())
    assert art["errors"] == 0
    assert art["rules_run"] == ["concurrency", "purity"]
    assert art["files_scanned"] > 100


def test_concurrency_lint_cli_fails_on_planted(tmp_path):
    bad = tmp_path / "pkg"
    (bad / "serve").mkdir(parents=True)
    (bad / "serve" / "bad.py").write_text(
        "import time\n\ndef t():\n    return time.time()\n"
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "concurrency_lint.py"),
         "--root", str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "replay-wall-clock" in proc.stdout


def test_repo_lint_delegates_to_purity_module_list():
    # satellite: the repo_lint wall-clock rule's module list IS
    # purity.REPLAY_CRITICAL — no second copy to drift
    spec = importlib.util.spec_from_file_location(
        "_rl_test", os.path.join(REPO, "tools", "repo_lint.py")
    )
    rl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rl)
    lines = ["t0 = time.time()"]
    hits = rl._replay_clock_violations("serve/engine.py", lines)
    assert len(hits) == 1 and hits[0][0] == "serve/engine.py"
    # same line, non-critical path: silent
    assert rl._replay_clock_violations("ops/fused.py", lines) == []
    # the purity waiver syntax is honored here too
    waived = ["t0 = time.time()  # lint: allow(replay-wall-clock): banner"]
    assert rl._replay_clock_violations("serve/engine.py", waived) == []
    assert rl._purity_mod().REPLAY_CRITICAL == purity.REPLAY_CRITICAL


# ---------------------------------------------------------------------------
# the runtime sanitizer: TrackedLock + lock-order graph
# ---------------------------------------------------------------------------


@pytest.fixture
def armed_sanitizer():
    locks_mod.reset_sanitizer()
    locks_mod.arm(True)
    try:
        yield
    finally:
        locks_mod.arm(None)
        locks_mod.reset_sanitizer()


def test_tracked_lock_is_a_lock():
    lk = locks_mod.TrackedLock("t")
    assert lk.holder is None and lk.acquires == 0
    with lk:
        assert lk.holder == threading.current_thread().name
        assert lk.locked()
    assert lk.holder is None and lk.acquires == 1
    assert lk.acquire(blocking=False)
    assert not lk.acquire(blocking=False)  # a real Lock underneath
    lk.release()


def test_lock_order_graph_records_edges(armed_sanitizer):
    a, b = locks_mod.TrackedLock("A"), locks_mod.TrackedLock("B")
    with a:
        with b:
            pass
    assert locks_mod.lock_order_graph() == {"A": ["B"]}
    rep = locks_mod.sanitizer_report()
    assert rep["armed"] and rep["cycles"] == []
    assert rep["locks"] == {"A": 1, "B": 1}
    assert rep["edges"] == [["A", "B"]]


def test_lock_order_cycle_is_detected(armed_sanitizer):
    # A->B then B->A: the classic two-lock inversion, driven from one
    # thread sequentially (the graph is about ORDER, not simultaneity)
    a, b = locks_mod.TrackedLock("A"), locks_mod.TrackedLock("B")
    with a:
        with b:
            pass
    with pytest.warns(RuntimeWarning, match="lock-order cycle"):
        with b:
            with a:
                pass
    cyc = locks_mod.cycles()
    assert len(cyc) == 1
    assert set(cyc[0]["cycle"]) == {"A", "B"}
    assert cyc[0]["closing_edge"] == ["B", "A"]
    # dedup: the same inversion again is not a second report
    with b:
        with a:
            pass
    assert len(locks_mod.cycles()) == 1


def test_cycle_reported_to_flight_recorder(armed_sanitizer):
    from apex_tpu.observability import FlightRecorder

    fr = FlightRecorder(capacity=16)
    locks_mod.attach_flight(fr)
    try:
        a = locks_mod.TrackedLock("FA")
        b = locks_mod.TrackedLock("FB")
        with a:
            with b:
                pass
        with pytest.warns(RuntimeWarning):
            with b:
                with a:
                    pass
        kinds = [e["kind"] for e in fr.events]
        assert "locksan_cycle" in kinds
    finally:
        locks_mod.attach_flight(None)


def test_unarmed_sanitizer_records_nothing():
    locks_mod.reset_sanitizer()
    locks_mod.arm(False)
    try:
        a, b = locks_mod.TrackedLock("UA"), locks_mod.TrackedLock("UB")
        with a:
            with b:
                pass
        assert locks_mod.lock_order_graph() == {}
        assert locks_mod.sanitizer_report()["locks"] == {}
        # the cheap diagnostics still work unarmed
        assert a.acquires == 1 and b.acquires == 1
    finally:
        locks_mod.arm(None)
        locks_mod.reset_sanitizer()


def test_reentrant_tracked_lock_no_self_edge(armed_sanitizer):
    lk = locks_mod.TrackedLock("R", reentrant=True)
    with lk:
        with lk:
            pass
    assert locks_mod.lock_order_graph() == {}
    assert locks_mod.cycles() == []


# ---------------------------------------------------------------------------
# regression: the races the pass found in the shipped code
# ---------------------------------------------------------------------------


def _hammer(fn, nthreads, per_thread):
    """Run fn nthreads x per_thread times with a vicious switch
    interval — the deterministic race window: before the lock fix the
    lost-update count here was reliably nonzero."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        def body():
            for _ in range(per_thread):
                fn()
        ts = [threading.Thread(target=body) for _ in range(nthreads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(prev)


def test_ops_server_concurrent_scrape_exact_count():
    from apex_tpu.observability.ometrics import OpsServer

    srv = OpsServer(include_board=False)
    _hammer(srv.scrape, nthreads=8, per_thread=50)
    assert srv.scrapes == 400  # lost updates = missing lock
    assert srv.last_scrape_ms is not None
    assert srv._lock.acquires == 400  # the lock actually guards it


def test_async_ckpt_concurrent_saves_exact_ledger(tmp_path):
    from apex_tpu.goodput import AsyncCheckpointEngine

    state = {"w": np.zeros((4,), np.float32)}
    with AsyncCheckpointEngine(tmp_path, queue_depth=64) as eng:
        eng.save(0, state)  # boot the writer before the hammer
        eng.wait_until_finished()
        counter = {"n": 0}
        clock = threading.Lock()

        def one_save():
            with clock:
                counter["n"] += 1
                step = counter["n"]
            eng.save(step, state, force=True)

        _hammer(one_save, nthreads=4, per_thread=4)
        eng.wait_until_finished()
        st = eng.stats()
    assert st["saves"] == 17.0  # 1 boot + 16 hammered, none lost
    assert st["failures"] == 0.0
    assert eng._lock.acquires > 17  # save + writer both acquired


def test_async_ckpt_close_names_stuck_phase(tmp_path):
    from apex_tpu.goodput import AsyncCheckpointEngine

    release = threading.Event()
    eng = AsyncCheckpointEngine(tmp_path)
    eng._commit_hook = lambda step: release.wait()
    try:
        eng.save(7, {"w": np.zeros((2,), np.float32)})
        with pytest.warns(RuntimeWarning) as rec:
            eng.close(timeout=0.3)
        msgs = [str(w.message) for w in rec]
        stuck = [m for m in msgs if "still busy" in m]
        assert stuck, msgs
        assert "stuck phase: write step 7" in stuck[0]
        assert "lock held by" in stuck[0]
    finally:
        release.set()
        if eng._thread is not None:
            eng._thread.join(timeout=30)


def test_prefetcher_producer_wait_is_locked():
    from apex_tpu.data import DevicePrefetcher

    with DevicePrefetcher(iter(range(6)), depth=1) as pf:
        got = []
        for x in pf:
            time.sleep(0.01)  # slow consumer: producer must wait
            got.append(x)
    assert len(got) == 6
    assert pf.metrics()["producer_wait_s"] > 0.0
    # every successful producer put went through the lock
    assert pf._lock.acquires >= 6
