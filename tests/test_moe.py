"""Switch-MoE / expert parallelism tests.

The load-bearing invariant: expert parallelism is a LAYOUT — running the
same tokens through experts sharded over the dp axis (all_to_all
dispatch) must produce the same outputs as the unsharded module with the
same global expert weights (ep-degree invariance, the EP analog of the
tp-invariance tests in test_tp_layers.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.transformer.moe import MoeConfig, SwitchMoe, moe_dispatch_combine

H, F, E = 16, 32, 4
S, B_LOCAL = 8, 2  # per-rank tokens = 16


def _cfg(**kw):
    base = dict(
        hidden_size=H, ffn_hidden_size=F, num_experts=E,
        dtype=jnp.float32, capacity_factor=1.5,
    )
    base.update(kw)
    return MoeConfig(**base)


class TestDispatchCombine:
    def test_positions_and_drops(self):
        # 4 tokens, 2 experts, capacity 1: tokens 0,1 -> expert 0 (token 1
        # overflows and is dropped), tokens 2,3 -> expert 1 (3 dropped)
        probs = jnp.array(
            [[0.9, 0.1], [0.8, 0.2], [0.3, 0.7], [0.4, 0.6]], jnp.float32
        )
        dispatch, combine, aux = moe_dispatch_combine(probs, 1, 1)
        np.testing.assert_array_equal(
            np.asarray(jnp.sum(dispatch, axis=(1, 2))), [1, 0, 1, 0]
        )
        # kept tokens carry their router gate
        assert float(combine[0, 0, 0]) == pytest.approx(0.9)
        assert float(combine[2, 1, 0]) == pytest.approx(0.7)
        assert float(jnp.sum(combine[1])) == 0.0
        assert np.isfinite(float(aux))

    def test_top2_renormalizes(self):
        probs = jnp.array([[0.6, 0.3, 0.1]], jnp.float32)
        dispatch, combine, _ = moe_dispatch_combine(probs, 2, 2)
        # both choices kept; gates renormalized to sum to 1
        assert float(jnp.sum(dispatch)) == 2.0
        assert float(jnp.sum(combine)) == pytest.approx(1.0, rel=1e-5)

    def test_capacity_bounds_per_expert(self):
        probs = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(0), (64, E)), axis=-1
        )
        dispatch, _, _ = moe_dispatch_combine(probs, 1, 3)
        per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
        assert (per_expert <= 3).all()


class TestSwitchMoe:
    def test_forward_and_grads_unsharded(self):
        m = SwitchMoe(_cfg())
        x = jax.random.normal(jax.random.PRNGKey(0), (S, B_LOCAL, H))
        params = m.init(jax.random.PRNGKey(1), x)

        def loss(p):
            y, aux = m.apply(p, x)
            return jnp.sum(y.astype(jnp.float32) ** 2) + 0.01 * aux

        val, grads = jax.value_and_grad(loss)(params)
        assert np.isfinite(float(val))
        for g in jax.tree_util.tree_leaves(grads):
            assert bool(jnp.all(jnp.isfinite(g)))
        # router must receive gradient (it only gets one through the
        # combine weights — a classic silent-failure spot)
        assert float(
            jnp.sum(jnp.abs(grads["params"]["router"]))
        ) > 0.0

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_ep_matches_unsharded(self, eight_devices, top_k):
        """dp=4-sharded experts == unsharded module, shard by shard."""
        ep = 4
        mesh = ps.initialize_model_parallel(devices=jax.devices()[:ep])
        key = jax.random.PRNGKey(2)
        xg = jax.random.normal(
            jax.random.PRNGKey(3), (S, B_LOCAL * ep, H)
        )

        m_sharded = SwitchMoe(_cfg(top_k=top_k, expert_axis="dp"))

        def run(x):
            params = m_sharded.init(key, x)
            y, aux = m_sharded.apply(params, x)
            return y, jax.lax.pmean(aux, "dp")

        y_sh, aux_sh = jax.jit(
            jax.shard_map(
                run, mesh=mesh,
                in_specs=P(None, "dp"), out_specs=(P(None, "dp"), P()),
                check_vma=False,
            )
        )(xg)

        m_ref = SwitchMoe(_cfg(top_k=top_k, expert_axis=None))
        aux_refs = []
        for r in range(ep):
            xr = xg[:, r * B_LOCAL:(r + 1) * B_LOCAL]
            params = m_ref.init(key, xr)
            y_ref, aux_ref = m_ref.apply(params, xr)
            aux_refs.append(float(aux_ref))
            np.testing.assert_allclose(
                np.asarray(y_sh[:, r * B_LOCAL:(r + 1) * B_LOCAL]),
                np.asarray(y_ref),
                atol=1e-5, rtol=1e-5,
            )
        assert float(aux_sh) == pytest.approx(
            np.mean(aux_refs), rel=1e-5
        )
        ps.destroy_model_parallel()

    def test_ep_requires_divisibility(self, eight_devices):
        mesh = ps.initialize_model_parallel(devices=jax.devices()[:3])
        m = SwitchMoe(_cfg(expert_axis="dp"))  # E=4 not divisible by 3
        x = jax.random.normal(jax.random.PRNGKey(0), (S, 3, H))
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(
                jax.shard_map(
                    lambda x: m.init(jax.random.PRNGKey(1), x),
                    mesh=mesh, in_specs=P(None, "dp"), out_specs=P(),
                    check_vma=False,
                )
            )(x)
        ps.destroy_model_parallel()


class TestGptMoe:
    def test_gpt_moe_trains_and_matches_ep(self, eight_devices):
        """GptModel(num_experts=4): loss finite with grads, aux folded in,
        and identical across ep degrees (dp=1 vs dp=4)."""
        from apex_tpu.models import GptConfig, GptModel, gpt_lm_loss

        cfg = GptConfig(
            vocab_size=64, hidden_size=16, num_layers=2, num_heads=4,
            intermediate_size=32, max_seq_len=32, dtype=jnp.float32,
            num_experts=4, moe_top_k=2,
        )
        m = GptModel(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(0), (16, 4), 0, 64)
        key = jax.random.PRNGKey(1)

        def run(dp, ids):
            mesh = ps.initialize_model_parallel(
                devices=jax.devices()[:dp]
            )

            def f(ids):
                params = m.init(key, ids)
                loss, grads = jax.value_and_grad(
                    lambda p: gpt_lm_loss(p, m, ids)
                )(params)
                return jax.lax.pmean(loss, "dp"), sum(
                    jnp.sum(jnp.abs(g))
                    for g in jax.tree_util.tree_leaves(grads)
                )

            loss, gsum = jax.jit(
                jax.shard_map(
                    f, mesh=mesh, in_specs=P(None, "dp"),
                    out_specs=(P(), P()), check_vma=False,
                )
            )(ids)
            ps.destroy_model_parallel()
            return float(loss), float(gsum)

        l4, g4 = run(4, ids)
        assert np.isfinite(l4) and np.isfinite(g4) and g4 > 0
        # Routing capacity is per rank, so the dp=4 loss must equal the
        # MEAN of four independent single-device runs on the same shards
        # with the same (ep-degree-invariant) global expert weights —
        # sharding the experts is a layout, not a model change.
        singles = [
            run(1, ids[:, r:r + 1])[0] for r in range(4)
        ]
        assert l4 == pytest.approx(float(np.mean(singles)), rel=1e-5)


class TestSyncMoeGradients:
    def test_synced_grads_match_global_objective(self, eight_devices):
        """dp=4 grads after sync_moe_gradients == grads of the global mean
        objective computed shard-by-shard unsharded: router (replicated)
        pmean'd, expert shards passed through with the 1/N scale."""
        from apex_tpu.transformer.moe import sync_moe_gradients

        ep = 4
        key = jax.random.PRNGKey(5)
        xg = jax.random.normal(jax.random.PRNGKey(6), (S, B_LOCAL * ep, H))

        def local_loss(m, p, x):
            y, aux = m.apply(p, x)
            return jnp.mean(y.astype(jnp.float32) ** 2) + 0.01 * aux

        # --- sharded: per-rank mean loss, then the MoE-aware sync ------
        m_sh = SwitchMoe(_cfg(expert_axis="dp"))
        mesh = ps.initialize_model_parallel(devices=jax.devices()[:ep])

        def f(x):
            params = m_sh.init(key, x)
            grads = jax.grad(lambda p: local_loss(m_sh, p, x))(params)
            return sync_moe_gradients(grads)

        g_sh = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=P(None, "dp"),
                out_specs=P("dp"), check_vma=False,
            )
        )(xg)
        ps.destroy_model_parallel()
        # leaves come back dp-stacked: router (4, H, E) (one copy per
        # rank, all equal), experts (E, ...) = ranks' shards concatenated
        g_sh = jax.tree_util.tree_map(np.asarray, g_sh)

        # --- reference: global mean objective over the 4 shards --------
        m_ref = SwitchMoe(_cfg(expert_axis=None))
        accum = None
        for r in range(ep):
            xr = xg[:, r * B_LOCAL:(r + 1) * B_LOCAL]
            params = m_ref.init(key, xr)
            g = jax.grad(lambda p: local_loss(m_ref, p, xr))(params)
            g = jax.tree_util.tree_map(lambda a: np.asarray(a) / ep, g)
            accum = g if accum is None else jax.tree_util.tree_map(
                np.add, accum, g
            )

        # out_specs=P("dp") concatenates the per-rank leaves on dim 0, so
        # the replicated router comes back as (ep*H, E) = ep stacked copies
        router_sh = g_sh["params"]["router"].reshape(ep, H, -1)
        np.testing.assert_allclose(
            router_sh[0],
            np.asarray(accum["params"]["router"]),
            atol=1e-5, rtol=1e-5,
        )
        # every rank's router copy is identical after the pmean
        assert np.allclose(router_sh, router_sh[:1], atol=1e-6)
        for name in ("expert_w1", "expert_w2"):
            np.testing.assert_allclose(
                g_sh["params"][name].reshape(
                    accum["params"][name].shape
                ),
                np.asarray(accum["params"][name]),
                atol=1e-5, rtol=1e-5,
            )
