"""The observability subsystem: registry under jit on the 8-device
mesh, goodput accounting across an injected-chaos rollback, JSONL
schema convergence with bench.py, comm gauge publication, trace
scheduling, and the <1% registry overhead budget (ISSUE 3 acceptance).
"""

import json
import os
import struct
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.observability import (
    GoodputAccountant,
    JSONLSink,
    MetricRegistry,
    Reporter,
    StepMeter,
    TensorBoardSink,
    TraceScheduler,
    bench_record,
    board,
    transformer_train_flops,
)
from apex_tpu.observability.export import CSVSink, _masked_crc
from apex_tpu.observability.trace import parse_trace_spec, window_dir
from apex_tpu.parallel import comm
from apex_tpu.resilience import chaos, run_resilient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# MetricRegistry
# ---------------------------------------------------------------------------


def test_registry_accumulate_fetch_under_jit_on_mesh(eight_devices):
    """Counters/gauges/max fold inside a jitted shard_map step over the
    8-device mesh; the host fetches on the cadence, never per step."""
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    reg = MetricRegistry(fetch_every=4)
    reg.counter("steps")
    reg.gauge("grad_norm")
    reg.maximum("max_norm")
    state = reg.init()

    @jax.jit
    def step(mstate, x):
        def inner(mstate, local):
            norm = jnp.sqrt(
                jax.lax.psum(jnp.sum(local.astype(jnp.float32) ** 2), "dp")
            )
            return reg.update(
                mstate,
                {"steps": 1, "grad_norm": norm, "max_norm": norm},
            )

        return jax.shard_map(
            inner, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False,
        )(mstate, x)

    for s in range(10):
        x = jnp.full((8, 4), float(s + 1))
        state = step(state, x)
        reg.observe(s, state)

    # cadence semantics: observe(8) materialized the copy started at
    # observe(4) — values are present but deliberately stale, and no
    # step in between blocked on the device
    assert reg.fetched_step == 4
    assert reg.values()["steps"] == 5.0  # counter after steps 0..4

    vals = reg.fetch()  # force-drain at shutdown
    assert reg.fetched_step == 9
    assert vals["steps"] == 10.0
    expected = float(np.sqrt(32.0) * 10.0)  # psum over all 32 elements
    np.testing.assert_allclose(vals["grad_norm"], expected, rtol=1e-6)
    np.testing.assert_allclose(vals["max_norm"], expected, rtol=1e-6)


def test_registry_rejects_undeclared_metric():
    reg = MetricRegistry()
    reg.gauge("known")
    with pytest.raises(KeyError):
        reg.update(reg.init(), {"typo": 1.0})


class _DeadBuffer:
    """An array-like whose host materialization fails — the shape of a
    device buffer poisoned by the crash being debugged."""

    def __float__(self):
        raise RuntimeError("device buffer dead")


def test_fetch_flushes_pending_even_when_inflight_raises():
    """ISSUE 5 satellite pin: the NEWEST (pending) stash lands in a
    ``finally`` — an exception materializing the OLDER in-flight copy
    must not leave the flight recorder's last frame a cadence stale."""
    reg = MetricRegistry(fetch_every=4)
    reg.gauge("x")
    reg._inflight = (0, {"x": _DeadBuffer()})
    reg._pending = (1, {"x": 2.5})
    with pytest.raises(RuntimeError, match="device buffer dead"):
        reg.fetch()
    assert reg.values()["x"] == 2.5  # the pending stash was flushed
    assert reg.fetched_step == 1
    # both buffers are consumed: a second fetch is clean
    assert reg.fetch() == {"x": 2.5}


def test_close_drains_best_effort_and_never_raises():
    """The dump path: per-value failures keep previous values, healthy
    scalars in the same stash still land, and close() returns."""
    reg = MetricRegistry(fetch_every=4)
    reg.gauge("dead")
    reg.gauge("alive")
    reg._inflight = (2, {"dead": 1.0, "alive": 1.0})
    reg._pending = (3, {"dead": _DeadBuffer(), "alive": 7.0})
    values = reg.close()
    assert values["alive"] == 7.0  # newest healthy value won
    assert values["dead"] == 1.0  # poisoned newest -> previous kept
    assert reg.fetched_step == 3
    assert reg._inflight is None and reg._pending is None


def test_close_fully_poisoned_stash_does_not_claim_freshness():
    """A stash where NOTHING materialized must not advance
    fetched_step: the flight dump would otherwise stamp cadence-old
    values with the crash step."""
    reg = MetricRegistry(fetch_every=4)
    reg.gauge("x")
    reg._inflight = (8, {"x": 1.0})
    reg._pending = (14, {"x": _DeadBuffer()})
    assert reg.close() == {"x": 1.0}
    assert reg.fetched_step == 8  # not 14: step 14 never landed


def test_registry_overhead_under_one_percent():
    """ISSUE 3 acceptance: at the default fetch cadence the registry
    adds <1% step-time overhead.

    The device-side claim is asserted on XLA's compiled cost model
    (flops + bytes accessed of an instrumented vs bare 32-step chunk):
    the registry adds a handful of scalar ops to a program, which the
    cost model prices deterministically — measured ~1e-7 relative flops
    and ~4e-5 relative bytes, four orders under the budget.  Wall clock
    on this 1-core shared container wobbles ±10% between IDENTICAL runs
    (tests/conftest.py documents ±30 s on a 240 s tier), so the timed
    comparison below is only a coarse tripwire for a host-path
    regression (e.g. an accidental per-step blocking fetch), not the
    <1% assertion itself.
    """
    reg = MetricRegistry(fetch_every=32)  # default cadence: fetch 1/32
    reg.gauge("loss")
    reg.counter("steps")
    x = jnp.eye(256, dtype=jnp.float32) * 0.5
    chunk = 32  # one fetch per chunk == the default cadence

    def make_chunk(instrumented):
        @jax.jit
        def fn(w, m):
            def body(carry, _):
                w, m = carry
                w = jnp.tanh(w @ x)
                loss = jnp.sum(w)  # both arms compute the loss — a real
                # step has it anyway; the registry ADDS only the fold
                if instrumented:
                    m = reg.update(m, {"loss": loss, "steps": 1})
                return (w, m), loss

            (w, m), losses = jax.lax.scan(body, (w, m), None, length=chunk)
            return w, m, losses[-1]

        return fn

    chunk_bare, chunk_inst = make_chunk(False), make_chunk(True)
    w0 = jnp.ones((256, 256), jnp.float32)
    m0 = reg.init()

    def costs(fn):
        c = fn.lower(w0, m0).compile().cost_analysis()
        c = c[0] if isinstance(c, (list, tuple)) else c
        return float(c.get("flops", 0.0)), float(c.get("bytes accessed", 0.0))

    bare_flops, bare_bytes = costs(chunk_bare)
    inst_flops, inst_bytes = costs(chunk_inst)
    assert bare_flops > 0 and bare_bytes > 0
    assert (inst_flops - bare_flops) / bare_flops < 0.01, (
        f"instrumented chunk flops {inst_flops} vs bare {bare_flops}"
    )
    assert (inst_bytes - bare_bytes) / bare_bytes < 0.01, (
        f"instrumented chunk bytes {inst_bytes} vs bare {bare_bytes}"
    )

    def time_once(fn, observe, base_step):
        t0 = time.perf_counter()
        w, m, loss = fn(w0, m0)
        if observe:
            for j in range(chunk):  # the real per-step host cost
                reg.observe(base_step + j, m)
        float(loss)  # device->host sync point
        return time.perf_counter() - t0

    for fn in (chunk_bare, chunk_inst):  # warmup/compile both arms
        w, m, loss = fn(w0, m0)
        float(loss)
    # PAIRED back-to-back trials: a background-load spike inflates both
    # halves of a pair, so the MIN ratio over pairs is stable where an
    # absolute min-of-each-arm is not (this 1-core box drifts ±30%
    # under concurrent suite load); one clean pair is enough, and a
    # systematic per-step blocking fetch would inflate EVERY pair
    ratios = []
    for t in range(9):
        tb = time_once(chunk_bare, False, 0)
        ti = time_once(chunk_inst, True, t * chunk)
        ratios.append(ti / tb)
    overhead = min(ratios) - 1.0
    assert overhead < 0.25, (
        f"host-path tripwire: best instrumented/bare chunk ratio "
        f"{min(ratios):.3f} — did a per-step blocking fetch sneak in? "
        f"(all ratios: {[round(r, 3) for r in ratios]})"
    )
    # and the fold actually happened
    assert reg.fetch()["steps"] > 0


# ---------------------------------------------------------------------------
# goodput accounting across an injected-chaos rollback
# ---------------------------------------------------------------------------


def test_goodput_accounting_across_chaos_rollback(tmp_path):
    """Chaos NaNs three consecutive steps (healing after 3 hits), the
    runner rolls back past two accepted-but-unsaved steps; the
    accountant's ledger matches RunResult exactly and prices the
    discarded work."""
    acct = GoodputAccountant()
    state = {"w": jnp.zeros(())}

    def step_fn(state, batch):
        grads = {"w": jnp.ones(())}
        grads = chaos.corrupt_tree(grads, int(batch))
        skipped = bool(jnp.isnan(grads["w"]) | jnp.isinf(grads["w"]))
        if not skipped:
            state = {"w": state["w"] + grads["w"]}
        return state, {"skipped": skipped}

    with chaos.inject(
        chaos.Fault(chaos.GRADS, steps=(3, 4, 5), mode="nan", max_hits=3)
    ):
        result = run_resilient(
            step_fn,
            state,
            lambda step: step,
            directory=tmp_path / "ckpt",
            num_steps=8,
            save_interval_steps=5,  # steps 1..2 accepted but UNSAVED
            rollback_after=3,
            observer=acct,
        )

    # first pass: 0,1,2 accepted (only 0 checkpointed), 3,4,5 skipped
    # -> rollback to anchor 0; replay 1..7 accepted (faults exhausted)
    assert result.skipped_steps == 3
    assert result.rollbacks == 1
    assert result.steps_run == 13
    assert acct.skipped == result.skipped_steps
    assert acct.rollbacks == result.rollbacks
    assert acct.executed == result.steps_run
    assert acct.accepted == 10
    # rollback span 5 - 0 = 5, of which 3 were the skips: steps 1 and 2
    # were accepted work the rollback threw away
    assert acct.discarded == 2
    assert acct.goodput() == pytest.approx(8 / 13)
    # step 0's increment survived in the restored checkpoint; replayed
    # steps 1..7 added the rest — the discarded first-pass 1..2 did not
    assert float(result.state["w"]) == 8.0


def test_goodput_prices_broken_skip_streaks_exactly(tmp_path):
    """A skip streak BROKEN by an accepted step inside the rollback
    span must not be double-charged: the runner reports the exact
    accepted-but-unsaved count (here 1 — step 7), not the span-minus-
    final-streak estimate (which would say 2)."""
    acct = GoodputAccountant()

    def step_fn(state, batch):
        grads = {"w": jnp.ones(())}
        grads = chaos.corrupt_tree(grads, int(batch))
        skipped = bool(jnp.isnan(grads["w"]) | jnp.isinf(grads["w"]))
        if not skipped:
            state = {"w": state["w"] + grads["w"]}
        return state, {"skipped": skipped}

    with chaos.inject(
        chaos.Fault(chaos.GRADS, steps=(6,), mode="nan", max_hits=1),
        chaos.Fault(chaos.GRADS, steps=(8, 9, 10), mode="nan", max_hits=3),
    ):
        result = run_resilient(
            step_fn,
            {"w": jnp.zeros(())},
            lambda step: step,
            directory=tmp_path / "ckpt",
            num_steps=12,
            save_interval_steps=5,
            rollback_after=3,
            observer=acct,
        )

    # pass 1: 0..5 accepted (saved at 0 and 5), 6 skip, 7 accept
    # (unsaved), 8..10 skip -> rollback to anchor 5; replay 6..11 clean
    assert result.skipped_steps == 4
    assert result.rollbacks == 1
    assert acct.discarded == 1  # ONLY step 7 — not (span 5 - streak 3) = 2
    assert acct.executed == result.steps_run == 17
    assert acct.accepted == 13
    assert acct.goodput() == pytest.approx(12 / 17)


def test_goodput_snapshot_is_the_stable_read_api():
    """ISSUE 5 satellite: snapshot() carries the monotonic counts +
    derived fractions consumers (flight dump, fleet rows, the example's
    final goodput line) read instead of reaching into fields."""
    acct = GoodputAccountant()
    for i in range(10):
        acct.on_step(i, skipped=(i >= 8))
    acct.on_rollback(9, 5, 2, discarded=1)
    acct.on_retry("save", 1, OSError("disk"))
    snap = acct.snapshot()
    assert snap == {
        "accepted": 8, "skipped": 2, "discarded": 1, "rollbacks": 1,
        "retries": 1, "resumes": 0, "preempted": False,
        "executed": 10, "productive": 7, "goodput": 0.7,
    }
    # a snapshot is a copy, not a live view
    acct.on_step(10, skipped=False)
    assert snap["accepted"] == 8


def test_goodput_counts_checkpoint_retries(tmp_path):
    """A healing checkpoint-save fault reaches the accountant through
    the runner's retry bridge."""
    from apex_tpu.resilience import RetryPolicy

    acct = GoodputAccountant()

    def step_fn(state, batch):
        return {"n": state["n"] + 1}, None

    with chaos.inject(
        chaos.Fault(
            chaos.CHECKPOINT_SAVE, steps=(2,), mode="raise", max_hits=1
        )
    ):
        with pytest.warns(RuntimeWarning, match="checkpoint save"):
            result = run_resilient(
                step_fn,
                {"n": jnp.zeros((), jnp.int32)},
                lambda step: step,
                directory=tmp_path / "ckpt",
                num_steps=4,
                policy=RetryPolicy(
                    max_attempts=3, backoff=0.0, sleep=lambda _: None
                ),
                observer=acct,
            )
    assert result.last_step == 3
    assert acct.retries == 1
    assert acct.goodput() == 1.0  # a retried save wastes no step


# ---------------------------------------------------------------------------
# export: schema convergence with bench.py, sinks
# ---------------------------------------------------------------------------


def test_jsonl_schema_round_trips_vs_bench_line(tmp_path, capsys):
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    bench._emit("bert_large_lamb_mfu", 0.5884, "MFU", 1.1768)
    bench_line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    path = tmp_path / "metrics.jsonl"
    with JSONLSink(path) as sink:
        sink.write(bench_record("bert_large_lamb_mfu", 0.5884, "MFU", 1.1768))
    ours = json.loads(path.read_text())

    assert ours == bench_line
    assert list(ours) == ["metric", "value", "unit", "vs_baseline"]


def test_jsonl_sink_writes_nonfinite_as_null(tmp_path):
    """NaN grad norms / untouched ±inf min-max seeds must not produce
    bare NaN tokens (invalid JSON for jq/JS consumers)."""
    path = tmp_path / "nan.jsonl"
    with JSONLSink(path) as sink:
        sink.write(bench_record("guard/grad_norm", float("nan"), "", None))
        sink.write(bench_record("m/min", float("inf"), "", None, step=2))
    lines = path.read_text().splitlines()
    assert "NaN" not in lines[0] and "Infinity" not in lines[1]
    assert json.loads(lines[0])["value"] is None
    assert json.loads(lines[1])["value"] is None
    assert json.loads(lines[1])["step"] == 2


def test_reporter_merges_sources_and_steps(tmp_path):
    reg = MetricRegistry(fetch_every=1)
    reg.gauge("train/loss", unit="nats")
    state = reg.update(reg.init(), {"train/loss": jnp.float32(2.5)})
    reg.observe(0, state)
    reg.fetch()

    clockv = [0.0]

    def clock():
        return clockv[0]

    meter = StepMeter(
        tokens_per_step=128,
        flops_per_step=transformer_train_flops(1000, 128),
        peak_flops=1e12,
        clock=clock,
    )
    for _ in range(3):
        meter.tick()
        clockv[0] += 0.25

    acct = GoodputAccountant()
    acct.on_step(0, skipped=False)
    acct.on_step(1, skipped=True)

    path = tmp_path / "telemetry.jsonl"
    with Reporter(
        [JSONLSink(path)], registry=reg, meter=meter, goodput=acct,
        include_board=False,
    ) as rep:
        values = rep.report(7)

    recs = [json.loads(line) for line in path.read_text().splitlines()]
    by_metric = {r["metric"]: r for r in recs}
    assert values["train/loss"] == 2.5
    assert by_metric["train/loss"]["unit"] == "nats"
    assert all(r["step"] == 7 for r in recs)
    assert by_metric["train/step_time_ms"]["value"] == pytest.approx(250.0)
    assert by_metric["train/goodput"]["value"] == 0.5
    assert by_metric["train/mfu"]["value"] == pytest.approx(
        6 * 1000 * 128 / (0.25 * 1e12)
    )
    # every line is the bench schema + step
    for r in recs:
        assert list(r)[:4] == ["metric", "value", "unit", "vs_baseline"]


def test_csv_sink_fixed_header(tmp_path):
    path = tmp_path / "m.csv"
    with CSVSink(path) as sink:
        sink.write(bench_record("a", 1, "u", None, step=0))
        sink.write(bench_record("b", 2, "u", None, step=1, extra="dropped"))
    lines = path.read_text().strip().splitlines()
    assert lines[0] == "metric,value,unit,vs_baseline,step"
    assert len(lines) == 3 and "dropped" not in lines[2]


def test_tensorboard_sink_valid_tfrecord_framing(tmp_path):
    with TensorBoardSink(tmp_path) as sink:
        sink.write(bench_record("train/loss", 2.5, "", None, step=3))
        sink.add_scalars(4, {"train/mfu": 0.5})
        path = sink.path
    data = open(path, "rb").read()
    events = []
    off = 0
    while off < len(data):
        (length,) = struct.unpack_from("<Q", data, off)
        (len_crc,) = struct.unpack_from("<I", data, off + 8)
        assert len_crc == _masked_crc(data[off:off + 8])
        payload = data[off + 12:off + 12 + length]
        (payload_crc,) = struct.unpack_from("<I", data, off + 12 + length)
        assert payload_crc == _masked_crc(payload)
        events.append(payload)
        off += 12 + length + 4
    assert len(events) == 3  # file_version + two scalar events
    assert b"brain.Event:2" in events[0]
    assert b"train/loss" in events[1] and b"train/mfu" in events[2]


# ---------------------------------------------------------------------------
# comm gauges on the board
# ---------------------------------------------------------------------------


def test_sync_gradients_publishes_board_gauges(eight_devices):
    board.clear()
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    tree = {"w": jnp.ones((4096,)), "b": jnp.ones((8,))}
    fn = jax.jit(
        jax.shard_map(
            lambda t: comm.sync_gradients(t, wire="int8", chunks=2),
            mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False,
        )
    )
    hlo = fn.lower(tree).compile().as_text()
    summary = comm.collective_summary(hlo)
    snap = board.snapshot()

    assert snap["comm/sync/wire"] == "int8"
    assert snap["comm/sync/bucket_elements"] == 4096
    # the trace-time plan matches the compiled program's collectives:
    # chunked all_to_all (reduce-scatter phase) + all_gather phase, and
    # one exact psum for the small leaf
    assert (
        snap["comm/rs/collectives"]
        == summary.get("all-to-all", {}).get("count", 0)
    )
    assert (
        snap["comm/ag/collectives"]
        == summary.get("all-gather", {}).get("count", 0)
    )
    assert (
        snap["comm/sync/psum_leaves"]
        == summary.get("all-reduce", {}).get("count", 0)
    )

    comm.publish_collective_summary(summary, world=8)
    snap = board.snapshot()
    assert snap["comm/hlo/all_to_all_count"] == snap["comm/rs/collectives"]
    assert snap["comm/hlo/ring_wire_bytes"] == comm.ring_wire_bytes(
        summary, 8
    )
    board.clear()


# ---------------------------------------------------------------------------
# trace scheduling
# ---------------------------------------------------------------------------


def test_parse_trace_spec_forms():
    assert parse_trace_spec("120+3") == (120, 122, None)
    assert parse_trace_spec("5..9") == (5, 9, None)
    assert parse_trace_spec("7") == (7, 7, None)
    assert parse_trace_spec("4+2:/tmp/prof") == (4, 5, "/tmp/prof")
    with pytest.raises(ValueError):
        parse_trace_spec("banana")
    with pytest.raises(ValueError):
        parse_trace_spec("9..4")


def test_trace_scheduler_window(tmp_path):
    calls = []
    sched = TraceScheduler(
        "5+2", base_dir=str(tmp_path),
        _start_fn=lambda d: calls.append(("start", d)),
        _stop_fn=lambda: calls.append(("stop",)),
    )
    for step in range(10):
        sched.on_step(step)
    sched.stop()
    expect_dir = window_dir(str(tmp_path), 5, 6)
    assert calls == [("start", expect_dir), ("stop",)]
    assert os.path.isdir(expect_dir)
    assert not sched.active  # one window per arming

    idle = TraceScheduler(spec="", base_dir=str(tmp_path))
    for step in range(3):
        idle.on_step(step)  # cheap no-ops
    assert not idle.active


def test_trace_scheduler_rearms_after_rollback_rewind(tmp_path):
    """A rollback replay rewinding steps mid-window aborts the capture
    and retakes the window cleanly on the replay pass."""
    calls = []
    sched = TraceScheduler(
        "5+3", base_dir=str(tmp_path),
        _start_fn=lambda d: calls.append("start"),
        _stop_fn=lambda: calls.append("stop"),
    )
    for step in (0, 1, 2, 3, 4, 5, 6):  # window arms at 5
        sched.on_step(step)
    assert calls == ["start"]
    for step in (3, 4, 5, 6, 7, 8):  # rollback replay from step 3
        sched.on_step(step)
    # rewind to 3 aborts; the replay reaches 5 and recaptures 5..7
    assert calls == ["start", "stop", "start", "stop"]
    assert not sched.active and not sched.tracing

    # a rollback anchor INSIDE the window must not restart mid-window —
    # a partial capture under a dir named for the full range would lie
    calls2 = []
    s2 = TraceScheduler(
        "5+3", base_dir=str(tmp_path),
        _start_fn=lambda d: calls2.append("start"),
        _stop_fn=lambda: calls2.append("stop"),
    )
    for step in (4, 5, 6):
        s2.on_step(step)
    for step in (6, 7, 8, 9):  # replay from inside the window
        s2.on_step(step)
    assert calls2 == ["start", "stop"]


def test_profiling_shim_still_exports():
    """apex_tpu.utils.profiling stays import-compatible after the move,
    and the package attribute `observability.trace` is the SUBMODULE
    (the trace() function is deliberately not re-exported — it would
    shadow the submodule)."""
    import importlib
    import types

    import apex_tpu.observability as obs

    profiling = importlib.import_module("apex_tpu.utils.profiling")
    obs_trace = obs.trace
    assert isinstance(obs_trace, types.ModuleType)
    assert obs_trace is sys.modules["apex_tpu.observability.trace"]

    for name in ("annotate", "nvtx_range", "range_push", "range_pop",
                 "trace"):
        assert getattr(profiling, name) is getattr(obs_trace, name)
    import apex_tpu.utils as utils

    assert utils.trace is obs_trace.trace
