"""Pallas kernel static analyzer (apex_tpu.analysis.kernels, ISSUE 10).

Each pass gets a planted-defect fixture asserting the EXACT rule id,
plus a clean-kernel zero-findings fixture; the VMEM model is validated
against captured real ``pallas_call`` arguments (the interpret-mode
call path) across >6 tile configs; the FLOP model is validated against
the dots actually traced into the kernel jaxprs; and the prune/ranking
acceptance runs against the recorded v5e sweep fixture
(tests/data/attn_sweep_r05.json): >=30% of the default grid
eliminated, every cell within 5% of the measured best retained.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import analysis
from apex_tpu.analysis import kernels as ka
from apex_tpu.ops.pallas import decode_attention as da
from apex_tpu.ops.pallas import flash_attention as fa
from apex_tpu.ops.pallas import layer_norm as ln
from apex_tpu.ops.pallas import tune_cache
from apex_tpu.ops.pallas.introspect import (
    BlockArg,
    KernelSpec,
    buffer_bytes,
    dtype_width,
)

DATA = os.path.join(os.path.dirname(__file__), "data")
V5E = "TPU v5 lite"


def fwd_specs(bh, sq, sk, d, **kw):
    kw.setdefault("modes", ("fwd",))
    return fa.kernel_specs(bh, sq, sk, d, **kw)


# ---------------------------------------------------------------------------
# VMEM model vs the real pallas_call (the +-10% acceptance)
# ---------------------------------------------------------------------------


class TestVmemModel:
    # 7 (block_q, block_k) configs at the flash fwd kernel — the
    # acceptance criterion asks for >= 6
    CONFIGS = [
        (128, 128), (128, 256), (256, 128), (256, 256),
        (512, 256), (256, 512), (512, 512),
    ]

    def _captured_bytes(self, monkeypatch, bq, bk, sq=512, d=64, bh=2):
        """Trace the REAL flash_fwd (the interpret-mode call path) with
        a spying pallas_call and rebuild its block+scratch bytes from
        the captured arguments."""
        captured = {}
        real = fa.pl.pallas_call

        def spy(kernel, **kw):
            captured.update(kw)
            return real(kernel, **kw)

        monkeypatch.setattr(fa.pl, "pallas_call", spy)
        q = jnp.zeros((bh, sq, d), jnp.bfloat16)
        jax.eval_shape(
            lambda q, k, v: fa.flash_fwd(
                q, k, v, None, scale=1.0, causal=True,
                block_q=bq, block_k=bk,
            ),
            q, q, q,
        )
        assert captured, "pallas_call was never traced"
        in_dtypes = ["bfloat16"] * 3
        blocks = 0
        for spec, dt in zip(captured["in_specs"], in_dtypes):
            blocks += int(np.prod(spec.block_shape)) * dtype_width(dt)
        for spec, sd in zip(captured["out_specs"], captured["out_shape"]):
            blocks += (
                int(np.prod(spec.block_shape))
                * dtype_width(np.dtype(sd.dtype).name)
            )
        scratch = sum(
            int(np.prod(ref.shape)) * dtype_width(np.dtype(ref.dtype).name)
            for ref in captured["scratch_shapes"]
        )
        return 2 * blocks + scratch

    @pytest.mark.parametrize("bq,bk", CONFIGS)
    def test_model_within_10pct_of_captured_call(self, monkeypatch, bq, bk):
        ref = self._captured_bytes(monkeypatch, bq, bk)
        (spec,) = fwd_specs(2, 512, 512, 64, block_q=bq, block_k=bk)
        fp = ka.vmem_footprint(spec)
        model = fp["block_bytes"] + fp["scratch_bytes"]
        assert abs(model - ref) <= 0.10 * ref, (model, ref, bq, bk)

    def test_footprint_terms(self):
        (spec,) = fwd_specs(2, 512, 512, 64, block_q=256, block_k=256)
        fp = ka.vmem_footprint(spec)
        # q/k/v bf16 blocks + o bf16 + lse f32, double-buffered
        blk = 2 * (3 * 256 * 64 * 2 + 256 * 64 * 2 + 256 * 128 * 4)
        assert fp["block_bytes"] == blk
        # acc (256,64) + m/l (256,128) f32
        assert fp["scratch_bytes"] == (256 * 64 + 2 * 256 * 128) * 4
        # one (bq, bk) f32 score value at fwd steady state
        assert fp["intermediate_bytes"] == 256 * 256 * 4
        assert fp["total_bytes"] == sum(
            fp[k] for k in
            ("block_bytes", "scratch_bytes", "intermediate_bytes")
        )

    def test_oversized_block_is_vmem_overflow(self):
        # a (4096, 4096) f32 score tile is 64 MiB — dead on arrival
        specs = fwd_specs(
            2, 4096, 4096, 128, block_q=4096, block_k=4096,
        )
        report = ka.analyze(specs, device_kind=V5E)
        assert "kernel-vmem-overflow" in {
            f.rule for f in report.errors()
        }

    def test_beyond_edge_probe_stays_feasible(self):
        # docs/flash-roofline.md: a (1024, 2048) fwd score tile (8 MiB)
        # is "comfortably inside v5e's budget" — the ROADMAP's
        # 2048-wide probe must NOT be vmem-pruned; (2048, 2048)'s
        # 16 MiB score tile alone busts the budget and must be
        specs = fwd_specs(
            8, 16384, 16384, 128, block_q=1024, block_k=2048,
        )
        assert ka.analyze(specs, device_kind=V5E).errors() == []
        specs = fwd_specs(
            8, 16384, 16384, 128, block_q=2048, block_k=2048,
        )
        assert ka.analyze(specs, device_kind=V5E).by_rule(
            "kernel-vmem-overflow"
        )

    def test_budget_override(self):
        (spec,) = fwd_specs(2, 512, 512, 64, block_q=256, block_k=256)
        assert ka.analyze([spec], vmem_budget=1 << 30).ok()
        over = ka.analyze([spec], vmem_budget=1 << 16)
        assert over.by_rule("kernel-vmem-overflow")


# ---------------------------------------------------------------------------
# FLOP model vs the dots actually traced into the kernels
# ---------------------------------------------------------------------------


def _dot_flops(eqn):
    (cl, cr), (bl, br) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    k = int(np.prod([lhs[i] for i in cl])) if cl else 1
    b = int(np.prod([lhs[i] for i in bl])) if bl else 1
    m = int(np.prod(
        [s for i, s in enumerate(lhs) if i not in cl and i not in bl]
    ))
    n = int(np.prod(
        [s for i, s in enumerate(rhs) if i not in cr and i not in br]
    ))
    return 2.0 * b * m * n * k


def _pallas_kernel_dot_flops(jaxpr):
    """name -> per-cell dot FLOPs of every pallas_call in a jaxpr."""
    out = []
    for eqn in analysis.iter_eqns(jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        kernel_jaxpr = eqn.params["jaxpr"]
        flops = sum(
            _dot_flops(e) for e in analysis.iter_eqns(kernel_jaxpr)
            if e.primitive.name == "dot_general"
        )
        out.append(flops)
    return out


class TestFlopModel:
    def test_fwd_flops_match_traced_dots(self):
        q = jnp.zeros((2, 512, 64), jnp.bfloat16)
        jaxpr = jax.make_jaxpr(
            lambda q, k, v: fa.flash_fwd(
                q, k, v, None, scale=1.0, causal=True,
                block_q=256, block_k=128,
            )
        )(q, q, q)
        (traced,) = _pallas_kernel_dot_flops(jaxpr)
        (spec,) = fwd_specs(2, 512, 512, 64, block_q=256, block_k=128)
        assert abs(spec.flops_per_cell - traced) <= 0.10 * traced

    def test_bwd_flops_match_traced_dots(self):
        q = jnp.zeros((2, 512, 64), jnp.bfloat16)
        o = jnp.zeros_like(q)
        lse = jnp.zeros((2, 512, 128), jnp.float32)
        jaxpr = jax.make_jaxpr(
            lambda q, k, v, o, lse: fa.flash_bwd(
                q, k, v, o, lse, o, None, scale=1.0, causal=True,
                block_q=256, block_k=256,
            )
        )(q, q, q, o, lse)
        dkdv_traced, dq_traced = _pallas_kernel_dot_flops(jaxpr)
        dkdv, dq = fa.kernel_specs(
            2, 512, 512, 64, block_q=256, block_k=256,
            modes=("dkdv", "dq"),
        )
        assert abs(dkdv.flops_per_cell - dkdv_traced) <= 0.10 * dkdv_traced
        assert abs(dq.flops_per_cell - dq_traced) <= 0.10 * dq_traced


# ---------------------------------------------------------------------------
# Tiling-alignment lint
# ---------------------------------------------------------------------------


class TestTilingPass:
    def test_96_wide_block_is_tile_misaligned(self):
        # 1536 % 96 == 0, so only the MXU 128-alignment rule can (and
        # must) catch it — the satellite's planted defect
        specs = fwd_specs(
            2, 1536, 1536, 128, causal=False, block_q=96, block_k=96,
        )
        report = ka.analyze(specs, device_kind=V5E)
        assert "kernel-tile-misaligned" in report.rule_ids()

    def test_ragged_tail_is_tile_misaligned_error(self):
        # 100 neither divides 512 nor is sublane-aligned for bf16
        specs = fwd_specs(
            2, 512, 512, 64, causal=False, block_q=100, block_k=128,
        )
        report = ka.analyze(specs, device_kind=V5E)
        ragged = report.by_rule("kernel-tile-misaligned")
        assert ragged and any(f.severity == "error" for f in ragged)
        assert any("does not divide" in f.message for f in ragged)

    def test_full_axis_blocks_exempt(self):
        # d=64 trailing blocks and (br, 1) stat blocks cover their
        # whole axis — the shipped kernels must not self-flag
        report = ka.analyze(
            fwd_specs(2, 512, 512, 64, block_q=256, block_k=256)
            + ln.kernel_specs(4096, 1024),
            device_kind=V5E,
        )
        assert report.by_rule("kernel-tile-misaligned") == []


# ---------------------------------------------------------------------------
# Grid coverage / race
# ---------------------------------------------------------------------------


def _synthetic_spec(out_map, semantics=("parallel", "arbitrary"),
                    grid=(2, 2)):
    out = BlockArg(
        name="o", shape=(4, 128, 128), block=(1, 128, 128),
        index_map=out_map, dtype="float32",
    )
    inp = BlockArg(
        name="x", shape=(4, 128, 128), block=(1, 128, 128),
        index_map=lambda i, j: (i, 0, 0), dtype="float32",
    )
    return KernelSpec(
        name="synthetic", grid=grid, inputs=(inp,), outputs=(out,),
        dimension_semantics=semantics,
    )


class TestCoveragePass:
    def test_oob_index_map(self):
        spec = _synthetic_spec(lambda i, j: (i + 3, 0, 0))
        report = ka.analyze(spec, device_kind=V5E)
        assert "kernel-grid-oob" in {f.rule for f in report.errors()}

    def test_parallel_overlap_is_block_race(self):
        # both parallel-axis cells write block (0, ...) — the planted
        # overlapping-index-map defect
        spec = _synthetic_spec(
            lambda i, j: (0, 0, 0), semantics=("parallel", "parallel"),
        )
        report = ka.analyze(spec, device_kind=V5E)
        assert "kernel-block-race" in {f.rule for f in report.errors()}

    def test_arbitrary_axis_revisit_is_not_a_race(self):
        # the flash kernels' accumulate-over-j pattern: the output
        # block ignores the ARBITRARY axis — sanctioned, no finding
        spec = _synthetic_spec(lambda i, j: (i, 0, 0))
        report = ka.analyze(spec, device_kind=V5E)
        assert report.by_rule("kernel-block-race") == []
        assert report.by_rule("kernel-grid-oob") == []

    def test_decode_page_table_out_of_pool(self):
        # a page id beyond the pool is an OOB DMA the coverage pass
        # must catch through the REAL scalar-prefetch index map
        bad_table = np.full((2, 4), 99, np.int32)  # pool has 8 pages
        (spec,) = da.kernel_specs(
            2, 4, 128, pool_pages=8, page=16, pages_per_seq=4,
            page_table=bad_table,
        )
        report = ka.analyze(spec, device_kind=V5E)
        assert "kernel-grid-oob" in {f.rule for f in report.errors()}

    def test_shipped_kernels_cover_cleanly(self):
        specs = (
            fa.kernel_specs(2, 512, 512, 64, block_q=128, block_k=128)
            + ln.kernel_specs(2048, 768)
            + da.kernel_specs(
                2, 4, 128, pool_pages=8, page=16, pages_per_seq=4,
            )
        )
        report = ka.analyze(specs, device_kind=V5E)
        assert report.by_rule("kernel-grid-oob") == []
        assert report.by_rule("kernel-block-race") == []


# ---------------------------------------------------------------------------
# Causal dead tiles
# ---------------------------------------------------------------------------


class TestDeadTiles:
    def test_hand_checkable_stats(self):
        # seq 4, 2x2 tiles of 2: live {(0,0),(1,0),(1,1)}; causal pairs
        # = 10 of the 12 executed elements -> waste 1/6
        (spec,) = fwd_specs(1, 4, 4, 8, block_q=2, block_k=2)
        stats = ka.dead_tile_stats(spec)
        assert stats["total_tiles"] == 4
        assert stats["live_tiles"] == 3
        assert stats["dead_tiles"] == 1
        assert stats["waste_fraction"] == pytest.approx(1 / 6)

    def test_non_causal_has_no_stats(self):
        (spec,) = fwd_specs(
            1, 256, 256, 64, causal=False, block_q=128, block_k=128,
        )
        assert ka.dead_tile_stats(spec) is None

    def test_naive_causal_config_flags_dead_tiles(self):
        # 2 tiles per side: boundary tiles pay ~33% masked FLOPs
        specs = fwd_specs(1, 1024, 1024, 64, block_q=512, block_k=512)
        report = ka.analyze(
            specs, device_kind=V5E, dead_tile_threshold=0.25,
        )
        assert "kernel-dead-tiles" in report.rule_ids()
        assert all(
            f.severity == "warning"
            for f in report.by_rule("kernel-dead-tiles")
        )

    def test_default_config_under_ci_bound(self):
        # the verify_tier1 pin: tuned long-shape tiles waste < 15%
        specs = fa.kernel_specs(8, 16384, 16384, 128, causal=True)
        for spec in specs:
            stats = ka.dead_tile_stats(spec)
            assert stats["waste_fraction"] < 0.15, (spec.name, stats)


# ---------------------------------------------------------------------------
# Roofline / byte model
# ---------------------------------------------------------------------------


class TestRoofline:
    def test_fetch_counts_replay_the_pipeline(self):
        # grid (bh, nq, nk) row-major: q re-fetched per (bh, i), k/v
        # per cell, o written once per (bh, i)
        (spec,) = fwd_specs(2, 512, 512, 64, block_q=128, block_k=256)
        by_name = {a.name: a for a in spec.inputs + spec.outputs}
        assert ka._fetch_count(by_name["q"], spec.grid) == 2 * 4
        assert ka._fetch_count(by_name["k"], spec.grid) == 2 * 4 * 2
        assert ka._fetch_count(by_name["o"], spec.grid) == 2 * 4

    def test_fetch_count_dependence_probe_on_huge_grid(self):
        arg = BlockArg(
            name="x", shape=(1 << 20, 128), block=(1, 128),
            index_map=lambda i, j, k: (i, 0), dtype="float32",
        )
        # 2^21 cells >> the simulation cap; the probe sees dependence
        # on axis 0 only -> one fetch per axis-0 value
        assert ka._fetch_count(arg, (1 << 19, 2, 2)) == 1 << 19
        assert ka._fetch_count(arg, (1 << 19, 2, 2)) == 1 << 19

    def test_roofline_fields(self):
        (spec,) = fwd_specs(2, 512, 512, 64, block_q=128, block_k=128)
        r = ka.roofline(spec, device_kind=V5E)
        assert r["flops"] > 0 and r["bytes"] > 0
        assert r["ceiling_tflops"] <= 197.0 + 1e-9
        assert r["bound"] in ("compute", "memory", "grid")
        assert r["predicted_tflops"] <= r["ceiling_tflops"] + 1e-9

    def test_larger_tiles_predict_faster_at_long_context(self):
        # the measured r05 fact the model must reproduce: (1024, 1024)
        # beats (128, 128) at the long shape
        def t(b):
            specs = fwd_specs(
                8, 16384, 16384, 128, block_q=b, block_k=b,
            )
            return ka.predict_config(specs, device_kind=V5E)["time_s"]

        assert t(1024) < t(512) < t(128)


# ---------------------------------------------------------------------------
# Prune acceptance on the recorded sweep fixture
# ---------------------------------------------------------------------------


class TestPruneRecordedSweep:
    @pytest.fixture(scope="class")
    def fixture(self):
        with open(os.path.join(DATA, "attn_sweep_r05.json")) as f:
            return json.load(f)

    @pytest.mark.parametrize("shape", ["long", "mha"])
    def test_prune_eliminates_30pct_and_keeps_the_best(
        self, fixture, shape
    ):
        from tools import attn_tune

        sweep = next(
            s for s in fixture["sweeps"] if s["shape"] == shape
        )
        measured = {
            tuple(int(x) for x in cell.split(",")): tflops
            for cell, tflops in sweep["cells"].items()
        }
        verdicts = attn_tune._prune_verdicts(
            shape, sweep["mode"], sweep["blocks"], 1.5, fixture["chip"]
        )
        assert set(verdicts) == set(measured)
        kept = {
            c for c, (v, _, _) in verdicts.items() if v == "KEEP"
        }
        pruned = len(verdicts) - len(kept)
        # >= 30% of the default sweep grid eliminated...
        assert pruned >= 0.3 * len(verdicts), (pruned, len(verdicts))
        # ...while every config within 5% of the measured best survives
        best = max(measured.values())
        within = {c for c, m in measured.items() if m >= 0.95 * best}
        assert within <= kept, (within, kept)

    def test_dq_only_prune_prices_the_dq_kernel_alone(self):
        """The bwd-only phase-2 sweep varies dq tiles with dkdv
        pinned: its keep set must come from a dq-only prediction, not
        the combined dkdv+dq one (a cell with a slow dkdv can hold
        the best dq tile)."""
        from tools import attn_tune

        combined = attn_tune._prune_verdicts(
            "tiny", "bwd-only", [128, 256], 1e9, V5E
        )
        dq_only = attn_tune._prune_verdicts(
            "tiny", "dq-only", [128, 256], 1e9, V5E
        )
        assert set(combined) == set(dq_only)
        for cell in dq_only:
            # dq-only predictions price strictly less work
            assert (
                dq_only[cell][1]["time_s"]
                < combined[cell][1]["time_s"]
            )

    def test_infeasible_cells_prune_regardless_of_speed(self):
        from tools import attn_tune

        verdicts = attn_tune._prune_verdicts(
            "long", "fwd", [1024, 4096], 1e9, V5E
        )
        verdict, _, reason = verdicts[(4096, 4096)]
        assert verdict == "PRUNE" and "infeasible" in reason
        assert "kernel-vmem-overflow" in reason


# ---------------------------------------------------------------------------
# Tuning cache round-trips
# ---------------------------------------------------------------------------


class TestTuneCache:
    @pytest.fixture(autouse=True)
    def _clean(self, monkeypatch):
        monkeypatch.delenv(tune_cache.ENV_VAR, raising=False)
        tune_cache.reset()
        yield
        tune_cache.reset()

    def _arm(self, monkeypatch, tmp_path, data):
        path = tmp_path / "tune_cache.json"
        path.write_text(json.dumps(data))
        monkeypatch.setenv(tune_cache.ENV_VAR, str(path))
        tune_cache.reset()
        return str(path)

    def test_flash_round_trip(self, monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path, {
            "version": 1,
            "flash_attention": [{
                "sq": 4096, "d": 64, "causal": True, "dtype": None,
                "backend": None,
                "tiles": {"fwd": [512, 1024], "bwd": [256, 512]},
            }],
        })
        assert fa._tuned_tile("fwd", 4096, 4096, 64, True) == (512, 1024)
        assert fa._tuned_tile("bwd", 4096, 4096, 64, True) == (256, 512)
        # no entry for this mode / shape -> (None, None)
        assert fa._tuned_tile("bwd_dq", 4096, 4096, 64, True) == (None, None)
        assert fa._tuned_tile("fwd", 8192, 8192, 64, True) == (None, None)

    def test_cached_tile_must_divide_the_axis(self, monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path, {
            "flash_attention": [{
                "sq": 4096, "d": 64, "causal": True,
                "tiles": {"fwd": [512, 1024]},
            }],
        })
        # cross-attention sk=768: the cached bk=1024 cannot tile it
        assert fa._tuned_tile("fwd", 4096, 768, 64, True) == (512, None)

    def test_cache_wins_over_source_table(self, monkeypatch, tmp_path):
        # (16384, 128, True) is a committed _TUNED_TILES entry
        assert fa._tuned_tile("fwd", 16384, 16384, 128, True) == \
            (1024, 1024)
        self._arm(monkeypatch, tmp_path, {
            "flash_attention": [{
                "sq": 16384, "d": 128, "causal": True,
                "tiles": {"fwd": [512, 512]},
            }],
        })
        assert fa._tuned_tile("fwd", 16384, 16384, 128, True) == (512, 512)

    def test_backend_mismatch_falls_through(self, monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path, {
            "flash_attention": [{
                "sq": 4096, "d": 64, "causal": True,
                "backend": "TPU v999",
                "tiles": {"fwd": [512, 512]},
            }],
        })
        assert fa._tuned_tile("fwd", 4096, 4096, 64, True) == (None, None)

    def test_layer_norm_round_trip(self, monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path, {
            "layer_norm": [{"hidden": 4096, "block_rows": 16}],
        })
        assert ln._block_rows(16384, 4096) == 16
        # uncached hidden falls back to the source table
        assert ln._block_rows(16384, 1024) == \
            ln._TUNED_BLOCK_ROWS[1024]

    def test_dispatch_uses_cached_tile(self, monkeypatch, tmp_path):
        """End to end: the cache entry changes the block shape of the
        REAL traced pallas_call."""
        self._arm(monkeypatch, tmp_path, {
            "flash_attention": [{
                "sq": 640, "d": 64, "causal": False,
                "tiles": {"fwd": [64, 128]},
            }],
        })
        captured = {}
        real = fa.pl.pallas_call

        def spy(kernel, **kw):
            captured.update(kw)
            return real(kernel, **kw)

        monkeypatch.setattr(fa.pl, "pallas_call", spy)
        q = jnp.zeros((1, 640, 64), jnp.bfloat16)
        jax.eval_shape(
            lambda q, k, v: fa.flash_fwd(
                q, k, v, None, scale=1.0, causal=False
            ),
            q, q, q,
        )
        assert captured["in_specs"][0].block_shape == (1, 64, 64)
        assert captured["in_specs"][1].block_shape == (1, 128, 64)

    def test_update_flash_merge_write(self, tmp_path, monkeypatch):
        path = str(tmp_path / "cache.json")
        tune_cache.update_flash(
            path, sq=2048, d=64, causal=True,
            tiles={"fwd": (1024, 1024)},
        )
        tune_cache.update_flash(
            path, sq=2048, d=64, causal=True,
            tiles={"fwd": (512, 512), "bwd": (256, 1024)},
        )
        tune_cache.update_flash(
            path, sq=4096, d=64, causal=True,
            tiles={"fwd": (256, 256)},
        )
        data = json.loads(open(path).read())
        assert len(data["flash_attention"]) == 2  # same-key merged
        monkeypatch.setenv(tune_cache.ENV_VAR, path)
        tune_cache.reset()
        assert fa._tuned_tile("fwd", 2048, 2048, 64, True) == (512, 512)
        assert fa._tuned_tile("bwd", 2048, 2048, 64, True) == (256, 1024)
        assert fa._tuned_tile("fwd", 4096, 4096, 64, True) == (256, 256)

    def test_bwd_write_keeps_the_fwd_winner(self, tmp_path, monkeypatch):
        """The default attn_tune --cache-out flow: a fwd sweep's write
        followed by a bwd sweep's write to the SAME key must
        accumulate tile modes, not clobber."""
        path = str(tmp_path / "cache.json")
        tune_cache.update_flash(
            path, sq=2048, d=64, causal=True,
            tiles={"fwd": (1024, 1024)},
        )
        tune_cache.update_flash(
            path, sq=2048, d=64, causal=True,
            tiles={"bwd": (256, 1024), "bwd_dq": (512, 512)},
        )
        monkeypatch.setenv(tune_cache.ENV_VAR, path)
        tune_cache.reset()
        assert fa._tuned_tile("fwd", 2048, 2048, 64, True) == (1024, 1024)
        assert fa._tuned_tile("bwd", 2048, 2048, 64, True) == (256, 1024)
        assert fa._tuned_tile("bwd_dq", 2048, 2048, 64, True) == (512, 512)

    def test_malformed_cache_warns_and_is_ignored(
        self, monkeypatch, tmp_path
    ):
        path = tmp_path / "bad.json"
        path.write_text("{not json")
        monkeypatch.setenv(tune_cache.ENV_VAR, str(path))
        tune_cache.reset()
        with pytest.warns(UserWarning, match="malformed tuning cache"):
            assert tune_cache.flash_tiles("fwd", 2048, 64, True) is None
        # and dispatch falls back to the source table untouched
        assert fa._tuned_tile("fwd", 16384, 16384, 128, True) == \
            (1024, 1024)


# ---------------------------------------------------------------------------
# Defaults, report plumbing, board publication
# ---------------------------------------------------------------------------


class TestDefaultsAndReport:
    def test_default_kernels_are_clean(self):
        report = ka.analyze_default_kernels(device_kind=V5E)
        assert report.findings == [], report.render()
        assert set(report.rules_run) == set(ka.KERNEL_PASSES)
        names = {e["name"] for e in report.sections["kernels"]}
        assert names == {
            "flash_fwd", "flash_bwd_dkdv", "flash_bwd_dq",
            "layer_norm_fwd", "layer_norm_bwd", "paged_decode_fwd",
        }
        for e in report.sections["kernels"]:
            assert e["vmem"]["total_bytes"] <= e["vmem_budget_bytes"]

    def test_pass_timings_recorded(self):
        report = ka.analyze_default_kernels(device_kind=V5E)
        for name in ka.KERNEL_PASSES:
            assert name in report.pass_timings

    def test_rules_are_cataloged(self):
        for rule in (
            "kernel-vmem-overflow", "kernel-tile-misaligned",
            "kernel-grid-oob", "kernel-block-race",
            "kernel-dead-tiles", "kernel-hardcoded-block",
        ):
            assert rule in analysis.RULES

    def test_publish_kernel_report_gauges_the_board(self):
        from apex_tpu.observability.metrics import board

        report = ka.analyze_default_kernels(device_kind=V5E)
        ka.publish_kernel_report(report)
        snap = board.snapshot()
        assert snap["analysis/kernels/errors"] == 0
        assert snap["analysis/kernels/flash_fwd/vmem_bytes"] > 0
        assert snap["analysis/kernels/flash_fwd/predicted_tflops"] > 0
        assert 0 < snap["analysis/kernels/flash_fwd/dead_tile_waste"] < 0.15


# ---------------------------------------------------------------------------
# repo_lint source rule (the kernel-hardcoded-block satellite)
# ---------------------------------------------------------------------------


def test_repo_lint_kernel_hardcoded_block():
    from tools import repo_lint

    planted = [
        "o, lse = fa.flash_fwd(q, k, v, None, scale=s,",
        "                      block_q=128, block_k=block)",
    ]
    got = repo_lint._kernel_violations("x/m.py", planted, jitted=True)
    assert len(got) == 1 and got[0][1] == 2
    assert "tuned-tile lookup" in got[0][3]

    # variable-valued plumbing and None defaults never match
    clean = [
        "def flash_fwd(q, k, v, *, block_q=None, block_k=None):",
        "    fa.flash_fwd(q, k, v, None, block_q=bq, block_k=bk)",
    ]
    assert repo_lint._kernel_violations("x/m.py", clean, True) == []
    # host-side files (tuners, tests) are exempt
    assert repo_lint._kernel_violations("x/m.py", planted, False) == []
    # the waiver comment works like every other repo_lint rule
    waived = ["flash_fwd(q, k, v, block_q=128)  # repo-lint: allow why"]
    assert repo_lint._kernel_violations("x/m.py", waived, True) == []
