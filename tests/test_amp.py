"""≙ tests/L0/run_amp — opt-level matrix, loss scaling, overflow skip,
checkpointing (state_dict round trip), master weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, fp16_utils
from apex_tpu.optimizers import fused_adam, fused_sgd


def toy_params():
    return {
        "w": jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32),
        "b": jnp.zeros((4,), jnp.float32),
    }


def test_opt_levels_table():
    levels = amp.opt_levels(jnp.float16)
    assert levels["O0"].cast_model_type is None
    assert levels["O0"].loss_scale == 1.0
    assert levels["O1"].compute_dtype == jnp.float16
    assert levels["O1"].loss_scale == "dynamic"
    assert levels["O2"].master_weights
    assert levels["O2"].cast_model_type == jnp.float16
    assert levels["O3"].loss_scale == 1.0
    # bf16 (TPU default): no dynamic scaling needed
    bf = amp.opt_levels(jnp.bfloat16)
    assert bf["O1"].loss_scale == 1.0
    assert bf["O2"].cast_model_type == jnp.bfloat16


def test_policy_casting():
    p = amp.Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16)
    tree = {"w": jnp.ones((2,), jnp.float32), "step": jnp.zeros((), jnp.int32)}
    c = p.cast_to_compute(tree)
    assert c["w"].dtype == jnp.bfloat16
    assert c["step"].dtype == jnp.int32  # non-floats untouched


def test_initialize_rejects_bad_level():
    with pytest.raises(ValueError):
        amp.initialize(toy_params(), fused_adam(1e-3), opt_level="O4")


def test_dynamic_scaler_growth_and_backoff():
    s = amp.DynamicLossScaler(
        init_scale=1024.0, growth_interval=3, hysteresis=2
    )
    st = s.init()
    one = jnp.zeros(())
    inf = jnp.ones(())
    # two clean steps: no growth yet
    st = s.update(st, one)
    st = s.update(st, one)
    assert float(st.loss_scale) == 1024.0
    # third clean step: growth fires
    st = s.update(st, one)
    assert float(st.loss_scale) == 2048.0
    assert int(st.growth_tracker) == 0
    # first overflow: hysteresis absorbs it, scale unchanged
    st = s.update(st, inf)
    assert float(st.loss_scale) == 2048.0
    assert int(st.hysteresis) == 1
    # second overflow: backoff fires, hysteresis restored
    st = s.update(st, inf)
    assert float(st.loss_scale) == 1024.0
    assert int(st.hysteresis) == 2


def test_hysteresis_restored_by_clean_step():
    # isolated overflows separated by clean steps never trigger backoff
    s = amp.DynamicLossScaler(init_scale=1024.0, hysteresis=2,
                              growth_interval=1000)
    st = s.init()
    st = s.update(st, jnp.ones(()))  # overflow: hysteresis 2 -> 1
    assert int(st.hysteresis) == 1
    st = s.update(st, jnp.zeros(()))  # clean: restored to 2
    assert int(st.hysteresis) == 2
    st = s.update(st, jnp.ones(()))  # isolated overflow again: absorbed
    assert float(st.loss_scale) == 1024.0


def test_scale_multiplies_in_f32():
    # 2**16 cast to fp16 would be inf; the multiply must happen in f32
    s = amp.DynamicLossScaler(init_scale=2.0**16)
    st = s.init()
    scaled = s.scale(jnp.asarray(0.5, jnp.float16), st)
    assert scaled.dtype == jnp.float32
    assert np.isfinite(float(scaled))
    np.testing.assert_allclose(float(scaled), 32768.0)


def test_scaler_min_max_clamps():
    s = amp.DynamicLossScaler(
        init_scale=2.0, hysteresis=1, min_loss_scale=1.0, growth_interval=1,
        max_loss_scale=4.0,
    )
    st = s.init()
    st = s.update(st, jnp.ones(()))  # 2 -> 1
    st = s.update(st, jnp.ones(()))  # clamped at 1
    assert float(st.loss_scale) == 1.0
    st = s.update(st, jnp.zeros(()))  # 1 -> 2
    st = s.update(st, jnp.zeros(()))  # 2 -> 4
    st = s.update(st, jnp.zeros(()))  # clamped at 4
    assert float(st.loss_scale) == 4.0


def test_amp_update_skips_step_on_overflow():
    tx = fused_sgd(0.1)
    params = {"w": jnp.ones((4,))}
    scaler = amp.DynamicLossScaler(init_scale=4.0, hysteresis=1)
    sstate = scaler.init()
    ostate = tx.init(params)
    bad_grads = {"w": jnp.array([1.0, jnp.inf, 1.0, 1.0])}

    new_params, new_ostate, new_sstate, found_inf = jax.jit(
        lambda g, o, p, s: amp.amp_update(tx, scaler, g, o, p, s)
    )(bad_grads, ostate, params, sstate)
    assert float(found_inf) == 1.0
    np.testing.assert_allclose(np.asarray(new_params["w"]), 1.0)  # untouched
    assert int(new_ostate.count) == int(ostate.count)  # opt state frozen
    assert float(new_sstate.loss_scale) == 2.0  # backed off

    good_grads = {"w": jnp.full((4,), 4.0)}  # scaled grads; unscale -> 1.0
    new_params, new_ostate, _, found_inf = amp.amp_update(
        tx, scaler, good_grads, ostate, params, sstate
    )
    assert float(found_inf) == 0.0
    np.testing.assert_allclose(np.asarray(new_params["w"]), 1.0 - 0.1)


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
def test_opt_level_end_to_end(opt_level):
    """≙ L1 cross-product harness (minimal): all levels descend the loss."""
    params0 = toy_params()
    tx = fused_adam(5e-2)
    params, handle = amp.initialize(
        params0, tx, opt_level=opt_level, half_dtype=jnp.bfloat16
    )
    state = handle.init(params)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)
    y = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)

    def loss_fn(p):
        cp = handle.policy.cast_to_compute(p)
        cx = handle.policy.cast_to_compute(x)
        pred = cx @ cp["w"] + cp["b"]
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        scaled = jax.tree_util.tree_map(
            lambda g: handle.scale_loss(g, state), grads
        )
        params, state, _ = handle.step(params, scaled, state)
        return params, state, loss

    losses = []
    for _ in range(40):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0]
    if opt_level in ("O2", "O3"):
        assert params["w"].dtype == jnp.bfloat16
    if opt_level == "O2":
        assert state.master_params["w"].dtype == jnp.float32


def test_state_dict_roundtrip():
    params, handle = amp.initialize(
        toy_params(), fused_adam(1e-3), opt_level="O2", half_dtype=jnp.float16
    )
    state = handle.init(params)
    sd = handle.state_dict(state)
    assert float(sd["loss_scale"]) == 2.0**16
    state2 = handle.load_state_dict(state, {"loss_scale": 42.0,
                                            "growth_tracker": 7,
                                            "hysteresis": 1})
    assert float(state2.scaler_state.loss_scale) == 42.0
    assert int(state2.scaler_state.growth_tracker) == 7


def test_fp16_optimizer_end_to_end():
    params = fp16_utils.network_to_half(toy_params())
    assert params["w"].dtype == jnp.bfloat16
    opt = fp16_utils.FP16_Optimizer(
        fused_adam(5e-2), dynamic_loss_scale=True,
        dynamic_loss_args=dict(init_scale=8.0),
    )
    state = opt.init(params)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.bfloat16)
    y = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            pred = (x @ p["w"] + p["b"]).astype(jnp.float32)
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        scaled = jax.tree_util.tree_map(
            lambda g: opt.scale_loss(g, state), grads
        )
        params, state, overflow = opt.step(params, scaled, state)
        return params, state, loss

    losses = []
    for _ in range(50):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0]
    assert state["master"]["w"].dtype == jnp.float32
    assert params["w"].dtype == jnp.bfloat16


def test_helper_roundtrips():
    p = toy_params()
    model, master = fp16_utils.prep_param_lists(
        fp16_utils.network_to_half(p)
    )
    assert master["w"].dtype == jnp.float32
    back = fp16_utils.master_params_to_model_params(model, master)
    assert back["w"].dtype == jnp.bfloat16
    g32 = fp16_utils.model_grads_to_master_grads({"w": jnp.ones(3, jnp.bfloat16)})
    assert g32["w"].dtype == jnp.float32
