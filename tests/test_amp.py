"""≙ tests/L0/run_amp — opt-level matrix, loss scaling, overflow skip,
checkpointing (state_dict round trip), master weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, fp16_utils
from apex_tpu.optimizers import fused_adam, fused_sgd


def toy_params():
    return {
        "w": jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32),
        "b": jnp.zeros((4,), jnp.float32),
    }


def test_opt_levels_table():
    levels = amp.opt_levels(jnp.float16)
    assert levels["O0"].cast_model_type is None
    assert levels["O0"].loss_scale == 1.0
    assert levels["O1"].compute_dtype == jnp.float16
    assert levels["O1"].loss_scale == "dynamic"
    assert levels["O2"].master_weights
    assert levels["O2"].cast_model_type == jnp.float16
    assert levels["O3"].loss_scale == 1.0
    # bf16 (TPU default): no dynamic scaling needed
    bf = amp.opt_levels(jnp.bfloat16)
    assert bf["O1"].loss_scale == 1.0
    assert bf["O2"].cast_model_type == jnp.bfloat16


def test_policy_casting():
    p = amp.Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16)
    tree = {"w": jnp.ones((2,), jnp.float32), "step": jnp.zeros((), jnp.int32)}
    c = p.cast_to_compute(tree)
    assert c["w"].dtype == jnp.bfloat16
    assert c["step"].dtype == jnp.int32  # non-floats untouched


def test_initialize_rejects_bad_level():
    with pytest.raises(ValueError):
        amp.initialize(toy_params(), fused_adam(1e-3), opt_level="O4")


def test_dynamic_scaler_growth_and_backoff():
    s = amp.DynamicLossScaler(
        init_scale=1024.0, growth_interval=3, hysteresis=2
    )
    st = s.init()
    one = jnp.zeros(())
    inf = jnp.ones(())
    # two clean steps: no growth yet
    st = s.update(st, one)
    st = s.update(st, one)
    assert float(st.loss_scale) == 1024.0
    # third clean step: growth fires
    st = s.update(st, one)
    assert float(st.loss_scale) == 2048.0
    assert int(st.growth_tracker) == 0
    # first overflow: hysteresis absorbs it, scale unchanged
    st = s.update(st, inf)
    assert float(st.loss_scale) == 2048.0
    assert int(st.hysteresis) == 1
    # second overflow: backoff fires, hysteresis restored
    st = s.update(st, inf)
    assert float(st.loss_scale) == 1024.0
    assert int(st.hysteresis) == 2


def test_hysteresis_restored_by_clean_step():
    # isolated overflows separated by clean steps never trigger backoff
    s = amp.DynamicLossScaler(init_scale=1024.0, hysteresis=2,
                              growth_interval=1000)
    st = s.init()
    st = s.update(st, jnp.ones(()))  # overflow: hysteresis 2 -> 1
    assert int(st.hysteresis) == 1
    st = s.update(st, jnp.zeros(()))  # clean: restored to 2
    assert int(st.hysteresis) == 2
    st = s.update(st, jnp.ones(()))  # isolated overflow again: absorbed
    assert float(st.loss_scale) == 1024.0


def test_scale_multiplies_in_f32():
    # 2**16 cast to fp16 would be inf; the multiply must happen in f32
    s = amp.DynamicLossScaler(init_scale=2.0**16)
    st = s.init()
    scaled = s.scale(jnp.asarray(0.5, jnp.float16), st)
    assert scaled.dtype == jnp.float32
    assert np.isfinite(float(scaled))
    np.testing.assert_allclose(float(scaled), 32768.0)


def test_scaler_min_max_clamps():
    s = amp.DynamicLossScaler(
        init_scale=2.0, hysteresis=1, min_loss_scale=1.0, growth_interval=1,
        max_loss_scale=4.0,
    )
    st = s.init()
    st = s.update(st, jnp.ones(()))  # 2 -> 1
    st = s.update(st, jnp.ones(()))  # clamped at 1
    assert float(st.loss_scale) == 1.0
    st = s.update(st, jnp.zeros(()))  # 1 -> 2
    st = s.update(st, jnp.zeros(()))  # 2 -> 4
    st = s.update(st, jnp.zeros(()))  # clamped at 4
    assert float(st.loss_scale) == 4.0


def test_hysteresis_exhaustion_then_recovery():
    """A full overflow burst walks hysteresis to zero, backs off once,
    restores the budget — and a subsequent clean stretch grows again."""
    s = amp.DynamicLossScaler(
        init_scale=4096.0, hysteresis=3, growth_interval=2
    )
    st = s.init()
    inf, one = jnp.ones(()), jnp.zeros(())
    st = s.update(st, inf)  # 3 -> 2, scale held
    st = s.update(st, inf)  # 2 -> 1, scale held
    assert float(st.loss_scale) == 4096.0
    st = s.update(st, inf)  # exhausted: backoff, budget restored
    assert float(st.loss_scale) == 2048.0
    assert int(st.hysteresis) == 3
    # recovery: growth_interval clean steps regrow the scale
    st = s.update(st, one)
    st = s.update(st, one)
    assert float(st.loss_scale) == 4096.0
    assert int(st.hysteresis) == 3
    assert int(st.growth_tracker) == 0


def test_min_loss_scale_clamp_under_sustained_overflow():
    """A pathological run (every step overflows) floors at min_loss_scale
    instead of underflowing the scale to zero."""
    s = amp.DynamicLossScaler(
        init_scale=8.0, hysteresis=1, min_loss_scale=2.0
    )
    st = s.init()
    inf = jnp.ones(())
    for _ in range(10):
        st = s.update(st, inf)
        assert float(st.loss_scale) >= 2.0
    assert float(st.loss_scale) == 2.0  # clamped, not 8/2**10


def test_amp_update_skipped_step_is_bit_identical():
    """On overflow, params AND opt state come back bit-for-bit unchanged —
    the where-select must not even round-trip values through an op that
    could re-normalize them."""
    tx = fused_adam(1e-3)
    # awkward values: denormal-adjacent, negative zero, bf16 param
    params = {
        "w": jnp.asarray([1e-38, -0.0, 3.1415927, -2.718], jnp.float32),
        "h": jnp.asarray([0.1, -7.0], jnp.bfloat16),
    }
    scaler = amp.DynamicLossScaler(init_scale=8.0, hysteresis=1)
    sstate = scaler.init()
    ostate = tx.init(params)
    # advance one clean step so opt state is non-trivial
    good = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 8.0), params)
    params, ostate, sstate, found = amp.amp_update(
        tx, scaler, good, ostate, params, sstate
    )
    assert float(found) == 0.0

    def bits(tree):
        return [
            (np.asarray(x).dtype.str, np.asarray(x).tobytes())
            for x in jax.tree_util.tree_leaves(tree)
        ]

    p_bits, o_bits = bits(params), bits(ostate)
    bad = {
        "w": jnp.asarray([1.0, jnp.nan, 1.0, 1.0], jnp.float32),
        "h": jnp.ones((2,), jnp.bfloat16),
    }
    new_params, new_ostate, new_sstate, found = amp.amp_update(
        tx, scaler, bad, ostate, params, sstate
    )
    assert float(found) == 1.0
    assert bits(new_params) == p_bits
    assert bits(new_ostate) == o_bits
    assert float(new_sstate.loss_scale) == float(sstate.loss_scale) / 2


def test_amp_update_skips_step_on_overflow():
    tx = fused_sgd(0.1)
    params = {"w": jnp.ones((4,))}
    scaler = amp.DynamicLossScaler(init_scale=4.0, hysteresis=1)
    sstate = scaler.init()
    ostate = tx.init(params)
    bad_grads = {"w": jnp.array([1.0, jnp.inf, 1.0, 1.0])}

    new_params, new_ostate, new_sstate, found_inf = jax.jit(
        lambda g, o, p, s: amp.amp_update(tx, scaler, g, o, p, s)
    )(bad_grads, ostate, params, sstate)
    assert float(found_inf) == 1.0
    np.testing.assert_allclose(np.asarray(new_params["w"]), 1.0)  # untouched
    assert int(new_ostate.count) == int(ostate.count)  # opt state frozen
    assert float(new_sstate.loss_scale) == 2.0  # backed off

    good_grads = {"w": jnp.full((4,), 4.0)}  # scaled grads; unscale -> 1.0
    new_params, new_ostate, _, found_inf = amp.amp_update(
        tx, scaler, good_grads, ostate, params, sstate
    )
    assert float(found_inf) == 0.0
    np.testing.assert_allclose(np.asarray(new_params["w"]), 1.0 - 0.1)


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
def test_opt_level_end_to_end(opt_level):
    """≙ L1 cross-product harness (minimal): all levels descend the loss."""
    losses, _, params, state = _train_trajectory(opt_level)
    assert losses[-1] < 0.5 * losses[0]
    if opt_level in ("O2", "O3"):
        assert params["w"].dtype == jnp.bfloat16
    if opt_level == "O2":
        assert state.master_params["w"].dtype == jnp.float32


def _train_trajectory(opt_level, loss_scale=None, steps=40):
    """Loss trajectory + final f32 weights for one (opt_level, loss_scale)
    cell of the reference's L1 cross-product harness."""
    params0 = toy_params()
    params, handle = amp.initialize(
        params0, fused_adam(5e-2), opt_level=opt_level,
        half_dtype=jnp.bfloat16, loss_scale=loss_scale,
    )
    state = handle.init(params)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)
    y = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)

    def loss_fn(p):
        cp = handle.policy.cast_to_compute(p)
        cx = handle.policy.cast_to_compute(x)
        pred = cx @ cp["w"] + cp["b"]
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        scaled = jax.tree_util.tree_map(
            lambda g: handle.scale_loss(g, state), grads
        )
        params, state, _ = handle.step(params, scaled, state)
        return params, state, loss

    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    final = (
        state.master_params if state.master_params is not None else params
    )
    final = jax.tree_util.tree_map(
        lambda a: np.asarray(a, np.float32), final
    )
    return np.asarray(losses), final, params, state


def test_cross_run_equivalence_loss_scale():
    """≙ tests/L1 compare.py: the loss-scale choice must not change the
    math — scale/unscale by powers of two is exact, so O2 trajectories
    under static 2**10, static 2**4, and dynamic scaling must agree to
    f32 noise, weights included."""
    base_l, base_w, _, _ = _train_trajectory("O2", loss_scale=2.0**10)
    for ls in (2.0**4, "dynamic"):
        li, wi, _, _ = _train_trajectory("O2", loss_scale=ls)
        np.testing.assert_allclose(li, base_l, rtol=1e-5, atol=1e-7)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-7
            ),
            base_w, wi,
        )


def test_cross_run_equivalence_opt_levels():
    """≙ tests/L1 compare.py cross-opt-level rows: bf16 compute (O1/O2)
    tracks the f32 run (O0) within half-precision tolerance on a smooth
    problem, and all four levels land near the same optimum."""
    l0, _, _, _ = _train_trajectory("O0")
    for level in ("O1", "O2", "O3"):
        li, _, _, _ = _train_trajectory(level)
        # trajectory-wise: bf16 rounding noise, not divergence
        np.testing.assert_allclose(li, l0, rtol=0.15, atol=5e-3)
        # and the optimum is reached (descent parity, not just closeness)
        assert li[-1] < 0.5 * li[0]


def test_state_dict_roundtrip():
    params, handle = amp.initialize(
        toy_params(), fused_adam(1e-3), opt_level="O2", half_dtype=jnp.float16
    )
    state = handle.init(params)
    sd = handle.state_dict(state)
    assert float(sd["loss_scale"]) == 2.0**16
    state2 = handle.load_state_dict(state, {"loss_scale": 42.0,
                                            "growth_tracker": 7,
                                            "hysteresis": 1})
    assert float(state2.scaler_state.loss_scale) == 42.0
    assert int(state2.scaler_state.growth_tracker) == 7


def test_bn_convert_float_and_master_params():
    """≙ fp16_utils.BN_convert_float + module-level amp.master_params."""
    tree = {
        "conv": {"kernel": jnp.ones((2, 2), jnp.float32)},
        "bn_1": {"scale": jnp.ones((2,), jnp.float32)},
        "BatchNorm_0": {"bias": jnp.zeros((2,), jnp.float32)},
    }
    half = fp16_utils.network_to_half(tree)
    assert half["bn_1"]["scale"].dtype == jnp.bfloat16
    fixed = fp16_utils.BN_convert_float(half)
    assert fixed["bn_1"]["scale"].dtype == jnp.float32
    assert fixed["BatchNorm_0"]["bias"].dtype == jnp.float32
    assert fixed["conv"]["kernel"].dtype == jnp.bfloat16  # untouched

    # master_params: O2 returns the fp32 masters, O0 the params themselves
    p2, h2 = amp.initialize(toy_params(), fused_adam(1e-3), opt_level="O2",
                            half_dtype=jnp.bfloat16)
    s2 = h2.init(p2)
    assert amp.master_params(p2, s2)["w"].dtype == jnp.float32
    p0, h0 = amp.initialize(toy_params(), fused_adam(1e-3), opt_level="O0")
    s0 = h0.init(p0)
    assert amp.master_params(p0, s0) is p0
    # module-level state_dict round trip
    sd = amp.state_dict(h2, s2)
    s2b = amp.load_state_dict(h2, s2, sd)
    assert float(s2b.scaler_state.loss_scale) == float(sd["loss_scale"])


def test_fp16_optimizer_end_to_end():
    params = fp16_utils.network_to_half(toy_params())
    assert params["w"].dtype == jnp.bfloat16
    opt = fp16_utils.FP16_Optimizer(
        fused_adam(5e-2), dynamic_loss_scale=True,
        dynamic_loss_args=dict(init_scale=8.0),
    )
    state = opt.init(params)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.bfloat16)
    y = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            pred = (x @ p["w"] + p["b"]).astype(jnp.float32)
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        scaled = jax.tree_util.tree_map(
            lambda g: opt.scale_loss(g, state), grads
        )
        params, state, overflow = opt.step(params, scaled, state)
        return params, state, loss

    losses = []
    for _ in range(50):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0]
    assert state["master"]["w"].dtype == jnp.float32
    assert params["w"].dtype == jnp.bfloat16


def test_helper_roundtrips():
    p = toy_params()
    model, master = fp16_utils.prep_param_lists(
        fp16_utils.network_to_half(p)
    )
    assert master["w"].dtype == jnp.float32
    back = fp16_utils.master_params_to_model_params(model, master)
    assert back["w"].dtype == jnp.bfloat16
    g32 = fp16_utils.model_grads_to_master_grads({"w": jnp.ones(3, jnp.bfloat16)})
    assert g32["w"].dtype == jnp.float32


# ---------------------------------------------------------------------------
# Per-op O1 cast registry (amp.lists — ≙ apex/amp/lists/*_overrides)
# ---------------------------------------------------------------------------


def test_lists_categories():
    assert amp.lists.category("attention") == "half"
    assert amp.lists.category("layer_norm") == "fp32"
    assert amp.lists.category("add") == "promote"
    assert amp.lists.category("not_an_op") is None


def test_o1_patch_half_ops_cast_down():
    from apex_tpu.fused_dense import fused_dense_function
    from apex_tpu.ops.attention import flash_attention

    q = jnp.ones((1, 2, 8, 16), jnp.float32)
    x = jnp.ones((4, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)
    # no active policy: f32 stays f32
    assert flash_attention(q, q, q).dtype == jnp.float32
    assert fused_dense_function(x, w).dtype == jnp.float32
    with amp.lists.o1_patch(jnp.bfloat16):
        assert flash_attention(q, q, q).dtype == jnp.bfloat16
        assert fused_dense_function(x, w).dtype == jnp.bfloat16


def test_o1_patch_fp32_ops_cast_up():
    from apex_tpu.ops.layer_norm import fused_layer_norm, fused_layer_norm_affine
    from apex_tpu.ops.scaled_softmax import scaled_softmax
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    x = jnp.ones((4, 128), jnp.bfloat16)
    assert fused_layer_norm(x, 128).dtype == jnp.bfloat16
    with amp.lists.o1_patch(jnp.bfloat16):
        # the reference's FP32_FUNCS semantics: norm runs (and returns) f32
        assert fused_layer_norm(x, 128).dtype == jnp.float32
        # affine params are upcast too (the norm math sees f32 w/b even
        # for bf16 inputs); cotangent dtype still follows the primal leaf
        w = jnp.ones((128,), jnp.bfloat16)
        b = jnp.zeros((128,), jnp.bfloat16)
        y, vjp = jax.vjp(
            lambda xx, ww, bb: fused_layer_norm_affine(xx, ww, bb, 128), x, w, b
        )
        assert y.dtype == jnp.float32
        _, dw, db = vjp(jnp.ones_like(y))
        assert dw.dtype == w.dtype and db.dtype == b.dtype
        assert scaled_softmax(x, 1.0).dtype == jnp.float32
        loss = softmax_cross_entropy_loss(x, jnp.zeros((4,), jnp.int32))
        assert loss.dtype == jnp.float32


def test_o1_promote_widest_wins():
    with amp.lists.o1_patch(jnp.bfloat16):
        a, b = amp.lists.amp_cast(
            "add", jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.float32)
        )
        assert a.dtype == jnp.float32
        assert b.dtype == jnp.float32


def test_o1_differs_from_o2():
    """The VERDICT item: O1 is per-op (norm f32, gemm half); O2 is
    whole-tree half.  Same input, different dtype outcomes."""
    from apex_tpu.ops.layer_norm import fused_layer_norm

    x32 = jnp.ones((4, 128), jnp.float32)
    params = toy_params()
    tx = fused_sgd(learning_rate=0.1)

    # O2: params cast bf16 (whole-tree policy)
    cast_params, handle2 = amp.initialize(
        params, tx, opt_level="O2", half_dtype=jnp.bfloat16
    )
    assert cast_params["w"].dtype == jnp.bfloat16
    o2_norm = fused_layer_norm(
        handle2.policy.cast_to_compute(x32), 128
    ).dtype  # O2: bf16 in, bf16 out

    # O1: params stay f32; per-op registry governs compute dtypes
    cast_params1, handle1 = amp.initialize(
        params, tx, opt_level="O1", half_dtype=jnp.bfloat16
    )
    assert cast_params1["w"].dtype == jnp.float32
    with handle1.patch_functions():
        from apex_tpu.fused_dense import fused_dense_function

        o1_norm = fused_layer_norm(x32, 128).dtype
        o1_gemm = fused_dense_function(
            x32, jnp.ones((128, 8), jnp.float32)
        ).dtype
    assert o2_norm == jnp.bfloat16
    assert o1_norm == jnp.float32  # differs from O2
    assert o1_gemm == jnp.bfloat16

    # only O1 may patch functions (reference: patch_torch_functions table)
    with pytest.raises(RuntimeError):
        handle2.patch_functions()


def test_registry_register_and_unregistered_passthrough():
    from apex_tpu.amp.lists import _registry

    amp.lists.register("my_custom_op", "half")
    try:
        with amp.lists.o1_patch(jnp.bfloat16):
            y = amp.lists.amp_cast("my_custom_op", jnp.ones((2,), jnp.float32))
            assert y.dtype == jnp.bfloat16
            z = amp.lists.amp_cast("unknown_op", jnp.ones((2,), jnp.float32))
            assert z.dtype == jnp.float32
    finally:
        del _registry._CATEGORY["my_custom_op"]
    with pytest.raises(ValueError):
        amp.lists.register("bad", "int8")
