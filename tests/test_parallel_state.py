"""≙ tests/L0/run_transformer/test_parallel_state.py — mesh registry tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps


def test_initialize_and_sizes(eight_devices):
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2
    )
    assert ps.model_parallel_is_initialized()
    assert ps.get_tensor_model_parallel_world_size() == 2
    assert ps.get_pipeline_model_parallel_world_size() == 2
    assert ps.get_data_parallel_world_size() == 2
    assert mesh.shape == {"dp": 2, "pp": 2, "cp": 1, "tp": 2}
    assert ps.get_context_parallel_world_size() == 1
    ps.destroy_model_parallel()
    assert not ps.model_parallel_is_initialized()


@pytest.mark.parametrize("tp,pp", [(1, 1), (2, 1), (1, 2), (4, 2), (8, 1), (2, 4)])
def test_valid_factorizations(eight_devices, tp, pp):
    ps.initialize_model_parallel(tp, pp)
    assert ps.get_data_parallel_world_size() * tp * pp == 8


def test_indivisible_world_size_raises(eight_devices):
    with pytest.raises(RuntimeError):
        ps.initialize_model_parallel(tensor_model_parallel_size=3)


def test_uninitialized_raises():
    ps.destroy_model_parallel()
    with pytest.raises(RuntimeError):
        ps.get_mesh()


def test_ranks_inside_shard_map(eight_devices):
    mesh = ps.initialize_model_parallel(2, 2)

    def f(_):
        return (
            ps.get_data_parallel_rank()[None],
            ps.get_pipeline_model_parallel_rank()[None],
            ps.get_tensor_model_parallel_rank()[None],
        )

    dp, pp, tp = jax.shard_map(
        f,
        mesh=mesh,
        in_specs=P("dp", "pp", "tp"),
        out_specs=(P("dp"), P("pp"), P("tp")),
    )(jnp.zeros((2, 2, 2)))
    assert list(np.asarray(dp)) == [0, 1]
    assert list(np.asarray(pp)) == [0, 1]
    assert list(np.asarray(tp)) == [0, 1]


def test_rank_outside_shard_map_raises(eight_devices):
    ps.initialize_model_parallel(2, 2)
    with pytest.raises(RuntimeError):
        ps.get_tensor_model_parallel_rank()


def test_pipeline_stage_predicates(eight_devices):
    mesh = ps.initialize_model_parallel(1, 4)

    def f(_):
        first = ps.is_pipeline_first_stage()
        last = ps.is_pipeline_last_stage()
        return (
            jnp.asarray(first, jnp.int32)[None],
            jnp.asarray(last, jnp.int32)[None],
        )

    first, last = jax.shard_map(
        f, mesh=mesh, in_specs=P("pp"), out_specs=P("pp")
    )(jnp.zeros((4,)))
    assert list(np.asarray(first)) == [1, 0, 0, 0]
    assert list(np.asarray(last)) == [0, 0, 0, 1]


def test_virtual_pipeline_bookkeeping(eight_devices):
    ps.initialize_model_parallel(
        1, 2, virtual_pipeline_model_parallel_size=2
    )
    assert ps.get_virtual_pipeline_model_parallel_world_size() == 2
    assert ps.get_virtual_pipeline_model_parallel_rank() == 0
    ps.set_virtual_pipeline_model_parallel_rank(1)
    assert ps.get_virtual_pipeline_model_parallel_rank() == 1


def test_virtual_pipeline_requires_pp(eight_devices):
    with pytest.raises(RuntimeError):
        ps.initialize_model_parallel(1, 1, virtual_pipeline_model_parallel_size=2)


def test_reinit_without_destroy_raises(eight_devices):
    ps.initialize_model_parallel(2, 2)
    with pytest.raises(RuntimeError):
        ps.initialize_model_parallel(1, 1)
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(1, 1)  # ok after destroy


def test_virtual_pp_enabled_after_init(eight_devices):
    ps.initialize_model_parallel(1, 2)
    ps.set_virtual_pipeline_model_parallel_world_size(2)
    assert ps.get_virtual_pipeline_model_parallel_rank() == 0
    ps.set_virtual_pipeline_model_parallel_world_size(None)
    assert ps.get_virtual_pipeline_model_parallel_rank() is None


def test_lazy_attr_probe_is_attributeerror():
    import apex_tpu

    # contrib doesn't exist yet on disk; availability probes must see
    # AttributeError (hasattr False), not ModuleNotFoundError.
    assert not hasattr(apex_tpu, "does_not_exist")


def test_divide():
    assert ps.divide(8, 2) == 4
    with pytest.raises(ValueError):
        ps.divide(7, 2)


def test_sharding_helpers(eight_devices):
    ps.initialize_model_parallel(2, 2)
    s = ps.data_parallel_sharding(3)
    assert s.spec == P("dp", None, None)
    x = jax.device_put(jnp.zeros((4, 3, 3)), s)
    assert x.sharding.spec == P("dp", None, None)
    assert ps.replicated_sharding().spec == P()
