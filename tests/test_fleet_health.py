"""Fleet aggregation on the 8-device CPU mesh + the health watchdog.

ISSUE 5 acceptance: per-host columns correct under skewed step times
(the synthetic straggler fixture names the right host), aggregation
adds no per-step host sync (cadence-dispatch counting + the paired
timing tripwire, the MetricRegistry overhead test's method), and every
rule of the declarative set fires on its synthetic trigger and lands
in the sinks / flight recorder / escalation callback.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.observability import (
    FleetAggregator,
    FleetView,
    FlightRecorder,
    GoodputAccountant,
    JSONLSink,
    MetricRegistry,
    Reporter,
    StepMeter,
    TraceScheduler,
    Watchdog,
    board,
    default_rules,
)
from apex_tpu.observability.health import (
    GoodputFloorRule,
    HungStepRule,
    LossSpikeRule,
    MFUFloorRule,
    NaNRateRule,
    StaleFetchRule,
    StragglerRule,
)


# ---------------------------------------------------------------------------
# fleet aggregation
# ---------------------------------------------------------------------------


def test_all_gather_rows_collects_per_host_columns(eight_devices):
    """Each participant's distinct row comes back as its column of the
    gathered matrix, identical on every participant."""
    from apex_tpu.parallel import comm

    mesh = ps.initialize_model_parallel(devices=eight_devices)
    rows = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    placed = jax.device_put(rows, NamedSharding(mesh, P("dp")))

    fn = jax.jit(
        jax.shard_map(
            lambda local: comm.all_gather_rows(local[0], "dp"),
            mesh=mesh, in_specs=P("dp"), out_specs=P(), check_vma=False,
        )
    )
    out = np.asarray(fn(placed))
    np.testing.assert_array_equal(out, rows)


def test_fleet_skewed_step_times_per_host_columns(eight_devices):
    """The synthetic straggler fixture: host 5 reports 4x step time;
    the gathered columns and min/median/max rollups reflect it
    exactly."""
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    agg = FleetAggregator(
        ("train/step_time_ms", "train/mfu"), mesh=mesh, publish=False
    )
    rows = np.tile(np.array([[100.0, 0.4]], np.float32), (8, 1))
    rows[5, 0] = 400.0  # the straggler
    rows[5, 1] = 0.1
    out = agg.gather_rows(rows)
    view = FleetView(12, agg.names, out)
    assert view.per_host("train/step_time_ms") == [
        100.0, 100.0, 100.0, 100.0, 100.0, 400.0, 100.0, 100.0
    ]
    roll = view.rollup("train/step_time_ms")
    assert roll == {"min": 100.0, "median": 100.0, "max": 400.0}
    flat = view.as_dict()
    assert flat["fleet/train/step_time_ms/host5"] == 400.0
    assert flat["fleet/train/mfu/min"] == pytest.approx(0.1)


def test_fleet_cadence_dispatches_only_on_cadence(eight_devices):
    """No per-step device contact: off-cadence observe is a stash; the
    gather dispatches 1/every steps and materializes one cadence late
    (the registry's double-buffer discipline)."""
    board.clear()
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    agg = FleetAggregator(("m",), mesh=mesh, every=4)
    calls = []
    real = agg._gather
    agg._gather = lambda rows: (calls.append(1), real(rows))[1]
    for step in range(10):
        agg.observe(step, {"m": float(step)})
    assert len(calls) == 3  # steps 0, 4, 8 only
    view = agg.view()
    assert view is not None and view.step == 4  # one cadence stale
    assert view.per_host("m") == [4.0] * 8
    final = agg.fetch()  # force-drain: inflight(8) then pending(9)
    assert final.step == 9
    # host-0 publication: columns + rollups on the board
    snap = board.snapshot()
    assert snap["fleet/m/host0"] == 9.0
    assert snap["fleet/m/median"] == 9.0
    assert snap["fleet/step"] == 9
    board.clear()


def test_fleet_missing_metric_rides_as_nan(eight_devices):
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    agg = FleetAggregator(("a", "b"), mesh=mesh, every=1, publish=False)
    agg.observe(0, {"a": 1.0})  # b missing
    view = agg.fetch()
    assert view.per_host("a") == [1.0] * 8
    assert all(v != v for v in view.per_host("b"))


def test_fleet_observe_adds_no_per_step_sync(eight_devices):
    """The MetricRegistry overhead test's method, applied to the fleet
    path: paired back-to-back trials of a jitted chunk with and
    without per-step ``observe`` + an on-cadence gather; the MIN ratio
    over pairs is a tripwire against an accidental per-step blocking
    collective (wall clock on this 1-core box wobbles, so min-of-pairs
    is the stable statistic — see test_observability.py)."""
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    chunk = 16
    agg = FleetAggregator(
        ("train/step_time_ms",), mesh=mesh, every=chunk, publish=False
    )
    x = jnp.eye(128, dtype=jnp.float32) * 0.5

    @jax.jit
    def chunk_fn(w):
        def body(w, _):
            return jnp.tanh(w @ x), ()

        w, _ = jax.lax.scan(body, w, None, length=chunk)
        return w

    w0 = jnp.ones((128, 128), jnp.float32)
    chunk_fn(w0).block_until_ready()  # compile
    agg.observe(0, {"train/step_time_ms": 1.0})  # compile the gather
    agg.fetch()

    def time_once(observe, base):
        t0 = time.perf_counter()
        w = chunk_fn(w0)
        if observe:
            for j in range(chunk):
                agg.observe(base + j, {"train/step_time_ms": 1.0})
        jax.block_until_ready(w)
        return time.perf_counter() - t0

    ratios = []
    for t in range(9):
        tb = time_once(False, 0)
        ti = time_once(True, (t + 1) * chunk)
        ratios.append(ti / tb)
    assert min(ratios) - 1.0 < 0.25, (
        f"fleet host-path tripwire: best observed/bare ratio "
        f"{min(ratios):.3f} — did a per-step blocking gather sneak in? "
        f"(all ratios: {[round(r, 3) for r in ratios]})"
    )


# ---------------------------------------------------------------------------
# watchdog rules — each fires on its synthetic trigger
# ---------------------------------------------------------------------------


def test_straggler_rule_names_the_slow_host(eight_devices):
    """ISSUE 5 acceptance: skewed per-host step times raise a
    `straggler` HealthEvent naming the right host."""
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    agg = FleetAggregator(
        ("train/step_time_ms",), mesh=mesh, every=1, publish=False
    )
    rows = np.full((8, 1), 100.0, np.float32)
    rows[5, 0] = 400.0
    agg._view = FleetView(10, agg.names, agg.gather_rows(rows))

    wd = Watchdog([StragglerRule(zmax=3.0)], fleet=agg)
    events = wd.check(10)
    assert len(events) == 1
    ev = events[0]
    assert ev.rule == "straggler"
    assert ev.host == 5
    assert "host 5" in ev.message
    assert ev.value == 400.0

    # lockstep fleet: micro-jitter must NOT alert (std floor)
    calm = np.full((8, 1), 100.0, np.float32)
    calm[2, 0] = 101.0
    agg._view = FleetView(11, agg.names, agg.gather_rows(calm))
    wd2 = Watchdog([StragglerRule(zmax=3.0)], fleet=agg)
    assert wd2.check(11) == []


def test_multihost_rows_collapse_to_per_host_columns(eight_devices):
    """On a real pod each host's row rides ALL its axis devices: the
    duplicates must collapse to one row per host (scoring them would
    dilute the leave-one-out z-score and hide the straggler) and the
    event must carry the PROCESS index, not a device index."""
    mesh = ps.initialize_model_parallel(devices=eight_devices)
    agg = FleetAggregator(
        ("train/step_time_ms",), mesh=mesh, every=1, publish=False
    )
    # simulate 2 hosts x 4 devices on the axis
    agg._row_host = [0, 0, 0, 0, 1, 1, 1, 1]
    rows = np.full((8, 1), 100.0, np.float32)
    rows[4:, 0] = 400.0  # host 1's row, duplicated over its 4 devices
    view = agg._collapse(9, rows)
    assert view.hosts == 2
    assert view.labels == (0, 1)
    assert view.per_host("train/step_time_ms") == [100.0, 400.0]
    assert view.as_dict()["fleet/train/step_time_ms/host1"] == 400.0

    agg._view = view
    wd = Watchdog([StragglerRule(zmax=3.0, min_hosts=2)], fleet=agg)
    (ev,) = wd.check(9)
    assert ev.host == 1 and "host 1" in ev.message


def test_goodput_floor_rule():
    acct = GoodputAccountant()
    for i in range(30):
        acct.on_step(i, skipped=(i % 2 == 0))  # 50% skipped
    wd = Watchdog(
        [GoodputFloorRule(floor=0.8, min_executed=20)], goodput=acct
    )
    (ev,) = wd.check(30)
    assert ev.rule == "goodput_floor" and ev.value == pytest.approx(0.5)


def test_loss_spike_rule_ema_and_nonfinite():
    reg = MetricRegistry(fetch_every=1)
    reg.gauge("train/loss")

    def push(step, loss):
        reg.observe(step, reg.update(reg.init(), {"train/loss": loss}))
        reg.fetch()

    rule = LossSpikeRule(factor=5.0, warmup_fetches=2)
    wd = Watchdog([rule], registry=reg)
    for s, loss in enumerate([2.0, 2.1, 1.9, 2.0]):
        push(s, jnp.float32(loss))
        assert wd.check(s) == []
    push(4, jnp.float32(50.0))  # > 5x EMA(~2)
    (ev,) = wd.check(4)
    assert ev.rule == "loss_spike" and ev.value == pytest.approx(50.0)

    # a spike must not re-teach the EMA: the next normal fetch is calm
    rule._last_fired = None  # bypass cooldown for the assertion
    push(5, jnp.float32(2.0))
    assert wd.check(5) == []

    # non-finite loss is critical, immediately
    rule._last_fired = None
    push(6, jnp.float32(float("nan")))
    (ev,) = wd.check(6)
    assert ev.severity == "critical" and "non-finite" in ev.message


def test_nan_rate_rule_fires_on_storms_not_single_skips():
    wd = Watchdog(
        [NaNRateRule(max_rate=0.25, window=8)], check_every=10 ** 9
    )
    for i in range(8):
        wd.on_step(i, skipped=(i == 3))  # 1/8 = under budget
    assert wd.check(7) == []
    for i in range(8, 16):
        wd.on_step(i, skipped=(i % 2 == 0))  # 4/8 = storm
    (ev,) = wd.check(15)
    assert ev.rule == "nan_rate" and ev.value == pytest.approx(0.5)


def test_stale_fetch_rule():
    reg = MetricRegistry(fetch_every=4)
    reg.gauge("x")
    wd = Watchdog([StaleFetchRule()], registry=reg)
    wd.on_step(0)
    assert wd.check(10) == []  # within the 4*fetch_every budget
    (ev,) = wd.check(20)  # never fetched, 20 steps in
    assert ev.rule == "stale_fetch" and ev.value == 20


def test_hung_step_rule_and_poll():
    clock = [0.0]
    wd = Watchdog(
        [HungStepRule(deadline_s=5.0)], check_every=10 ** 9,
        clock=lambda: clock[0],
    )
    wd.on_step(0)
    clock[0] = 1.0
    wd.on_step(1)
    assert wd.check(1) == []
    clock[0] = 11.0
    wd.on_step(2)  # the closed interval took 10s
    (ev,) = wd.check(2)
    assert ev.rule == "hung_step" and ev.severity == "critical"
    assert ev.value == pytest.approx(10.0)
    # poll() honors the cooldown: the in-loop event already covered
    # this step — a monitor thread must not duplicate it
    clock[0] = 30.0
    assert wd.poll() == []
    # the NEXT step hangs mid-flight: poll catches it (no on_step has
    # closed the interval), then repeated polls of the SAME hung step
    # are deduped — one event per hung step, not one per poll
    clock[0] = 31.0
    wd.on_step(3)
    clock[0] = 50.0
    evs = wd.poll()
    assert evs and evs[0].rule == "hung_step"
    assert evs[0].value == pytest.approx(19.0)
    clock[0] = 60.0
    assert wd.poll() == []  # no event storm while still hung


def test_mfu_floor_rule():
    clockv = [0.0]

    def clock():
        clockv[0] += 1.0  # 1 s/step
        return clockv[0]

    meter = StepMeter(
        flops_per_step=1e9, peak_flops=1e12, clock=clock
    )  # mfu = 1e9/1e12 = 0.001
    for _ in range(20):
        meter.tick()
    wd = Watchdog([MFUFloorRule(floor=0.05, warmup_steps=16)], meter=meter)
    (ev,) = wd.check(20)
    assert ev.rule == "mfu_floor" and ev.value == pytest.approx(0.001)


# ---------------------------------------------------------------------------
# watchdog plumbing: emission, escalation, resilience of the rules
# ---------------------------------------------------------------------------


def test_events_reach_sinks_board_flight_and_callback(tmp_path):
    board.clear()
    acct = GoodputAccountant()
    for i in range(30):
        acct.on_step(i, skipped=True)
    flight = FlightRecorder(capacity=8, directory=str(tmp_path))
    path = tmp_path / "health.jsonl"
    seen = []
    with Reporter([JSONLSink(path)]) as reporter:
        wd = Watchdog(
            [GoodputFloorRule(floor=0.5)], goodput=acct,
            reporter=reporter, flight=flight,
            on_unhealthy=seen.append,
        )
        (ev,) = wd.check(30)

    assert wd.events == [ev] and seen == [ev]
    assert board.get("health/goodput_floor") == 0.0
    rec = json.loads(path.read_text().splitlines()[0])
    assert rec["metric"] == "health/goodput_floor"
    assert list(rec)[:4] == ["metric", "value", "unit", "vs_baseline"]
    assert rec["severity"] == "warn" and rec["step"] == 30
    assert flight.events[-1]["kind"] == "health"
    assert flight.events[-1]["rule"] == "goodput_floor"
    board.clear()


def test_on_unhealthy_arms_a_trace_window(tmp_path):
    """Alert -> profile in one run: the escalation callback re-arms the
    TraceScheduler for the next steps, and the capture happens."""
    calls = []
    sched = TraceScheduler(
        spec="", base_dir=str(tmp_path),
        _start_fn=lambda d: calls.append(("start", d)),
        _stop_fn=lambda: calls.append(("stop",)),
    )
    assert not sched.active  # nothing armed by env

    acct = GoodputAccountant()
    for i in range(30):
        acct.on_step(i, skipped=True)
    wd = Watchdog(
        [GoodputFloorRule(floor=0.5)], goodput=acct,
        on_unhealthy=lambda ev: sched.arm(ev.step + 1, 2),
    )
    wd.check(30)
    assert sched.active and sched.start == 31 and sched.end == 32
    for step in (31, 32, 33):
        sched.on_step(step)
    assert [c[0] for c in calls] == ["start", "stop"]
    # a second alert while a future window is armed must not push the
    # window out of reach (first alert wins)
    sched2 = TraceScheduler(spec="", base_dir=str(tmp_path))
    sched2.arm(100, 2)
    sched2.arm(200, 2)
    assert sched2.start == 100


def test_broken_rule_is_disabled_not_fatal():
    class Exploding(StaleFetchRule):
        name = "exploding"

        def evaluate(self, wd, step):
            raise ZeroDivisionError("telemetry bug")

    acct = GoodputAccountant()
    for i in range(30):
        acct.on_step(i, skipped=True)
    wd = Watchdog([Exploding(), GoodputFloorRule(floor=0.5)], goodput=acct)
    with pytest.warns(RuntimeWarning, match="exploding"):
        events = wd.check(30)
    assert [e.rule for e in events] == ["goodput_floor"]  # others ran
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")  # disabled: no second warning
        wd2_events = wd.check(200)
    assert [e.rule for e in wd2_events] == ["goodput_floor"]


def test_default_rules_overrides_and_unknown():
    rules = default_rules(straggler={"zmax": 2.5})
    names = [r.name for r in rules]
    assert names == ["straggler", "mfu_floor", "goodput_floor",
                     "loss_spike", "nan_rate", "stale_fetch", "hung_step",
                     "collective_fraction", "host_stall"]
    assert rules[0].zmax == 2.5
    with pytest.raises(ValueError, match="unknown health rules"):
        default_rules(typo={})


def test_watchdog_rollback_clears_skip_history():
    wd = Watchdog([NaNRateRule(max_rate=0.25, window=8)],
                  check_every=10 ** 9)
    for i in range(8):
        wd.on_step(i, skipped=True)
    wd.on_rollback(7, 0, 8, 0)  # the rollback handled the streak
    for i in range(8):
        wd.on_step(i, skipped=False)  # clean replay
    assert wd.check(8) == []
