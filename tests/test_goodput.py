"""apex_tpu.goodput — zero-stall async checkpointing + resumable
streaming input (docs/goodput.md).

Pins the subsystem's three contracts: snapshot isolation (state
mutated after save() returns never corrupts the written checkpoint),
crash consistency (a mid-write death leaves the previous checkpoint
intact and invisible debris), and deterministic resume (a stormed
run's batch/loss sequence is bit-identical to an uninterrupted one,
with the stream cursor riding inside the checkpoint).
"""

import os
import shutil

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import checkpoint as ckpt
from apex_tpu.data import DataLoader, TokenFileDataset, write_token_file
from apex_tpu.goodput import (
    AsyncCheckpointEngine,
    ResumableStream,
    StreamStateError,
    host_snapshot,
    stream_state,
    verify_stream_state,
)
from apex_tpu.observability.metrics import board
from apex_tpu.resilience import (
    ObserverFanout,
    ResilientCheckpointManager,
    chaos,
    run_resilient,
)


def _bits(tree):
    return [
        np.asarray(x).tobytes() for x in jax.tree_util.tree_leaves(tree)
    ]


# ---------------------------------------------------------------------------
# host_snapshot: copy-on-snapshot isolation
# ---------------------------------------------------------------------------


def test_host_snapshot_isolates_numpy_mutation():
    arr = np.arange(4.0, dtype=np.float32)
    snap = host_snapshot({"a": arr, "j": jnp.ones((2,)), "s": 3})
    arr[:] = -1.0
    np.testing.assert_array_equal(snap["a"], [0.0, 1.0, 2.0, 3.0])
    assert isinstance(snap["j"], np.ndarray)  # device leaves land on host
    assert snap["s"] == 3


def test_host_snapshot_preserves_dtypes():
    snap = host_snapshot({
        "bf": jnp.ones((3,), jnp.bfloat16),
        "i": np.asarray(7, np.int64),
    })
    assert snap["bf"].dtype == jnp.bfloat16
    assert snap["i"].dtype == np.int64


# ---------------------------------------------------------------------------
# AsyncCheckpointEngine
# ---------------------------------------------------------------------------


def test_engine_roundtrip_interval_retention(tmp_path):
    state = {"w": jnp.arange(4.0), "n": np.asarray(0, np.int64)}
    with AsyncCheckpointEngine(
        tmp_path, max_to_keep=2, save_interval_steps=2
    ) as eng:
        for step in range(6):
            saved = eng.save(step, {"w": state["w"] + step, "n": state["n"]})
            assert saved == (step % 2 == 0)  # interval policy
        eng.wait_until_finished()
        assert eng.all_steps() == [2, 4]  # max_to_keep pruned step 0
        assert eng.latest_step() == 4
        out = eng.restore(template=state)
        np.testing.assert_allclose(np.asarray(out["w"]), [4, 5, 6, 7])
        st = eng.stats()
        assert st["saves"] == 3 and st["writes"] == 3
        assert st["failures"] == 0


def test_engine_save_returns_before_write_lands(tmp_path):
    """The zero-stall contract: save() returns after snapshot+enqueue;
    the step dir appears only once the BACKGROUND write commits (the
    finalize barrier observes it)."""
    import threading

    gate = threading.Event()
    eng = AsyncCheckpointEngine(tmp_path)
    eng._commit_hook = lambda step: gate.wait(timeout=30)
    try:
        assert eng.save(0, {"w": jnp.ones((2,))})
        # enqueued but the writer is gated: nothing on disk yet
        assert eng.latest_step() is None
        gate.set()
        eng.wait_until_finished()
        assert eng.latest_step() == 0
    finally:
        gate.set()
        eng.close()


def test_engine_queue_depth_resolution(tmp_path, monkeypatch):
    """Depth resolution order: env APEX_TPU_CKPT_QUEUE > explicit arg
    > default 4; floored at 1 (depth 0 would make every save
    synchronous)."""
    from apex_tpu.goodput import resolve_queue_depth

    monkeypatch.delenv("APEX_TPU_CKPT_QUEUE", raising=False)
    assert resolve_queue_depth() == 4
    assert resolve_queue_depth(9) == 9
    assert resolve_queue_depth(0) == 1
    monkeypatch.setenv("APEX_TPU_CKPT_QUEUE", "16")
    assert resolve_queue_depth() == 16
    assert resolve_queue_depth(2) == 16  # env wins over the arg
    eng = AsyncCheckpointEngine(tmp_path, queue_depth=2)
    try:
        assert eng._q.maxsize == 16
    finally:
        eng.close()


def test_engine_mutation_after_save_is_invisible(tmp_path):
    """The ISSUE's snapshot hazard, pinned at the engine: mutate the
    state right after save() returns — the written checkpoint must
    carry the pre-mutation values."""
    with AsyncCheckpointEngine(tmp_path) as eng:
        arr = np.ones((8,), np.float32)
        eng.save(0, {"a": arr})
        arr[:] = 999.0  # the hazard
        eng.wait_until_finished()
        out = eng.restore(0)
        np.testing.assert_array_equal(np.asarray(out["a"]), np.ones((8,)))


def test_engine_midwrite_crash_keeps_previous_intact(tmp_path):
    """A writer that dies mid-write (commit hook raises — the on-disk
    moment BEFORE the atomic rename) must leave the previous complete
    step restorable, the failed step invisible, and surface the error
    at the next synchronization point — the finalize barrier here, or
    the next ``save`` (the deferred-error retry contract)."""
    with AsyncCheckpointEngine(tmp_path) as eng:
        eng.save(0, {"w": jnp.zeros((2,))})
        eng.wait_until_finished()

        def die(step):
            raise OSError(f"disk died mid-write of step {step}")

        eng._commit_hook = die
        eng.save(1, {"w": jnp.ones((2,))})
        # the finalize barrier must NOT report success for a write
        # that never reached disk (the shutdown/preemption drain)
        with pytest.raises(OSError, match="mid-write"):
            eng.wait_until_finished()
        eng._commit_hook = None
        # previous checkpoint intact, failed step invisible — and
        # restore() keeps working: fall-back IS the failure contract
        assert eng.all_steps() == [0]
        out = eng.restore(0)
        np.testing.assert_array_equal(np.asarray(out["w"]), [0.0, 0.0])
        # the raise cleared the error: the next save re-enters clean
        assert eng.save(2, {"w": jnp.full((2,), 2.0)})
        eng.wait_until_finished()
        assert eng.all_steps() == [0, 2]
        assert eng.stats()["failures"] == 1


def test_engine_deferred_error_surfaces_at_next_save(tmp_path):
    """Without an intervening finalize, the deferred write error
    surfaces at the NEXT save, once — the RCM retry wrapper clears it
    and re-enqueues the current step."""
    with AsyncCheckpointEngine(tmp_path) as eng:
        eng.save(0, {"w": jnp.zeros((2,))})
        eng.wait_until_finished()

        def die(step):
            raise OSError(f"disk died mid-write of step {step}")

        eng._commit_hook = die
        eng.save(1, {"w": jnp.ones((2,))})
        eng._q.join()  # write settled, error still deferred
        eng._commit_hook = None
        with pytest.raises(OSError, match="mid-write"):
            eng.save(2, {"w": jnp.ones((2,))})
        assert eng.save(2, {"w": jnp.full((2,), 2.0)})  # retry clears
        eng.wait_until_finished()
        assert eng.all_steps() == [0, 2]
        assert eng.stats()["failures"] == 1


def test_engine_writer_bootstrap_failure_does_not_deadlock(
    tmp_path, monkeypatch
):
    """A writer thread that cannot bootstrap (orbax broken) must not
    leave enqueued items un-task_done'd — ``q.join()`` callers
    (finalize, shutdown) would deadlock.  The failure surfaces through
    the normal deferral contract instead."""
    import orbax.checkpoint as ocp

    def boom(*a, **k):
        raise RuntimeError("orbax broken at writer bootstrap")

    monkeypatch.setattr(ocp, "StandardCheckpointer", boom)
    eng = AsyncCheckpointEngine(tmp_path)
    try:
        assert eng.save(0, {"w": jnp.ones((2,))})
        with pytest.raises(RuntimeError, match="writer bootstrap"):
            eng.wait_until_finished()  # returns (no hang) and raises
        assert eng.all_steps() == []
        # the dead writer keeps DRAINING but every swallowed snapshot
        # is a lost checkpoint — the error must re-arm per dropped
        # item (before task_done, so a join waiter observes it), so no
        # later sync point reports success for writes that never
        # reached disk
        eng._interval = 1
        eng.save(1, {"w": jnp.ones((2,))})  # enqueue ok (err cleared)...
        with pytest.raises(RuntimeError, match="writer bootstrap"):
            eng.wait_until_finished()  # ...but its drop re-armed
        assert eng.all_steps() == []
    finally:
        eng.close()


def test_engine_events_carry_phase_spans(tmp_path):
    with AsyncCheckpointEngine(tmp_path) as eng:
        eng.save(0, {"w": jnp.ones((2,))})
        eng.wait_until_finished()
        evs = eng.drain_events()
    writes = [e for e in evs if e["phase"] == "write"]
    assert len(writes) == 1 and writes[0]["step"] == 0
    w = writes[0]
    assert w["snapshot_t0"] <= w["snapshot_t1"] <= w["t0"] <= w["t1"]
    assert w["ok"] is True
    assert eng.drain_events() == []  # drained


def test_engine_close_drains_pending_writes(tmp_path):
    eng = AsyncCheckpointEngine(tmp_path)
    eng.save(0, {"w": jnp.ones((4,))})
    eng.close()  # no wait_until_finished: close IS the shutdown drain
    assert ckpt.latest_step(tmp_path) == 0


def test_rcm_sync_engine_gets_snapshot_isolation(tmp_path):
    """The satellite fix: the SYNC manager path snapshots before the
    orbax enqueue too — params mutated right after save() returns
    stay out of the written checkpoint."""
    with ResilientCheckpointManager(tmp_path, engine="sync") as mgr:
        arr = np.ones((8,), np.float32)
        assert mgr.save(0, {"a": arr})
        arr[:] = -5.0
        mgr.wait_until_finished()
        out = mgr.restore(0)
        np.testing.assert_array_equal(np.asarray(out["a"]), np.ones((8,)))


# ---------------------------------------------------------------------------
# stream state + ResumableStream
# ---------------------------------------------------------------------------


@pytest.fixture
def loader(tmp_path):
    toks = np.arange(1000, 1000 + 4096, dtype=np.uint16)
    p = tmp_path / "corpus.bin"
    write_token_file(p, toks)
    ds = TokenFileDataset(p, seq_len=128)  # 32 samples
    return DataLoader(ds, batch_size=4, seed=7)  # 8 batches/epoch


def test_stream_state_roundtrip_through_checkpoint(loader, tmp_path):
    """The cursor is an ordinary pytree leaf: snapshot_training_state
    carries it, the engine writes it, verify_stream_state accepts it
    back and returns the exact batch index."""
    state = ckpt.snapshot_training_state(
        {"w": jnp.zeros((2,))}, step=11, stream=stream_state(loader, 12),
    )
    with AsyncCheckpointEngine(tmp_path / "c") as eng:
        eng.save(11, state)
        restored = eng.restore(11)
    assert verify_stream_state(loader, restored["stream"]) == 12


def test_stream_state_mismatch_is_loud(loader, tmp_path):
    st = stream_state(loader, 5)
    ds = loader.dataset
    for other, what in (
        (DataLoader(ds, batch_size=4, seed=8), "seed"),
        (DataLoader(ds, batch_size=2, seed=7), "batch_size"),
        (DataLoader(ds, batch_size=4, seed=7, shard=(1, 2)), "rank"),
        (DataLoader(ds, batch_size=4, seed=7, shuffle=False), "shuffle"),
    ):
        with pytest.raises(StreamStateError, match=what):
            verify_stream_state(other, st)


def test_resumable_stream_matches_plain_iteration(loader):
    plain = list(__import__("itertools").islice(iter(loader), 12))
    with ResumableStream(loader) as stream:
        for k in range(12):
            np.testing.assert_array_equal(stream(k), plain[k])


def test_resumable_stream_seeks_on_rollback_and_resume(loader):
    plain = list(__import__("itertools").islice(iter(loader), 20))
    with ResumableStream(loader) as stream:
        stream(0), stream(1), stream(2)
        # rollback: jump backwards
        np.testing.assert_array_equal(stream(1), plain[1])
        # resume in a "fresh process": jump forward across the epoch
        # boundary (8 batches/epoch)
        np.testing.assert_array_equal(stream(13), plain[13])
        np.testing.assert_array_equal(stream(14), plain[14])
        assert stream.seeks == 2
        assert int(stream.state()["next_batch"]) == 15


def test_resumable_stream_prefetch_identical_and_gauges(loader):
    board.clear()
    # >= 8 batches: the prefetcher withholds the board gauge until the
    # stall fraction is statistically meaningful (cold-start guard)
    plain = list(__import__("itertools").islice(iter(loader), 10))
    with ResumableStream(loader, prefetch=2) as stream:
        for k in range(10):
            got = stream(k)
            assert isinstance(got, jax.Array)
            np.testing.assert_array_equal(np.asarray(got), plain[k])
        assert 0.0 <= stream.stall_fraction() <= 1.0
    assert board.get("data/input_stall_fraction") is not None


def test_prefetcher_metrics_ledger(loader):
    from apex_tpu.data import DevicePrefetcher

    with DevicePrefetcher(loader.epoch(0), depth=2) as pf:
        n = sum(1 for _ in pf)
    m = pf.metrics()
    assert m["batches"] == n == loader.batches_per_epoch
    assert 0.0 <= m["stall_fraction"] <= 1.0
    assert m["depth"] == 2


# ---------------------------------------------------------------------------
# run_resilient integration: events, spans, rules
# ---------------------------------------------------------------------------


def _counting_job():
    def batch_fn(step):
        return jnp.asarray(float(step + 1), jnp.float32)

    def step_fn(state, batch):
        return {"acc": state["acc"] + batch}, {"skipped": False}

    return {"acc": jnp.zeros((), jnp.float32)}, step_fn, batch_fn


def test_run_resilient_forwards_write_events_and_spans(tmp_path):
    from apex_tpu.observability.spans import SpanRecorder

    init, step_fn, batch_fn = _counting_job()
    rec = SpanRecorder()
    infos = []

    class Obs:
        def on_checkpoint(self, step, info=None):
            if info is not None:
                infos.append(info)

    run_resilient(
        step_fn, init, batch_fn, directory=tmp_path, num_steps=3,
        observer=ObserverFanout([Obs(), rec]), spans=rec,
    )
    phases = {i["phase"] for i in infos}
    assert "write" in phases
    steps_written = {i["step"] for i in infos if i["phase"] == "write"}
    assert steps_written == {0, 1, 2}
    names = [s["name"] for s in rec.snapshot()]
    assert "ckpt/write" in names and "ckpt/snapshot" in names


def test_run_resilient_legacy_one_arg_observer_survives(tmp_path):
    """An observer written to the pre-goodput protocol
    (``on_checkpoint(step)`` — no info parameter) must keep working
    under the default async engine: it gets the enqueue instants and
    never sees the additive phase records, bare or fanned out."""
    init, step_fn, batch_fn = _counting_job()
    enqueues = []

    class Legacy:
        def on_checkpoint(self, step):
            enqueues.append(step)

    run_resilient(
        step_fn, init, batch_fn, directory=str(tmp_path / "bare"),
        num_steps=3, observer=Legacy(),
    )
    assert enqueues == [0, 1, 2]

    enqueues.clear()
    run_resilient(
        step_fn, init, batch_fn, directory=str(tmp_path / "fanout"),
        num_steps=3, observer=ObserverFanout([Legacy()]),
    )
    assert enqueues == [0, 1, 2]


def test_run_resilient_sync_engine_still_works(tmp_path):
    init, step_fn, batch_fn = _counting_job()
    res = run_resilient(
        step_fn, init, batch_fn, directory=tmp_path, num_steps=3,
        checkpoint="sync",
    )
    assert res.last_step == 2
    assert ckpt.latest_step(tmp_path) == 2


def test_checkpoint_stall_rule_pages_over_budget():
    from apex_tpu.observability import CheckpointStallRule, Watchdog

    board.clear()
    wd = Watchdog(rules=[CheckpointStallRule(max_fraction=0.01)],
                  check_every=1)
    board.set("goodput/ckpt/stall_frac", 0.005)
    wd.on_step(1, False)
    assert wd.events == []
    board.set("goodput/ckpt/stall_frac", 0.05)  # 5x the budget
    wd.on_step(2, False)
    assert [e.rule for e in wd.events] == ["ckpt_stall"]
    assert wd.events[0].severity == "critical"  # > 2x budget


def test_input_stall_rule_pages_and_cross_references():
    from apex_tpu.observability import InputStallRule, Watchdog

    board.clear()
    wd = Watchdog(rules=[InputStallRule(max_fraction=0.15)], check_every=1)
    board.set("data/input_stall_fraction", 0.4)
    # the key publish_attribution actually writes (pinned so the xref
    # branch exercises the production key, not a test-invented one)
    board.set("attribution/host_stall_fraction", 0.3)
    wd.on_step(1, False)
    assert [e.rule for e in wd.events] == ["input_stall"]
    assert "host-stall" in wd.events[0].message
    assert "0.300" in wd.events[0].message


def test_goodput_rules_composition():
    from apex_tpu.observability import goodput_rules

    rules = goodput_rules(floor=0.97)
    names = [r.name for r in rules]
    assert names == ["goodput_floor", "ckpt_stall", "input_stall",
                     "stale_fetch", "hung_step"]
    assert rules[0].floor == 0.97
    with pytest.raises(ValueError, match="unknown"):
        goodput_rules(nope={})


# ---------------------------------------------------------------------------
# the mini storm: preemption chaos, stream-fed, bit-exact resume
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_preemption_storm_stream_fed_bit_exact(tmp_path, loader):
    """The tentpole acceptance in miniature (tools/goodput_drill.py is
    the full version): a preemption storm over a stream-fed run, with
    the stream cursor checkpointed inside the state, sustains 100%
    goodput and reproduces the uninterrupted loss sequence bit-exactly."""
    from apex_tpu.observability import GoodputAccountant

    w_true = np.linspace(-1, 1, 8 * 4, dtype=np.float32).reshape(8, 4)

    def make_batch(toks):
        x = (toks[:, :8].astype(np.float32) / 6000.0) - 0.5
        return x, x @ w_true

    @jax.jit
    def sgd(w, batch):
        x, y = batch

        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.1 * g, loss

    def run(directory, stream, faults=(), losses=None):
        cur = {"step": -1}

        def batch_fn(step):
            cur["step"] = step
            return make_batch(stream(step))

        def step_fn(state, batch):
            new_w, loss = sgd(state["w"], batch)
            step = cur["step"]
            new_state = {"w": new_w, "stream": stream.state(step + 1)}
            if losses is not None:
                losses[step] = float(loss)
            return new_state, {"skipped": False}

        init = {"w": jnp.zeros((8, 4)), "stream": stream.state(0)}
        acct = GoodputAccountant()
        with chaos.inject(*faults):
            while True:
                res = run_resilient(
                    step_fn, init, batch_fn, directory=directory,
                    num_steps=16, save_interval_steps=4, observer=acct,
                )
                if not res.preempted:
                    return res, acct

    losses_ref = {}
    ref_stream = ResumableStream(loader)
    run(tmp_path / "ref", ref_stream, losses=losses_ref)
    ref_stream.close()

    losses_storm = {}
    storm_stream = ResumableStream(loader)
    res, acct = run(
        tmp_path / "storm", storm_stream,
        faults=(chaos.Fault(chaos.PREEMPTION, steps=(5, 11)),),
        losses=losses_storm,
    )
    storm_stream.close()

    assert acct.resumes == 2  # two relaunches after the two evictions
    assert acct.goodput() >= 0.99
    assert losses_storm == losses_ref  # bit-exact trajectory
    # the stream cursor inside the final checkpoint points past the run
    restored = ckpt.restore_step_dir(tmp_path / "storm", 15)
    assert verify_stream_state(loader, restored["stream"]) == 16
