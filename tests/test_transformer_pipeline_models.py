"""Pipeline schedules driving REAL transformer stages — ≙ the reference's
``test_bert_minimal.py`` / ``test_gpt_minimal.py`` /
``test_dynamic_batchsize.py`` (standalone models through the 1F1B
schedules; golden = sequential composition of the same stages)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.models.bert import BertConfig, BertEncoderCore
from apex_tpu.transformer.microbatches import RampupBatchsizeNumMicroBatches
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
    split_batch_into_microbatches,
)
from apex_tpu.transformer.testing import (
    bert_model_provider,
    cpu_mesh,
    gpt_model_provider,
    set_random_seed,
)

CFG = dict(
    vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
    intermediate_size=64, max_position_embeddings=64, dtype=jnp.float32,
)
NM, MB, S = 4, 2, 8  # microbatches, microbatch size, seq len


def _stage(pp, sp=False):
    cfg = BertConfig(sequence_parallel=sp, **CFG)
    return BertEncoderCore(cfg, num_layers=CFG["num_layers"] // pp)


def _bert_stage_batch():
    h = CFG["hidden_size"]
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(NM, S, MB, h), np.float32)  # (nm, S, B, H)
    ts = jnp.asarray(rng.randn(NM, S, MB, h), np.float32)
    return xs, ts


def _run_bert_stage_schedule(mesh, pp, schedule, xs, ts, **kw):
    """(losses, grads-pytree) of one pipeline schedule over real BERT
    encoder stages on the live (tp, pp) mesh — shared driver so every
    schedule under test sees identical params/inputs/sharding."""
    stage = _stage(pp)

    def run(key, xs, ts):
        pp_rank = ps.get_pipeline_model_parallel_rank()
        stage_key = jax.random.fold_in(key, pp_rank)
        params = stage.init(stage_key, xs[0])

        def stage_fn(p, x):
            return stage.apply(p, x)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        losses, grads = schedule(
            stage_fn, loss_fn, params, (xs, ts), num_microbatches=NM, **kw
        )
        # grads are per-(pp, tp)-rank shards: stack them under two
        # leading axes so the caller can compare schedules leaf-by-leaf
        return losses, jax.tree_util.tree_map(
            lambda g: g[None, None], grads
        )

    return jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(
                P(),
                P(ps.PIPELINE_PARALLEL_AXIS, ps.TENSOR_PARALLEL_AXIS),
            ),
            check_vma=False,
        )
    )(jax.random.PRNGKey(3), xs, ts)


_LOCKSTEP_REF_CACHE = {}


def _lockstep_bert_stage_ref(mesh, pp, xs, ts):
    """Module-cached lockstep-schedule reference run: identical for every
    `stash` parametrization, and the pp=4 x tp=2 BERT compile is the
    expensive part of the test."""
    key = (pp, np.asarray(xs).tobytes(), np.asarray(ts).tobytes())
    if key not in _LOCKSTEP_REF_CACHE:
        losses, grads = _run_bert_stage_schedule(
            mesh, pp, forward_backward_pipelining_without_interleaving,
            xs, ts, remat=False,
        )
        _LOCKSTEP_REF_CACHE[key] = (
            np.asarray(losses),
            [np.asarray(l) for l in jax.tree_util.tree_leaves(grads)],
        )
    return _LOCKSTEP_REF_CACHE[key]


def _sequential_bert_stage_losses(pp, xs, ts):
    """Sequential composition of the same stages (same per-stage keys)."""
    ps.destroy_model_parallel()
    stage1 = _stage(pp)
    stage_params = [
        stage1.init(jax.random.fold_in(jax.random.PRNGKey(3), r), xs[0])
        for r in range(pp)
    ]
    seq_losses = []
    for m in range(NM):
        hcur = xs[m]
        for p in stage_params:
            hcur = stage1.apply(p, hcur)
        seq_losses.append(float(jnp.mean((hcur - ts[m]) ** 2)))
    return seq_losses


def test_1f1b_bert_stages_match_sequential(eight_devices):
    """4 encoder stages through 1F1B (pp=4, tp=2 inside) == sequential."""
    pp, tp = 4, 2
    xs, ts = _bert_stage_batch()
    with cpu_mesh(tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp) as mesh:
        losses, _ = _run_bert_stage_schedule(
            mesh, pp, forward_backward_pipelining_without_interleaving,
            xs, ts,
        )
    seq_losses = _sequential_bert_stage_losses(pp, xs, ts)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(seq_losses), rtol=2e-4, atol=1e-5
    )


@pytest.mark.slow
@pytest.mark.parametrize("stash", ["residuals", "input"])
def test_hand_1f1b_bert_stages_match_sequential(eight_devices, stash):
    """The hand-scheduled 1F1B (explicit stash ring, reversed permutes)
    through REAL BERT encoder stages with tp=2 inside pp=4: the per-tick
    ``jax.vjp`` must compose with the stage's internal tp collectives
    (psum/all-gather transposes) and the residual ring must stash
    tp-sharded activation residuals.  Losses check against the
    sequential composition; GRADS check leaf-exactly against the
    lockstep schedule on identical params/inputs — the tp-composed
    backward is exactly what tests/test_pipeline_parallel.py (tp=1)
    does not cover."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_1f1b,
    )

    pp, tp = 4, 2
    xs, ts = _bert_stage_batch()
    with cpu_mesh(tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp) as mesh:
        losses, grads = _run_bert_stage_schedule(
            mesh, pp, forward_backward_pipelining_1f1b, xs, ts, stash=stash
        )
        ref_losses, ref_grad_leaves = _lockstep_bert_stage_ref(
            mesh, pp, xs, ts
        )
    np.testing.assert_allclose(
        np.asarray(losses), ref_losses, rtol=1e-6, atol=1e-7
    )
    seq_losses = _sequential_bert_stage_losses(pp, xs, ts)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(seq_losses), rtol=2e-4, atol=1e-5
    )
    flat = jax.tree_util.tree_leaves(grads)
    assert flat and len(flat) == len(ref_grad_leaves)
    for g, gr in zip(flat, ref_grad_leaves):
        np.testing.assert_allclose(
            np.asarray(g), gr, rtol=2e-4, atol=1e-5
        )


@pytest.mark.slow
def test_hand_interleaved_bert_stages_match_lockstep(eight_devices):
    """The hand-scheduled INTERLEAVED 1F1B through REAL BERT encoder
    stages: pp=2 ranks x vpp=2 chunks (4 virtual stages of 1 layer)
    with tp=2 inside every chunk.  The chunk-granular ring must stash
    tp-sharded residuals, the per-tick ``dynamic_index_in_dim`` chunk
    gather must compose with the stage's internal tp collectives, and
    the chunk-param passthrough re-materialization must pick the
    BACKWARD tick's chunk.  Losses vs the sequential composition;
    grads leaf-exactly vs the lockstep interleaved schedule."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_interleaved_1f1b,
        forward_backward_pipelining_with_interleaving,
    )

    pp, tp, vpp = 2, 2, 2
    n_virtual = pp * vpp
    cfg = BertConfig(**CFG)
    stage = BertEncoderCore(cfg, num_layers=CFG["num_layers"] // n_virtual)
    xs, ts = _bert_stage_batch()

    def runner(schedule, **kw):
        def run(key, xs, ts):
            pp_rank = ps.get_pipeline_model_parallel_rank()
            chunks = [
                stage.init(jax.random.fold_in(key, c * pp + pp_rank), xs[0])
                for c in range(vpp)
            ]
            params = jax.tree_util.tree_map(
                lambda *l: jnp.stack(l, axis=0), *chunks
            )

            def stage_fn(p, x):
                return stage.apply(p, x)

            def loss_fn(y, t):
                return jnp.mean((y - t) ** 2)

            losses, grads = schedule(
                stage_fn, loss_fn, params, (xs, ts),
                num_microbatches=NM, num_model_chunks=vpp, **kw,
            )
            return losses, jax.tree_util.tree_map(
                lambda g: g[None, None], grads
            )

        with cpu_mesh(
            tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp
        ) as mesh:
            return jax.jit(
                jax.shard_map(
                    run, mesh=mesh, in_specs=(P(), P(), P()),
                    out_specs=(
                        P(),
                        P(ps.PIPELINE_PARALLEL_AXIS,
                          ps.TENSOR_PARALLEL_AXIS),
                    ),
                    check_vma=False,
                )
            )(jax.random.PRNGKey(3), xs, ts)

    losses, grads = runner(
        forward_backward_pipelining_interleaved_1f1b, stash="residuals"
    )
    ref_losses, ref_grads = runner(
        forward_backward_pipelining_with_interleaving, remat=False
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-6, atol=1e-7
    )
    flat = jax.tree_util.tree_leaves(grads)
    ref_flat = jax.tree_util.tree_leaves(ref_grads)
    assert flat and len(flat) == len(ref_flat)
    for g, gr in zip(flat, ref_flat):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(gr), rtol=2e-4, atol=1e-5
        )

    # sequential composition golden for the losses: virtual stage v =
    # c*pp + r with key folded by v — exactly the layout
    # _sequential_bert_stage_losses(n_virtual, ...) builds (one
    # CFG.num_layers/n_virtual-layer stage per fold index)
    seq_losses = _sequential_bert_stage_losses(n_virtual, xs, ts)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(seq_losses), rtol=2e-4, atol=1e-5
    )


@pytest.mark.parametrize("provider", [bert_model_provider, gpt_model_provider])
def test_standalone_providers_forward(provider):
    model = provider()
    key = set_random_seed(0)
    ids = jax.random.randint(key, (16, 2), 0, 64)
    params = model.init(jax.random.PRNGKey(1), ids)
    out = model.apply(params, ids)
    leaves = jax.tree_util.tree_leaves(out)
    assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)


def test_dynamic_batchsize_rampup_drives_microbatches(eight_devices):
    """≙ test_dynamic_batchsize.py — the rampup calculator changes
    num_microbatches across consumed samples and the pipeline runs at
    each size."""
    calc = RampupBatchsizeNumMicroBatches(
        start_batch_size=4,
        batch_size_increment=4,
        ramup_samples=64,
        global_batch_size=16,
        micro_batch_size=2,
        data_parallel_size=1,
    )
    h = 8
    with cpu_mesh(pipeline_model_parallel_size=2) as mesh:
        seen = []
        for consumed in (0, 24, 48):  # walk the ramp: 4 -> 8 -> 12 samples/batch
            calc.update(consumed)
            nm = calc.get()
            seen.append(nm)
            batch = {
                "x": jnp.ones((nm * 2, 4, h)),
                "t": jnp.zeros((nm * 2, 4, h)),
            }
            mbs = split_batch_into_microbatches(batch, nm)

            def run(xs, ts, _nm=nm):
                w = jnp.eye(h)

                def stage_fn(p, x):
                    return jnp.tanh(x @ p)

                losses, grads = (
                    forward_backward_pipelining_without_interleaving(
                        stage_fn, lambda y, t: jnp.mean((y - t) ** 2), w,
                        (xs, ts), num_microbatches=_nm,
                    )
                )
                return losses

            losses = jax.jit(
                jax.shard_map(
                    run, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                    check_vma=False,
                )
            )(mbs["x"], mbs["t"])
            assert losses.shape == (nm,)
        assert seen[0] < seen[-1]  # rampup actually ramped
